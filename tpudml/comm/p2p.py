"""Point-to-point activation/gradient transfer for MPMD stage groups.

gloo collectives (``comm/collectives.py``) span exactly one
``jax.distributed`` world, and an MPMD pipeline is deliberately many
worlds — one process group per stage (``tpudml/mpmd``). The tensors
that cross a stage boundary therefore travel OUTSIDE any jitted
program, over plain TCP between the boundary ranks, with a framing
contract strict enough that a resumed incarnation replays the same
byte stream:

- **deterministic (step, microbatch, edge) framing** — every frame
  carries the training step, the wire-chunk (microbatch) index, a
  direction tag (``act`` forward / ``grad`` backward / ``ctl`` for the
  drain barrier) and the edge label (``s0r1->s1r0``). The receiver
  states what it expects; any mismatch is a :class:`FramingError`
  (a protocol bug), never silently reordered data.
- **integrity** — payload CRC-32 per frame, verified on receipt (the
  checkpoint layer's bit-exactness discipline applied to the wire).
- **peer death is a membership event, not an exception trace** — EOF,
  connection reset and receive timeout all raise :class:`PeerDeadError`
  carrying the last good (step, microbatch); the stage loop catches it
  and drains (``mpmd/runtime.py``).

Wire pricing: an MPMD edge ships its payload exactly once, so it is
priced as the ``"p2p"`` kind in the shared ring wire model
(``comm/timing.py`` — same table the static analyzer and the planner
score with): :func:`p2p_wire_bytes`. Channels feed the flight recorder
the same way :class:`~tpudml.comm.timing.CommStats` does — one
``cat="comm"`` complete span per frame, labeled with the edge and the
byte count.

This module is deliberately jax-free (stdlib + numpy): the MPMD
controller and the meshless fixture replay import it without touching
a backend. ``bfloat16`` payloads rely on ``ml_dtypes`` (jax's own
dependency) only when such a frame is actually seen.
"""

from __future__ import annotations

import json
import socket
import struct
import time
import zlib

import numpy as np

from tpudml.comm.timing import collective_wire_bytes

FRAME_MAGIC = 0x4D504D44  # "MPMD"
FRAME_VERSION = 1

TAG_ACT = "act"
TAG_GRAD = "grad"
TAG_CTL = "ctl"
_TAGS = (TAG_ACT, TAG_GRAD, TAG_CTL)

#: Barrier verdicts (1-byte ctl payloads).
VOTE_OK = b"\x01"
VOTE_DRAIN = b"\x00"


class FramingError(RuntimeError):
    """The peer sent a frame the receiver did not expect — a protocol
    bug (schedule divergence), distinct from peer death."""


class PeerDeadError(RuntimeError):
    """EOF / reset / timeout on a p2p channel: the peer (or its whole
    stage group) is gone. Carries the last successfully framed
    (step, microbatch) so the drain report can say what was in flight."""

    def __init__(self, msg: str, *, edge: str = "?", step: int = -1,
                 microbatch: int = -1):
        super().__init__(msg)
        self.edge = edge
        self.step = step
        self.microbatch = microbatch


def p2p_wire_bytes(payload_bytes: int) -> float:
    """Ring-model bytes for one MPMD edge transfer: the ``"p2p"`` kind
    ships the payload once (``comm/timing._WIRE_MODEL``), so planner
    dataflow rules price an MPMD edge like any other collective."""
    return collective_wire_bytes("p2p", payload_bytes, 2)


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401 — registers bfloat16 et al.

        return np.dtype(name)


def _recv_exact(sock: socket.socket, n: int, *, edge: str) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            k = sock.recv_into(view[got:], n - got)
        except (socket.timeout, TimeoutError) as e:
            raise PeerDeadError(
                f"p2p recv timeout on edge {edge}", edge=edge
            ) from e
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise PeerDeadError(
                f"p2p connection lost on edge {edge}: {e!r}", edge=edge
            ) from e
        if k == 0:
            raise PeerDeadError(f"p2p EOF on edge {edge}", edge=edge)
        got += k
    return bytes(buf)


_HDR = struct.Struct("!II")  # magic, header_len


def send_frame(sock: socket.socket, arr: np.ndarray, *, step: int,
               microbatch: int, tag: str, edge: str) -> int:
    """Send one framed array; returns payload bytes on the wire."""
    if tag not in _TAGS:
        raise ValueError(f"unknown frame tag {tag!r}")
    a = np.ascontiguousarray(arr)
    payload = a.tobytes()
    header = json.dumps(
        {
            "v": FRAME_VERSION,
            "step": int(step),
            "microbatch": int(microbatch),
            "tag": tag,
            "edge": edge,
            "dtype": a.dtype.name,
            "shape": list(a.shape),
            "nbytes": len(payload),
            "crc": zlib.crc32(payload),
        },
        sort_keys=True,
        separators=(",", ":"),
    ).encode()
    try:
        sock.sendall(_HDR.pack(FRAME_MAGIC, len(header)) + header + payload)
    except (ConnectionResetError, BrokenPipeError, OSError) as e:
        raise PeerDeadError(
            f"p2p send failed on edge {edge}: {e!r}",
            edge=edge, step=step, microbatch=microbatch,
        ) from e
    return len(payload)


def recv_frame(sock: socket.socket, *, step: int, microbatch: int,
               tag: str, edge: str) -> np.ndarray:
    """Receive one frame, enforcing the deterministic framing: the frame
    on the wire must carry exactly the (step, microbatch, tag, edge) the
    caller expects."""
    magic, hlen = _HDR.unpack(_recv_exact(sock, _HDR.size, edge=edge))
    if magic != FRAME_MAGIC:
        raise FramingError(
            f"edge {edge}: bad magic {magic:#x} (expected {FRAME_MAGIC:#x})"
        )
    hdr = json.loads(_recv_exact(sock, hlen, edge=edge))
    # Consume the payload before any mismatch check so the byte stream
    # stays frame-aligned even when the error is caught.
    payload = _recv_exact(sock, int(hdr["nbytes"]), edge=edge)
    got = (hdr.get("step"), hdr.get("microbatch"), hdr.get("tag"),
           hdr.get("edge"))
    want = (int(step), int(microbatch), tag, edge)
    if got != want:
        raise FramingError(
            f"frame mismatch on edge {edge}: got (step, microbatch, tag, "
            f"edge)={got}, expected {want}"
        )
    if zlib.crc32(payload) != hdr["crc"]:
        raise FramingError(
            f"edge {edge}: payload CRC mismatch at step {step} "
            f"microbatch {microbatch}"
        )
    return np.frombuffer(payload, dtype=_resolve_dtype(hdr["dtype"])).reshape(
        hdr["shape"]
    )


class Channel:
    """One full-duplex p2p connection between two boundary ranks.

    Forward activations and backward gradients for the same rank pair
    share the socket (strict alternation per the 1F1B schedule keeps the
    turn order unambiguous). Every frame lands on the ambient tracer as
    a ``cat="comm"`` complete span with edge-labeled byte counts — the
    same category/args convention :class:`~tpudml.comm.timing.CommStats`
    uses, so merged traces show MPMD edges next to in-group collectives.
    """

    def __init__(self, sock: socket.socket, edge: str, *, tracer=None,
                 timeout_s: float | None = 60.0):
        self.sock = sock
        self.edge = edge
        self.tracer = tracer
        self.bytes_sent = 0
        self.bytes_received = 0
        self.frames = 0
        if timeout_s is not None:
            sock.settimeout(timeout_s)

    def _span(self, name: str, t0: float, nbytes: int, step: int,
              microbatch: int) -> None:
        tracer = self.tracer
        if tracer is None:
            from tpudml.obs.tracer import get_tracer

            tracer = get_tracer()
        if tracer is None:
            return
        dur_us = int((time.perf_counter() - t0) * 1e6)
        tracer.add_complete(
            name, cat="comm", ts_us=max(0, tracer.now_us() - dur_us),
            dur_us=dur_us,
            args={
                "edge": self.edge, "bytes": int(nbytes),
                "wire_bytes": p2p_wire_bytes(nbytes),
                "step": int(step), "microbatch": int(microbatch),
            },
        )

    def send(self, arr: np.ndarray, *, step: int, microbatch: int,
             tag: str) -> int:
        t0 = time.perf_counter()
        n = send_frame(self.sock, arr, step=step, microbatch=microbatch,
                       tag=tag, edge=self.edge)
        self.bytes_sent += n
        self.frames += 1
        self._span(f"p2p_send:{tag}", t0, n, step, microbatch)
        return n

    def recv(self, *, step: int, microbatch: int, tag: str) -> np.ndarray:
        t0 = time.perf_counter()
        arr = recv_frame(self.sock, step=step, microbatch=microbatch,
                         tag=tag, edge=self.edge)
        self.bytes_received += arr.nbytes
        self.frames += 1
        self._span(f"p2p_recv:{tag}", t0, arr.nbytes, step, microbatch)
        return arr

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def channel_pair(edge: str, **kw) -> tuple[Channel, Channel]:
    """An in-process full-duplex channel pair (``socket.socketpair``) —
    the exact wire path, no listener: what the in-process pipeline tests
    and the threaded hetero-parity harness run over."""
    a, b = socket.socketpair()
    return Channel(a, edge, **kw), Channel(b, edge, **kw)


def connect_channel(host: str, port: int, *, edge: str, hello: dict,
                    deadline_s: float = 30.0, tracer=None,
                    timeout_s: float | None = 60.0) -> Channel:
    """Dial a boundary listener, retrying until ``deadline_s`` (stage
    groups start in parallel; the listener may not be up yet), then
    introduce ourselves with a ctl hello frame carrying ``hello``."""
    deadline = time.monotonic() + deadline_s
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            break
        except OSError as e:
            last = e
            time.sleep(0.05)
    else:
        raise PeerDeadError(
            f"could not connect edge {edge} to {host}:{port} within "
            f"{deadline_s:.0f}s: {last!r}",
            edge=edge,
        )
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    ch = Channel(sock, edge, tracer=tracer, timeout_s=timeout_s)
    payload = np.frombuffer(
        json.dumps(hello, sort_keys=True).encode(), np.uint8
    )
    send_frame(sock, payload, step=0, microbatch=0, tag=TAG_CTL, edge=edge)
    return ch


def accept_channels(listener: socket.socket, n: int, *,
                    deadline_s: float = 30.0, tracer=None,
                    timeout_s: float | None = 60.0) -> dict[str, tuple[Channel, dict]]:
    """Accept ``n`` dialers on an already-bound listener; returns
    ``edge -> (channel, hello)`` keyed by each hello frame's edge."""
    listener.settimeout(deadline_s)
    out: dict[str, tuple[Channel, dict]] = {}
    for _ in range(n):
        try:
            sock, _addr = listener.accept()
        except (socket.timeout, TimeoutError) as e:
            raise PeerDeadError(
                f"listener timed out waiting for {n} peers "
                f"(got {len(out)})"
            ) from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(deadline_s)
        # The hello's edge is unknown until read: accept any edge label.
        magic, hlen = _HDR.unpack(_recv_exact(sock, _HDR.size, edge="hello"))
        if magic != FRAME_MAGIC:
            raise FramingError(f"hello: bad magic {magic:#x}")
        hdr = json.loads(_recv_exact(sock, hlen, edge="hello"))
        if hdr.get("tag") != TAG_CTL:
            raise FramingError(f"hello must be a ctl frame, got {hdr!r}")
        payload = _recv_exact(sock, int(hdr["nbytes"]), edge="hello")
        if zlib.crc32(payload) != hdr["crc"]:
            raise FramingError("hello payload CRC mismatch")
        hello = json.loads(bytes(payload))
        edge = hdr["edge"]
        out[edge] = (Channel(sock, edge, tracer=tracer,
                             timeout_s=timeout_s), hello)
    return out


class DrainBarrier:
    """Step-boundary consensus inside one stage group, over ctl frames.

    Why it exists: the step-end gradient psum is a gloo collective —
    a rank that enters it while a peer has already drained (its boundary
    socket died first) hangs until the job timeout. So before every
    collective the group votes over a host-level star (stage-local rank
    0 is the hub): each leaf sends ``ok``/``drain``, the hub broadcasts
    the AND. A rank only enters the psum after a unanimous ``ok`` — and
    a rank that voted ok is committed to enter it, so the collective can
    never half-start. Peer death during the vote counts as ``drain``
    (the whole point: the dead stage's EOF propagates through the
    surviving group at a step boundary, in deterministic drain order).
    """

    def __init__(self, *, hub: bool, channels: dict[int, Channel]):
        self.hub = hub
        self.channels = dict(channels)  # peer local-rank -> Channel

    def vote(self, step: int, *, ok: bool = True) -> bool:
        """True iff every rank in the group voted ok this step."""
        mine = VOTE_OK if ok else VOTE_DRAIN
        verdict = ok
        if self.hub:
            for rank in sorted(self.channels):
                ch = self.channels[rank]
                try:
                    token = ch.recv(step=step, microbatch=rank, tag=TAG_CTL)
                    if bytes(token.tobytes()) != VOTE_OK:
                        verdict = False
                except PeerDeadError:
                    verdict = False
            out = VOTE_OK if verdict else VOTE_DRAIN
            for rank in sorted(self.channels):
                try:
                    self.channels[rank].send(
                        np.frombuffer(out, np.uint8), step=step,
                        microbatch=rank, tag=TAG_CTL,
                    )
                except PeerDeadError:
                    pass  # a peer that died mid-broadcast is draining anyway
            return verdict
        # Leaf: exactly one channel (to the hub).
        ((rank, ch),) = self.channels.items()
        try:
            ch.send(np.frombuffer(mine, np.uint8), step=step,
                    microbatch=rank, tag=TAG_CTL)
            token = ch.recv(step=step, microbatch=rank, tag=TAG_CTL)
        except PeerDeadError:
            return False
        return ok and bytes(token.tobytes()) == VOTE_OK
