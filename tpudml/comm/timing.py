"""Communication-time accounting.

The reference measures comm cost by bracketing the per-step allreduce call
with ``time.time()`` and accumulating ``comm_time_sum`` (codes/task2/
model-mp.py:61-66, printed :79; GPU-accurate recipe via cuda Events,
sections/task2.tex:69-80). Under XLA that span does not exist: collectives
are scheduled inside one fused jitted program (SURVEY.md §7 "hard parts").

Two mechanisms reproduce the capability:

1. **Split-step mode** (``measure_comm=True`` in the DP engine): the step is
   deliberately compiled as two XLA programs — (a) local grads, (b)
   aggregate + apply — and the host brackets program (b) with
   ``block_until_ready`` timers. This trades fusion for measurability,
   exactly the trade the reference's eager loop makes implicitly.
2. **comm_time_trial**: times an aggregation strategy in isolation on a
   gradient-shaped pytree (jitted, warmed up, block_until_ready-bracketed) —
   the cleanest way to produce task2's AllReduce-vs-AllGather comparison
   table (sections/checking.tex:20-21) without perturbing training.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import jax
import numpy as np


# Ring-model wire bytes moved per device for one collective, as a
# function of the per-shard input payload P and the axis size N. psum is
# a ring allreduce (reduce-scatter + all-gather legs, 2·P·(N−1)/N);
# all_gather ships the local shard to the other N−1 devices;
# reduce_scatter/all_to_all move one (N−1)/N fraction; ppermute ships
# the whole buffer once. The static analyzer (analysis/dataflow.py) and
# the runtime byte accounting below share this table so the --cost
# cross-validation compares like against like.
_WIRE_MODEL = {
    "psum": lambda p, n: 2.0 * p * (n - 1) / n,
    "pmax": lambda p, n: 2.0 * p * (n - 1) / n,
    "pmin": lambda p, n: 2.0 * p * (n - 1) / n,
    "pbroadcast": lambda p, n: p * (n - 1) / n,
    "all_gather": lambda p, n: float(p * (n - 1)),
    "psum_scatter": lambda p, n: p * (n - 1) / n,
    "reduce_scatter": lambda p, n: p * (n - 1) / n,
    "all_to_all": lambda p, n: p * (n - 1) / n,
    "pgather": lambda p, n: p * (n - 1) / n,
    "ppermute": lambda p, n: float(p),
    # MPMD stage-boundary edge (comm/p2p.py): the activation/gradient
    # payload crosses the wire exactly once, sender to receiver.
    "p2p": lambda p, n: float(p),
}


def collective_wire_bytes(kind: str, payload_bytes: float, world: int) -> float:
    """Ring-model bytes one device moves for a single ``kind`` collective
    over an axis of size ``world``, given per-shard input ``payload_bytes``.
    Unknown kinds fall back to shipping the payload once."""
    if world <= 1:
        return 0.0
    fn = _WIRE_MODEL.get(kind)
    return float(fn(payload_bytes, world) if fn else payload_bytes)


@dataclass
class CommStats:
    """Accumulates the reference's ``comm_time_sum`` (model-mp.py:48,79),
    plus — since the static cost reports landed — the ring-model wire
    bytes each timed call moved, so measured and predicted comm volume
    can be compared on the same units."""

    comm_time_s: float = 0.0
    calls: int = 0
    per_call_s: list = field(default_factory=list)
    comm_bytes: float = 0.0
    # Flight-recorder feed (tpudml.obs): with a Tracer attached, every
    # timed call additionally lands on the trace timeline as a complete
    # span in the "comm" category — the engines' obs= knob sets this.
    tracer: Any = None
    label: str = "comm"

    def add(self, dt: float, nbytes: float = 0.0) -> None:
        self.comm_time_s += dt
        self.calls += 1
        self.per_call_s.append(dt)
        self.comm_bytes += nbytes
        if self.tracer is not None and self.tracer.enabled:
            dur_us = int(dt * 1e6)
            args = {"bytes": nbytes} if nbytes else None
            self.tracer.add_complete(
                self.label, cat="comm",
                ts_us=max(self.tracer.now_us() - dur_us, 0),
                dur_us=dur_us, args=args,
            )

    def percentiles(self) -> dict:
        """p50/p99 of the recorded per-call spans (empty dict when no
        calls were recorded). p99 interpolates over whatever sample count
        exists — at few calls it tracks the max, which is the honest
        reading of a small sample."""
        if not self.per_call_s:
            return {}
        arr = np.asarray(self.per_call_s)
        return {
            "p50_s": float(np.percentile(arr, 50)),
            "p99_s": float(np.percentile(arr, 99)),
        }

    def report(self) -> str:
        # Reference print parity: "Total communication time:" (model-mp.py:79)
        # — the prefix is load-bearing for output-comparison; percentiles
        # append after it.
        line = f"Total communication time: {self.comm_time_s:.4f}s over {self.calls} calls"
        pct = self.percentiles()
        if pct:
            line += (
                f" (p50 {pct['p50_s'] * 1e3:.2f}ms,"
                f" p99 {pct['p99_s'] * 1e3:.2f}ms)"
            )
        if self.comm_bytes:
            line += f", {self.comm_bytes / 1e6:.2f} MB moved/device"
        return line


def timed_call(stats: CommStats, fn: Callable, *args) -> Any:
    """Run ``fn`` (a jitted program) and charge its wall time to ``stats``.

    ``block_until_ready`` on the output plays the role of
    ``torch.cuda.synchronize`` in the reference's Event recipe
    (sections/task2.tex:72-80): without it the async dispatch would make the
    span meaningless.
    """
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    stats.add(time.perf_counter() - t0)
    return out


def comm_time_trial(
    mesh,
    grads_like: Any,
    aggregator: Callable,
    axis_name: str = "data",
    iters: int = 20,
    warmup: int = 3,
) -> dict:
    """Median/total wall time of one aggregation strategy in isolation.

    Compiles ``aggregator`` alone under shard_map over ``mesh`` and times it
    on synthetic gradients shaped like ``grads_like``.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpudml.parallel.sharding import shard_map_fn

    agg = shard_map_fn(
        partial(aggregator, axis_name=axis_name),
        mesh,
        in_specs=P(),
        out_specs=P(),
    )
    agg = jax.jit(agg)
    grads = jax.device_put(grads_like, NamedSharding(mesh, P()))
    for _ in range(warmup):
        jax.block_until_ready(agg(grads))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(agg(grads))
        times.append(time.perf_counter() - t0)
    times_arr = np.asarray(times)
    return {
        "median_s": float(np.median(times_arr)),
        "mean_s": float(times_arr.mean()),
        "total_s": float(times_arr.sum()),
        "iters": iters,
    }


def comm_time_table(
    mesh,
    grads_like: Any,
    strategies: dict | None = None,
    axis_name: str = "data",
    iters: int = 20,
    warmup: int = 3,
) -> dict:
    """:func:`comm_time_trial` over every aggregation strategy — the
    task2 comparison table in one call. Defaults to all registered
    aggregators (allreduce / allgather / reducescatter), so the table
    covers the ReduceScatter decomposition ZeRO-1 builds on."""
    from tpudml.comm.collectives import AGGREGATORS

    strategies = AGGREGATORS if strategies is None else strategies
    return {
        name: comm_time_trial(
            mesh, grads_like, agg, axis_name=axis_name, iters=iters,
            warmup=warmup,
        )
        for name, agg in strategies.items()
    }


def attribute_overlap(fused_s: float, compute_s: float, comm_s: float) -> dict:
    """Split a step's communication time into EXPOSED (the step waited on
    it) vs HIDDEN (the schedule absorbed it behind compute), from three
    wall-time spans measured as separate programs on the same inputs:
    the fused step, the compute-only span, and the comm-only span.

    ``exposed = clamp(fused − compute, 0, comm)``: whatever the fused
    program costs beyond pure compute is comm it could not hide, bounded
    by the comm span itself (program-splitting overhead cannot inflate
    exposure past what the collectives cost in isolation); ``hidden``
    is the remainder. ``overlap_frac`` = hidden/comm (0 when comm ≈ 0).
    """
    exposed = min(max(fused_s - compute_s, 0.0), comm_s)
    hidden = comm_s - exposed
    return {
        "fused_s": fused_s,
        "compute_s": compute_s,
        "comm_s": comm_s,
        "exposed_comm_s": exposed,
        "hidden_comm_s": hidden,
        "overlap_frac": (hidden / comm_s) if comm_s > 0 else 0.0,
    }
