"""Communication layer: collective wrappers + comm-time accounting.

TPU-native replacement of the reference's ``dist_utils`` trio
(codes/task{2,3,4}/dist_utils.py — three near-identical copies, unified
here per SURVEY.md §1). Collectives ride XLA's TPU fabric (ICI intra-slice,
DCN cross-host) instead of NCCL/gloo process groups.
"""

from tpudml.comm.collectives import (
    allgather_average_gradients,
    allreduce_average_gradients,
    all_gather_tree,
    all_to_all,
    broadcast_from,
    pmean_tree,
    ppermute_ring,
    psum_scatter_tree,
    psum_tree,
    reduce_scatter_average_gradients,
)
from tpudml.comm.timing import (
    CommStats,
    attribute_overlap,
    comm_time_table,
    comm_time_trial,
)

__all__ = [
    "allgather_average_gradients",
    "allreduce_average_gradients",
    "all_gather_tree",
    "all_to_all",
    "broadcast_from",
    "pmean_tree",
    "ppermute_ring",
    "psum_scatter_tree",
    "psum_tree",
    "reduce_scatter_average_gradients",
    "CommStats",
    "attribute_overlap",
    "comm_time_table",
    "comm_time_trial",
]
