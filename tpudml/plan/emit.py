"""Plan emission: rank the survivors, build + trace + verify the
winner, serialize the runnable ``plan.json`` (v2 schema).

``make_plan`` is the whole pipeline.  Self-verification is the
load-bearing part: the winning candidate is constructed as a *real*
engine on the dryrun mesh (the same classes the tasks instantiate),
its jitted step traced, and the PR 10 dataflow rules (J112–J116) run
over the jaxpr — a plan that would lose a psum, reuse a donated
buffer, or blow the HBM budget is demoted before it is ever emitted,
and the next-ranked survivor is tried.  The verification trace also
stamps the plan's ``predicted`` block (ring-model wire bytes +
peak-live HBM of the winner), which is the contract rule J118 later
holds the code to: re-trace the entrypoint, compare against
``predicted``, flag >10% drift.

plan.json v2 schema (all byte-deterministic — no timestamps, sorted
keys)::

    {
      "version": 2,
      "world": int,
      "spec": ModelSpec.to_dict(),
      "hbm_budget_bytes": int | null,
      "winner": {"candidate": {...}, "score": {...}},
      "engine_config": {... flat knobs train/task wiring consumes ...},
      "ranking": [{"candidate", "score"}, ...],          # survivors, best first
      "pruned": [{"candidate", "rule", "reason"}, ...],  # every drop, with why
      "predicted": {"comm_wire_bytes": float, "peak_hbm_bytes": int},
      "verification": {"entrypoint", "ok", "findings": [...],
                       "demoted": [...]},                # winners that failed
      "calibration": null | Calibration.to_dict(),       # measured constants
      "replan": null | {"trigger", "why", "old_world",   # re-plan provenance
                        "old_winner", "receipts": [...]}
    }

v2 adds two always-present keys over v1 (schema totality keeps the
byte-determinism pin trivial): ``calibration`` — the measured scales a
drift-triggered re-score folded into the roofline (null for a plan
scored on the nominal constants) — and ``replan`` — the provenance of
an adaptive re-plan (what triggered it, what the previous winner was,
and the machine-readable receipts for why it lost), null for a plan
made fresh.  ``load_plan`` still reads v1 files, upgrading them
in-memory with both keys null.
"""

from __future__ import annotations

import dataclasses
import json

from tpudml.plan.prune import prune
from tpudml.plan.score import PP_MICROBATCHES, score_candidate
from tpudml.plan.space import Candidate, ModelSpec, enumerate_candidates

PLAN_VERSION = 2

#: Versions ``load_plan`` accepts; older ones are upgraded in-memory.
SUPPORTED_PLAN_VERSIONS = (1, 2)


def _mesh(axes: dict, world: int):
    import jax
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh

    if len(jax.devices()) < world:
        raise RuntimeError(
            f"plan verification needs {world} devices, have "
            f"{len(jax.devices())} — set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    return make_mesh(MeshConfig(axes), jax.devices()[:world])


def _model(spec: ModelSpec):
    from tpudml.models import TransformerLM

    return TransformerLM(
        vocab_size=spec.vocab_size,
        embed_dim=spec.embed_dim,
        num_heads=spec.num_heads,
        num_layers=spec.num_layers,
        max_len=spec.seq_len,
        impl="full",
        rope=True,
    )


def _batch(spec: ModelSpec, world: int):
    import numpy as np

    rng = np.random.default_rng(0)
    rows = spec.global_batch(world)
    seqs = rng.integers(
        0, spec.vocab_size, size=(rows, spec.seq_len + 1)
    ).astype(np.int32)
    return seqs[:, :-1], seqs[:, 1:]


def build_candidate(spec: ModelSpec, cand: Candidate):
    """Instantiate the candidate as a real engine on the dryrun mesh.

    Returns ``(engine, train_state, step, (x, y))`` — ``step`` is the
    engine's train step whose ``.jitted`` is the traceable program.
    The construction mirrors the task CLIs; if the candidate violated a
    composition rule the constructor would raise here, which is exactly
    the planner/runtime agreement the capability table guarantees never
    happens for a pruned-in candidate.
    """
    from tpudml.core.prng import seed_key
    from tpudml.optim import make_optimizer

    world = 1
    for _, s in cand.mesh:
        world *= s
    mesh_axes = cand.mesh_dict
    model = _model(spec)
    opt = make_optimizer("adamw", 3e-4)
    common = dict(
        fused_xent=cand.fused_xent,
        sentinel=cand.sentinel,
        obs=cand.obs,
    )
    if cand.engine in ("dp", "zero1"):
        from tpudml.parallel.dp import DataParallel

        eng = DataParallel(
            model, opt, _mesh(mesh_axes, world),
            stacked_batches=False,
            accum_steps=cand.accum_steps,
            zero1=cand.zero1,
            zero1_overlap=cand.zero1_overlap,
            **common,
        )
    elif cand.engine in ("fsdp", "fsdp_tp"):
        from tpudml.parallel.fsdp import FSDP
        from tpudml.parallel.mp import tensor_parallel_rules

        eng = FSDP(
            model, opt, _mesh(mesh_axes, world),
            base_rule=(
                tensor_parallel_rules("model")
                if cand.engine == "fsdp_tp" else None
            ),
            accum_steps=cand.accum_steps,
            **common,
        )
    elif cand.engine == "tp":
        from tpudml.parallel.mp import GSPMDParallel, tensor_parallel_rules

        eng = GSPMDParallel(
            model, opt, _mesh(mesh_axes, world),
            rule=tensor_parallel_rules("model"),
            axis_name="model",
            accum_steps=cand.accum_steps,
            **common,
        )
    elif cand.engine == "pp_dp":
        from tpudml.models import (
            TransformerBlock,
            TransformerEmbed,
            TransformerHead,
        )
        from tpudml.nn.layers import Sequential
        from tpudml.parallel.pp import GPipe

        stages = mesh_axes["stage"]
        per_stage = spec.num_layers // stages
        block = TransformerBlock(
            spec.embed_dim, spec.num_heads, causal=True, impl="full",
            rope=True,
        )
        if per_stage > 1:
            block = Sequential(tuple(
                dataclasses.replace(block) for _ in range(per_stage)
            ))
        eng = GPipe(
            block,
            n_microbatches=PP_MICROBATCHES,
            mesh=_mesh(mesh_axes, world),
            optimizer=opt,
            prologue=TransformerEmbed(
                spec.vocab_size, spec.embed_dim, spec.seq_len,
                use_pos_embed=False,  # blocks carry RoPE
            ),
            epilogue=TransformerHead(spec.embed_dim, spec.vocab_size),
            batch_axis="data",
            sentinel=cand.sentinel,
            obs=cand.obs,
        )
    else:
        raise ValueError(f"unknown engine {cand.engine!r}")
    ts = eng.create_state(seed_key(0))
    step = eng.make_train_step()
    x, y = _batch(spec, world)
    return eng, ts, step, (x, y)


def verify_candidate(
    spec: ModelSpec,
    cand: Candidate,
    hbm_budget_bytes: int | None = None,
) -> dict:
    """Build, trace, and run the dataflow rules over the candidate.

    Returns the plan's ``verification`` record plus the traced
    ``predicted`` costs.  ``ok`` is False when any error-severity
    finding (J112–J116 family) fires — the caller demotes the
    candidate and tries the next survivor.
    """
    import jax

    from tpudml.analysis.cost import peak_live_bytes
    from tpudml.analysis.dataflow import analyze_dataflow
    from tpudml.analysis.findings import RULES
    from tpudml.analysis.jaxpr_pass import analyze_closed_jaxpr

    _, ts, step, (x, y) = build_candidate(spec, cand)
    fn = getattr(step, "jitted", step)
    entrypoint = f"plan:{cand.key()}"
    in_specs = getattr(step, "in_specs", None)
    mesh_axes = getattr(step, "mesh_axes", None)
    closed = jax.make_jaxpr(fn)(ts, x, y)
    findings = analyze_closed_jaxpr(
        closed,
        entrypoint=entrypoint,
        in_specs=in_specs,
        mesh_axes=mesh_axes,
        hbm_budget_bytes=hbm_budget_bytes,
    )
    flow = analyze_dataflow(
        closed, entrypoint, in_specs=in_specs, mesh_axes=mesh_axes
    )
    traced_comm = float(
        sum(ev.wire_bytes * ev.trips for ev in flow.comm_events)
    )
    peak = int(peak_live_bytes(closed))
    # J116 (over HBM budget) is warn-severity for the reporting CLI but
    # a hard plan rejection here: an over-budget winner never ships.
    errors = [
        f for f in findings
        if RULES[f.rule][0] == "error" or f.rule == "J116"
    ]
    return {
        "entrypoint": entrypoint,
        "ok": not errors,
        "findings": [dataclasses.asdict(f) for f in findings],
        "predicted": {
            "comm_wire_bytes": traced_comm,
            "peak_hbm_bytes": peak,
        },
    }


def plan_drift_findings(plan: dict) -> list:
    """Re-trace the plan's winning entrypoint with rule J118 armed.

    The contract check ``python -m tpudml.analysis --plan`` runs: build
    the winner the plan describes, trace it, and compare the traced
    collective wire bytes + peak-live HBM against the plan's
    ``predicted`` block (10% tolerance, the obs drift threshold).  A
    fresh plan is green by construction — ``predicted`` was stamped from
    this same trace; code drift after emission is what fires.
    """
    import jax

    from tpudml.analysis.jaxpr_pass import analyze_closed_jaxpr
    from tpudml.plan.space import Candidate

    spec = ModelSpec.from_dict(plan["spec"])
    cand = Candidate.from_dict(plan["winner"]["candidate"])
    _, ts, step, (x, y) = build_candidate(spec, cand)
    fn = getattr(step, "jitted", step)
    closed = jax.make_jaxpr(fn)(ts, x, y)
    return analyze_closed_jaxpr(
        closed,
        entrypoint=f"plan:{cand.key()}",
        in_specs=getattr(step, "in_specs", None),
        mesh_axes=getattr(step, "mesh_axes", None),
        hbm_budget_bytes=plan.get("hbm_budget_bytes"),
        plan=plan,
    )


def make_plan(
    spec: ModelSpec,
    world: int,
    hbm_budget_bytes: int | None = None,
    engines=None,
    verify: bool = True,
    calibration=None,
    replan: dict | None = None,
) -> dict:
    """enumerate → prune → score → verify-the-winner → plan dict.

    ``calibration`` (a :class:`tpudml.plan.score.Calibration`) re-scores
    the lattice with measured constants — the drift-triggered re-plan
    path; ``replan`` is the provenance record an adaptive re-plan stamps
    (trigger + old winner + receipts), recorded verbatim.  Both default
    to None, which is what the corresponding plan keys serialize as for
    a fresh plan.
    """
    cands = enumerate_candidates(world, engines=engines)
    survivors, dropped = prune(spec, cands, hbm_budget_bytes)
    if not survivors:
        raise RuntimeError(
            f"no feasible candidate at world {world}: all "
            f"{len(cands)} pruned"
        )
    scored = [
        (score_candidate(spec, c, calibration=calibration), c)
        for c in survivors
    ]
    scored.sort(key=lambda sc: (sc[0].per_token_s, sc[1].key()))

    demoted = []
    verification = {"entrypoint": None, "ok": True, "findings": []}
    predicted = None
    winner_idx = 0
    if verify:
        for i, (_, cand) in enumerate(scored):
            v = verify_candidate(spec, cand, hbm_budget_bytes)
            if v["ok"]:
                winner_idx = i
                predicted = v.pop("predicted")
                verification = v
                break
            demoted.append({
                "candidate": cand.to_dict(),
                "findings": v["findings"],
            })
        else:
            raise RuntimeError(
                f"every scored candidate at world {world} failed "
                f"dataflow verification ({len(demoted)} demoted)"
            )
    score, winner = scored[winner_idx]
    if predicted is None:
        # verify=False: fall back to the analytic estimates so the
        # schema stays total (J118 will then hold code to the model).
        predicted = {
            "comm_wire_bytes": score.comm_wire_bytes,
            "peak_hbm_bytes": score.est_hbm_bytes,
        }
    verification["demoted"] = demoted
    return {
        "version": PLAN_VERSION,
        "world": world,
        "spec": spec.to_dict(),
        "hbm_budget_bytes": hbm_budget_bytes,
        "winner": {
            "candidate": winner.to_dict(),
            "score": score.to_dict(),
        },
        "engine_config": engine_config(winner),
        "ranking": [
            {"candidate": c.to_dict(), "score": s.to_dict()}
            for s, c in scored
        ],
        "pruned": [r.to_dict() for r in dropped],
        "predicted": predicted,
        "verification": verification,
        "calibration": (
            calibration.to_dict() if calibration is not None else None
        ),
        "replan": replan,
    }


def engine_config(cand: Candidate) -> dict:
    """The flat runnable knob record ``--plan plan.json`` wiring
    consumes (core/config.py merges it into TrainConfig)."""
    return {
        "engine": cand.engine,
        "mesh": cand.mesh_dict,
        "zero1": cand.zero1,
        "zero1_overlap": cand.zero1_overlap,
        "accum_steps": cand.accum_steps,
        "fused_xent": cand.fused_xent,
        "sentinel": cand.sentinel,
        "obs": cand.obs,
        "tp_overlap": cand.tp_overlap,
        "aggregation": "allreduce",
    }


def plan_to_json(plan: dict) -> str:
    """Byte-deterministic serialization — the determinism test pins
    two same-input emissions to identical bytes."""
    return json.dumps(plan, indent=2, sort_keys=True) + "\n"


def load_plan(path: str) -> dict:
    """Read a plan.json, accepting every supported schema version.

    v1 files (pre-calibration) are upgraded in-memory: the v2-only keys
    are filled with their fresh-plan null values so downstream readers
    can rely on the total v2 schema. The on-disk file is never touched.
    """
    with open(path) as fh:
        plan = json.load(fh)
    ver = plan.get("version")
    if ver not in SUPPORTED_PLAN_VERSIONS:
        raise ValueError(
            f"{path}: plan version {ver!r} not in supported "
            f"{SUPPORTED_PLAN_VERSIONS}"
        )
    if ver < PLAN_VERSION:
        plan.setdefault("calibration", None)
        plan.setdefault("replan", None)
    return plan
