"""Planner search space: model specs and candidate enumeration.

Pure Python (no jax import) — enumeration must stay cheap and
deterministic so the planner can be exercised meshless and its output
byte-pinned.  A *candidate* is one fully-specified engine
configuration; the flat dict form (:meth:`Candidate.to_dict`) is the
record the capability-table predicates and the prune pass read.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Iterable, Sequence

#: Engine chains the planner knows how to build, score, and emit.
ENGINES = ("dp", "zero1", "fsdp", "tp", "fsdp_tp", "pp_dp")


@dataclass(frozen=True)
class ModelSpec:
    """Decoder-only LM shape the planner sizes candidates against.

    ``per_chip_batch`` is the data-parallel per-chip row count; the
    global workload per step is fixed at ``per_chip_batch × world``
    rows regardless of mesh shape, so candidates that do not shard the
    batch (pure TP) are charged the full global batch per device —
    comparisons are per fixed global work, never per whatever batch
    happens to fit.
    """

    vocab_size: int
    embed_dim: int
    num_heads: int
    num_layers: int
    seq_len: int
    per_chip_batch: int
    dtype_bytes: int = 4
    mlp_ratio: int = 4

    def global_batch(self, world: int) -> int:
        return self.per_chip_batch * world

    def param_count(self) -> int:
        """Parameter count of the matching TransformerLM (rope=True, so
        no learned position table): embedding + per-block attention/MLP/
        layernorms + final norm + untied head."""
        d, v, h = self.embed_dim, self.vocab_size, self.mlp_ratio * self.embed_dim
        attn = 4 * (d * d + d)
        mlp = d * h + h + h * d + d
        norms = 2 * 2 * d
        block = attn + mlp + norms
        return v * d + self.num_layers * block + 2 * d + d * v + v

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ModelSpec":
        return cls(**d)


def flagship_lm() -> ModelSpec:
    """The CPU-dryrun flagship spec — the same shape ``bench.py``'s
    dryrun rows train, so planner ranks and measured step times talk
    about the identical workload."""
    return ModelSpec(
        vocab_size=256,
        embed_dim=64,
        num_heads=4,
        num_layers=2,
        seq_len=128,
        per_chip_batch=4,
    )


@dataclass(frozen=True)
class Candidate:
    """One point of the search space. ``mesh`` is an axis-name → size
    mapping stored as a sorted tuple of pairs (frozen dataclasses need
    hashable fields)."""

    engine: str
    mesh: tuple  # tuple[tuple[str, int], ...]
    zero1: bool
    zero1_overlap: bool
    accum_steps: int
    fused_xent: bool
    sentinel: bool
    obs: bool
    # Chunked psum-overlapped TP matmuls (parallel/overlap.py): hide
    # (K−1)/K of the per-block activation allreduce behind the chunked
    # matmul. Only meaningful with a model axis — the capability row
    # ``tp_overlap_needs_model_axis`` prunes the rest of the lattice.
    tp_overlap: bool = False

    @property
    def mesh_dict(self) -> dict:
        return dict(self.mesh)

    def key(self) -> str:
        """Canonical id — stable sort key and the plan.json label."""
        mesh = ",".join(f"{a}={s}" for a, s in self.mesh)
        flags = (
            f"z{int(self.zero1)}{int(self.zero1_overlap)}"
            f"a{self.accum_steps}f{int(self.fused_xent)}"
            f"s{int(self.sentinel)}o{int(self.obs)}"
            f"t{int(self.tp_overlap)}"
        )
        return f"{self.engine}[{mesh}]{flags}"

    def to_dict(self) -> dict:
        """Flat record for the capability predicates and plan.json."""
        return {
            "engine": self.engine,
            "mesh": self.mesh_dict,
            "zero1": self.zero1,
            "zero1_overlap": self.zero1_overlap,
            "accum_steps": self.accum_steps,
            "fused_xent": self.fused_xent,
            "sentinel": self.sentinel,
            "obs": self.obs,
            "tp_overlap": self.tp_overlap,
            "aggregation": "allreduce",
            "schedule": "gpipe" if self.engine == "pp_dp" else None,
            "key": self.key(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Candidate":
        return cls(
            engine=d["engine"],
            mesh=tuple(sorted(d["mesh"].items())),
            zero1=d["zero1"],
            zero1_overlap=d["zero1_overlap"],
            accum_steps=d["accum_steps"],
            fused_xent=d["fused_xent"],
            sentinel=d["sentinel"],
            obs=d["obs"],
            tp_overlap=d.get("tp_overlap", False),  # pre-v3 plan records
        )


def _two_axis(world: int) -> list:
    """(a, b) with a*b == world, both >= 2 — every genuine 2-D mesh."""
    return [
        (a, world // a) for a in range(2, world) if world % a == 0
        and world // a >= 2
    ]


def _engine_meshes(engine: str, world: int) -> list:
    """Mesh shapes an engine chain can occupy at ``world`` chips.

    At ``world == 1`` only plain DP is enumerable: every other chain
    exists to shard something across chips (ZeRO-1/FSDP shard state
    over data, TP shards features, PP shards layers) and degenerates
    to DP-with-extra-collectives on a single chip — the planner's
    answer there is an *empty* mesh list, which the re-plan path turns
    into an honest "infeasible at world 1" receipt rather than a
    silently-degenerate candidate.
    """
    if engine == "dp":
        return [(("data", world),)]
    if engine in ("zero1", "fsdp"):
        return [(("data", world),)] if world >= 2 else []
    if engine == "tp":
        return [(("model", world),)] if world >= 2 else []
    if engine == "fsdp_tp":
        return [
            (("data", a), ("model", b)) for a, b in _two_axis(world)
        ]
    if engine == "pp_dp":
        return [
            (("data", a), ("stage", b)) for a, b in _two_axis(world)
        ]
    raise ValueError(f"unknown engine {engine!r}")


def enumerate_candidates(
    world: int, engines: Sequence[str] | None = None
) -> list:
    """The full knob cross-product, in deterministic order.

    Deliberately includes combinations the capability table rejects
    (e.g. ``zero1_overlap`` without zero1, pp×fused_xent): the prune
    pass drops them *with the table's reason*, so the plan's dropped-
    candidate report demonstrates the shared rejection rules firing
    rather than silently never generating the combination.
    """
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    out = []
    for engine in engines if engines is not None else ENGINES:
        if engine not in ENGINES:
            raise ValueError(f"unknown engine {engine!r}; one of {ENGINES}")
        for mesh in _engine_meshes(engine, world):
            for overlap in (False, True):
                for accum in (1, 2):
                    for fused in (False, True):
                        for sentinel in (False, True):
                            for obs in (False, True):
                                for tp_ov in (False, True):
                                    out.append(Candidate(
                                        engine=engine,
                                        mesh=mesh,
                                        zero1=engine == "zero1",
                                        zero1_overlap=overlap,
                                        accum_steps=accum,
                                        fused_xent=fused,
                                        sentinel=sentinel,
                                        obs=obs,
                                        tp_overlap=tp_ov,
                                    ))
    out.sort(key=Candidate.key)
    return out
