"""Static autosharding planner: enumerate → prune → score → emit.

Closes the loop ROADMAP item 3 describes: PR 10's dataflow cost model
(``tpudml/analysis``) can price any traced program — this package turns
that reporter into a *decider*.  Given a :class:`~tpudml.plan.space.ModelSpec`
and a chip count it

1. **enumerates** the candidate space (``space.py``): mesh factorization
   × engine chain {DP, ZeRO-1, FSDP, TP, FSDP×TP, PP×DP} × zero1-overlap
   × accumulation × fused-kernel / sentinel / obs knobs;
2. **prunes** statically (``prune.py``): divisibility of heads / vocab /
   layers against the axis sizes, HBM over budget via the same peak-live
   estimate J116 uses, and every engine composition rejection through the
   shared capability table (``tpudml.capabilities``) the engines
   themselves raise from — planner and runtime cannot disagree;
3. **scores** survivors (``score.py``) on the shared ring wire model
   (``tpudml.comm.timing.collective_wire_bytes``) plus a roofline
   step-time estimate (compute FLOPs vs MXU, memory traffic vs HBM,
   exposed comm after overlap attribution);
4. **emits** the winner (``emit.py``) as a runnable ``plan.json`` (v2
   schema; v1 files still load) — and self-verifies it first: the
   winning engine is built on the dryrun mesh, traced, and run through
   the J112–J116 dataflow rules; a plan that would lose a psum or blow
   the HBM budget is rejected before it ever runs, and the traced
   comm/HBM land in the plan's ``predicted`` block, which rule J118
   later holds the code to.

Since PR 16 the planner is also a *runtime* controller: on an elastic
membership change ``tpudml.elastic.replan.Replanner`` re-runs this
pipeline at the new world size (recording receipts for why the old
config lost), and a J118/drift firing re-scores the lattice with the
measured constants folded in as a :class:`~tpudml.plan.score.Calibration`
— both land in the plan's v2 ``replan`` / ``calibration`` blocks.

CLI: ``python -m tpudml.plan`` (``--format text|json|github``,
``--check`` for the world-4/8 smoke).  Validation the other way:
``python bench.py --plan`` measures the dryrun regimes and pins the
planner's top-1 within tolerance of the measured best.
"""

from tpudml.plan.emit import (
    PLAN_VERSION,
    SUPPORTED_PLAN_VERSIONS,
    build_candidate,
    load_plan,
    make_plan,
    plan_drift_findings,
    plan_to_json,
    verify_candidate,
)
from tpudml.plan.prune import PruneRecord, prune
from tpudml.plan.score import Calibration, Hardware, Score, score_candidate
from tpudml.plan.space import (
    Candidate,
    ModelSpec,
    enumerate_candidates,
    flagship_lm,
)

__all__ = [
    "PLAN_VERSION",
    "SUPPORTED_PLAN_VERSIONS",
    "Calibration",
    "Candidate",
    "Hardware",
    "ModelSpec",
    "PruneRecord",
    "Score",
    "build_candidate",
    "enumerate_candidates",
    "flagship_lm",
    "load_plan",
    "make_plan",
    "plan_drift_findings",
    "plan_to_json",
    "prune",
    "score_candidate",
    "verify_candidate",
]
