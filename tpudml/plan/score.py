"""Roofline scoring of pruned candidates.

Three priced terms per candidate, all per device per optimizer step and
all for the *fixed global workload* (``spec.global_batch(world)`` rows —
accumulation splits that batch into micro-batches, it never adds rows):

- **compute**: dense-matmul FLOPs ``6·params·tokens`` plus the
  quadratic attention term, divided by every mesh axis that splits the
  work (data shards rows, model shards features, stage shards layers —
  the stage axis additionally pays the pipeline bubble ``(M+S-1)/M``);
- **memory**: weight streaming (fwd + bwd + update), optimizer-state
  update traffic (sharded 1/N under ZeRO-1/FSDP — the whole point of
  those regimes), and the logits round-trip the fused xent kernel
  avoids materializing;
- **comm**: explicit collectives priced on the shared ring wire model
  (``tpudml.comm.timing.collective_wire_bytes`` — the same table the
  measured ``CommStats`` counters and the ``--cost`` reports use), with
  overlap attribution: ZeRO-1's param all_gather counts as *hidden*
  when ``zero1_overlap`` double-buffers it behind the micro-batch scan
  (priced from the same exposed-vs-hidden split ``overlap_report()``
  measures), exposed otherwise.

``step_time = max(compute, memory) + exposed_comm`` — the roofline max
for the overlappable device work, plus the comm the schedule cannot
hide.  Ranking metric is per-token time so candidates with different
meshes stay comparable.

Nominal TPU-v4-ish constants; absolute seconds are not the contract —
*rank order* is, and it is pinned against ``bench.py --plan`` dryrun
measurements.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from tpudml.comm.timing import collective_wire_bytes
from tpudml.plan.space import Candidate, ModelSpec

#: Micro-batch count the planner assumes for PP×DP (GPipe) candidates.
PP_MICROBATCHES = 4

#: Fraction of optimizer-state bytes moved per update (read p/m/v,
#: write p/m/v, plus the gradient read) — AdamW-shaped.
_UPDATE_TRAFFIC_FACTOR = 7.0

#: Sentinel / obs knobs add a small in-graph overhead (an is-finite
#: reduction / telemetry counters) — real but tiny; priced as a
#: multiplicative epsilon so knob-on never beats knob-off on ties.
_SENTINEL_OVERHEAD = 0.01
_OBS_OVERHEAD = 0.005


@dataclass(frozen=True)
class Hardware:
    """Nominal accelerator constants the roofline divides by."""

    flops_per_s: float = 1.8e14
    hbm_bytes_per_s: float = 1.2e12
    ici_bytes_per_s: float = 9.0e10


DEFAULT_HARDWARE = Hardware()


@dataclass(frozen=True)
class Calibration:
    """Measured correction factors folded into the roofline terms.

    The self-calibrating half of the planner loop: when the drift
    monitor (``obs/drift.py`` / rule J118) observes measured comm or
    HBM deviating from the static model past the shared threshold, the
    re-plan re-scores the lattice with these scales applied — the cost
    model learns the constant it was wrong by instead of ranking with
    it forever.  ``basis`` keeps the drift records the scales were
    fitted from, so a plan's ``calibration`` block is auditable.
    """

    comm_scale: float = 1.0
    hbm_scale: float = 1.0
    source: str = "default"
    basis: tuple = ()  # tuple of drift-record dicts (sorted-key frozen)

    @classmethod
    def from_drift_records(cls, records, source: str = "obs/drift") -> "Calibration":
        """Fit ``comm_scale`` as the wire-byte-weighted measured/static
        ratio over the drift records — the single multiplicative
        constant that would zero the aggregate drift."""
        static = sum(float(r["static_wire_bytes"]) for r in records)
        measured = sum(float(r["measured_wire_bytes"]) for r in records)
        scale = measured / static if static > 0 else 1.0
        basis = tuple(
            {
                "entrypoint": r["entrypoint"],
                "static_wire_bytes": float(r["static_wire_bytes"]),
                "measured_wire_bytes": float(r["measured_wire_bytes"]),
                "rel_err": float(r["rel_err"]),
            }
            for r in records
        )
        return cls(comm_scale=scale, source=source, basis=basis)

    def to_dict(self) -> dict:
        return {
            "comm_scale": self.comm_scale,
            "hbm_scale": self.hbm_scale,
            "source": self.source,
            "basis": [dict(b) for b in self.basis],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Calibration":
        return cls(
            comm_scale=d["comm_scale"],
            hbm_scale=d["hbm_scale"],
            source=d["source"],
            basis=tuple(d.get("basis", ())),
        )


@dataclass(frozen=True)
class Score:
    """Priced candidate: the ranked table row and plan.json record."""

    step_time_s: float
    compute_s: float
    memory_s: float
    exposed_comm_s: float
    hidden_comm_s: float
    comm_wire_bytes: float
    est_hbm_bytes: int
    tokens_per_step: int

    @property
    def per_token_s(self) -> float:
        return self.step_time_s / self.tokens_per_step

    def to_dict(self) -> dict:
        d = asdict(self)
        d["per_token_s"] = self.per_token_s
        return d


def _axes(cand: Candidate) -> tuple:
    m = cand.mesh_dict
    return m.get("data", 1), m.get("model", 1), m.get("stage", 1)


def estimate_hbm(spec: ModelSpec, cand: Candidate) -> int:
    """Static per-chip peak-live estimate (same quantity rule J116
    budgets on the traced program; this is the closed-form preview the
    prune pass can afford for every candidate).

    params + grads + optimizer moments under the candidate's sharding,
    plus the live activation working set and — unless the fused kernel
    streams them — the materialized [B, T, V] logits.
    """
    data, model, stage = _axes(cand)
    p_bytes = spec.param_count() * spec.dtype_bytes
    # Parameter residency: TP/stage shard structurally; FSDP shards
    # over data too; ZeRO-1 shards only the optimizer moments.
    param_div = model * stage * (data if cand.engine in ("fsdp", "fsdp_tp") else 1)
    opt_div = model * stage * (
        data if (cand.zero1 or cand.engine in ("fsdp", "fsdp_tp")) else 1
    )
    params = p_bytes / param_div
    grads = p_bytes / param_div
    moments = 2 * p_bytes / opt_div
    rows = spec.global_batch(_world(cand)) // data
    micro_rows = max(1, rows // max(1, cand.accum_steps))
    if cand.engine == "pp_dp":
        micro_rows = max(1, rows // PP_MICROBATCHES)
    act = (
        spec.num_layers
        * micro_rows
        * spec.seq_len
        * spec.embed_dim
        * spec.dtype_bytes
        * 12  # qkv/attn/mlp residual working set per layer
    ) / (model * stage)
    logits = 0.0
    if not cand.fused_xent:
        logits = micro_rows * spec.seq_len * spec.vocab_size * spec.dtype_bytes / model
    return int(params + grads + moments + act + logits)


def _world(cand: Candidate) -> int:
    w = 1
    for _, s in cand.mesh:
        w *= s
    return w


def score_candidate(
    spec: ModelSpec,
    cand: Candidate,
    hw: Hardware = DEFAULT_HARDWARE,
    calibration: Calibration | None = None,
) -> Score:
    data, model, stage = _axes(cand)
    world = _world(cand)
    n_params = spec.param_count()
    p_bytes = n_params * spec.dtype_bytes
    rows = spec.global_batch(world)
    tokens = rows * spec.seq_len

    # ---- compute: every mesh axis divides the matmul work; the stage
    # axis pays the GPipe bubble on top.
    flops = 6.0 * n_params * tokens
    flops += 12.0 * spec.num_layers * rows * spec.seq_len**2 * spec.embed_dim
    flops /= data * model * stage
    compute_s = flops / hw.flops_per_s
    if stage > 1:
        m = PP_MICROBATCHES
        compute_s *= (m + stage - 1) / m

    # ---- memory: weight streaming + sharded update + logits traffic.
    weight_div = model * stage
    opt_div = model * stage * (
        data if (cand.zero1 or cand.engine in ("fsdp", "fsdp_tp")) else 1
    )
    traffic = 3.0 * p_bytes / weight_div  # fwd read, bwd read, grad write
    traffic += _UPDATE_TRAFFIC_FACTOR * 3.0 * p_bytes / opt_div
    if not cand.fused_xent:
        # materialize + re-read the [B, T, V] logits around the softmax
        traffic += 3.0 * (rows // data) * spec.seq_len * spec.vocab_size \
            * spec.dtype_bytes / model
    memory_s = traffic / hw.hbm_bytes_per_s

    # ---- comm: ring wire model, per device, with overlap attribution.
    exposed = 0.0
    hidden = 0.0
    accum = max(1, cand.accum_steps)
    if cand.engine == "dp":
        exposed += collective_wire_bytes("psum", p_bytes, data)
    elif cand.engine == "zero1":
        exposed += collective_wire_bytes("psum_scatter", p_bytes, data)
        gather = collective_wire_bytes("all_gather", p_bytes / data, data)
        if cand.zero1_overlap and accum >= 2:
            hidden += gather  # double-buffered behind the micro scan
        else:
            exposed += gather
    elif cand.engine in ("fsdp", "fsdp_tp"):
        shard = p_bytes / (model * data)
        # params re-gathered on use, per micro-batch, fwd + bwd
        exposed += 2 * accum * collective_wire_bytes("all_gather", shard, data)
        exposed += collective_wire_bytes("psum_scatter", p_bytes / model, data)
    elif cand.engine == "pp_dp":
        micro_rows = max(1, rows // data // PP_MICROBATCHES)
        boundary = micro_rows * spec.seq_len * spec.embed_dim * spec.dtype_bytes
        # activations fwd + grads bwd across each stage boundary
        exposed += 2 * PP_MICROBATCHES * (stage - 1) / stage \
            * collective_wire_bytes("ppermute", boundary, stage)
        exposed += collective_wire_bytes("psum", p_bytes / stage, data)
    if model > 1:
        # TP: two psums per block per direction of [B_dev, T, d] acts.
        act = (rows // data) * spec.seq_len * spec.embed_dim * spec.dtype_bytes
        tp_wire = 4 * spec.num_layers * collective_wire_bytes("psum", act, model)
        if cand.tp_overlap:
            # Chunked collective-matmul placement (parallel/overlap.py):
            # chunk i's psum rides under chunk i+1's matmul, so only the
            # last chunk's reduce (1/K of the wire) stays exposed — the
            # same exposed-vs-hidden attribution the zero1_overlap
            # branch uses for its double-buffered gather.
            from tpudml.parallel.overlap import OVERLAP_CHUNKS

            exposed += tp_wire / OVERLAP_CHUNKS
            hidden += tp_wire * (OVERLAP_CHUNKS - 1) / OVERLAP_CHUNKS
        else:
            exposed += tp_wire
        if cand.fused_xent:
            # vocab-sharded head: online lse-merge statistics, [B_dev, T]
            stats = 3 * (rows // data) * spec.seq_len * spec.dtype_bytes
            exposed += collective_wire_bytes("psum", stats, model)
    comm_scale = calibration.comm_scale if calibration is not None else 1.0
    hbm_scale = calibration.hbm_scale if calibration is not None else 1.0
    exposed_s = exposed * comm_scale / hw.ici_bytes_per_s
    hidden_s = hidden * comm_scale / hw.ici_bytes_per_s

    step = max(compute_s, memory_s) + exposed_s
    if cand.sentinel:
        step *= 1.0 + _SENTINEL_OVERHEAD
    if cand.obs:
        step *= 1.0 + _OBS_OVERHEAD
    return Score(
        step_time_s=step,
        compute_s=compute_s,
        memory_s=memory_s,
        exposed_comm_s=exposed_s,
        hidden_comm_s=hidden_s,
        comm_wire_bytes=(exposed + hidden) * comm_scale,
        est_hbm_bytes=int(estimate_hbm(spec, cand) * hbm_scale),
        tokens_per_step=tokens,
    )
