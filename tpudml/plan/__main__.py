"""CLI: ``python -m tpudml.plan [--world N] [--out plan.json] [...]``.

Emits the winning candidate as a runnable ``plan.json`` (v1 schema) and
prints the ranked candidate table.  ``--format`` follows the analysis
CLI contract: ``text`` (human table), ``json`` (the full plan),
``github`` (workflow-annotation lines — ``notice`` for the winner,
``warning`` per demoted candidate, ``error`` when planning fails).
``--check`` is the CI smoke: plan the flagship spec at world 4 and 8,
require a verified winner at both, write nothing.

The self-verification trace needs >= 2 visible devices, so an 8-device
CPU host platform is provisioned before the first backend touch — the
same dance as ``python -m tpudml.analysis`` — making the planner
runnable on any dev box, no TPU required.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

PLAN_OUT_PATH = os.path.join("analysis", "plan.json")


def _provision_devices() -> None:
    """Force an 8-device CPU platform before jax initializes a backend."""
    try:
        # Repo harness helper (handles site hooks that latch JAX_PLATFORMS).
        from __graft_entry__ import _provision_cpu_mesh

        _provision_cpu_mesh(8)
        return
    except Exception:
        pass
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _spec_from_args(args):
    from tpudml.plan.space import ModelSpec, flagship_lm

    if args.spec:
        with open(args.spec) as fh:
            return ModelSpec.from_dict(json.load(fh))
    return flagship_lm()


def _fmt_row(rank: int, entry: dict) -> str:
    c, s = entry["candidate"], entry["score"]
    return (f"{rank:3d}  {s['per_token_s']:.3e}  {s['step_time_s']:.3e}  "
            f"{s['exposed_comm_s']:.2e}  {s['est_hbm_bytes']:>12d}  "
            f"{c['key']}")


def _print_text(plan: dict, top: int) -> None:
    w = plan["winner"]
    print(f"plan v{plan['version']}  world={plan['world']}  "
          f"spec={plan['spec']['embed_dim']}d/"
          f"{plan['spec']['num_layers']}L/"
          f"{plan['spec']['num_heads']}h/v{plan['spec']['vocab_size']}")
    print(f"winner: {w['candidate']['key']}")
    ver = plan["verification"]
    print(f"verified: entrypoint={ver['entrypoint']} ok={ver['ok']} "
          f"findings={len(ver['findings'])} demoted={len(ver['demoted'])}")
    print(f"predicted: comm_wire_bytes={plan['predicted']['comm_wire_bytes']:.0f} "
          f"peak_hbm_bytes={plan['predicted']['peak_hbm_bytes']}")
    print(f"\nrank  per_token_s  step_time_s  exposed_s   est_hbm_bytes"
          f"  candidate")
    for i, entry in enumerate(plan["ranking"][:top], 1):
        print(_fmt_row(i, entry))
    shown = min(top, len(plan["ranking"]))
    print(f"\n{len(plan['ranking'])} ranked ({shown} shown), "
          f"{len(plan['pruned'])} pruned")
    if plan["pruned"]:
        by_rule: dict = {}
        for r in plan["pruned"]:
            by_rule[r["rule"]] = by_rule.get(r["rule"], 0) + 1
        for rule in sorted(by_rule):
            print(f"  {by_rule[rule]:4d}  {rule}")


def _print_github(plan: dict) -> None:
    # Same annotation grammar as ``python -m tpudml.analysis --format
    # github``: '::' inside a message would end the annotation early.
    def msg(s: str) -> str:
        return s.replace("::", ":")

    w = plan["winner"]
    print(f"::notice ::PLAN[world={plan['world']}]: winner "
          + msg(w["candidate"]["key"])
          + f" per_token_s={w['score']['per_token_s']:.3e}")
    for d in plan["verification"]["demoted"]:
        rules = ",".join(sorted({f["rule"] for f in d["findings"]}))
        print(f"::warning ::PLAN[world={plan['world']}]: demoted "
              + msg(d["candidate"]["key"]) + f" ({rules})")


def _check(parser) -> int:
    """CI smoke: verified winner at world 4 and 8 on the flagship spec."""
    from tpudml.plan.emit import make_plan
    from tpudml.plan.space import flagship_lm

    spec = flagship_lm()
    failures = 0
    for world in (4, 8):
        try:
            plan = make_plan(spec, world)
        except Exception as exc:  # noqa: BLE001 — CI smoke reports, never raises
            print(f"::error ::PLAN[world={world}]: {exc}")
            failures += 1
            continue
        ver = plan["verification"]
        ok = ver["ok"] and not ver["demoted"]
        status = "ok" if ok else "FAIL"
        print(f"plan --check world={world}: {status} winner="
              f"{plan['winner']['candidate']['key']} "
              f"findings={len(ver['findings'])} demoted={len(ver['demoted'])}")
        if not ok:
            failures += 1
    return 1 if failures else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudml.plan",
        description="Static autosharding planner: enumerate, prune, "
                    "score, and emit a verified runnable plan.json.",
    )
    parser.add_argument("--world", type=int, default=8,
                        help="chip count to plan for (default: 8)")
    parser.add_argument("--spec", default=None, metavar="JSON",
                        help="ModelSpec json file (default: the dryrun "
                             "flagship LM)")
    parser.add_argument("--hbm_budget", type=float, default=None,
                        metavar="MB",
                        help="prune candidates whose static peak-live "
                             "estimate exceeds this many megabytes (and "
                             "arm J116 on the verification trace)")
    parser.add_argument("--engines", default=None, metavar="A,B",
                        help="restrict the engine chains enumerated "
                             "(default: all)")
    parser.add_argument("--out", default=PLAN_OUT_PATH, metavar="PATH",
                        help=f"plan.json output path (default: "
                             f"{PLAN_OUT_PATH}; '-' to skip writing)")
    parser.add_argument("--format", default="text", dest="fmt",
                        choices=("text", "json", "github"),
                        help="stdout format (default: text)")
    parser.add_argument("--top", type=int, default=10,
                        help="ranked-table rows to print (default: 10)")
    parser.add_argument("--no-verify", action="store_true",
                        help="skip the trace + J112-J116 verification "
                             "(plan carries analytic estimates instead)")
    parser.add_argument("--check", action="store_true",
                        help="CI smoke: plan the flagship spec at world "
                             "4 and 8, exit non-zero unless both verify")
    args = parser.parse_args(argv)

    _provision_devices()
    if args.check:
        return _check(parser)

    from tpudml.plan.emit import make_plan, plan_to_json

    engines = None
    if args.engines:
        engines = [e.strip() for e in args.engines.split(",") if e.strip()]
    hbm_budget_bytes = None
    if args.hbm_budget is not None:
        hbm_budget_bytes = int(args.hbm_budget * 1e6)

    try:
        plan = make_plan(
            _spec_from_args(args),
            args.world,
            hbm_budget_bytes=hbm_budget_bytes,
            engines=engines,
            verify=not args.no_verify,
        )
    except (RuntimeError, ValueError) as exc:
        if args.fmt == "github":
            print(f"::error ::PLAN[world={args.world}]: {exc}")
        else:
            print(f"planning failed: {exc}", file=sys.stderr)
        return 1

    if args.out != "-":
        out_dir = os.path.dirname(args.out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(args.out, "w") as fh:
            fh.write(plan_to_json(plan))

    if args.fmt == "json":
        print(plan_to_json(plan), end="")
    elif args.fmt == "github":
        _print_github(plan)
    else:
        _print_text(plan, args.top)
        if args.out != "-":
            print(f"\nwrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
