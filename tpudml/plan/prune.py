"""Static feasibility pruning — every dropped candidate is reported.

Three pruning families, checked in order:

1. **capability** — the shared engine composition table
   (``tpudml.capabilities``).  The reason string carries the *exact*
   message the engine constructor would raise, because it is the same
   table entry; planner and runtime cannot skew.
2. **divisibility** — heads/vocab against the ``model`` axis, layers
   against the ``stage`` axis: shapes a manual shard body cannot demote
   its way out of.
3. **hbm** — the closed-form per-chip peak-live preview
   (``score.estimate_hbm``, the same quantity rule J116 budgets on the
   traced winner) against the caller's budget.

The contract is *honesty*: ``prune()`` returns every dropped candidate
with its rule and reason — no silent caps, pinned by test.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpudml.capabilities import TABLE, candidate_rejection
from tpudml.plan.score import estimate_hbm
from tpudml.plan.space import Candidate, ModelSpec


@dataclass(frozen=True)
class PruneRecord:
    candidate: Candidate
    rule: str
    reason: str

    def to_dict(self) -> dict:
        return {
            "candidate": self.candidate.to_dict(),
            "rule": self.rule,
            "reason": self.reason,
        }


def _check(spec: ModelSpec, cand: Candidate, hbm_budget_bytes):
    key = candidate_rejection(cand.to_dict())
    if key is not None:
        return f"capability:{key}", TABLE[key].message
    mesh = cand.mesh_dict
    model = mesh.get("model", 1)
    stage = mesh.get("stage", 1)
    if model > 1:
        if spec.num_heads % model:
            return "divisibility", (
                f"num_heads {spec.num_heads} not divisible by the "
                f"'model' axis size {model}"
            )
        if spec.vocab_size % model:
            return "divisibility", (
                f"vocab_size {spec.vocab_size} not divisible by the "
                f"'model' axis size {model}"
            )
    if stage > 1 and spec.num_layers % stage:
        return "divisibility", (
            f"num_layers {spec.num_layers} not divisible by the "
            f"'stage' axis size {stage}"
        )
    if hbm_budget_bytes is not None:
        est = estimate_hbm(spec, cand)
        if est > hbm_budget_bytes:
            return "hbm", (
                f"estimated per-chip peak {est} bytes exceeds the "
                f"budget {hbm_budget_bytes}"
            )
    return None


def prune(
    spec: ModelSpec,
    candidates,
    hbm_budget_bytes: int | None = None,
):
    """(survivors, dropped) — ``len(survivors) + len(dropped)`` always
    equals ``len(candidates)``."""
    survivors, dropped = [], []
    for cand in candidates:
        hit = _check(spec, cand, hbm_budget_bytes)
        if hit is None:
            survivors.append(cand)
        else:
            dropped.append(PruneRecord(cand, *hit))
    return survivors, dropped
