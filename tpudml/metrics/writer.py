"""Metrics / observability.

Re-design of the reference's TensorBoard writer factory (codes/
datawriter.py:6-11: ``getSummaryWriter(epochs, del_dir)`` creating
``./logs/{YYYY-MM-DD}/{HH-MM-SS}-epoch{N}/``) with a backend-pluggable
scalar sink:

- **jsonl** (default, dependency-free): one ``{"tag", "value", "step",
  "wall_time"}`` record per line in ``metrics.jsonl`` — trivially parseable
  by the bench harness and tests.
- **tensorboard** (optional): if ``torch.utils.tensorboard`` is importable,
  event files are written alongside, so the reference's TensorBoard workflow
  keeps working unchanged.

Extended beyond the reference with the scalars the TPU runtime cares about:
per-chip throughput (``imgs_per_sec_per_chip``) and communication-time
accounting (task2's measured quantity, codes/task2/model-mp.py:61-66) are
just tags written through the same interface.
"""

from __future__ import annotations

import json
import math
import shutil
import time
from datetime import datetime
from pathlib import Path


class MetricsWriter:
    def __init__(
        self,
        log_dir: str | Path,
        run_name: str | None = None,
        backends: tuple[str, ...] = ("jsonl",),
        del_dir: bool = False,
    ):
        log_dir = Path(log_dir)
        if del_dir and log_dir.exists():
            shutil.rmtree(log_dir)
        now = datetime.now()
        # Timestamped layout parity: logs/<date>/<time>-<run_name>/; a
        # collision suffix keeps runs started within one second separate.
        sub = now.strftime("%H-%M-%S") + (f"-{run_name}" if run_name else "")
        base = log_dir / now.strftime("%Y-%m-%d") / sub
        self.run_dir = base
        for i in range(1, 1000):
            try:
                self.run_dir.mkdir(parents=True, exist_ok=False)
                break
            except FileExistsError:
                self.run_dir = base.with_name(f"{base.name}-{i}")
        self._jsonl = None
        self._tb = None
        if "jsonl" in backends:
            self._jsonl = open(self.run_dir / "metrics.jsonl", "a", buffering=1)
        if "tensorboard" in backends:
            try:
                from torch.utils.tensorboard import SummaryWriter  # optional

                self._tb = SummaryWriter(log_dir=str(self.run_dir))
            except Exception:
                self._tb = None

    def add_scalar(self, tag: str, value, step: int) -> None:
        """Reference-compatible scalar API (``writer.add_scalar('Train Loss',
        loss, counter)``, codes/task1/pytorch/model.py:57-58).

        Non-finite values serialize as ``null`` with ``"finite": false``
        — ``json.dumps(float("nan"))`` emits a bare ``NaN`` token, which
        is not JSON and broke every strict parser reading
        ``metrics.jsonl`` (a diverged run's loss would corrupt the whole
        file for downstream tooling). Every line this writer emits
        round-trips through ``json.loads``.
        """
        v = float(value)
        rec = {
            "tag": tag,
            "value": v,
            "step": int(step),
            "wall_time": time.time(),
        }
        if not math.isfinite(v):
            rec["value"] = None
            rec["finite"] = False
        if self._jsonl:
            self._jsonl.write(json.dumps(rec) + "\n")
        if self._tb:
            self._tb.add_scalar(tag, v, step)

    def add_scalars(self, scalars: dict, step: int) -> None:
        """Write a dict of scalars in one call (the obs StepStats
        streaming path); insertion order is preserved in the jsonl."""
        for tag, value in scalars.items():
            self.add_scalar(tag, value, step)

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
        if self._tb:
            self._tb.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def get_summary_writer(
    epochs: int, del_dir: bool = False, log_dir: str = "./logs"
) -> MetricsWriter:
    """Drop-in analogue of the reference's ``getSummaryWriter(epochs,
    del_dir)`` factory (codes/datawriter.py:6-11)."""
    return MetricsWriter(
        log_dir,
        run_name=f"epoch{epochs}",
        backends=("jsonl", "tensorboard"),
        del_dir=del_dir,
    )
