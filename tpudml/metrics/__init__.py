from tpudml.metrics.profiler import SpanTimer, annotate, trace
from tpudml.metrics.writer import MetricsWriter, get_summary_writer

__all__ = ["MetricsWriter", "SpanTimer", "annotate", "get_summary_writer", "trace"]
