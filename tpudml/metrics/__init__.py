from tpudml.metrics.writer import MetricsWriter, get_summary_writer

__all__ = ["MetricsWriter", "get_summary_writer"]
