"""Tracing / profiling (SURVEY.md §5.1).

The reference measures performance with inline ``time.time()`` spans and
recommends ``torch.cuda.Event`` timing (codes/task2/model-mp.py:48-79,
sections/task2.tex:69-80); it has no profiler. Here both layers exist:

- :func:`trace` captures an XLA/TPU profile via ``jax.profiler`` into the
  run directory — open in TensorBoard (or Perfetto) to see per-op device
  time, fusion boundaries, and collective overlap; the TPU-accurate
  answer to "where did the step time go".
- :class:`SpanTimer` is the host-side wall-clock layer (the model-mp.py
  accounting, device-synchronized like the ``torch.cuda.Event`` recipe):
  named spans with totals/counts, e.g. ``step`` vs ``comm``.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator

import jax


@contextmanager
def trace(log_dir: str | Path, enabled: bool = True) -> Iterator[None]:
    """Capture a jax.profiler trace under ``log_dir`` (no-op when
    ``enabled`` is False, so call sites can pass a config flag through)."""
    if not enabled:
        yield
        return
    with jax.profiler.trace(str(log_dir)):
        yield


def annotate(name: str):
    """Label a host-side region so it shows up on the trace timeline
    (thin alias of ``jax.profiler.TraceAnnotation``)."""
    return jax.profiler.TraceAnnotation(name)


class SpanTimer:
    """Named wall-clock spans with device synchronization.

    ``sync=`` values are blocked on (``jax.block_until_ready``) before the
    span closes, so async-dispatched XLA work is charged to the span that
    launched it — the semantic of the reference's cuda-Event timing
    (sections/task2.tex:72-80).

    Each span's per-call durations feed a :class:`CommStats`, so
    ``report()`` carries p50/p99 alongside the mean (totals-only means
    hide tail latency — the quantity serving/step-time work cares about)
    on the same interpolation as every other percentile in the repo.

    A :class:`tpudml.obs.Tracer` passed as ``tracer=`` additionally
    receives every span as a structured trace event — SpanTimer is the
    thin wall-clock façade; the tracer is the flight recorder that
    subsumes it.

    Usage::

        timer = SpanTimer()
        with timer.span("step", sync=metrics["loss"]):
            ts, metrics = step(ts, x, y)
        print(timer.report())
    """

    def __init__(self, tracer=None):
        from tpudml.comm.timing import CommStats

        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.stats: dict[str, CommStats] = defaultdict(CommStats)
        self.tracer = tracer

    @contextmanager
    def span(self, name: str, sync=None) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                jax.block_until_ready(sync)
            dt = time.perf_counter() - t0
            self.totals[name] += dt
            self.counts[name] += 1
            self.stats[name].add(dt)
            if self.tracer is not None and self.tracer.enabled:
                dur_us = int(dt * 1e6)
                self.tracer.add_complete(
                    name, cat="timer",
                    ts_us=max(self.tracer.now_us() - dur_us, 0),
                    dur_us=dur_us,
                )

    def mean(self, name: str) -> float:
        return self.totals[name] / max(self.counts[name], 1)

    def percentiles(self, name: str) -> dict:
        """p50/p99 seconds for one span (``{}`` before any call) —
        delegated to ``CommStats.percentiles`` so SpanTimer and the comm
        accounting interpolate identically."""
        return self.stats[name].percentiles()

    def report(self) -> str:
        parts = []
        for name in sorted(self.totals):
            line = (
                f"{name}: {self.totals[name]:.4f}s over {self.counts[name]} "
                f"calls (mean {self.mean(name) * 1e3:.2f}ms"
            )
            pct = self.percentiles(name)
            if pct:
                line += (f", p50 {pct['p50_s'] * 1e3:.2f}ms,"
                         f" p99 {pct['p99_s'] * 1e3:.2f}ms")
            parts.append(line + ")")
        return "\n".join(parts)
