"""Tracing / profiling (SURVEY.md §5.1).

The reference measures performance with inline ``time.time()`` spans and
recommends ``torch.cuda.Event`` timing (codes/task2/model-mp.py:48-79,
sections/task2.tex:69-80); it has no profiler. Here both layers exist:

- :func:`trace` captures an XLA/TPU profile via ``jax.profiler`` into the
  run directory — open in TensorBoard (or Perfetto) to see per-op device
  time, fusion boundaries, and collective overlap; the TPU-accurate
  answer to "where did the step time go".
- :class:`SpanTimer` is the host-side wall-clock layer (the model-mp.py
  accounting, device-synchronized like the ``torch.cuda.Event`` recipe):
  named spans with totals/counts, e.g. ``step`` vs ``comm``.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator

import jax


@contextmanager
def trace(log_dir: str | Path, enabled: bool = True) -> Iterator[None]:
    """Capture a jax.profiler trace under ``log_dir`` (no-op when
    ``enabled`` is False, so call sites can pass a config flag through)."""
    if not enabled:
        yield
        return
    with jax.profiler.trace(str(log_dir)):
        yield


def annotate(name: str):
    """Label a host-side region so it shows up on the trace timeline
    (thin alias of ``jax.profiler.TraceAnnotation``)."""
    return jax.profiler.TraceAnnotation(name)


class SpanTimer:
    """Named wall-clock spans with device synchronization.

    ``sync=`` values are blocked on (``jax.block_until_ready``) before the
    span closes, so async-dispatched XLA work is charged to the span that
    launched it — the semantic of the reference's cuda-Event timing
    (sections/task2.tex:72-80).

    Usage::

        timer = SpanTimer()
        with timer.span("step", sync=metrics["loss"]):
            ts, metrics = step(ts, x, y)
        print(timer.report())
    """

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextmanager
    def span(self, name: str, sync=None) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                jax.block_until_ready(sync)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def mean(self, name: str) -> float:
        return self.totals[name] / max(self.counts[name], 1)

    def report(self) -> str:
        parts = [
            f"{name}: {self.totals[name]:.4f}s over {self.counts[name]} calls "
            f"(mean {self.mean(name) * 1e3:.2f}ms)"
            for name in sorted(self.totals)
        ]
        return "\n".join(parts)
