"""Registry of traceable train-step entrypoints for the jaxpr pass.

Each builder constructs the *real* engine from the parallel layer — the
same classes the tasks instantiate — around a deliberately tiny model,
then hands back the raw jitted program (``step.jitted``, attached by
every engine's ``make_train_step``) plus matching abstract-shaped
inputs. Tracing that program on CPU walks the identical jaxpr that
would lower for a TPU slice: shard_map axis bindings, collectives,
donation annotations and all. Nothing here requires accelerator
hardware, only >= 2 visible devices (the CLI forces an 8-device host
platform before importing jax; the test suite's conftest does the same).

Coverage vs the parallel layer:

==============  =====================================  ================
entrypoint      engine / step builder                  task analogue
==============  =====================================  ================
task1_single    tpudml.train.make_train_step           task1
task2_dp        parallel/dp.py DataParallel (fused)    task2, task3
dp_zero1        DataParallel + ZeRO-1 sharded update   task2 --zero1
dp_sentinel     dp_zero1 + in-graph step sentinel      task2 --sentinel
task4_mp        parallel/mp.py GSPMDParallel           task4
fsdp            parallel/fsdp.py FSDP                  task5 --mode fsdp
tp_fused        GSPMDParallel + sharded fused head     task5 tp --fused_xent
fsdp_fused      FSDP + sharded fused head              task5 fsdp --fused_xent
pp_gpipe        parallel/pp.py GPipe                   task5 --mode pp
cp_ring         parallel/cp.py ContextParallel         task5 --mode cp
ep_moe          parallel/ep.py ExpertParallel          task5 --mode ep
lm_bf16         make_train_step on a bf16 LM           task5 --mode single
serve_decode    serve/engine.py make_decode_step       task6
serve_paged     serve/engine.py make_paged_decode_step task6 --paged
==============  =====================================  ================

(``serve_paged`` is registered as ``serve_paged_decode``.)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from tpudml.analysis.findings import Finding
from tpudml.analysis.jaxpr_pass import analyze_callable


@dataclass(frozen=True)
class Program:
    """One traceable device program: a jitted callable + example args.

    ``in_specs``/``mesh_axes`` (when the engine attaches them to its
    step next to ``.jitted``) seed the dataflow interpreter's top-level
    replication states and the ``--cost`` per-device arithmetic; both
    default to None for mesh-less single-device programs.
    """

    name: str
    fn: Callable
    args: tuple
    expects_donation: bool = True
    in_specs: tuple | None = None
    mesh_axes: dict | None = None


def _program(name: str, step, args: tuple, **kw) -> Program:
    """Build a Program from an engine step, lifting the in_spec metadata
    the engines attach next to ``.jitted``."""
    return Program(
        name, step.jitted, args,
        in_specs=getattr(step, "in_specs", None),
        mesh_axes=getattr(step, "mesh_axes", None),
        **kw,
    )


def _np():
    import numpy as np
    return np


def _mesh(axis: str, size: int):
    import jax
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh

    if len(jax.devices()) < size:
        raise RuntimeError(
            f"need {size} devices for axis '{axis}', have "
            f"{len(jax.devices())} — set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8")
    return make_mesh(MeshConfig({axis: size}), jax.devices()[:size])


def _lenet_batch(n=4):
    np = _np()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(n,)).astype(np.int32)
    return x, y


def _lm_batch(b=2, t=8, vocab=32):
    np = _np()
    rng = np.random.default_rng(0)
    seqs = rng.integers(0, vocab, size=(b, t + 1)).astype(np.int32)
    return seqs[:, :-1], seqs[:, 1:]


def _tiny_lm(**kw):
    from tpudml.models import TransformerLM

    base = dict(vocab_size=32, embed_dim=16, num_heads=2, num_layers=1,
                max_len=8)
    base.update(kw)
    return TransformerLM(**base)


def build_task1_single() -> list[Program]:
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState, make_train_step

    model, opt = LeNet(), make_optimizer("sgd", 0.01)
    ts = TrainState.create(model, opt, seed_key(0))
    step = make_train_step(model, opt)  # already the jitted program
    x, y = _lenet_batch()
    return [Program("task1_single", step, (ts, x, y))]


def build_task2_dp() -> list[Program]:
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    dp = DataParallel(LeNet(), make_optimizer("sgd", 0.01), _mesh("data", 2))
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    x, y = _lenet_batch()
    return [_program("task2_dp", step, (ts, x, y))]


def build_dp_zero1() -> list[Program]:
    """Data parallelism with the ZeRO-1 weight-update shard: the traced
    step must reduce-scatter the gradients and all-gather the params
    (J108 stays silent — the psum_scatter is the whole point)."""
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    dp = DataParallel(LeNet(), make_optimizer("adam", 1e-3),
                      _mesh("data", 2), zero1=True)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    x, y = _lenet_batch()
    return [_program("dp_zero1", step, (ts, x, y))]


def build_dp_sentinel() -> list[Program]:
    """ZeRO-1 data parallelism with the in-graph step sentinel: the
    traced step carries an ``is_finite`` gate between the gradients and
    the update, so J111 stays silent here (and J108 stays silent via the
    reduce-scatter) — the guarded counterpart of the plain engines the
    rule fires on."""
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    dp = DataParallel(LeNet(), make_optimizer("adam", 1e-3),
                      _mesh("data", 2), zero1=True, sentinel=True)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    x, y = _lenet_batch()
    return [_program("dp_sentinel", step, (ts, x, y))]


def build_task4_mp() -> list[Program]:
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.mp import GSPMDParallel

    mp = GSPMDParallel(LeNet(), make_optimizer("sgd", 0.01),
                       _mesh("stage", 2))
    ts = mp.create_state(seed_key(0))
    step = mp.make_train_step()
    x, y = _lenet_batch()
    return [_program("task4_mp", step, (ts, x, y))]


def build_fsdp() -> list[Program]:
    from tpudml.core.prng import seed_key
    from tpudml.models import ForwardMLP
    from tpudml.optim import make_optimizer
    from tpudml.parallel.fsdp import FSDP

    eng = FSDP(ForwardMLP(), make_optimizer("adam", 1e-3), _mesh("data", 2))
    ts = eng.create_state(seed_key(0))
    step = eng.make_train_step()
    np = _np()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=(4,)).astype(np.int32)
    return [_program("fsdp", step, (ts, x, y))]


def build_tp_fused() -> list[Program]:
    """Tensor parallelism with the vocab-sharded fused head: the traced
    step must carry the SHARDED marker (J107 stays silent) and the lse
    merge collectives inside the shard_map loss region."""
    from tpudml.core.prng import seed_key
    from tpudml.optim import make_optimizer
    from tpudml.parallel.mp import GSPMDParallel, tensor_parallel_rules

    eng = GSPMDParallel(
        _tiny_lm(), make_optimizer("sgd", 0.05), _mesh("model", 2),
        rule=tensor_parallel_rules("model"), axis_name="model",
        fused_xent=True,
    )
    ts = eng.create_state(seed_key(0))
    step = eng.make_train_step()
    x, y = _lm_batch()
    return [_program("tp_fused", step, (ts, x, y))]


def build_fsdp_fused() -> list[Program]:
    """1-D FSDP with the fused head: vocab and tokens share the data
    axis, so the loss region all-gathers the batch and merges vocab
    statistics over the same axis."""
    from tpudml.core.prng import seed_key
    from tpudml.optim import make_optimizer
    from tpudml.parallel.fsdp import FSDP

    eng = FSDP(_tiny_lm(), make_optimizer("sgd", 0.05), _mesh("data", 2),
               fused_xent=True)
    ts = eng.create_state(seed_key(0))
    step = eng.make_train_step()
    x, y = _lm_batch()
    return [_program("fsdp_fused", step, (ts, x, y))]


def build_pp_gpipe() -> list[Program]:
    import jax
    from tpudml.core.prng import seed_key
    from tpudml.nn.layers import Activation, Dense, Sequential
    from tpudml.optim import make_optimizer
    from tpudml.parallel.pp import GPipe

    pipe = GPipe(
        Sequential((Dense(8, 8), Activation(jax.nn.relu))),
        n_microbatches=2,
        mesh=_mesh("stage", 2),
        optimizer=make_optimizer("sgd", 0.05),
        prologue=Dense(4, 8),
        epilogue=Dense(8, 4),
    )
    ts = pipe.create_state(seed_key(0))
    step = pipe.make_train_step()
    np = _np()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 4)).astype(np.float32)
    y = rng.integers(0, 4, size=(4,)).astype(np.int32)
    return [_program("pp_gpipe", step, (ts, x, y))]


def build_cp_ring() -> list[Program]:
    from tpudml.core.prng import seed_key
    from tpudml.optim import make_optimizer
    from tpudml.parallel.cp import ContextParallel

    lm = _tiny_lm(impl="ring", seq_sharded=True)
    cp = ContextParallel(lm, make_optimizer("sgd", 0.1), _mesh("seq", 2))
    ts = cp.create_state(seed_key(0))
    step = cp.make_train_step()
    x, y = _lm_batch()
    return [_program("cp_ring", step, (ts, x, y))]


def build_ep_moe() -> list[Program]:
    from tpudml.core.prng import seed_key
    from tpudml.optim import make_optimizer
    from tpudml.parallel.ep import ExpertParallel

    lm = _tiny_lm(moe_experts=2, moe_axis="expert")
    ep = ExpertParallel(lm, make_optimizer("adam", 0.01), _mesh("expert", 2))
    ts = ep.create_state(seed_key(0))
    step = ep.make_train_step()
    x, y = _lm_batch()
    return [_program("ep_moe", step, (ts, x, y))]


def build_lm_bf16() -> list[Program]:
    import jax.numpy as jnp
    from tpudml.core.prng import seed_key
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState, make_train_step

    lm = _tiny_lm(dtype=jnp.bfloat16)
    opt = make_optimizer("sgd", 0.01)
    ts = TrainState.create(lm, opt, seed_key(0))
    step = make_train_step(lm, opt)
    x, y = _lm_batch()
    return [Program("lm_bf16", step, (ts, x, y))]


def build_moe_ragged() -> list[Program]:
    """Single-shard dropless ragged MoE — the surface J109 guards. The
    default grouped-dW backward must trace J109-silent; flipping
    moe_ragged_dw='stock' here is the rule's firing fixture (covered in
    tests/test_analysis.py, not registered as an entrypoint)."""
    from tpudml.core.prng import seed_key
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState, make_train_step

    lm = _tiny_lm(moe_experts=2, moe_dispatch="ragged")
    opt = make_optimizer("adam", 0.01)
    ts = TrainState.create(lm, opt, seed_key(0))
    step = make_train_step(lm, opt)
    x, y = _lm_batch()
    return [Program("moe_ragged", step, (ts, x, y))]


def build_serve_decode() -> list[Program]:
    """The serving engine's jitted per-token decode step — the surface
    J110 guards. The cache-carrying step must trace J110-silent (its
    softmax is [B, H, 1, L]); ``make_cacheless_decode_step`` is the
    rule's firing fixture (covered in tests/test_analysis.py, not
    registered as an entrypoint)."""
    import jax
    from tpudml.serve import ServeConfig, ServingEngine

    lm = _tiny_lm(rope=True, num_kv_heads=1)
    params, _ = lm.init(jax.random.key(0))
    eng = ServingEngine(
        lm, params,
        ServeConfig(slots=2, max_len=8, prefill_chunk=4),
    )
    np = _np()
    tokens = np.zeros(2, np.int32)
    pos = np.zeros(2, np.int32)
    return [Program(
        "serve_decode", eng._decode, (params, eng.caches, tokens, pos),
        # The donated buffers are the per-layer KV caches — a few KiB at
        # this toy size, far under the J106 large-input threshold — so
        # lowering-level donation analysis has nothing to check here.
        expects_donation=False,
    )]


def build_serve_paged_decode() -> list[Program]:
    """The paged serving engine's jitted decode step — the surface J117
    guards. The table-gathering step must trace J117-silent (its softmax
    keys on max_pages·page_size gathered rows); a step that broadcasts
    the whole pool per token is the rule's firing fixture (covered in
    tests/analysis_fixtures/jaxpr/, not registered). ``num_pages`` is
    chosen strictly above one slot's table (5 > 4) so pool rows and
    table rows cannot collide shape-wise — the rule's documented
    detectability bound."""
    import jax
    import numpy as np
    from tpudml.serve import ServeConfig, ServingEngine

    lm = _tiny_lm(rope=True, num_kv_heads=1)
    params, _ = lm.init(jax.random.key(0))
    eng = ServingEngine(
        lm, params,
        ServeConfig(slots=2, max_len=8, prefill_chunk=4,
                    cache_layout="paged", page_size=2, num_pages=5),
    )
    tokens = np.zeros(2, np.int32)
    pos = np.zeros(2, np.int32)
    table = np.zeros((2, eng.cfg.max_pages), np.int32)
    return [Program(
        "serve_paged_decode", eng._decode,
        (params, eng.caches, table, tokens, pos),
        expects_donation=False,  # donated pool is KiB-scale, like serve_decode
    )]


def build_serve_fused() -> list[Program]:
    """The dense decode step with the fused head tail
    (``ServeConfig(fused_head=True)``) — the surface J119's tail check
    guards. The fused step must trace J119-silent: its greedy pick lives
    INSIDE the ``_fused_decode_head`` marker pjit, which the scan skips;
    the plain ``serve_decode`` entrypoint above is the rule's
    (allowlisted) firing fixture."""
    import jax
    from tpudml.serve import ServeConfig, ServingEngine

    lm = _tiny_lm(rope=True, num_kv_heads=1)
    params, _ = lm.init(jax.random.key(0))
    eng = ServingEngine(
        lm, params,
        ServeConfig(slots=2, max_len=8, prefill_chunk=4, fused_head=True),
    )
    np = _np()
    tokens = np.zeros(2, np.int32)
    pos = np.zeros(2, np.int32)
    return [Program(
        "serve_fused", eng._decode, (params, eng.caches, tokens, pos),
        expects_donation=False,  # KiB-scale caches, like serve_decode
    )]


#: name -> builder; order is reporting order.
ENTRYPOINTS: dict[str, Callable[[], list[Program]]] = {
    "task1_single": build_task1_single,
    "task2_dp": build_task2_dp,
    "dp_zero1": build_dp_zero1,
    "dp_sentinel": build_dp_sentinel,
    "task4_mp": build_task4_mp,
    "fsdp": build_fsdp,
    "tp_fused": build_tp_fused,
    "fsdp_fused": build_fsdp_fused,
    "pp_gpipe": build_pp_gpipe,
    "cp_ring": build_cp_ring,
    "ep_moe": build_ep_moe,
    "moe_ragged": build_moe_ragged,
    "lm_bf16": build_lm_bf16,
    "serve_decode": build_serve_decode,
    "serve_paged_decode": build_serve_paged_decode,
    "serve_fused": build_serve_fused,
}


def analyze_entrypoint(
    name: str, hbm_budget_bytes: int | None = None
) -> list[Finding]:
    """Build one entrypoint and run every jaxpr rule on its program(s).

    A builder that raises becomes a J100 finding rather than an
    exception: an entrypoint that cannot even be constructed on CPU is
    itself a pre-flight failure worth reporting.
    """
    builder = ENTRYPOINTS[name]
    try:
        programs = builder()
    except Exception as e:  # noqa: BLE001 - converted to a finding
        return [Finding("J100", f"entrypoint failed to build: {e!r}",
                        entrypoint=name)]
    findings: list[Finding] = []
    for prog in programs:
        findings.extend(analyze_callable(
            prog.fn, prog.args, entrypoint=prog.name,
            expects_donation=prog.expects_donation,
            in_specs=prog.in_specs, mesh_axes=prog.mesh_axes,
            hbm_budget_bytes=hbm_budget_bytes))
    return findings


def analyze_entrypoints(
    names: list[str] | None = None, hbm_budget_bytes: int | None = None
) -> list[Finding]:
    findings: list[Finding] = []
    for name in names or list(ENTRYPOINTS):
        findings.extend(analyze_entrypoint(name, hbm_budget_bytes))
    return findings


def cost_entrypoints(names: list[str] | None = None):
    """Static cost summaries (``--cost``) for the registered entrypoints:
    one dataflow walk + CommEvent aggregation + peak-HBM estimate per
    program. Returns ``(costs, findings)`` — build/trace failures become
    an EntrypointCost carrying ``error`` plus a J100 finding, so the cost
    table never hides a broken entrypoint."""
    import jax

    from tpudml.analysis.cost import EntrypointCost, summarize_cost
    from tpudml.analysis.dataflow import analyze_dataflow

    costs = []
    findings: list[Finding] = []
    for name in names or list(ENTRYPOINTS):
        try:
            programs = ENTRYPOINTS[name]()
        except Exception as e:  # noqa: BLE001 - converted to a finding
            findings.append(Finding(
                "J100", f"entrypoint failed to build: {e!r}",
                entrypoint=name))
            costs.append(EntrypointCost(entrypoint=name, error=repr(e)))
            continue
        for prog in programs:
            try:
                closed = jax.make_jaxpr(prog.fn)(*prog.args)
            except Exception as e:  # noqa: BLE001 - converted to a finding
                findings.append(Finding(
                    "J100", f"trace failed: {e!r}", entrypoint=prog.name))
                costs.append(EntrypointCost(entrypoint=prog.name,
                                            error=repr(e)))
                continue
            flow = analyze_dataflow(closed, prog.name,
                                    in_specs=prog.in_specs,
                                    mesh_axes=prog.mesh_axes)
            findings.extend(flow.findings)
            costs.append(summarize_cost(prog.name, flow, closed))
    return costs, findings
