"""CLI: ``python -m tpudml.analysis [--strict] [...]``.

Report-only by default; ``--strict`` (the CI mode) exits non-zero when
any finding is not covered by the committed allowlist. The jaxpr pass
needs >= 2 visible devices, so an 8-device CPU host platform is
provisioned before the first backend touch — same dance as
``tests/conftest.py`` — which makes the tool runnable on any dev box
with ``JAX_PLATFORMS=cpu``, no TPU required.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _provision_devices() -> None:
    """Force an 8-device CPU platform before jax initializes a backend."""
    try:
        # Repo harness helper (handles site hooks that latch JAX_PLATFORMS).
        from __graft_entry__ import _provision_cpu_mesh

        _provision_cpu_mesh(8)
        return
    except Exception:
        pass
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudml.analysis",
        description="Static pre-flight analysis for TPU distributed "
                    "training hazards (jaxpr + AST passes).",
    )
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any finding not in the allowlist")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as JSON on stdout")
    parser.add_argument("--entrypoints", default=None, metavar="A,B",
                        help="comma-separated jaxpr entrypoints "
                             "(default: all; see --list-rules)")
    parser.add_argument("--paths", nargs="*", default=None,
                        help="roots for the AST pass "
                             "(default: tpudml tasks tools)")
    parser.add_argument("--allowlist", default=None, metavar="TOML",
                        help="allowlist path (default: "
                             "analysis/allowlist.toml)")
    parser.add_argument("--skip-jaxpr", action="store_true",
                        help="AST pass only (no tracing, no jax import)")
    parser.add_argument("--skip-ast", action="store_true",
                        help="jaxpr pass only")
    parser.add_argument("--show-allowed", action="store_true",
                        help="also print findings the allowlist suppressed")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and entrypoints")
    args = parser.parse_args(argv)

    from tpudml.analysis.findings import RULES, sort_findings

    if args.list_rules:
        from tpudml.analysis.entrypoints import ENTRYPOINTS

        for rule, (sev, desc) in RULES.items():
            print(f"{rule}  {sev:5s}  {desc}")
        print("\nentrypoints:", ", ".join(ENTRYPOINTS))
        return 0

    findings = []
    if not args.skip_ast:
        from tpudml.analysis.ast_pass import analyze_tree

        roots = args.paths or [r for r in ("tpudml", "tasks", "tools")
                               if os.path.isdir(r)]
        findings.extend(analyze_tree(roots))
    if not args.skip_jaxpr:
        _provision_devices()
        from tpudml.analysis.entrypoints import ENTRYPOINTS, analyze_entrypoints

        names = None
        if args.entrypoints:
            names = [n.strip() for n in args.entrypoints.split(",") if n.strip()]
            unknown = [n for n in names if n not in ENTRYPOINTS]
            if unknown:
                parser.error(f"unknown entrypoints {unknown}; "
                             f"known: {', '.join(ENTRYPOINTS)}")
        findings.extend(analyze_entrypoints(names))

    from tpudml.analysis.allowlist import load_allowlist, split_allowed

    entries = load_allowlist(args.allowlist)
    active, allowed = split_allowed(sort_findings(findings), entries)

    if args.as_json:
        print(json.dumps({
            "active": [f.__dict__ | {"severity": f.severity} for f in active],
            "allowed": [f.__dict__ | {"severity": f.severity} for f in allowed],
        }, indent=2))
    else:
        for f in active:
            print(f.format())
        if args.show_allowed and allowed:
            print(f"\n-- allowlisted ({len(allowed)}) --")
            for f in allowed:
                print(f.format())
        print(f"\n{len(active)} finding(s), {len(allowed)} allowlisted "
              f"({len(entries)} allowlist entr{'y' if len(entries) == 1 else 'ies'})")

    if args.strict and active:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
