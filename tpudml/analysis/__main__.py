"""CLI: ``python -m tpudml.analysis [--strict] [--cost] [...]``.

Report-only by default; ``--strict`` (the CI mode) exits non-zero when
any finding is not covered by the committed allowlist, and warns on
allowlist entries that matched nothing (stale suppressions). ``--cost``
switches to the static cost reports: a per-entrypoint comm/HBM table on
stdout plus ``analysis/cost_report.json`` for machines. ``--protocol``
runs only the cross-rank protocol pass (P300–P303 over the repo's
drill/fixture ``PipelineSpec`` surface plus the AST-hosted P304 port
lint) — jax-free, milliseconds, byte-deterministic; the same findings
are folded into the default full run, so ``--strict`` covers them.
``--format`` selects the findings output: ``text`` (human), ``json``,
or ``github`` (workflow-annotation lines). The jaxpr pass needs >= 2
visible devices, so an 8-device CPU host platform is provisioned before
the first backend touch — same dance as ``tests/conftest.py`` — which
makes the tool runnable on any dev box with ``JAX_PLATFORMS=cpu``, no
TPU required.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

COST_REPORT_PATH = os.path.join("analysis", "cost_report.json")

# --format github: one workflow-annotation line per finding, mapped from
# the rule severity (info → notice).
_GITHUB_LEVEL = {"error": "error", "warn": "warning", "info": "notice"}


def _provision_devices() -> None:
    """Force an 8-device CPU platform before jax initializes a backend."""
    try:
        # Repo harness helper (handles site hooks that latch JAX_PLATFORMS).
        from __graft_entry__ import _provision_cpu_mesh

        _provision_cpu_mesh(8)
        return
    except Exception:
        pass
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _github_line(f) -> str:
    level = _GITHUB_LEVEL.get(f.severity, "warning")
    loc = ""
    if f.file:
        loc = f"file={f.file}"
        if f.line:
            loc += f",line={f.line}"
    ep = f" [{f.entrypoint}]" if f.entrypoint else ""
    # '::' inside the message would terminate the annotation early.
    msg = f"{f.rule}{ep}: {f.message}".replace("::", ":")
    return f"::{level} {loc}::{msg}"


def _finding_dicts(findings) -> list[dict]:
    return [f.__dict__ | {"severity": f.severity} for f in findings]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudml.analysis",
        description="Static pre-flight analysis for TPU distributed "
                    "training hazards (jaxpr + AST + dataflow passes).",
    )
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any finding not in the allowlist; "
                             "warn on stale allowlist entries")
    parser.add_argument("--format", default=None, dest="fmt",
                        choices=("text", "json", "github"),
                        help="findings output format (default: text)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="alias for --format json")
    parser.add_argument("--protocol", action="store_true",
                        help="cross-rank protocol pass only: P300-P303 "
                             "over the drill/fixture PipelineSpec surface "
                             "plus the AST P304 port-discipline lint "
                             "(no tracing, no device mesh)")
    parser.add_argument("--cost", action="store_true",
                        help="emit the static comm/HBM cost table and "
                             f"write {COST_REPORT_PATH}")
    parser.add_argument("--hbm_budget", type=float, default=None,
                        metavar="MB",
                        help="arm J116: flag entrypoints whose static "
                             "peak-live-buffer estimate exceeds this many "
                             "megabytes")
    parser.add_argument("--plan", default=None, metavar="PLAN_JSON",
                        help="arm J118: re-trace the plan's winning "
                             "entrypoint and flag traced comm/HBM that "
                             "deviates >10%% from its predicted block")
    parser.add_argument("--entrypoints", default=None, metavar="A,B",
                        help="comma-separated jaxpr entrypoints "
                             "(default: all; see --list-rules)")
    parser.add_argument("--paths", nargs="*", default=None,
                        help="roots for the AST pass "
                             "(default: tpudml tasks tools)")
    parser.add_argument("--allowlist", default=None, metavar="TOML",
                        help="allowlist path (default: "
                             "analysis/allowlist.toml)")
    parser.add_argument("--skip-jaxpr", action="store_true",
                        help="AST pass only (no tracing, no jax import)")
    parser.add_argument("--skip-ast", action="store_true",
                        help="jaxpr pass only")
    parser.add_argument("--show-allowed", action="store_true",
                        help="also print findings the allowlist suppressed")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and entrypoints")
    args = parser.parse_args(argv)
    fmt = args.fmt or ("json" if args.as_json else "text")

    from tpudml.analysis.findings import RULES, sort_findings

    if args.list_rules:
        from tpudml.analysis.entrypoints import ENTRYPOINTS

        for rule, (sev, desc) in RULES.items():
            print(f"{rule}  {sev:5s}  {desc}")
        print("\nentrypoints:", ", ".join(ENTRYPOINTS))
        return 0

    names = None
    if args.entrypoints:
        from tpudml.analysis.entrypoints import ENTRYPOINTS

        names = [n.strip() for n in args.entrypoints.split(",") if n.strip()]
        unknown = [n for n in names if n not in ENTRYPOINTS]
        if unknown:
            parser.error(f"unknown entrypoints {unknown}; "
                         f"known: {', '.join(ENTRYPOINTS)}")

    if args.cost:
        _provision_devices()
        from tpudml.analysis.cost import (
            build_cost_report,
            format_cost_table,
            write_cost_report,
        )
        from tpudml.analysis.entrypoints import cost_entrypoints

        costs, cost_findings = cost_entrypoints(names)
        os.makedirs(os.path.dirname(COST_REPORT_PATH), exist_ok=True)
        write_cost_report(costs, COST_REPORT_PATH)
        if fmt == "json":
            print(json.dumps(build_cost_report(costs), indent=2))
        else:
            print(format_cost_table(costs))
            print(f"\nwrote {COST_REPORT_PATH}")
        # Cost mode reports but does not gate: broken entrypoints still
        # surface (as J100 lines) so the table can't silently shrink.
        for f in sort_findings(cost_findings):
            print(f.format())
        return 1 if (args.strict and cost_findings) else 0

    hbm_budget_bytes = None
    if args.hbm_budget is not None:
        hbm_budget_bytes = int(args.hbm_budget * 1e6)

    findings = []
    if args.protocol:
        # Protocol-only mode: the schedule checks plus the P304 slice of
        # the AST pass — no tracing, no jax, byte-deterministic.
        from tpudml.analysis.ast_pass import analyze_tree
        from tpudml.analysis.protocol import analyze_protocol_surface

        roots = args.paths or [r for r in ("tpudml", "tasks", "tools")
                               if os.path.isdir(r)]
        findings.extend(analyze_protocol_surface())
        findings.extend(f for f in analyze_tree(roots)
                        if f.rule == "P304")
    else:
        if not args.skip_ast:
            from tpudml.analysis.ast_pass import analyze_tree

            roots = args.paths or [r for r in ("tpudml", "tasks", "tools")
                                   if os.path.isdir(r)]
            findings.extend(analyze_tree(roots))
        if not args.skip_jaxpr:
            _provision_devices()
            from tpudml.analysis.entrypoints import analyze_entrypoints

            findings.extend(analyze_entrypoints(names, hbm_budget_bytes))
        if not args.skip_ast and not args.skip_jaxpr:
            # Full runs also cover the protocol surface (cheap, jax-free)
            # so --strict gates P300-P303 alongside everything else.
            from tpudml.analysis.protocol import analyze_protocol_surface

            findings.extend(analyze_protocol_surface())
        if args.plan:
            _provision_devices()
            from tpudml.plan import load_plan, plan_drift_findings

            findings.extend(plan_drift_findings(load_plan(args.plan)))

    from tpudml.analysis.allowlist import (
        load_allowlist,
        split_allowed,
        unused_entries,
    )

    entries = load_allowlist(args.allowlist)
    active, allowed = split_allowed(sort_findings(findings), entries)
    # Stale-entry detection needs the full finding surface: a filtered
    # run (subset of entrypoints/paths, or a skipped pass) legitimately
    # misses findings its allowlist entries cover.
    full_run = (not args.protocol and names is None and args.paths is None
                and not args.skip_jaxpr and not args.skip_ast)
    stale = unused_entries(findings, entries) if full_run else []

    if fmt == "json":
        print(json.dumps({
            "active": _finding_dicts(active),
            "allowed": _finding_dicts(allowed),
            "stale_allowlist": [e.__dict__ for e in stale],
        }, indent=2))
    elif fmt == "github":
        for f in active:
            print(_github_line(f))
        for e in stale:
            print(f"::warning file={os.path.join('analysis', 'allowlist.toml')}"
                  f"::stale allowlist entry rule={e.rule} path={e.path} "
                  f"matched no finding ({e.reason})")
    else:
        for f in active:
            print(f.format())
        if args.show_allowed and allowed:
            print(f"\n-- allowlisted ({len(allowed)}) --")
            for f in allowed:
                print(f.format())
        if args.strict and stale:
            print(f"\n-- stale allowlist entries ({len(stale)}) --")
            for e in stale:
                print(f"  {e.rule} path={e.path!r}"
                      + (f" line={e.line}" if e.line else "")
                      + f" — matched no finding (reason was: {e.reason})")
        print(f"\n{len(active)} finding(s), {len(allowed)} allowlisted "
              f"({len(entries)} allowlist entr{'y' if len(entries) == 1 else 'ies'})")

    if args.strict and active:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
