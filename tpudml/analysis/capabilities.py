"""Analysis-side face of the engine capability table.

The table itself lives in the dependency-free :mod:`tpudml.capabilities`
(the engines import it at module top, and importing anything under
``tpudml.analysis`` from an engine would cycle through
``analysis.entrypoints`` back into the engines).  The planner and the
analysis CLI import it from here so the public API stays where the
rule catalogue lives.
"""

from tpudml.capabilities import (
    TABLE,
    Capability,
    CompositionError,
    candidate_rejection,
    reject,
)

__all__ = [
    "TABLE",
    "Capability",
    "CompositionError",
    "candidate_rejection",
    "reject",
]
