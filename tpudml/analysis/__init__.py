"""Static-analysis suite: pre-flight lint for TPU distributed training.

Two complementary passes over the codebase (docs/ANALYSIS.md has the
full rule catalogue):

- the **jaxpr pass** (``jaxpr_pass``, rules J1xx) traces the real train
  steps — the engines in ``tpudml/parallel/`` wired to tiny models by
  ``entrypoints`` — with abstract inputs on CPU and walks the resulting
  ClosedJaxpr for hazards that otherwise only fail on a multi-host
  slice: unbound collective axes, branch-divergent collectives, host
  callbacks, stray bf16→f32 upcasts, closure-captured megabyte
  constants, undonated training state;
- the **AST pass** (``ast_pass``, rules A2xx) lints the source for
  hazards tracing cannot see: Python control flow over traced values,
  PRNG key reuse, epoch loops missing ``set_epoch``, host-clock timing
  without ``block_until_ready``;
- the **dataflow pass** (``dataflow``, rules J112–J116) abstractly
  interprets the same traced programs under a per-(value, mesh-axis)
  replication lattice — missing psums under ``check_rep=False``,
  shard-dependent while trip counts around collectives, donated-buffer
  reuse, allreduce-then-shard waste — and feeds the static comm/HBM
  cost reports in ``cost`` (``--cost`` / ``analysis/cost_report.json``);
- the **protocol pass** (``protocol``, rules P300–P304) models every
  (stage, rank) of the MPMD pipeline as an ordered schedule of blocking
  events (p2p frames, drain votes, stage-group collectives) and checks
  the *composed* system for boundary asymmetry, cross-rank deadlock,
  collective-sequence divergence and vote-before-collective ordering —
  jax-free, so ``MPMDController`` runs it as a pre-launch gate
  (``--protocol`` on the CLI; P304, the port-discipline lint, rides in
  the AST pass).

Run it as ``python -m tpudml.analysis`` (``--strict`` for CI, paired
with the committed ``analysis/allowlist.toml``).
"""

from tpudml.analysis.allowlist import (
    load_allowlist,
    split_allowed,
    unused_entries,
)
from tpudml.analysis.ast_pass import analyze_file, analyze_source, analyze_tree
from tpudml.analysis.cost import (
    EntrypointCost,
    build_cost_report,
    check_hbm_budget,
    format_cost_table,
    peak_live_bytes,
    summarize_cost,
    write_cost_report,
)
from tpudml.analysis.dataflow import (
    CommEvent,
    DataflowResult,
    analyze_dataflow,
)
from tpudml.analysis.entrypoints import (
    ENTRYPOINTS,
    analyze_entrypoint,
    analyze_entrypoints,
    cost_entrypoints,
)
from tpudml.analysis.findings import RULES, Finding, sort_findings
from tpudml.analysis.jaxpr_pass import (
    analyze_callable,
    analyze_closed_jaxpr,
    collective_shape_signature,
    donation_findings,
)
from tpudml.analysis.protocol import (
    Ev,
    analyze_pipeline,
    analyze_protocol_surface,
    build_schedules,
    check_schedules,
    protocol_surface,
    traced_collective_events,
    validate_fixture_events,
)

__all__ = [
    "RULES",
    "CommEvent",
    "DataflowResult",
    "EntrypointCost",
    "Ev",
    "Finding",
    "ENTRYPOINTS",
    "analyze_callable",
    "analyze_closed_jaxpr",
    "analyze_dataflow",
    "analyze_entrypoint",
    "analyze_entrypoints",
    "analyze_file",
    "analyze_pipeline",
    "analyze_protocol_surface",
    "analyze_source",
    "analyze_tree",
    "build_cost_report",
    "build_schedules",
    "check_schedules",
    "collective_shape_signature",
    "protocol_surface",
    "traced_collective_events",
    "validate_fixture_events",
    "check_hbm_budget",
    "cost_entrypoints",
    "donation_findings",
    "format_cost_table",
    "load_allowlist",
    "peak_live_bytes",
    "sort_findings",
    "split_allowed",
    "summarize_cost",
    "unused_entries",
    "write_cost_report",
]
