"""Static-analysis suite: pre-flight lint for TPU distributed training.

Two complementary passes over the codebase (docs/ANALYSIS.md has the
full rule catalogue):

- the **jaxpr pass** (``jaxpr_pass``, rules J1xx) traces the real train
  steps — the engines in ``tpudml/parallel/`` wired to tiny models by
  ``entrypoints`` — with abstract inputs on CPU and walks the resulting
  ClosedJaxpr for hazards that otherwise only fail on a multi-host
  slice: unbound collective axes, branch-divergent collectives, host
  callbacks, stray bf16→f32 upcasts, closure-captured megabyte
  constants, undonated training state;
- the **AST pass** (``ast_pass``, rules A2xx) lints the source for
  hazards tracing cannot see: Python control flow over traced values,
  PRNG key reuse, epoch loops missing ``set_epoch``, host-clock timing
  without ``block_until_ready``.

Run it as ``python -m tpudml.analysis`` (``--strict`` for CI, paired
with the committed ``analysis/allowlist.toml``).
"""

from tpudml.analysis.allowlist import load_allowlist, split_allowed
from tpudml.analysis.ast_pass import analyze_file, analyze_source, analyze_tree
from tpudml.analysis.entrypoints import (
    ENTRYPOINTS,
    analyze_entrypoint,
    analyze_entrypoints,
)
from tpudml.analysis.findings import RULES, Finding, sort_findings
from tpudml.analysis.jaxpr_pass import (
    analyze_callable,
    analyze_closed_jaxpr,
    donation_findings,
)

__all__ = [
    "RULES",
    "Finding",
    "ENTRYPOINTS",
    "analyze_callable",
    "analyze_closed_jaxpr",
    "analyze_entrypoint",
    "analyze_entrypoints",
    "analyze_file",
    "analyze_source",
    "analyze_tree",
    "donation_findings",
    "load_allowlist",
    "sort_findings",
    "split_allowed",
]
