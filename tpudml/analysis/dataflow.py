"""Sharding-aware dataflow analysis: a replication-lattice interpreter.

The rule passes before this module were local pattern matchers — they
could spot *a* psum with no bound axis, but not answer the questions
that actually bite on a multi-host slice: "is this value still identical
across the data axis when it reaches the optimizer?", "do all ranks
execute the same collective sequence through this while loop?", "how
many bytes does this step move per collective?". This module answers
them by abstractly interpreting a ``ClosedJaxpr`` and propagating, for
every value and every mesh axis, an element of the replication lattice

    ``replicated``  proven identical across the axis' shards
    ``sharded``     a GLOBAL array dim-partitioned over the axis
                    (outside-shard_map state, seeded from in_specs)
    ``varying``     per-shard bytes may differ (derived from
                    in_names-split data or ``axis_index`` without an
                    intervening reducing collective)
    ``unknown``     no claim (join of conflicting facts)

through pjit / scan / while / cond / shard_map / custom_vjp sub-jaxprs.
Loop carries reach a fixpoint by iterating the body until states stop
changing (the lattice has height 2, so this converges in a couple of
rounds; ``DataflowResult.iterations`` records the worst loop).

Transfer rules for the collectives that matter:

- ``psum/pmax/pmin/pbroadcast`` over axis *a* → ``replicated`` on *a*
  (every shard computes the same reduction);
- ``all_gather`` over *a* → ``replicated`` (everyone receives all
  shards);
- ``psum_scatter/reduce_scatter/ppermute/all_to_all`` over *a* →
  ``varying`` (each shard keeps a different piece);
- ``axis_index`` over *a* → ``varying`` by definition;
- everything else: ``varying`` is contagious, then ``unknown``, then
  ``sharded``; constants/literals are ``replicated`` everywhere.

On top of the walk this module implements:

- **J112** (missing psum / lost transpose factor): a ``shard_map``
  output whose ``out_names`` declare it UNSHARDED over a bound axis
  while the body value is ``varying`` over that axis. With
  ``check_rep=False`` (every engine here — custom_vjp regions force it)
  JAX cannot catch this, and each device silently returns different
  bytes for a nominally replicated global — the exact class of bug the
  fused cross-entropy backward had to hand-fix with an out-cotangent
  psum.
- **J113** (unbalanced collective under a shard-dependent loop): a
  ``while`` whose predicate is ``varying`` over axis *a* and whose
  body/cond issue collectives over *a* — shards run different trip
  counts, so some ranks enter a collective their peers never post:
  the slice deadlocks.
- **J115** (allreduce-then-shard): a ``psum`` over *a* whose output is
  consumed ONLY by slices, at least one indexed by ``axis_index`` over
  *a* — each chip keeps 1/N of a fully-replicated reduction, paying
  ~2× the wire bytes a ``psum_scatter`` would (the exact waste ZeRO-1
  removes).

The same walk records every collective's payload/wire bytes and scan
trip counts into ``CommEvent``s — the raw material for the static cost
reports in :mod:`tpudml.analysis.cost`. Everything runs on abstract
values on CPU; no accelerator needed.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Iterable

from tpudml.analysis.findings import Finding
from tpudml.comm.timing import collective_wire_bytes

REPLICATED = "replicated"
SHARDED = "sharded"
VARYING = "varying"
UNKNOWN = "unknown"

#: per-value lattice state: axis name -> element; missing = REPLICATED.
AxisState = dict[str, str]

# Collectives that make their result identical across the named axis.
_REPLICATING = frozenset({"psum", "pmax", "pmin", "pbroadcast", "all_gather"})
# Collectives whose result is a per-shard piece.
_VARYING_OUT = frozenset(
    {"psum_scatter", "reduce_scatter", "ppermute", "all_to_all", "pgather"}
)
_COMM = _REPLICATING | _VARYING_OUT


def _repo_rel(path: str) -> str:
    if not path:
        return path
    try:
        rel = os.path.relpath(path, os.getcwd())
    except ValueError:  # pragma: no cover - different drive (windows)
        return path
    return path if rel.startswith("..") else rel


def _src_loc(eqn) -> tuple[str, int]:
    """(file, line) of the user frame that built an equation."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return _repo_rel(frame.file_name), int(frame.start_line)
    except Exception:
        pass
    return "", 0


def _axis_strs(value: Any) -> tuple[str, ...]:
    if isinstance(value, str):
        return (value,)
    if isinstance(value, (tuple, list, frozenset, set)):
        out: list[str] = []
        for v in value:
            out.extend(_axis_strs(v))
        return tuple(out)
    return ()


def _eqn_axes(eqn) -> tuple[str, ...]:
    axes: list[str] = []
    for key in ("axes", "axis_name"):
        if key in eqn.params:
            axes.extend(_axis_strs(eqn.params[key]))
    return tuple(axes)


def _inner_jaxpr(obj):
    """Normalize Jaxpr | ClosedJaxpr -> Jaxpr."""
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


def _is_jaxpr_like(obj) -> bool:
    return hasattr(obj, "eqns") or (
        hasattr(obj, "jaxpr") and hasattr(obj.jaxpr, "eqns")
    )


def _is_var(v) -> bool:
    # Literals carry ``val``; Vars do not.
    return not hasattr(v, "val")


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):  # dynamic dim
            pass
    return n * getattr(dtype, "itemsize", 4)


@dataclass
class CommEvent:
    """One collective site in the walked program."""

    kind: str
    axes: tuple[str, ...]
    world: int  # product of the axes' sizes
    payload_bytes: int  # per-shard input bytes at this site
    wire_bytes: float  # ring-model bytes moved per device, per execution
    trips: int  # scan-multiplied executions per step
    file: str = ""
    line: int = 0


@dataclass
class DataflowResult:
    """Everything one interpreter walk produces."""

    findings: list[Finding] = field(default_factory=list)
    comm_events: list[CommEvent] = field(default_factory=list)
    iterations: int = 0  # worst loop-carry fixpoint iteration count
    converged: bool = True
    out_states: list[AxisState] = field(default_factory=list)
    axis_sizes: dict[str, int] = field(default_factory=dict)
    unbounded_loops: int = 0  # while loops (trip count unknown to cost)


# Fixpoint safety valve: the lattice has height 2 so carries settle in
# <= 3 rounds; anything past this is a bug, reported as non-convergence.
_MAX_FIXPOINT_ITERS = 8


class _Interpreter:
    def __init__(self, entrypoint: str, mesh_axes: dict[str, int] | None):
        self.entrypoint = entrypoint
        self.result = DataflowResult(axis_sizes=dict(mesh_axes or {}))
        # id(var) -> AxisState. Var objects are kept alive by the closed
        # jaxpr for the duration of the walk, so ids are stable.
        self.env: dict[int, AxisState] = {}

    # ------------------------------------------------------------- states

    def state(self, v) -> AxisState:
        if not _is_var(v):
            return {}
        return self.env.get(id(v), {})

    def set_state(self, v, st: AxisState) -> None:
        if _is_var(v):
            self.env[id(v)] = {a: e for a, e in st.items() if e != REPLICATED}

    def _join_inputs(self, eqn) -> AxisState:
        out: AxisState = {}
        for v in eqn.invars:
            for a, e in self.state(v).items():
                prev = out.get(a, REPLICATED)
                out[a] = _join(prev, e)
        return out

    # --------------------------------------------------------------- walk

    def interpret(self, obj, trips: int = 1) -> None:
        jaxpr = _inner_jaxpr(obj)
        for cv in getattr(jaxpr, "constvars", ()):
            self.set_state(cv, {})
        producers = {id(ov): e for e in jaxpr.eqns for ov in e.outvars}
        consumers: dict[int, list] = {}
        for e in jaxpr.eqns:
            for v in e.invars:
                if _is_var(v):
                    consumers.setdefault(id(v), []).append(e)
        for eqn in jaxpr.eqns:
            self._eqn(eqn, trips, producers, consumers)
        # J115 runs after the level settles: the slice indices' states
        # (downstream of the psum) only exist once the walk passes them.
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "psum":
                self._check_allreduce_then_slice(eqn, producers, consumers)

    def _eqn(self, eqn, trips, producers, consumers) -> None:
        name = eqn.primitive.name
        if name in _COMM:
            self._comm(eqn, trips)
            return
        if name == "axis_index":
            st: AxisState = {a: VARYING for a in _eqn_axes(eqn)}
            for ov in eqn.outvars:
                self.set_state(ov, st)
            return
        if name == "shard_map":
            self._shard_map(eqn, trips)
            return
        if name == "scan":
            self._scan(eqn, trips)
            return
        if name == "while":
            self._while(eqn, trips)
            return
        if name == "cond":
            self._cond(eqn, trips)
            return
        sub = self._call_jaxpr(eqn)
        if sub is not None:
            self._call(eqn, sub, trips)
            return
        # Default transfer: varying is contagious, then unknown/sharded.
        joined = self._join_inputs(eqn)
        for ov in eqn.outvars:
            self.set_state(ov, joined)

    # --------------------------------------------------------- collectives

    def _comm(self, eqn, trips: int) -> None:
        axes = _eqn_axes(eqn)
        name = eqn.primitive.name
        joined = self._join_inputs(eqn)
        groups = eqn.params.get("axis_index_groups")
        out = dict(joined)
        for a in axes:
            if groups:
                # Partial-group collectives reduce within subgroups only;
                # claim nothing rather than risk a false J112.
                out[a] = UNKNOWN
            elif name in _REPLICATING:
                out[a] = REPLICATED
            else:
                out[a] = VARYING
        for ov in eqn.outvars:
            self.set_state(ov, out)
        world = 1
        for a in axes:
            world *= self.result.axis_sizes.get(a, 1)
        if world <= 1:
            return
        payload = sum(_aval_bytes(v) for v in eqn.invars if _is_var(v))
        wire = collective_wire_bytes(name, payload, world)
        f, ln = _src_loc(eqn)
        self.result.comm_events.append(CommEvent(
            kind=name, axes=tuple(sorted(axes)), world=world,
            payload_bytes=payload, wire_bytes=wire, trips=trips,
            file=f, line=ln,
        ))

    def _check_allreduce_then_slice(self, eqn, producers, consumers) -> None:
        """J115 at the psum site: every consumer of the allreduced value
        is a slice, and at least one is a dynamic_slice whose start index
        varies over the psum's own axis (the ``axis_index``-addressed
        keep-my-1/N pattern a psum_scatter serves at half the wire
        bytes)."""
        axes = set(_eqn_axes(eqn))
        if not axes:
            return
        for ov in eqn.outvars:
            uses = consumers.get(id(ov), [])
            if not uses:
                continue
            if any(u.primitive.name not in ("slice", "dynamic_slice",
                                            "convert_element_type")
                   for u in uses):
                continue
            hit = None
            for u in uses:
                if u.primitive.name != "dynamic_slice":
                    continue
                idx_axes = set()
                for iv in u.invars[1:]:
                    idx_axes.update(
                        a for a, e in self.state(iv).items() if e == VARYING
                    )
                if idx_axes & axes:
                    hit = u
                    break
            if hit is None:
                continue
            world = 1
            for a in sorted(axes):
                world *= self.result.axis_sizes.get(a, 2)
            f, ln = _src_loc(hit)
            self.result.findings.append(Finding(
                "J115",
                f"psum (allreduce) over axis {sorted(axes)} whose result "
                f"is consumed only by per-shard slices (dynamic_slice "
                f"indexed by axis_index) — every chip receives the full "
                f"reduction and keeps 1/{world}; a psum_scatter moves "
                f"about half the bytes and lands each shard where it is "
                f"used",
                file=f, line=ln, entrypoint=self.entrypoint,
            ))

    # ----------------------------------------------------------- shard_map

    def _shard_map(self, eqn, trips: int) -> None:
        mesh = eqn.params.get("mesh")
        body = eqn.params.get("jaxpr")
        in_names = eqn.params.get("in_names")
        out_names = eqn.params.get("out_names")
        if mesh is None or body is None:
            return
        try:
            mesh_axes = {str(a): int(s)
                         for a, s in zip(mesh.axis_names, mesh.devices.shape)}
        except Exception:
            mesh_axes = {str(a): int(mesh.shape[a]) for a in mesh.axis_names}
        self.result.axis_sizes.update(mesh_axes)
        jaxpr = _inner_jaxpr(body)
        # Body invar states are fully determined by in_names: axes the
        # names split a dim over differ per shard; the rest of the bound
        # axes see identical bytes of the one global value. Axes bound
        # further out (nested shard_map) propagate from the outer state.
        for var, names in zip(jaxpr.invars, in_names or ()):
            st: AxisState = {}
            split_axes = set()
            for dim_axes in (names or {}).values():
                split_axes.update(str(a) for a in _axis_strs(tuple(dim_axes)))
            for a in mesh_axes:
                st[a] = VARYING if a in split_axes else REPLICATED
            self.set_state(var, st)
        # Outer axes not bound by this mesh: carry through from inputs.
        outer_axes = {
            a for v in eqn.invars for a in self.state(v) if a not in mesh_axes
        }
        if outer_axes:
            for var, src in zip(jaxpr.invars, eqn.invars):
                st = dict(self.state(var))
                for a in outer_axes:
                    e = self.state(src).get(a, REPLICATED)
                    if e != REPLICATED:
                        st[a] = UNKNOWN
                self.set_state(var, st)
        self.interpret(body, trips)
        check_rep = bool(eqn.params.get("check_rep", False))
        for ov, body_ov, names in zip(
            eqn.outvars, jaxpr.outvars, out_names or ()
        ):
            declared = set()
            for dim_axes in (names or {}).values():
                declared.update(str(a) for a in _axis_strs(tuple(dim_axes)))
            body_st = self.state(body_ov)
            out_st: AxisState = {}
            for a in mesh_axes:
                if a in declared:
                    out_st[a] = SHARDED
                elif body_st.get(a, REPLICATED) == VARYING:
                    if not check_rep:
                        prod_eqn = self._producer_of(jaxpr, body_ov)
                        f, ln = (_src_loc(prod_eqn) if prod_eqn is not None
                                 else _src_loc(eqn))
                        self.result.findings.append(Finding(
                            "J112",
                            f"shard_map output is declared UNSHARDED over "
                            f"mesh axis '{a}' but the body value varies "
                            f"per shard — no reducing collective (psum/"
                            f"all_gather) stands between the shard-local "
                            f"computation and the replicated output; with "
                            f"check_rep=False each device silently returns "
                            f"different bytes (the missing-psum / lost "
                            f"transpose-factor class)",
                            file=f, line=ln, entrypoint=self.entrypoint,
                        ))
                    out_st[a] = UNKNOWN
                elif body_st.get(a, REPLICATED) == UNKNOWN:
                    out_st[a] = UNKNOWN
            # Outer axes carry through.
            for a, e in body_st.items():
                if a not in mesh_axes and e != REPLICATED:
                    out_st[a] = e
            self.set_state(ov, out_st)

    @staticmethod
    def _producer_of(jaxpr, var):
        for e in jaxpr.eqns:
            if any(ov is var for ov in e.outvars):
                return e
        return None

    # -------------------------------------------------------- control flow

    def _scan(self, eqn, trips: int) -> None:
        body = eqn.params["jaxpr"]
        jaxpr = _inner_jaxpr(body)
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        length = int(eqn.params.get("length", 1) or 1)
        self._loop_fixpoint(
            jaxpr,
            eqn.invars,
            n_consts=n_consts,
            n_carry=n_carry,
            carry_out_slice=slice(0, n_carry),
            trips=trips * max(length, 1),
        )
        # Outputs: carries then stacked ys, straight from body out states.
        for ov, body_ov in zip(eqn.outvars, jaxpr.outvars):
            self.set_state(ov, dict(self.state(body_ov)))

    def _while(self, eqn, trips: int) -> None:
        cond_jaxpr = eqn.params["cond_jaxpr"]
        body_jaxpr = eqn.params["body_jaxpr"]
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        cond = _inner_jaxpr(cond_jaxpr)
        body = _inner_jaxpr(body_jaxpr)
        carry_in = eqn.invars[cn + bn:]
        # Fixpoint on the body carry.
        self._loop_fixpoint(
            body,
            list(eqn.invars[cn:cn + bn]) + list(carry_in),
            n_consts=bn,
            n_carry=len(carry_in),
            carry_out_slice=slice(0, len(carry_in)),
            trips=trips,
        )
        self.result.unbounded_loops += 1
        # Evaluate the predicate on the settled carry states.
        for var, src in zip(cond.invars[:cn], eqn.invars[:cn]):
            self.set_state(var, dict(self.state(src)))
        for var, body_ov in zip(cond.invars[cn:], body.outvars):
            self.set_state(var, dict(self.state(body_ov)))
        self.interpret(cond_jaxpr, trips)
        pred_st = self.state(cond.outvars[0]) if cond.outvars else {}
        varying_axes = {a for a, e in pred_st.items() if e == VARYING}
        if varying_axes:
            comm_axes = set()
            for sub in (cond, body):
                comm_axes |= _comm_axes(sub)
            clash = sorted(varying_axes & comm_axes)
            if clash:
                f, ln = _src_loc(eqn)
                self.result.findings.append(Finding(
                    "J113",
                    f"while loop's predicate varies per shard over axis "
                    f"{clash} and its body/cond issue collectives over the "
                    f"same axis — shards run different trip counts, so "
                    f"some ranks post a collective their peers never "
                    f"enter: the slice deadlocks; derive the predicate "
                    f"from a reduced (psum/pmax) value so every shard "
                    f"agrees on the trip count",
                    file=f, line=ln, entrypoint=self.entrypoint,
                ))
        for ov, body_ov in zip(eqn.outvars, body.outvars):
            self.set_state(ov, dict(self.state(body_ov)))

    def _loop_fixpoint(self, body_jaxpr, invars, *, n_consts: int,
                       n_carry: int, carry_out_slice: slice,
                       trips: int) -> None:
        """Interpret a loop body until the carry states stop changing."""
        for var, src in zip(body_jaxpr.invars[:n_consts], invars[:n_consts]):
            self.set_state(var, dict(self.state(src)))
        carry_vars = body_jaxpr.invars[n_consts:n_consts + n_carry]
        xs_vars = body_jaxpr.invars[n_consts + n_carry:]
        for var, src in zip(carry_vars, invars[n_consts:n_consts + n_carry]):
            self.set_state(var, dict(self.state(src)))
        for var, src in zip(xs_vars, invars[n_consts + n_carry:]):
            self.set_state(var, dict(self.state(src)))
        events_mark = len(self.result.comm_events)
        findings_mark = len(self.result.findings)
        for it in range(1, _MAX_FIXPOINT_ITERS + 1):
            # Re-walks emit duplicate comm events/findings; keep only the
            # final iteration's.
            del self.result.comm_events[events_mark:]
            del self.result.findings[findings_mark:]
            self.interpret(body_jaxpr, trips)
            changed = False
            outs = body_jaxpr.outvars[carry_out_slice]
            for var, out in zip(carry_vars, outs):
                joined = dict(self.state(var))
                for a, e in self.state(out).items():
                    new = _join(joined.get(a, REPLICATED), e)
                    if new != joined.get(a, REPLICATED):
                        joined[a] = new
                        changed = True
                if changed:
                    self.set_state(var, joined)
            self.result.iterations = max(self.result.iterations, it)
            if not changed:
                return
        self.result.converged = False

    def _cond(self, eqn, trips: int) -> None:
        branches = eqn.params.get("branches", ())
        operands = eqn.invars[1:]
        out_states: list[AxisState] = [dict() for _ in eqn.outvars]
        for br in branches:
            jaxpr = _inner_jaxpr(br)
            for var, src in zip(jaxpr.invars, operands):
                self.set_state(var, dict(self.state(src)))
            self.interpret(br, trips)
            for i, body_ov in enumerate(jaxpr.outvars):
                for a, e in self.state(body_ov).items():
                    prev = out_states[i].get(a, REPLICATED)
                    out_states[i][a] = _join(prev, e)
        # A varying predicate makes the branch choice itself per-shard.
        pred_st = self.state(eqn.invars[0])
        pred_var = {a for a, e in pred_st.items() if e == VARYING}
        for ov, st in zip(eqn.outvars, out_states):
            st = dict(st)
            for a in pred_var:
                st[a] = _join(st.get(a, REPLICATED), UNKNOWN)
            self.set_state(ov, st)

    # -------------------------------------------------------------- calls

    @staticmethod
    def _call_jaxpr(eqn):
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            sub = eqn.params.get(key)
            if sub is not None and _is_jaxpr_like(sub):
                return sub
        return None

    def _call(self, eqn, sub, trips: int) -> None:
        jaxpr = _inner_jaxpr(sub)
        n_body, n_eqn = len(jaxpr.invars), len(eqn.invars)
        if n_body == n_eqn:
            pairs = zip(jaxpr.invars, eqn.invars)
        elif n_body < n_eqn:
            # Consts-first conventions (custom_vjp num_consts): the
            # trailing eqn invars are the real arguments.
            pairs = zip(jaxpr.invars, eqn.invars[n_eqn - n_body:])
        else:
            joined = self._join_inputs(eqn)
            pairs = ((v, None) for v in jaxpr.invars)
            for v, _ in pairs:
                self.set_state(v, dict(joined))
            pairs = ()
        for var, src in pairs:
            self.set_state(var, dict(self.state(src)))
        self.interpret(sub, trips)
        if len(jaxpr.outvars) == len(eqn.outvars):
            for ov, body_ov in zip(eqn.outvars, jaxpr.outvars):
                self.set_state(ov, dict(self.state(body_ov)))
        else:
            joined: AxisState = {}
            for body_ov in jaxpr.outvars:
                for a, e in self.state(body_ov).items():
                    joined[a] = _join(joined.get(a, REPLICATED), e)
            for ov in eqn.outvars:
                self.set_state(ov, dict(joined))


def _join(a: str, b: str) -> str:
    if a == b:
        return a
    if VARYING in (a, b):
        return VARYING
    return UNKNOWN


def _comm_axes(obj) -> set[str]:
    """All axes any communicating collective touches, recursively."""
    jaxpr = _inner_jaxpr(obj)
    axes: set[str] = set()
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COMM:
            axes.update(_eqn_axes(eqn))
        for val in eqn.params.values():
            if _is_jaxpr_like(val):
                axes |= _comm_axes(val)
            elif isinstance(val, (tuple, list)):
                for item in val:
                    if _is_jaxpr_like(item):
                        axes |= _comm_axes(item)
    return axes


def _seed_states(
    jaxpr, in_specs, mesh_axes: dict[str, int] | None
) -> Iterable[tuple[Any, AxisState]]:
    """Top-level invar states from entrypoint in_specs: an axis a spec
    mentions partitions that argument (``sharded``); the rest of the
    mesh is ``replicated`` (the engines place state either replicated or
    explicitly sharded — there is no third placement)."""
    if in_specs is None:
        return [(v, {}) for v in jaxpr.invars]
    import jax

    flat_specs: list = []
    try:
        for spec in in_specs:
            leaves = jax.tree.leaves(
                spec, is_leaf=lambda x: x is None or _is_partition_spec(x)
            )
            flat_specs.extend(leaves if leaves else [None])
    except Exception:
        flat_specs = []
    out = []
    for i, v in enumerate(jaxpr.invars):
        spec = flat_specs[i] if i < len(flat_specs) else None
        st: AxisState = {}
        if _is_partition_spec(spec):
            for a in _axis_strs(tuple(spec)):
                st[a] = SHARDED
        out.append((v, st))
    return out


def _is_partition_spec(x) -> bool:
    return type(x).__name__ == "PartitionSpec"


def analyze_dataflow(
    closed,
    entrypoint: str = "",
    in_specs=None,
    mesh_axes: dict[str, int] | None = None,
) -> DataflowResult:
    """Run the replication-lattice interpreter over one traced program.

    ``in_specs`` is the entrypoint's (optional) argument PartitionSpec
    pytree — flattened against the top-level invars to seed ``sharded``
    states; ``mesh_axes`` maps axis name -> size for collectives outside
    any shard_map (sizes inside shard_map come from the mesh param).
    """
    interp = _Interpreter(entrypoint, mesh_axes)
    jaxpr = _inner_jaxpr(closed)
    for v, st in _seed_states(jaxpr, in_specs, mesh_axes):
        interp.set_state(v, st)
    try:
        interp.interpret(closed)
    except RecursionError:
        interp.result.converged = False
        interp.result.findings.append(Finding(
            "J100",
            "dataflow interpreter exceeded recursion depth (jaxpr nesting)",
            entrypoint=entrypoint,
        ))
    interp.result.out_states = [
        dict(interp.state(v)) for v in jaxpr.outvars
    ]
    if not interp.result.converged and not any(
        f.rule == "J100" for f in interp.result.findings
    ):
        interp.result.findings.append(Finding(
            "J100",
            f"dataflow fixpoint did not converge within "
            f"{_MAX_FIXPOINT_ITERS} iterations",
            entrypoint=entrypoint,
        ))
    return interp.result
