"""Static comm / HBM cost reports over the dataflow walk.

:mod:`tpudml.analysis.dataflow` records every explicit collective the
interpreter passes (kind, axes, axis size, per-shard payload bytes,
ring-model wire bytes, scan-trip multiplier). This module turns those
``CommEvent`` streams into the per-entrypoint reports the ``--cost``
CLI mode emits:

- **comm volume**: wire bytes one device moves per step, aggregated by
  (collective kind, axes), plus a per-axis breakdown — the numbers a
  capacity plan needs before anyone rents the slice. The ring-model
  formulas live in :func:`tpudml.comm.timing.collective_wire_bytes`, the
  same table the runtime ``CommStats`` byte accounting uses, so the
  static prediction and the measured counters are directly comparable
  (the cross-validation test pins them within 5%).
- **peak-live-buffer HBM estimate**: a last-use liveness walk over the
  jaxpr (sub-jaxprs contribute their own internal peak as a transient
  on top of the caller's live set). It deliberately ignores XLA fusion
  and rematerialization — it is an upper-ish bound for "does this step
  even fit", not a simulator — and rule **J116** fires when the
  estimate exceeds a caller-provided budget.

Caveat that belongs next to the numbers: collectives inserted by the
GSPMD partitioner (the jit+in_shardings engines: mp.py / fsdp.py) are
invisible in the traced jaxpr, so their comm volume is reported as 0.
The shard_map engines (DP, ZeRO-1, TP, PP, CP, EP) express collectives
explicitly and are fully covered.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

from tpudml.analysis.dataflow import (
    CommEvent,
    DataflowResult,
    _aval_bytes,
    _inner_jaxpr,
    _is_jaxpr_like,
    _is_var,
)
from tpudml.analysis.findings import Finding

COST_REPORT_VERSION = 1


# --------------------------------------------------------------- peak HBM


def peak_live_bytes(closed) -> int:
    """Last-use-liveness estimate of peak simultaneously-live bytes.

    Walks equations in program order: a value is born at its defining
    equation and dies after its final consumer (outputs live to the
    end). An equation with sub-jaxprs adds the sub-program's internal
    peak beyond its arguments as a transient while it runs — so a scan
    body's scratch counts once, not per trip.
    """
    jaxpr = _inner_jaxpr(closed)
    eqns = jaxpr.eqns
    last_use: dict[int, int] = {}
    for idx, eqn in enumerate(eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[id(v)] = idx
    for v in jaxpr.outvars:
        if _is_var(v):
            last_use[id(v)] = len(eqns)

    live: dict[int, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        live[id(v)] = _aval_bytes(v)
    current = sum(live.values())
    peak = current
    for idx, eqn in enumerate(eqns):
        sub_extra = 0
        for sub in _sub_jaxprs_of(eqn):
            inner = _inner_jaxpr(sub)
            arg_bytes = sum(_aval_bytes(v) for v in inner.invars)
            sub_extra = max(sub_extra, max(0, peak_live_bytes(sub) - arg_bytes))
        born = 0
        for ov in eqn.outvars:
            if _is_var(ov) and id(ov) not in live:
                b = _aval_bytes(ov)
                live[id(ov)] = b
                born += b
        current += born
        peak = max(peak, current + sub_extra)
        for v in eqn.invars:
            if _is_var(v) and last_use.get(id(v)) == idx:
                current -= live.pop(id(v), 0)
    return peak


def _sub_jaxprs_of(eqn):
    for val in eqn.params.values():
        if _is_jaxpr_like(val):
            yield val
        elif isinstance(val, (tuple, list)):
            for item in val:
                if _is_jaxpr_like(item):
                    yield item


# ----------------------------------------------------------------- report


@dataclass
class EntrypointCost:
    """One entrypoint's static cost summary."""

    entrypoint: str
    mesh_axes: dict[str, int] = field(default_factory=dict)
    collectives: list[dict] = field(default_factory=list)
    total_wire_bytes: float = 0.0
    per_axis_wire_bytes: dict[str, float] = field(default_factory=dict)
    peak_hbm_bytes: int = 0
    unbounded_loops: int = 0
    fixpoint_iterations: int = 0
    error: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "entrypoint": self.entrypoint,
            "mesh_axes": dict(self.mesh_axes),
            "collectives": list(self.collectives),
            "total_wire_bytes": self.total_wire_bytes,
            "per_axis_wire_bytes": dict(self.per_axis_wire_bytes),
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "unbounded_loops": self.unbounded_loops,
            "fixpoint_iterations": self.fixpoint_iterations,
            **({"error": self.error} if self.error else {}),
        }


def summarize_cost(
    entrypoint: str,
    flow: DataflowResult,
    closed=None,
) -> EntrypointCost:
    """Aggregate one walk's CommEvents by (kind, axes) and attach the
    peak-HBM estimate (when the traced program is provided)."""
    groups: dict[tuple[str, tuple[str, ...]], dict] = {}
    for ev in flow.comm_events:
        key = (ev.kind, ev.axes)
        g = groups.setdefault(key, {
            "kind": ev.kind,
            "axes": list(ev.axes),
            "world": ev.world,
            "calls": 0,
            "payload_bytes": 0.0,
            "wire_bytes": 0.0,
        })
        g["calls"] += ev.trips
        g["payload_bytes"] += float(ev.payload_bytes) * ev.trips
        g["wire_bytes"] += ev.wire_bytes * ev.trips
    per_axis: dict[str, float] = {}
    for (_, axes), g in groups.items():
        share = g["wire_bytes"] / max(len(axes), 1)
        for a in axes:
            per_axis[a] = per_axis.get(a, 0.0) + share
    cost = EntrypointCost(
        entrypoint=entrypoint,
        mesh_axes=dict(flow.axis_sizes),
        collectives=sorted(
            groups.values(), key=lambda g: -g["wire_bytes"]
        ),
        total_wire_bytes=sum(g["wire_bytes"] for g in groups.values()),
        per_axis_wire_bytes=per_axis,
        unbounded_loops=flow.unbounded_loops,
        fixpoint_iterations=flow.iterations,
    )
    if closed is not None:
        try:
            cost.peak_hbm_bytes = int(peak_live_bytes(closed))
        except RecursionError:
            cost.error = "peak-HBM walk exceeded recursion depth"
    return cost


def check_hbm_budget(
    cost: EntrypointCost, hbm_budget_bytes: int | None
) -> list[Finding]:
    """J116: static peak estimate over the configured budget."""
    if not hbm_budget_bytes or cost.peak_hbm_bytes <= hbm_budget_bytes:
        return []
    return [Finding(
        "J116",
        f"static peak-live-buffer estimate "
        f"{cost.peak_hbm_bytes / 1e6:.1f} MB exceeds the "
        f"{hbm_budget_bytes / 1e6:.1f} MB HBM budget "
        f"(liveness walk; ignores XLA fusion/remat, so treat as an "
        f"upper-ish bound)",
        entrypoint=cost.entrypoint,
    )]


def check_plan_drift(
    cost: EntrypointCost,
    plan: dict,
    threshold: float | None = None,
) -> list[Finding]:
    """J118: traced comm/HBM vs the emitted plan's ``predicted`` block.

    ``plan`` is a plan.json document (or any dict with a ``predicted``
    record); the tolerance defaults to the same 10% the obs drift
    monitor gates on (``tpudml.obs.drift.DEFAULT_THRESHOLD``) — one
    knob for "how far may static and truth diverge", everywhere.
    Relative error is measured against the predicted value; a predicted
    value of 0 with a nonzero traced one counts as full drift.
    """
    if threshold is None:
        from tpudml.obs.drift import DEFAULT_THRESHOLD

        threshold = DEFAULT_THRESHOLD
    predicted = (plan or {}).get("predicted") or {}
    findings: list[Finding] = []
    checks = (
        ("comm_wire_bytes", "collective wire bytes",
         float(cost.total_wire_bytes)),
        ("peak_hbm_bytes", "peak-live HBM bytes",
         float(cost.peak_hbm_bytes)),
    )
    for key, label, traced in checks:
        if key not in predicted:
            continue
        pred = float(predicted[key])
        if pred == traced:
            continue
        rel = abs(traced - pred) / pred if pred else float("inf")
        if rel <= threshold:
            continue
        findings.append(Finding(
            "J118",
            f"traced {label} {traced:.0f} deviates "
            f"{rel * 100:.0f}% from the plan's predicted {pred:.0f} "
            f"(tolerance {threshold * 100:.0f}%) — the emitted plan no "
            f"longer describes this program; re-plan or allowlist",
            entrypoint=cost.entrypoint,
        ))
    return findings


def build_cost_report(costs: list[EntrypointCost]) -> dict[str, Any]:
    """The ``analysis/cost_report.json`` document."""
    return {
        "version": COST_REPORT_VERSION,
        "wire_model": "ring (see tpudml.comm.timing.collective_wire_bytes)",
        "units": "bytes moved per device per step",
        "entrypoints": [c.to_dict() for c in costs],
        "total_wire_bytes": sum(c.total_wire_bytes for c in costs),
    }


def write_cost_report(costs: list[EntrypointCost], path: str) -> None:
    with open(path, "w") as fh:
        json.dump(build_cost_report(costs), fh, indent=2, sort_keys=False)
        fh.write("\n")


def format_cost_table(costs: list[EntrypointCost]) -> str:
    """The ``--cost`` terminal table."""
    lines = [
        "Static comm/HBM cost (ring model, bytes per device per step)",
        f"{'entrypoint':<16} {'collective':<16} {'axes':<14} "
        f"{'world':>5} {'calls':>5} {'wire MB':>9}",
    ]
    for c in costs:
        if c.error:
            lines.append(f"{c.entrypoint:<16} <error: {c.error}>")
            continue
        if not c.collectives:
            lines.append(
                f"{c.entrypoint:<16} {'-':<16} {'-':<14} {'-':>5} {'-':>5} "
                f"{0.0:>9.3f}"
            )
        for i, g in enumerate(c.collectives):
            name = c.entrypoint if i == 0 else ""
            axes = ",".join(g["axes"]) or "-"
            lines.append(
                f"{name:<16} {g['kind']:<16} {axes:<14} {g['world']:>5} "
                f"{g['calls']:>5} {g['wire_bytes'] / 1e6:>9.3f}"
            )
        extra = f"{'':<16}   total {c.total_wire_bytes / 1e6:.3f} MB"
        if c.peak_hbm_bytes:
            extra += f", peak HBM est {c.peak_hbm_bytes / 1e6:.1f} MB"
        if c.unbounded_loops:
            extra += (f", {c.unbounded_loops} unbounded while loop(s) "
                      f"(per-trip bytes only)")
        lines.append(extra)
    lines.append(
        f"{'TOTAL':<16} {sum(c.total_wire_bytes for c in costs) / 1e6:.3f} MB"
    )
    return "\n".join(lines)
