"""Pass 1 — jaxpr analysis of traced train steps.

Walks a ``ClosedJaxpr`` (recursing through pjit/scan/while/cond/shard_map
sub-jaxprs while tracking which collective axis names are bound) and
flags the hazard classes that otherwise fail only at runtime on a
multi-host slice:

- J101  collectives whose axis name is not bound by an enclosing
        shard_map/pmap (the same class of bug also surfaces as a trace
        NameError — ``analyze_callable`` converts that to J101 too);
- J102  cond/switch branches that issue different collective sequences —
        with a shard-dependent predicate this deadlocks the slice;
- J103  host callback primitives inside the step (debug prints,
        pure/io_callback): every call is a device→host sync;
- J104  bf16→f32 upcast edges whose results feed non-accumulating
        consumers (mixed-precision leaks that silently re-inflate
        bandwidth); explicit accumulation (reductions, dots) is exempt;
- J105  large (>1 MiB) arrays captured as jaxpr constants — baked into
        the program instead of passed (and donated) as arguments;
- J106  (from the lowered module, not the jaxpr) steps whose large
        inputs carry no donation aliasing at all;
- J107  the UNSHARDED fused cross-entropy head consuming a kernel whose
        vocab (last) dimension is sharded over a mesh axis — each shard
        then normalizes over only its local vocab slice and the losses
        are silently wrong; the sharded wrapper
        (``sharded_linear_cross_entropy``) merges per-shard statistics
        and stays silent.
- J108  a REPLICATED optimizer update under ``shard_map`` on a mesh with
        a data axis: gradient-shaped tensors are allreduced (psum) over
        the axis and returned replicated, with no reduce-scatter in
        sight — every chip pays the full optimizer FLOPs/HBM, the exact
        waste ZeRO-1 weight-update sharding (``optim.zero1``) removes.
- J109  ``lax.ragged_dot``'s stock grouped-transpose dW surviving into a
        backward: both dW operands materialized as ``[E, P, ·]``
        range-masked broadcasts feeding a batched ``dot_general`` — E×
        the dense dW FLOPs (the 3.4× ragged-MoE backward of BASELINE
        round 5); the grouped-dW kernel path (``ops.moe_kernel``) never
        builds those broadcasts and stays silent.
- J110  a decode-marked program (``tpudml.serve``'s jitted per-token
        step) that recomputes FULL-sequence attention per emitted token:
        a softmax ``exp`` over scores whose trailing two (query, key)
        dims are both > 1 means the step pays O(T²) attention for one
        token — generation goes quadratic-per-token instead of reading
        the KV cache. The cache-carrying step's scores are [B, H, 1, L]
        (query dim 1) and stay silent.
- J111  a training step that UPDATES parameters (≥2 elementwise ``sub``
        equations whose minuend is a jaxpr invar, possibly through
        reshape/concat/slice — the SGD/Adam ``p - update`` shape, incl.
        ZeRO-1's flattened chunks) while the WHOLE program contains no
        ``is_finite`` predicate: one non-finite microbatch then reaches
        the weights and, under synchronous collectives, every replica at
        once — the unrecoverable-divergence mode the step sentinel
        (``resilience.GradSentinel``) closes. Sentinel-wrapped steps
        carry the finiteness check in-graph and stay silent.

- J114  a buffer donated to a jitted call (``donated_invars``) consumed
        AGAIN afterwards — by a later equation at the same level, by the
        program's own outputs, or twice within the one call: XLA may
        have aliased the memory to an output, so the second read sees
        whatever the donating program wrote over it.

Since the replication-lattice interpreter landed
(:mod:`tpudml.analysis.dataflow`), ``analyze_closed_jaxpr`` also runs
the sharding-aware dataflow rules over the same traced program: J112
(missing psum under ``check_rep=False``), J113 (shard-dependent while
trip counts around collectives), J115 (allreduce-then-shard), and —
when an HBM budget is supplied — J116 from the static cost walk
(:mod:`tpudml.analysis.cost`).

The pass is backend-free: everything works on abstract values on CPU.
"""

from __future__ import annotations

import os
import re
from typing import Any, Callable, Iterable

from tpudml.analysis.findings import Finding

# Primitives that require a bound axis name (J101). The subset that
# actually communicates (everything but axis_index) forms the J102
# branch signature.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather", "axis_index",
})
COMM_PRIMS = COLLECTIVE_PRIMS - {"axis_index"}

CALLBACK_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "callback",
    "outside_call", "host_callback_call", "infeed", "outfeed",
})

# Direct consumers under which a bf16→f32 upcast is the intended
# accumulate-in-f32 idiom (J104 stays silent).
ACCUM_OK_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_precision", "dot_general", "conv_general_dilated",
    "cumsum", "cumprod", "cumlogsumexp", "cummax", "cummin",
    "scan", "while", "psum", "psum_scatter", "reduce_scatter",
    "convert_element_type",
})

LARGE_CONST_BYTES = 1 << 20  # 1 MiB

# The fused cross-entropy dispatchers are jitted under marker names that
# survive as pjit ``name`` params in any traced jaxpr (J107). Mirrors
# FUSED_XENT_MARKER / SHARDED_XENT_MARKER in tpudml/ops/xent_kernel.py —
# string literals here so the analyzer never imports kernel code; the
# pairing is pinned by test_analysis.
FUSED_XENT_NAME = "_fused_xent_unsharded"
SHARDED_XENT_NAME = "_fused_xent_sharded"

# The serving decode step is jitted under this marker name (J110).
# Mirrors SERVE_DECODE_MARKER in tpudml/serve/engine.py — a string
# literal for the same reason; the pairing is pinned by test_analysis.
SERVE_DECODE_NAME = "_serve_decode_step"

# Paged/speculative decode steps carry their own marker names (J117) —
# NOT the dense marker: the spec verify window's [B, H, K+1, L] softmax
# would false-fire J110's both-trailing-dims>1 check on a single-token
# contract. Mirror PAGED_DECODE_MARKER (tpudml/serve/paged.py) and
# SPEC_DECODE_MARKER (tpudml/serve/spec.py); pinned by test_analysis.
PAGED_DECODE_NAMES = ("_serve_paged_decode_step", "_serve_spec_decode_step")

# The fused decode-tail dispatchers (head matmul + greedy pick + step
# stats as one vocab-tiled program) are jitted under these marker names
# (J119's tail check skips their bodies — their internal argmax IS the
# fused pick). Mirror FUSED_HEAD_MARKER / FUSED_HEAD_INT8_MARKER in
# tpudml/ops/decode_head.py; pinned by test_analysis.
FUSED_HEAD_NAMES = ("_fused_decode_head", "_fused_decode_head_int8")

# The chunked psum-overlapped TP matmul is jitted under this marker name
# (J119's overlap-claim check). Mirrors TP_OVERLAP_MARKER in
# tpudml/parallel/overlap.py; pinned by test_analysis.
TP_OVERLAP_NAME = "_tp_overlap_matmul"

# Decode-marked pjit names whose bodies J119's unfused-tail check scans.
_DECODE_TAIL_NAMES = (SERVE_DECODE_NAME,) + PAGED_DECODE_NAMES

# Primitives a last-dim sharding survives on the way from a shard_map
# body invar to the fused head's w operand (J107 taint propagation).
_LASTDIM_PRESERVING = frozenset({"convert_element_type", "copy"})

# Mesh axis names that conventionally carry data parallelism (J108 only
# reasons about replicated WEIGHT updates, which live on these axes).
_DATA_AXIS_NAMES = frozenset({"data", "batch"})

# Primitives through which "this value is (a repartitioned view of) a
# jaxpr invar" survives on the way to a parameter-update ``sub`` (J111
# taint) — ZeRO-1 reshapes/concatenates/slices param leaves into flat
# chunks before its inner update subtracts from them. Compute primitives
# (dot, conv, reductions) deliberately KILL the taint: activations
# derived from the batch never count as parameters.
_J111_PRESERVING = frozenset({
    "reshape", "concatenate", "slice", "dynamic_slice",
    "convert_element_type", "transpose", "squeeze", "copy",
})


def _repo_rel(path: str) -> str:
    """Repo/cwd-relative path for stable reporting + allowlist matching."""
    if not path:
        return path
    cwd = os.getcwd()
    try:
        rel = os.path.relpath(path, cwd)
    except ValueError:  # pragma: no cover - different drive (windows)
        return path
    return path if rel.startswith("..") else rel


def _src_loc(eqn) -> tuple[str, int]:
    """(file, line) of the user frame that built an equation."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return _repo_rel(frame.file_name), int(frame.start_line)
    except Exception:
        pass
    return "", 0


def _axis_strs(value: Any) -> tuple[str, ...]:
    """String axis names out of an ``axes``/``axis_name`` param value
    (str | int | tuple thereof; ints are positional vmap axes)."""
    if isinstance(value, str):
        return (value,)
    if isinstance(value, (tuple, list, frozenset, set)):
        out: list[str] = []
        for v in value:
            out.extend(_axis_strs(v))
        return tuple(out)
    return ()


def _eqn_axes(eqn) -> tuple[str, ...]:
    axes: list[str] = []
    for key in ("axes", "axis_name"):
        if key in eqn.params:
            axes.extend(_axis_strs(eqn.params[key]))
    return tuple(axes)


def _inner_jaxpr(obj):
    """Normalize Jaxpr | ClosedJaxpr -> (Jaxpr, consts)."""
    if hasattr(obj, "jaxpr"):  # ClosedJaxpr
        return obj.jaxpr, getattr(obj, "consts", ())
    return obj, ()


def _is_jaxpr_like(obj) -> bool:
    return hasattr(obj, "eqns") or (
        hasattr(obj, "jaxpr") and hasattr(obj.jaxpr, "eqns")
    )


def _sub_jaxprs(eqn) -> Iterable[tuple[Any, frozenset[str]]]:
    """(sub-jaxpr, extra bound axes) pairs under an equation."""
    extra: frozenset[str] = frozenset()
    name = eqn.primitive.name
    if name == "shard_map":
        mesh = eqn.params.get("mesh")
        if mesh is not None:
            extra = frozenset(str(a) for a in mesh.axis_names)
    elif name in ("xla_pmap", "pmap"):
        extra = frozenset(_axis_strs(eqn.params.get("axis_name", ())))
    for val in eqn.params.values():
        if _is_jaxpr_like(val):
            yield val, extra
        elif isinstance(val, (tuple, list)):
            for item in val:
                if _is_jaxpr_like(item):
                    yield item, extra


def _collective_signature(obj) -> tuple[tuple[str, tuple[str, ...]], ...]:
    """Ordered (prim, axes) sequence of communicating collectives inside a
    jaxpr, recursing through sub-jaxprs — the J102 branch fingerprint."""
    jaxpr, _ = _inner_jaxpr(obj)
    sig: list[tuple[str, tuple[str, ...]]] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in COMM_PRIMS:
            sig.append((eqn.primitive.name, tuple(sorted(_eqn_axes(eqn)))))
        for sub, _extra in _sub_jaxprs(eqn):
            sig.extend(_collective_signature(sub))
    return tuple(sig)


def collective_shape_signature(obj) -> tuple:
    """Ordered ``(prim, axes, operand shape)`` sequence of communicating
    collectives, recursing through sub-jaxprs — the shape-carrying
    variant of the J102 fingerprint that the protocol pass's P302 check
    (``analysis/protocol.py``) compares across the ranks of one MPMD
    stage group."""
    jaxpr, _ = _inner_jaxpr(obj)
    sig: list = []
    for eqn in jaxpr.eqns:
        # shard_map's rewrite pass emits numbered variants (psum -> psum2)
        # of the same wire collective; normalize so signatures compare
        # across pmap- and shard_map-traced ranks.
        name = eqn.primitive.name
        if name not in COMM_PRIMS and name.rstrip("0123456789") in COMM_PRIMS:
            name = name.rstrip("0123456789")
        if name in COMM_PRIMS:
            shape = ()
            if eqn.invars:
                shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            sig.append((
                name,
                tuple(sorted(_eqn_axes(eqn))),
                shape,
            ))
        for sub, _extra in _sub_jaxprs(eqn):
            sig.extend(collective_shape_signature(sub))
    return tuple(sig)


def _check_upcasts(jaxpr, entrypoint: str, findings: list[Finding]) -> None:
    """J104 within one jaxpr level: convert_element_type bf16→f32 whose
    result has a non-accumulating direct consumer."""
    import numpy as np

    consumers: dict[int, list[str]] = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if hasattr(v, "count") or type(v).__name__ == "Var":
                consumers.setdefault(id(v), []).append(eqn.primitive.name)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name != "convert_element_type":
            continue
        try:
            src_dtype = eqn.invars[0].aval.dtype
            dst_dtype = np.dtype(eqn.params["new_dtype"])
        except Exception:
            continue
        if str(src_dtype) != "bfloat16" or str(dst_dtype) != "float32":
            continue
        used_by = consumers.get(id(eqn.outvars[0]), [])
        bad = [p for p in used_by if p not in ACCUM_OK_PRIMS]
        if bad:
            f, ln = _src_loc(eqn)
            findings.append(Finding(
                "J104",
                f"bf16 value upcast to f32 feeds non-accumulating "
                f"consumer(s) {sorted(set(bad))}",
                file=f, line=ln, entrypoint=entrypoint,
            ))


def _check_ragged_transpose(jaxpr, entrypoint: str,
                            findings: list[Finding]) -> None:
    """J109 within one jaxpr level: ``lax.ragged_dot``'s transpose rule
    left in a backward. The stock VJP materializes BOTH dW operands as
    ``[E, P, ·]`` range-masked broadcasts (``select_n`` of a
    ``broadcast_in_dim`` over dims (1, 2) of a rank-2 array) and
    contracts them with a batched ``dot_general`` over the P dim — E×
    the dense dW FLOPs plus an E-fold activation materialization. The
    grouped-dW path (ops.moe_kernel) never builds those broadcasts, so
    it stays silent; only levels that also contain a ``ragged_dot``
    (i.e. an actual ragged-MoE backward) are considered."""
    if not any(e.primitive.name == "ragged_dot" for e in jaxpr.eqns):
        return
    producers = {id(v): e for e in jaxpr.eqns for v in e.outvars}

    def chase(var):
        eqn = producers.get(id(var))
        while eqn is not None and eqn.primitive.name == "convert_element_type":
            eqn = producers.get(id(eqn.invars[0]))
        return eqn

    def is_masked_bcast(var) -> bool:
        eqn = chase(var)
        if eqn is None or eqn.primitive.name != "select_n":
            return False
        for v in eqn.invars:
            p = chase(v)
            if (p is not None and p.primitive.name == "broadcast_in_dim"
                    and tuple(p.params.get("broadcast_dimensions", ())) == (1, 2)
                    and getattr(getattr(p.invars[0], "aval", None), "ndim",
                                None) == 2):
                return True
        return False

    for eqn in jaxpr.eqns:
        if eqn.primitive.name != "dot_general":
            continue
        dims = eqn.params.get("dimension_numbers")
        if dims != (((1,), (1,)), ((0,), (0,))):
            continue
        if any(getattr(getattr(v, "aval", None), "ndim", 0) != 3
               for v in eqn.invars[:2]):
            continue
        if is_masked_bcast(eqn.invars[0]) and is_masked_bcast(eqn.invars[1]):
            f, ln = _src_loc(eqn)
            e_dim = eqn.invars[0].aval.shape[0]
            findings.append(Finding(
                "J109",
                f"ragged_dot grouped-transpose dW: batched dot_general over "
                f"two [{e_dim}, P, ·] range-masked broadcasts — {e_dim}× the "
                f"dense dW FLOPs in the backward",
                file=f, line=ln, entrypoint=entrypoint,
            ))


def _fused_xent_seed(eqn) -> dict[int, tuple[str, ...]]:
    """J107 taint seed for one shard_map equation: body invars whose
    LAST dimension the in_names shard, mapped to the sharding axes."""
    in_names = eqn.params.get("in_names")
    body = eqn.params.get("jaxpr")
    if in_names is None or body is None:
        return {}
    jaxpr, _ = _inner_jaxpr(body)
    tainted: dict[int, tuple[str, ...]] = {}
    for var, names in zip(jaxpr.invars, in_names):
        ndim = getattr(getattr(var, "aval", None), "ndim", 0)
        axes = names.get(ndim - 1, ()) if ndim else ()
        if axes:
            tainted[id(var)] = tuple(str(a) for a in axes)
    return tainted


def _check_fused_xent(obj, tainted: dict[int, tuple[str, ...]],
                      entrypoint: str, findings: list[Finding]) -> None:
    """J107 within a shard_map body: propagate 'vocab dim is sharded'
    from the seed through last-dim-preserving ops (and all_gathers over
    other dims) to the w operand (position 1) of a pjit carrying the
    unsharded fused-xent marker name. The sharded dispatcher's distinct
    marker keeps correct compositions silent."""
    jaxpr, _ = _inner_jaxpr(obj)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "pjit":
            jit_name = str(eqn.params.get("name", ""))
            if jit_name == FUSED_XENT_NAME:
                axes = (tainted.get(id(eqn.invars[1]))
                        if len(eqn.invars) > 1 else None)
                if axes:
                    f, ln = _src_loc(eqn)
                    findings.append(Finding(
                        "J107",
                        f"fused cross-entropy head consumes a kernel whose "
                        f"vocab (last) dim is sharded over mesh axis "
                        f"{list(axes)} without the shard-merge wrapper — "
                        f"each shard normalizes over its local slice only; "
                        f"use sharded_linear_cross_entropy(axis_name=...)",
                        file=f, line=ln, entrypoint=entrypoint,
                    ))
                continue
            if jit_name == SHARDED_XENT_NAME:
                continue  # merge wrapper present — correct by construction
            sub = eqn.params.get("jaxpr")
            if sub is not None:
                sj, _ = _inner_jaxpr(sub)
                inner = {
                    id(sj.invars[i]): axes
                    for i, v in enumerate(eqn.invars)
                    if (axes := tainted.get(id(v))) and i < len(sj.invars)
                }
                if inner:
                    _check_fused_xent(sub, inner, entrypoint, findings)
            continue
        if not eqn.invars or not eqn.outvars:
            continue
        axes = tainted.get(id(eqn.invars[0]))
        if not axes:
            continue
        if name in _LASTDIM_PRESERVING:
            tainted[id(eqn.outvars[0])] = axes
        elif name == "all_gather":
            out = eqn.outvars[0]
            ndim = getattr(getattr(out, "aval", None), "ndim", 0)
            if eqn.params.get("all_gather_dimension", 0) != ndim - 1:
                tainted[id(out)] = axes


def _find_wide_softmax_exp(obj):
    """First ``exp`` equation (recursing through sub-jaxprs) whose operand
    keeps BOTH trailing dims > 1 — the [.., T, T] attention-probability
    tensor of a full-sequence softmax. A cache-reading decode step's
    softmax runs on [B, H, 1, L] scores (one query row per emitted
    token), so its exp never matches. Fused-head marker bodies are
    skipped: their lse statistics exp over [B, V_tile] vocab columns,
    not attention scores."""
    jaxpr, _ = _inner_jaxpr(obj)
    for eqn in jaxpr.eqns:
        if (eqn.primitive.name == "pjit"
                and str(eqn.params.get("name", "")) in FUSED_HEAD_NAMES):
            continue
        if eqn.primitive.name == "exp":
            shape = tuple(
                getattr(getattr(eqn.invars[0], "aval", None), "shape", ())
            )
            if len(shape) >= 2 and shape[-1] > 1 and shape[-2] > 1:
                return eqn, shape
        for sub, _extra in _sub_jaxprs(eqn):
            hit = _find_wide_softmax_exp(sub)
            if hit is not None:
                return hit
    return None


def _check_cacheless_decode(eqn, entrypoint: str,
                            findings: list[Finding]) -> None:
    """J110 for one decode-marked pjit equation: the per-token step
    contains a full-sequence attention softmax, i.e. it recomputes every
    previous position's scores to emit ONE token. One finding per marked
    program (the per-layer repeats add nothing)."""
    body = eqn.params.get("jaxpr")
    if body is None:
        return
    hit = _find_wide_softmax_exp(body)
    if hit is None:
        return
    exp_eqn, shape = hit
    f, ln = _src_loc(exp_eqn)
    findings.append(Finding(
        "J110",
        f"decode step recomputes full-sequence attention per emitted "
        f"token: softmax exp over {list(shape)} scores (query and key "
        f"dims both > 1) inside the per-token program — O(T²) per token; "
        f"carry a KV cache (tpudml.serve) so decode attends [B, H, 1, L]",
        file=f, line=ln, entrypoint=entrypoint,
    ))


def _find_pool_wide_exp(obj, pool_rows: frozenset):
    """First ``exp`` equation (recursing through sub-jaxprs) whose operand's
    LAST dim equals some pool's total row count — attention scores keyed
    over every page in the pool instead of one slot's table window."""
    jaxpr, _ = _inner_jaxpr(obj)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "exp":
            shape = tuple(
                getattr(getattr(eqn.invars[0], "aval", None), "shape", ())
            )
            if shape and shape[-1] in pool_rows:
                return eqn, shape
        for sub, _extra in _sub_jaxprs(eqn):
            hit = _find_pool_wide_exp(sub, pool_rows)
            if hit is not None:
                return hit
    return None


def _check_full_pool_gather(eqn, entrypoint: str,
                            findings: list[Finding]) -> None:
    """J117 for one paged-decode-marked pjit equation: a healthy paged
    step's softmax is keyed on ``max_pages·page_size`` gathered table
    rows per slot; keying on ``num_pages·page_size`` (the leading-dims
    product of a rank-4 pool invar) means the program materializes the
    WHOLE pool per token — attention cost scaling with total HBM
    provisioned instead of one tenant's window.

    Detectability bound (documented, like J110's): the pool is
    identified shape-wise as any rank-4 invar with both leading dims
    > 1, so the check needs the pool strictly larger than one slot's
    table (num_pages > max_pages — true of any multi-tenant pool; the
    registered entrypoint and fixtures guarantee it) and, for spec
    programs whose DENSE caches are also rank-4, slots >= 2 (else
    slots·max_len collides with the draft's own max_len softmax width).
    One finding per marked program."""
    body = eqn.params.get("jaxpr")
    if body is None:
        return
    jaxpr, _ = _inner_jaxpr(body)
    pool_rows = set()
    for iv in jaxpr.invars:
        shape = tuple(getattr(getattr(iv, "aval", None), "shape", ()))
        if len(shape) == 4 and shape[0] > 1 and shape[1] > 1:
            pool_rows.add(shape[0] * shape[1])
    if not pool_rows:
        return
    hit = _find_pool_wide_exp(body, frozenset(pool_rows))
    if hit is None:
        return
    exp_eqn, shape = hit
    f, ln = _src_loc(exp_eqn)
    findings.append(Finding(
        "J117",
        f"paged decode step attends over the full page pool: softmax exp "
        f"over {list(shape)} scores whose key dim matches a pool's total "
        f"rows (num_pages·page_size) — per-token cost scales with pool "
        f"HBM, not the slot's table window",
        file=f, line=ln, entrypoint=entrypoint,
    ))


def _scan_unfused_tail(obj, dot_dims: set, hits: list) -> None:
    """Recursive in-order scan for J119's tail half: collect the last
    output dim of every ``dot_general`` seen so far, and record any
    ``argmax`` that reduces its operand's LAST axis when that axis's
    size matches a collected matmul output dim — the greedy pick
    consuming a materialized full-width logits row. Sub-pjits named in
    ``FUSED_HEAD_NAMES`` are skipped wholesale: their internal argmax is
    the fused epilogue, not a round-trip."""
    jaxpr, _ = _inner_jaxpr(obj)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if (name == "pjit"
                and str(eqn.params.get("name", "")) in FUSED_HEAD_NAMES):
            continue
        if name == "dot_general":
            for ov in eqn.outvars:
                shape = tuple(getattr(getattr(ov, "aval", None), "shape", ()))
                if shape:
                    dot_dims.add(shape[-1])
        if name == "argmax":
            shape = tuple(
                getattr(getattr(eqn.invars[0], "aval", None), "shape", ())
            )
            axes = tuple(eqn.params.get("axes", ()))
            if (shape and axes and axes == (len(shape) - 1,)
                    and shape[-1] > 1 and shape[-1] in dot_dims):
                hits.append((eqn, shape))
        for sub, _extra in _sub_jaxprs(eqn):
            _scan_unfused_tail(sub, dot_dims, hits)


def _check_unfused_decode_tail(eqn, entrypoint: str,
                               findings: list[Finding]) -> None:
    """J119 (tail half) for one decode-marked pjit equation: the step
    materializes the full-vocab logits row out of the head matmul and
    argmaxes it as a separate reduction — a [B, V] HBM round-trip per
    emitted token that the fused head (``ops.fused_decode_head``) folds
    into the matmul's epilogue. Vocab is identified as any matmul output
    last-dim seen earlier in the same marked body (the head is the only
    matmul whose output width the pick reduces over). One finding per
    marked program."""
    body = eqn.params.get("jaxpr")
    if body is None:
        return
    hits: list = []
    _scan_unfused_tail(body, set(), hits)
    if not hits:
        return
    am_eqn, shape = hits[0]
    f, ln = _src_loc(am_eqn)
    findings.append(Finding(
        "J119",
        f"decode step materializes the full-vocab logits and argmaxes "
        f"them outside the head matmul: argmax over {list(shape)} whose "
        f"reduced dim matches a matmul output width — the [B, V] tail "
        f"round-trips HBM every emitted token",
        file=f, line=ln, entrypoint=entrypoint,
    ))


def _contains_pjit_named(obj, names: tuple) -> bool:
    """True if any (recursively nested) pjit equation carries one of the
    marker ``names`` — J119's overlap-claim verification."""
    jaxpr, _ = _inner_jaxpr(obj)
    for eqn in jaxpr.eqns:
        if (eqn.primitive.name == "pjit"
                and str(eqn.params.get("name", "")) in names):
            return True
        for sub, _extra in _sub_jaxprs(eqn):
            if _contains_pjit_named(sub, names):
                return True
    return False


def _scan_update_collectives(obj, axes: tuple[str, ...], acc: dict) -> None:
    """Recursively collect, for J108: the output shapes of tensor psums
    over any of ``axes`` (the allreduced gradients), and whether any
    reduce-scatter over those axes occurs (the ZeRO-1 signature)."""
    jaxpr, _ = _inner_jaxpr(obj)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in ("psum", "psum_scatter", "reduce_scatter"):
            eq_axes = _eqn_axes(eqn)
            if any(a in eq_axes for a in axes):
                if name == "psum":
                    for ov in eqn.outvars:
                        shape = tuple(
                            getattr(getattr(ov, "aval", None), "shape", ())
                        )
                        if shape:
                            acc["psum_shapes"].append(shape)
                            if "loc" not in acc:
                                # The shard_map eqn itself carries the
                                # re-trace frame; the first gradient psum
                                # points at the aggregation call site.
                                acc["loc"] = _src_loc(eqn)
                else:
                    acc["rs"] = True
        for sub, _extra in _sub_jaxprs(eqn):
            _scan_update_collectives(sub, axes, acc)


def _check_replicated_update(eqn, entrypoint: str,
                             findings: list[Finding]) -> None:
    """J108 for one shard_map equation: the body allreduces ≥2 tensor
    gradients over a data axis, returns ≥2 matching-shape outputs
    REPLICATED over that axis (per out_names), and never reduce-scatters
    — i.e. a replicated weight update. A ZeRO-1 body (psum_scatter on
    the grads, state outputs sharded over the axis) stays silent, as
    does a reduce-scatter aggregation strategy."""
    mesh = eqn.params.get("mesh")
    body = eqn.params.get("jaxpr")
    out_names = eqn.params.get("out_names")
    if mesh is None or body is None or out_names is None:
        return
    axes = tuple(
        a for a in (str(x) for x in mesh.axis_names) if a in _DATA_AXIS_NAMES
    )
    if not axes:
        return
    acc: dict = {"psum_shapes": [], "rs": False}
    _scan_update_collectives(body, axes, acc)
    if acc["rs"] or len(acc["psum_shapes"]) < 2:
        return
    budget: dict[tuple, int] = {}
    for s in acc["psum_shapes"]:
        budget[s] = budget.get(s, 0) + 1
    jaxpr, _ = _inner_jaxpr(body)
    hits = 0
    for var, names in zip(jaxpr.outvars, out_names):
        shape = tuple(getattr(getattr(var, "aval", None), "shape", ()))
        if not shape or budget.get(shape, 0) <= 0:
            continue
        sharded_over = set()
        for dim_axes in names.values():
            sharded_over.update(str(a) for a in _axis_strs(tuple(dim_axes)))
        if any(a in sharded_over for a in axes):
            continue
        budget[shape] -= 1
        hits += 1
    if hits >= 2:
        f, ln = acc.get("loc") or _src_loc(eqn)
        findings.append(Finding(
            "J108",
            f"replicated optimizer update under shard_map over data axis "
            f"{list(axes)}: {hits} allreduced gradient-shaped tensors "
            f"return replicated with no reduce-scatter — every chip "
            f"applies the FULL weight update (N× optimizer FLOPs and "
            f"state HBM); ZeRO-1 (optim.zero1) shards it",
            file=f, line=ln, entrypoint=entrypoint,
        ))


def _has_isfinite(obj) -> bool:
    """True if ``is_finite`` appears anywhere in the program (J111's
    silence condition — the sentinel's in-graph grad check)."""
    jaxpr, _ = _inner_jaxpr(obj)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "is_finite":
            return True
        for sub, _extra in _sub_jaxprs(eqn):
            if _has_isfinite(sub):
                return True
    return False


def _count_param_update_subs(obj, acc: dict) -> None:
    """Count, per jaxpr level, elementwise ``sub`` equations whose
    minuend is taint-derived from one of THAT level's invars through
    shape-repartitioning ops only — the ``p - update`` signature of an
    optimizer step (params enter every level as invars; activations lose
    the taint at the first dot/conv/reduce)."""
    jaxpr, _ = _inner_jaxpr(obj)
    tainted = set(id(v) for v in jaxpr.invars)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        in_tainted = any(
            id(v) in tainted for v in eqn.invars if hasattr(v, "aval")
        )
        if name in _J111_PRESERVING and in_tainted:
            tainted.update(id(v) for v in eqn.outvars)
        elif name == "sub" and eqn.invars:
            op0 = eqn.invars[0]
            shape = tuple(getattr(getattr(op0, "aval", None), "shape", ()))
            out_shape = tuple(
                getattr(getattr(eqn.outvars[0], "aval", None), "shape", ())
            )
            if (
                id(op0) in tainted
                and shape
                and shape == out_shape
            ):
                acc["count"] += 1
                f, ln = _src_loc(eqn)
                per_file = acc["by_file"].setdefault(f, [0, ln])
                per_file[0] += 1
        for sub, _extra in _sub_jaxprs(eqn):
            _count_param_update_subs(sub, acc)


def _check_unguarded_update(closed, entrypoint: str,
                            findings: list[Finding]) -> None:
    """J111 for one traced program: it writes parameters (≥2 invar-
    derived elementwise subs) yet never evaluates ``is_finite`` — no
    finiteness gate stands between the gradients and the weights."""
    acc: dict = {"count": 0, "by_file": {}}
    _count_param_update_subs(closed, acc)
    if acc["count"] < 2 or _has_isfinite(closed):
        return
    # Anchor at the file contributing the MOST update subs — the
    # optimizer itself, not an incidental tainted sub elsewhere (a loss
    # kernel's shift-by-max on a weight invar) — so one allowlist entry
    # covers every engine sharing that optimizer.
    f, (_, ln) = max(acc["by_file"].items(), key=lambda kv: kv[1][0])
    findings.append(Finding(
        "J111",
        f"optimizer update writes {acc['count']} parameter tensors "
        f"(invar-derived elementwise subs) but the step evaluates no "
        f"is_finite predicate — a single non-finite microbatch reaches "
        f"the weights on every replica at once",
        file=f, line=ln, entrypoint=entrypoint,
    ))


def _check_donated_reuse(jaxpr, entrypoint: str,
                         findings: list[Finding]) -> None:
    """J114: a var donated into a pjit is read again at the same level.

    ``donate_argnums`` tells XLA it may alias the argument's buffer to
    an output; a read after the donating call (a later equation) or a
    second occurrence among the same call's arguments observes clobbered
    memory. A donated invar appearing directly in the enclosing
    program's outvars is NOT flagged: that is jax forwarding an
    unmodified input to an output (common for cache slots a step leaves
    untouched), not a host-level reuse.
    """
    for idx, eqn in enumerate(jaxpr.eqns):
        donated = eqn.params.get("donated_invars")
        if eqn.primitive.name != "pjit" or not donated or not any(donated):
            continue
        callee = str(eqn.params.get("name", "")) or "<anonymous>"
        for pos, (v, don) in enumerate(zip(eqn.invars, donated)):
            if not don or hasattr(v, "val"):
                continue
            reuse = None
            if any(v is w for j, w in enumerate(eqn.invars)
                   if j != pos):
                reuse = f"passed again to the same call '{callee}'"
            else:
                for later in jaxpr.eqns[idx + 1:]:
                    if any(v is w for w in later.invars):
                        reuse = (f"consumed again by a later "
                                 f"'{later.primitive.name}' equation")
                        break
            if reuse:
                f, ln = _src_loc(eqn)
                findings.append(Finding(
                    "J114",
                    f"argument {pos} is donated to jitted call '{callee}' "
                    f"but its buffer is {reuse} — XLA may alias donated "
                    f"memory to an output, so the second read observes "
                    f"overwritten bytes",
                    file=f, line=ln, entrypoint=entrypoint,
                ))


def _walk(obj, bound: frozenset[str], entrypoint: str,
          findings: list[Finding]) -> None:
    jaxpr, consts = _inner_jaxpr(obj)
    _check_consts(consts, entrypoint, findings)
    _check_upcasts(jaxpr, entrypoint, findings)
    _check_ragged_transpose(jaxpr, entrypoint, findings)
    _check_donated_reuse(jaxpr, entrypoint, findings)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            missing = [a for a in _eqn_axes(eqn) if a not in bound]
            if missing:
                f, ln = _src_loc(eqn)
                findings.append(Finding(
                    "J101",
                    f"{name} over axis {missing} but enclosing "
                    f"shard_map/pmap binds {sorted(bound) or 'no axes'}",
                    file=f, line=ln, entrypoint=entrypoint,
                ))
        if name in CALLBACK_PRIMS:
            f, ln = _src_loc(eqn)
            cb = eqn.params.get("callback", None)
            detail = f" ({getattr(cb, '__name__', cb)})" if cb is not None else ""
            findings.append(Finding(
                "J103",
                f"host callback primitive {name}{detail} inside the "
                f"jitted step",
                file=f, line=ln, entrypoint=entrypoint,
            ))
        if name == "cond":
            branches = eqn.params.get("branches", ())
            sigs = [_collective_signature(b) for b in branches]
            if sigs and any(s != sigs[0] for s in sigs[1:]):
                f, ln = _src_loc(eqn)
                desc = "; ".join(
                    f"branch {i}: " + (
                        ", ".join(p for p, _ in s) if s else "<none>")
                    for i, s in enumerate(sigs)
                )
                findings.append(Finding(
                    "J102",
                    f"cond/switch branches issue different collective "
                    f"sequences — {desc}",
                    file=f, line=ln, entrypoint=entrypoint,
                ))
        if name == "pjit" and str(eqn.params.get("name", "")) == SERVE_DECODE_NAME:
            _check_cacheless_decode(eqn, entrypoint, findings)
        if name == "pjit" and str(eqn.params.get("name", "")) in PAGED_DECODE_NAMES:
            _check_full_pool_gather(eqn, entrypoint, findings)
        if name == "pjit" and str(eqn.params.get("name", "")) in _DECODE_TAIL_NAMES:
            _check_unfused_decode_tail(eqn, entrypoint, findings)
        if name == "shard_map":
            seed = _fused_xent_seed(eqn)
            if seed:
                _check_fused_xent(eqn.params["jaxpr"], seed, entrypoint,
                                  findings)
            _check_replicated_update(eqn, entrypoint, findings)
        for sub, extra in _sub_jaxprs(eqn):
            _walk(sub, bound | extra, entrypoint, findings)


def _check_consts(consts, entrypoint: str, findings: list[Finding]) -> None:
    for c in consts:
        nbytes = getattr(c, "nbytes", 0)
        if nbytes and nbytes > LARGE_CONST_BYTES:
            shape = getattr(c, "shape", ())
            dtype = getattr(c, "dtype", "?")
            findings.append(Finding(
                "J105",
                f"{nbytes / (1 << 20):.1f} MiB constant "
                f"({dtype}{list(shape)}) captured by closure — pass it as "
                f"a (donatable) argument instead",
                entrypoint=entrypoint,
            ))


def analyze_closed_jaxpr(
    closed,
    entrypoint: str = "",
    in_specs=None,
    mesh_axes: dict[str, int] | None = None,
    hbm_budget_bytes: int | None = None,
    plan: dict | None = None,
) -> list[Finding]:
    """All jaxpr-level findings (J101-J105, J107-J118) for one traced
    program: the local pattern rules plus the replication-lattice
    dataflow rules. ``in_specs``/``mesh_axes`` seed the interpreter's
    top-level states (engines attach them to their jitted steps);
    ``hbm_budget_bytes`` arms J116; ``plan`` (a plan.json document)
    arms J118 — traced comm/HBM vs the plan's ``predicted`` block."""
    from tpudml.analysis.cost import (
        check_hbm_budget,
        check_plan_drift,
        summarize_cost,
    )
    from tpudml.analysis.dataflow import analyze_dataflow

    findings: list[Finding] = []
    _walk(closed, frozenset(), entrypoint, findings)
    _check_unguarded_update(closed, entrypoint, findings)
    flow = analyze_dataflow(closed, entrypoint, in_specs=in_specs,
                            mesh_axes=mesh_axes)
    findings.extend(flow.findings)
    if hbm_budget_bytes or plan is not None:
        cost = summarize_cost(entrypoint, flow, closed)
        if hbm_budget_bytes:
            findings.extend(check_hbm_budget(cost, hbm_budget_bytes))
        if plan is not None:
            findings.extend(check_plan_drift(cost, plan))
    if plan is not None:
        cand = ((plan.get("winner") or {}).get("candidate") or {})
        if cand.get("tp_overlap") and not _contains_pjit_named(
                closed, (TP_OVERLAP_NAME,)):
            findings.append(Finding(
                "J119",
                f"plan winner {cand.get('key', '?')} claims psum-"
                f"overlapped TP matmuls (tp_overlap) but the traced "
                f"program carries no {TP_OVERLAP_NAME} marker — the wire "
                f"time the plan priced as hidden is actually exposed on "
                f"the critical path",
                entrypoint=entrypoint,
            ))
    return findings


# ------------------------------------------------------------- donation

_MAIN_SIG_RE = re.compile(
    r"func\.func public @main\((.*?)\)\s*->", re.DOTALL)
_ARG_RE = re.compile(r"%arg\d+: tensor<([^>]*)>\s*(\{[^}]*\})?")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1, "c64": 8, "c128": 16,
}


def _tensor_bytes(spec: str) -> int:
    parts = spec.strip().split("x")
    dtype = parts[-1]
    n = 1
    for d in parts[:-1]:
        try:
            n *= int(d)
        except ValueError:  # dynamic dim — treat as 1
            pass
    return n * _DTYPE_BYTES.get(dtype, 4)


def donation_findings(
    lowered_text: str,
    entrypoint: str = "",
    min_bytes: int = LARGE_CONST_BYTES,
) -> list[Finding]:
    """J106 from a lowered StableHLO module: large entry args with no
    donation aliasing anywhere. (Per-arg precision is deliberate-ly NOT
    attempted — batch inputs legitimately go undonated; the hazard is a
    step whose whole TrainState is undonated, i.e. zero aliased args.)"""
    m = _MAIN_SIG_RE.search(lowered_text)
    if not m:
        return []
    donated_bytes = 0
    undonated_large = 0
    undonated_bytes = 0
    for spec, attrs in _ARG_RE.findall(m.group(1)):
        nbytes = _tensor_bytes(spec)
        if attrs and ("tf.aliasing_output" in attrs
                      or "jax.buffer_donor" in attrs):
            donated_bytes += nbytes
        elif nbytes >= min_bytes:
            undonated_large += 1
            undonated_bytes += nbytes
    if donated_bytes == 0 and undonated_large > 0:
        return [Finding(
            "J106",
            f"{undonated_bytes / (1 << 20):.1f} MiB across "
            f"{undonated_large} large input(s) and no argument is donated "
            f"— params/opt-state double-buffer every step",
            entrypoint=entrypoint,
        )]
    return []


# ----------------------------------------------------------- callable API

def analyze_callable(
    fn: Callable,
    args: tuple,
    entrypoint: str = "",
    expects_donation: bool = False,
    in_specs=None,
    mesh_axes: dict[str, int] | None = None,
    hbm_budget_bytes: int | None = None,
    plan: dict | None = None,
) -> list[Finding]:
    """Trace ``fn(*args)`` abstractly and run every jaxpr rule on it.

    Unbound-axis collectives abort the trace itself (JAX raises
    ``NameError`` at bind time), so that failure mode is caught here and
    reported as J101 rather than ever reaching ``_walk``. Other trace
    failures surface as J100 — a step that cannot even abstract-eval
    will not run on the chip either.
    """
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except NameError as e:
        if "unbound axis name" in str(e):
            return [Finding(
                "J101",
                f"trace failed: {e} — collective issued outside any "
                f"shard_map/pmap binding that axis",
                entrypoint=entrypoint,
            )]
        return [Finding("J100", f"trace failed: {e!r}", entrypoint=entrypoint)]
    except Exception as e:  # noqa: BLE001 - converted to a finding
        return [Finding("J100", f"trace failed: {e!r}", entrypoint=entrypoint)]
    findings = analyze_closed_jaxpr(
        closed, entrypoint, in_specs=in_specs, mesh_axes=mesh_axes,
        hbm_budget_bytes=hbm_budget_bytes, plan=plan)
    if expects_donation and hasattr(fn, "lower"):
        try:
            text = fn.lower(*args).as_text()
        except Exception as e:  # noqa: BLE001 - converted to a finding
            findings.append(Finding(
                "J100", f"lowering failed: {e!r}", entrypoint=entrypoint))
        else:
            findings.extend(donation_findings(text, entrypoint))
    return findings
