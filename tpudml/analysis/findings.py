"""Finding model + rule registry for the static-analysis suite.

Every rule has a stable id (J1xx = jaxpr pass, A2xx = AST pass, P3xx =
cross-rank protocol pass — P304 is AST-hosted), a severity, and a
one-line contract. Findings carry file:line provenance —
the jaxpr pass pulls it from equation ``source_info`` (so a hazard inside
a traced step still points at the Python line that built it), the AST
pass from the node. The committed allowlist (``allowlist.toml``) matches
on (rule, path[, line]) and is how triaged true-but-accepted findings
stay visible without failing ``--strict`` CI.
"""

from __future__ import annotations

from dataclasses import dataclass, field


ERROR = "error"
WARN = "warn"
INFO = "info"

_SEV_ORDER = {ERROR: 0, WARN: 1, INFO: 2}

#: rule id -> (severity, one-line description)
RULES: dict[str, tuple[str, str]] = {
    "J100": (ERROR, "entrypoint failed to trace (abstract evaluation error)"),
    "J101": (ERROR, "collective axis name not bound by an enclosing "
                    "shard_map/pmap"),
    "J102": (WARN, "cond/switch branches issue different collective "
                   "sequences (multi-host deadlock hazard)"),
    "J103": (WARN, "host callback primitive inside a jitted step"),
    "J104": (INFO, "bf16 value upcast to f32 outside an accumulation site"),
    "J105": (WARN, "large constant (>1 MiB) captured by closure instead of "
                   "passed as an argument"),
    "J106": (WARN, "large training-state buffers are never donated"),
    "J107": (WARN, "unsharded fused cross-entropy head consumes a "
                   "vocab-sharded kernel (per-shard softmax is wrong)"),
    "J108": (INFO, "replicated (unsharded) optimizer update under shard_map "
                   "on a data axis with no reduce-scatter (every chip pays "
                   "the full update)"),
    "J109": (WARN, "ragged_dot's E-scaled grouped-transpose dW in the "
                   "backward (E× the dense dW FLOPs via masked [E, P, ·] "
                   "broadcasts)"),
    "J110": (WARN, "decode-marked program recomputes full-sequence "
                   "attention per emitted token (O(T²) softmax inside the "
                   "per-token step)"),
    "J111": (INFO, "optimizer update consumes gradients with no finiteness "
                   "predicate anywhere in the step (one NaN microbatch "
                   "poisons the weights unrecoverably)"),
    "J112": (ERROR, "shard_map output declared replicated over an axis the "
                    "body value varies on (missing psum / lost transpose "
                    "factor under check_rep=False)"),
    "J113": (ERROR, "while loop trip count varies per shard while its "
                    "body/cond issue collectives over the same axis "
                    "(collective imbalance: the slice deadlocks)"),
    "J114": (ERROR, "donated buffer consumed again after the donating call "
                    "(XLA may have aliased the memory away)"),
    "J115": (INFO, "allreduce (psum) whose result is consumed only by "
                   "per-shard slices (a psum_scatter moves ~half the "
                   "bytes)"),
    "J116": (WARN, "static peak-live-buffer estimate exceeds the configured "
                   "HBM budget"),
    "J117": (WARN, "paged-decode-marked program attends over the FULL page "
                   "pool per token (softmax keyed on num_pages·page_size "
                   "rows instead of the slot's max_pages table rows)"),
    "J118": (WARN, "traced collectives/HBM deviate >10% from the emitted "
                   "plan's predicted cost (the plan.json no longer "
                   "describes the program that runs)"),
    "J119": (WARN, "decode-marked program materializes the full-vocab "
                   "logits row and argmaxes it outside the head matmul "
                   "(the [B, V] tail round-trips HBM every token), or a "
                   "program claims psum-overlapped TP matmuls without the "
                   "overlap marker"),
    "P300": (ERROR, "p2p frame sent with (edge, mb, tag, rows) that no peer "
                    "schedule receives, or vice versa (boundary schedule "
                    "asymmetry)"),
    "P301": (ERROR, "wait-for cycle across ranks: the composed 1F1B/vote/"
                    "collective schedules cannot all run to completion "
                    "(cross-rank deadlock)"),
    "P302": (ERROR, "ranks of one stage group issue different (op, axis, "
                    "shape) collective sequences (cross-rank J102: gloo "
                    "deadlocks, it does not diagnose)"),
    "P303": (WARN, "schedule reaches a stage-group collective with no "
                   "preceding drain vote (a membership event mid-step parks "
                   "the group in gloo instead of draining)"),
    "P304": (INFO, "port-reservation discipline: bind-and-hold released "
                   "before the wiring is committed, or a listening socket "
                   "leaked on an error path"),
    "A201": (WARN, "Python for/if over a traced (jnp/lax) value"),
    "A202": (WARN, "jax.random key consumed more than once without split"),
    "A203": (WARN, "epoch loop iterates a loader without set_epoch"),
    "A204": (WARN, "host-clock timing without block_until_ready bracket"),
}

HINTS: dict[str, str] = {
    "J100": "run the entrypoint eagerly under JAX_PLATFORMS=cpu to reproduce",
    "J101": "name the axis in the enclosing shard_map mesh / pmap axis_name",
    "J102": "hoist the collective out of the branches (or issue it in both)",
    "J103": "drop jax.debug.* / callbacks from production steps; they "
            "force host sync every step",
    "J104": "cast back to bf16 after the reduction, or wrap the site in an "
            "explicit accumulation (this rule allowlists cleanly)",
    "J105": "pass the array as a (donated) argument so XLA can alias it",
    "J106": "jit the step with donate_argnums on the TrainState",
    "J107": "use sharded_linear_cross_entropy(axis_name=...) so per-shard "
            "(lse, picked) statistics merge before the loss",
    "J108": "shard the weight update: DataParallel(zero1=True) / "
            "optim.ZeRO1 reduce-scatters grads and updates a 1/N shard",
    "J109": "route the ragged FFN through ops.moe_kernel.ragged_ffn "
            "(MoELayer ragged_dw='grouped'): grouped-dW accumulates each "
            "expert's contiguous slab at cost ∝ tokens",
    "J110": "carry a KV cache through the decode loop "
            "(serve.ServingEngine / TransformerLM.apply_decode) so each "
            "step attends [B, H, 1, L] over cached K/V",
    "J111": "wrap the optimizer with resilience.attach_sentinel (engines: "
            "sentinel=True) so non-finite steps are skipped in-graph with "
            "the previous state carried forward bit-exactly",
    "J112": "reduce before returning: psum/all_gather the shard-local "
            "value over the axis (or declare the output sharded in "
            "out_specs if per-shard results are intended)",
    "J113": "derive the loop predicate from a reduced value (psum/pmax of "
            "the local condition) so every shard agrees on the trip count",
    "J114": "thread the updated value out of the donating call instead of "
            "reusing the donated input (donate_argnums aliases its buffer)",
    "J115": "replace psum+dynamic_slice(axis_index) with psum_scatter: "
            "each shard receives exactly the piece it keeps",
    "J116": "shard or rematerialize the largest live buffers, or raise "
            "--hbm_budget if the estimate is for a larger part",
    "J117": "gather K/V through the slot's page table "
            "(serve.paged.read_table: pool[table] → [B, max_pages·P, ...]) "
            "so attention cost scales with per-slot capacity, not pool "
            "size",
    "J118": "re-plan (python -m tpudml.plan) so plan.json matches the "
            "current program, or allowlist the entry with the reason the "
            "drift is intended",
    "J119": "serve with ServeConfig(fused_head=True) so the head matmul, "
            "greedy pick, and step stats run as one vocab-tiled program "
            "(ops.fused_decode_head); for the overlap half, route the "
            "claimed matmul through parallel.overlap.tp_overlap_matmul "
            "(which carries the marker) or drop the claim",
    "P300": "re-derive both sides from the same boundary_plan(spec, b) — "
            "the (step, mb, edge) framing only works when sender and "
            "receiver enumerate the identical transfer list",
    "P301": "keep per-channel sends/recvs in plan-index order and the "
            "vote+collective tail after all p2p (the StageWorker.run_step "
            "order); check warmup_microbatches feeds enough rows downstream",
    "P302": "trace every rank of the group from the same StageProgram — "
            "per-rank model code must keep the collective sequence "
            "identical (hoist divergent collectives out, as for J102)",
    "P303": "vote on the DrainBarrier before entering the GroupReducer "
            "allreduce so a dead peer drains the group at the barrier",
    "P304": "hold port reservations until write_wiring has committed the "
            "topology, and close (or hand off) listening sockets in a "
            "finally block",
    "A201": "use lax.cond/lax.fori_loop/jnp.where, or materialize with "
            "float(...) first if this is host-side code",
    "A202": "key, sub = jax.random.split(key) before the second use",
    "A203": "call loader.set_epoch(epoch) so shuffles differ per epoch",
    "A204": "jax.block_until_ready(...) before reading the second clock",
}


@dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id + provenance + human-readable message."""

    rule: str
    message: str
    file: str = ""
    line: int = 0
    entrypoint: str = ""  # jaxpr pass: which traced step surfaced it

    @property
    def severity(self) -> str:
        return RULES.get(self.rule, (WARN, ""))[0]

    @property
    def hint(self) -> str:
        return HINTS.get(self.rule, "")

    def location(self) -> str:
        if self.file and self.line:
            return f"{self.file}:{self.line}"
        return self.file or (f"<{self.entrypoint}>" if self.entrypoint else "?")

    def format(self) -> str:
        ep = f" [{self.entrypoint}]" if self.entrypoint else ""
        out = (f"{self.rule} {self.severity:5s} {self.location()}{ep}: "
               f"{self.message}")
        if self.hint:
            out += f"\n      hint: {self.hint}"
        return out


def sort_findings(findings: list[Finding]) -> list[Finding]:
    return sorted(
        findings,
        key=lambda f: (_SEV_ORDER.get(f.severity, 9), f.rule, f.file, f.line),
    )
