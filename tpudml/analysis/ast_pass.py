"""Pass 2 — AST lint for source-level hazards the tracer cannot see.

The jaxpr pass only sees what survives tracing; these four hazard
classes disappear (or worse, silently bake in) before a jaxpr exists:

- A201  Python ``for``/``if`` over a traced value: under jit this either
        raises a ConcretizationTypeError at runtime or — for ``for`` over
        a concrete-shaped array — silently unrolls the loop into the
        program;
- A202  a PRNG key consumed by two sampler calls without an intervening
        ``split``/reassignment: both draws are identical;
- A203  an epoch loop that re-iterates a sharded loader without calling
        ``set_epoch``: every epoch replays epoch-0's shuffle order;
- A204  host-clock deltas (``time.time``/``perf_counter``) around device
        work with no ``block_until_ready`` in the function: the clock
        measures dispatch, not execution;
- P304  port-reservation discipline (the protocol pass's one
        source-level rule): a bind-and-hold reservation closed *before*
        the round's wiring document is written (a squatter can take the
        port in the window), or a locally-created listening socket that
        neither escapes the scope nor reaches ``close()`` — leaked on
        any error path.

All checks are deliberately name-based heuristics scoped to one function
at a time (module top-level counts as a function for scripts in
``tools/``). They are tuned for this repo's idiom — low false-positive
rate beats completeness, and anything accepted lands in the allowlist.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from tpudml.analysis.findings import Finding

#: jax.random samplers that consume (fold in) their key argument.
_SAMPLERS = frozenset({
    "normal", "uniform", "bernoulli", "randint", "permutation", "choice",
    "categorical", "gumbel", "truncated_normal", "bits", "exponential",
    "laplace", "beta", "gamma", "dirichlet", "poisson", "shuffle",
})
#: jax.random functions that derive fresh keys (uses are fine).
_KEY_DERIVERS = frozenset({"split", "fold_in", "clone", "key_data", "wrap_key_data"})
_KEY_MAKERS = frozenset({"PRNGKey", "key"})

_CLOCKS = frozenset({"time", "perf_counter", "monotonic", "process_time"})


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('' when not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_traced_call(node: ast.AST) -> bool:
    """Call whose result is a traced array: jnp.*/lax.*/jax.numpy.* etc."""
    if not isinstance(node, ast.Call):
        return False
    chain = _attr_chain(node.func)
    head = chain.split(".")[0] if chain else ""
    return head in ("jnp", "lax") or chain.startswith(("jax.numpy", "jax.lax"))


def _mentions_jax(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in ("jax", "jnp", "lax"):
            return True
    return False


class _FunctionLinter:
    """Runs every rule over one function-like scope."""

    def __init__(self, path: str, scope_body: list[ast.stmt]):
        self.path = path
        self.body = scope_body
        self.findings: list[Finding] = []

    def _emit(self, rule: str, message: str, node: ast.AST) -> None:
        self.findings.append(Finding(
            rule, message, file=self.path, line=getattr(node, "lineno", 0)))

    # -- A201 ---------------------------------------------------------
    def check_traced_control_flow(self) -> None:
        traced: set[str] = set()
        for node in self._ordered_nodes():
            if isinstance(node, ast.Assign) and _is_traced_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        traced.add(tgt.id)
            elif isinstance(node, ast.Assign):
                # any other reassignment launders the name (float(x), .item())
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        traced.discard(tgt.id)
            elif isinstance(node, (ast.If, ast.While)):
                expr = node.test
                if self._expr_is_traced(expr, traced):
                    self._emit(
                        "A201",
                        "branch condition is a traced value — under jit "
                        "this raises ConcretizationTypeError",
                        node)
            elif isinstance(node, ast.For):
                if self._expr_is_traced(node.iter, traced):
                    self._emit(
                        "A201",
                        "Python for-loop over a traced value — the loop "
                        "unrolls into the program (or fails to trace)",
                        node)

    def _expr_is_traced(self, expr: ast.AST, traced: set[str]) -> bool:
        if _is_traced_call(expr):
            return True
        if isinstance(expr, ast.Name) and expr.id in traced:
            return True
        if isinstance(expr, ast.Compare):
            return any(self._expr_is_traced(e, traced)
                       for e in [expr.left, *expr.comparators])
        if isinstance(expr, ast.BoolOp):
            return any(self._expr_is_traced(e, traced) for e in expr.values)
        if isinstance(expr, ast.UnaryOp):
            return self._expr_is_traced(expr.operand, traced)
        return False

    # -- A202 ---------------------------------------------------------
    def check_key_reuse(self) -> None:
        consumed: dict[str, int] = {}  # key name -> line of first consume
        for node in self._ordered_nodes():
            if isinstance(node, ast.Assign):
                for tgt in self._assign_names(node):
                    consumed.pop(tgt, None)  # reassignment refreshes the key
                continue
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            leaf = chain.rsplit(".", 1)[-1] if chain else ""
            if "random" not in chain or leaf not in _SAMPLERS:
                continue
            if leaf in _KEY_DERIVERS or leaf in _KEY_MAKERS:
                continue
            for arg in node.args[:1]:  # key is positionally first
                if isinstance(arg, ast.Name):
                    if arg.id in consumed:
                        self._emit(
                            "A202",
                            f"key '{arg.id}' already consumed by a sampler "
                            f"at line {consumed[arg.id]} — both draws are "
                            f"identical; split first",
                            node)
                    else:
                        consumed[arg.id] = node.lineno

    @staticmethod
    def _assign_names(node: ast.Assign) -> list[str]:
        names: list[str] = []
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                names.extend(e.id for e in tgt.elts if isinstance(e, ast.Name))
        return names

    # -- A203 ---------------------------------------------------------
    def check_set_epoch(self) -> None:
        for node in self._ordered_nodes():
            if not isinstance(node, ast.For):
                continue
            tgt = node.target
            is_epoch_loop = (isinstance(tgt, ast.Name)
                             and "epoch" in tgt.id.lower())
            if not is_epoch_loop:
                continue
            iterates_loader = False
            calls_set_epoch = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.For) and sub is not node:
                    name = ""
                    if isinstance(sub.iter, ast.Name):
                        name = sub.iter.id
                    elif isinstance(sub.iter, ast.Call):
                        name = _attr_chain(sub.iter.func)
                    if "loader" in name.lower() or "dataloader" in name.lower():
                        iterates_loader = True
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "set_epoch"):
                    calls_set_epoch = True
            if iterates_loader and not calls_set_epoch:
                self._emit(
                    "A203",
                    "epoch loop iterates a loader without set_epoch(epoch) "
                    "— every epoch replays the same shuffle order",
                    node)

    # -- A204 ---------------------------------------------------------
    def check_timing(self) -> None:
        clock_calls: list[ast.Call] = []
        has_block = False
        for node in self._ordered_nodes():
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            leaf = chain.rsplit(".", 1)[-1] if chain else ""
            if leaf in _CLOCKS and chain.split(".")[0] in ("time", leaf):
                clock_calls.append(node)
            if "block_until_ready" in chain or leaf == "block_until_ready":
                has_block = True
        if len(clock_calls) >= 2 and not has_block:
            self._emit(
                "A204",
                "host-clock delta with no block_until_ready in scope — "
                "async dispatch means this times the Python overhead, not "
                "the device work",
                clock_calls[1])

    # -- P304 ---------------------------------------------------------
    def check_port_discipline(self) -> None:
        """Two reservation-discipline hazards in one scope.

        (a) a name assigned from a ``*.socket(...)`` call that has
        ``.listen()`` called on it but never ``.close()``, and never
        *escapes* (passed to a call, returned/yielded, aliased, or
        stored into a container/attribute) — leaked on any error path;
        (b) ``close()`` on a hold/reservation-named socket (directly or
        through a for-loop over a matching name) at a line *before* the
        scope's ``write_wiring``-style call — the bind-and-hold defense
        is void for the window between release and commit.
        """
        created: set[str] = set()
        listening: set[str] = set()
        closed: set[str] = set()
        escaped: set[str] = set()
        listen_nodes: dict[str, ast.AST] = {}
        aliases: dict[str, str] = {}  # loop var -> iterated name
        hold_close: ast.AST | None = None
        wiring_line: int | None = None

        def names_in(expr: ast.AST) -> Iterable[str]:
            for n in ast.walk(expr):
                if isinstance(n, ast.Name):
                    yield n.id

        for node in self._ordered_nodes():
            if (isinstance(node, ast.For)
                    and isinstance(node.target, ast.Name)
                    and isinstance(node.iter, ast.Name)):
                aliases[node.target.id] = node.iter.id
            if isinstance(node, ast.Assign):
                plain = all(isinstance(t, ast.Name) for t in node.targets)
                if (plain and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    leaf = _attr_chain(node.value.func).rsplit(".", 1)[-1]
                    if leaf == "socket":
                        created.add(node.targets[0].id)
                        continue
                if not plain and not isinstance(node.value, ast.Call):
                    # stored into an attribute/subscript/container:
                    # escapes. (A Call value's receiver is NOT an escape
                    # — its arguments are collected at the Call visit.)
                    escaped.update(names_in(node.value))
                elif plain and isinstance(node.value, ast.Name):
                    escaped.add(node.value.id)  # aliased away
            elif isinstance(node, (ast.Return, ast.Yield)):
                if node.value is not None:
                    escaped.update(names_in(node.value))
            elif isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                leaf = chain.rsplit(".", 1)[-1] if chain else ""
                recv = chain.rsplit(".", 1)[0] if "." in chain else ""
                if leaf == "listen" and recv:
                    listening.add(recv)
                    listen_nodes.setdefault(recv, node)
                elif leaf == "close" and recv:
                    closed.add(recv)
                    base = aliases.get(recv, recv)
                    if hold_close is None and re.search(
                            r"hold|reserv", base, re.IGNORECASE):
                        hold_close = node
                if "wiring" in leaf.lower() and wiring_line is None:
                    wiring_line = node.lineno
                for arg in (*node.args,
                            *(kw.value for kw in node.keywords)):
                    escaped.update(names_in(arg))

        for name in sorted((created & listening) - closed - escaped):
            self._emit(
                "P304",
                f"listener socket '{name}' is bound and listening but "
                f"never reaches close() and never escapes this scope — "
                f"leaked on any error path (close in a finally, or hand "
                f"it off)",
                listen_nodes[name])
        if (hold_close is not None and wiring_line is not None
                and hold_close.lineno < wiring_line):
            self._emit(
                "P304",
                "bind-and-hold port reservation released before the "
                "round's wiring is committed — a squatter can take the "
                "port between release and spawn; keep the hold until "
                "write_wiring returns",
                hold_close)

    # ------------------------------------------------------------------
    def _ordered_nodes(self) -> Iterable[ast.AST]:
        """Every node in this scope in source order, NOT descending into
        nested function/class definitions (they get their own linter)."""
        out: list[ast.AST] = []

        def visit(n: ast.AST) -> None:
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                out.append(child)
                visit(child)

        for stmt in self.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            out.append(stmt)
            visit(stmt)
        out.sort(key=lambda n: (getattr(n, "lineno", 0),
                                getattr(n, "col_offset", 0)))
        return out

    def run(self, jax_in_scope: bool) -> list[Finding]:
        self.check_traced_control_flow()
        self.check_key_reuse()
        self.check_set_epoch()
        self.check_port_discipline()
        if jax_in_scope:
            self.check_timing()
        return self.findings


def _scopes(tree: ast.Module):
    """Yield (body, node_for_jax_check) for the module and each def."""
    yield tree.body, tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body, node


def analyze_source(source: str, path: str) -> list[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("J100", f"file failed to parse: {e}", file=path,
                        line=e.lineno or 0)]
    findings: list[Finding] = []
    for body, scope_node in _scopes(tree):
        linter = _FunctionLinter(path, body)
        findings.extend(linter.run(jax_in_scope=_mentions_jax(scope_node)))
    # Module-level A204 double counts nothing: nested defs are skipped by
    # _ordered_nodes, so each clock call belongs to exactly one scope.
    return findings


def analyze_file(path: str) -> list[Finding]:
    rel = os.path.relpath(path, os.getcwd())
    rel = path if rel.startswith("..") else rel
    with open(path, "r", encoding="utf-8") as f:
        return analyze_source(f.read(), rel)


def iter_python_files(roots: list[str]) -> list[str]:
    files: list[str] = []
    for root in roots:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            files.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    return sorted(files)


def analyze_tree(roots: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_python_files(roots):
        findings.extend(analyze_file(path))
    return findings
