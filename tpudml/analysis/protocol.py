"""Pass 4 — cross-rank protocol analysis of the MPMD pipeline (P3xx).

Every other pass in this suite reasons about ONE program at a time; the
MPMD runtime's hardest invariants live *between* programs: S gloo stage
groups, ``(step, microbatch, edge)``-framed p2p transfers, ctl-star
drain votes, and heterogeneous 1F1B host loops that must compose into a
deadlock-free schedule. This module makes that composition a static
object. :func:`build_schedules` constructs, per (stage, rank), the
ordered list of *blocking events* the runtime will execute — exactly
mirroring ``StageWorker.run_step``:

1. ``warmup_microbatches`` forwards (each: recv acts in plan order,
   then send acts in plan order),
2. strict 1F1B forward/backward alternation, then the backward tail
   (each backward: the head sends cotangents up; interior stages recv
   cotangents from below, then send their own up),
3. the group drain vote (:class:`~tpudml.comm.p2p.DrainBarrier`) when
   ``dp > 1``,
4. the stage-group gradient collective(s). GSPMD inserts the
   :class:`~tpudml.mpmd.runtime.GroupReducer` allreduce at compile
   time, so the default model uses one symbolic
   ``("allreduce_sum", "data")`` event; pass ``stage_collectives``
   (e.g. from :func:`traced_collective_events`, which reuses the jaxpr
   pass) to check the stage's REAL traced collective sequence instead.

:func:`check_schedules` then verifies the composed system:

- **P300** (error) — frame multiset asymmetry: a ``(edge, mb, tag,
  rows)`` frame sent that no peer schedule receives, or received but
  never sent, or issued by a rank that is not the edge's endpoint.
- **P301** (error) — wait-for cycle: an exhaustive may-progress
  simulation (sends are buffered and non-blocking, recvs block on
  their channel, votes and collectives are stage-group barriers)
  either runs every schedule to completion or names the ranks left
  blocked — e.g. both edge endpoints parked in ``recv``, or a rank
  entering the gloo allreduce while a group peer still waits in a p2p
  recv. Per-channel frame-order mismatches (the runtime's
  ``FramingError``) are reported from the same simulation.
- **P302** (error) — ranks of one stage group issuing different
  ``(op, axis, shape)`` collective sequences: the cross-rank
  generalization of J102 (gloo deadlocks, it does not diagnose).
- **P303** (warn) — a schedule reaching a stage-group collective with
  no preceding drain vote: a membership event during the step would
  park the group in gloo instead of draining at the barrier.

(P304, the port-discipline lint, is source-level and lives in the AST
pass — see ``ast_pass.check_port_discipline``.)

Findings carry ``entrypoint="protocol:<name>"`` and no file, so the
allowlist's ``<protocol:...>`` pseudo-paths apply — same policy as the
jaxpr entrypoints. The whole pass is jax-free and runs in milliseconds,
which is why ``MPMDController`` can afford to run it as a pre-launch
gate on every (re-)meshed ``PipelineSpec``.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from pathlib import Path

from tpudml.analysis.findings import Finding, sort_findings
from tpudml.mpmd.spec import (
    PipelineSpec,
    StageQuorumError,
    StageSpec,
    boundary_plan,
    replace_pipeline,
    warmup_microbatches,
)

__all__ = [
    "Ev",
    "build_schedules",
    "check_schedules",
    "analyze_pipeline",
    "protocol_surface",
    "analyze_protocol_surface",
    "validate_fixture_events",
    "traced_collective_events",
]

#: Committed meshless fixtures double as protocol-surface specs.
FIXTURE_DIR = Path(__file__).resolve().parents[2] / "tests" / "mpmd_fixtures"

_EDGE_RE = re.compile(r"^s(\d+)r(\d+)->s(\d+)r(\d+)$")


@dataclass(frozen=True)
class Ev:
    """One blocking event in a rank's schedule.

    ``kind`` is ``send``/``recv`` (p2p frames: ``edge`` + the frame's
    ``mb`` = the boundary transfer's plan index, ``tag`` = ``act`` or
    ``grad``, ``rows`` = the global row interval — the payload size),
    ``vote`` (drain barrier), or ``collective`` (stage-group gloo op:
    ``op``/``axis``/``shape``).
    """

    kind: str
    edge: str = ""
    mb: int = -1
    tag: str = ""
    rows: tuple = ()
    op: str = ""
    axis: str = ""
    shape: tuple = ()

    def describe(self) -> str:
        if self.kind in ("send", "recv"):
            return (f"{self.kind}(edge={self.edge}, mb={self.mb}, "
                    f"tag={self.tag})")
        if self.kind == "vote":
            return "vote(drain barrier)"
        return f"collective({self.op}, axis={self.axis})"


def _edge_endpoints(edge: str):
    """``(src (stage, rank), dst (stage, rank))`` or None."""
    m = _EDGE_RE.match(edge)
    if not m:
        return None
    a, b, c, d = map(int, m.groups())
    return (a, b), (c, d)


# ------------------------------------------------------- schedule model


def build_schedules(spec: PipelineSpec, *, stage_collectives=None) -> dict:
    """``(stage, rank) -> [Ev, ...]`` for every rank of the pipeline.

    ``stage_collectives`` optionally maps ``stage`` (or ``(stage,
    rank)``, which wins) to an iterable of ``(op, axis, shape)`` tuples
    — the stage's traced collective sequence from
    :func:`traced_collective_events`. Without it, dp>1 stages get the
    single symbolic allreduce the GroupReducer compiles to.
    """
    n = len(spec.stages)
    plans = [boundary_plan(spec, b) for b in range(n - 1)]
    out: dict = {}
    for s, st in enumerate(spec.stages):
        for r in range(st.dp):
            in_plan: dict = {}
            if s > 0:
                for t in plans[s - 1]:
                    if t.dst_rank == r:
                        in_plan.setdefault(t.dst_microbatch, []).append(t)
            out_plan: dict = {}
            if s < n - 1:
                for t in plans[s]:
                    if t.src_rank == r:
                        out_plan.setdefault(t.src_microbatch, []).append(t)
            for lst in (*in_plan.values(), *out_plan.values()):
                lst.sort(key=lambda t: t.index)

            evs: list = []

            def forward(mb, evs=evs, s=s, in_plan=in_plan, out_plan=out_plan):
                for t in in_plan.get(mb, []):
                    evs.append(Ev("recv", edge=t.edge, mb=t.index,
                                  tag="act", rows=t.rows))
                if s < n - 1:
                    for t in out_plan.get(mb, []):
                        evs.append(Ev("send", edge=t.edge, mb=t.index,
                                      tag="act", rows=t.rows))

            def backward(mb, evs=evs, s=s, in_plan=in_plan,
                         out_plan=out_plan):
                if s == n - 1:
                    for t in in_plan.get(mb, []):
                        evs.append(Ev("send", edge=t.edge, mb=t.index,
                                      tag="grad", rows=t.rows))
                else:
                    for t in out_plan.get(mb, []):
                        evs.append(Ev("recv", edge=t.edge, mb=t.index,
                                      tag="grad", rows=t.rows))
                    if s > 0:
                        for t in in_plan.get(mb, []):
                            evs.append(Ev("send", edge=t.edge, mb=t.index,
                                          tag="grad", rows=t.rows))

            w, m = warmup_microbatches(spec, s), st.microbatches
            for k in range(w):
                forward(k)
            for i in range(m - w):
                forward(w + i)
                backward(i)
            for i in range(m - w, m):
                backward(i)

            if st.dp > 1:
                evs.append(Ev("vote", edge=f"ctl:s{s}", mb=r, tag="ctl"))
                colls = None
                if stage_collectives is not None:
                    colls = stage_collectives.get(
                        (s, r), stage_collectives.get(s))
                if colls is None:
                    colls = (("allreduce_sum", "data", ()),)
                for op, axis, shape in colls:
                    if isinstance(axis, (tuple, list)):
                        axis = ",".join(str(a) for a in axis)
                    evs.append(Ev("collective", op=str(op), axis=str(axis),
                                  shape=tuple(shape)))
            out[(s, r)] = evs
    return out


def traced_collective_events(fn, args) -> tuple:
    """Trace ``fn(*args)`` and return its ordered ``(op, axis, shape)``
    collective sequence via the jaxpr pass — ready to feed a stage's
    entry in ``build_schedules(stage_collectives=...)`` so P302 compares
    the group's *real* programs instead of the symbolic reducer. Needs
    jax (the only function in this module that does)."""
    import jax

    from tpudml.analysis.jaxpr_pass import collective_shape_signature

    closed = jax.make_jaxpr(fn)(*args)
    return collective_shape_signature(closed)


# ---------------------------------------------------------- the checks


def _frame_key(e: Ev) -> tuple:
    return (e.edge, e.mb, e.tag, tuple(e.rows))


def _check_frames(schedules: dict, entrypoint: str,
                  findings: list) -> None:
    """P300: every sent frame has exactly one receiver and vice versa,
    and p2p events are issued only by their edge's endpoints."""
    sends: dict = {}
    recvs: dict = {}
    for key in sorted(schedules):
        for e in schedules[key]:
            if e.kind not in ("send", "recv"):
                continue
            ends = _edge_endpoints(e.edge)
            if ends is not None:
                src, dst = ends
                sender, receiver = (src, dst) if e.tag == "act" else (dst, src)
                expected = sender if e.kind == "send" else receiver
                if key != expected:
                    findings.append(Finding(
                        "P300",
                        f"stage {key[0]} rank {key[1]} schedules "
                        f"{e.describe()} but is not the edge's "
                        f"{'sending' if e.kind == 'send' else 'receiving'} "
                        f"endpoint for tag={e.tag}",
                        entrypoint=entrypoint,
                    ))
                    continue
            bucket = sends if e.kind == "send" else recvs
            k = _frame_key(e)
            bucket[k] = bucket.get(k, 0) + 1
    for k in sorted(set(sends) | set(recvs), key=repr):
        ns, nr = sends.get(k, 0), recvs.get(k, 0)
        if ns != nr:
            edge, mb, tag, rows = k
            findings.append(Finding(
                "P300",
                f"frame (edge={edge}, mb={mb}, tag={tag}, rows={rows}) "
                f"sent {ns}x but received {nr}x — boundary schedule "
                f"asymmetry",
                entrypoint=entrypoint,
            ))


def _check_collective_agreement(spec: PipelineSpec, schedules: dict,
                                entrypoint: str, findings: list) -> None:
    """P302: every rank of a dp>1 stage group must issue the identical
    ordered (op, axis, shape) collective sequence."""
    def fmt(seq):
        return "[" + ", ".join(
            f"{op}@{axis}{list(shape)}" for op, axis, shape in seq) + "]"

    for s, st in enumerate(spec.stages):
        if st.dp < 2:
            continue
        seqs = {
            r: tuple((e.op, e.axis, e.shape)
                     for e in schedules.get((s, r), ())
                     if e.kind == "collective")
            for r in range(st.dp)
        }
        base = seqs[0]
        bad = sorted(r for r, q in seqs.items() if q != base)
        if bad:
            findings.append(Finding(
                "P302",
                f"stage {s} ({st.name}): rank(s) {bad} issue a different "
                f"(op, axis, shape) collective sequence than rank 0 — "
                f"rank 0: {fmt(base)} vs rank {bad[0]}: "
                f"{fmt(seqs[bad[0]])}; gloo will deadlock or corrupt, "
                f"not diagnose",
                entrypoint=entrypoint,
            ))


def _check_drain_votes(schedules: dict, entrypoint: str,
                       findings: list) -> None:
    """P303: the first stage-group collective on every rank must be
    preceded by a drain vote, else a membership event mid-step parks
    the group in gloo instead of draining."""
    for key in sorted(schedules):
        voted = False
        for e in schedules[key]:
            if e.kind == "vote":
                voted = True
            elif e.kind == "collective" and not voted:
                findings.append(Finding(
                    "P303",
                    f"stage {key[0]} rank {key[1]} reaches stage-group "
                    f"collective '{e.op}' with no preceding drain vote — "
                    f"a peer death mid-step would hang the allreduce "
                    f"instead of draining at the barrier",
                    entrypoint=entrypoint,
                ))
                break


def _simulate(schedules: dict, entrypoint: str) -> list:
    """P301: may-progress simulation of the composed schedules.

    Sends are buffered (the wire has a socket buffer; the runtime never
    blocks on send for drill-sized payloads), recvs block on their
    per-(edge, sender) FIFO and must match the channel head's
    ``(mb, tag)`` frame exactly (else the runtime raises FramingError),
    votes and collectives are stage-group barriers. Anything left
    unfinished when no rank can advance is a wait-for cycle.
    """
    keys = sorted(schedules)
    pc = {k: 0 for k in keys}
    queues: dict = {}
    groups: dict = {}
    for k in keys:
        groups.setdefault(k[0], []).append(k)

    def current(k):
        evs = schedules[k]
        return evs[pc[k]] if pc[k] < len(evs) else None

    progressed = True
    while progressed:
        progressed = False
        for k in keys:
            e = current(k)
            if e is None:
                continue
            if e.kind == "send":
                queues.setdefault((e.edge, k), []).append((e.mb, e.tag))
                pc[k] += 1
                progressed = True
            elif e.kind == "recv":
                ends = _edge_endpoints(e.edge)
                peer = None
                if ends is not None:
                    src, dst = ends
                    peer = src if k == dst else dst if k == src else None
                q = queues.get((e.edge, peer)) if peer is not None else None
                if not q:
                    continue
                if q[0] != (e.mb, e.tag):
                    return [Finding(
                        "P301",
                        f"stage {k[0]} rank {k[1]}: frames cross edge "
                        f"{e.edge} out of order — schedule expects "
                        f"(mb={e.mb}, tag={e.tag}) but the channel head "
                        f"is (mb={q[0][0]}, tag={q[0][1]}); at runtime "
                        f"this is a FramingError mid-step",
                        entrypoint=entrypoint,
                    )]
                q.pop(0)
                pc[k] += 1
                progressed = True
            else:  # vote / collective: stage-group barrier
                members = groups[k[0]]
                if all((c := current(m)) is not None and c.kind == e.kind
                       for m in members):
                    for m in members:
                        pc[m] += 1
                    progressed = True
    blocked = [k for k in keys if current(k) is not None]
    if not blocked:
        return []
    desc = "; ".join(
        f"stage {k[0]} rank {k[1]} blocked in {current(k).describe()}"
        for k in blocked
    )
    return [Finding(
        "P301",
        f"wait-for cycle across ranks — no schedule can advance: {desc}",
        entrypoint=entrypoint,
    )]


def check_schedules(spec: PipelineSpec, schedules: dict, *,
                    entrypoint: str = "pipeline") -> list:
    """Run P300–P303 over a schedule model (tamper-friendly: the fixture
    twins hand-mutate ``build_schedules`` output and call this)."""
    findings: list = []
    _check_frames(schedules, entrypoint, findings)
    _check_collective_agreement(spec, schedules, entrypoint, findings)
    _check_drain_votes(schedules, entrypoint, findings)
    findings.extend(_simulate(schedules, entrypoint))
    return sort_findings(findings)


def analyze_pipeline(spec: PipelineSpec, *, entrypoint: str = "pipeline",
                     stage_collectives=None) -> list:
    """Model + check one ``PipelineSpec`` — the MPMDController's
    pre-launch gate calls exactly this."""
    schedules = build_schedules(spec, stage_collectives=stage_collectives)
    return check_schedules(spec, schedules, entrypoint=entrypoint)


# ------------------------------------------------------ repo surface


def protocol_surface() -> dict:
    """``name -> PipelineSpec`` for every spec the repo actually runs:
    the e2e drill's [2,2] pipeline, a 3-stage [2,2,2] heterogeneous
    spec (the property tests' second subject), and the committed
    meshless fixtures — initial AND every post-kill shrink, so the gate
    and the goldens can never silently diverge."""
    from tpudml.mpmd.drill import _drill_pipeline

    out = {"mpmd_drill": _drill_pipeline()}
    out["mpmd_3stage"] = PipelineSpec(
        stages=(
            StageSpec("s0", dp=2, microbatches=2, dtype="bfloat16"),
            StageSpec("s1", dp=2, microbatches=2, dtype="bfloat16"),
            StageSpec("s2", dp=2, microbatches=1, dtype="float32"),
        ),
        global_batch=8,
    )
    if FIXTURE_DIR.is_dir():
        for p in sorted(FIXTURE_DIR.glob("*.json")):
            doc = json.loads(p.read_text())
            pipeline = PipelineSpec.from_dict(doc["pipeline"])
            out[f"fixture:{p.stem}"] = pipeline
            for ev in doc.get("events", ()):
                if ev.get("type") != "kill":
                    continue
                try:
                    pipeline, _ = replace_pipeline(
                        pipeline, {int(ev["slot"])})
                except (StageQuorumError, ValueError):
                    break
                out[f"fixture:{p.stem}:after_kill{ev['slot']}"] = pipeline
    return out


def analyze_protocol_surface() -> list:
    """P300–P303 over :func:`protocol_surface` — the ``--protocol`` CLI
    body, also folded into the default full run / ``--strict``."""
    findings: list = []
    for name, spec in sorted(protocol_surface().items()):
        findings.extend(
            analyze_pipeline(spec, entrypoint=f"protocol:{name}"))
    return sort_findings(findings)


# ----------------------------------------------- fixture cross-check


def validate_fixture_events(fixture, *, lines=None) -> list:
    """Check a meshless fixture's replayed transfer stream against the
    schedule model: every ``transfer`` line must be a modeled act frame
    of the pipeline incarnation it ran under (same edge, same plan
    index, same byte count), and every step must replay the boundary
    frame set exactly. Mismatches are P300 findings — this is what pins
    fixture goldens and checker to one another.

    ``fixture`` is a path or parsed dict; ``lines`` overrides the
    replayed event lines (the tamper tests inject mutated streams).
    """
    if not isinstance(fixture, dict):
        fixture = json.loads(Path(fixture).read_text())
    name = fixture.get("name", "fixture")
    entrypoint = f"protocol:{name}"
    if lines is None:
        from tpudml.mpmd.fixture import replay_fixture

        lines = replay_fixture(dict(fixture))["lines"]
    bytes_per_row = int(fixture.get("bytes_per_row", 64))

    def act_frames(pipeline: PipelineSpec) -> dict:
        frames: dict = {}
        for evs in build_schedules(pipeline).values():
            for e in evs:
                if e.kind == "send" and e.tag == "act":
                    frames[(e.edge, e.mb)] = (
                        (e.rows[1] - e.rows[0]) * bytes_per_row)
        return frames

    findings: list = []
    pipeline = PipelineSpec.from_dict(fixture["pipeline"])
    frames = act_frames(pipeline)
    pending = None  # pipeline awaiting its post-kill "form"
    seen_by_step: dict = {}

    def flush_steps():
        for step in sorted(seen_by_step):
            seen = seen_by_step[step]
            missing = sorted(set(frames) - set(seen), key=repr)
            if missing:
                findings.append(Finding(
                    "P300",
                    f"step {step}: replay omitted modeled frame(s) "
                    f"{missing} — fixture stream and schedule model "
                    f"disagree",
                    entrypoint=entrypoint,
                ))
        seen_by_step.clear()

    for line in lines:
        ev = json.loads(line)
        kind = ev.get("event")
        if kind == "kill":
            try:
                pending, _ = replace_pipeline(pipeline, {int(ev["slot"])})
            except (StageQuorumError, ValueError):
                pending = None
        elif kind == "form":
            flush_steps()
            if pending is not None:
                pipeline = pending
                frames = act_frames(pipeline)
                pending = None
        elif kind == "transfer":
            key = (ev["edge"], ev["index"])
            step = ev.get("step")
            if key not in frames:
                findings.append(Finding(
                    "P300",
                    f"replayed transfer step={step} edge={ev['edge']} "
                    f"index={ev['index']} matches no modeled act frame "
                    f"of the current pipeline",
                    entrypoint=entrypoint,
                ))
                continue
            if int(ev.get("bytes", -1)) != frames[key]:
                findings.append(Finding(
                    "P300",
                    f"replayed transfer step={step} edge={ev['edge']} "
                    f"index={ev['index']} carries {ev.get('bytes')} bytes "
                    f"but the modeled frame is {frames[key]} bytes",
                    entrypoint=entrypoint,
                ))
                continue
            seen_by_step.setdefault(step, set()).add(key)
    flush_steps()
    return sort_findings(findings)
