"""Committed allowlist: triaged true-but-accepted findings.

``analysis/allowlist.toml`` (repo root) holds ``[[allow]]`` tables:

    [[allow]]
    rule = "J104"                  # required
    path = "tpudml/nn/layers.py"   # fnmatch glob against the finding's
                                   # file (or "<entrypoint>" pseudo-path)
    reason = "LN stats accumulate in f32 by design"   # required
    # line = 123                   # optional: pin to an exact line

Matching is on (rule, path[, line]) — not message text, which changes
with shapes. An entry with no ``path`` matches the rule everywhere; use
that sparingly. ``--strict`` fails on any finding NOT matched here, so
the workflow is: run the analyzer, fix what is fixable, and commit an
entry with a one-line ``reason`` for what is accepted. The reason field
is mandatory precisely so the allowlist stays reviewable.
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass

from tpudml.analysis.findings import Finding

DEFAULT_PATH = os.path.join("analysis", "allowlist.toml")


@dataclass(frozen=True)
class AllowEntry:
    rule: str
    path: str = "*"
    line: int = 0  # 0 = any line
    reason: str = ""

    def matches(self, f: Finding) -> bool:
        if f.rule != self.rule:
            return False
        loc = f.file or (f"<{f.entrypoint}>" if f.entrypoint else "")
        if not fnmatch.fnmatch(loc, self.path):
            return False
        return self.line == 0 or self.line == f.line


def _load_toml(path: str) -> dict:
    try:
        import tomllib  # py311+
    except ModuleNotFoundError:
        import tomli as tomllib  # py310: vendored with the toolchain
    with open(path, "rb") as fh:
        return tomllib.load(fh)


def load_allowlist(path: str | None = None) -> list[AllowEntry]:
    path = path or DEFAULT_PATH
    if not os.path.exists(path):
        return []
    data = _load_toml(path)
    entries: list[AllowEntry] = []
    for i, raw in enumerate(data.get("allow", [])):
        if "rule" not in raw or "reason" not in raw:
            raise ValueError(
                f"{path}: [[allow]] entry #{i + 1} needs 'rule' and "
                f"'reason' keys (got {sorted(raw)})")
        entries.append(AllowEntry(
            rule=str(raw["rule"]),
            path=str(raw.get("path", "*")),
            line=int(raw.get("line", 0)),
            reason=str(raw["reason"]),
        ))
    return entries


def split_allowed(
    findings: list[Finding], entries: list[AllowEntry],
) -> tuple[list[Finding], list[Finding]]:
    """(active, allowed) partition of findings against the allowlist."""
    active: list[Finding] = []
    allowed: list[Finding] = []
    for f in findings:
        (allowed if any(e.matches(f) for e in entries) else active).append(f)
    return active, allowed


def unused_entries(
    findings: list[Finding], entries: list[AllowEntry],
) -> list[AllowEntry]:
    """Entries that matched NO finding in this run — stale suppressions
    whose bug was fixed (or whose path/rule drifted). ``--strict`` warns
    on these so an allowlist entry cannot silently outlive the finding
    it was written for. Only meaningful for runs covering the full
    surface (all entrypoints + default AST roots); partial runs see a
    partial finding set and would report false staleness."""
    return [e for e in entries if not any(e.matches(f) for f in findings)]
