"""Device prefetch: overlap host→device transfer with device compute.

The reference's DataLoader hands batches to `.cuda()` synchronously inside
the hot loop (codes/task1/pytorch/model.py:44-49). On TPU the idiomatic
shape is a small device-side queue (the MindSpore notebook's
``dataset_sink_mode`` is the same idea, SURVEY.md §3.5): while step N
computes, batch N+1's host→device copy is already in flight, so input
transfer disappears from the step's critical path.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator

import jax


def prefetch_to_device(
    iterator: Iterable,
    size: int = 2,
    sharding=None,
) -> Iterator:
    """Yield items from ``iterator`` with up to ``size`` batches resident
    on device ahead of the consumer.

    Each item (any pytree of arrays) is ``jax.device_put`` — with
    ``sharding`` when given (e.g. a batch NamedSharding for DP) — as soon
    as a queue slot frees, so the copy overlaps the previous steps'
    compute. ``size=2`` is the classic double buffer; larger sizes only
    help when batch arrival jitters.
    """
    if size < 1:
        # Validate eagerly (this is a plain function returning a generator,
        # so the error fires at call time, not at first iteration).
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    return _prefetch_gen(iterator, size, sharding)


def _prefetch_gen(iterator, size, sharding):
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                item = next(it)
            except StopIteration:
                return
            queue.append(jax.device_put(item, sharding))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)
