"""Dataset loading: MNIST / CIFAR-10 from disk, with a deterministic
learnable synthetic fallback.

The reference downloads MNIST via torchvision (codes/task1/pytorch/
model.py:93-100). This framework reads the same IDX files offline from
``data_dir``; when they are absent (e.g. air-gapped TPU-VM), it generates a
deterministic synthetic classification problem with the same shapes so every
entrypoint, test, and benchmark still runs end-to-end. The synthetic data is
class-structured (per-class prototype + noise), so models actually learn and
accuracy assertions remain meaningful.
"""

from __future__ import annotations

import pickle
import tarfile
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from tpudml.data.idx import read_idx

MNIST_FILES = {
    "train_images": ["train-images-idx3-ubyte", "train-images.idx3-ubyte"],
    "train_labels": ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"],
    "test_images": ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"],
    "test_labels": ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"],
}


@dataclass
class ArrayDataset:
    """In-memory dataset of (images, labels); the framework's Dataset role
    in the reference's Dataset/Sampler/DataLoader triad
    (sections/task3.tex:27-43).

    Two storage modes: float32 already normalized (scale=1, bias=0), or raw
    uint8 with normalization deferred to batch time (``scale``/``bias``
    applied by :meth:`gather` via the C++ data-plane) — 4× less resident
    memory and one fused pass per batch instead of a load-time full-dataset
    conversion.
    """

    images: np.ndarray  # [N, H, W, C] float32 normalized, or uint8 raw
    labels: np.ndarray  # [N] int32
    name: str = "dataset"
    scale: float = 1.0  # batch-time normalization: f32 = raw * scale + bias
    bias: float = 0.0

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx):
        # Same semantics as gather (normalized float32 for u8 storage) so
        # the two access paths of the Dataset protocol never disagree.
        # Supports scalars, index arrays, boolean masks, and slices.
        if isinstance(idx, slice):
            idx = np.arange(len(self))[idx]
        idx = np.asarray(idx)
        if idx.dtype == np.bool_:
            if len(idx) != len(self):
                raise IndexError(
                    f"boolean mask length {len(idx)} does not match dataset "
                    f"length {len(self)}"
                )
            idx = np.nonzero(idx)[0]
        if idx.ndim == 0:
            imgs, lbls = self.gather(idx[None].astype(np.int64))
            return imgs[0], lbls[0]
        return self.gather(idx.astype(np.int64))

    def gather(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Materialize a batch: fused row-gather (+ dequantize-normalize for
        uint8 storage) through tpudml.native, numpy fallback otherwise."""
        from tpudml import native

        if self.images.dtype == np.uint8:
            imgs = native.gather_normalize(self.images, idx, self.scale, self.bias)
        else:
            imgs = native.gather_rows(self.images, idx)
        return imgs, native.gather_labels(self.labels, idx)


def _check_storage(storage: str) -> None:
    if storage not in ("u8", "f32"):
        raise ValueError(f"storage must be 'u8' or 'f32', got {storage!r}")


def _find_file(data_dir: Path, candidates: list[str]) -> Path | None:
    # torchvision layout (MNIST/raw/...) and flat layout both supported.
    for sub in ("", "MNIST/raw", "mnist", "raw"):
        for name in candidates:
            for suffix in ("", ".gz"):
                p = data_dir / sub / (name + suffix)
                if p.exists():
                    return p
    return None


def synthetic_classification(
    n: int,
    shape: tuple[int, ...],
    num_classes: int,
    seed: int,
    noise: float = 0.35,
    proto_seed: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic class-structured data: per-class prototype + Gaussian
    noise, clipped to [0,1]. Learnable by a linear model yet not trivially
    separable at high noise. ``proto_seed`` fixes the class prototypes
    independently of the sample draw, so train/test splits share one
    distribution (different ``seed``, same ``proto_seed``)."""
    proto_rng = np.random.default_rng(seed if proto_seed is None else proto_seed)
    protos = proto_rng.uniform(0.0, 1.0, size=(num_classes, *shape)).astype(np.float32)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    imgs = protos[labels] + rng.normal(0.0, noise, size=(n, *shape)).astype(np.float32)
    return np.clip(imgs, 0.0, 1.0).astype(np.float32), labels


def synthetic_lm(
    n: int, seq_len: int, vocab: int, seed: int, noise: float = 0.0
) -> np.ndarray:
    """Deterministic next-token sequences: x[t+1] = π(x[t]) for a fixed
    vocab permutation π (optionally corrupted with probability ``noise``).
    A language model must learn π, so LM loss → 0 is achievable and
    training-progress assertions stay meaningful — the sequence-modeling
    analogue of :func:`synthetic_classification`. Returns [n, seq_len+1]
    int32 tokens; slice [:, :-1] / [:, 1:] for inputs/targets."""
    perm = np.random.default_rng(0xC0FFEE).permutation(vocab)
    rng = np.random.default_rng(seed)
    seqs = np.empty((n, seq_len + 1), np.int32)
    seqs[:, 0] = rng.integers(0, vocab, size=n)
    for t in range(seq_len):
        seqs[:, t + 1] = perm[seqs[:, t]]
    if noise:
        corrupt = rng.random(seqs.shape) < noise
        seqs = np.where(corrupt, rng.integers(0, vocab, size=seqs.shape), seqs)
    return seqs.astype(np.int32)


def load_mnist(
    data_dir: str = "./data",
    split: str = "train",
    synthetic_fallback: bool = True,
    synthetic_size: int | None = None,
    storage: str = "u8",
) -> ArrayDataset:
    """MNIST, semantically normalized float32 NHWC in [0,1].

    Matches the reference's transform (ToTensor only — scales to [0,1],
    codes/task1/pytorch/model.py:93-95; no mean/std normalization).
    ``storage="u8"`` (default) keeps the raw bytes resident and fuses the
    /255 into batch gathering; ``"f32"`` converts at load time.
    """
    _check_storage(storage)
    data_dir = Path(data_dir)
    img_key = f"{split if split == 'train' else 'test'}_images"
    lbl_key = f"{split if split == 'train' else 'test'}_labels"
    img_path = _find_file(data_dir, MNIST_FILES[img_key])
    lbl_path = _find_file(data_dir, MNIST_FILES[lbl_key])
    if img_path is not None and lbl_path is not None:
        images = read_idx(img_path)[..., None]  # [N,28,28,1] uint8
        labels = read_idx(lbl_path).astype(np.int32)
        if storage == "u8":
            return ArrayDataset(
                np.ascontiguousarray(images),
                labels,
                name=f"mnist-{split}",
                scale=1.0 / 255.0,
            )
        return ArrayDataset(
            images.astype(np.float32) / 255.0, labels, name=f"mnist-{split}"
        )
    if not synthetic_fallback:
        raise FileNotFoundError(f"MNIST IDX files not found under {data_dir}")
    n = synthetic_size or (60000 if split == "train" else 10000)
    imgs, labels = synthetic_classification(
        n, (28, 28, 1), 10, seed=0 if split == "train" else 1, proto_seed=100
    )
    return ArrayDataset(imgs, labels, name=f"mnist-synthetic-{split}")


def load_cifar10(
    data_dir: str = "./data",
    split: str = "train",
    synthetic_fallback: bool = True,
    synthetic_size: int | None = None,
    storage: str = "u8",
) -> ArrayDataset:
    """CIFAR-10 python-pickle batches, NHWC in [0,1] (u8 storage defers the
    /255 to batch time, as in load_mnist)."""
    _check_storage(storage)
    data_dir = Path(data_dir)
    base = None
    for cand in (data_dir / "cifar-10-batches-py", data_dir):
        if (cand / "data_batch_1").exists():
            base = cand
            break
    tar = data_dir / "cifar-10-python.tar.gz"
    if base is None and tar.exists():
        with tarfile.open(tar) as tf:
            try:
                tf.extractall(data_dir, filter="data")  # no path traversal
            except TypeError:  # Python < 3.12 has no filter kwarg
                tf.extractall(data_dir)
        base = data_dir / "cifar-10-batches-py"
    if base is not None:
        files = (
            [base / f"data_batch_{i}" for i in range(1, 6)]
            if split == "train"
            else [base / "test_batch"]
        )
        imgs, labels = [], []
        for f in files:
            with open(f, "rb") as fh:
                d = pickle.load(fh, encoding="bytes")
            imgs.append(d[b"data"])
            labels.append(np.asarray(d[b"labels"]))
        raw = (
            np.concatenate(imgs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        )
        all_labels = np.concatenate(labels).astype(np.int32)
        if storage == "u8":
            return ArrayDataset(
                np.ascontiguousarray(raw),
                all_labels,
                name=f"cifar10-{split}",
                scale=1.0 / 255.0,
            )
        return ArrayDataset(
            raw.astype(np.float32) / 255.0, all_labels, name=f"cifar10-{split}"
        )
    if not synthetic_fallback:
        raise FileNotFoundError(f"CIFAR-10 not found under {data_dir}")
    n = synthetic_size or (50000 if split == "train" else 10000)
    imgs, labels = synthetic_classification(
        n, (32, 32, 3), 10, seed=2 if split == "train" else 3, proto_seed=101
    )
    return ArrayDataset(imgs, labels, name=f"cifar10-synthetic-{split}")


def load_dataset(name: str, data_dir: str, split: str, **kw) -> ArrayDataset:
    name = name.lower()
    if name == "mnist":
        return load_mnist(data_dir, split, **kw)
    if name == "cifar10":
        return load_cifar10(data_dir, split, **kw)
    if name == "synthetic":
        storage = kw.pop("storage", "f32")
        _check_storage(storage)
        n = kw.get("synthetic_size") or (4096 if split == "train" else 1024)
        imgs, labels = synthetic_classification(
            n, (28, 28, 1), 10, seed=0 if split == "train" else 1, proto_seed=100
        )
        if storage == "u8":
            # Honor the requested resident format: quantize the generated
            # [0,1] floats to bytes, normalization deferred to gather.
            return ArrayDataset(
                np.ascontiguousarray((imgs * 255.0).round().astype(np.uint8)),
                labels,
                name=f"synthetic-{split}",
                scale=1.0 / 255.0,
            )
        return ArrayDataset(imgs, labels, name=f"synthetic-{split}")
    raise ValueError(f"unknown dataset {name!r}")
