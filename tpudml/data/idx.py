"""IDX (MNIST) binary format reader/writer.

The reference gets MNIST through ``torchvision.datasets.MNIST`` (codes/task1/
pytorch/model.py:93-100), which reads the classic IDX files. This is a
from-scratch, dependency-free decoder for the same on-disk format (and an
encoder, used by tests and the synthetic-data cache), with an optional
C++-accelerated path (tpudml/native) for large files.

Format: big-endian; 2 zero bytes, 1 dtype byte, 1 ndim byte, then ndim
uint32 dims, then row-major payload.
"""

from __future__ import annotations

import gzip
import struct
import sys
from pathlib import Path

import numpy as np

_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.int16,
    0x0C: np.int32,
    0x0D: np.float32,
    0x0E: np.float64,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _IDX_DTYPES.items()}


def read_idx(path: str | Path) -> np.ndarray:
    """Decode an IDX file (transparently handles .gz)."""
    path = Path(path)
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        data = f.read()
    if len(data) < 4 or data[0] != 0 or data[1] != 0:
        raise ValueError(f"{path}: not an IDX file (bad magic {data[:4]!r})")
    dtype_code, ndim = data[2], data[3]
    if dtype_code not in _IDX_DTYPES:
        raise ValueError(f"{path}: unknown IDX dtype 0x{dtype_code:02x}")
    dims = struct.unpack(f">{ndim}I", data[4 : 4 + 4 * ndim])
    arr = (
        np.frombuffer(
            data,
            dtype=_IDX_DTYPES[dtype_code],
            count=int(np.prod(dims)),
            offset=4 + 4 * ndim,
        )
        .reshape(dims)
        .copy()
    )
    if arr.dtype.itemsize > 1 and sys.byteorder == "little":
        # IDX payloads are big-endian; swap in place (C++ fast path).
        from tpudml import native

        native.byteswap_inplace(arr)
    return arr


def write_idx(path: str | Path, arr: np.ndarray) -> None:
    """Encode an array to IDX (used by tests / synthetic-data caching)."""
    path = Path(path)
    dtype = np.dtype(arr.dtype)
    if dtype not in _DTYPE_CODES:
        raise ValueError(f"dtype {dtype} not representable in IDX")
    header = bytes([0, 0, _DTYPE_CODES[dtype], arr.ndim]) + struct.pack(
        f">{arr.ndim}I", *arr.shape
    )
    payload = np.ascontiguousarray(arr).astype(dtype.newbyteorder(">")).tobytes()
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "wb") as f:
        f.write(header + payload)
