from tpudml.data.datasets import ArrayDataset, load_cifar10, load_dataset, load_mnist
from tpudml.data.idx import read_idx, write_idx
from tpudml.data.loader import DataLoader, ShardedDataLoader
from tpudml.data.prefetch import prefetch_to_device
from tpudml.data.sampler import (
    RandomPartitionSampler,
    RandomSamplingSampler,
    Sampler,
    SequentialSampler,
    make_sampler,
)

__all__ = [
    "ArrayDataset",
    "load_dataset",
    "load_mnist",
    "load_cifar10",
    "read_idx",
    "write_idx",
    "DataLoader",
    "ShardedDataLoader",
    "prefetch_to_device",
    "Sampler",
    "SequentialSampler",
    "RandomPartitionSampler",
    "RandomSamplingSampler",
    "make_sampler",
]
