"""Sampler framework: how the global dataset is divided across replicas.

Re-design of the reference's task3 sampler layer (codes/task3/sampler.py:5-25
+ torch ``DistributedSampler`` at codes/task2/model.py:124). The two required
division modes (sections/task3.tex:19-24, sections/checking.tex:13):

- **random partition** — one shuffle from a seed shared by all replicas,
  each replica takes a disjoint stride → disjoint, jointly-exhaustive shards.
- **random sampling** — each replica shuffles independently (the reference
  achieves this by passing ``seed=rank``, codes/task3/model.py:111) → random
  sampling with replacement *across* replicas (examples may be seen by
  several replicas or none in a given epoch).

Both are bit-reproducible from (seed, epoch, rank) and support the
``set_epoch`` per-epoch reshuffle contract (sections/task3.tex:52).
Index generation is host-side numpy — it composes with per-host data
sharding (each host materializes only its replicas' indices).
"""

from __future__ import annotations

import numpy as np


class Sampler:
    """Iterable of dataset indices for one replica's epoch.

    Parity with the reference's ``MySampler`` surface: ``__iter__``,
    ``__len__``, ``set_epoch`` (codes/task3/sampler.py:16-25).
    """

    def __init__(
        self,
        dataset_size: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_size = int(dataset_size)
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        # ceil(N / num_replicas), as in the reference (sampler.py:14).
        self.num_samples = -(-self.dataset_size // num_replicas)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def _indices(self) -> np.ndarray:
        raise NotImplementedError

    def __iter__(self):
        return iter(self._indices())


class SequentialSampler(Sampler):
    """Un-shuffled strided shard; the shuffle=False degenerate case."""

    def _indices(self) -> np.ndarray:
        padded = _pad_to_multiple(np.arange(self.dataset_size), self.num_replicas)
        return padded[self.rank :: self.num_replicas]


class RandomPartitionSampler(Sampler):
    """Random partition: shared-seed shuffle, disjoint per-rank stride.

    All replicas must construct this with the SAME seed; the per-epoch
    reshuffle folds in ``epoch`` so shards change across epochs but remain
    disjoint within one.
    """

    def _indices(self) -> np.ndarray:
        order = np.arange(self.dataset_size)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            rng.shuffle(order)
        padded = _pad_to_multiple(order, self.num_replicas)
        return padded[self.rank :: self.num_replicas]


class RandomSamplingSampler(Sampler):
    """Random sampling: per-rank independent shuffle (reference's
    ``seed=rank`` discipline) — replicas draw overlapping samples."""

    def _indices(self) -> np.ndarray:
        if not self.shuffle:
            # Without shuffling, independent per-rank draws would collapse to
            # every rank reading the same head of the dataset; degrade to the
            # strided disjoint shard instead (SequentialSampler semantics).
            padded = _pad_to_multiple(np.arange(self.dataset_size), self.num_replicas)
            return padded[self.rank :: self.num_replicas]
        rng = np.random.default_rng((self.seed, self.rank, self.epoch))
        return rng.permutation(self.dataset_size)[: self.num_samples]


def _pad_to_multiple(order: np.ndarray, m: int) -> np.ndarray:
    """Pad by wrapping from the front so every rank gets num_samples
    indices (torch DistributedSampler semantics)."""
    total = -(-len(order) // m) * m
    if total == len(order):
        return order
    return np.concatenate([order, order[: total - len(order)]])


def make_sampler(
    division: str,
    dataset_size: int,
    num_replicas: int,
    rank: int,
    shuffle: bool = True,
    seed: int = 0,
) -> Sampler:
    """Factory keyed by the config's ``division`` field."""
    division = division.lower()
    cls = {
        "partition": RandomPartitionSampler,
        "sampling": RandomSamplingSampler,
        "sequential": SequentialSampler,
    }.get(division)
    if cls is None:
        raise ValueError(f"unknown division mode {division!r}")
    return cls(dataset_size, num_replicas, rank, shuffle=shuffle, seed=seed)
