"""Batching data loader.

The DataLoader role of the reference's Dataset/Sampler/DataLoader triad
(sections/task3.tex:27-43): draws an index stream from a Sampler, gathers
rows from the in-memory dataset, and yields fixed-shape numpy batches.
Fixed shapes matter on TPU — a ragged final batch would trigger an XLA
recompile, so ``drop_remainder`` defaults to True (the MindSpore notebook's
``batch(drop_remainder=True)`` made the same choice for graph mode,
reference: codes/task1/mindspore/model.ipynb cell 2).

For multi-replica training the loader can batch for SEVERAL replicas at
once (``global_batch``): on a single host driving an N-device mesh, it
stacks each replica's sampler stream into a leading device axis, ready to be
sharded over the mesh's ``data`` axis.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from tpudml.data.datasets import ArrayDataset
from tpudml.data.sampler import Sampler, SequentialSampler


class DataLoader:
    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        sampler: Sampler | None = None,
        drop_remainder: bool = True,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or SequentialSampler(len(dataset), shuffle=False)
        self.drop_remainder = drop_remainder

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_remainder else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        idx = np.fromiter(iter(self.sampler), dtype=np.int64)
        end = (
            len(idx) - len(idx) % self.batch_size if self.drop_remainder else len(idx)
        )
        # Fused native gather (+ normalize for u8 storage) when the dataset
        # provides it; plain fancy indexing otherwise.
        gather = getattr(self.dataset, "gather", None)
        for start in range(0, end, self.batch_size):
            batch = idx[start : start + self.batch_size]
            if gather is not None:
                yield gather(batch)
            else:
                yield self.dataset.images[batch], self.dataset.labels[batch]


class ShardedDataLoader:
    """Batches for all replicas of a mesh ``data`` axis at once.

    Yields ``[R, B, ...]`` arrays (R = num_replicas, B = per-replica batch):
    the single-host analogue of R processes each running their own loader,
    with identical per-replica index streams (each replica r's stream comes
    from its own Sampler(rank=r)). Reshape/shard over the mesh data axis to
    feed a shard_map/pjit step.
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        samplers: list[Sampler],
        drop_remainder: bool = True,
    ):
        if not samplers:
            raise ValueError("need at least one sampler")
        self.loaders = [
            DataLoader(dataset, batch_size, s, drop_remainder) for s in samplers
        ]

    def set_epoch(self, epoch: int) -> None:
        for ld in self.loaders:
            ld.set_epoch(epoch)

    def __len__(self) -> int:
        return min(len(ld) for ld in self.loaders)

    def __iter__(self):
        its = [iter(ld) for ld in self.loaders]
        for _ in range(len(self)):
            parts = [next(it) for it in its]
            yield (
                np.stack([p[0] for p in parts]),
                np.stack([p[1] for p in parts]),
            )
