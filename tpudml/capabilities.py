"""Engine-composition capability table: one source of truth for what
does NOT compose.

Every "X does not compose with Y" rejection in the engines
(``tpudml/parallel``), the optimizer wrappers (``tpudml/optim``), the
serving tier (``tpudml/serve``), and the task CLIs lives here as a
:class:`Capability` entry.  Runtime guard sites call :func:`reject`
with the entry's key instead of hand-writing the message, and the
static planner (``tpudml/plan``) prunes its candidate space with the
same entries via each entry's ``when`` predicate — so the planner and
the runtime can never disagree about feasibility: a plan candidate the
planner keeps is, by construction, one no constructor will throw on.

This module is deliberately dependency-free (stdlib only).  The
engines import it at module top; anything heavier here would tax every
``import tpudml.parallel.dp``.  The analysis package re-exports it as
``tpudml.analysis.capabilities`` (importing it from an engine through
that path would cycle back through ``analysis.entrypoints`` into the
engines, so guard sites import ``tpudml.capabilities`` directly).

``when`` predicates read a flat *candidate* dict (the planner's
normalized knob record — see ``tpudml/plan/space.py``).  Keys they may
consult, all optional: ``engine`` (one of ``dp / zero1 / fsdp / tp /
fsdp_tp / pp_dp / ep``), ``mesh`` (axis-name → size dict), ``zero1``,
``zero1_overlap``, ``accum_steps``, ``fused_xent``, ``save_scores``,
``measure_comm``, ``custom_loss``, ``aggregation``, ``dropout``,
``moe_experts``, ``grad_clip``, ``schedule``, ``flash_attn``, ``impl``,
``seq_sharded``, ``tp_overlap``, ``serve_tp``, ``serve_cache_layout``,
``serve_spec_k``, ``serve_weight_quant``, ``serve_fused_head``,
``serve_fleet``, ``mpmd``, ``serve``.  Entries with ``when=None``
are constructor-level invariants the planner can never generate (e.g.
handing a pre-wrapped ZeRO1 optimizer to a non-zero1 engine) — they
still own their runtime message here so the guard text stays in the
table.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional


class CompositionError(ValueError):
    """An engine/knob combination that is rejected by design.

    Subclasses ``ValueError`` so every pre-existing ``pytest.raises``
    and caller-side ``except ValueError`` keeps working.
    """


# Engine families the predicates reason over. ``zero1`` is the DP
# engine with zero1=True; fsdp/tp/fsdp_tp all construct GSPMDParallel.
_DP_FAMILY = ("dp", "zero1")
_GSPMD_FAMILY = ("tp", "fsdp", "fsdp_tp")


def _g(c: dict, key: str, default=None):
    return c.get(key, default)


@dataclass(frozen=True)
class Capability:
    """One composition rejection: where it is enforced, the exact
    message the runtime raises, and (when statically decidable) the
    predicate the planner prunes with."""

    key: str
    owner: str  # module(s) whose constructor raises it
    message: str
    when: Optional[Callable[[dict], bool]] = None


_ENTRIES = (
    Capability(
        key="save_scores_needs_fused_xent",
        owner="tpudml.parallel.dp / mp / cp",
        message="save_scores requires fused_xent=True",
        when=lambda c: bool(_g(c, "save_scores")) and not _g(c, "fused_xent"),
    ),
    Capability(
        key="dp_fused_xent_split_step",
        owner="tpudml.parallel.dp",
        message=(
            "fused_xent composes with the fused step and the "
            "built-in cross-entropy only (measure_comm=False, "
            "default loss)"
        ),
        when=lambda c: _g(c, "engine") in _DP_FAMILY
        and bool(_g(c, "fused_xent"))
        and bool(_g(c, "measure_comm") or _g(c, "custom_loss")),
    ),
    Capability(
        key="gspmd_fused_xent_accum",
        owner="tpudml.parallel.mp",
        message=(
            "fused_xent composes with the fused LM step and the built-in "
            "cross-entropy only (no accum_steps, no custom loss)"
        ),
        when=lambda c: _g(c, "engine") in _GSPMD_FAMILY
        and bool(_g(c, "fused_xent"))
        and (_g(c, "accum_steps", 1) != 1 or bool(_g(c, "custom_loss"))),
    ),
    Capability(
        key="zero1_overlap_needs_zero1",
        owner="tpudml.parallel.dp",
        message="zero1_overlap requires zero1=True",
        when=lambda c: bool(_g(c, "zero1_overlap")) and not _g(c, "zero1"),
    ),
    Capability(
        key="zero1_replaces_aggregation",
        owner="tpudml.parallel.dp",
        message=(
            "zero1=True replaces gradient aggregation with its own "
            "reduce-scatter; leave aggregation='allreduce' (the default)"
        ),
        when=lambda c: bool(_g(c, "zero1"))
        and _g(c, "aggregation", "allreduce") != "allreduce",
    ),
    Capability(
        key="zero1_overlap_needs_accum",
        owner="tpudml.parallel.dp",
        message=(
            "zero1_overlap needs accum_steps >= 2: the overlap hides "
            "the param all_gather behind the micro-batch scan"
        ),
        when=lambda c: bool(_g(c, "zero1_overlap"))
        and bool(_g(c, "zero1"))
        and _g(c, "accum_steps", 1) < 2,
    ),
    Capability(
        key="zero1_overlap_measure_comm",
        owner="tpudml.parallel.dp",
        message=(
            "measure_comm is unsupported with zero1_overlap (the "
            "split bracketing assumes the gather-at-end step layout); "
            "use overlap_report() for exposed/hidden attribution"
        ),
        when=lambda c: bool(_g(c, "zero1_overlap"))
        and bool(_g(c, "zero1"))
        and bool(_g(c, "measure_comm")),
    ),
    Capability(
        key="zero1_optimizer_needs_zero1",
        owner="tpudml.parallel.dp",
        message=(
            "a ZeRO1-wrapped optimizer needs zero1=True (the "
            "engine must shard the optimizer state it creates)"
        ),
        when=None,  # constructor invariant: the planner never pre-wraps
    ),
    Capability(
        key="pp_zero1_needs_batch_axis",
        owner="tpudml.parallel.pp",
        message=(
            "a ZeRO1 optimizer needs a data axis to shard the "
            "update over: pass batch_axis (PP×DP composition)"
        ),
        when=lambda c: _g(c, "engine") == "pp_dp"
        and bool(_g(c, "zero1"))
        and not _g(c, "mesh", {}).get("data"),
    ),
    Capability(
        key="pp_fused_xent",
        owner="tasks.task5_longcontext",
        message=(
            "--fused_xent does not compose with --parallel pp: the "
            "pipeline epilogue ships logits between stages, so there "
            "is no feature tensor for the fused head to consume"
        ),
        when=lambda c: _g(c, "engine") == "pp_dp" and bool(_g(c, "fused_xent")),
    ),
    Capability(
        key="pp_moe",
        owner="tasks.task5_longcontext",
        message="--parallel pp does not support --moe_experts",
        when=lambda c: _g(c, "engine") == "pp_dp"
        and bool(_g(c, "moe_experts")),
    ),
    Capability(
        key="gpipe_dropout",
        owner="tpudml.parallel.pp",
        message=(
            "GPipe stages do not support dropout; use OneFOneB "
            "(schedule='1f1b') with rng_root for dropout pipelines"
        ),
        when=lambda c: _g(c, "engine") == "pp_dp"
        and bool(_g(c, "dropout"))
        and _g(c, "schedule", "gpipe") == "gpipe",
    ),
    Capability(
        key="zero1_stacked_clip",
        owner="tpudml.optim.zero1",
        message=(
            "ZeRO1(stacked=...) cannot wrap a ClipByGlobalNorm chain: "
            "stage-stacked chunks shard over two mesh axes and the "
            "clip's single-psum norm would double-count or miss shards"
        ),
        when=lambda c: _g(c, "engine") == "pp_dp"
        and bool(_g(c, "zero1"))
        and bool(_g(c, "grad_clip")),
    ),
    Capability(
        key="ep_dropout",
        owner="tasks.task5_longcontext",
        message="--parallel ep does not support --dropout",
        when=lambda c: _g(c, "engine") == "ep" and bool(_g(c, "dropout")),
    ),
    Capability(
        key="train_flash_attn_dense",
        owner="tpudml.parallel.dp / mp",
        message=(
            "flash_attn swaps the dense causal trunk onto the Pallas "
            "flash kernel; it requires impl='full' (ring/ulysses trunks "
            "already run fused sequence-sharded attention) and "
            "seq_sharded=False"
        ),
        when=lambda c: bool(_g(c, "flash_attn"))
        and (
            _g(c, "impl", "full") != "full" or bool(_g(c, "seq_sharded"))
        ),
    ),
    Capability(
        key="tp_overlap_needs_model_axis",
        owner="tpudml.parallel.overlap / tpudml.plan",
        message=(
            "tp_overlap chunks a row-sharded matmul against its psum; "
            "without a model axis of size > 1 there is no reduce to "
            "hide — run the unchunked matmul"
        ),
        when=lambda c: bool(_g(c, "tp_overlap"))
        and _g(c, "mesh", {}).get("model", 1) <= 1,
    ),
    Capability(
        key="serve_fused_head_dense",
        owner="tpudml.serve.engine",
        message=(
            "fused_head folds the greedy pick into the head matmul "
            "epilogue of the dense single-device decode step only: the "
            "paged/spec steps consume full logits windows and TP "
            "shards the head — run those unfused"
        ),
        when=lambda c: bool(_g(c, "serve_fused_head"))
        and (
            bool(_g(c, "serve_tp"))
            or _g(c, "serve_cache_layout", "dense") != "dense"
            or _g(c, "serve_spec_k", 0) > 0
        ),
    ),
    Capability(
        key="serve_tp_paged_spec",
        owner="tpudml.serve.engine",
        message=(
            "tensor-parallel serving does not compose with "
            "cache_layout='paged' or spec_k>0 yet; run TP dense, or "
            "paged/spec single-device"
        ),
        when=lambda c: bool(_g(c, "serve_tp"))
        and (
            _g(c, "serve_cache_layout", "dense") == "paged"
            or _g(c, "serve_spec_k", 0) > 0
        ),
    ),
    Capability(
        key="serve_tp_weight_quant",
        owner="tpudml.serve.engine",
        message=(
            "tensor-parallel serving does not compose with "
            "weight_quant: shard_params knows nothing of int8 kernels "
            "+ scale trees; quantize single-device replicas"
        ),
        when=lambda c: bool(_g(c, "serve_tp"))
        and _g(c, "serve_weight_quant") is not None,
    ),
    Capability(
        key="serve_fleet_spec",
        owner="tpudml.serve.fleet.router",
        message=(
            "fleet replicas do not compose with spec_k>0 yet: the "
            "router's drain/re-admit continuation assumes one committed "
            "token per slot per step; run spec single-engine"
        ),
        when=lambda c: bool(_g(c, "serve_fleet"))
        and _g(c, "serve_spec_k", 0) > 0,
    ),
    Capability(
        key="serve_tp_dense_only",
        owner="tpudml.serve.tp",
        message=(
            "TPServing supports cache_layout='dense' with spec_k=0 "
            "only; paged/speculative serving is single-device"
        ),
        when=lambda c: bool(_g(c, "serve_tp"))
        and (
            _g(c, "serve_cache_layout", "dense") != "dense"
            or _g(c, "serve_spec_k", 0) > 0
        ),
    ),
    Capability(
        key="mpmd_moe_aux_loss",
        owner="tpudml.mpmd.spec",
        message=(
            "MPMD stages do not compose with moe_experts: the router "
            "aux loss is a global mean over all tokens, and an MPMD "
            "trunk stage has no channel to fold its aux term into the "
            "head stage's loss"
        ),
        when=lambda c: bool(_g(c, "mpmd")) and bool(_g(c, "moe_experts")),
    ),
    Capability(
        key="mpmd_fused_xent_head",
        owner="tpudml.mpmd.spec",
        message=(
            "MPMD head stages do not compose with fused_xent: the fused "
            "head recomputes logits inside one jitted loss+grad program, "
            "but the MPMD head must expose the activation cotangent as a "
            "host array for the backward wire transfer"
        ),
        when=lambda c: bool(_g(c, "mpmd")) and bool(_g(c, "fused_xent")),
    ),
    Capability(
        key="mpmd_serve",
        owner="tpudml.mpmd.spec",
        message=(
            "MPMD stage groups do not compose with the serving tier: "
            "ServingEngine slot state lives in one process's jitted "
            "decode step and cannot span multi-controller stage worlds; "
            "serve from a single-program replica (FleetRouter)"
        ),
        when=lambda c: bool(_g(c, "mpmd")) and bool(_g(c, "serve")),
    ),
)

TABLE: dict[str, Capability] = {e.key: e for e in _ENTRIES}
assert len(TABLE) == len(_ENTRIES), "duplicate capability keys"


def reject(key: str, exc: type = CompositionError):
    """Raise the capability table's rejection for ``key``.

    Guard sites call this instead of inlining the message; ``exc`` lets
    a site keep its historical exception type (``ServeCompositionError``)
    as long as it subclasses :class:`CompositionError`.
    """
    raise exc(TABLE[key].message)


def candidate_rejection(candidate: dict) -> Optional[str]:
    """First table key whose predicate rejects ``candidate`` (insertion
    order — deterministic), or None when every statically-decidable
    composition rule admits it."""
    for key, cap in TABLE.items():
        if cap.when is not None and cap.when(candidate):
            return key
    return None
