"""Functional neural-net module system.

A minimal init/apply layer framework in the JAX idiom: a ``Module`` is an
immutable description; ``init(key)`` returns a parameter pytree and a state
pytree (e.g. batch-norm running stats); ``apply(params, state, x, train=...)``
is a pure function returning ``(y, new_state)``. Parameters are plain nested
dicts, so the hand-written optimizers in ``tpudml.optim`` (reference:
codes/task1/pytorch/MyOptimizer.py) operate on them directly as pytrees, and
GSPMD sharding annotations attach to them without framework cooperation.

Data layout is NHWC (channels-last), the layout XLA:TPU prefers for
convolutions; the reference's NCHW torch models (codes/task1/pytorch/
model.py:16-35) map onto this with identical math.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

Params = Any
State = Any


class Module:
    """Base class: immutable layer description with pure init/apply."""

    def init(self, key: jax.Array) -> tuple[Params, State]:
        return {}, {}

    def apply(
        self,
        params: Params,
        state: State,
        x: jax.Array,
        *,
        train: bool = False,
        rng: jax.Array | None = None,
    ) -> tuple[jax.Array, State]:
        raise NotImplementedError

    # Convenience call; pass ``state`` for models with stateful layers
    # (e.g. BatchNorm running stats), whose apply would KeyError on {}.
    def __call__(self, params, x, state=None, **kw):
        y, _ = self.apply(params, state if state is not None else {}, x, **kw)
        return y


def _uniform_fan_in(key, shape, fan_in, dtype):
    """Kaiming-uniform à la torch's default Linear/Conv init: U(-b, b) with
    b = 1/sqrt(fan_in). Keeps initial loss scale close to the reference's
    torch models so loss curves are comparable."""
    bound = 1.0 / jnp.sqrt(jnp.maximum(fan_in, 1.0))
    return jax.random.uniform(key, shape, dtype, -bound, bound)


@dataclass(frozen=True)
class Dense(Module):
    in_features: int
    out_features: int
    use_bias: bool = True
    dtype: Any = jnp.float32

    def init(self, key):
        kw, kb = jax.random.split(key)
        params = {
            "kernel": _uniform_fan_in(
                kw, (self.in_features, self.out_features), self.in_features, self.dtype
            )
        }
        if self.use_bias:
            params["bias"] = _uniform_fan_in(
                kb, (self.out_features,), self.in_features, self.dtype
            )
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        y = x @ params["kernel"]
        if self.use_bias:
            y = y + params["bias"]
        return y, state


@dataclass(frozen=True)
class Conv2D(Module):
    """2-D convolution, NHWC x HWIO -> NHWC."""

    in_channels: int
    out_channels: int
    kernel_size: int | tuple[int, int] = 3
    stride: int | tuple[int, int] = 1
    padding: str | int = "SAME"
    use_bias: bool = True
    dtype: Any = jnp.float32

    def _ksize(self):
        k = self.kernel_size
        return (k, k) if isinstance(k, int) else tuple(k)

    def init(self, key):
        kh, kw_ = self._ksize()
        fan_in = kh * kw_ * self.in_channels
        kw, kb = jax.random.split(key)
        params = {
            "kernel": _uniform_fan_in(
                kw, (kh, kw_, self.in_channels, self.out_channels), fan_in, self.dtype
            )
        }
        if self.use_bias:
            params["bias"] = _uniform_fan_in(kb, (self.out_channels,), fan_in, self.dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        s = self.stride
        strides = (s, s) if isinstance(s, int) else tuple(s)
        if isinstance(self.padding, int):
            p = self.padding
            padding = [(p, p), (p, p)]
        else:
            padding = self.padding
        y = lax.conv_general_dilated(
            x,
            params["kernel"],
            window_strides=strides,
            padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + params["bias"]
        return y, state


@dataclass(frozen=True)
class MaxPool(Module):
    window: int = 2
    stride: int | None = None

    def apply(self, params, state, x, *, train=False, rng=None):
        w, s = self.window, self.stride or self.window
        y = lax.reduce_window(
            x, -jnp.inf, lax.max, (1, w, w, 1), (1, s, s, 1), "VALID"
        )
        return y, state


@dataclass(frozen=True)
class AvgPool(Module):
    window: int = 2
    stride: int | None = None

    def apply(self, params, state, x, *, train=False, rng=None):
        w, s = self.window, self.stride or self.window
        y = lax.reduce_window(x, 0.0, lax.add, (1, w, w, 1), (1, s, s, 1), "VALID")
        return y / (w * w), state


@dataclass(frozen=True)
class Flatten(Module):
    def apply(self, params, state, x, *, train=False, rng=None):
        return x.reshape(x.shape[0], -1), state


@dataclass(frozen=True)
class Activation(Module):
    fn: Callable[[jax.Array], jax.Array] = jax.nn.relu

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.fn(x), state


@dataclass(frozen=True)
class Dropout(Module):
    rate: float = 0.5

    def apply(self, params, state, x, *, train=False, rng=None):
        if not train or self.rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout in train mode requires an rng")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0), state


@dataclass(frozen=True)
class BatchNorm(Module):
    """Batch normalization with running-average inference statistics."""

    num_features: int
    momentum: float = 0.9
    eps: float = 1e-5
    dtype: Any = jnp.float32

    def init(self, key):
        params = {
            "scale": jnp.ones((self.num_features,), self.dtype),
            "bias": jnp.zeros((self.num_features,), self.dtype),
        }
        state = {
            "mean": jnp.zeros((self.num_features,), self.dtype),
            "var": jnp.ones((self.num_features,), self.dtype),
        }
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        axes = tuple(range(x.ndim - 1))
        if train:
            mean = jnp.mean(x, axes, dtype=jnp.float32)
            if x.dtype == jnp.bfloat16:
                # Single-pass moments in f32 accumulated straight off the bf16
                # stream: sum and sum-of-squares reduce in ONE fused read of
                # x instead of jnp.var's mean-then-deviations second pass, and
                # the stream is never materialized as an f32 copy. Clamped
                # E[x²] − m² (cancellation can go slightly negative in f32;
                # rsqrt(negative + eps) would NaN-poison the step). The bf16
                # input already bounds the stats' accuracy, so the single-pass
                # cancellation is below the quantization floor.
                var = jnp.maximum(
                    jnp.mean(jnp.square(x.astype(jnp.float32)), axes)
                    - jnp.square(mean),
                    0.0,
                )
            else:
                # Two-pass E[(x−m)²] for f32 inputs: at large activation
                # means (m² ≫ var) the single-pass form loses ALL variance
                # bits to f32 cancellation and the clamp silently returns
                # var=0 — normalization then amplifies by rsqrt(eps).
                var = jnp.mean(jnp.square(x.astype(jnp.float32) - mean), axes)
            m = self.momentum
            new_state = {
                "mean": m * state["mean"] + (1 - m) * mean.astype(state["mean"].dtype),
                "var": m * state["var"] + (1 - m) * var.astype(state["var"].dtype),
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        # Normalization in f32 (scale/bias params are f32), back in x's dtype —
        # pure elementwise, so XLA fuses the cast/normalize/cast chain into the
        # neighbouring ops; a bf16 compute path stays bf16 end to end.
        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(
            var.astype(jnp.float32) + self.eps
        )
        y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
        return y.astype(x.dtype), new_state


@dataclass(frozen=True)
class LayerNorm(Module):
    """Layer normalization over the trailing feature axis (the transformer
    norm; batch-size independent, so it needs no cross-replica state sync
    under data or sequence sharding)."""

    num_features: int
    eps: float = 1e-5
    dtype: Any = jnp.float32

    def init(self, key):
        return {
            "scale": jnp.ones((self.num_features,), self.dtype),
            "bias": jnp.zeros((self.num_features,), self.dtype),
        }, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        # Statistics always in float32 (bf16 mean/var is numerically weak
        # at transformer widths); result back in the input dtype so the
        # bf16 compute path stays bf16 end to end. Single-pass moments
        # (E[x²] − m² instead of jnp.var's second mean pass) — one fewer
        # reduction over the row for XLA to schedule; fine in f32 at
        # activation magnitudes.
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        # Clamped at 0: E[x²] − m² can go slightly NEGATIVE from f32
        # cancellation when m² >> var (large-mean rows), and
        # rsqrt(negative + eps) would NaN-poison the step.
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), axis=-1, keepdims=True) - jnp.square(mean),
            0.0,
        )
        y = (xf - mean) * lax.rsqrt(var + self.eps)
        y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(
            jnp.float32
        )
        return y.astype(x.dtype), state


@dataclass(frozen=True)
class Sequential(Module):
    """Chain of modules; params/state are dicts keyed ``layer{i}``."""

    layers: Sequence[Module] = field(default_factory=tuple)

    def init(self, key):
        params, state = {}, {}
        keys = jax.random.split(key, max(len(self.layers), 1))
        for i, (layer, k) in enumerate(zip(self.layers, keys)):
            p, s = layer.init(k)
            if p:
                params[f"layer{i}"] = p
            if s:
                state[f"layer{i}"] = s
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = {}
        rngs = (
            jax.random.split(rng, max(len(self.layers), 1)) if rng is not None else None
        )
        for i, layer in enumerate(self.layers):
            p = params.get(f"layer{i}", {})
            s = state.get(f"layer{i}", {})
            x, s2 = layer.apply(
                p, s, x, train=train, rng=rngs[i] if rngs is not None else None
            )
            if s2:
                new_state[f"layer{i}"] = s2
        return x, new_state


def iter_module_tree(obj):
    """Yield ``obj`` and every nested candidate module: dataclass fields,
    tuple/list items, and dict values. The ONE walker behind structural
    model inspection (dropout detection in the pipeline engines, MoE
    detection in the training engine) — containers added here propagate
    to every detector at once instead of drifting per copy (ADVICE r2 +
    review r3)."""
    import dataclasses

    yield obj
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            yield from iter_module_tree(getattr(obj, f.name))
    elif isinstance(obj, (tuple, list)):
        for o in obj:
            yield from iter_module_tree(o)
    elif isinstance(obj, dict):
        for o in obj.values():
            yield from iter_module_tree(o)
