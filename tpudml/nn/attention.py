"""Attention ops and the multi-head attention module.

The reference has no attention anywhere (models are a 28×28 CNN and an
MLP; SURVEY.md §5.7) — but long-context support is first-class in this
framework, so attention is built TPU-first from the start:

- layout [B, T, H, D] with the contraction kept as two einsums that XLA
  maps straight onto the MXU;
- optional causal masking by *global* position offsets, so the same code
  is correct when the sequence axis is sharded across devices (ring /
  Ulysses context parallelism in ``tpudml.parallel.cp``);
- the module's ``impl`` field selects full, flash (Pallas kernel), ring,
  or Ulysses attention, letting one model definition run single-chip or
  sequence-sharded.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from tpudml.comm.collectives import axis_size
from tpudml.nn.layers import Dense, Module

NEG_INF = -1e30  # large-finite mask value: avoids inf-inf → NaN in softmax


def sharded_positions(
    axis_name: str, t_local: int, seq_sharded: bool, seq_layout: str
) -> jax.Array:
    """GLOBAL token positions of this device's [t_local] sequence shard —
    the ONE definition RoPE, the position table, and the ring masks all
    derive from (a divergence between them is silent model corruption):
    contiguous → idx·Tl + j; striped → idx + W·j; unsharded → j."""
    if not seq_sharded:
        return jnp.arange(t_local)
    if seq_layout == "striped":
        world = axis_size(axis_name)
        return jax.lax.axis_index(axis_name) + world * jnp.arange(t_local)
    return jax.lax.axis_index(axis_name) * t_local + jnp.arange(t_local)


def rotary_embedding(
    x: jax.Array, positions: jax.Array, base: float = 10000.0
) -> jax.Array:
    """Rotary position embedding (RoPE) over [B, T, H, D_head].

    ``positions`` are GLOBAL token positions [T] — under a sharded
    sequence axis each device passes its shard's offset positions, and
    because RoPE encodes relative position in the q·k phase difference,
    ring/Ulysses attention then needs no further position handling.
    """
    d = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(d, dtype=jnp.float32) / d)  # [d]
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # [T, d]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :d], x[..., d:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
) -> jax.Array:
    """Scaled dot-product attention over [B, T, H, D] tensors.

    ``q_offset``/``k_offset`` are the global positions of q[:,0] and
    k[:,0]: with a sharded sequence axis each device passes its shard's
    offset and the causal mask stays globally correct.
    """
    d = q.shape[-1]
    # Scores + softmax in float32 regardless of input dtype (bf16 exp/sum
    # loses mass at long T); the PV contraction runs in the value dtype so
    # the MXU still sees bf16 operands on the bf16 path.
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@dataclass(frozen=True)
class MultiHeadAttention(Module):
    """Self-attention with separate head-aligned q/k/v projections (TP
    shards each kernel's output dim without in-layer resharding).

    ``impl``: "full" (one-device softmax(QKᵀ)V), "flash" (Pallas fused
    kernel on TPU, reference math elsewhere — tpudml.ops), "ring"
    (sequence sharded over ``axis_name``, K/V blocks rotated over the ring
    — must run under shard_map), or "ulysses" (all-to-all head↔sequence
    transpose — heads must divide the axis size).
    """

    embed_dim: int
    num_heads: int
    causal: bool = False
    impl: str = "full"
    axis_name: str = "seq"
    # Accepted for API compatibility; the ring custom-VJP backward always
    # recomputes per-block (flash-style), so rematerialization is implied.
    remat: bool = False
    num_kv_heads: int | None = None  # GQA/MQA: K/V head groups (< num_heads)
    rope: bool = False  # rotary position embeddings on q/k
    rope_base: float = 10000.0
    seq_sharded: bool = False  # rope offsets from axis_name when sharded
    # Sharded-sequence token layout: "contiguous" (device i owns
    # [i·Tl, (i+1)·Tl)) or "striped" (device i owns {t : t mod W == i} —
    # the balanced causal-ring layout; positions become idx + W·j).
    seq_layout: str = "contiguous"
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.embed_dim % self.num_heads:
            raise ValueError(
                f"embed_dim {self.embed_dim} % num_heads {self.num_heads} != 0"
            )
        kv = self.num_kv_heads
        if kv is not None and (kv < 1 or self.num_heads % kv):
            raise ValueError(
                f"num_kv_heads {kv} must divide num_heads {self.num_heads}"
            )
        if self.seq_layout not in ("contiguous", "striped"):
            raise ValueError(f"unknown seq_layout {self.seq_layout!r}")
        if self.seq_layout == "striped" and self.impl != "ring":
            # Ulysses/full gather shards in device order — under striping
            # that is a PERMUTED sequence, so their causal masks would
            # silently let tokens attend the future. Only the ring fold
            # understands striped positions.
            raise ValueError(
                f"seq_layout='striped' requires impl='ring', got {self.impl!r}"
            )
        if self.rope and (self.embed_dim // self.num_heads) % 2:
            # RoPE rotates feature PAIRS; an odd head_dim would silently
            # broadcast to the wrong width instead of erroring later.
            raise ValueError(
                f"rope requires an even head_dim, got "
                f"{self.embed_dim // self.num_heads}"
            )

    @property
    def _kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def init(self, key):
        # Separate q/k/v projections (not a fused [d, 3d] kernel): shards of
        # each kernel's output dim stay head-aligned under tensor
        # parallelism, so Megatron-style column sharding needs no in-layer
        # resharding for any mesh size dividing num_heads — for the K/V
        # kernels under GQA that bound is num_kv_heads (a smaller mesh);
        # otherwise apply_rules demotes K/V to replicated, which stays
        # CORRECT (GSPMD inserts the resharding) but costs the
        # one-allreduce-per-sublayer property. With GQA the K/V projections
        # shrink to kv_heads·head_dim — fewer KV parameters and a
        # kv_heads-sized cache at inference.
        kq, kk, kv, ko = jax.random.split(key, 4)
        head_dim = self.embed_dim // self.num_heads
        proj = Dense(self.embed_dim, self.embed_dim, dtype=self.dtype)
        kv_proj = Dense(self.embed_dim, self._kv_heads * head_dim, dtype=self.dtype)
        return {
            "q": proj.init(kq)[0],
            "k": kv_proj.init(kk)[0],
            "v": kv_proj.init(kv)[0],
            "out": proj.init(ko)[0],
        }, {}

    def _heads(self, x, n_heads):
        b, t, _ = x.shape
        return x.reshape(b, t, n_heads, self.embed_dim // self.num_heads)

    def apply(self, params, state, x, *, train=False, rng=None):
        b, t, _ = x.shape
        q = self._heads(x @ params["q"]["kernel"] + params["q"]["bias"], self.num_heads)
        k, v = (
            self._heads(
                x @ params[n]["kernel"] + params[n]["bias"], self._kv_heads
            )
            for n in ("k", "v")
        )
        if self.rope:
            # Before the GQA repeat: rotating the kv_heads-wide tensor does
            # group× less work and repeating rotated heads is identical.
            positions = sharded_positions(
                self.axis_name, t, self.seq_sharded, self.seq_layout
            )
            q = rotary_embedding(q, positions, self.rope_base)
            k = rotary_embedding(k, positions, self.rope_base)
        if self._kv_heads != self.num_heads:
            # Broadcast each KV group across its query heads; the attention
            # ops then see ordinary per-head tensors (GQA's savings are in
            # parameters and the inference KV cache, not this training op).
            group = self.num_heads // self._kv_heads
            k, v = (jnp.repeat(a, group, axis=2) for a in (k, v))
        if self.impl == "full":
            o = dot_product_attention(q, k, v, causal=self.causal)
        elif self.impl == "flash":
            from tpudml.ops import flash_attention

            o = flash_attention(q, k, v, causal=self.causal)
        elif self.impl == "ring":
            from tpudml.parallel.cp import ring_attention

            o = ring_attention(
                q, k, v, self.axis_name, causal=self.causal, remat=self.remat,
                layout=self.seq_layout,
            )
        elif self.impl == "ulysses":
            from tpudml.parallel.cp import ulysses_attention

            o = ulysses_attention(q, k, v, self.axis_name, causal=self.causal)
        else:
            raise ValueError(f"unknown attention impl {self.impl!r}")
        o = o.reshape(b, t, self.embed_dim)
        return o @ params["out"]["kernel"] + params["out"]["bias"], state
