"""Attention ops and the multi-head attention module.

The reference has no attention anywhere (models are a 28×28 CNN and an
MLP; SURVEY.md §5.7) — but long-context support is first-class in this
framework, so attention is built TPU-first from the start:

- layout [B, T, H, D] with the contraction kept as two einsums that XLA
  maps straight onto the MXU;
- optional causal masking by *global* position offsets, so the same code
  is correct when the sequence axis is sharded across devices (ring /
  Ulysses context parallelism in ``tpudml.parallel.cp``);
- the module's ``impl`` field selects full, flash (Pallas kernel), ring,
  or Ulysses attention, letting one model definition run single-chip or
  sequence-sharded.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from tpudml.comm.collectives import axis_size
from tpudml.nn.layers import Dense, Module

NEG_INF = -1e30  # large-finite mask value: avoids inf-inf → NaN in softmax


def sharded_positions(
    axis_name: str, t_local: int, seq_sharded: bool, seq_layout: str
) -> jax.Array:
    """GLOBAL token positions of this device's [t_local] sequence shard —
    the ONE definition RoPE, the position table, and the ring masks all
    derive from (a divergence between them is silent model corruption):
    contiguous → idx·Tl + j; striped → idx + W·j; unsharded → j."""
    if not seq_sharded:
        return jnp.arange(t_local)
    if seq_layout == "striped":
        world = axis_size(axis_name)
        return jax.lax.axis_index(axis_name) + world * jnp.arange(t_local)
    return jax.lax.axis_index(axis_name) * t_local + jnp.arange(t_local)


def rotary_embedding(
    x: jax.Array, positions: jax.Array, base: float = 10000.0
) -> jax.Array:
    """Rotary position embedding (RoPE) over [B, T, H, D_head].

    ``positions`` are GLOBAL token positions [T] — under a sharded
    sequence axis each device passes its shard's offset positions, and
    because RoPE encodes relative position in the q·k phase difference,
    ring/Ulysses attention then needs no further position handling.
    A [B, T] ``positions`` gives each batch row its own positions — the
    continuous-batching decode regime, where every cache slot sits at
    its own depth.
    """
    d = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(d, dtype=jnp.float32) / d)  # [d]
    # [T, d] or [B, T, d]; the batch dim (if any) then aligns with x's.
    angles = positions.astype(jnp.float32)[..., :, None] * freqs
    cos = jnp.cos(angles)[..., :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[..., :, None, :].astype(x.dtype)
    if positions.ndim == 1:
        cos, sin = cos[None], sin[None]
    x1, x2 = x[..., :d], x[..., d:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
) -> jax.Array:
    """Scaled dot-product attention over [B, T, H, D] tensors.

    ``q_offset``/``k_offset`` are the global positions of q[:,0] and
    k[:,0]: with a sharded sequence axis each device passes its shard's
    offset and the causal mask stays globally correct.
    """
    d = q.shape[-1]
    # Scores + softmax in float32 regardless of input dtype (bf16 exp/sum
    # loses mass at long T); the PV contraction runs in the value dtype so
    # the MXU still sees bf16 operands on the bf16 path.
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def decode_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, pos: jax.Array
) -> jax.Array:
    """Single-token decode attention: q [B, 1, H, D] over a full cache
    k/v [B, L, H, D] with per-slot current positions ``pos`` [B].

    The mask ``k_pos <= pos[b]`` replaces the causal triangle: each slot
    attends exactly its own written prefix (the current token's K/V are
    written at ``pos`` BEFORE this call), and unwritten cache rows are
    excluded the same way future tokens are in training — NEG_INF before
    the f32 softmax, so they carry exactly zero weight and the valid
    rows produce the same statistics as the training kernel's masked
    row. O(L) per emitted token; the O(T²) training kernels never run."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    mask = jnp.arange(k.shape[1])[None, :] <= pos[:, None]  # [B, L]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def decode_attention_window(
    q: jax.Array, k: jax.Array, v: jax.Array, pos: jax.Array
) -> jax.Array:
    """Multi-token decode attention: q [B, Q, H, D] — Q consecutive
    tokens per slot, the first at per-slot position ``pos`` [B] — over a
    full cache k/v [B, L, H, D]. The speculative-decoding verify window
    (Q = K+1) and the paged decode step both land here; Q = 1 reduces
    exactly to :func:`decode_attention`.

    Query j (global position pos+j) masks ``k_pos <= pos[b] + j``: its
    own row plus the committed prefix plus the earlier window rows —
    all written before this call — and NOTHING else. Rows the mask
    excludes may hold stale K/V from an evicted request; NEG_INF before
    the f32 softmax gives them exactly zero weight, so they never need
    zeroing."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q_pos = pos[:, None] + jnp.arange(q.shape[1])[None, :]  # [B, Q]
    mask = jnp.arange(k.shape[1])[None, None, :] <= q_pos[:, :, None]
    s = jnp.where(mask[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _chunk_flash_window(
    q: jax.Array, k: jax.Array, v: jax.Array, start: int
) -> jax.Array:
    """Prefill-chunk attention on TPU via the flash kernel: q [B, C, H, D]
    at global offset ``start`` over the window k/v [B, start+C, H, D]
    (``start`` static, a multiple of C).

    The flash kernels fold K/V at the QUERY length, so the window runs as
    ``start/C + 1`` equal-length block calls — every block below the
    chunk is fully visible (causal=False), the diagonal block masks
    locally — merged with the same online log-sum-exp combination the
    ring forward uses. Identical work to one causal flash over the
    window; no O(T²) recompute of earlier chunks."""
    from tpudml.ops.attention_kernel import flash_forward_lse

    b, c, h, d = q.shape
    n = start // c + 1
    num = jnp.zeros((b, c, h, d), jnp.float32)
    m = jnp.full((b, h, c), NEG_INF, jnp.float32)
    den = jnp.zeros((b, h, c), jnp.float32)
    for j in range(n):
        kb = k[:, j * c:(j + 1) * c]
        vb = v[:, j * c:(j + 1) * c]
        o_b, lse_b = flash_forward_lse(q, kb, vb, causal=(j == n - 1))
        m_new = jnp.maximum(m, lse_b)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(lse_b - m_new)
        num = (
            num * c_old.transpose(0, 2, 1)[..., None]
            + o_b * c_new.transpose(0, 2, 1)[..., None]
        )
        den = den * c_old + c_new
        m = m_new
    return (num / den.transpose(0, 2, 1)[..., None]).astype(q.dtype)


@dataclass(frozen=True)
class MultiHeadAttention(Module):
    """Self-attention with separate head-aligned q/k/v projections (TP
    shards each kernel's output dim without in-layer resharding).

    ``impl``: "full" (one-device softmax(QKᵀ)V), "flash" (Pallas fused
    kernel on TPU, reference math elsewhere — tpudml.ops), "ring"
    (sequence sharded over ``axis_name``, K/V blocks rotated over the ring
    — must run under shard_map), or "ulysses" (all-to-all head↔sequence
    transpose — heads must divide the axis size).
    """

    embed_dim: int
    num_heads: int
    causal: bool = False
    impl: str = "full"
    axis_name: str = "seq"
    # Accepted for API compatibility; the ring custom-VJP backward always
    # recomputes per-block (flash-style), so rematerialization is implied.
    remat: bool = False
    num_kv_heads: int | None = None  # GQA/MQA: K/V head groups (< num_heads)
    rope: bool = False  # rotary position embeddings on q/k
    rope_base: float = 10000.0
    seq_sharded: bool = False  # rope offsets from axis_name when sharded
    # Sharded-sequence token layout: "contiguous" (device i owns
    # [i·Tl, (i+1)·Tl)) or "striped" (device i owns {t : t mod W == i} —
    # the balanced causal-ring layout; positions become idx + W·j).
    seq_layout: str = "contiguous"
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.embed_dim % self.num_heads:
            raise ValueError(
                f"embed_dim {self.embed_dim} % num_heads {self.num_heads} != 0"
            )
        kv = self.num_kv_heads
        if kv is not None and (kv < 1 or self.num_heads % kv):
            raise ValueError(
                f"num_kv_heads {kv} must divide num_heads {self.num_heads}"
            )
        if self.seq_layout not in ("contiguous", "striped"):
            raise ValueError(f"unknown seq_layout {self.seq_layout!r}")
        if self.seq_layout == "striped" and self.impl != "ring":
            # Ulysses/full gather shards in device order — under striping
            # that is a PERMUTED sequence, so their causal masks would
            # silently let tokens attend the future. Only the ring fold
            # understands striped positions.
            raise ValueError(
                f"seq_layout='striped' requires impl='ring', got {self.impl!r}"
            )
        if self.rope and (self.embed_dim // self.num_heads) % 2:
            # RoPE rotates feature PAIRS; an odd head_dim would silently
            # broadcast to the wrong width instead of erroring later.
            raise ValueError(
                f"rope requires an even head_dim, got "
                f"{self.embed_dim // self.num_heads}"
            )

    @property
    def _kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def init(self, key):
        # Separate q/k/v projections (not a fused [d, 3d] kernel): shards of
        # each kernel's output dim stay head-aligned under tensor
        # parallelism, so Megatron-style column sharding needs no in-layer
        # resharding for any mesh size dividing num_heads — for the K/V
        # kernels under GQA that bound is num_kv_heads (a smaller mesh);
        # otherwise apply_rules demotes K/V to replicated, which stays
        # CORRECT (GSPMD inserts the resharding) but costs the
        # one-allreduce-per-sublayer property. With GQA the K/V projections
        # shrink to kv_heads·head_dim — fewer KV parameters and a
        # kv_heads-sized cache at inference.
        kq, kk, kv, ko = jax.random.split(key, 4)
        head_dim = self.embed_dim // self.num_heads
        proj = Dense(self.embed_dim, self.embed_dim, dtype=self.dtype)
        kv_proj = Dense(self.embed_dim, self._kv_heads * head_dim, dtype=self.dtype)
        return {
            "q": proj.init(kq)[0],
            "k": kv_proj.init(kk)[0],
            "v": kv_proj.init(kv)[0],
            "out": proj.init(ko)[0],
        }, {}

    def _heads(self, x, n_heads):
        b, t, _ = x.shape
        return x.reshape(b, t, n_heads, self.embed_dim // self.num_heads)

    def apply(self, params, state, x, *, train=False, rng=None):
        b, t, _ = x.shape
        q = self._heads(x @ params["q"]["kernel"] + params["q"]["bias"], self.num_heads)
        k, v = (
            self._heads(
                x @ params[n]["kernel"] + params[n]["bias"], self._kv_heads
            )
            for n in ("k", "v")
        )
        if self.rope:
            # Before the GQA repeat: rotating the kv_heads-wide tensor does
            # group× less work and repeating rotated heads is identical.
            positions = sharded_positions(
                self.axis_name, t, self.seq_sharded, self.seq_layout
            )
            q = rotary_embedding(q, positions, self.rope_base)
            k = rotary_embedding(k, positions, self.rope_base)
        if self._kv_heads != self.num_heads:
            # Broadcast each KV group across its query heads; the attention
            # ops then see ordinary per-head tensors (GQA's savings are in
            # parameters and the inference KV cache, not this training op).
            group = self.num_heads // self._kv_heads
            k, v = (jnp.repeat(a, group, axis=2) for a in (k, v))
        if self.impl == "full":
            o = dot_product_attention(q, k, v, causal=self.causal)
        elif self.impl == "flash":
            from tpudml.ops import flash_attention

            o = flash_attention(q, k, v, causal=self.causal)
        elif self.impl == "ring":
            from tpudml.parallel.cp import ring_attention

            o = ring_attention(
                q, k, v, self.axis_name, causal=self.causal, remat=self.remat,
                layout=self.seq_layout,
            )
        elif self.impl == "ulysses":
            from tpudml.parallel.cp import ulysses_attention

            o = ulysses_attention(q, k, v, self.axis_name, causal=self.causal)
        else:
            raise ValueError(f"unknown attention impl {self.impl!r}")
        o = o.reshape(b, t, self.embed_dim)
        return o @ params["out"]["kernel"] + params["out"]["bias"], state

    # ----------------------------------------------------- serving paths
    # Incremental decode + chunked prefill over a tpudml.serve KVCache.
    # Same projections/RoPE/GQA-repeat/softmax math as apply() — the
    # greedy-decode parity tests pin logit-exactness against it — but
    # attention reads K/V from the cache instead of recomputing them, so
    # one emitted token costs O(L) instead of the O(T²) training kernel.

    def _serve_guard(self):
        if self.impl not in ("full", "flash"):
            raise ValueError(
                f"serve decode supports impl='full'/'flash' attention "
                f"configs, not {self.impl!r} (ring/ulysses shard the "
                f"sequence axis, which a per-slot cache does not)"
            )
        if self.seq_sharded:
            raise ValueError("serve decode requires seq_sharded=False")

    def _project(self, params, x, n_local_heads=None, n_local_kv=None):
        """(q, k, v) head tensors for x [B, T, d]. Local head counts are
        overridable so the TP decode step can run the same code on a
        head-sharded parameter shard."""
        q = self._heads(
            x @ params["q"]["kernel"] + params["q"]["bias"],
            n_local_heads or self.num_heads,
        )
        k, v = (
            self._heads(
                x @ params[n]["kernel"] + params[n]["bias"],
                n_local_kv or self._kv_heads,
            )
            for n in ("k", "v")
        )
        return q, k, v

    def _gqa_repeat(self, k, v, n_heads):
        group = n_heads // k.shape[2]
        if group > 1:
            k, v = (jnp.repeat(a, group, axis=2) for a in (k, v))
        return k, v

    def apply_decode(self, params, cache, x, pos):
        """One decode step: x [B, 1, d] (the current token's features),
        ``pos`` [B] its per-slot position. Writes this token's K/V into
        the cache at ``pos``, attends q over the cached prefix, returns
        (out [B, 1, d], updated cache)."""
        from tpudml.serve.cache import read_all, write_token

        self._serve_guard()
        b = x.shape[0]
        q, k_new, v_new = self._project(params, x)
        if self.rope:
            q = rotary_embedding(q, pos[:, None], self.rope_base)
            k_new = rotary_embedding(k_new, pos[:, None], self.rope_base)
        cache = write_token(cache, k_new, v_new, pos)
        k, v = read_all(cache, x.dtype)
        k, v = self._gqa_repeat(k, v, self.num_heads)
        o = decode_attention(q, k, v, pos).reshape(b, 1, self.embed_dim)
        return o @ params["out"]["kernel"] + params["out"]["bias"], cache

    def apply_decode_window(self, params, cache, x, pos):
        """Decode a window of Q consecutive tokens per slot: x [B, Q, d]
        at positions pos..pos+Q-1 (the speculative verify window).
        Writes all Q rows' K/V, attends each window query over prefix +
        earlier window rows, returns (out [B, Q, d], updated cache).
        Rows past the committed count are overwritten by a later window
        before any unmasked read — the same stale-row invariant the
        single-token path relies on."""
        from tpudml.serve.cache import read_all, write_token

        self._serve_guard()
        b, qlen = x.shape[:2]
        q, k_new, v_new = self._project(params, x)
        if self.rope:
            positions = pos[:, None] + jnp.arange(qlen)[None, :]  # [B, Q]
            q = rotary_embedding(q, positions, self.rope_base)
            k_new = rotary_embedding(k_new, positions, self.rope_base)
        cache = write_token(cache, k_new, v_new, pos)
        k, v = read_all(cache, x.dtype)
        k, v = self._gqa_repeat(k, v, self.num_heads)
        o = decode_attention_window(q, k, v, pos)
        o = o.reshape(b, qlen, self.embed_dim)
        return o @ params["out"]["kernel"] + params["out"]["bias"], cache

    def apply_decode_paged(self, params, pool, table, x, pos):
        """Decode step over a paged pool: x [B, Q, d] (Q=1 plain decode,
        Q=K+1 spec verify), ``table`` [B, max_pages] each slot's page
        map, ``pos`` [B]. Same math as apply_decode/apply_decode_window
        — the gathered table window puts identical values at identical
        flat positions, and masked rows carry zero weight — so greedy
        parity vs the dense cache holds bit-for-bit in practice. Returns
        (out [B, Q, d], updated pool)."""
        from tpudml.serve.paged import read_table, write_tokens

        self._serve_guard()
        b, qlen = x.shape[:2]
        q, k_new, v_new = self._project(params, x)
        if self.rope:
            positions = pos[:, None] + jnp.arange(qlen)[None, :]
            q = rotary_embedding(q, positions, self.rope_base)
            k_new = rotary_embedding(k_new, positions, self.rope_base)
        pool = write_tokens(pool, k_new, v_new, table, pos)
        k, v = read_table(pool, table, x.dtype)
        k, v = self._gqa_repeat(k, v, self.num_heads)
        o = decode_attention_window(q, k, v, pos)
        o = o.reshape(b, qlen, self.embed_dim)
        return o @ params["out"]["kernel"] + params["out"]["bias"], pool

    def apply_prefill_paged(self, params, pool, table_row, x, start: int):
        """Prefill one chunk of the slot owning ``table_row``
        [max_pages]: x [1, C, d] at global positions [start, start+C).
        Mirrors apply_prefill over the paged pool; ``start`` static."""
        from tpudml.serve.paged import read_row_prefix, write_chunk

        self._serve_guard()
        c = x.shape[1]
        q, k_new, v_new = self._project(params, x)
        if self.rope:
            positions = start + jnp.arange(c)
            q = rotary_embedding(q, positions, self.rope_base)
            k_new = rotary_embedding(k_new, positions, self.rope_base)
        pool = write_chunk(pool, k_new, v_new, table_row, start)
        k, v = read_row_prefix(pool, table_row, start + c, x.dtype)
        k, v = self._gqa_repeat(k, v, self.num_heads)
        if jax.default_backend() == "tpu":
            o = _chunk_flash_window(q, k, v, start)
        else:
            o = dot_product_attention(q, k, v, causal=True, q_offset=start)
        o = o.reshape(1, c, self.embed_dim)
        return o @ params["out"]["kernel"] + params["out"]["bias"], pool

    def apply_prefill(self, params, cache, x, slot, start: int):
        """Prefill one chunk of one slot: x [1, C, d] are features of
        prompt tokens at global positions [start, start+C). Writes their
        K/V, attends the chunk over the slot's [0, start+C) window with
        the globally-offset causal mask, returns (out [1, C, d], updated
        cache). ``start`` is STATIC — one compiled program per chunk
        index, shared across slots/requests. On TPU the window attention
        reuses the flash kernel (``k_shift`` moves the causal diagonal
        to the chunk's global offset)."""
        from tpudml.serve.cache import read_slot_prefix, write_chunk

        self._serve_guard()
        c = x.shape[1]
        q, k_new, v_new = self._project(params, x)
        if self.rope:
            positions = start + jnp.arange(c)
            q = rotary_embedding(q, positions, self.rope_base)
            k_new = rotary_embedding(k_new, positions, self.rope_base)
        cache = write_chunk(cache, k_new, v_new, slot, start)
        k, v = read_slot_prefix(cache, slot, start + c, x.dtype)
        k, v = self._gqa_repeat(k, v, self.num_heads)
        if jax.default_backend() == "tpu":
            o = _chunk_flash_window(q, k, v, start)
        else:
            o = dot_product_attention(q, k, v, causal=True, q_offset=start)
        o = o.reshape(1, c, self.embed_dim)
        return o @ params["out"]["kernel"] + params["out"]["bias"], cache
