"""Mixture-of-Experts layer with expert parallelism.

Absent from the reference (SURVEY.md §2.3 lists EP/MoE as out of parity
scope), built here to complete the parallelism matrix. TPU-first design:

- top-k routing (k=1 Switch, k>1 GShard) with a static per-shard expert
  capacity C, so every shape is fixed under jit;
- dispatch/combine are static-shape ROW GATHERS over a flat slot index
  (default ``dispatch="gather"``): the choice-priority cumsum assigns each
  (token, choice) a flat slot in [0, E·C) (sentinel when capacity-dropped),
  dispatch gathers token rows into [E, C, d], combine gathers each token's
  k expert outputs back, gate-weighted. The slot map is injective, so both
  backwards are the INVERSE gather (custom VJPs — no row scatter-adds, no
  [G, E, C] one-hot buffers, no O(G·E·C·d) einsum FLOPs). The GShard
  one-hot einsum formulation survives as ``dispatch="einsum"``, the parity
  oracle: both paths consume the identical slot assignment. Measured on a
  v5e (tools/moe_perf.py): the einsum dispatch cost ~1.9-2.5× dense at
  matched active FLOPs; gather removes that overhead (recording in
  BASELINE.md round 5). Tokens past capacity are dropped (combine weight
  0), the standard Switch trade;
- under expert parallelism (``axis_name`` set, run inside shard_map),
  tokens AND experts are sharded over the same mesh axis: each shard
  routes its local tokens, one ``all_to_all`` ships the [E, C, d] dispatch
  to the owning experts, the local expert FFNs run, and the inverse
  ``all_to_all`` returns outputs to the token owners. Communication is two
  all_to_alls of C·d per expert — never the full activations.

Routing gradients flow through the combine gate (straight-through on the
argmax path); an auxiliary load-balancing loss is exposed via
:func:`load_balancing_loss` for callers that want Switch-style balance
pressure in their objective.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpudml.nn.layers import Module, _uniform_fan_in
from tpudml.ops.moe_kernel import ragged_ffn


def _pad0(rows):
    """Append one zero row — the landing pad for sentinel indices."""
    return jnp.concatenate([rows, jnp.zeros((1, rows.shape[-1]), rows.dtype)], 0)


def _switch_aux(frac, probs, num_experts):
    """Switch/GShard load-balance loss E · Σ_e frac_e · p̄_e (=1 uniform).
    ``frac`` is the per-expert dispatch fraction averaged over all k
    choices — shared by every dispatch branch so the formulation cannot
    silently diverge between them."""
    return num_experts * jnp.sum(frac * jnp.mean(probs, axis=0))


@jax.custom_vjp
def _permute_rows(tokens_pad, token_src, flat_dst):
    """Dispatch gather: out[s] = tokens_pad[token_src[s]] for every expert
    slot s (``token_src`` sentinel = G hits the appended zero row).

    The slot assignment is INJECTIVE — each slot holds at most one
    (token, choice) and each (token, choice) owns at most one slot — so
    the backward is the inverse gather over ``flat_dst`` [G, k] (sentinel
    = S), never a scatter-add of [*, d] rows (the op autodiff would emit
    for ``take``, which serializes on TPU — the same finding that moved
    the embedding backward to an MXU matmul in round 4)."""
    return jnp.take(tokens_pad, token_src, axis=0)


def _permute_rows_fwd(tokens_pad, token_src, flat_dst):
    return _permute_rows(tokens_pad, token_src, flat_dst), (
        flat_dst,
        tokens_pad.shape[0],
    )


def _permute_rows_bwd(res, dy):
    flat_dst, n_pad = res
    # dTokens[g] = Σ_j dy[flat_dst[g, j]]; sentinel rides the zero row.
    d_tok = jnp.sum(jnp.take(_pad0(dy), flat_dst, axis=0), axis=1)
    d_pad = jnp.zeros((n_pad - d_tok.shape[0], dy.shape[-1]), dy.dtype)
    return jnp.concatenate([d_tok, d_pad], 0), None, None


_permute_rows.defvjp(_permute_rows_fwd, _permute_rows_bwd)


@jax.custom_vjp
def _combine_rows(expert_flat, w, flat_dst, token_src):
    """Combine gather: y[g] = Σ_j w[g, j] · expert_flat[flat_dst[g, j]]
    (gate-weighted return of each token's k expert outputs; dropped
    choices carry w = 0 and a sentinel index onto the zero row).

    Backward wrt ``expert_flat`` is again the inverse gather — slot s's
    cotangent is w_at_slot[s] · dy[token_src[s]] — computed via a [S]
    scalar scatter of the gate values (tiny) plus one row gather."""
    rows = jnp.take(_pad0(expert_flat), flat_dst, axis=0)  # [G, k, d]
    return jnp.einsum("gk,gkd->gd", w, rows.astype(w.dtype))


def _combine_rows_fwd(expert_flat, w, flat_dst, token_src):
    return _combine_rows(expert_flat, w, flat_dst, token_src), (
        expert_flat,
        w,
        flat_dst,
        token_src,
    )


def _combine_rows_bwd(res, dy):
    expert_flat, w, flat_dst, token_src = res
    s_total = expert_flat.shape[0]
    # Re-gather the rows (cheaper than holding [G, k, d] as a residual).
    rows = jnp.take(_pad0(expert_flat), flat_dst, axis=0)
    dw = jnp.einsum("gd,gkd->gk", dy, rows.astype(dy.dtype)).astype(w.dtype)
    # Gate value seen by each slot: a [S]-scalar scatter (collisions only
    # on the sliced-off sentinel row).
    w_src = (
        jnp.zeros((s_total + 1,), w.dtype)
        .at[flat_dst.reshape(-1)]
        .set(w.reshape(-1))[:s_total]
    )
    dy_tok = jnp.take(_pad0(dy), token_src, axis=0)  # [S, d]
    d_expert = (w_src[:, None] * dy_tok).astype(expert_flat.dtype)
    return d_expert, dw, None, None


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


@dataclass(frozen=True)
class MoELayer(Module):
    """Top-k mixture-of-experts FFN over [..., embed_dim] inputs.

    ``top_k=1`` is the Switch formulation (raw top-1 probability as the
    gate); ``top_k>1`` is GShard-style — each token dispatches to its k
    best experts with gates renormalized over the chosen k, capacity
    scaled by k, and choice 0 taking buffer priority over choice 1 (a
    token's secondary pick is dropped first under overflow).

    ``axis_name=None``: single-shard dense routing. ``axis_name="expert"``:
    expert-parallel — must run under shard_map with tokens sharded over the
    axis and ``num_experts`` divisible by the axis size.
    """

    embed_dim: int
    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    top_k: int = 1
    axis_name: str | None = None
    dtype: Any = jnp.float32
    # "gather": slot-index dispatch/combine via row gathers with
    # inverse-gather backwards — O(S·d) data movement, no O(G·E·C·d)
    # FLOPs and no [G, E, C] buffers. "einsum": the GShard one-hot
    # formulation, kept as the parity oracle (identical routing by
    # construction — both consume the same flat_dst slot assignment).
    # "ragged": DROPLESS — tokens sorted by expert feed lax.ragged_dot
    # grouped matmuls; no capacity, no drops, no padded slots (single-
    # shard only: EP's all_to_all needs the static capacity buffers).
    dispatch: str = "gather"
    # Backward for the ragged FFN's weight gradients. "grouped" routes
    # dW1/dW2 through ops.moe_kernel.ragged_ffn (Pallas grouped-dW on
    # TPU, reference segment-einsum elsewhere — cost ∝ tokens).
    # "stock" keeps lax.ragged_dot's own transpose (an E-scaled masked
    # matmul — the 3.4× backward of BASELINE round 5) for A/B runs;
    # the analyzer flags it as J109.
    ragged_dw: str = "grouped"

    def __post_init__(self):
        if not 1 <= self.top_k <= self.num_experts:
            raise ValueError(
                f"top_k {self.top_k} must be in [1, num_experts={self.num_experts}]"
            )
        if self.dispatch not in ("gather", "einsum", "ragged"):
            raise ValueError(
                f"dispatch must be 'gather', 'einsum', or 'ragged', got {self.dispatch!r}"
            )
        if self.ragged_dw not in ("grouped", "stock"):
            raise ValueError(
                f"ragged_dw must be 'grouped' or 'stock', got {self.ragged_dw!r}"
            )
        if self.dispatch == "ragged" and self.axis_name is not None:
            raise ValueError(
                "dispatch='ragged' is single-shard only — expert parallelism "
                "ships static [E, C, d] capacity buffers over all_to_all, "
                "which the dropless path deliberately does not build; use "
                "dispatch='gather' under EP"
            )

    def init(self, key):
        d, e, h = self.embed_dim, self.num_experts, self.mlp_ratio * self.embed_dim
        kr, k1, kb1, k2, kb2 = jax.random.split(key, 5)
        params = {
            "router": {"kernel": _uniform_fan_in(kr, (d, e), d, self.dtype)},
            "experts": {
                "w1": _uniform_fan_in(k1, (e, d, h), d, self.dtype),
                "b1": _uniform_fan_in(kb1, (e, h), d, self.dtype),
                "w2": _uniform_fan_in(k2, (e, h, d), h, self.dtype),
                "b2": _uniform_fan_in(kb2, (e, d), h, self.dtype),
            },
        }
        # aux_loss lives in state from init so the TrainState pytree
        # structure is stable across steps; make_loss_fn(aux_loss_weight=α)
        # folds it into the objective (gradients flow to the router).
        return params, {"aux_loss": jnp.zeros((), jnp.float32)}

    def _capacity(self, n_tokens: int) -> int:
        return max(
            1,
            int(n_tokens * self.top_k * self.capacity_factor / self.num_experts + 0.5),
        )

    def apply(self, params, state, x, *, train=False, rng=None):
        shape = x.shape
        d, e = self.embed_dim, self.num_experts
        g = 1
        for s in shape[:-1]:
            g *= s
        tokens = x.reshape(g, d)
        cap = self._capacity(g)

        logits = tokens @ params["router"]["kernel"]  # [G, E]
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = lax.top_k(probs, self.top_k)  # [G, k]
        if self.top_k == 1:
            gates = topv  # Switch: the raw top-1 probability
        else:
            gates = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)

        if self.dispatch == "ragged":
            y = self._ragged_ffn(params["experts"], tokens, topi, gates)
            frac = jnp.mean(
                jnp.sum(jax.nn.one_hot(topi, e, dtype=jnp.float32), axis=1), axis=0
            ) / self.top_k
            return y.reshape(shape), {"aux_loss": _switch_aux(frac, probs, e)}

        # Choice-priority slot assignment: choice 0 claims buffer slots for
        # ALL tokens before choice 1 sees the remaining capacity (k static
        # and small, so the Python loop unrolls). Bookkeeping stays float32
        # regardless of the token dtype — bf16 represents integers exactly
        # only to 256, so a bf16 cumsum would corrupt capacity positions on
        # any real batch. Output: flat_dst [G, k] — each (token, choice)'s
        # flat slot id e·cap + slot, sentinel S = E·cap when dropped.
        s_total = e * cap
        counts = jnp.zeros((e,), jnp.float32)  # slots used per expert
        choice_sum = jnp.zeros((g, e), jnp.float32)  # Σ_j onehot_j per token
        flat_dst = []
        kept_flags = []
        for j in range(self.top_k):
            onehot = jax.nn.one_hot(topi[:, j], e, dtype=jnp.float32)  # [G, E]
            choice_sum = choice_sum + onehot
            pos = counts[None, :] + jnp.cumsum(onehot, axis=0) - onehot  # [G, E]
            kept = onehot * (pos < cap)
            slot = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
            kept_g = jnp.sum(kept, axis=-1)  # [G] ∈ {0, 1}
            flat_dst.append(
                jnp.where(kept_g > 0, topi[:, j] * cap + slot, s_total).astype(
                    jnp.int32
                )
            )
            kept_flags.append(kept_g)
            counts = counts + jnp.sum(kept, axis=0)
        flat_dst = jnp.stack(flat_dst, axis=1)  # [G, k]
        w_eff = gates * jnp.stack(kept_flags, axis=1).astype(gates.dtype)  # [G, k]

        if self.dispatch == "gather":
            # Invert the injective (token, choice) → slot map with a [G·k]
            # int32 scatter (tiny; collisions land only on the sentinel
            # row, which the slice drops), then dispatch = one row gather.
            token_src = (
                jnp.full((s_total + 1,), g, jnp.int32)
                .at[flat_dst.reshape(-1)]
                .set(jnp.repeat(jnp.arange(g, dtype=jnp.int32), self.top_k))[:s_total]
            )
            expert_in = _permute_rows(_pad0(tokens), token_src, flat_dst).reshape(
                e, cap, d
            )
        else:
            # GShard one-hot materialization of the SAME slot assignment:
            # [G, k, S] one-hots reduce to the classic [G, E, C] dispatch /
            # combine tensors (O(G·E·C·d) einsum FLOPs — the parity oracle).
            oh = jax.nn.one_hot(flat_dst, s_total + 1, dtype=jnp.float32)[
                :, :, :s_total
            ]
            disp = jnp.sum(oh, axis=1).reshape(g, e, cap)
            combine = jnp.einsum("gks,gk->gs", oh, w_eff).reshape(g, e, cap)
            expert_in = jnp.einsum(
                "gec,gd->ecd", disp.astype(tokens.dtype), tokens
            )  # [E, C, d]
        ep = self.axis_name is not None
        if ep:
            # Ship each expert's buffer to its owning shard: [E, C, d] →
            # [E/W, W·C, d] (and back after the FFN).
            expert_in = lax.all_to_all(
                expert_in, self.axis_name, split_axis=0, concat_axis=1, tiled=True
            )
        w = params["experts"]
        hidden = jax.nn.relu(
            jnp.einsum("ecd,edh->ech", expert_in, w["w1"]) + w["b1"][:, None, :]
        )
        expert_out = (
            jnp.einsum("ech,ehd->ecd", hidden, w["w2"]) + w["b2"][:, None, :]
        )
        if ep:
            expert_out = lax.all_to_all(
                expert_out, self.axis_name, split_axis=1, concat_axis=0, tiled=True
            )
        if self.dispatch == "gather":
            y = _combine_rows(
                expert_out.reshape(s_total, d), w_eff, flat_dst, token_src
            ).astype(tokens.dtype)
        else:
            y = jnp.einsum(
                "gec,ecd->gd", combine.astype(expert_out.dtype), expert_out
            )
        # Aux loss over this shard's tokens, frac averaged over ALL k
        # choices (first-choice-only frac — ADVICE r2 — would leave
        # secondary-choice expert collapse invisible); differentiable
        # through probs.
        frac = jnp.mean(choice_sum, axis=0) / self.top_k
        return y.reshape(shape), {"aux_loss": _switch_aux(frac, probs, e)}

    def _ragged_ffn(self, w, tokens, topi, gates):
        """Dropless grouped-matmul expert FFN (``dispatch="ragged"``).

        (token, choice) pairs are sorted by expert id; ``lax.ragged_dot``
        runs each expert's contiguous row block through its weights — no
        capacity buffers, no dropped tokens, no padded slots computing on
        zeros. The sort permutation is injective and total, so both the
        dispatch and the un-sort are `_permute_rows` gathers (backwards are
        the inverse gathers). Biases ride a [P, E] one-hot MATMUL rather
        than a row gather, so their backward is an MXU matmul instead of a
        scatter-add onto [E, ·] rows.
        """
        g, d = tokens.shape
        e, k = self.num_experts, self.top_k
        p = g * k  # (token, choice) pairs
        eids = topi.reshape(p)  # pair -> expert, pair id = g·k + j
        # Stable argsort keeps same-expert pairs in token order.
        order = jnp.argsort(eids)  # [P] sorted position -> pair id
        inv = (
            jnp.zeros((p,), jnp.int32)
            .at[order]
            .set(jnp.arange(p, dtype=jnp.int32))
        )  # pair id -> sorted position
        group_sizes = jnp.bincount(eids, length=e).astype(jnp.int32)

        token_src = (order // k).astype(jnp.int32)  # sorted position -> token
        flat_dst = inv.reshape(g, k)  # token -> its k sorted positions
        x_sorted = _permute_rows(_pad0(tokens), token_src, flat_dst)  # [P, d]

        # ragged_dot wants matching operand dtypes; promote like einsum would.
        ct = jnp.promote_types(x_sorted.dtype, w["w1"].dtype)
        onehot = jax.nn.one_hot(eids[order], e, dtype=ct)  # [P, E]
        if self.ragged_dw == "grouped":
            # custom_vjp FFN: dW1/dW2 via the grouped-dW kernel (one row
            # walk, f32 accumulation) instead of ragged_dot's E-scaled
            # masked-matmul transpose; dx/dh stay ragged_dot forward-form.
            out_sorted = ragged_ffn(
                x_sorted.astype(ct),
                w["w1"].astype(ct),
                w["b1"].astype(ct),
                w["w2"].astype(ct),
                w["b2"].astype(ct),
                onehot,
                group_sizes,
            )
        else:  # "stock": lax.ragged_dot's own transpose, kept for A/B.
            hidden = jax.nn.relu(
                lax.ragged_dot(x_sorted.astype(ct), w["w1"].astype(ct), group_sizes)
                + onehot @ w["b1"].astype(ct)
            )
            out_sorted = lax.ragged_dot(
                hidden, w["w2"].astype(ct), group_sizes
            ) + onehot @ w["b2"].astype(ct)
        # Gate-weighted un-sort: the same injective-map combine as the
        # gather dispatch, with every choice kept (w_eff = gates).
        return _combine_rows(out_sorted, gates, flat_dst, token_src).astype(
            tokens.dtype
        )


def load_balancing_loss(params: dict, x: jax.Array, num_experts: int) -> jax.Array:
    """Switch-style auxiliary loss: E · Σ_e fraction_e · mean_prob_e —
    minimized (→1) when routing is uniform. Add ``α·aux`` to the training
    objective (α ≈ 0.01) to keep experts load-balanced."""
    tokens = x.reshape(-1, x.shape[-1])
    probs = jax.nn.softmax(tokens @ params["router"]["kernel"], axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), num_experts, dtype=probs.dtype), axis=0
    )
    return _switch_aux(frac, probs, num_experts)
