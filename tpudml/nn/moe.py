"""Mixture-of-Experts layer with expert parallelism.

Absent from the reference (SURVEY.md §2.3 lists EP/MoE as out of parity
scope), built here to complete the parallelism matrix. TPU-first design —
the GShard/Switch dense-dispatch formulation, not per-token gather loops:

- top-k routing (k=1 Switch, k>1 GShard) with a static per-shard expert
  capacity C, so every shape is fixed and XLA tiles the dispatch/combine
  einsums onto the MXU;
- dispatch is a [G, E, C] one-hot tensor: ``expert_in = einsum(
  'gec,gd->ecd')``, combine is its gate-weighted transpose — tokens past
  capacity are dropped (combine weight 0), the standard Switch trade;
- under expert parallelism (``axis_name`` set, run inside shard_map),
  tokens AND experts are sharded over the same mesh axis: each shard
  routes its local tokens, one ``all_to_all`` ships the [E, C, d] dispatch
  to the owning experts, the local expert FFNs run, and the inverse
  ``all_to_all`` returns outputs to the token owners. Communication is two
  all_to_alls of C·d per expert — never the full activations.

Routing gradients flow through the combine gate (straight-through on the
argmax path); an auxiliary load-balancing loss is exposed via
:func:`load_balancing_loss` for callers that want Switch-style balance
pressure in their objective.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpudml.nn.layers import Module, _uniform_fan_in


@dataclass(frozen=True)
class MoELayer(Module):
    """Top-k mixture-of-experts FFN over [..., embed_dim] inputs.

    ``top_k=1`` is the Switch formulation (raw top-1 probability as the
    gate); ``top_k>1`` is GShard-style — each token dispatches to its k
    best experts with gates renormalized over the chosen k, capacity
    scaled by k, and choice 0 taking buffer priority over choice 1 (a
    token's secondary pick is dropped first under overflow).

    ``axis_name=None``: single-shard dense routing. ``axis_name="expert"``:
    expert-parallel — must run under shard_map with tokens sharded over the
    axis and ``num_experts`` divisible by the axis size.
    """

    embed_dim: int
    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    top_k: int = 1
    axis_name: str | None = None
    dtype: Any = jnp.float32

    def __post_init__(self):
        if not 1 <= self.top_k <= self.num_experts:
            raise ValueError(
                f"top_k {self.top_k} must be in [1, num_experts={self.num_experts}]"
            )

    def init(self, key):
        d, e, h = self.embed_dim, self.num_experts, self.mlp_ratio * self.embed_dim
        kr, k1, kb1, k2, kb2 = jax.random.split(key, 5)
        params = {
            "router": {"kernel": _uniform_fan_in(kr, (d, e), d, self.dtype)},
            "experts": {
                "w1": _uniform_fan_in(k1, (e, d, h), d, self.dtype),
                "b1": _uniform_fan_in(kb1, (e, h), d, self.dtype),
                "w2": _uniform_fan_in(k2, (e, h, d), h, self.dtype),
                "b2": _uniform_fan_in(kb2, (e, d), h, self.dtype),
            },
        }
        # aux_loss lives in state from init so the TrainState pytree
        # structure is stable across steps; make_loss_fn(aux_loss_weight=α)
        # folds it into the objective (gradients flow to the router).
        return params, {"aux_loss": jnp.zeros((), jnp.float32)}

    def _capacity(self, n_tokens: int) -> int:
        return max(
            1,
            int(n_tokens * self.top_k * self.capacity_factor / self.num_experts + 0.5),
        )

    def apply(self, params, state, x, *, train=False, rng=None):
        shape = x.shape
        d, e = self.embed_dim, self.num_experts
        g = 1
        for s in shape[:-1]:
            g *= s
        tokens = x.reshape(g, d)
        cap = self._capacity(g)

        logits = tokens @ params["router"]["kernel"]  # [G, E]
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = lax.top_k(probs, self.top_k)  # [G, k]
        if self.top_k == 1:
            gates = topv  # Switch: the raw top-1 probability
        else:
            gates = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)

        # Choice-priority dispatch: choice 0 claims buffer slots for ALL
        # tokens before choice 1 sees the remaining capacity (k static and
        # small, so the Python loop unrolls into k fused dispatch builds).
        # Bookkeeping stays float32 regardless of the token dtype — bf16
        # represents integers exactly only to 256, so a bf16 cumsum would
        # corrupt capacity positions on any real batch.
        counts = jnp.zeros((e,), jnp.float32)  # slots used per expert
        disp = jnp.zeros((g, e, cap), jnp.float32)
        combine = jnp.zeros((g, e, cap), jnp.float32)
        choice_sum = jnp.zeros((g, e), jnp.float32)  # Σ_j onehot_j per token
        for j in range(self.top_k):
            onehot = jax.nn.one_hot(topi[:, j], e, dtype=jnp.float32)  # [G, E]
            choice_sum = choice_sum + onehot
            pos = counts[None, :] + jnp.cumsum(onehot, axis=0) - onehot  # [G, E]
            kept = onehot * (pos < cap)
            slot = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
            disp_j = kept[:, :, None] * jax.nn.one_hot(slot, cap, dtype=jnp.float32)[
                :, None, :
            ]  # [G, E, C] (disjoint slots across choices by construction)
            disp = disp + disp_j
            combine = combine + disp_j * gates[:, j][:, None, None]
            counts = counts + jnp.sum(kept, axis=0)

        expert_in = jnp.einsum(
            "gec,gd->ecd", disp.astype(tokens.dtype), tokens
        )  # [E, C, d]
        ep = self.axis_name is not None
        if ep:
            # Ship each expert's buffer to its owning shard: [E, C, d] →
            # [E/W, W·C, d] (and back after the FFN).
            expert_in = lax.all_to_all(
                expert_in, self.axis_name, split_axis=0, concat_axis=1, tiled=True
            )
        w = params["experts"]
        hidden = jax.nn.relu(
            jnp.einsum("ecd,edh->ech", expert_in, w["w1"]) + w["b1"][:, None, :]
        )
        expert_out = (
            jnp.einsum("ech,ehd->ecd", hidden, w["w2"]) + w["b2"][:, None, :]
        )
        if ep:
            expert_out = lax.all_to_all(
                expert_out, self.axis_name, split_axis=1, concat_axis=0, tiled=True
            )
        y = jnp.einsum("gec,ecd->gd", combine.astype(expert_out.dtype), expert_out)
        # Switch/GShard aux loss over this shard's tokens: E · Σ_e frac_e ·
        # p̄_e, with frac_e the dispatch fraction averaged over ALL k
        # choices (GShard's formulation; =1 when routing is uniform).
        # First-choice-only frac (ADVICE r2) would leave secondary-choice
        # expert collapse invisible to the loss; differentiable through
        # probs.
        frac = jnp.mean(choice_sum, axis=0) / self.top_k
        aux = self.num_experts * jnp.sum(frac * jnp.mean(probs, axis=0))
        return y.reshape(shape), {"aux_loss": aux}


def load_balancing_loss(params: dict, x: jax.Array, num_experts: int) -> jax.Array:
    """Switch-style auxiliary loss: E · Σ_e fraction_e · mean_prob_e —
    minimized (→1) when routing is uniform. Add ``α·aux`` to the training
    objective (α ≈ 0.01) to keep experts load-balanced."""
    tokens = x.reshape(-1, x.shape[-1])
    probs = jax.nn.softmax(tokens @ params["router"]["kernel"], axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), num_experts, dtype=probs.dtype), axis=0
    )
    return num_experts * jnp.sum(frac * jnp.mean(probs, axis=0))
