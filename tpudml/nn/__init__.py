from tpudml.nn.layers import (
    Activation,
    AvgPool,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    MaxPool,
    Module,
    Sequential,
)

__all__ = [
    "Module",
    "Dense",
    "Conv2D",
    "MaxPool",
    "AvgPool",
    "Flatten",
    "Activation",
    "BatchNorm",
    "Dropout",
    "Sequential",
]
