from tpudml.nn.layers import (
    Activation,
    AvgPool,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    LayerNorm,
    MaxPool,
    Module,
    Sequential,
)
from tpudml.nn.attention import MultiHeadAttention, dot_product_attention

__all__ = [
    "Module",
    "Dense",
    "Conv2D",
    "MaxPool",
    "AvgPool",
    "Flatten",
    "Activation",
    "BatchNorm",
    "Dropout",
    "LayerNorm",
    "Sequential",
    "MultiHeadAttention",
    "dot_product_attention",
]
