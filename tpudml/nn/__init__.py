from tpudml.nn.layers import (
    Activation,
    AvgPool,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    LayerNorm,
    MaxPool,
    Module,
    Sequential,
)
from tpudml.nn.attention import MultiHeadAttention, dot_product_attention
from tpudml.nn.moe import MoELayer, load_balancing_loss

__all__ = [
    "Module",
    "Dense",
    "Conv2D",
    "MaxPool",
    "AvgPool",
    "Flatten",
    "Activation",
    "BatchNorm",
    "Dropout",
    "LayerNorm",
    "Sequential",
    "MultiHeadAttention",
    "dot_product_attention",
    "MoELayer",
    "load_balancing_loss",
]
