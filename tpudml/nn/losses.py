"""Loss and metric functions.

The reference uses ``nn.CrossEntropyLoss`` over logits + integer labels
(codes/task1/pytorch/model.py:103) and argmax top-1 accuracy in ``test()``
(model.py:67-81); these are the pure-function equivalents.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


@jax.custom_vjp
def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over integer labels (torch
    CrossEntropyLoss semantics, reduction='mean').

    Memory-lean custom VJP: the autodiff path would keep a full f32
    ``log_softmax(logits)`` residual AND row-gather it (both expensive at
    LM scale — [B·T, 32k] logits are ~1 GB in f32); here the forward
    keeps only the per-row log-sum-exp (plus the logits it was handed),
    the label pick is a fused where+sum instead of a TPU row-gather, and
    the backward recomputes softmax in one fused pass. Statistics are f32
    regardless of the logits dtype, so bf16 logits need no up-cast
    materialization.

    REVERSE-MODE ONLY (ADVICE r3): ``jax.custom_vjp`` does not support
    forward-mode AD, so ``jax.jvp``/``jacfwd``/higher-order
    differentiation through this loss raises. Every training path in the
    framework is reverse-mode; if forward-mode is ever needed, compose
    the same math inline (``_xent_fwd_value`` without the custom-vjp
    wrapper) at the call site."""
    loss, _ = _xent_fwd_value(logits, labels)
    return loss


def _label_mask(labels: jax.Array, shape) -> jax.Array:
    """One-hot mask [..., V] via fused iota-compare (no TPU row-gather).

    Labels are clamped to [0, V-1] — the same semantics the previous
    ``take_along_axis`` implementation had under jit (XLA clamps
    out-of-range gathers), so invalid ids map to an edge class instead of
    silently dropping their pull-up term. Torch-style ignore ids (-100)
    are NOT supported; mask such rows out before the loss."""
    ids = jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)
    clamped = jnp.clip(labels.astype(jnp.int32), 0, shape[-1] - 1)
    return ids == clamped[..., None]


def _xent_fwd_value(logits, labels):
    f32 = jnp.float32
    m = jnp.max(logits, axis=-1)  # bf16 max is exact under compare
    shifted = logits.astype(f32) - m.astype(f32)[..., None]
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m.astype(f32)
    picked = jnp.sum(
        jnp.where(_label_mask(labels, logits.shape), logits, 0).astype(f32),
        axis=-1,
    )
    return jnp.mean(lse - picked), lse


def _xent_fwd(logits, labels):
    loss, lse = _xent_fwd_value(logits, labels)
    return loss, (logits, labels, lse)


def _xent_bwd(res, g):
    logits, labels, lse = res
    n = lse.size  # number of rows averaged over
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = _label_mask(labels, logits.shape)
    dlogits = ((p - onehot.astype(jnp.float32)) * (g / n)).astype(logits.dtype)
    return dlogits, np.zeros(labels.shape, dtype=jax.dtypes.float0)


softmax_cross_entropy.defvjp(_xent_fwd, _xent_bwd)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Top-1 accuracy."""
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
