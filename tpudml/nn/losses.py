"""Loss and metric functions.

The reference uses ``nn.CrossEntropyLoss`` over logits + integer labels
(codes/task1/pytorch/model.py:103) and argmax top-1 accuracy in ``test()``
(model.py:67-81); these are the pure-function equivalents.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over integer labels (torch
    CrossEntropyLoss semantics, reduction='mean')."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)
    return jnp.mean(nll)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Top-1 accuracy."""
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
