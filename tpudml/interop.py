"""Torch-weight interop: load reference-trained checkpoints into tpudml.

Migration bridge for users of the reference lab code: a ``state_dict``
from the reference's ``Net`` (codes/task1/pytorch/model.py:16-35) or the
MindSpore-track MLP drops into the matching tpudml model, producing
bit-equal logits. Handles the layout changes the TPU-first design made:

- conv kernels: torch OIHW → NHWC-conv HWIO;
- linear kernels: torch [out, in] → [in, out];
- the first dense layer after a conv stack additionally permutes its input
  rows from torch's channel-major flatten (C,H,W) to this framework's
  channel-last flatten (H,W,C).

Accepts torch tensors or numpy arrays (torch itself is not required
unless the values are tensors).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np


def _to_np(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor, without importing torch
        x = x.detach().cpu().numpy()
    return np.asarray(x, dtype=np.float32)


def _pairs(state_dict: Mapping[str, Any]) -> list[tuple[np.ndarray, np.ndarray]]:
    """(weight, bias) per layer, in the state_dict's insertion order."""
    weights = [(k, _to_np(v)) for k, v in state_dict.items() if k.endswith(".weight")]
    out = []
    for name, w in weights:
        bias_key = name[: -len(".weight")] + ".bias"
        b = _to_np(state_dict[bias_key]) if bias_key in state_dict else None
        out.append((w, b))
    return out


def _conv_kernel(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))  # OIHW → HWIO


def _dense_kernel(w: np.ndarray, prev_conv_spatial=None) -> np.ndarray:
    k = np.transpose(w, (1, 0))  # [out, in] → [in, out]
    if prev_conv_spatial is not None:
        c, h, ww = prev_conv_spatial
        # Rows are ordered by torch's (C,H,W) flatten; reorder to (H,W,C).
        k = k.reshape(c, h, ww, -1).transpose(1, 2, 0, 3).reshape(c * h * ww, -1)
    return k


def lenet_params_from_torch(
    state_dict: Mapping[str, Any], conv_out_spatial: tuple[int, int, int] = (16, 5, 5)
) -> dict:
    """Params tree for ``tpudml.models.LeNet`` from a reference ``Net``
    state_dict (two convs then two linears, classified by tensor rank —
    robust to parameter names). ``conv_out_spatial`` is the (C, H, W) of
    the final conv output that the first linear consumes."""
    convs = []
    denses = []
    for w, b in _pairs(state_dict):
        (convs if w.ndim == 4 else denses).append((w, b))
    if len(convs) != 2 or len(denses) != 2:
        raise ValueError(
            f"expected 2 conv + 2 linear layers, got {len(convs)} conv / "
            f"{len(denses)} linear"
        )
    params: dict = {}
    for idx, (w, b) in zip((0, 3), convs):
        params[f"layer{idx}"] = {"kernel": _conv_kernel(w), "bias": b}
    params["layer7"] = {
        "kernel": _dense_kernel(denses[0][0], conv_out_spatial),
        "bias": denses[0][1],
    }
    params["layer9"] = {"kernel": _dense_kernel(denses[1][0]), "bias": denses[1][1]}
    return params


def mlp_params_from_torch(state_dict: Mapping[str, Any]) -> dict:
    """Params tree for ``tpudml.models.ForwardMLP`` from a pure-linear
    torch/MindSpore MLP state_dict (layer order = state_dict order)."""
    denses = [(w, b) for w, b in _pairs(state_dict) if w.ndim == 2]
    if not denses:
        raise ValueError("no linear layers found in state_dict")
    params = {}
    # ForwardMLP layout: Flatten, then (Dense, Activation)*; Dense layers
    # land at Sequential indices 1, 3, 5, ... and the head last.
    for i, (w, b) in enumerate(denses):
        params[f"layer{2 * i + 1}"] = {"kernel": _dense_kernel(w), "bias": b}
    return params
