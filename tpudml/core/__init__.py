from tpudml.core.config import (
    DataConfig,
    DistributedConfig,
    MeshConfig,
    TrainConfig,
)
from tpudml.core.dist import (
    distributed_init,
    get_local_rank,
    get_world_size,
    local_device_count,
    make_mesh,
    process_count,
    process_index,
)
from tpudml.core.prng import fold_in_epoch, key_for_step, seed_key

__all__ = [
    "DataConfig",
    "DistributedConfig",
    "MeshConfig",
    "TrainConfig",
    "distributed_init",
    "get_local_rank",
    "get_world_size",
    "local_device_count",
    "make_mesh",
    "process_count",
    "process_index",
    "seed_key",
    "key_for_step",
    "fold_in_epoch",
]
