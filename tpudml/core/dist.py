"""Distributed runtime init + device mesh construction.

TPU-native replacement for the reference's process-group layer
(``dist_init`` / ``get_local_rank`` / ``get_world_size``, reference:
codes/task2/dist_utils.py:6-30). On TPU there is no NCCL/gloo choice: XLA
emits collectives over ICI (intra-slice) and DCN (cross-host); the only
host-level step is ``jax.distributed.initialize`` for multi-process runs.
"""

from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh

from tpudml.core.config import DistributedConfig, MeshConfig

log = logging.getLogger("tpudml")

_initialized = False


def distributed_init(cfg: DistributedConfig | None = None) -> None:
    """Initialize the multi-process JAX runtime (idempotent).

    Parity contract with the reference's ``dist_init`` (codes/task2/
    dist_utils.py:6-15): blocks until all processes join the coordinator,
    and afterwards ``process_index()``/``process_count()`` report the
    caller's rank/world. Single-process runs (coordinator_address=None) are
    a no-op, matching the reference's single-GPU task1 path.
    """
    global _initialized
    if _initialized:
        return
    cfg = cfg or DistributedConfig.from_env()
    if cfg.coordinator_address is not None and cfg.num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
            initialization_timeout=cfg.initialize_timeout_s,
        )
        log.info(
            "distributed runtime up: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    _initialized = True


def process_index() -> int:
    """This process's rank among all hosts.

    Reference parity: ``get_local_rank`` with its uninitialized→0 fallback
    (codes/task2/dist_utils.py:18-23) — jax.process_index() is 0 before/
    without distributed init, so the fallback holds by construction.
    """
    return jax.process_index()


def process_count() -> int:
    """Number of participating host processes.

    Reference parity: ``get_world_size`` with its uninitialized→1 fallback
    (codes/task2/dist_utils.py:26-30).
    """
    return jax.process_count()


# Aliases with the reference's names, for drop-in familiarity.
get_local_rank = process_index
get_world_size = process_count


def local_device_count() -> int:
    return jax.local_device_count()


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a named device Mesh from a MeshConfig.

    Axis sizes of -1 absorb all remaining devices. Devices default to all
    global devices; their order follows ``jax.devices()`` so that identical
    configs produce identical meshes on every host (a requirement for SPMD
    program agreement — the TPU analogue of "all ranks call init with the
    same world_size").
    """
    cfg = cfg or MeshConfig()
    devices = np.asarray(devices if devices is not None else jax.devices())
    sizes = dict(cfg.axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    known = int(np.prod([v for v in sizes.values() if v != -1])) if sizes else 1
    if len(unknown) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
    if unknown:
        if devices.size % known:
            raise ValueError(
                f"device count {devices.size} not divisible by fixed axes {sizes}"
            )
        sizes[unknown[0]] = devices.size // known
    total = int(np.prod(list(sizes.values()))) if sizes else 1
    if total != devices.size:
        raise ValueError(f"mesh {sizes} wants {total} devices, have {devices.size}")
    return Mesh(devices.reshape(tuple(sizes.values())), tuple(sizes.keys()))
