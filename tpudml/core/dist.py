"""Distributed runtime init + device mesh construction.

TPU-native replacement for the reference's process-group layer
(``dist_init`` / ``get_local_rank`` / ``get_world_size``, reference:
codes/task2/dist_utils.py:6-30). On TPU there is no NCCL/gloo choice: XLA
emits collectives over ICI (intra-slice) and DCN (cross-host); the only
host-level step is ``jax.distributed.initialize`` for multi-process runs.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh

from tpudml.core.config import DistributedConfig, MeshConfig

log = logging.getLogger("tpudml")

_initialized = False


def _platform_is_cpu(cfg: DistributedConfig) -> bool:
    """Whether this job will run on the CPU backend — decided WITHOUT
    touching ``jax.devices()`` (instantiating a backend here would latch
    it before the collectives knob below can take effect)."""
    if cfg.backend is not None:
        return cfg.backend == "cpu"
    return os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu"


def resolve_cpu_collectives(cfg: DistributedConfig) -> str | None:
    """The cross-process CPU collectives implementation this config asks
    for: the explicit ``cpu_collectives`` value (env
    ``TPUDML_CPU_COLLECTIVES``; ``"none"`` opts out), else ``"gloo"``
    exactly when the job is multi-process on the CPU platform — the wiring
    that makes ``JAX_PLATFORMS=cpu`` multi-process jobs actually compute
    (XLA:CPU alone rejects them with "Multiprocess computations aren't
    implemented on the CPU backend")."""
    impl = cfg.cpu_collectives
    if impl is None and cfg.num_processes > 1 and _platform_is_cpu(cfg):
        impl = "gloo"
    return None if impl in (None, "none", "") else impl


def distributed_init(cfg: DistributedConfig | None = None) -> None:
    """Initialize the multi-process JAX runtime (idempotent).

    Parity contract with the reference's ``dist_init`` (codes/task2/
    dist_utils.py:6-15): blocks until all processes join the coordinator,
    and afterwards ``process_index()``/``process_count()`` report the
    caller's rank/world. Single-process runs (coordinator_address=None) are
    a no-op, matching the reference's single-GPU task1 path.

    On the CPU platform, multi-process init also selects a cross-process
    collectives implementation (:func:`resolve_cpu_collectives`, default
    gloo) BEFORE the backend instantiates — the reference's
    ``init_process_group(backend="gloo")`` finally has a real analogue
    here, and the 2-process CI jobs psum across process boundaries for
    real instead of failing in the first collective.
    """
    global _initialized
    if _initialized:
        return
    cfg = cfg or DistributedConfig.from_env()
    if cfg.coordinator_address is not None and cfg.num_processes > 1:
        impl = resolve_cpu_collectives(cfg)
        if impl is not None:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
            initialization_timeout=cfg.initialize_timeout_s,
        )
        log.info(
            "distributed runtime up: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    _initialized = True


def process_index() -> int:
    """This process's rank among all hosts.

    Reference parity: ``get_local_rank`` with its uninitialized→0 fallback
    (codes/task2/dist_utils.py:18-23) — jax.process_index() is 0 before/
    without distributed init, so the fallback holds by construction.
    """
    return jax.process_index()


def process_count() -> int:
    """Number of participating host processes.

    Reference parity: ``get_world_size`` with its uninitialized→1 fallback
    (codes/task2/dist_utils.py:26-30).
    """
    return jax.process_count()


# Aliases with the reference's names, for drop-in familiarity.
get_local_rank = process_index
get_world_size = process_count


def local_device_count() -> int:
    return jax.local_device_count()


def assert_same_program(fingerprint: str, tag: str = "program") -> None:
    """Fail fast if processes are about to run different SPMD programs.

    The reference's only concurrency safety is structural — all ranks call
    the same collectives in the same order, and a mismatch (e.g. one rank
    launched with different hyperparameters) hangs every rank in the
    rendezvous forever (SURVEY.md §5.2). This is the launcher-level
    same-program check that section calls for: every process allgathers a
    hash of its program fingerprint (config, code version, …) and raises
    on divergence BEFORE any training collective is issued, turning a
    silent deadlock into an immediate, attributed error.

    No-op in single-process runs.
    """
    if process_count() <= 1:
        return
    import hashlib

    from jax.experimental import multihost_utils

    digest = hashlib.sha256(fingerprint.encode()).digest()[:8]
    mine = np.frombuffer(digest, dtype=np.int64)
    everyone = np.asarray(multihost_utils.process_allgather(mine, tiled=True))
    if not (everyone == everyone[0]).all():
        bad = sorted(
            int(i) for i in np.nonzero(everyone != everyone[0])[0]
        )
        raise RuntimeError(
            f"SPMD {tag} mismatch: processes {bad} disagree with process 0 "
            f"(this process={process_index()}). All ranks must run the same "
            "program/config; a mismatch would deadlock in the first collective."
        )


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a named device Mesh from a MeshConfig.

    Axis sizes of -1 absorb all remaining devices. Devices default to all
    global devices; their order follows ``jax.devices()`` so that identical
    configs produce identical meshes on every host (a requirement for SPMD
    program agreement — the TPU analogue of "all ranks call init with the
    same world_size").
    """
    cfg = cfg or MeshConfig()
    devices = np.asarray(devices if devices is not None else jax.devices())
    sizes = dict(cfg.axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    known = int(np.prod([v for v in sizes.values() if v != -1])) if sizes else 1
    if len(unknown) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
    if unknown:
        if devices.size % known:
            raise ValueError(
                f"device count {devices.size} not divisible by fixed axes {sizes}"
            )
        sizes[unknown[0]] = devices.size // known
    total = int(np.prod(list(sizes.values()))) if sizes else 1
    if total != devices.size:
        raise ValueError(f"mesh {sizes} wants {total} devices, have {devices.size}")
    return Mesh(devices.reshape(tuple(sizes.values())), tuple(sizes.keys()))


def shard_index_key(index) -> tuple:
    """Hashable key for a ``Shard.index`` (a tuple of ``slice`` objects —
    unhashable before Python 3.12). Use it to group/dedupe addressable
    shards by the array region they cover."""
    return tuple(
        (s.start, s.stop, s.step) if isinstance(s, slice) else s
        for s in index
    )
