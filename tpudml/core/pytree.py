"""Pytree key-path helpers shared by every module that classifies
parameters by their tree path (sharding rules, expert-leaf detection,
mixed-precision cast filters). One copy, so a JAX key-type change (e.g.
a new SequenceKey spelling) can't silently diverge path matching between
the classifiers."""

from __future__ import annotations


def key_name(k) -> str | None:
    """The human name of one pytree key entry (DictKey.key /
    GetAttrKey.name / SequenceKey.idx), or its str as a last resort."""
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return getattr(k, attr)
    return str(k)


def path_names(key_path) -> tuple:
    return tuple(key_name(k) for k in key_path)
