"""PRNG utilities.

The reference relies on torch's implicit global RNG plus per-rank seeds
(``seed=args.rank`` at codes/task3/model.py:111). JAX keys are explicit; these
helpers give the framework one deterministic seeding discipline: a root key
from the config seed, folded with epoch / step / rank as needed so every
result is bit-reproducible from the config.
"""

from __future__ import annotations

import jax


def seed_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def key_for_step(root: jax.Array, step: int) -> jax.Array:
    return jax.random.fold_in(root, step)


def fold_in_epoch(root: jax.Array, epoch: int) -> jax.Array:
    """Sampler-style per-epoch reshuffle key — the ``set_epoch`` analogue
    (reference: sections/task3.tex:52)."""
    return jax.random.fold_in(root, epoch)
