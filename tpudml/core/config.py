"""Typed configuration for tpu-dml.

Replaces the reference's per-entrypoint ``argparse`` flag sets and hardcoded
hyperparameter constants (reference: codes/task2/model.py:92-102,
codes/task4/model.py:142-151) and the docker-compose YAML that doubled as the
de-facto cluster config (codes/task2/docker-compose.yml). One dataclass tree
covers process topology, mesh shape, data division, and task hyperparameters;
every field can be overridden from CLI flags or environment variables.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Sequence


@dataclass
class DistributedConfig:
    """Process-level topology.

    JAX-distributed analogue of the reference's rendezvous contract
    (``MASTER_ADDR``/``MASTER_PORT`` env + ``init_process_group(backend,
    rank, world_size)``, reference: codes/task2/dist_utils.py:6-15).
    ``coordinator_address`` plays the role of master_addr:master_port;
    ``process_id``/``num_processes`` play rank/world_size. ``backend`` is
    advisory ("tpu", "cpu", "gpu") — on TPU the collectives ride ICI/DCN via
    XLA, there is no NCCL/gloo choice to make.
    """

    coordinator_address: str | None = None  # "host:port"; None = single-process
    num_processes: int = 1
    process_id: int = 0
    backend: str | None = None  # None = autodetect platform
    initialize_timeout_s: int = 300
    # Cross-process collective implementation for the CPU backend. XLA's
    # CPU client cannot run multi-process computations natively; jax
    # 0.4.37 wires MPI or gloo underneath via
    # ``jax_cpu_collectives_implementation``. None = auto: "gloo" whenever
    # the job is multi-process AND the platform is CPU (JAX_PLATFORMS=cpu
    # or backend="cpu"), nothing otherwise. "none" opts out explicitly.
    # Env: TPUDML_CPU_COLLECTIVES.
    cpu_collectives: str | None = None
    # True when the world size was given explicitly (--n_devices / env), so
    # single-host runs can distinguish "--n_devices 1" (use ONE device — the
    # single-machine baseline of sections/task3.tex:23) from the default
    # "use every available device".
    explicit_world: bool = False

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        """Build from env vars, honoring the reference's names as fallbacks.

        Recognizes TPUDML_COORDINATOR / TPUDML_NUM_PROCESSES /
        TPUDML_PROCESS_ID first, then the reference's MASTER_ADDR/MASTER_PORT
        (+ RANK/WORLD_SIZE) for drop-in familiarity.
        """
        coord = os.environ.get("TPUDML_COORDINATOR")
        if coord is None:
            addr = os.environ.get("MASTER_ADDR")
            port = os.environ.get("MASTER_PORT")
            if addr and port:
                coord = f"{addr}:{port}"
        nproc = os.environ.get(
            "TPUDML_NUM_PROCESSES", os.environ.get("WORLD_SIZE")
        )
        return cls(
            coordinator_address=coord,
            num_processes=int(nproc) if nproc is not None else 1,
            process_id=int(os.environ.get("TPUDML_PROCESS_ID", os.environ.get("RANK", "0"))),
            backend=os.environ.get("TPUDML_BACKEND"),
            cpu_collectives=os.environ.get("TPUDML_CPU_COLLECTIVES"),
            explicit_world=nproc is not None,
        )


@dataclass
class MeshConfig:
    """Logical device mesh over which SPMD programs are sharded.

    ``axes`` maps axis name -> size; -1 means "all remaining devices". The
    canonical axis names used across the framework are ``data`` (DP),
    ``stage`` (inter-layer MP / pipeline), ``model`` (tensor parallel) and
    ``seq`` (sequence/context parallel).
    """

    axes: dict[str, int] = field(default_factory=lambda: {"data": -1})

    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.axes.keys())


@dataclass
class DataConfig:
    """Dataset + division strategy.

    ``division`` selects the sampler mode required by the reference's task3
    (sections/task3.tex:19-24, sections/checking.tex:13): "partition" =
    random partition (shared seed, disjoint stride), "sampling" = random
    sampling (per-rank seed → independent shuffles, sampling with
    replacement across ranks).
    """

    dataset: str = "mnist"  # mnist | cifar10 | synthetic
    data_dir: str = "./data"
    batch_size: int = 200  # per-replica batch (reference task1: 200, task2/3/4: 32)
    division: str = "partition"  # partition | sampling
    shuffle: bool = True
    seed: int = 0
    drop_remainder: bool = True
    synthetic_fallback: bool = True  # use deterministic synthetic data if files absent


@dataclass
class TrainConfig:
    """Top-level training configuration for the task entrypoints."""

    epochs: int = 1
    lr: float = 1e-3
    momentum: float = 0.0
    optimizer: str = "adam"  # gd | sgd | adam | adam_ref
    aggregation: str = "allreduce"  # allreduce | allgather  (task2 contract)
    log_every: int = 20  # reference cadence: print/log every 20 iters
    bottleneck_rank: int | None = None  # straggler-injection target rank
    bottleneck_delay_s: float = 0.1  # reference: model-mp.py:47
    measure_comm: bool = False  # split-step comm-time accounting mode
    zero1: bool = False  # ZeRO-1 weight-update sharding on the DP engine
    sentinel: bool = False  # in-graph step sentinel (skip non-finite updates)
    obs: bool = False  # flight recorder: trace.json + in-graph StepStats
    accum_steps: int = 1  # gradient-accumulation micro-batches per step
    log_dir: str = "./logs"
    profile: bool = False  # capture a jax.profiler trace into the run dir
    ckpt_dir: str | None = None  # enable checkpointing under this directory
    ckpt_every: int = 0  # steps between rolling checkpoints (0 = end only)
    resume: bool = False  # restore the latest checkpoint before training
    seed: int = 0
    dist: DistributedConfig = field(default_factory=DistributedConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    data: DataConfig = field(default_factory=DataConfig)

    def fingerprint(self) -> str:
        """Rank-invariant program identity for the cross-process
        same-program check (``assert_same_program``): every field except
        the per-process ``dist`` block, rank-targeted fault injection, and
        host-local paths (log/ckpt dirs may legitimately be rank-templated
        without changing the SPMD program)."""
        d = dataclasses.asdict(self)
        for k in ("dist", "bottleneck_rank", "log_dir", "ckpt_dir"):
            d.pop(k, None)
        d["data"].pop("data_dir", None)
        return repr(dict(sorted(d.items())))


def _add_flag(
    parser: argparse.ArgumentParser, name: str, default: Any, annotation: str = ""
) -> None:
    typ = type(default)
    if typ is bool:
        parser.add_argument(f"--{name}", action=argparse.BooleanOptionalAction, default=default)
    elif default is None:
        # Optional fields: recover the parser type from the annotation so
        # e.g. --bottleneck_rank yields an int, not a str.
        typ = int if "int" in annotation else float if "float" in annotation else str
        parser.add_argument(f"--{name}", type=typ, default=None)
    else:
        parser.add_argument(f"--{name}", type=typ, default=default)


def build_parser(
    defaults: TrainConfig | None = None, extra: Sequence[str] = ()
) -> argparse.ArgumentParser:
    """CLI parser exposing the flat fields of TrainConfig plus the
    reference's historical flag names (``--n_devices``, ``--rank``,
    ``--master_addr``, ``--master_port``, ``--mode``) for parity
    (reference: codes/task2/model.py:92-102, codes/task4/model.py:142-151).
    """
    defaults = defaults or TrainConfig()
    p = argparse.ArgumentParser()
    for f in dataclasses.fields(TrainConfig):
        if f.name in ("dist", "mesh", "data"):
            continue
        _add_flag(p, f.name, getattr(defaults, f.name), str(f.type))
    taken = {f.name for f in dataclasses.fields(TrainConfig)}
    for f in dataclasses.fields(DataConfig):
        if f.name not in taken:  # e.g. `seed`: one --seed flag feeds both configs
            _add_flag(p, f.name, getattr(defaults.data, f.name), str(f.type))
    # Reference-parity flags.
    p.add_argument("--n_devices", type=int, default=None, help="world size (reference parity)")
    p.add_argument("--rank", type=int, default=None, help="process id (reference parity)")
    p.add_argument("--master_addr", type=str, default=None)
    p.add_argument("--master_port", type=str, default=None)
    p.add_argument("--mode", type=str, default=None, help="alias of --division (task4 parity)")
    p.add_argument("--plan", type=str, default=None, metavar="PLAN_JSON",
                   help="apply a planner-emitted plan.json (python -m "
                        "tpudml.plan): its engine_config fills every knob "
                        "left at its default (explicit flags win)")
    for name in extra:
        p.add_argument(name)
    return p


def config_from_args(args: argparse.Namespace) -> TrainConfig:
    """Materialize a TrainConfig from parsed CLI args + environment."""
    cfg = TrainConfig()
    for f in dataclasses.fields(TrainConfig):
        if f.name in ("dist", "mesh", "data"):
            continue
        if hasattr(args, f.name):
            setattr(cfg, f.name, getattr(args, f.name))
    for f in dataclasses.fields(DataConfig):
        if hasattr(args, f.name):
            setattr(cfg.data, f.name, getattr(args, f.name))
    cfg.data.seed = cfg.seed  # single --seed governs data division too
    cfg.dist = DistributedConfig.from_env()
    if getattr(args, "n_devices", None) is not None:
        cfg.dist.num_processes = args.n_devices
        cfg.dist.explicit_world = True
    if getattr(args, "rank", None) is not None:
        cfg.dist.process_id = args.rank
    if getattr(args, "master_addr", None) is not None and getattr(args, "master_port", None):
        cfg.dist.coordinator_address = f"{args.master_addr}:{args.master_port}"
    if getattr(args, "mode", None):
        # task4 historical values: "division" -> partition, "sampling" -> sampling
        cfg.data.division = {"division": "partition", "sampling": "sampling"}.get(
            args.mode, args.mode
        )
    # Planner output (python -m tpudml.plan). Same precedence contract as
    # the env knobs below: the plan's engine_config fills only the knobs
    # the user left at their defaults, so explicit flags always win.
    if getattr(args, "plan", None):
        from tpudml.plan.emit import load_plan

        ec = load_plan(args.plan)["engine_config"]
        defaults = TrainConfig()
        for name in ("zero1", "accum_steps", "sentinel", "obs", "aggregation"):
            if name in ec and getattr(cfg, name) == getattr(defaults, name):
                setattr(cfg, name, ec[name])
    # Fault-injection knobs exported by the launcher (tpudml.launch) ride the
    # environment so the task command line stays rank-agnostic. Precedence is
    # CLI > env: env fills only fields the user left at their defaults.
    if cfg.bottleneck_rank is None and os.environ.get("TPUDML_BOTTLENECK_RANK"):
        cfg.bottleneck_rank = int(os.environ["TPUDML_BOTTLENECK_RANK"])
        if cfg.bottleneck_delay_s == TrainConfig.bottleneck_delay_s:
            cfg.bottleneck_delay_s = float(
                os.environ.get("TPUDML_BOTTLENECK_DELAY_S", cfg.bottleneck_delay_s)
            )
    return cfg
