from tpudml.optim.optimizers import (
    Adam,
    AdamW,
    ClipByGlobalNorm,
    GradientDescent,
    Optimizer,
    ReferenceAdam,
    Sgd,
    make_optimizer,
    shard_aware_clip,
)
from tpudml.optim.schedules import (
    Scheduled,
    constant,
    cosine_decay,
    linear_warmup,
    step_decay,
    warmup_cosine,
)
from tpudml.optim.zero1 import ZeRO1, stages_stacked, with_stacked, zero1_handles

__all__ = [
    "Optimizer",
    "GradientDescent",
    "Sgd",
    "Adam",
    "AdamW",
    "ClipByGlobalNorm",
    "ReferenceAdam",
    "make_optimizer",
    "shard_aware_clip",
    "Scheduled",
    "constant",
    "cosine_decay",
    "linear_warmup",
    "step_decay",
    "warmup_cosine",
    "ZeRO1",
    "zero1_handles",
    "stages_stacked",
    "with_stacked",
]
