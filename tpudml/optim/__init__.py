from tpudml.optim.optimizers import (
    Adam,
    GradientDescent,
    Optimizer,
    ReferenceAdam,
    Sgd,
    make_optimizer,
)

__all__ = [
    "Optimizer",
    "GradientDescent",
    "Sgd",
    "Adam",
    "ReferenceAdam",
    "make_optimizer",
]
