"""Hand-written first-order optimizers as pure pytree transforms.

TPU-native re-design of the reference's custom optimizer layer
(codes/task1/pytorch/MyOptimizer.py): where the reference mutates
``p.data`` in a per-parameter Python loop, these are pure functions over
parameter pytrees, so the entire update fuses into the jitted train step
(one XLA program — no per-parameter kernel launches).

The reference's eager-mode ``zero_grad`` (grad detach + zero,
MyOptimizer.py:11-15) has no analogue here: ``jax.grad`` returns fresh
gradients each step by construction, which is the semantic the detach
requirement was enforcing.

Contract: ``init(params) -> state``; ``update(grads, state, params) ->
(new_params, new_state)``. Both are jit-compatible and work on any pytree.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


class Optimizer:
    """Base optimizer. Subclasses implement init/update as pure functions.

    Reference parity: ``BaseOptimizer`` (MyOptimizer.py:3-15) holds params +
    lr and defines step/zero_grad; here state is explicit and updates are
    functional.
    """

    def init(self, params: PyTree) -> PyTree:
        return ()

    def init_spec(self, param_specs: PyTree) -> PyTree:
        """Mirror of ``init`` over PartitionSpecs: given the sharding specs
        of ``params``, return the specs of the optimizer state. Because the
        reference's DistributedOptimizer updates parameters where they live
        (via RRefs, codes/task4/model.py:126), the TPU-native analogue is
        optimizer state sharded IDENTICALLY to its parameters — updates then
        happen on the owning devices by construction (SURVEY.md §2.3
        parameter-server row; this is also ZeRO-style state sharding).

        A subclass that overrides ``init`` (i.e. carries state) MUST also
        override ``init_spec``; the base fails fast here rather than letting
        a stateless-spec/stateful-state mismatch surface as an opaque pytree
        structure error inside ``create_state``.
        """
        if type(self).init is not Optimizer.init:
            raise NotImplementedError(
                f"{type(self).__name__} overrides init() but not init_spec(); "
                "sharded engines need the optimizer-state spec tree"
            )
        return ()

    def update(self, grads: PyTree, state: PyTree, params: PyTree) -> tuple[PyTree, PyTree]:
        raise NotImplementedError


@dataclass(frozen=True)
class GradientDescent(Optimizer):
    """Vanilla gradient descent: ``p -= lr * g``.

    Reference parity: ``GdOptimizer`` (MyOptimizer.py:18-24). Whether it acts
    as GD or SGD is a property of the data pipeline (full batch vs
    minibatch), as in the reference labs (sections/task1.tex:8-23).
    """

    lr: float = 1e-3

    def update(self, grads, state, params):
        new_params = jax.tree.map(lambda p, g: p - self.lr * g, params, grads)
        return new_params, state


@dataclass(frozen=True)
class Sgd(Optimizer):
    """SGD with (optional) heavy-ball momentum, matching torch.optim.SGD's
    formulation used by the distributed tasks (codes/task2/model.py:131:
    ``SGD(lr=0.01, momentum=0.9)``): ``buf = mu*buf + g; p -= lr*buf``.
    """

    lr: float = 1e-2
    momentum: float = 0.0

    def init(self, params):
        if self.momentum == 0.0:
            return ()
        return jax.tree.map(jnp.zeros_like, params)

    def init_spec(self, param_specs):
        if self.momentum == 0.0:
            return ()
        return param_specs

    def update(self, grads, state, params):
        if self.momentum == 0.0:
            return jax.tree.map(lambda p, g: p - self.lr * g, params, grads), state
        new_buf = jax.tree.map(lambda b, g: self.momentum * b + g, state, grads)
        new_params = jax.tree.map(lambda p, b: p - self.lr * b, params, new_buf)
        return new_params, new_buf


@dataclass(frozen=True)
class Adam(Optimizer):
    """Standard Adam (Kingma & Ba) with bias correction."""

    lr: float = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8

    def init(self, params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return {"m": zeros(), "v": zeros(), "t": jnp.zeros((), jnp.int32)}

    def init_spec(self, param_specs):
        from jax.sharding import PartitionSpec

        return {"m": param_specs, "v": param_specs, "t": PartitionSpec()}

    def update(self, grads, state, params):
        t = state["t"] + 1
        m = jax.tree.map(lambda m_, g: self.b1 * m_ + (1 - self.b1) * g, state["m"], grads)
        v = jax.tree.map(
            lambda v_, g: self.b2 * v_ + (1 - self.b2) * g * g, state["v"], grads
        )
        tf = t.astype(jnp.float32)
        c1 = 1.0 - self.b1**tf
        c2 = 1.0 - self.b2**tf
        new_params = jax.tree.map(
            lambda p, m_, v_: p - self.lr * (m_ / c1) / (jnp.sqrt(v_ / c2) + self.eps),
            params,
            m,
            v,
        )
        return new_params, {"m": m, "v": v, "t": t}


@dataclass(frozen=True)
class ReferenceAdam(Optimizer):
    """The reference's hand-written Adam WITHOUT bias correction
    (codes/task1/pytorch/MyOptimizer.py:26-43): ``m = b1*m + (1-b1)*g;
    v = b2*v + (1-b2)*g²; p -= lr * m / (sqrt(v) + eps)`` — the m̂/v̂ terms
    are absent. Reproduced faithfully (and separately from standard Adam)
    because task1's training behavior, including its early-step update
    scale, depends on it.
    """

    lr: float = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8

    def init(self, params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return {"m": zeros(), "v": zeros()}

    def init_spec(self, param_specs):
        return {"m": param_specs, "v": param_specs}

    def update(self, grads, state, params):
        m = jax.tree.map(lambda m_, g: self.b1 * m_ + (1 - self.b1) * g, state["m"], grads)
        v = jax.tree.map(
            lambda v_, g: self.b2 * v_ + (1 - self.b2) * g * g, state["v"], grads
        )
        new_params = jax.tree.map(
            lambda p, m_, v_: p - self.lr * m_ / (jnp.sqrt(v_) + self.eps), params, m, v
        )
        return new_params, {"m": m, "v": v}


@dataclass(frozen=True)
class AdamW(Adam):
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter): the decay
    term applies directly to the parameters, not through the adaptive
    moments — the default optimizer of modern transformer training."""

    weight_decay: float = 0.01

    def update(self, grads, state, params):
        new_params, new_state = super().update(grads, state, params)
        if self.weight_decay:
            new_params = jax.tree.map(
                lambda np_, p: np_ - self.lr * self.weight_decay * p,
                new_params,
                params,
            )
        return new_params, new_state


@dataclass(frozen=True)
class ClipByGlobalNorm(Optimizer):
    """Gradient clipping wrapper: rescales the WHOLE gradient pytree when
    its global L2 norm exceeds ``max_norm``, then defers to ``base``;
    state and its sharding spec pass straight through.

    Sharded engines (the TPU-native form of the reference's
    update-where-params-live contract, codes/task4/model.py:126) call
    ``update`` inside shard_map with DEVICE-LOCAL gradient shards (GPipe's
    per-stage slices, ExpertParallel's expert slices). There the norm must
    be reduced across the mesh or each device derives a different clip
    scale and silently de-synchronizes the replicated parameters:
    ``axes`` names the mesh axes to psum the squared norm over, and
    ``sharded`` (a key-path predicate) marks which leaves are local shards
    — replicated leaves are counted once outside the psum. Engines whose
    optimizer.update runs on shard-local gradients rewrap the clip with
    the right axes automatically (see GPipe / ExpertParallel); engines
    that aggregate gradients before the update (DP, CP) and GSPMD-jitted
    engines (where ``jnp.sum`` over a sharded array is already global)
    need no axes.
    """

    base: Optimizer = None  # type: ignore[assignment]
    max_norm: float = 1.0
    axes: tuple = ()
    sharded: Any = None  # Callable[[key_path], bool]; None = every leaf local

    def __post_init__(self):
        if self.base is None:
            raise ValueError("ClipByGlobalNorm needs a base optimizer")

    def init(self, params):
        return self.base.init(params)

    def init_spec(self, param_specs):
        return self.base.init_spec(param_specs)

    def update(self, grads, state, params):
        zero = jnp.zeros((), jnp.float32)
        if not self.axes:
            sq = sum(
                (jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in jax.tree.leaves(grads)),
                zero,
            )
        else:
            local = rep = zero
            for path, g in jax.tree_util.tree_flatten_with_path(grads)[0]:
                s = jnp.sum(jnp.square(g.astype(jnp.float32)))
                if self.sharded is None or self.sharded(path):
                    local = local + s
                else:
                    rep = rep + s
            sq = jax.lax.psum(local, self.axes) + rep
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(norm, 1e-12))
        grads = jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)
        return self.base.update(grads, state, params)


def shard_aware_clip(opt: Optimizer, axes: tuple, sharded) -> Optimizer:
    """Rewrap any :class:`ClipByGlobalNorm` in the optimizer chain (when
    the caller didn't already set ``axes``) so its norm reduces across the
    engine's mesh axes. Engines whose ``optimizer.update`` runs on
    device-local gradient shards call this on their optimizer at
    construction. Recurses through ``.base`` wrapper chains (clip under
    clip today — ``Scheduled`` rejects a clip base at construction — and
    any future wrapper with a ``.base``): a clip nested below the top of
    the chain would otherwise silently compute per-shard norms inside
    shard_map and de-synchronize replicated params (ADVICE r2)."""
    import dataclasses

    if isinstance(opt, ClipByGlobalNorm) and not opt.axes:
        opt = dataclasses.replace(opt, axes=tuple(axes), sharded=sharded)
        # fall through: the clip's own .base may nest another clip
    base = getattr(opt, "base", None)
    if isinstance(base, Optimizer):
        new_base = shard_aware_clip(base, axes, sharded)
        if new_base is not base:
            opt = dataclasses.replace(opt, base=new_base)
    return opt


def make_optimizer(
    name: str, lr: float, momentum: float = 0.0, weight_decay: float = 0.01
) -> Optimizer:
    """Factory used by the task entrypoints' ``--optimizer`` flag."""
    name = name.lower()
    if name == "gd":
        return GradientDescent(lr=lr)
    if name == "sgd":
        return Sgd(lr=lr, momentum=momentum)
    if name == "adam":
        return Adam(lr=lr)
    if name == "adamw":
        return AdamW(lr=lr, weight_decay=weight_decay)
    if name in ("adam_ref", "reference_adam"):
        return ReferenceAdam(lr=lr)
    raise ValueError(f"unknown optimizer {name!r}")
