"""ZeRO-1 weight-update sharding as a pure optimizer-wrapper transform.

The transform from "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv 2004.13336 — the paper this repo's FSDP
cites): instead of every data replica applying the FULL optimizer update
to a replicated state, reduce-scatter the gradients over the data axis,
update a 1/N shard of the parameters + optimizer state per chip, then
all_gather the updated parameters. Total communication volume equals the
ring-allreduce the replicated update already paid (allreduce = reduce-
scatter + all_gather), but optimizer FLOPs and optimizer-state HBM both
drop by the axis size N.

Design: :class:`ZeRO1` wraps ANY :class:`Optimizer` — the base optimizer
never learns about sharding; it simply runs on flattened 1/N chunk leaves.
Layout is per-leaf flatten-and-chunk: each parameter leaf is raveled,
zero-padded to a multiple of ``world``, and reduce-scattered along that
flat dim, so non-divisible shapes need no per-shape special cases. The
zero padding is exact for every optimizer in the repo: a zero gradient
keeps zero moments and produces a zero update, and decoupled weight decay
on a zero parameter is zero.

Because ``psum_scatter(g)/N`` over N replicas of the SAME value returns
that value, ``update`` is idempotent with respect to a prior ``pmean`` —
callers that already aggregated (PP×DP keeps its metrics pmean) stay
exact; callers that skip aggregation (DataParallel ``zero1=True``) get
the mean for free from the reduce-scatter itself.

Engines with stage-stacked parameter leaves (the pipelines' ``stages``
subtree, leading dim sharded over ``stage``) set the ``stacked`` key-path
predicate: those leaves flatten per-stage-row to ``[S, N·c]`` so the
optimizer-state spec ``P(stage, data)`` composes both shardings.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpudml.capabilities import reject
from tpudml.optim.optimizers import ClipByGlobalNorm, Optimizer, shard_aware_clip

PyTree = Any


def _chain_has_clip(opt: Optimizer) -> bool:
    while isinstance(opt, Optimizer):
        if isinstance(opt, ClipByGlobalNorm):
            return True
        opt = getattr(opt, "base", None)
    return False


def _flat_pad(x: jax.Array, world: int) -> jax.Array:
    """Ravel + zero-pad to a multiple of ``world`` (scalars become [1])."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    c = -(-n // world)
    pad = world * c - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _rows_pad(x: jax.Array, world: int) -> jax.Array:
    """Stacked-leaf layout: [S, ...] -> [S, world*c], zero-padded columns."""
    rows = x.reshape(x.shape[0], -1)
    n = rows.shape[1]
    c = -(-n // world)
    pad = world * c - n
    if pad:
        rows = jnp.concatenate(
            [rows, jnp.zeros((rows.shape[0], pad), rows.dtype)], axis=1
        )
    return rows


@dataclass(frozen=True)
class ZeRO1(Optimizer):
    """Weight-update-sharding wrapper: ``base`` runs on 1/N chunk leaves.

    ``world`` must be the static size of ``axis_name`` on the mesh the
    engine runs on (``mesh.shape[axis_name]``) — ``init``/``init_spec``
    run OUTSIDE shard_map where the axis is not bound, so the size cannot
    be inferred. ``stacked`` (optional key-path predicate) marks leaves
    whose LEADING dim is a stage-stacked dim sharded over another mesh
    axis (the pipelines' ``stages`` subtree); those keep that dim and
    chunk the flattened remainder.

    Must be the OUTERMOST optimizer wrapper: any :class:`ClipByGlobalNorm`
    in the chain below is rewrapped at construction to psum its norm over
    the data axis (chunk leaves are disjoint across it, so the psum'd
    chunk norm IS the global norm of the mean gradient — clip-then-update
    stays exact vs replicated DP). With ``stacked`` set, a clip in the
    chain is rejected: stacked chunks shard over two axes with different
    replication per leaf, which the two-bucket clip model cannot express.
    """

    base: Optimizer = None  # type: ignore[assignment]
    axis_name: str = "data"
    world: int = None  # type: ignore[assignment]
    stacked: Callable[[tuple], bool] | None = None

    def __post_init__(self):
        if self.base is None:
            raise ValueError("ZeRO1 needs a base optimizer")
        if not isinstance(self.world, int) or self.world < 1:
            raise ValueError(
                "ZeRO1 needs the static data-axis size: pass "
                "world=mesh.shape[axis_name]"
            )
        if _chain_has_clip(self.base):
            if self.stacked is not None:
                reject("zero1_stacked_clip")
            object.__setattr__(
                self,
                "base",
                shard_aware_clip(self.base, (self.axis_name,), None),
            )

    # -- layout helpers ---------------------------------------------------

    def _is_stacked(self, path) -> bool:
        return self.stacked is not None and self.stacked(path)

    def _chunk_len(self, n: int) -> int:
        return -(-n // self.world)

    def flatten_params(self, params: PyTree) -> PyTree:
        """FULL (unsharded) flat-padded layout of every leaf: ``[N·c]``,
        or ``[S, N·c]`` for stacked leaves. This is the global shape of
        the optimizer-state moment leaves; engines that carry parameter
        SHARDS in TrainState (the overlap variant) device_put this tree
        with the ``init_spec`` shardings."""
        return jax.tree_util.tree_map_with_path(
            lambda path, p: (
                _rows_pad(p, self.world)
                if self._is_stacked(path)
                else _flat_pad(p, self.world)
            ),
            params,
        )

    # -- Optimizer contract -----------------------------------------------

    def init(self, params):
        return self.base.init(self.flatten_params(params))

    def init_spec(self, param_specs):
        """Map the (possibly prefix) param spec tree to chunk-layout
        specs: ``P(axis)`` flat leaves, ``P(stage_axes, axis)`` for
        stacked leaves (dim0 keeps whatever the param spec sharded the
        stage dim over), then defer to ``base.init_spec`` so moment
        leaves inherit the chunk specs and scalars stay replicated."""

        def spec_leaf(path, spec):
            if self._is_stacked(path):
                lead = spec[0] if len(spec) else None
                return P(lead, self.axis_name)
            return P(self.axis_name)

        specs = jax.tree_util.tree_map_with_path(
            spec_leaf, param_specs, is_leaf=lambda x: isinstance(x, P)
        )
        return self.base.init_spec(specs)

    def scatter_grads(self, grads: PyTree) -> PyTree:
        """Reduce-scatter-MEAN each leaf over the data axis: this chip
        keeps the ``axis_index``-th chunk of the mean gradient. Exact
        whether or not the grads were already pmean'd (N identical
        copies sum to N× the value; /N restores it)."""

        def scatter(path, g):
            if self._is_stacked(path):
                rows = _rows_pad(g, self.world)
                chunk = lax.psum_scatter(
                    rows, self.axis_name, scatter_dimension=1, tiled=True
                )
            else:
                flat = _flat_pad(g, self.world)
                chunk = lax.psum_scatter(
                    flat, self.axis_name, scatter_dimension=0, tiled=True
                )
            return chunk / self.world

        return jax.tree_util.tree_map_with_path(scatter, grads)

    def shard_params(self, params: PyTree) -> PyTree:
        """Slice this chip's chunk out of (replicated) full param leaves."""
        idx = lax.axis_index(self.axis_name)

        def shard(path, p):
            if self._is_stacked(path):
                rows = _rows_pad(p, self.world)
                c = rows.shape[1] // self.world
                return lax.dynamic_slice(rows, (0, idx * c), (rows.shape[0], c))
            flat = _flat_pad(p, self.world)
            c = flat.shape[0] // self.world
            return lax.dynamic_slice(flat, (idx * c,), (c,))

        return jax.tree_util.tree_map_with_path(shard, params)

    def gather_params(self, chunks: PyTree, template: PyTree) -> PyTree:
        """All_gather chunk leaves back to full leaves shaped like
        ``template`` (arrays or ShapeDtypeStructs with the ORIGINAL param
        shapes); the zero padding is sliced off before reshaping."""

        def gather(path, ch, p):
            if self._is_stacked(path):
                full = lax.all_gather(ch, self.axis_name, axis=1, tiled=True)
                n = math.prod(p.shape[1:]) if len(p.shape) > 1 else 1
                return full[:, :n].reshape(p.shape)
            full = lax.all_gather(ch, self.axis_name, axis=0, tiled=True)
            n = math.prod(p.shape)
            return full[:n].reshape(p.shape)

        return jax.tree_util.tree_map_with_path(gather, chunks, template)

    def update_shards(self, grads, state, param_chunks):
        """The sharded update WITHOUT the trailing all_gather: returns
        ``(new_param_chunks, new_state)``. The overlap engine carries
        chunks across steps and gathers at the START of the next step so
        XLA can overlap the gather with the first microbatch's forward."""
        gchunks = self.scatter_grads(grads)
        return self.base.update(gchunks, state, param_chunks)

    def update(self, grads, state, params):
        """Full ZeRO-1 step (inside shard_map, ``axis_name`` bound,
        ``grads``/``params`` replicated-or-local full leaves, ``state``
        local chunk leaves): reduce-scatter -> 1/N base update ->
        all_gather updated params."""
        gchunks = self.scatter_grads(grads)
        pchunks = self.shard_params(params)
        new_chunks, new_state = self.base.update(gchunks, state, pchunks)
        return self.gather_params(new_chunks, params), new_state


def zero1_handles(optimizer, axis_name: str) -> bool:
    """True when ``optimizer`` is a ZeRO1 over ``axis_name`` — engines use
    this to SKIP their pre-update gradient pmean over that axis (the
    reduce-scatter inside ``update`` performs the mean; a prior pmean is
    harmlessly exact but doubles the gradient traffic)."""
    return isinstance(optimizer, ZeRO1) and optimizer.axis_name == axis_name


def stages_stacked(path) -> bool:
    """The pipelines' stacked-leaf predicate: leaves under the top-level
    ``stages`` key carry a leading stage-sharded dim. GPipe fills this
    into a ``stacked=None`` ZeRO1 automatically at construction."""
    return bool(path) and getattr(path[0], "key", None) == "stages"


def with_stacked(opt: ZeRO1, pred: Callable[[tuple], bool]) -> ZeRO1:
    """Return ``opt`` with its ``stacked`` predicate filled (no-op when
    already set)."""
    if opt.stacked is not None:
        return opt
    return dataclasses.replace(opt, stacked=pred)
