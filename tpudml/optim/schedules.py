"""Learning-rate schedules + a schedule-driving optimizer wrapper.

The reference trains with constants (lrs hardcoded per task, SURVEY.md
§5.6). Schedules are pure ``step -> lr`` functions; ``Scheduled`` wraps
any tpudml optimizer, tracking the step count in its own state and
re-deriving the wrapped optimizer's lr each update — everything stays a
pure pytree transform, jit/shard-compatible, and the optimizer-state
sharding contract (``init_spec``) passes straight through.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpudml.optim.optimizers import Optimizer


def constant(lr: float) -> Callable:
    return lambda step: jnp.asarray(lr, jnp.float32)


def cosine_decay(lr: float, decay_steps: int, alpha: float = 0.0) -> Callable:
    """lr · (α + (1-α)·(1+cos(π·t/T))/2), clamped after T."""

    def schedule(step):
        frac = jnp.clip(step / max(decay_steps, 1), 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return lr * (alpha + (1.0 - alpha) * cos)

    return schedule


def linear_warmup(lr: float, warmup_steps: int) -> Callable:
    """0 → lr over ``warmup_steps``, constant after."""

    def schedule(step):
        return lr * jnp.clip((step + 1) / max(warmup_steps, 1), 0.0, 1.0)

    return schedule


def warmup_cosine(
    lr: float, warmup_steps: int, total_steps: int, alpha: float = 0.0
) -> Callable:
    """Linear warmup into a cosine decay — the standard transformer recipe."""
    decay = cosine_decay(lr, max(total_steps - warmup_steps, 1), alpha)

    def schedule(step):
        return jnp.where(
            step < warmup_steps,
            lr * (step + 1) / max(warmup_steps, 1),
            decay(step - warmup_steps),
        )

    return schedule


def step_decay(lr: float, step_size: int, gamma: float = 0.1) -> Callable:
    """lr · γ^floor(t/step_size) (torch StepLR semantics)."""

    def schedule(step):
        return lr * gamma ** jnp.floor(step / max(step_size, 1))

    return schedule


@dataclass(frozen=True)
class Scheduled(Optimizer):
    """Drive ``base``'s learning rate from ``schedule(step)``.

    Usage::

        opt = Scheduled(Sgd(momentum=0.9), warmup_cosine(0.1, 100, 1000))
    """

    base: Optimizer
    schedule: Callable

    def __post_init__(self):
        # update() swaps the lr via dataclasses.replace — fail at
        # construction, not mid-jit-trace, if the base can't support that.
        if not dataclasses.is_dataclass(self.base) or not any(
            f.name == "lr" for f in dataclasses.fields(self.base)
        ):
            raise ValueError(
                f"Scheduled needs a dataclass optimizer with an 'lr' field; "
                f"got {type(self.base).__name__}"
            )

    def init(self, params):
        return {
            "inner": self.base.init(params),
            "t": jnp.zeros((), jnp.int32),
        }

    def init_spec(self, param_specs):
        return {"inner": self.base.init_spec(param_specs), "t": P()}

    def update(self, grads, state, params):
        lr = self.schedule(state["t"])
        # Re-instantiate the wrapped optimizer with the scheduled lr (a
        # traced scalar); its update math is unchanged.
        inner_opt = dataclasses.replace(self.base, lr=lr)
        new_params, inner_state = inner_opt.update(grads, state["inner"], params)
        return new_params, {"inner": inner_state, "t": state["t"] + 1}

    def current_lr(self, state) -> jax.Array:
        return self.schedule(state["t"])
