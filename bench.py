"""Benchmark entrypoint (driver contract: prints ONE JSON line).

Headline = the north-star metric (BASELINE.json): steady-state CIFAR-10
ResNet-18 training throughput in images/sec/chip, bfloat16 compute on the
MXU. A transformer-LM tokens/sec/chip secondary metric (task5's flagship
model, flash attention on TPU) tracks the sequence workload too.

Three timing protocols (VERDICT round 2, item 1 — the honest clock):

- ``fori`` (HEADLINE): K train steps inside ONE XLA dispatch via
  ``lax.fori_loop``; the device cannot elide or overlap them, and the
  measurement syncs by fetching the final loss to the host (a
  device->host copy cannot complete before the value exists). Per-step
  time is differenced between two trip counts, which cancels dispatch +
  transfer overhead. This is the artifact-proof number: its MFU must be
  <= 1.0 on working hardware.
- ``synced``: one dispatch per step, host-fetching the loss every step.
  Includes per-step dispatch/transfer latency — the lower bound a naive
  eager-style loop would see.
- ``pipelined`` (legacy, rounds 1-2 protocol): chained donated-state
  dispatches, sync once at the end via ``block_until_ready``. Through
  the tunneled relay this measured dispatch throughput, not compute
  (r2: 18.2x "MFU") — kept only for continuity with prior recordings;
  ``mfu_pipelined_artifact`` flags it independently when it exceeds peak.

``mfu`` = flops_per_step (XLA compiled cost analysis of the single-chip
step) / sec_per_step(fori) / chip bf16 peak.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6": 918e12,  # Trillium
}


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_BF16.items():
        if key in kind:
            return peak
    return None


def _compiled_flops(fn, *args) -> float | None:
    """FLOPs of one call from XLA's cost analysis (None if unavailable).
    ``fn`` may already be jitted (lowered directly — nothing executes, so
    donated arguments are safe to pass)."""
    try:
        if not hasattr(fn, "lower"):
            fn = jax.jit(fn)
        cost = fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops or None
    except Exception:
        return None


def _fetch(x) -> float:
    """Host materialization as the sync barrier. ``block_until_ready``
    through the tunneled relay has been observed to return before the
    device finishes (r2's >100%-of-peak artifact); a device->host copy of
    the value itself cannot."""
    return float(jax.device_get(x))


def _make_step_body(model, optimizer):
    """(ts, images, labels) -> (new_ts, loss): the real training step body
    (shared with make_train_step, so the bench times what training runs)."""
    from tpudml.train import make_train_step_body

    step = make_train_step_body(model, optimizer)

    def body(ts, images, labels):
        new_ts, metrics = step(ts, images, labels)
        return new_ts, metrics["loss"]

    return body


def _time_fori(body, ts, batch, k_lo, k_hi, reps=3):
    """Artifact-proof seconds/step: run K steps inside ONE dispatch, sync by
    fetching the final loss, difference two trip counts to cancel the
    constant dispatch + transfer overhead. ``k`` is a dynamic argument so
    both trip counts share one compiled program.

    Returns ``(median, runs)``: the whole differencing is repeated
    ``reps`` times and the MEDIAN is the headline, so a single noisy rep
    can neither inflate nor deflate the recorded number (VERDICT r3
    item 7 — r3 shipped a below-pin artifact from a one-shot run while
    BASELINE.md carried a better best-of-round); ``runs`` lets the
    artifact record the spread."""
    import statistics

    @jax.jit
    def run(ts, images, labels, k):
        def one(_, carry):
            ts, _ = carry
            return body(ts, images, labels)

        return jax.lax.fori_loop(0, k, one, (ts, jnp.zeros((), jnp.float32)))

    images, labels = batch

    def timed(k) -> float:
        t0 = time.perf_counter()
        _, loss = run(ts, images, labels, k)
        _fetch(loss)
        return time.perf_counter() - t0

    timed(2)  # compile + warm
    runs = []
    for _ in range(reps):
        # Symmetric sampling (min of 2 each) so a one-off tunnel hiccup on
        # either trip count cannot bias or sign-flip the difference.
        t_lo = min(timed(k_lo) for _ in range(2))
        t_hi = min(timed(k_hi) for _ in range(2))
        if t_hi <= t_lo:
            # Degenerate measurement (jitter swamped the spread): fall
            # back to the k_hi run including overhead — an upper bound on
            # sec/step, never a garbage near-zero headline.
            runs.append(t_hi / k_hi)
        else:
            runs.append((t_hi - t_lo) / (k_hi - k_lo))
    return statistics.median(runs), runs


def _time_synced(step, ts, batch, iters):
    """One dispatch per step, host sync (loss fetch) every step. ``step``
    is a (ts, *batch) -> (ts, loss) body (jitted or not)."""
    for _ in range(3):
        ts, loss = step(ts, *batch)
        _fetch(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, loss = step(ts, *batch)
        _fetch(loss)
    return (time.perf_counter() - t0) / iters


def _time_pipelined(step, ts, batch, iters):
    """Rounds 1-2 protocol: chained donated-state dispatches, one sync at
    the end. Protocol-relative through the tunneled relay (see module
    docstring) — NOT the headline."""
    for _ in range(3):
        ts, m = step(ts, *batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, m = step(ts, *batch)
    jax.block_until_ready(m["loss"])
    return (time.perf_counter() - t0) / iters


def _mfu_fields(flops_per_step, sec_fori, sec_synced, sec_pipelined, peak,
                fori_runs=None):
    fields = {
        "sec_per_step": round(sec_fori, 6),
        "sec_per_step_synced": round(sec_synced, 6),
        "sec_per_step_pipelined": round(sec_pipelined, 6),
        "protocol": "fori",
    }
    if fori_runs:
        # Median-of-N protocol (VERDICT r3 item 7): publish the spread so
        # the artifact itself shows whether a delta is signal or jitter.
        fields["sec_per_step_runs"] = [round(s, 6) for s in sorted(fori_runs)]
        fields["fori_spread"] = round(
            (max(fori_runs) - min(fori_runs)) / sec_fori, 4
        )
    if flops_per_step and peak:
        mfu = flops_per_step / sec_fori / peak
        mfu_pipe = flops_per_step / sec_pipelined / peak
        fields.update(
            flops_per_step=round(flops_per_step),
            mfu=round(mfu, 4),
            # The fori protocol cannot exceed peak on working hardware; a
            # True here means the measurement itself is broken.
            mfu_artifact=bool(mfu > 1.0),
            mfu_pipelined=round(mfu_pipe, 4),
            # The pipelined protocol CAN exceed peak through the relay
            # (r2's 18x) — flagged independently of the headline.
            mfu_pipelined_artifact=bool(mfu_pipe > 1.0),
        )
    return fields


def bench_resnet(on_tpu: bool, n_devices: int) -> dict:
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_classification
    from tpudml.models import ResNet18
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel
    from tpudml.train import TrainState

    # 1024/chip keeps the MXU fed and amortizes dispatch; fits v5e HBM
    # comfortably for CIFAR-sized inputs. CPU dev mode stays tiny: XLA CPU
    # executes conv bodies inside while-loops ~25x slower than the plain
    # step (observed 30.8 vs 1.25 s/step at batch 16), so the fori smoke
    # must be minimal there.
    per_chip_batch = 1024 if on_tpu else 8
    batch = per_chip_batch * n_devices
    images, labels = synthetic_classification(batch, (32, 32, 3), 10, seed=0)
    images, labels = jnp.asarray(images), jnp.asarray(labels)

    model = ResNet18(compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    opt = make_optimizer("sgd", 0.1, momentum=0.9)

    # Headline clock: single-chip step body under fori (what imgs/sec/CHIP
    # and MFU measure; the DP collective is timed by the pipelined path).
    chip_batch = (images[:per_chip_batch], labels[:per_chip_batch])
    body = _make_step_body(model, opt)
    ts0 = TrainState.create(model, opt, seed_key(0))
    sec_fori, fori_runs = _time_fori(
        body, ts0, chip_batch,
        *((8, 40) if on_tpu else (1, 3)), reps=3 if on_tpu else 1,
    )

    step1 = jax.jit(body)
    sec_synced = _time_synced(step1, ts0, chip_batch, 10 if on_tpu else 2)

    mesh = make_mesh(MeshConfig(axes={"data": n_devices}), jax.devices())
    dp = DataParallel(model, opt, mesh, stacked_batches=False)
    sec_pipe = _time_pipelined(
        dp.make_train_step(), dp.create_state(seed_key(0)),
        (images, labels), 30 if on_tpu else 3,
    )

    # FLOPs from the single-chip step on the per-chip batch (what each
    # chip executes; collectives excluded, matching the per-chip metric).
    # ts0 is safe to pass: step1 does not donate and lowering executes
    # nothing.
    flops = _compiled_flops(step1, ts0, *chip_batch)
    return {
        # "_fori" names the protocol (ADVICE r3): the pre-r3 metric
        # "cifar10_resnet18_train_imgs_per_sec_per_chip" measured the
        # multi-device pipelined step and its history is NOT comparable
        # to this single-chip fori number.
        "metric": "cifar10_resnet18_train_imgs_per_sec_per_chip_fori",
        "value": round(per_chip_batch / sec_fori, 1),
        "unit": "imgs/sec/chip",
        "value_synced": round(per_chip_batch / sec_synced, 1),
        "value_pipelined": round(batch / sec_pipe / max(n_devices, 1), 1),
        **_mfu_fields(flops, sec_fori, sec_synced, sec_pipe,
                      _peak_flops(jax.devices()[0]), fori_runs),
    }


def _analytic_lm_flops(cfg, batch: int, seq_len: int) -> float:
    """Matmul-math FLOPs per train step of the decoder LM, counted
    analytically: XLA's cost analysis cannot see inside Pallas custom
    calls (flash attention, fused add+LN, fused linear-cross-entropy),
    so as more of the model moves into kernels the cost-analysis MFU
    silently DEFLATES (the fused-xent step dropped it to 0.26 while
    getting FASTER). Convention (PaLM-style strict matmul accounting):
    2 FLOP/MAC, backward = 2× forward (dX + dW), causal attention counts
    the ~half of the score/value matmuls actually computed, elementwise/
    norm/embedding-gather work excluded. GQA (``num_kv_heads``) shrinks
    the k/v projections to 2·d·(kv_heads·dh)."""
    d, L, V = cfg["embed_dim"], cfg["num_layers"], cfg["vocab_size"]
    # num_heads only matters under GQA (kv_heads < heads shrinks the k/v
    # projections); MHA callers (tools/ablate_lm.py) may omit both. A cfg
    # with kv_heads but no heads would silently inflate the k/v term under
    # the heads=1 fallback (dh would be d), so reject it loudly.
    heads = cfg.get("num_heads") or 1
    if cfg.get("num_kv_heads") and not cfg.get("num_heads"):
        raise ValueError("cfg sets num_kv_heads but not num_heads")
    kv_heads = cfg.get("num_kv_heads") or heads
    dh = d // heads
    tokens = batch * seq_len
    # Per layer: q d² + out-proj d² + k/v 2·d·(kv·dh) + fc1/fc2 2·4d²;
    # head d·V.
    per_layer = 2 * d * d + 2 * d * (kv_heads * dh) + 8 * d * d
    matmul_params = L * per_layer + d * V
    matmul = 6 * tokens * matmul_params
    # Full attention fwd 4·B·T²·d + bwd 8·B·T²·d = 12·B·T²·d; causal ≈ ½.
    # (GQA shares k/v across query heads — the score/value matmul FLOPs
    # are unchanged: every query head still contracts against T keys.)
    attn = 6 * L * batch * seq_len * seq_len * d
    return float(matmul + attn)


def bench_transformer(on_tpu: bool, large: bool = False) -> dict:
    """task5 flagship: decoder LM, flash attention on TPU, bf16, fused
    add+LN junctions, fused linear-cross-entropy head (save-scores speed
    mode) — the fastest exported train-step path.

    ``large=True`` is the chip-filling config (VERDICT r4 item 3): d=1024
    (8 heads × dh 128), L=12, GQA 4:1, T=2048 — ~218M params, 16k tokens
    per step, sized so the MXU sees big contractions and the 50%-MFU
    claim is tested at a scale that exercises HBM, not just caches."""
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM
    from tpudml.optim import make_optimizer
    from tpudml.train import (
        TrainState,
        make_lm_fused_train_step,
        make_lm_fused_train_step_body,
    )

    if on_tpu and large:
        cfg = dict(vocab_size=32768, embed_dim=1024, num_heads=8,
                   num_layers=12, num_kv_heads=2)
        seq_len, batch = 2048, 8
    elif on_tpu:
        # head_dim 128 (4 heads at d=512), matching the MXU/VPU 128-lane
        # geometry: dh=64 half-fills the contraction dim of every
        # attention matmul and the lane dim of every Q/O tile (measured
        # 36.8 -> 25.4 ms/step on v5e, same parameter count and FLOPs).
        cfg = dict(vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6)
        seq_len, batch = 1024, 8
    elif large:  # CPU smoke of the large path: GQA plumbing only
        cfg = dict(vocab_size=256, embed_dim=64, num_heads=4, num_layers=2,
                   num_kv_heads=2)
        seq_len, batch = 128, 4
    else:  # dev smoke on CPU: keep it seconds, not minutes
        cfg = dict(vocab_size=256, embed_dim=64, num_heads=4, num_layers=2)
        seq_len, batch = 128, 4
    model = TransformerLM(
        **cfg,
        max_len=seq_len,
        impl="flash" if on_tpu else "full",
        rope=True,
        # Master-weight mixed precision: f32 params (the optimizer state),
        # bf16 MXU compute, f32 norms/softmax/logits.
        compute_dtype=jnp.bfloat16 if on_tpu else None,
        # Fused residual-add+LN junction kernels: measured 20.88 →
        # 18.68 ms/step on v5e at this config (BASELINE.md round 4).
        fused_ln=on_tpu,
    )
    opt = make_optimizer("adamw", 3e-4)
    # synthetic_lm returns [n, seq_len+1] ALREADY (slice x/y from it) —
    # passing seq_len+1 here would train at T = seq_len+1, a block-
    # misaligned length that every flash kernel pads up per layer per
    # direction (the r1-r3 recordings did exactly that: T=1025).
    seqs = jnp.asarray(synthetic_lm(batch, seq_len, cfg["vocab_size"], seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]

    # The fused linear-cross-entropy head in save-scores speed mode:
    # measured 21.6 → 18.0 ms/step vs the materialized-logits step at
    # this config (BASELINE.md round 4). V=32k at B·T=8k fits the f32
    # score residual comfortably on-chip.
    fused_body = make_lm_fused_train_step_body(model, opt, save_scores=on_tpu)

    def body(ts, tokens_in, labels):
        new_ts, metrics = fused_body(ts, tokens_in, labels)
        return new_ts, metrics["loss"]

    ts0 = TrainState.create(model, opt, seed_key(0))
    sec_fori, fori_runs = _time_fori(
        body, ts0, (x, y),
        *((8, 40) if on_tpu else (1, 3)), reps=3 if on_tpu else 1,
    )

    step1 = jax.jit(body)
    sec_synced = _time_synced(step1, ts0, (x, y), 10 if on_tpu else 2)
    step = make_lm_fused_train_step(model, opt, save_scores=on_tpu)
    sec_pipe = _time_pipelined(
        step, TrainState.create(model, opt, seed_key(0)), (x, y),
        20 if on_tpu else 3,
    )
    # Analytic matmul FLOPs (docstring of _analytic_lm_flops: the Pallas
    # kernels hide their FLOPs from XLA's cost analysis); the XLA number
    # rides along for the record.
    flops = _analytic_lm_flops(cfg, batch, seq_len)
    flops_xla = _compiled_flops(step1, ts0, x, y)
    tokens = batch * seq_len
    return {
        # "_fori" versions the protocol (ADVICE r3), as for the headline.
        "metric": "transformer_lm_large_train_tokens_per_sec_per_chip_fori"
        if large else "transformer_lm_train_tokens_per_sec_per_chip_fori",
        "config": {**cfg, "seq_len": seq_len, "batch": batch},
        "value": round(tokens / sec_fori, 1),
        "unit": "tokens/sec/chip",
        "value_synced": round(tokens / sec_synced, 1),
        "value_pipelined": round(tokens / sec_pipe, 1),
        "flops_source": "analytic_model_math",
        "flops_per_step_xla": round(flops_xla) if flops_xla else None,
        **_mfu_fields(flops, sec_fori, sec_synced, sec_pipe,
                      _peak_flops(jax.devices()[0]), fori_runs),
        **_residual_fields(cfg, batch, seq_len, on_tpu),
    }


def _residual_fields(cfg, batch, seq_len, on_tpu) -> dict:
    """Round-20 per-residual breakdown for the flagship row: fori-timed
    ms/step of the two non-MXU residual sites this round fused — the
    decode head tail (``ops.fused_decode_head`` at the flagship head
    shape) and one step's worth of block junctions
    (``ops.fused_attn_junction`` chained ``num_layers`` deep) — so
    BENCH_r06+ tracks the residuals shrinking next to ``mfu``.
    ``exposed_comm_ms`` is structurally 0.0 on the single-chip flagship
    row; the multi-chip rows (``--zero1``) carry the measured
    exposed-vs-hidden attribution from ``overlap_report``, and the
    planner's per-candidate split lives in plan.json."""
    import numpy as np

    from tpudml.ops.decode_head import fused_decode_head
    from tpudml.ops.junction_kernel import fused_attn_junction

    d, heads, L = cfg["embed_dim"], cfg["num_heads"], cfg["num_layers"]
    v, dh = cfg["vocab_size"], d // heads
    rng = np.random.default_rng(0)
    f32 = lambda *s: jnp.asarray(rng.normal(size=s).astype(np.float32))
    ks = ((8, 40) if on_tpu else (1, 3))
    reps = 3 if on_tpu else 1

    # Head tail at the decode shape: [batch, d] features into the [d, V]
    # head. The 1e-20·carry term threads a loop-carried dependency so
    # fori iterations cannot collapse; it never changes the measured math.
    h, w = f32(batch, d), f32(d, v) * 0.1

    def head_body(ts, h, w):
        _, _, lse = fused_decode_head(h + ts * 1e-20, w)
        out = jnp.sum(lse)
        return out, out

    head_s, head_runs = _time_fori(head_body, jnp.zeros(()), (h, w), *ks,
                                   reps=reps)

    # One step's junctions: L fused attention junctions chained through
    # the residual stream (each layer's s feeds the next), the train
    # trunk's per-step junction count.
    q, k, vv = f32(batch, seq_len, heads, dh), f32(batch, seq_len, heads, dh), \
        f32(batch, seq_len, heads, dh)
    wo, bo = f32(d, d) * 0.1, f32(d)
    g, b2 = f32(d), f32(d)

    def junction_body(ts, q, r):
        r = r + ts * 1e-20
        y = r
        for _ in range(L):
            r, y = fused_attn_junction(q, k, vv, r, wo, bo, g, b2)
        out = jnp.sum(y)
        return out, out

    junc_s, junc_runs = _time_fori(
        junction_body, jnp.zeros(()), (q, f32(batch, seq_len, d)), *ks,
        reps=reps)

    return {
        "head_ms": round(head_s * 1e3, 4),
        "junction_ms": round(junc_s * 1e3, 4),
        "exposed_comm_ms": 0.0,  # single-chip row: no wire to expose
        "residual_runs_ms": {
            "head": [round(s * 1e3, 4) for s in sorted(head_runs)],
            "junction": [round(s * 1e3, 4) for s in sorted(junc_runs)],
        },
    }


def _bytes_on_device0(tree) -> int:
    """Bytes of ``tree``'s leaves resident on device 0 — the per-chip
    memory footprint, read from the arrays' addressable shards (a
    replicated leaf counts its FULL size; a sharded leaf only its local
    slice), so the replicated-vs-ZeRO-1 HBM delta is measured, not
    inferred."""
    dev0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            total += getattr(leaf, "nbytes", 0)
            continue
        total += sum(s.data.nbytes for s in shards if s.device == dev0)
    return total


def bench_zero1(on_tpu: bool, n_devices: int) -> dict:
    """``--zero1`` mode: the ZeRO-1 comparison protocol (BASELINE.md).

    Four engines train the SAME flagship LM on the SAME ``{"data": N}``
    mesh with the SAME global batch, so every delta is the weight-update
    strategy and nothing else:

    - ``dp_replicated``  — allreduce grads, every chip runs the full update
    - ``dp_zero1``       — reduce-scatter grads, 1/N update, all_gather params
    - ``dp_zero1_overlap`` — double-buffered variant (gather at step START,
      accum_steps=2 so compute exists to hide it under)
    - ``fsdp``           — 1-D param sharding, the other point on the
      memory/comm trade-off curve

    Per engine: pipelined sec/step (the engines' donated-state protocol —
    fine for RELATIVE comparison on one box; the fori headline stays the
    absolute clock) plus per-chip param and optimizer-state bytes from
    the arrays' addressable shards. The ZeRO-1 rows also carry the
    exposed-vs-hidden comm attribution from ``overlap_report``.
    """
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel
    from tpudml.parallel.fsdp import FSDP

    if on_tpu:
        cfg = dict(vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6)
        seq_len, per_chip_batch, iters = 1024, 8, 20
    else:  # CPU dryrun: tiny LM, enough steps to median away jitter
        cfg = dict(vocab_size=256, embed_dim=64, num_heads=4, num_layers=2)
        seq_len, per_chip_batch, iters = 128, 4, 6
    batch = per_chip_batch * n_devices
    model = TransformerLM(
        **cfg,
        max_len=seq_len,
        impl="flash" if on_tpu else "full",
        rope=True,
        compute_dtype=jnp.bfloat16 if on_tpu else None,
        fused_ln=on_tpu,
    )
    opt = make_optimizer("adamw", 3e-4)
    seqs = jnp.asarray(synthetic_lm(batch, seq_len, cfg["vocab_size"], seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]

    mesh = make_mesh(MeshConfig(axes={"data": n_devices}), jax.devices())
    fused = True  # the flagship head; composes with zero1 and accum
    engines = {
        "dp_replicated": lambda: DataParallel(
            model, opt, mesh, fused_xent=fused),
        "dp_zero1": lambda: DataParallel(
            model, opt, mesh, fused_xent=fused, zero1=True),
        "dp_zero1_overlap": lambda: DataParallel(
            model, opt, mesh, fused_xent=fused, zero1=True,
            zero1_overlap=True, accum_steps=2),
        "fsdp": lambda: FSDP(model, opt, mesh, fused_xent=fused),
    }

    rows: dict[str, dict] = {}
    reports: dict[str, dict] = {}
    for name, build in engines.items():
        eng = build()
        ts = eng.create_state(seed_key(0))
        row = {
            "params_bytes_per_chip": _bytes_on_device0(ts.params),
            "opt_state_bytes_per_chip": _bytes_on_device0(ts.opt_state),
        }
        step = eng.make_train_step()
        # Bytes were read above; the timing loop is free to donate ts.
        row["sec_per_step"] = round(_time_pipelined(step, ts, (x, y), iters), 6)
        rows[name] = row
        if name in ("dp_zero1", "dp_zero1_overlap"):
            # Fresh (undonated) state for the attribution spans.
            reports[name] = {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in eng.overlap_report(
                    eng.create_state(seed_key(0)), x, y,
                    iters=10 if on_tpu else 4, warmup=2 if on_tpu else 1,
                ).items()
            }

    rep, zro = rows["dp_replicated"], rows["dp_zero1"]
    return {
        "metric": "zero1_weight_update_sharding_comparison",
        "config": {**cfg, "seq_len": seq_len, "global_batch": batch,
                   "n_devices": n_devices, "fused_xent": fused,
                   "optimizer": "adamw"},
        "protocol": "pipelined_relative",
        "on_tpu": on_tpu,
        "rows": rows,
        "opt_state_bytes_ratio_zero1_vs_replicated": round(
            zro["opt_state_bytes_per_chip"] / rep["opt_state_bytes_per_chip"],
            4),
        "sec_per_step_ratio_zero1_vs_replicated": round(
            zro["sec_per_step"] / rep["sec_per_step"], 4),
        "overlap": reports,
    }


def bench_moe(on_tpu) -> dict:
    """``--moe`` report: one LM step time for the three MoE FFN paths —
    gather+capacity, dropless ragged with lax.ragged_dot's stock dW
    transpose, and dropless ragged with the grouped-dW backward
    (ops/moe_kernel.py) — at E ∈ {4, 8}, top-1, on the same trunk/
    protocol as the transformer row (fori differencing, median of 3).
    Single-shard by construction: dispatch='ragged' rejects EP."""
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState

    if on_tpu:
        cfg = dict(vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6)
        seq_len, batch, k_lo, k_hi = 1024, 8, 4, 12
    else:  # CPU dryrun: wiring + ratio sanity, not chip numbers
        cfg = dict(vocab_size=256, embed_dim=64, num_heads=4, num_layers=2)
        seq_len, batch, k_lo, k_hi = 128, 4, 2, 6
    seqs = jnp.asarray(synthetic_lm(batch, seq_len, cfg["vocab_size"], seed=3))
    x, y = seqs[:, :-1], seqs[:, 1:]

    variants = {
        "gather": dict(moe_dispatch="gather"),
        "ragged_stock": dict(moe_dispatch="ragged", moe_ragged_dw="stock"),
        "ragged_grouped": dict(moe_dispatch="ragged", moe_ragged_dw="grouped"),
    }
    rows: dict[str, dict] = {}
    for e in (4, 8):
        for name, kv in variants.items():
            model = TransformerLM(
                **cfg,
                max_len=seq_len,
                impl="flash" if on_tpu else "full",
                rope=True,
                compute_dtype=jnp.bfloat16 if on_tpu else None,
                fused_ln=on_tpu,
                moe_experts=e,
                moe_capacity_factor=1.25,
                moe_top_k=1,
                **kv,
            )
            opt = make_optimizer("adamw", 3e-4)
            ts = TrainState.create(model, opt, seed_key(0))
            body = _make_step_body(model, opt)
            sec, runs = _time_fori(body, ts, (x, y), k_lo, k_hi)
            rows[f"E{e}_{name}"] = {
                "sec_per_step": round(sec, 6),
                "runs": [round(r, 6) for r in runs],
            }
    ratios = {
        f"E{e}_{name}_vs_gather": round(
            rows[f"E{e}_{name}"]["sec_per_step"]
            / rows[f"E{e}_gather"]["sec_per_step"], 4)
        for e in (4, 8)
        for name in ("ragged_stock", "ragged_grouped")
    }
    return {
        "metric": "moe_dispatch_backward_comparison",
        "config": {**cfg, "seq_len": seq_len, "batch": batch,
                   "capacity_factor": 1.25, "top_k": 1,
                   "optimizer": "adamw"},
        "protocol": "fori_median",
        "on_tpu": on_tpu,
        # Off-TPU the grouped path runs its reference segment-einsum, not
        # the Pallas kernel — a CPU row checks wiring, not the kernel.
        "grouped_dw_backend": "pallas" if on_tpu else "reference_einsum",
        "rows": rows,
        "ratios": ratios,
    }


def main_moe() -> None:
    """Driver for ``python bench.py --moe``: prints ONE JSON line, same
    contract as ``main()``, for the MoE dispatch/backward comparison."""
    on_tpu = jax.devices()[0].platform != "cpu"
    print(json.dumps(bench_moe(on_tpu)))


def bench_plan(world: int) -> dict:
    """``--plan`` mode: planner rank order vs measured step times.

    Measures the dryrun weight-update regimes (the ``--zero1`` engine
    set: DP-replicated, ZeRO-1, ZeRO-1+overlap, FSDP — all fused-xent on
    the same ``{"data": world}`` mesh, same flagship LM, same global
    batch) by building each one THROUGH the planner's own
    ``build_candidate``, so the program timed is exactly the program the
    emitted plan describes. The planner then scores the same four
    candidates; the report carries both orderings and the acceptance
    ratio: measured time of the planner's top-1 over the measured best.
    The planner validation test pins ``within_tolerance`` (<= 1.10).
    """
    from tpudml.plan.emit import build_candidate
    from tpudml.plan.score import score_candidate
    from tpudml.plan.space import Candidate, flagship_lm

    spec = flagship_lm()
    mesh = (("data", world),)

    def cand(engine, zero1=False, overlap=False, accum=1):
        return Candidate(
            engine=engine, mesh=mesh, zero1=zero1, zero1_overlap=overlap,
            accum_steps=accum, fused_xent=True, sentinel=False, obs=False,
        )

    named = {
        "dp_replicated": cand("dp"),
        "dp_zero1": cand("zero1", zero1=True),
        "dp_zero1_overlap": cand("zero1", zero1=True, overlap=True, accum=2),
        "fsdp": cand("fsdp"),
    }
    rows: dict[str, dict] = {}
    for name, c in named.items():
        score = score_candidate(spec, c)
        _, ts, step, (x, y) = build_candidate(spec, c)
        sec = _time_pipelined(step, ts, (x, y), iters=6)
        rows[name] = {
            "candidate": c.key(),
            "sec_per_step": round(sec, 6),
            "planner_per_token_s": score.per_token_s,
        }
    planner_order = sorted(
        named, key=lambda n: (rows[n]["planner_per_token_s"], n))
    measured_order = sorted(named, key=lambda n: rows[n]["sec_per_step"])
    for i, n in enumerate(planner_order, 1):
        rows[n]["planner_rank"] = i
    for i, n in enumerate(measured_order, 1):
        rows[n]["measured_rank"] = i
    top1, best = planner_order[0], measured_order[0]
    ratio = rows[top1]["sec_per_step"] / rows[best]["sec_per_step"]
    return {
        "metric": "planner_rank_validation",
        "config": {**spec.to_dict(), "world": world, "fused_xent": True,
                   "optimizer": "adamw"},
        "protocol": "pipelined_relative",
        "rows": rows,
        "planner_order": planner_order,
        "measured_order": measured_order,
        "planner_top1": top1,
        "measured_best": best,
        "top1_vs_best_ratio": round(ratio, 4),
        "tolerance": 1.10,
        "within_tolerance": ratio <= 1.10,
    }


def main_plan() -> None:
    """Driver for ``python bench.py --plan [--world N]``: prints ONE JSON
    line, same contract as ``main()``, for the planner rank validation.
    Self-provisions an 8-device CPU mesh when no accelerator is visible
    (same dance as ``--zero1``)."""
    import os
    import sys

    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ) and not os.environ.get("TPU_NAME"):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    argv = sys.argv[1:]
    world = jax.device_count()
    if "--world" in argv:
        world = min(int(argv[argv.index("--world") + 1]), jax.device_count())
    print(json.dumps(bench_plan(world)))


def bench_serve(on_tpu, smoke=False) -> dict:
    """``--serve`` report for the multi-tenant serving tier.

    ``smoke=True`` (tier-1 canary, seconds on CPU): one seeded workload
    through dense / paged / paged+spec engines, asserting token parity —
    the wiring check that the three compiled decode paths agree.

    The full report (minutes on CPU; ``@slow`` in tests), six sections:

    (a) the A/B the KV cache exists for — per-token decode step time,
        cached vs cacheless (full forward over the whole history) at
        T ∈ {512, 1024}, per-call median with a host token fetch as the
        sync barrier;
    (b) dense engine throughput/latency at fixed QPS points;
    (c) equal-HBM paged vs dense on a mixed short/long workload where
        dense strands >50% of its reserved rows — occupancy, HBM-row
        occupancy, and tokens per decode step at byte-identical KV HBM;
    (d) prefix sharing — admit→first-token wall time for requests
        repeating a 96-token head, shared vs unshared pages;
    (e) speculative decoding — accepted_len and target-step collapse
        with a 1-layer trunk draft on damped-residual params (a
        converged-model stand-in: blocks contribute small corrections,
        the regime where a trunk draft agrees; random-init blocks
        disagree at chance level and would measure nothing);
    (f) a slots × page_size × cache_kind × spec_k Pareto sweep (16 paged
        rows): virtual tokens/sec and p50/p99 TTFT/TPOT on the
        deterministic step clock.

    All scheduler-level rows run the virtual step clock
    (``step_time_s``), so their numbers are a pure function of
    (seed, config) on any host; wall seconds ride along for scale.
    CPU-dryrun numbers are wiring + ratio sanity, not chip numbers —
    BASELINE.md protocol requires a named-chip rerun before recording.
    """
    import math
    import statistics

    import numpy as np

    from tpudml.models import TransformerLM
    from tpudml.serve import (
        Request, ServeConfig, ServingEngine, make_cacheless_decode_step,
        make_decode_step, poisson_workload,
    )

    STEP_S = 0.01  # virtual decode-step clock for all scheduler rows

    def pct(xs, q):
        xs = [x for x in xs if x is not None]
        if not xs:
            return None
        return round(float(np.percentile(np.asarray(xs), q)), 5)

    def hbm_occupancy(rep, hbm_rows):
        """Fraction of KV HBM rows holding LIVE request state, averaged
        over decode steps — replayed from the admit/evict event log."""
        start, end = {}, {}
        for e in rep.events:
            kind, rid, _slot, step = e[:4]
            if kind == "admit":
                start[rid] = step
            elif kind in ("evict", "expire"):
                end[rid] = step
        row_steps = 0
        for rid, s0 in start.items():
            st = rep.requests[rid]
            used = st.prompt_len + len(st.tokens)
            row_steps += (end.get(rid, rep.decode_steps) - s0) * used
        denom = rep.decode_steps * hbm_rows
        return round(row_steps / denom, 4) if denom else 0.0

    if smoke:
        # Tier-1 canary: parity across the three decode paths, tiny
        # model, virtual clock — deterministic and CPU-cheap.
        model = TransformerLM(vocab_size=64, embed_dim=32, num_heads=4,
                              num_kv_heads=2, num_layers=2, max_len=32,
                              rope=True, impl="full")
        params, _ = model.init(jax.random.key(0))

        def run_mode(**kw):
            scfg = ServeConfig(slots=2, max_len=32, prefill_chunk=4,
                               step_time_s=STEP_S, **kw)
            reqs, _ = poisson_workload(6, math.inf, 11, vocab_size=64,
                                       prompt_len=(2, 8), new_tokens=(3, 6))
            return ServingEngine(model, params, scfg, draft_layers=1).run(reqs)

        dense = run_mode()
        paged = run_mode(cache_layout="paged", page_size=4)
        spec = run_mode(cache_layout="paged", page_size=4, spec_k=2)

        def toks(rep):
            return {r: rep.requests[r].tokens for r in rep.requests}

        rows = {
            name: {
                "decode_steps": rep.decode_steps,
                "tokens_per_step": round(
                    rep.generated_tokens / max(rep.decode_steps, 1), 3),
                "occupancy": round(rep.occupancy, 4),
            }
            for name, rep in (("dense", dense), ("paged", paged),
                              ("paged_spec", spec))
        }
        rows["paged_spec"]["mean_accepted_len"] = round(
            spec.mean_accepted_len, 3)
        return {
            "metric": "serving_multitenant_parity_smoke",
            "on_tpu": on_tpu,
            "smoke": True,
            "parity_dense_paged_spec": toks(dense) == toks(paged) == toks(spec),
            "rows": rows,
        }

    if on_tpu:
        cfg = dict(vocab_size=32768, embed_dim=512, num_heads=8,
                   num_kv_heads=2, num_layers=6)
        slots, reps = 8, 20
    else:  # CPU dryrun: ratio + wiring sanity, not chip numbers
        cfg = dict(vocab_size=256, embed_dim=64, num_heads=4,
                   num_kv_heads=2, num_layers=2)
        slots, reps = 2, 7

    def timed_median(fn, *args, n=reps):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.device_get(out)  # host copy of the tokens = sync barrier
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    decode_rows: dict[str, dict] = {}
    for t_hist in (512, 1024):
        model = TransformerLM(**cfg, max_len=t_hist, rope=True,
                              impl="flash" if on_tpu else "full")
        params, _ = model.init(jax.random.key(0))
        rng = np.random.default_rng(1)

        # Cached: one token per slot at cache depth t_hist - 1. The step
        # donates its caches, so thread them through the warmup calls and
        # time with a fixed re-bound cache state.
        step = make_decode_step(model)
        caches = model.init_decode_cache(slots, t_hist)
        toks = rng.integers(0, cfg["vocab_size"], slots).astype(np.int32)
        pos = np.full(slots, t_hist - 1, np.int32)
        for _ in range(2):  # compile + warm
            _, _, caches = step(params, caches, toks, pos)

        def cached_once():
            nonlocal caches
            out, _, caches = step(params, caches, toks, pos)
            return out

        cached_sec = timed_median(cached_once)

        # Cacheless: the same emitted token pays a full forward over the
        # entire history (the J110 shape).
        bad_step = make_cacheless_decode_step(model)
        history = rng.integers(
            0, cfg["vocab_size"], (slots, t_hist)).astype(np.int32)
        for _ in range(2):
            bad_step(params, history)
        cacheless_sec = timed_median(bad_step, params, history)

        decode_rows[f"T{t_hist}"] = {
            "cached_sec_per_token_step": round(cached_sec, 6),
            "cacheless_sec_per_token_step": round(cacheless_sec, 6),
            "speedup": round(cacheless_sec / cached_sec, 2),
        }

    # (b) engine under load. Small horizon so the QPS points finish in
    # seconds; arrivals are open-loop, so queue depth (not generator
    # back-pressure) absorbs any engine slowness.
    serve_model = TransformerLM(**cfg, max_len=128, rope=True,
                                impl="flash" if on_tpu else "full")
    serve_params, _ = serve_model.init(jax.random.key(0))
    qps_rows: dict[str, dict] = {}
    for qps in (2.0, 4.0, math.inf):
        eng = ServingEngine(
            serve_model, serve_params,
            ServeConfig(slots=4, max_len=128, prefill_chunk=16))
        reqs, _ = poisson_workload(
            12, qps, 7, vocab_size=cfg["vocab_size"],
            prompt_len=(8, 24), new_tokens=(8, 24))
        rep = eng.run(reqs)
        lat = rep.latency_summary()
        qps_rows["saturated" if math.isinf(qps) else f"qps{qps:g}"] = {
            "tokens_per_sec": round(rep.tokens_per_sec, 2),
            "per_token_p50_ms": round(lat["per_token_p50_s"] * 1e3, 3),
            "per_token_p99_ms": round(lat["per_token_p99_s"] * 1e3, 3),
            "e2e_p50_s": round(lat["e2e_p50_s"], 4),
            "e2e_p99_s": round(lat["e2e_p99_s"], 4),
            "decode_steps": rep.decode_steps,
        }

    # (c) Equal-HBM paged vs dense. Dense reserves 4 slots × 128 rows =
    # 512 KV rows; paged provisions 65 pages × 8 rows = 520 (the +8 is
    # the reserved garbage page) but maps them to 16 slots. The mixed
    # workload (20 short requests stranding ~87% of a dense row, 4 long
    # ones) is exactly where per-slot reservation wastes the HBM.
    rng = np.random.default_rng(3)
    mixed = []
    for i in range(24):
        plen, new = (48, 48) if i % 6 == 0 else (8, 8)
        mixed.append(Request(
            rid=i, prompt=rng.integers(
                0, cfg["vocab_size"], plen).astype(np.int32),
            max_new_tokens=new, arrival_time=0.0))

    def run_hbm(scfg, hbm_rows):
        t0 = time.perf_counter()
        rep = ServingEngine(serve_model, serve_params, scfg).run(mixed)
        wall = time.perf_counter() - t0
        return {
            "hbm_rows": hbm_rows,
            "decode_steps": rep.decode_steps,
            "occupancy": round(rep.occupancy, 4),
            "hbm_occupancy": hbm_occupancy(rep, hbm_rows),
            "tokens_per_step": round(
                rep.generated_tokens / max(rep.decode_steps, 1), 3),
            "tokens_per_sec_virtual": round(rep.tokens_per_sec, 2),
            "wall_s": round(wall, 2),
        }, rep

    dense_row, dense_rep = run_hbm(
        ServeConfig(slots=4, max_len=128, prefill_chunk=8,
                    step_time_s=STEP_S), 4 * 128)
    paged_row, _ = run_hbm(
        ServeConfig(slots=16, max_len=128, prefill_chunk=8,
                    cache_layout="paged", page_size=8, num_pages=65,
                    step_time_s=STEP_S), 65 * 8)
    # How much of the dense reservation the workload could ever use:
    # resident-step-weighted used-rows fraction of the max_len rows each
    # admitted request pins for its whole lifetime.
    tok_steps = sum(len(s.tokens) for s in dense_rep.requests.values())
    used = sum((s.prompt_len + len(s.tokens)) * len(s.tokens)
               for s in dense_rep.requests.values())
    dense_row["stranded_hbm_frac"] = round(1 - used / (128 * tok_steps), 4)
    equal_hbm = {
        "workload": "20 short (8+8) + 4 long (48+48), all at t=0",
        "rows": {"dense": dense_row, "paged": paged_row},
        "paged_over_dense_tokens_per_step": round(
            paged_row["tokens_per_step"] / dense_row["tokens_per_step"], 3),
    }

    # (d) Prefix sharing: 6 requests repeating a 96-token head with a
    # 4-token divergent tail; slots=1 serializes them so admit→first-
    # token is each request's OWN prefill cost (wall clock — prefill is
    # real compute, which is the point). Request 0 is excluded from both
    # means: it pays the compiles AND (shared run) populates the cache.
    head = rng.integers(0, cfg["vocab_size"], 96).astype(np.int32)
    tails = [rng.integers(0, cfg["vocab_size"], 4).astype(np.int32)
             for _ in range(6)]

    def run_prefix(share):
        scfg = ServeConfig(slots=1, max_len=128, prefill_chunk=8,
                           cache_layout="paged", page_size=8,
                           prefix_sharing=share)
        reqs = [Request(rid=i, prompt=np.concatenate([head, tails[i]]),
                        max_new_tokens=8, arrival_time=0.0)
                for i in range(6)]
        rep = ServingEngine(serve_model, serve_params, scfg).run(reqs)
        ttfts = [rep.requests[i].first_token - rep.requests[i].admit_start
                 for i in range(1, 6)]
        return float(np.mean(ttfts)), rep

    unshared_s, _ = run_prefix(False)
    shared_s, shared_rep = run_prefix(True)
    prefix_sharing = {
        "workload": "6 requests, shared 96-token head, 4-token tails",
        "admit_to_first_token_ms_unshared": round(unshared_s * 1e3, 3),
        "admit_to_first_token_ms_shared": round(shared_s * 1e3, 3),
        "speedup_admit_to_first_token": round(unshared_s / shared_s, 2),
        "pool_stats": shared_rep.pool_stats,
        "shared_pages_per_hit": shared_rep.requests[1].shared_pages,
    }

    # (e) Speculative decoding on damped-residual params (see docstring):
    # blocks scaled ×0.25 so the 1-layer trunk draft tracks the 2-layer
    # target the way a draft tracks a converged model. Parity is checked
    # against the plain engine on the SAME params — damping changes what
    # is computed, never whether spec preserves it.
    damped = {k: (jax.tree.map(lambda x: x * 0.25, v)
                  if k.startswith("block") else v)
              for k, v in serve_params.items()}
    rep_head = np.tile(np.array([5, 7, 11, 13], np.int32), 6)

    def spec_reqs():
        return [Request(rid=i, prompt=rep_head.copy(), max_new_tokens=24,
                        arrival_time=0.0) for i in range(4)]

    srep = ServingEngine(
        serve_model, damped,
        ServeConfig(slots=4, max_len=128, prefill_chunk=8, spec_k=3,
                    step_time_s=STEP_S),
        draft_layers=1).run(spec_reqs())
    dref = ServingEngine(
        serve_model, damped,
        ServeConfig(slots=4, max_len=128, prefill_chunk=8,
                    step_time_s=STEP_S)).run(spec_reqs())
    spec_decode = {
        "workload": "4 requests, repetitive 24-token prompt, 24 new",
        "draft": "1-layer trunk (draft_from_trunk), spec_k=3",
        "mean_accepted_len": round(srep.mean_accepted_len, 3),
        "tokens_per_target_step": round(1 + srep.mean_accepted_len, 3),
        "decode_steps_spec": srep.decode_steps,
        "decode_steps_dense": dref.decode_steps,
        "parity": all(srep.requests[r].tokens == dref.requests[r].tokens
                      for r in srep.requests),
    }

    # (f) Pareto: slots × page_size × cache_kind × spec_k, all paged,
    # equal-capacity pools, one seeded finite-QPS workload, virtual
    # clock. TTFT/TPOT come from the annotated workload ledger — the
    # same per-request fields task6 asserts exact accounting on.
    pareto_rows: dict[str, dict] = {}
    for slots_n in (2, 4):
        for page in (8, 16):
            for kind in ("f32", "int8"):
                for k_spec in (0, 2):
                    scfg = ServeConfig(
                        slots=slots_n, max_len=64, prefill_chunk=8,
                        cache_layout="paged", page_size=page,
                        cache_kind=kind, spec_k=k_spec,
                        step_time_s=STEP_S)
                    eng = ServingEngine(serve_model, serve_params, scfg,
                                        draft_layers=1)
                    reqs, ledger = poisson_workload(
                        10, 8.0, 7, vocab_size=cfg["vocab_size"],
                        prompt_len=(8, 24), new_tokens=(8, 16))
                    t0 = time.perf_counter()
                    rep = eng.run(reqs)
                    wall = time.perf_counter() - t0
                    rep.annotate_ledger(ledger)
                    ttft = [r["ttft_s"] for r in ledger.values()]
                    tpot = [r["tpot_s"] for r in ledger.values()]
                    key = f"s{slots_n}_p{page}_{kind}_k{k_spec}"
                    pareto_rows[key] = {
                        "tokens_per_sec_virtual": round(
                            rep.tokens_per_sec, 2),
                        "ttft_p50_s": pct(ttft, 50),
                        "ttft_p99_s": pct(ttft, 99),
                        "tpot_p50_s": pct(tpot, 50),
                        "tpot_p99_s": pct(tpot, 99),
                        "decode_steps": rep.decode_steps,
                        "wall_s": round(wall, 2),
                    }

    return {
        "metric": "serving_multitenant_tier",
        "config": {**cfg, "slots": slots},
        "protocol": "per_call_median + virtual_step_clock",
        "on_tpu": on_tpu,
        "decode_step": decode_rows,
        "serve_load": {
            "n_requests": 12, "slots": 4, "max_len": 128,
            "prefill_chunk": 16, "rows": qps_rows,
        },
        "equal_hbm": equal_hbm,
        "prefix_sharing": prefix_sharing,
        "spec_decode": spec_decode,
        "pareto": {"step_time_s": STEP_S, "rows": pareto_rows},
    }


def bench_sentinel(on_tpu) -> dict:
    """``--sentinel`` report: the flagship-LM train step timed with and
    without the in-graph step sentinel (``resilience.GradSentinel``)
    wrapping the optimizer — the sentinel tax. Same model config and
    fori timing protocol as the secondary LM row, so the two step times
    differ by exactly the sentinel's finiteness reduction + counter
    selects. Acceptance (BASELINE.md round 9): ``overhead_frac`` ≤ 0.03.
    """
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM
    from tpudml.optim import make_optimizer
    from tpudml.resilience import attach_sentinel
    from tpudml.train import TrainState, make_lm_fused_train_step_body

    if on_tpu:
        cfg = dict(vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6)
        seq_len, batch = 1024, 8
    else:  # CPU dryrun: same shape as the dev-smoke LM row
        cfg = dict(vocab_size=256, embed_dim=64, num_heads=4, num_layers=2)
        seq_len, batch = 128, 4
    model = TransformerLM(
        **cfg, max_len=seq_len, impl="flash" if on_tpu else "full",
        rope=True, compute_dtype=jnp.bfloat16 if on_tpu else None,
        fused_ln=on_tpu,
    )
    seqs = jnp.asarray(synthetic_lm(batch, seq_len, cfg["vocab_size"], seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]
    tokens = batch * seq_len

    def timed(opt) -> float:
        fused_body = make_lm_fused_train_step_body(
            model, opt, save_scores=on_tpu
        )

        def body(ts, tokens_in, labels):
            new_ts, metrics = fused_body(ts, tokens_in, labels)
            return new_ts, metrics["loss"]

        ts0 = TrainState.create(model, opt, seed_key(0))
        # reps=3 on CPU too: the A/B divides two step times, and a
        # single-rep reading on the 1-core box jitters by ±20% — far
        # above the ≤3% tax this row exists to measure.
        sec, _ = _time_fori(
            body, ts0, (x, y),
            *((8, 40) if on_tpu else (1, 3)), reps=3,
        )
        return sec

    sec_plain = timed(make_optimizer("adamw", 3e-4))
    sec_sent = timed(attach_sentinel(make_optimizer("adamw", 3e-4)))
    return {
        "metric": "sentinel_overhead_lm_step_fori",
        "config": {**cfg, "seq_len": seq_len, "batch": batch,
                   "platform": "tpu" if on_tpu else "cpu_dryrun"},
        "step_ms_plain": round(sec_plain * 1e3, 3),
        "step_ms_sentinel": round(sec_sent * 1e3, 3),
        "tokens_per_sec_plain": round(tokens / sec_plain, 1),
        "tokens_per_sec_sentinel": round(tokens / sec_sent, 1),
        "value": round(sec_sent / sec_plain - 1.0, 4),
        "unit": "overhead_fraction",
    }


def main_sentinel() -> None:
    """Driver for ``python bench.py --sentinel``: prints ONE JSON line,
    same contract as ``main()``, for the sentinel on/off A/B."""
    on_tpu = jax.devices()[0].platform != "cpu"
    print(json.dumps(bench_sentinel(on_tpu)))


def bench_obs(on_tpu) -> dict:
    """``--obs`` report: the LeNet DP train step timed with the flight
    recorder (``tpudml.obs``) off vs on — the observability tax. The on
    position adds one host-side tracer span per dispatch AND the in-graph
    StepStats pytree (grad norm, sentinel counters, comm-bytes constant)
    to the jitted step, so the A/B prices the whole ``obs=True`` knob,
    not just the tracer. Dispatched-step timing (not fori): the tracer
    span wraps the dispatch, which fori would hide. Acceptance
    (docs/OBSERVABILITY.md): ``overhead_frac`` < 0.02.
    """
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    import numpy as np

    devices = jax.devices()
    mesh = make_mesh(MeshConfig({"data": len(devices)}), devices)
    batch = (64 if on_tpu else 32) * len(devices)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28, 1)).astype("float32")
    y = rng.integers(0, 10, size=(batch,)).astype("int32")
    iters, reps = (40, 3) if on_tpu else (10, 3)

    def timed(obs) -> float:
        dp = DataParallel(
            LeNet(), make_optimizer("sgd", 0.01, 0.9), mesh, obs=obs
        )
        ts = dp.create_state(seed_key(0))
        step = dp.make_train_step()
        for _ in range(3):  # compile + warm caches
            ts, m = step(ts, x, y)
        jax.block_until_ready(m["loss"])
        runs = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                ts, m = step(ts, x, y)
            jax.block_until_ready(m["loss"])
            runs.append((time.perf_counter() - t0) / iters)
        # Best-of-reps on both arms: the A/B divides two step times and
        # the minimum is the least-noise estimator of each.
        return min(runs)

    sec_off = timed(False)
    sec_on = timed(True)
    return {
        "metric": "obs_overhead_dp_step",
        "config": {"model": "lenet", "batch": batch,
                   "world": len(devices), "iters": iters, "reps": reps,
                   "platform": "tpu" if on_tpu else "cpu_dryrun"},
        "step_ms_off": round(sec_off * 1e3, 3),
        "step_ms_on": round(sec_on * 1e3, 3),
        "value": round(sec_on / sec_off - 1.0, 4),
        "unit": "overhead_fraction",
        "budget": 0.02,
    }


def main_obs() -> None:
    """Driver for ``python bench.py --obs``: prints ONE JSON line, same
    contract as ``main()``, for the flight-recorder on/off A/B."""
    on_tpu = jax.devices()[0].platform != "cpu"
    print(json.dumps(bench_obs(on_tpu)))


def bench_drill(*, shrink: bool = True, naive: bool = False) -> dict:
    """MTTR row for the elastic failure drills (``tpudml.elastic``).

    ``shrink=False`` is the PR 14 restart drill: 2-process gloo job run
    once uninterrupted and once with rank 1 hard-killed under the
    controller's restart policy, reporting steps lost, restart latency,
    and the bit-exactness verdict.

    ``shrink=True`` (the default) is the adaptive-recovery drill: the
    kill shrinks the gang, the controller consults the planner at the
    new world, and the run resumes under a *different* engine chain —
    the row grows the re-plan evidence (old/new chain, plan latency,
    receipts, post-shrink throughput). ``naive=True`` adds the A/B arm
    that forces the OLD chain at the shrunken world via explicit CLI
    flags, so ``replan_beats_naive`` is measured, not claimed."""
    import tempfile

    base = tempfile.mkdtemp(prefix="tpudml_bench_drill_")
    if not shrink:
        from tpudml.elastic.drill import run_drill

        rep = run_drill(base)
        return {
            "bench": "elastic_drill",
            "ok": rep["ok"],
            "bit_exact": rep["bit_exact"],
            "world": rep["world"],
            "steps": rep["steps"],
            "kill_step": rep["kill_step"],
            "resume_step": rep["resume_step"],
            "steps_lost": rep["steps_lost"],
            "reforms": rep["reforms"],
            "backoff_s": round(rep["backoff_s"], 3),
            "restart_latency_s": round(rep["restart_latency_s"], 3)
            if rep["restart_latency_s"] is not None
            else None,
            "clean_wall_s": round(rep["clean_wall_s"], 3),
            "drill_wall_s": round(rep["drill_wall_s"], 3),
            "overhead_vs_clean_frac": round(rep["overhead_vs_clean_frac"], 4)
            if rep["overhead_vs_clean_frac"] is not None
            else None,
        }

    from tpudml.elastic.drill import run_shrink_drill

    rep = run_shrink_drill(base, include_naive=naive)
    row = {
        "bench": "elastic_shrink_drill",
        "ok": rep["ok"],
        "bit_exact": rep["bit_exact"],
        "world": rep["world"],
        "final_world": rep["final_world"],
        "steps": rep["steps"],
        "kill_step": rep["kill_step"],
        "resume_step": rep["resume_step"],
        "steps_lost": rep["steps_lost"],
        "reforms": rep["reforms"],
        "backoff_s": round(rep["backoff_s"], 3),
        "restart_latency_s": round(rep["restart_latency_s"], 3)
        if rep["restart_latency_s"] is not None
        else None,
        "drill_wall_s": round(rep["drill_wall_s"], 3),
        # The re-plan evidence: what chain we left, what chain we
        # resumed under, how long the decision took, and why the old
        # config lost (machine-readable receipts).
        "old_chain": rep["old_plan"],
        "new_chain": rep["new_plan"],
        "plan_switched": rep["plan_switched"],
        "chain_switched": rep["chain_switched"],
        "replan_latency_s": round(rep["replan_latency_s"], 4)
        if rep["replan_latency_s"] is not None
        else None,
        "replan_receipts": [r["verdict"] for r in rep["replan_receipts"]],
        "post_shrink_steps_per_s": rep["post_shrink_steps_per_s"],
    }
    if naive:
        row["naive"] = rep["naive"]
        row["replan_beats_naive"] = rep["replan_beats_naive"]
    return row


def main_drill() -> None:
    """Driver for ``python bench.py --drill``: prints ONE JSON line, same
    contract as ``main()``, for the elastic MTTR row — by default the
    shrink-re-plan drill. ``--drill-restart`` runs the plain restart
    drill instead; ``--drill-naive`` adds the old-chain-at-new-world A/B
    arm. Requires a platform where the multi-process drill can run
    (JAX_PLATFORMS=cpu uses gloo)."""
    import sys

    print(json.dumps(bench_drill(
        shrink="--drill-restart" not in sys.argv[1:],
        naive="--drill-naive" in sys.argv[1:],
    )))


def main_serve() -> None:
    """Driver for ``python bench.py --serve``: prints ONE JSON line, same
    contract as ``main()``, for the serving tier. ``--smoke`` runs only
    the cheap dense/paged/spec parity canary (the tier-1 wiring check);
    the bare ``--serve`` runs the full six-section report including the
    Pareto sweep (minutes on CPU)."""
    import sys

    on_tpu = jax.devices()[0].platform != "cpu"
    print(json.dumps(bench_serve(on_tpu, smoke="--smoke" in sys.argv[1:])))


def bench_fleet(on_tpu, smoke=False) -> dict:
    """Serving-fleet row (ROADMAP item 3's success metric): aggregate
    tokens/s and ttft/tpot p50/p99 across N replicas at 2×-overload,
    with one replica killed mid-run and re-formed — the kill arm's tail
    latencies must HOLD against the no-kill arm, which is the whole
    point of drain/re-admit (a dead replica costs re-prefill work, not
    correctness or fairness). A third arm quantizes replica weights to
    int8 to show the DecodeCostModel pricing the smaller param-byte
    term (placement honesty, serve/sched.py).

    Deterministic by construction: the fleet runs on the virtual clock,
    so every number here is a pure function of (seed, config) — the
    CPU-dryrun caveat applies to the roofline CONSTANTS, not the
    scheduling."""
    from tpudml.models.transformer import TransformerLM
    from tpudml.serve.engine import ServeConfig
    from tpudml.serve.fleet import FleetConfig, FleetRouter
    from tpudml.serve.load import poisson_workload
    from tpudml.serve.sched import DecodeCostModel, SLOConfig

    model = TransformerLM(
        vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
        num_layers=2, max_len=64,
    )
    params = model.init(jax.random.PRNGKey(0))[0]
    replicas, slots, step_time = 3, 2, 0.01
    n = 12 if smoke else 48
    # Capacity ≈ replicas × slots tokens per step = 600 tok/s; at ~6
    # tokens/request that serves ~100 req/s — offer 2× that.
    qps = 200.0
    requests, ledger = poisson_workload(
        n, qps, 17, vocab_size=64, prompt_len=(4, 10), new_tokens=(4, 8),
    )
    slo = SLOConfig(tpot_budget_s=0.5)

    def fleet_cfg(weight_quant=None):
        return FleetConfig(
            engine=ServeConfig(
                slots=slots, max_len=64, prefill_chunk=8,
                step_time_s=step_time, deadline_s=2.0, slo=slo,
                weight_quant=weight_quant,
            ),
            replicas=replicas, max_queue=2 * n,
            reform_after_steps=6,
        )

    def arm(cfg, kills):
        rep = FleetRouter(model, params, cfg).run(requests, kills=kills)
        lat = rep.latency_summary()
        return {
            "replicas": rep.replicas,
            "steps": rep.steps,
            "tokens_per_sec": rep.tokens_per_sec,
            "generated_tokens": rep.generated_tokens,
            "finished": rep.finished,
            "rejected": rep.rejected,
            "expired": rep.expired,
            "kills": rep.kills,
            "drains": rep.drains,
            "readmits": sum(s.readmits for s in rep.requests.values()),
            "peak_queue_depth": rep.peak_queue_depth,
            "events_crc32": rep.events_crc32(),
            "ttft_p50_s": lat["ttft_p50_s"],
            "ttft_p99_s": lat["ttft_p99_s"],
            "tpot_p50_s": lat["per_token_p50_s"],
            "tpot_p99_s": lat["per_token_p99_s"],
        }

    kill_step = 6 if smoke else 12
    no_kill = arm(fleet_cfg(), [])
    kill = arm(fleet_cfg(), [(kill_step, 1)])
    int8_arm = arm(fleet_cfg(weight_quant="int8"), [(kill_step, 1)])
    cm_f32 = DecodeCostModel(model, fleet_cfg().engine, slo)
    cm_int8 = DecodeCostModel(
        model, fleet_cfg(weight_quant="int8").engine, slo
    )
    return {
        "bench": "fleet",
        "on_tpu": bool(on_tpu),
        "smoke": bool(smoke),
        "overload_x": 2.0,
        "requests": n,
        "no_kill": no_kill,
        "kill": kill,
        "int8_kill": int8_arm,
        "tpot_p99_kill_over_no_kill": (
            kill["tpot_p99_s"] / max(no_kill["tpot_p99_s"], 1e-12)
        ),
        "cost_params_bytes": {
            "f32": cm_f32.params_bytes,
            "int8": cm_int8.params_bytes,
            "ratio": cm_f32.params_bytes / max(cm_int8.params_bytes, 1),
        },
    }


def bench_mpmd(*, naive: bool = False) -> dict:
    """MPMD re-mesh row (``tpudml.mpmd``): the 2-stage×2-dp pipeline
    drill — SIGKILL one stage rank mid-run, survivors drain at the
    boundary, the planner is consulted fail-open, and the surviving
    stage groups re-form *in place* (fresh ports, no whole-world
    restart) resuming bit-exactly from the common checkpoint step.

    ``naive=True`` adds the whole-world-restart A/B arm (peers abort on
    peer death so every group's containment fires); both arms anchor
    MTTR on the kill marker's mtime, so ``remesh_beats_naive`` is
    measured, not claimed. CPU-dryrun caveat: absolute steps/s and
    MTTRs are host-CPU numbers (gloo + TCP loopback); the *ratio* and
    the bit-exactness verdict are the portable claims."""
    import tempfile

    from tpudml.mpmd.drill import run_mpmd_drill

    base = tempfile.mkdtemp(prefix="tpudml_bench_mpmd_")
    rep = run_mpmd_drill(base, include_naive=naive)
    row = {
        "bench": "mpmd_remesh_drill",
        "ok": rep["ok"],
        "bit_exact": rep["bit_exact"],
        "in_place": rep["in_place"],
        "stage_worlds": [st["dp"] for st in rep["pipeline"]["stages"]],
        "final_stage_worlds": rep["final_stage_worlds"],
        "steps": rep["steps"],
        "kill_step": rep["kill_step"],
        "resume_step": rep["resume_step"],
        "steps_lost": rep["steps_lost"],
        "reforms": rep["reforms"],
        "fresh_ports": rep["fresh_ports"],
        "remesh_mttr_s": round(rep["remesh_mttr_s"], 3)
        if rep["remesh_mttr_s"] is not None
        else None,
        "replan_receipts": rep["replan_receipts"],
        "steps_per_s": rep["steps_per_s"],
    }
    if naive:
        row["naive_restart_mttr_s"] = (
            round(rep["naive"]["restart_mttr_s"], 3)
            if rep["naive"] and rep["naive"]["restart_mttr_s"] is not None
            else None
        )
        row["remesh_beats_naive"] = rep["remesh_beats_naive"]
    return row


def main_mpmd() -> None:
    """Driver for ``python bench.py --mpmd``: prints ONE JSON line, same
    contract as ``main()``, for the MPMD pipeline re-mesh row.
    ``--mpmd-naive`` adds the whole-world-restart A/B arm so the row
    carries re-mesh MTTR vs restart MTTR. Requires a platform where the
    multi-process drill can run (JAX_PLATFORMS=cpu uses gloo)."""
    import sys

    print(json.dumps(bench_mpmd(naive="--mpmd-naive" in sys.argv[1:])))


def main_fleet() -> None:
    """Driver for ``python bench.py --fleet``: prints ONE JSON line, same
    contract as ``main()``, for the serving-fleet row (N replicas at
    2×-overload with a mid-run replica kill). ``--smoke`` shrinks the
    workload to the wiring-check size."""
    import sys

    on_tpu = jax.devices()[0].platform != "cpu"
    print(json.dumps(bench_fleet(on_tpu, smoke="--smoke" in sys.argv[1:])))


def main_zero1() -> None:
    """Driver for ``python bench.py --zero1``: prints ONE JSON line, same
    contract as ``main()`` but for the ZeRO-1 comparison. Self-provisions
    an 8-device CPU mesh when no accelerator is visible (same dance as
    the analysis CLI), since the comparison is meaningless on one chip."""
    import os

    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ) and not os.environ.get("TPU_NAME"):
        # Harmless if a real backend is present: the flag only affects the
        # CPU platform. Must be set before the backend initializes.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    on_tpu = jax.devices()[0].platform != "cpu"
    n_devices = jax.device_count()
    print(json.dumps(bench_zero1(on_tpu, n_devices)))


def main() -> None:
    # The TPU chip may surface under a tunnel platform name (e.g. "axon").
    on_tpu = jax.devices()[0].platform != "cpu"
    n_devices = jax.device_count()

    headline = bench_resnet(on_tpu, n_devices)
    secondary = bench_transformer(on_tpu)
    # The chip-filling LM row (VERDICT r4 item 3) records only on real
    # hardware — the 1-core CPU box cannot compile it in budget, and a
    # tiny stand-in would mislabel the metric.
    secondary_large = bench_transformer(on_tpu, large=True) if on_tpu else None

    baseline = lm_baseline = lm_large_baseline = None
    try:
        with open("BASELINE.json") as f:
            pub = json.load(f).get("published", {})
            # Median-protocol pin first (medians compare to medians —
            # VERDICT r3 item 7: r3 published vs_baseline 0.97 by
            # comparing a one-shot run against a best-of-3 pin); the
            # legacy pins are protocol-incompatible fallbacks.
            baseline = pub.get(
                "cifar10_resnet18_imgs_per_sec_per_chip_fori_median"
            ) or pub.get("cifar10_resnet18_imgs_per_sec_per_chip_fori")
            lm_baseline = pub.get(
                "transformer_lm_tokens_per_sec_per_chip_fori_median"
            )
            lm_large_baseline = pub.get(
                "transformer_lm_large_tokens_per_sec_per_chip_fori_median"
            )
    except Exception:
        pass
    if lm_baseline:
        secondary["vs_baseline"] = round(secondary["value"] / lm_baseline, 3)
    if secondary_large is not None and lm_large_baseline:
        secondary_large["vs_baseline"] = round(
            secondary_large["value"] / lm_large_baseline, 3
        )
    vs = headline["value"] / baseline if baseline else 1.0
    out = {
        **headline,
        # fori-protocol recordings only (see module docstring);
        # 1.0 until an honest pin exists in BASELINE.json.
        "vs_baseline": round(vs, 3),
        "secondary": secondary,
    }
    if secondary_large is not None:
        out["secondary_large"] = secondary_large
    print(json.dumps(out))


if __name__ == "__main__":
    import sys

    # --zero1 / --moe are separate reports (each its own single JSON
    # line); the bare invocation's driver contract is untouched.
    if "--zero1" in sys.argv[1:]:
        main_zero1()
    elif "--plan" in sys.argv[1:]:
        main_plan()
    elif "--moe" in sys.argv[1:]:
        main_moe()
    elif "--serve" in sys.argv[1:]:
        main_serve()
    elif "--fleet" in sys.argv[1:]:
        main_fleet()
    elif any(a.startswith("--mpmd") for a in sys.argv[1:]):
        main_mpmd()
    elif "--sentinel" in sys.argv[1:]:
        main_sentinel()
    elif "--obs" in sys.argv[1:]:
        main_obs()
    elif any(a.startswith("--drill") for a in sys.argv[1:]):
        main_drill()
    else:
        main()
