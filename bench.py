"""Benchmark entrypoint (driver contract: prints ONE JSON line).

Headline = the north-star metric (BASELINE.json): steady-state CIFAR-10
ResNet-18 data-parallel training throughput in images/sec/chip, bfloat16
compute on the MXU. Runs on whatever devices are visible (one real TPU chip
under the driver; a CPU mesh in dev). The reference publishes no numbers
(BASELINE.md); ``vs_baseline`` is computed against the recorded first-round
TPU measurement in BASELINE.json's ``published`` map when present, else 1.0.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def main() -> None:
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_classification
    from tpudml.models import ResNet18
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    # The TPU chip may surface under a tunnel platform name (e.g. "axon").
    on_tpu = jax.devices()[0].platform != "cpu"
    n_devices = jax.device_count()
    # 1024/chip keeps the MXU fed and amortizes dispatch; fits v5e HBM
    # comfortably for CIFAR-sized inputs.
    per_chip_batch = 1024 if on_tpu else 32
    batch = per_chip_batch * n_devices
    images, labels = synthetic_classification(batch, (32, 32, 3), 10, seed=0)
    images = jnp.asarray(images)
    labels = jnp.asarray(labels)

    model = ResNet18(compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    opt = make_optimizer("sgd", 0.1, momentum=0.9)
    mesh = make_mesh(MeshConfig(axes={"data": n_devices}), jax.devices())
    dp = DataParallel(model, opt, mesh)
    step = dp.make_train_step()
    ts = dp.create_state(seed_key(0))

    # Warmup / compile.
    for _ in range(3):
        ts, m = step(ts, images, labels)
    jax.block_until_ready(m["loss"])

    iters = 30 if on_tpu else 5
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, m = step(ts, images, labels)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    per_chip = batch * iters / dt / max(n_devices, 1)

    baseline = None
    try:
        with open("BASELINE.json") as f:
            baseline = json.load(f).get("published", {}).get(
                "cifar10_resnet18_imgs_per_sec_per_chip"
            )
    except Exception:
        pass
    vs = per_chip / baseline if baseline else 1.0
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet18_train_imgs_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
