"""Benchmark entrypoint (driver contract: prints ONE JSON line).

Measures the north-star-style headline on the available hardware: steady-
state training throughput (images/sec/chip) of the flagship DP training
step on MNIST-shaped data. The reference publishes no numbers (BASELINE.md);
``vs_baseline`` is computed against the recorded first-round TPU measurement
in BASELINE.json's ``published`` map when present, else 1.0.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_classification
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState, make_train_step

    batch = 512
    n_devices = jax.device_count()
    images, labels = synthetic_classification(batch, (28, 28, 1), 10, seed=0)
    images = jnp.asarray(images)
    labels = jnp.asarray(labels)

    model = LeNet()
    opt = make_optimizer("sgd", 0.01, momentum=0.9)
    step = make_train_step(model, opt)
    ts = TrainState.create(model, opt, seed_key(0))

    # Warmup / compile.
    ts, m = step(ts, images, labels)
    jax.block_until_ready(m["loss"])

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, m = step(ts, images, labels)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    imgs_per_sec = batch * iters / dt
    per_chip = imgs_per_sec / max(n_devices, 1)

    baseline = None
    try:
        with open("BASELINE.json") as f:
            baseline = json.load(f).get("published", {}).get(
                "mnist_lenet_imgs_per_sec_per_chip"
            )
    except Exception:
        pass
    vs = per_chip / baseline if baseline else 1.0
    print(
        json.dumps(
            {
                "metric": "mnist_lenet_train_imgs_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
