"""Benchmark entrypoint (driver contract: prints ONE JSON line).

Headline = the north-star metric (BASELINE.json): steady-state CIFAR-10
ResNet-18 data-parallel training throughput in images/sec/chip, bfloat16
compute on the MXU. A transformer-LM tokens/sec/chip secondary metric
(task5's flagship model, flash attention on TPU) tracks the sequence
workload too.

Honesty notes (VERDICT round 1):
- FLOPs/step come from XLA's compiled cost analysis of the single-chip
  step (not hand-waving), and ``mfu`` = achieved FLOP/s over the chip's
  bf16 peak.
- The tunneled chip's wall-clock is protocol-relative (the relay can
  overlap/elide dispatches), so MFU can exceed 1.0; ``mfu_artifact``
  flags that case and ``vs_baseline`` must only ever be read as
  bench.py-vs-its-own-prior-recording under the same protocol, never as
  a real speedup claim.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6": 918e12,  # Trillium
}


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_BF16.items():
        if key in kind:
            return peak
    return None


def _compiled_flops(fn, *args) -> float | None:
    """FLOPs of one call from XLA's cost analysis (None if unavailable).
    ``fn`` may already be jitted (lowered directly — nothing executes, so
    donated arguments are safe to pass)."""
    try:
        if not hasattr(fn, "lower"):
            fn = jax.jit(fn)
        cost = fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops or None
    except Exception:
        return None


def _time_steps(step, ts, batch, iters):
    """Steady-state seconds per step (post-warmup)."""
    for _ in range(3):
        ts, m = step(ts, *batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, m = step(ts, *batch)
    jax.block_until_ready(m["loss"])
    return (time.perf_counter() - t0) / iters


def _mfu_fields(flops_per_step, sec_per_step, peak):
    if not flops_per_step or not peak:
        return {}
    mfu = flops_per_step / sec_per_step / peak
    return {
        "flops_per_step": round(flops_per_step),
        "mfu": round(mfu, 4),
        # >100% of peak is physically impossible: the tunneled chip's
        # relay overlapped/elided dispatches and the timing is a protocol
        # artifact, not a throughput claim.
        "mfu_artifact": bool(mfu > 1.0),
    }


def bench_resnet(on_tpu: bool, n_devices: int) -> dict:
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_classification
    from tpudml.models import ResNet18
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel
    from tpudml.train import TrainState, make_train_step

    # 1024/chip keeps the MXU fed and amortizes dispatch; fits v5e HBM
    # comfortably for CIFAR-sized inputs.
    per_chip_batch = 1024 if on_tpu else 32
    batch = per_chip_batch * n_devices
    images, labels = synthetic_classification(batch, (32, 32, 3), 10, seed=0)
    images, labels = jnp.asarray(images), jnp.asarray(labels)

    model = ResNet18(compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    opt = make_optimizer("sgd", 0.1, momentum=0.9)
    mesh = make_mesh(MeshConfig(axes={"data": n_devices}), jax.devices())
    dp = DataParallel(model, opt, mesh, stacked_batches=False)
    sec = _time_steps(
        dp.make_train_step(), dp.create_state(seed_key(0)),
        (images, labels), 30 if on_tpu else 5,
    )

    # FLOPs from the single-chip step on the per-chip batch (what each
    # chip executes; collectives excluded, matching the per-chip metric).
    flops = _compiled_flops(
        make_train_step(model, opt),
        TrainState.create(model, opt, seed_key(0)),
        images[:per_chip_batch],
        labels[:per_chip_batch],
    )
    per_chip = batch / sec / max(n_devices, 1)
    return {
        "metric": "cifar10_resnet18_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        **_mfu_fields(flops, sec, _peak_flops(jax.devices()[0])),
    }


def bench_transformer(on_tpu: bool) -> dict:
    """task5 flagship: decoder LM, flash attention on TPU, bf16."""
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState, make_train_step

    if on_tpu:
        cfg = dict(vocab_size=32768, embed_dim=512, num_heads=8, num_layers=6)
        seq_len, batch = 1024, 8
    else:  # dev smoke on CPU: keep it seconds, not minutes
        cfg = dict(vocab_size=256, embed_dim=64, num_heads=4, num_layers=2)
        seq_len, batch = 128, 4
    model = TransformerLM(
        **cfg,
        max_len=seq_len,
        impl="flash" if on_tpu else "full",
        rope=True,
        # Master-weight mixed precision: f32 params (the optimizer state),
        # bf16 MXU compute, f32 norms/softmax/logits.
        compute_dtype=jnp.bfloat16 if on_tpu else None,
    )
    opt = make_optimizer("adamw", 3e-4)
    seqs = jnp.asarray(synthetic_lm(batch, seq_len + 1, cfg["vocab_size"], seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]

    step = make_train_step(model, opt)
    ts = TrainState.create(model, opt, seed_key(0))
    sec = _time_steps(step, ts, (x, y), 20 if on_tpu else 5)
    flops = _compiled_flops(
        step, TrainState.create(model, opt, seed_key(0)), x, y,
    )
    tokens = batch * seq_len
    return {
        "metric": "transformer_lm_train_tokens_per_sec_per_chip",
        "value": round(tokens / sec, 1),
        "unit": "tokens/sec/chip",
        **_mfu_fields(flops, sec, _peak_flops(jax.devices()[0])),
    }


def main() -> None:
    # The TPU chip may surface under a tunnel platform name (e.g. "axon").
    on_tpu = jax.devices()[0].platform != "cpu"
    n_devices = jax.device_count()

    headline = bench_resnet(on_tpu, n_devices)
    secondary = bench_transformer(on_tpu)

    baseline = None
    try:
        with open("BASELINE.json") as f:
            baseline = json.load(f).get("published", {}).get(
                "cifar10_resnet18_imgs_per_sec_per_chip"
            )
    except Exception:
        pass
    vs = headline["value"] / baseline if baseline else 1.0
    print(
        json.dumps(
            {
                **headline,
                # Protocol-relative: same-protocol bench.py recordings
                # only — NOT a hardware speedup claim (see module note).
                "vs_baseline": round(vs, 3),
                "secondary": secondary,
            }
        )
    )


if __name__ == "__main__":
    main()
