"""Transformer-LM step-time ablations on the real chip (fori protocol).

The tunneled relay cannot serve ``jax.profiler`` traces, so component
costs are measured by differencing whole-step times across model/config
ablations (vocab size, attention impl, batch, head count). Used to drive
the round-3 MFU tuning recorded in BASELINE.md.
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import (  # noqa: E402
    _analytic_lm_flops,
    _make_step_body,
    _peak_flops,
    _time_fori,
)

from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.train import TrainState


def run(name, batch=8, seq_len=1024, vocab=32768, heads=8, layers=6,
        dim=512, impl="flash", remat=False, fused_ln=False, fused_xent=False,
        opt_name="adamw"):
    model = TransformerLM(
        vocab_size=vocab, embed_dim=dim, num_heads=heads, num_layers=layers,
        max_len=seq_len, impl=impl, rope=True, remat=remat,
        compute_dtype=jnp.bfloat16, fused_ln=fused_ln,
    )
    opt = make_optimizer(opt_name, 3e-4)
    # synthetic_lm returns [n, seq_len+1] already; x/y slices give T=seq_len.
    seqs = jnp.asarray(synthetic_lm(batch, seq_len, vocab, seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]
    if fused_xent:
        from tpudml.train import make_lm_fused_train_step_body

        # save_scores: speed mode, V=32k fits comfortably on this chip.
        fb = make_lm_fused_train_step_body(model, opt, save_scores=True)

        def body(ts, tokens, labels):
            new_ts, metrics = fb(ts, tokens, labels)
            return new_ts, metrics["loss"]
    else:
        body = _make_step_body(model, opt)
    ts0 = TrainState.create(model, opt, seed_key(0))
    t0 = time.time()
    sec, _ = _time_fori(body, ts0, (x, y), 8, 24, reps=1)
    # Analytic matmul FLOPs: XLA cost analysis can't see inside the
    # Pallas custom calls, which would deflate exactly the fused rows
    # this tool exists to compare (bench.py's _analytic_lm_flops note).
    flops = _analytic_lm_flops(
        dict(embed_dim=dim, num_layers=layers, vocab_size=vocab),
        batch, seq_len,
    )
    peak = _peak_flops(jax.devices()[0])
    mfu = flops / sec / peak if flops and peak else float("nan")
    tokens = batch * seq_len
    print(
        f"{name:34s} {sec*1e3:8.2f} ms/step  {tokens/sec:12.0f} tok/s  "
        f"mfu {mfu:.3f}  ({time.time()-t0:.0f}s incl compile)",
        flush=True,
    )
    return sec


from contextlib import contextmanager  # noqa: E402


@contextmanager
def _patched(obj, name, repl):
    orig = getattr(obj, name)
    setattr(obj, name, repl)
    try:
        yield
    finally:
        setattr(obj, name, orig)


def budget(**cfg):
    """Per-component budget table for the flagship fused step (the 19.3 ms
    config: heads=4, fused_ln, fused-xent save-s, AdamW).

    Each arm removes ONE component — by monkeypatch-to-identity (the r3
    LN-ablation idiom) or config ablation — and its delta to the full step
    prices that component in ONE process, so relay-state drift between
    rounds differences out. Caveats per arm: the head arm (V=512) also
    shrinks the V-scaled part of the embedding backward, and the
    junction arm keeps the residual adds and the scale/bias affine (the
    delta prices the normalization + fusion structure, not the adds).
    Residual = total − Σ components (QKV/FFN matmuls + dispatch)."""
    import tpudml.ops as ops
    from tpudml.models import transformer as tr
    from tpudml.ops import layernorm_kernel as lnk

    base = dict(heads=4, fused_ln=True, fused_xent=True)
    base.update(cfg)

    def attn_identity(q, k, v, *, causal=True, **kw):
        return v

    def junction_identity(x, r, scale, bias, *, eps=1e-5, block_n=256,
                          interpret=None):
        s = x + r
        return s, s * scale + bias  # params stay live, no moments

    def embed_row0(table, tokens):
        return jnp.broadcast_to(
            table[0], (*tokens.shape, table.shape[-1]))

    total = run("flagship fused (total)", **base)
    rows = []
    with _patched(ops, "flash_attention", attn_identity):
        rows.append(("attention", run("  - attention -> identity", **base)))
    with _patched(lnk, "fused_add_layernorm", junction_identity):
        rows.append(("junctions", run("  - junctions -> add+affine", **base)))
    # Proportional vocab shrink (flagship 32k -> 512, the r2/r3 arm).
    tiny_v = max(8, base.get("vocab", 32768) // 64)
    rows.append(("head", run(f"  - head (V={tiny_v})",
                             **{**base, "vocab": tiny_v})))
    with _patched(tr, "embed_lookup", embed_row0):
        rows.append(("embed", run("  - embed -> row-0 broadcast", **base)))
    rows.append(("adamw", run("  - AdamW -> SGD",
                              **{**base, "opt_name": "sgd"})))

    print("\ncomponent budget (full - ablated):")
    accounted = 0.0
    for name, sec in rows:
        delta = total - sec
        accounted += delta
        print(f"  {name:10s} {delta*1e3:7.2f} ms  "
              f"({delta / total * 100:5.1f}% of step)")
    resid = total - accounted
    print(f"  {'residual':10s} {resid*1e3:7.2f} ms  "
          f"({resid / total * 100:5.1f}% of step)  "
          f"[QKV/FFN matmuls + dispatch]")
    return total, dict(rows)


if __name__ == "__main__":
    which = sys.argv[1:] or ["base", "tinyvocab", "fullattn", "b32", "h4"]
    if "budget" in which:
        budget()
        which = [w for w in which if w != "budget"]
    if "base" in which:
        run("base 6L512d V32k B8 flash")
    if "tinyvocab" in which:
        run("V=512 (head+loss removed)", vocab=512)
    if "fullattn" in which:
        run("impl=full (no flash kernel)", impl="full")
    if "b32" in which:
        run("B=32", batch=32)
    if "h4" in which:
        run("heads=4 (dh=128)", heads=4)
    if "h4fusedln" in which:
        run("heads=4 + fused add+LN junctions", heads=4, fused_ln=True)
    if "h4fusedall" in which:
        run("heads=4 + fused LN + fused xent", heads=4, fused_ln=True,
            fused_xent=True)
    if "h4fusedxent" in which:
        run("heads=4 + fused xent (save-s)", heads=4, fused_xent=True)
    if "b32v512" in which:
        run("B=32 V=512", batch=32, vocab=512)
