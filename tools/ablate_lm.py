"""Transformer-LM step-time ablations on the real chip (fori protocol).

The tunneled relay cannot serve ``jax.profiler`` traces, so component
costs are measured by differencing whole-step times across model/config
ablations (vocab size, attention impl, batch, head count). Used to drive
the round-3 MFU tuning recorded in BASELINE.md.
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _make_step_body, _time_fori, _compiled_flops, _peak_flops  # noqa: E402

from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.train import TrainState


def run(name, batch=8, seq_len=1024, vocab=32768, heads=8, layers=6,
        dim=512, impl="flash", remat=False, fused_ln=False, fused_xent=False):
    model = TransformerLM(
        vocab_size=vocab, embed_dim=dim, num_heads=heads, num_layers=layers,
        max_len=seq_len, impl=impl, rope=True, remat=remat,
        compute_dtype=jnp.bfloat16, fused_ln=fused_ln,
    )
    opt = make_optimizer("adamw", 3e-4)
    # synthetic_lm returns [n, seq_len+1] already; x/y slices give T=seq_len.
    seqs = jnp.asarray(synthetic_lm(batch, seq_len, vocab, seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]
    if fused_xent:
        # Un-jitted fused-xent body (mirrors train.make_lm_fused_train_step)
        # so _time_fori can wrap it in ONE dispatch.
        from tpudml.ops.xent_kernel import linear_cross_entropy

        def body(ts, tokens, labels):
            def loss_fn(params, model_state):
                feats, new_state = model.apply_features(
                    params, model_state, tokens, train=True, rng=None
                )
                head = model._cast_params(params)["head"]
                return linear_cross_entropy(
                    feats, head["kernel"], labels, head.get("bias"),
                    save_s=True,  # speed mode: V=32k fits comfortably
                ), new_state

            (loss, model_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(ts.params, ts.model_state)
            new_params, new_opt = opt.update(grads, ts.opt_state, ts.params)
            from tpudml.train import TrainState as TS
            return TS(params=new_params, model_state=model_state,
                      opt_state=new_opt, step=ts.step + 1), loss
    else:
        body = _make_step_body(model, opt)
    ts0 = TrainState.create(model, opt, seed_key(0))
    t0 = time.time()
    sec = _time_fori(body, ts0, (x, y), 8, 24)
    flops = _compiled_flops(jax.jit(body), ts0, x, y)
    peak = _peak_flops(jax.devices()[0])
    mfu = flops / sec / peak if flops and peak else float("nan")
    tokens = batch * seq_len
    print(
        f"{name:34s} {sec*1e3:8.2f} ms/step  {tokens/sec:12.0f} tok/s  "
        f"mfu {mfu:.3f}  ({time.time()-t0:.0f}s incl compile)",
        flush=True,
    )
    return sec


if __name__ == "__main__":
    which = sys.argv[1:] or ["base", "tinyvocab", "fullattn", "b32", "h4"]
    if "base" in which:
        run("base 6L512d V32k B8 flash")
    if "tinyvocab" in which:
        run("V=512 (head+loss removed)", vocab=512)
    if "fullattn" in which:
        run("impl=full (no flash kernel)", impl="full")
    if "b32" in which:
        run("B=32", batch=32)
    if "h4" in which:
        run("heads=4 (dh=128)", heads=4)
    if "h4fusedln" in which:
        run("heads=4 + fused add+LN junctions", heads=4, fused_ln=True)
    if "h4fusedall" in which:
        run("heads=4 + fused LN + fused xent", heads=4, fused_ln=True,
            fused_xent=True)
    if "h4fusedxent" in which:
        run("heads=4 + fused xent (save-s)", heads=4, fused_xent=True)
    if "b32v512" in which:
        run("B=32 V=512", batch=32, vocab=512)
