"""ResNet ceiling investigation (VERDICT r4 item 2): per-layer conv
timing + HLO dump + targeted experiments, on the real chip —
ResNet-18 (``layers``/``bn``/``block``/``hlo``) and ResNet-50 (``r50``).

The bench headline has sat at ~46-48% MFU for three rounds on the claim
that "CIFAR-scale early convs under-fill the MXU". This tool replaces
the claim with numbers:

- ``layers``: fori-timed fwd and fwd+bwd of every distinct conv shape in
  the ResNet-18 CIFAR step at the bench batch (1024, bf16), with
  achieved TFLOP/s and % of chip peak per layer — the weighted sum IS
  the model-level ceiling if the per-layer numbers are efficient.
- ``bn``: the BatchNorm+ReLU junction at each stage's shape (f32 stats
  on bf16 streams, the model's convention) — is the normalization
  breaking conv fusion expensively?
- ``block``: full BasicBlock fwd+bwd per stage (conv+BN+ReLU+residual),
  so (block − 2×conv − 2×bn) exposes unfused overhead.
- ``hlo``: dump the optimized HLO of the bench train step and print a
  fusion census (convolution count, fusion count, largest buffers).

``r50`` runs the same per-conv harness over every distinct ResNet-50
CIFAR conv shape at the fori-bench batch 256 (fwd+bwd only).

Usage: ``python tools/resnet_probe.py layers bn block r50`` (any subset).
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _peak_flops  # noqa: E402
from tools.micro_lm import time_fn  # noqa: E402

B = 1024  # the bench per-chip batch

# Distinct conv shapes in the CIFAR ResNet-18 step: (name, C_in, C_out,
# H_in, W_in, k, stride, count) — count = how many times the shape runs
# per forward (projection 1x1s listed separately).
CONVS = [
    ("stem 3->64 @32", 3, 64, 32, 32, 3, 1, 1),
    ("s1 64->64 @32", 64, 64, 32, 32, 3, 1, 4),
    ("s2 64->128 @32/s2", 64, 128, 32, 32, 3, 2, 1),
    ("s2 128->128 @16", 128, 128, 16, 16, 3, 1, 3),
    ("s2 proj 64->128 @32/s2", 64, 128, 32, 32, 1, 2, 1),
    ("s3 128->256 @16/s2", 128, 256, 16, 16, 3, 2, 1),
    ("s3 256->256 @8", 256, 256, 8, 8, 3, 1, 3),
    ("s3 proj 128->256 @16/s2", 128, 256, 16, 16, 1, 2, 1),
    ("s4 256->512 @8/s2", 256, 512, 8, 8, 3, 2, 1),
    ("s4 512->512 @4", 512, 512, 4, 4, 3, 1, 3),
    ("s4 proj 256->512 @8/s2", 256, 512, 8, 8, 1, 2, 1),
]


def conv_flops(ci, co, h, w, k, stride, batch):
    """Forward matmul FLOPs (2/MAC) of a SAME conv."""
    ho, wo = (h + stride - 1) // stride, (w + stride - 1) // stride
    return 2.0 * batch * ho * wo * k * k * ci * co


def run_layers(peak, batch=None, convs=None, fwd_too=True):
    batch = batch or B
    convs = convs if convs is not None else CONVS
    print(f"== per-conv timing, batch {batch}, bf16, peak {peak/1e12:.0f} TF/s")
    key = jax.random.PRNGKey(0)
    total_fwd_t = total_fb_t = total_fwd_f = 0.0
    for name, ci, co, h, w, k, stride, count in convs:
        x = jax.random.normal(key, (batch, h, w, ci), jnp.bfloat16)
        wgt = jax.random.normal(key, (k, k, ci, co), jnp.bfloat16) * 0.05
        dn = jax.lax.conv_dimension_numbers(
            x.shape, wgt.shape, ("NHWC", "HWIO", "NHWC")
        )

        def conv(x, wgt):
            # Pure-bf16 conv matching the model's Conv2D (nn/layers.py:125 —
            # no preferred_element_type; the MXU accumulates f32 internally).
            return jax.lax.conv_general_dilated(
                x, wgt, (stride, stride), "SAME", dimension_numbers=dn,
            )

        def fb(x, wgt):
            # fwd+bwd via vjp against a fixed-scale cotangent sum.
            y, pull = jax.vjp(conv, x, wgt)
            return pull(y)  # dX and dW with dY = y (shape-right cotangent)

        f = conv_flops(ci, co, h, w, k, stride, batch)
        # Sub-ms kernels: long fori windows so relay jitter differences out.
        line = f"   {name:26s} x{count}:"
        if fwd_too:
            t_fwd = time_fn(f"{name} fwd", conv, x, wgt, iters_lo=24, iters_hi=96)
            line += f" fwd {f/1e9:6.1f} GF {f/t_fwd/peak*100:5.1f}% |"
            total_fwd_t += count * t_fwd
        t_fb = time_fn(f"{name} fwd+bwd", fb, x, wgt, iters_lo=24, iters_hi=96)
        # fwd+bwd = 3x fwd FLOPs (dX + dW each equal the fwd contraction)
        print(line + f" fwd+bwd {3*f/t_fb/peak*100:5.1f}% of peak")
        total_fb_t += count * t_fb
        total_fwd_f += count * f
    if fwd_too:
        print(
            f"   SUM convs: fwd {total_fwd_t*1e3:.2f} ms"
            f" ({total_fwd_f/total_fwd_t/peak*100:.1f}% of peak),"
            f" fwd+bwd {total_fb_t*1e3:.2f} ms"
            f" ({3*total_fwd_f/total_fb_t/peak*100:.1f}% of peak)"
        )
    else:
        print(
            f"   SUM convs fwd+bwd {total_fb_t*1e3:.2f} ms"
            f" ({3*total_fwd_f/total_fb_t/peak*100:.1f}% of peak)"
        )


def run_bn(peak):
    print("== BatchNorm+ReLU at stage shapes (f32 stats, bf16 stream)")
    from tpudml.nn.layers import BatchNorm

    key = jax.random.PRNGKey(1)
    for ch, h in [(64, 32), (128, 16), (256, 8), (512, 4)]:
        x = jax.random.normal(key, (B, h, h, ch), jnp.bfloat16)
        bn = BatchNorm(ch)
        params, state = bn.init(jax.random.PRNGKey(2))

        def bnrelu(x):
            # Model convention (BasicBlock._bn): BN consumes the bf16 stream
            # directly; stats accumulate in f32 inside BatchNorm.apply.
            y, st = bn.apply(params, state, x, train=True)
            return jax.nn.relu(y).astype(jnp.bfloat16), st["mean"]

        time_fn(f"bn+relu {ch}ch @{h}x{h}", bnrelu, x)


def run_block(peak):
    print("== full BasicBlock fwd+bwd per stage")
    from tpudml.models.resnet import BasicBlock

    key = jax.random.PRNGKey(3)
    for ci, co, h, stride in [
        (64, 64, 32, 1), (64, 128, 32, 2), (128, 256, 16, 2),
        (256, 512, 8, 2),
    ]:
        blk = BasicBlock(ci, co, stride, compute_dtype=jnp.bfloat16)
        params, state = blk.init(jax.random.PRNGKey(4))
        x = jax.random.normal(key, (B, h, h, ci), jnp.bfloat16)

        def fb(x):
            def f(x):
                y, _ = blk.apply(params, state, x, train=True)
                return jnp.sum(y.astype(jnp.float32) ** 2)

            return jax.value_and_grad(f)(x)

        time_fn(f"block {ci}->{co} @{h} s{stride} fwd+bwd", fb, x)


def run_hlo():
    from bench import _make_step_body
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_classification
    from tpudml.models import ResNet18
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState

    model = ResNet18(compute_dtype=jnp.bfloat16)
    opt = make_optimizer("sgd", 0.1, momentum=0.9)
    images, labels = synthetic_classification(B, (32, 32, 3), 10, seed=0)
    body = _make_step_body(model, opt)
    ts0 = TrainState.create(model, opt, seed_key(0))
    txt = (
        jax.jit(body)
        .lower(ts0, jnp.asarray(images), jnp.asarray(labels))
        .compile()
        .as_text()
    )
    out = "/tmp/resnet_hlo.txt"
    with open(out, "w") as f:
        f.write(txt)
    convs = txt.count(" convolution(")
    fusions = txt.count(" fusion(")
    customs = txt.count(" custom-call(")
    print(f"wrote {len(txt)} chars to {out}")
    print(f"census: {convs} convolutions, {fusions} fusions, {customs} custom-calls")


# ResNet-50 CIFAR: EVERY distinct conv shape at the fori-bench batch 256
# (name, C_in, C_out, H, W, k, stride, count/fwd), from models/resnet.py
# ResNet(stage_sizes=(3,4,6,3), block="bottleneck"): block 0 of each
# stage reduces from the previous stage's width (and carries the stride
# and the 1x1 projection); blocks 1+ reduce from 4*mid.
R50_B = 256
R50_CONVS = [
    ("stem 3->64 @32", 3, 64, 32, 32, 3, 1, 1),
    ("s1 1x1 64->64 @32", 64, 64, 32, 32, 1, 1, 1),
    ("s1 1x1 256->64 @32", 256, 64, 32, 32, 1, 1, 2),
    ("s1 3x3 64->64 @32", 64, 64, 32, 32, 3, 1, 3),
    ("s1 1x1 64->256 @32 (+proj)", 64, 256, 32, 32, 1, 1, 4),
    ("s2 1x1 256->128 @32", 256, 128, 32, 32, 1, 1, 1),
    ("s2 1x1 512->128 @16", 512, 128, 16, 16, 1, 1, 3),
    ("s2 3x3 128->128 @32/s2", 128, 128, 32, 32, 3, 2, 1),
    ("s2 3x3 128->128 @16", 128, 128, 16, 16, 3, 1, 3),
    ("s2 1x1 128->512 @16", 128, 512, 16, 16, 1, 1, 4),
    ("s2 proj 256->512 @32/s2", 256, 512, 32, 32, 1, 2, 1),
    ("s3 1x1 512->256 @16", 512, 256, 16, 16, 1, 1, 1),
    ("s3 1x1 1024->256 @8", 1024, 256, 8, 8, 1, 1, 5),
    ("s3 3x3 256->256 @16/s2", 256, 256, 16, 16, 3, 2, 1),
    ("s3 3x3 256->256 @8", 256, 256, 8, 8, 3, 1, 5),
    ("s3 1x1 256->1024 @8", 256, 1024, 8, 8, 1, 1, 6),
    ("s3 proj 512->1024 @16/s2", 512, 1024, 16, 16, 1, 2, 1),
    ("s4 1x1 1024->512 @8", 1024, 512, 8, 8, 1, 1, 1),
    ("s4 1x1 2048->512 @4", 2048, 512, 4, 4, 1, 1, 2),
    ("s4 3x3 512->512 @8/s2", 512, 512, 8, 8, 3, 2, 1),
    ("s4 3x3 512->512 @4", 512, 512, 4, 4, 3, 1, 2),
    ("s4 1x1 512->2048 @4", 512, 2048, 4, 4, 1, 1, 3),
    ("s4 proj 1024->2048 @8/s2", 1024, 2048, 8, 8, 1, 2, 1),
]


def run_r50(peak):
    print("== ResNet-50 per-conv timing (shared harness, fwd+bwd only)")
    run_layers(peak, batch=R50_B, convs=R50_CONVS, fwd_too=False)


def main():
    which = set(sys.argv[1:]) or {"layers"}
    peak = _peak_flops(jax.devices()[0]) or 197e12
    if "hlo" in which:
        run_hlo()
    if "layers" in which:
        run_layers(peak)
    if "bn" in which:
        run_bn(peak)
    if "block" in which:
        run_block(peak)
    if "r50" in which:
        run_r50(peak)


if __name__ == "__main__":
    main()
