"""Striped vs contiguous ring-CP balance: real-chip kernel-fold timings.

The balanced-causal claim (tpudml/parallel/cp.py): with CONTIGUOUS
sequence layout, ring device i folds 1 causal diagonal block + i full
off-diagonal blocks, so the last device does ~2x the mean work and the
synchronous ring runs at the max; the STRIPED layout gives every device
the same ~half-visible fold per ring step. A 1-core virtual mesh cannot
show this (it serializes all devices: wall-clock = total, not max), so
this tool times the three fold kinds the ring actually issues — causal
diagonal, strict-causal (striped k_shift=1), and full off-diagonal —
with the real Pallas kernels on the chip, and derives both layouts'
per-ring-position time profiles.
"""

from __future__ import annotations

import os
import sys
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from tools.micro_lm import time_fn  # fori-protocol timer with LICM guard
from tpudml.ops import flash_forward_lse

def main():
    B, T_BLOCK, H, D = 2, 2048, 4, 128  # big enough to clear the tunnel's noise floor
    DEVICES = 8

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, T_BLOCK, H, D), jnp.bfloat16)

    t_diag = time_fn(
        "diag fold (causal)",
        partial(flash_forward_lse, causal=True),
        q, q, q, iters_lo=50, iters_hi=300,
    )
    t_strict = time_fn(
        "striped fold (strict causal, k_shift=1)",
        partial(flash_forward_lse, causal=True, k_shift=1),
        q, q, q, iters_lo=50, iters_hi=300,
    )
    t_full = time_fn(
        "off-diag fold (full)",
        partial(flash_forward_lse, causal=False),
        q, q, q, iters_lo=50, iters_hi=300,
    )

    print(f"\nderived per-ring-position totals (D={DEVICES}, ms):")
    contig = [(t_diag + i * t_full) * 1e3 for i in range(DEVICES)]
    # Striped: every ring step folds a ~half-visible block (diagonal-causal
    # on the own block, strict-causal on arriving ones) — identical on every
    # device by construction.
    striped = [(t_diag + (DEVICES - 1) * t_strict) * 1e3 for _ in range(DEVICES)]
    mean_c, max_c = sum(contig) / DEVICES, max(contig)
    print("contiguous:", " ".join(f"{t:6.2f}" for t in contig))
    print("striped:   ", " ".join(f"{t:6.2f}" for t in striped))
    print(
        f"contiguous max/mean imbalance: {max_c / mean_c:.2f}  "
        f"(ring step time is the MAX device)\n"
        f"striped max = {striped[0]:.2f} ms vs contiguous max = {max_c:.2f} ms "
        f"-> projected ring speedup {max_c / striped[0]:.2f}x"
    )


if __name__ == "__main__":
    main()
