"""Microbenchmarks for LM step components on the real chip (fori clock).

Isolates: embedding gather+scatter-add backward, LayerNorm stack,
flash-attention kernel at several block sizes, and the head matmul+loss.
``time_fn`` is importable (tools/cp_balance.py reuses it).
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _fetch  # noqa: E402


def time_fn(name, fn, *args, iters_lo=8, iters_hi=24):
    """fori-protocol timing of fn(*args) -> pytree; carries a f32 scalar."""

    @jax.jit
    def run(args, k):
        def one(_, carry):
            s, args = carry
            # Data-dependence on the carried runtime scalar so XLA's LICM
            # cannot hoist the (otherwise loop-invariant) body out of the
            # loop: adding s*1e-30 is numerically a no-op but opaque at
            # compile time. Int inputs (token ids) pass through untouched.
            eps = s * 1e-30
            args = jax.tree.map(
                lambda a: a + eps.astype(a.dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a,
                args,
            )
            out = fn(*args)
            s = sum(
                jnp.sum(x).astype(jnp.float32)
                for x in jax.tree.leaves(out)
            )
            return s, args

        return jax.lax.fori_loop(0, k, one, (jnp.zeros((), jnp.float32), args))

    def timed(k):
        t0 = time.perf_counter()
        s, _ = run(args, k)
        _fetch(s)
        return time.perf_counter() - t0

    timed(2)
    t_lo = min(timed(iters_lo) for _ in range(2))
    t_hi = min(timed(iters_hi) for _ in range(2))
    sec = (t_hi - t_lo) / (iters_hi - iters_lo) if t_hi > t_lo else t_hi / iters_hi
    print(f"{name:46s} {sec*1e3:8.3f} ms", flush=True)
    return sec


def main():
    B, T, H, D, V = 8, 1024, 8, 64, 32768
    d_model = H * D
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (B, T), 0, V)
    E = jax.random.normal(key, (V, d_model), jnp.bfloat16) * 0.02
    g_embed = jax.random.normal(key, (B, T, d_model), jnp.bfloat16)
    x = jax.random.normal(key, (B, T, d_model), jnp.bfloat16)
    qkv = jax.random.normal(key, (B, T, H, D), jnp.bfloat16)

    which = set(sys.argv[1:]) or {"embed", "ln", "flash", "head"}

    if "embed" in which:
        time_fn("embed gather fwd", lambda E: E[tokens], E)

        def embed_loss(E):
            return jnp.sum(
                E[tokens].astype(jnp.float32) * g_embed.astype(jnp.float32)
            )

        time_fn("embed gather+scatter bwd (grad)", jax.grad(embed_loss), E)

        def embed_loss_onehot(E):
            oh = jax.nn.one_hot(tokens.reshape(-1), V, dtype=jnp.bfloat16)
            h = (oh @ E).reshape(B, T, d_model)
            return jnp.sum(h.astype(jnp.float32) * g_embed.astype(jnp.float32))

        time_fn(
            "embed one-hot matmul fwd+bwd (grad)", jax.grad(embed_loss_onehot), E
        )

    if "ln" in which:
        from tpudml.nn.layers import LayerNorm

        ln = LayerNorm(d_model)
        p, _ = ln.init(key)

        def ln_stack(x):
            h = x
            for _ in range(12):  # 2 per block x 6 layers
                h = ln(p, h)
            return h

        time_fn("12x LayerNorm fwd", ln_stack, x)
        time_fn(
            "12x LayerNorm fwd+bwd",
            jax.grad(lambda x: jnp.sum(ln_stack(x).astype(jnp.float32))),
            x,
        )

    if "addln" in which:
        from tpudml.nn.layers import LayerNorm
        from tpudml.ops.layernorm_kernel import fused_add_layernorm

        ln = LayerNorm(d_model)
        p, _ = ln.init(key)
        r = jax.random.normal(key, (B, T, d_model), jnp.bfloat16)

        def chain_xla(s, r):
            for _ in range(12):
                s = s + r
                y = ln(p, s)
                r = y * 0.5  # stand-in branch: keeps the junctions chained
            return s

        def chain_fused(s, r):
            for _ in range(12):
                s, y = fused_add_layernorm(s, r, p["scale"], p["bias"])
                r = y * 0.5
            return s

        time_fn("12x (add+LN) chain fwd  XLA", chain_xla, x, r)
        time_fn("12x (add+LN) chain fwd  fused", chain_fused, x, r)
        for name, fn in (("XLA", chain_xla), ("fused", chain_fused)):
            time_fn(
                f"12x (add+LN) chain fwd+bwd {name}",
                jax.grad(
                    lambda s, r, fn=fn: jnp.sum(fn(s, r).astype(jnp.float32)),
                    argnums=(0, 1),
                ),
                x, r,
            )

    if "flash" in which:
        from tpudml.nn.attention import dot_product_attention
        from tpudml.ops.attention_kernel import flash_attention

        for bq, bk in [(128, 512), (256, 512), (512, 512), (512, 1024), (128, 128)]:
            time_fn(
                f"flash fwd causal bq={bq} bk={bk}",
                partial(flash_attention, causal=True, block_q=bq, block_k=bk),
                qkv, qkv, qkv,
            )
            time_fn(
                f"flash fwd+bwd causal bq={bq} bk={bk}",
                jax.grad(
                    lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                        flash_attention(
                            q, k, v, causal=True, block_q=bq, block_k=bk
                        ).astype(jnp.float32)
                    ),
                    argnums=(0, 1, 2),
                ),
                qkv, qkv, qkv,
            )
        time_fn(
            "xla full attn fwd+bwd causal",
            jax.grad(
                lambda q, k, v: jnp.sum(
                    dot_product_attention(q, k, v, causal=True).astype(jnp.float32)
                ),
                argnums=(0, 1, 2),
            ),
            qkv, qkv, qkv,
        )

    if "head" in which:
        from tpudml.nn.losses import softmax_cross_entropy

        W = jax.random.normal(key, (d_model, V), jnp.bfloat16) * 0.02
        y = jax.random.randint(key, (B, T), 0, V)

        def head_loss(W, x):
            logits = (x @ W).astype(jnp.float32)
            return softmax_cross_entropy(logits.reshape(-1, V), y.reshape(-1))

        time_fn("head matmul+xent fwd", head_loss, W, x)
        time_fn(
            "head matmul+xent fwd+bwd", jax.grad(head_loss, argnums=(0, 1)), W, x
        )

        from tpudml.ops.xent_kernel import linear_cross_entropy

        for mode in (False, True):
            tag = "save-s" if mode else "lean"

            def fused_loss(W, x, mode=mode):
                return linear_cross_entropy(
                    x.reshape(-1, d_model), W, y.reshape(-1), save_s=mode
                )

            time_fn(f"fused xent fwd ({tag})", fused_loss, W, x)
            time_fn(
                f"fused xent fwd+bwd ({tag})",
                jax.grad(fused_loss, argnums=(0, 1)), W, x,
            )


if __name__ == "__main__":
    main()
