"""Human-readable summary of one run directory's observability artifacts.

Reads whatever the flight recorder left behind (docs/OBSERVABILITY.md) —
any subset is fine; missing files just skip their section:

- ``metrics.jsonl``  — the MetricsWriter scalar stream (loss, ``obs/*``
  StepStats tags, comm/serve scalars);
- ``trace.json``     — the Chrome-trace-event export (per-category span
  count / total / p50 / p99);
- ``obs/drift.json`` — the static-vs-measured drift report
  (``python -m tpudml.obs --check-drift --out ...``);
- ``elastic.json``   — the elastic controller's reform/re-plan history
  (rounds, ports, backoffs, plan switches + receipts), plus any
  ``elastic``-category instants in the exported traces;
- ``fleet.json``     — the serving fleet's run summary (drill verdict
  rows with per-rank token CRCs + the merged per-replica trace path,
  or a deterministic router run's membership/latency aggregates);
- ``obs/mpmd.json``  — the MPMD re-mesh drill's verdict (bit-exactness
  vs the uninterrupted reference, re-mesh vs whole-world-restart MTTR)
  plus per-edge transfer-byte aggregates from the merged per-stage
  trace (one pid track per stage group);
- ``bench.json``     — a ``bench.py`` report dropped into the run dir:
  the residuals section surfaces the round-20 per-residual breakdown
  (``head_ms`` / ``junction_ms`` / ``exposed_comm_ms`` next to ``mfu``)
  for every row that carries it, cross-checked against the measured
  ``cat="comm"`` span total from the same run's ``trace.json``.

Usage::

    python -m tools.obs_report RUN_DIR
    python -m tools.obs_report logs/2026-08-05/12-00-00-task2-allreduce-w2
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _fmt_row(cols: list, widths: list[int]) -> str:
    return "  ".join(str(c).ljust(w) for c, w in zip(cols, widths)).rstrip()


def _table(header: list, rows: list[list]) -> str:
    widths = [
        max(len(str(header[i])), *(len(str(r[i])) for r in rows))
        for i in range(len(header))
    ]
    lines = [_fmt_row(header, widths), _fmt_row(["-" * w for w in widths], widths)]
    lines += [_fmt_row(r, widths) for r in rows]
    return "\n".join(lines)


def metrics_summary(path: Path) -> str | None:
    """Per-tag count / first / last from ``metrics.jsonl`` (every line is
    strict JSON — the writer serializes non-finite values as null with
    ``"finite": false``)."""
    if not path.is_file():
        return None
    series: dict[str, list] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)  # strict by contract
            series.setdefault(rec["tag"], []).append(rec["value"])
    if not series:
        return None
    rows = []
    for tag in sorted(series):
        vals = series[tag]
        fmt = lambda v: "non-finite" if v is None else f"{v:.6g}"
        rows.append([tag, len(vals), fmt(vals[0]), fmt(vals[-1])])
    return _table(["tag", "points", "first", "last"], rows)


def trace_summary(path: Path) -> str | None:
    """Per-(cat, name) span aggregates from an exported ``trace.json``,
    via the same ``Tracer.summary()`` percentiles the live recorder uses."""
    if not path.is_file():
        return None
    from tpudml.obs.tracer import Tracer

    doc = json.loads(path.read_text())
    tracer = Tracer()
    tracer.add_events([
        e for e in doc.get("traceEvents", []) if e.get("ph") in ("X", "i")
    ])
    spans = tracer.summary()["spans"]
    if not spans:
        return None
    rows = [
        [key, st["count"], st["total_us"], st["p50_us"], st["p99_us"]]
        for key, st in spans.items()
    ]
    return _table(["span (cat/name)", "count", "total_us", "p50_us", "p99_us"], rows)


def drift_summary(path: Path) -> str | None:
    """The drift monitor's verdict table (``obs/drift.json``)."""
    if not path.is_file():
        return None
    from tpudml.obs.drift import format_drift_table

    return format_drift_table(json.loads(path.read_text()))


def elastic_summary(run_dir: Path) -> str | None:
    """Reform/re-plan history from the elastic controller's artifacts:
    ``elastic.json`` (ElasticResult: one row per round, one per re-plan
    decision) plus any ``elastic``-category instants found in the
    exported traces (``trace_controller.json`` / ``trace.json``)."""
    path = run_dir / "elastic.json"
    if not path.is_file():
        return None
    res = json.loads(path.read_text())
    out = [
        f"outcome: {res.get('stop_reason', '?')}  "
        f"success={res.get('success')}  reforms={res.get('reforms')}  "
        f"final_world={res.get('final_world')}  "
        f"wall={res.get('total_elapsed_s', 0.0):.1f}s"
    ]
    rounds = res.get("records") or []
    if rounds:
        rows = [
            [
                r.get("round"),
                r.get("world"),
                r.get("coordinator_port"),
                r.get("failed_rank") if r.get("failed_rank") is not None else "-",
                "yes" if r.get("timed_out") else "no",
                f"{r.get('backoff_s', 0.0):.3f}",
                f"{r.get('elapsed_s', 0.0):.2f}",
            ]
            for r in rounds
        ]
        out.append(_table(
            ["round", "world", "port", "failed_rank", "timed_out",
             "backoff_s", "elapsed_s"],
            rows,
        ))
    replans = res.get("replans") or []
    if replans:
        rows = []
        for r in replans:
            verdicts = ",".join(
                rc.get("verdict", "?") for rc in r.get("receipts", ())
            ) or "-"
            rows.append([
                r.get("round", "-"),
                r.get("trigger"),
                f"{r.get('old_world')}→{r.get('new_world')}",
                r.get("old_key"),
                r.get("new_key"),
                "yes" if r.get("switched") else "no",
                f"{r.get('latency_s', 0.0) * 1e3:.1f}",
                verdicts,
                (r.get("error") or "-"),
            ])
        out.append(_table(
            ["round", "trigger", "world", "old plan", "new plan",
             "switched", "plan_ms", "receipts", "error"],
            rows,
        ))
    else:
        out.append("(no re-plans recorded)")
    # Controller-side instants, if a trace was exported alongside.
    instants = []
    for name in ("trace_controller.json", "trace.json"):
        tpath = run_dir / name
        if not tpath.is_file():
            continue
        try:
            doc = json.loads(tpath.read_text())
        except ValueError:
            continue
        instants += [
            e for e in doc.get("traceEvents", [])
            if e.get("ph") == "i" and e.get("cat") == "elastic"
        ]
    if instants:
        rows = [
            [
                e.get("name"),
                json.dumps(e.get("args", {}), sort_keys=True),
            ]
            for e in sorted(instants, key=lambda e: e.get("ts", 0))
        ]
        out.append(_table(["instant", "args"], rows))
    return "\n\n".join(out)


def fleet_summary(run_dir: Path) -> str | None:
    """Serving-fleet section: ``fleet.json`` left by either fleet form —
    the spawned drill (``python -m tpudml.serve.fleet --drill``: per-rank
    verdict rows + the merged per-replica trace) or a deterministic
    router run that dumped ``FleetReport.to_dict()``."""
    path = run_dir / "fleet.json"
    if not path.is_file():
        return None
    doc = json.loads(path.read_text())
    out = []
    if "ranks" in doc:  # drill report (fleet/drill.py)
        out.append(
            f"drill: ok={doc.get('ok')}  world={doc.get('world')}  "
            f"reforms={doc.get('reforms')}  "
            f"stop_reason={doc.get('stop_reason', '?')}  "
            f"crc_ok={doc.get('crc_ok')}"
        )
        rows = []
        for rank in sorted(doc.get("ranks", {}), key=int):
            r = doc["ranks"][rank]
            if "error" in r:
                rows.append([rank, "-", "-", "-", "-", r["error"]])
                continue
            rows.append([
                rank,
                r.get("requests"),
                r.get("generated_tokens"),
                f"{r.get('tokens_crc', 0):08x}",
                "yes" if r.get("match") else "NO",
                "-",
            ])
        out.append(_table(
            ["rank", "requests", "tokens", "crc", "match", "error"], rows
        ))
        if doc.get("merged_trace"):
            out.append(f"merged fleet trace: {doc['merged_trace']}")
    else:  # FleetReport.to_dict()
        lat = doc.get("latency", {})
        out.append(
            f"router: replicas={doc.get('replicas')}  "
            f"steps={doc.get('steps')}  "
            f"tok/s={doc.get('tokens_per_sec', 0.0):.1f}  "
            f"finished={doc.get('finished')}  "
            f"rejected={doc.get('rejected')}  expired={doc.get('expired')}"
        )
        out.append(
            f"membership: kills={doc.get('kills')}  "
            f"drains={doc.get('drains')}  readmits={doc.get('readmits')}  "
            f"peak_queue={doc.get('peak_queue_depth')}  "
            f"events_crc32={doc.get('events_crc32', 0):08x}"
        )
        if lat:
            out.append(
                f"latency: ttft p50/p99 = {lat.get('ttft_p50_s', 0.0):.4f}/"
                f"{lat.get('ttft_p99_s', 0.0):.4f}s  tpot p50/p99 = "
                f"{lat.get('per_token_p50_s', 0.0):.4f}/"
                f"{lat.get('per_token_p99_s', 0.0):.4f}s"
            )
        per_rep = doc.get("per_replica") or []
        if per_rep:
            rows = []
            for r in per_rep:
                busy = r.get("busy_slot_steps", 0)
                denom = max(r.get("decode_steps", 0) * r.get("slots", 1), 1)
                rows.append([
                    r.get("replica"),
                    r.get("decode_steps"),
                    f"{busy / denom:.2f}",
                    r.get("killed_at") if r.get("killed_at") is not None else "-",
                    r.get("reformed_at") if r.get("reformed_at") is not None else "-",
                ])
            out.append(_table(
                ["replica", "decode_steps", "occupancy", "killed_at",
                 "reformed_at"],
                rows,
            ))
        replans = doc.get("replans") or []
        for r in replans:
            out.append(
                f"replan @ step {r.get('step')}: {r.get('why', '?')} → "
                + (r.get("error") or json.dumps(
                    r.get("decision", {}), sort_keys=True))
            )
    return "\n\n".join(out)


def _bench_rows(doc: dict, label: str, rows: list) -> None:
    """Collect every bench row in ``doc`` (the top-level report plus the
    nested ``secondary`` / ``secondary_large`` / ``parsed`` sub-rows) that
    carries the round-20 per-residual fields."""
    if not isinstance(doc, dict):
        return
    if "head_ms" in doc or "junction_ms" in doc or "exposed_comm_ms" in doc:
        sec = doc.get("sec_per_step")
        step_ms = sec * 1e3 if isinstance(sec, (int, float)) else None
        resid = sum(
            doc.get(k) or 0.0
            for k in ("head_ms", "junction_ms", "exposed_comm_ms")
        )
        rows.append([
            doc.get("metric", label),
            f"{step_ms:.3f}" if step_ms is not None else "-",
            f"{doc.get('head_ms', 0.0):.4f}",
            f"{doc.get('junction_ms', 0.0):.4f}",
            f"{doc.get('exposed_comm_ms', 0.0):.4f}",
            f"{resid / step_ms:.1%}" if step_ms else "-",
        ])
    for key in ("secondary", "secondary_large", "parsed"):
        sub = doc.get(key)
        if isinstance(sub, dict):
            _bench_rows(sub, key, rows)


def residuals_summary(run_dir: Path) -> str | None:
    """Round-20 residuals section: the per-residual breakdown bench rows
    emit next to ``mfu`` (``head_ms`` — decode-head tail, ``junction_ms``
    — attention/residual/LN block junctions, ``exposed_comm_ms`` — wire
    time left on the critical path after overlap), read from a
    ``bench.json`` dropped in the run dir, plus the measured ``cat="comm"``
    span total from the same run's trace as the dynamic cross-check of
    the exposed-comm column."""
    out = []
    for name in ("bench.json", "obs/bench.json"):
        path = run_dir / name
        if not path.is_file():
            continue
        try:
            doc = json.loads(path.read_text())
        except ValueError:
            continue
        rows: list[list] = []
        _bench_rows(doc, "bench", rows)
        if rows:
            out.append(_table(
                ["bench row", "step_ms", "head_ms", "junction_ms",
                 "exposed_comm_ms", "residual_share"],
                rows,
            ))
        break
    # Dynamic cross-check: what the tracer actually measured on the wire.
    tpath = run_dir / "trace.json"
    if tpath.is_file():
        try:
            tdoc = json.loads(tpath.read_text())
        except ValueError:
            tdoc = {}
        comm = [
            e for e in tdoc.get("traceEvents", [])
            if e.get("ph") == "X" and e.get("cat") == "comm"
        ]
        if comm:
            total_us = sum(float(e.get("dur", 0.0)) for e in comm)
            out.append(
                f"measured comm spans: {len(comm)} span(s), "
                f"{total_us / 1e3:.3f} ms total wall on the wire "
                f"(compare against exposed_comm_ms: overlap hides the rest)"
            )
    return "\n\n".join(out) if out else None


def protocol_verdict(run_dir: Path) -> str | None:
    """One-line verdict of the MPMDController's pre-launch protocol
    gate (``protocol_report.json``, written per checked round), so the
    static evidence sits next to the dynamic drill verdict for the same
    spec."""
    for p in (run_dir / "protocol_report.json",
              run_dir / "run" / "protocol_report.json",
              run_dir / "obs" / "protocol_report.json"):
        if not p.is_file():
            continue
        try:
            doc = json.loads(p.read_text())
        except ValueError:
            return None
        checks = doc.get("checks") or []
        line = (f"protocol gate: {len(checks)} spec check(s)  "
                f"ok={doc.get('ok')}")
        bad = [c for c in checks if not c.get("ok")]
        if bad:
            c = bad[0]
            rules = sorted({
                f.get("rule") for f in c.get("findings", ())
                if f.get("severity") == "error"
            })
            line += (f"  — REJECTED at round {c.get('round')} "
                     f"({', '.join(rules)}); launch refused")
        else:
            line += "  (every round's spec P300-P303 clean pre-launch)"
        return line
    return None


def mpmd_summary(run_dir: Path) -> str | None:
    """MPMD section: the re-mesh drill's verdict (``obs/mpmd.json``,
    written by ``python -m tpudml.mpmd --drill``), the pre-launch
    protocol gate's verdict when a ``protocol_report.json`` is present,
    plus per-edge boundary transfer aggregates read out of the merged
    per-stage trace (one pid per stage group, ``cat="comm"`` spans with
    edge-labeled bytes)."""
    verdict = protocol_verdict(run_dir)
    path = run_dir / "obs" / "mpmd.json"
    if not path.is_file():
        path = run_dir / "mpmd.json"
    if not path.is_file():
        # A rejected launch leaves the gate receipts but no drill
        # verdict — still worth a section.
        return verdict
    doc = json.loads(path.read_text())
    out = []
    victim = doc.get("victim") or {}
    out.append(
        f"drill: ok={doc.get('ok')}  mode={doc.get('mode', '?')}  "
        f"bit_exact={doc.get('bit_exact')}  "
        f"in_place={doc.get('in_place')}  "
        f"stop_reason={doc.get('stop_reason', '?')}"
    )
    if verdict:
        out.append(verdict)
    out.append(
        f"re-mesh: victim=stage {victim.get('stage', '?')} rank "
        f"{victim.get('rank', '?')} (rc {victim.get('rc', '?')})  "
        f"final stage worlds={doc.get('final_stage_worlds')}  "
        f"resume_step={doc.get('resume_step')}  "
        f"steps_lost={doc.get('steps_lost')}  "
        f"fresh_ports={doc.get('fresh_ports')}"
    )
    mttr = doc.get("remesh_mttr_s")
    naive = doc.get("naive") or {}
    line = "mttr: re-mesh-in-place "
    line += f"{mttr:.2f}s" if mttr is not None else "-"
    if naive.get("restart_mttr_s") is not None:
        line += (
            f"  whole-world-restart {naive['restart_mttr_s']:.2f}s  "
            f"(re-mesh wins: {doc.get('remesh_beats_naive')})"
        )
    out.append(line)
    sps = doc.get("steps_per_s") or {}
    crcs = doc.get("params_crc") or {}
    if sps:
        rows = [
            [k, f"{sps[k]:.2f}", crcs.get(k, "-")]
            for k in sorted(sps)
        ]
        out.append(_table(["stage rank", "steps/s", "params_crc"], rows))
    # Per-edge transfer bytes from the merged trace: sum the cat="comm"
    # p2p spans' byte args per (pid, edge) — one row per stage track.
    tpath = run_dir / "obs" / "trace.json"
    if tpath.is_file():
        try:
            tdoc = json.loads(tpath.read_text())
        except ValueError:
            tdoc = {}
        edges: dict[tuple, list] = {}
        for e in tdoc.get("traceEvents", []):
            if e.get("cat") != "comm" or e.get("ph") != "X":
                continue
            args = e.get("args") or {}
            if "edge" not in args:
                continue
            key = (e.get("pid"), args["edge"], e.get("name"))
            agg = edges.setdefault(key, [0, 0])
            agg[0] += 1
            agg[1] += int(args.get("bytes", 0))
        if edges:
            rows = [
                [pid, edge, name, n, nbytes]
                for (pid, edge, name), (n, nbytes) in sorted(edges.items())
            ]
            out.append(_table(
                ["stage pid", "edge", "span", "frames", "bytes"], rows
            ))
    return "\n\n".join(out)


def report(run_dir: str | Path) -> str:
    run_dir = Path(run_dir)
    sections = [
        ("metrics.jsonl", metrics_summary(run_dir / "metrics.jsonl")),
        ("trace.json", trace_summary(run_dir / "trace.json")),
        ("obs/drift.json", drift_summary(run_dir / "obs" / "drift.json")),
        ("elastic.json (reform/re-plan)", elastic_summary(run_dir)),
        ("fleet.json (serving fleet)", fleet_summary(run_dir)),
        ("mpmd.json (MPMD re-mesh)", mpmd_summary(run_dir)),
        ("residuals (bench.json + comm spans)", residuals_summary(run_dir)),
    ]
    out = [f"== obs report: {run_dir} =="]
    found = False
    for title, body in sections:
        if body is None:
            continue
        found = True
        out.append(f"\n-- {title} --\n{body}")
    if not found:
        out.append("(no observability artifacts found)")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("run_dir", help="run directory (MetricsWriter.run_dir)")
    args = p.parse_args(argv)
    if not Path(args.run_dir).is_dir():
        print(f"error: {args.run_dir} is not a directory", file=sys.stderr)
        return 2
    print(report(args.run_dir))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
