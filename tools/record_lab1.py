"""Lab1 deliverable recording: the GD / SGD / Adam convergence comparison
the reference grades (sections/checking.tex:5-9, task1.tex:8-23 — compare
first/second-order & deterministic/stochastic optimizer character).

Runs tasks.task1 at a matched budget per optimizer on the current backend
and prints a loss-trajectory table for BASELINE.md. Per-optimizer lr is
tuned the way a student would (the reference's own lr rule is
Adam-specific); the comparison is convergence CHARACTER, not lr fairness.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tasks.task1 import reference_defaults, run  # noqa: E402

CONFIGS = [
    # (label, optimizer, batch, epochs, lr, momentum): all rows at the
    # reference's batch 200 (codes/task1/pytorch/model.py:96) and a
    # shared epoch budget — how the reference lab itself compares them
    # (its GdOptimizer also runs on DataLoader mini-batches; the
    # deterministic-vs-stochastic axis is discussed in the analysis).
    # A true full-batch (4096) GD row was attempted and DROPPED: the
    # LeNet train step at batch >=1024 sits >9 minutes in XLA
    # backend_compile through this environment's remote AOT helper on
    # every attempt (batch-200 compiles in ~5 s; ResNet-18 at batch
    # 1024 in ~40 s — it is large-batch-LeNet-specific).
    ("gd (plain first-order)", "gd", 200, 8, 0.05, 0.0),
    ("sgd + momentum 0.9", "sgd", 200, 8, 0.05, 0.9),
    ("adam", "adam", 200, 8, 0.002, 0.0),
    ("adam_ref (no bias corr.)", "adam_ref", 200, 8, 0.002, 0.0),
]


def loss_series(run_dir: Path) -> list[tuple[int, float]]:
    out = []
    with open(run_dir / "metrics.jsonl") as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("tag") == "Train Loss":
                out.append((rec["step"], rec["value"]))
    return out


def main():
    rows = []
    for label, opt, batch, epochs, lr, momentum in CONFIGS:
        cfg = reference_defaults()
        cfg.optimizer = opt
        cfg.lr = lr
        cfg.momentum = momentum
        cfg.epochs = epochs
        cfg.data.batch_size = batch
        cfg.data.dataset = "synthetic"
        cfg.log_every = 1 if batch >= 4096 else 5
        metrics = run(cfg)
        run_dir = max(
            (p for p in Path(cfg.log_dir).rglob("*task1-*") if p.is_dir()),
            key=lambda p: p.stat().st_mtime,
        )
        series = loss_series(run_dir)
        rows.append((label, batch, epochs, lr, series, metrics))

    print("\n=== Lab1 optimizer comparison (copy to BASELINE.md) ===")
    for label, batch, epochs, lr, series, metrics in rows:
        vals = dict(series)
        steps = sorted(vals)
        picks = [steps[0]] + [
            steps[min(len(steps) - 1, int(f * (len(steps) - 1)))]
            for f in (0.1, 0.25, 0.5, 1.0)
        ]
        traj = " → ".join(f"{vals[s]:.4f}@{s}" for s in dict.fromkeys(picks))
        print(
            f"| {label} | b={batch} lr={lr} e={epochs} | {traj} | "
            f"{metrics['test_accuracy'] * 100:.2f}% | "
            f"{metrics.get('train_time_s', float('nan')):.1f}s |"
        )


if __name__ == "__main__":
    main()
