"""Single-chip A/B recordings for the round-4/5 fused kernels.

Two recordings (both on one real chip, flagship config — 6L·512d·4H(dh128),
T=1024, B=8, V=32k, bf16, flash attention):

1. ``head``: in-situ 3-way head comparison on the SINGLE-device step —
   standard materialized-logits step vs fused-xent lean vs fused-xent
   save-s, fori median-of-3 each. The kernel-granularity microbench
   (tools/xent_micro.py) cannot separate these within jitter; the
   whole-step numbers are where the save-s default earns (or loses)
   its place.
2. ``cp``: the ContextParallel engine on a 1-device {"seq": 1} mesh,
   with and without the fused kernels (fused_ln trunk + fused_xent
   head) — VERDICT r4 item 1's done-criterion: the multi-chip engine's
   per-chip step must profit from the kernels exactly like the
   single-device step. World=1 makes the ring degenerate (no
   communication), so the delta is pure kernel effect at matched
   engine overhead. Protocol: pipelined (chained donated dispatches,
   sync at end) — the engine step is pre-jitted with donation, so the
   fori body cannot wrap it; both sides share the protocol, making the
   A/B valid, and today's pipelined runs sit within ~8% of fori.
"""

from __future__ import annotations

import os
import statistics
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _time_fori, _time_pipelined  # noqa: E402

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.parallel.cp import ContextParallel
from tpudml.train import (
    TrainState,
    make_lm_fused_train_step_body,
    make_train_step_body,
)

CFG = dict(
    vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6,
    max_len=1024, rope=True, compute_dtype=jnp.bfloat16,
)
T, B = 1024, 8


def _batch():
    seqs = jnp.asarray(synthetic_lm(B, T, CFG["vocab_size"], seed=1))
    return seqs[:, :-1], seqs[:, 1:]


def run_head():
    print("== in-situ head A/B (single-device step, fori median-of-3)")
    x, y = _batch()
    opt = make_optimizer("adamw", 3e-4)
    model = TransformerLM(**CFG, impl="flash", fused_ln=True)

    def variants():
        std = make_train_step_body(model, opt)
        yield "standard (materialized logits)", lambda ts, xx, yy: (
            lambda r: (r[0], r[1]["loss"])
        )(std(ts, xx, yy))
        for label, ss in [("fused lean", False), ("fused save-s", True)]:
            fb = make_lm_fused_train_step_body(model, opt, save_scores=ss)
            yield label, lambda ts, xx, yy, fb=fb: (
                lambda r: (r[0], r[1]["loss"])
            )(fb(ts, xx, yy))

    for label, body in variants():
        ts0 = TrainState.create(model, opt, seed_key(0))
        sec, runs = _time_fori(body, ts0, (x, y), 8, 40, reps=3)
        print(
            f"   {label:34s} {sec*1e3:7.2f} ms/step  "
            f"runs {[round(r*1e3, 2) for r in sorted(runs)]}",
            flush=True,
        )


def run_cp():
    print("== CP engine (1-device seq mesh) with/without fused kernels")
    print("   protocol: pipelined, 30 iters, median of 3 passes")
    x, y = _batch()
    mesh = make_mesh(MeshConfig({"seq": 1}), jax.devices()[:1])
    opt = make_optimizer("adamw", 3e-4)
    for label, fused in [("unfused trunk + logits head", False),
                         ("fused_ln + fused_xent", True)]:
        model = TransformerLM(
            **CFG, impl="ring", seq_sharded=True, fused_ln=fused
        )
        eng = ContextParallel(model, opt, mesh, fused_xent=fused)
        step = eng.make_train_step()
        secs = []
        for _ in range(3):
            ts = eng.create_state(seed_key(0))
            secs.append(_time_pipelined(step, ts, (x, y), 30))
        sec = statistics.median(secs)
        print(
            f"   {label:34s} {sec*1e3:7.2f} ms/step  "
            f"({B*T/sec:,.0f} tok/s)  runs "
            f"{[round(s*1e3, 2) for s in sorted(secs)]}",
            flush=True,
        )


def main():
    which = set(sys.argv[1:]) or {"head", "cp"}
    if "head" in which:
        run_head()
    if "cp" in which:
        run_cp()


if __name__ == "__main__":
    main()
