"""Capacity-drop quality study: gather-vs-ragged under induced routing
imbalance (VERDICT item 5).

The question the dispatch matrix leaves open: does the gather path's
capacity truncation COST QUALITY when routing is imbalanced, and does
the dropless ragged path buy it back? This tool measures it on a task
where imbalance is a controlled knob rather than an accident of
training dynamics:

- inputs are drawn from E Gaussian clusters with distinct means and the
  regression target is a DIFFERENT linear map per cluster, so a top-1
  MoE must specialize one expert per cluster to fit;
- the cluster mixture is the imbalance knob: ``balanced`` = uniform
  proportions (every expert near 1/E load), ``skewed`` = one cluster
  carries 70% of the tokens, so its expert's row count blows through a
  1.25 capacity at E=4 (cap slots ≈ 31% of tokens) and the gather path
  must drop most of that cluster every step;
- each (mixture × dispatch) variant trains the same MoELayer from the
  same init with adam + MSE + the standard aux pressure, recording the
  loss curve and the exact post-training keep-rate (recomputed from the
  trained router's top-1 counts against the static capacity — no
  instrumentation inside the layer).

Output: one loss-curve line per variant plus a final summary table —
the recording behind BASELINE.md's "gather default, ragged for skew"
verdict (or its refutation).
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudml.core.prng import seed_key  # noqa: E402
from tpudml.nn.moe import MoELayer  # noqa: E402
from tpudml.optim import make_optimizer  # noqa: E402

D = 32
E = 4
N_TOKENS = 1024
STEPS = 400
RECORD_EVERY = 50
AUX_WEIGHT = 0.01

MIXTURES = {
    "balanced": jnp.full((E,), 1.0 / E),
    "skewed": jnp.array([0.70, 0.15, 0.10, 0.05]),
}

VARIANTS = (
    ("gather_cap1.25", dict(dispatch="gather", capacity_factor=1.25)),
    ("gather_cap2.0", dict(dispatch="gather", capacity_factor=2.0)),
    ("ragged", dict(dispatch="ragged")),
)


def make_task(key, mixture):
    """Clustered regression: cluster c's tokens map through its own
    random linear map — solvable exactly only if every cluster's tokens
    reach a specialized expert."""
    kc, km, kx, kn = jax.random.split(key, 4)
    centers = jax.random.normal(kc, (E, D)) * 3.0
    maps = jax.random.normal(km, (E, D, D)) / jnp.sqrt(D)
    cluster = jax.random.choice(kx, E, (N_TOKENS,), p=mixture)
    x = centers[cluster] + jax.random.normal(kn, (N_TOKENS, D))
    y = jnp.einsum("nd,ndk->nk", x, maps[cluster])
    return x, y, cluster


def keep_rate(layer, params, x):
    """Fraction of tokens the trained router keeps under the static
    capacity: Σ_e min(count_e, cap) / N (ragged keeps everything by
    construction)."""
    if layer.dispatch == "ragged":
        return 1.0
    logits = x @ params["router"]["kernel"]
    top1 = jnp.argmax(logits, axis=-1)
    counts = jnp.bincount(top1, length=E)
    cap = layer._capacity(x.shape[0])
    return float(jnp.sum(jnp.minimum(counts, cap)) / x.shape[0])


def train_variant(layer, x, y):
    params, state = layer.init(seed_key(1))
    opt = make_optimizer("adam", 1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, model_state):
        def loss_fn(p):
            out, new_state = layer.apply(p, model_state, x)
            mse = jnp.mean((out - y) ** 2)
            return mse + AUX_WEIGHT * new_state["aux_loss"], (mse, new_state)

        (_, (mse, new_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = opt.update(grads, opt_state, params)
        return new_params, new_opt, new_state, mse

    curve = []
    for i in range(STEPS):
        params, opt_state, state, mse = step(params, opt_state, state)
        if (i + 1) % RECORD_EVERY == 0:
            curve.append(round(float(mse), 4))
    return params, curve


def main():
    summary = []
    for mix_name, mixture in MIXTURES.items():
        x, y, cluster = make_task(seed_key(0), mixture)
        frac = [round(float(jnp.mean(cluster == e)), 3) for e in range(E)]
        print(f"mixture={mix_name} cluster fractions={frac}", flush=True)
        for var_name, kw in VARIANTS:
            layer = MoELayer(D, E, mlp_ratio=2, top_k=1, **kw)
            params, curve = train_variant(layer, x, y)
            kr = keep_rate(layer, params, x)
            print(
                f"  {var_name:16s} keep-rate {kr:6.1%}  "
                f"loss curve (every {RECORD_EVERY}): {curve}",
                flush=True,
            )
            summary.append((mix_name, var_name, kr, curve[-1]))
    print("\nfinal-loss summary (mixture, variant, keep-rate, mse@400):")
    for row in summary:
        print(f"  {row[0]:9s} {row[1]:16s} {row[2]:6.1%} {row[3]:.4f}")


if __name__ == "__main__":
    main()
