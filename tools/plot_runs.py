"""Render the reference's loss-curve deliverables from metrics.jsonl.

The reference grades loss CURVES, not just terminal numbers — Lab1's
optimizer comparison ships TensorBoard screenshots
(/root/reference/sections/task1.tex:22, figures/) and the acceptance doc
pins curve quality (/root/reference/sections/checking.tex:5-9). Here the
curves are first-class repo artifacts: SVG+PNG rendered from the SAME
``metrics.jsonl`` series the MetricsWriter logs (one JSON record per
scalar — the TensorBoard event stream's plain-text twin), so the figures
are reproducible from checked-in data with no TensorBoard session.

Usage::

    python -m tools.plot_runs lab1            # figures/lab1_optimizer_loss.*
    python -m tools.plot_runs dp [--regen]    # figures/task23_dp_loss.*
    python -m tools.plot_runs curves RUN_DIR:LABEL ... --out figures/x.svg

``lab1`` renders the round-4 real-chip recordings checked in under
``figures/data/lab1/`` (the four runs of ``tools/record_lab1.py`` whose
trajectory table lives in BASELINE.md). ``dp`` renders the task2/task3
data-parallel convergence curves from ``figures/data/dp/``; ``--regen``
re-runs the 8-replica DP quality-pin configs on the current backend
(the simulated CPU mesh reproduces the 99.90% pins) and refreshes the
checked-in series first.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
# Only needed for direct `python tools/plot_runs.py` invocation; the
# documented `python -m tools.plot_runs` form resolves imports already.
sys.path.insert(0, str(REPO))

FIGURES = REPO / "figures"

# (label, checked-in series file) — the round-4 Lab1 recordings; labels
# match the BASELINE.md trajectory table rows.
LAB1_SERIES = [
    ("gd, lr 0.05", "gd.jsonl"),
    ("sgd + momentum 0.9, lr 0.05", "sgd_momentum.jsonl"),
    ("adam, lr 0.002", "adam.jsonl"),
    ("adam_ref (no bias corr.), lr 0.002", "adam_ref.jsonl"),
]

DP_SERIES = [
    ("task2 DP, 8 replicas (adam)", "task2_dp8.jsonl"),
    ("task3 DP, partition sampler", "task3_partition.jsonl"),
    ("task3 DP, sampling sampler", "task3_sampling.jsonl"),
]


def load_series(path: Path, tag: str = "Train Loss") -> tuple[list, list]:
    steps, values = [], []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("tag") == tag:
                steps.append(rec["step"])
                values.append(rec["value"])
    if not steps:
        raise SystemExit(f"no {tag!r} records in {path}")
    return steps, values


def render(series: list[tuple[str, list, list]], out_base: Path, *,
           title: str, logy: bool = False) -> list[Path]:
    """One loss-vs-step chart → ``out_base``.svg and .png."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7.2, 4.4), dpi=120)
    # Cycle linestyles as well as colors: coinciding curves (task2 DP ==
    # task3 partition by construction) stay individually visible.
    styles = ["-", "--", "-.", ":"]
    for i, (label, steps, values) in enumerate(series):
        ax.plot(steps, values, label=label, linewidth=1.8,
                linestyle=styles[i % len(styles)])
    ax.set_xlabel("training step")
    ax.set_ylabel("train loss")
    if logy:
        ax.set_yscale("log")
    ax.set_title(title)
    ax.grid(True, alpha=0.3)
    ax.legend(frameon=False, fontsize=9)
    fig.tight_layout()
    outs = []
    out_base.parent.mkdir(parents=True, exist_ok=True)
    for ext in ("svg", "png"):
        out = out_base.with_suffix(f".{ext}")
        fig.savefig(out)
        outs.append(out)
    plt.close(fig)
    return outs


def cmd_lab1(_args) -> None:
    data = FIGURES / "data" / "lab1"
    series = [
        (label, *load_series(data / fname)) for label, fname in LAB1_SERIES
    ]
    # Log y-axis: the comparison spans 2.3 → 3e-4; linear scale collapses
    # every fast optimizer onto the x-axis and the lab's asked-for
    # convergence CHARACTER (early-iter behavior) becomes invisible.
    outs = render(
        series, FIGURES / "lab1_optimizer_loss",
        title="Lab1: optimizer convergence (LeNet, batch 200, real TPU chip)",
        logy=True,
    )
    print("\n".join(str(o) for o in outs))


def _regen_dp() -> None:
    """Re-run the DP quality-pin configs and refresh figures/data/dp/.

    Provisions the 8-device simulated CPU mesh first and pins every job
    to ``--n_devices 8`` — ``tasks.common.select_devices`` silently falls
    back to whatever is visible when asked for more, which would label a
    1-replica regeneration as the 8-replica recording."""
    from __graft_entry__ import _provision_cpu_mesh

    _provision_cpu_mesh(8)
    import jax

    if jax.device_count() < 8:
        raise SystemExit(
            f"--regen needs an 8-device mesh (have {jax.device_count()}); "
            "run under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "JAX_PLATFORMS=cpu"
        )

    data = FIGURES / "data" / "dp"
    data.mkdir(parents=True, exist_ok=True)

    from tasks.task2 import main as task2_main
    from tasks.task3 import main as task3_main

    common = ["--dataset", "synthetic", "--epochs", "5", "--optimizer",
              "adam", "--lr", "0.002", "--log_every", "5",
              "--n_devices", "8"]
    jobs = [
        ("task2_dp8.jsonl", task2_main, common),
        ("task3_partition.jsonl", task3_main,
         common + ["--division", "partition"]),
        ("task3_sampling.jsonl", task3_main,
         common + ["--division", "sampling"]),
    ]
    for fname, entry, argv in jobs:
        run_dir = Path(entry(argv)["run_dir"])
        (data / fname).write_bytes((run_dir / "metrics.jsonl").read_bytes())
        print(f"refreshed {data / fname} from {run_dir}")


def cmd_dp(args) -> None:
    if args.regen:
        _regen_dp()
    data = FIGURES / "data" / "dp"
    series = [
        (label, *load_series(data / fname)) for label, fname in DP_SERIES
    ]
    outs = render(
        series, FIGURES / "task23_dp_loss",
        title="task2/task3: data-parallel convergence (8-replica mesh)",
        logy=True,
    )
    print("\n".join(str(o) for o in outs))


def cmd_curves(args) -> None:
    series = []
    for spec in args.runs:
        run_dir, _, label = spec.partition(":")
        series.append(
            (label or run_dir, *load_series(Path(run_dir) / "metrics.jsonl",
                                            args.tag))
        )
    outs = render(series, Path(args.out).with_suffix(""), title=args.tag,
                  logy=args.logy)
    print("\n".join(str(o) for o in outs))


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("lab1", help="Lab1 four-optimizer loss curves")
    dp = sub.add_parser("dp", help="task2/task3 DP loss curves")
    dp.add_argument("--regen", action="store_true",
                    help="re-run the DP configs to refresh figures/data/dp")
    cur = sub.add_parser("curves", help="generic RUN_DIR:LABEL plotting")
    cur.add_argument("runs", nargs="+", metavar="RUN_DIR:LABEL")
    cur.add_argument("--out", required=True)
    cur.add_argument("--tag", default="Train Loss")
    cur.add_argument("--logy", action="store_true")
    args = p.parse_args(argv)
    {"lab1": cmd_lab1, "dp": cmd_dp, "curves": cmd_curves}[args.cmd](args)


if __name__ == "__main__":
    main()
