"""Kernel-granularity fused-xent microbench (VERDICT r4 item 5).

Times the three head implementations at the flagship shape
([8192, 512] × [512, 32768], bf16 weights) with the fori differencing
discipline at KERNEL granularity, repeated enough to separate the
save-s mode from XLA run-to-run jitter (the round-4 recording read
"3.7-5.7 across runs" and could not call a winner):

- ``xla``: the memory-lean XLA reference (materialized logits,
  lean-VJP softmax_cross_entropy) — value_and_grad wrt (x, W).
- ``lean``: the Pallas fused kernel, O(N) residuals, recompute backward.
- ``saves``: the Pallas fused kernel with the f32 score residual
  (O(N·V) memory, 2 fewer backward matmuls).

Each variant is timed ``--reps`` times (median + spread printed); the
decision rule for the save-s default is printed at the end.
"""

from __future__ import annotations

import os
import statistics
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _fetch  # noqa: E402

from tpudml.nn.losses import softmax_cross_entropy
from tpudml.ops.xent_kernel import linear_cross_entropy


def time_grad(fn, x, w, y, reps, k_lo=8, k_hi=24):
    """Median-of-reps fori-differenced sec/call of value_and_grad(fn)."""
    vg = jax.value_and_grad(lambda x, w: fn(x, w, y), argnums=(0, 1))

    @jax.jit
    def run(x, w, k):
        def one(_, carry):
            s, x, w = carry
            eps = (s * 1e-30).astype(x.dtype)
            loss, (dx, dw) = vg(x + eps, w + eps.astype(w.dtype))
            s = loss + jnp.sum(dx).astype(jnp.float32) * 1e-30 + jnp.sum(
                dw
            ).astype(jnp.float32) * 1e-30
            return s.astype(jnp.float32), x, w

        return jax.lax.fori_loop(0, k, one, (jnp.zeros((), jnp.float32), x, w))

    def timed(k):
        t0 = time.perf_counter()
        s, _, _ = run(x, w, k)
        _fetch(s)
        return time.perf_counter() - t0

    timed(2)
    runs = []
    for _ in range(reps):
        t_lo = min(timed(k_lo) for _ in range(2))
        t_hi = min(timed(k_hi) for _ in range(2))
        runs.append(
            (t_hi - t_lo) / (k_hi - k_lo) if t_hi > t_lo else t_hi / k_hi
        )
    return statistics.median(runs), sorted(runs)


def main():
    reps = 5
    for a in sys.argv[1:]:
        if a.startswith("--reps="):
            reps = int(a.split("=")[1])
    n, d, v = 8192, 512, 32768
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, d), jnp.bfloat16)
    w = jax.random.normal(key, (d, v), jnp.bfloat16) * 0.02
    y = jax.random.randint(key, (n,), 0, v)

    variants = {
        "xla_lean": lambda x, w, y: softmax_cross_entropy(
            (x @ w).astype(jnp.float32), y
        ),
        # save_s=False EXPLICITLY: the default is None = auto, which at
        # this shape resolves to the save-s mode — the lean arm must
        # force the O(N) backward or it times save-s twice.
        "fused_lean": lambda x, w, y: linear_cross_entropy(
            x, w, y, save_s=False
        ),
        "fused_saves": lambda x, w, y: linear_cross_entropy(
            x, w, y, save_s=True
        ),
    }
    results = {}
    for name, fn in variants.items():
        med, runs = time_grad(fn, x, w, y, reps)
        results[name] = (med, runs)
        spread = (runs[-1] - runs[0]) / med
        print(
            f"{name:12s} median {med*1e3:7.3f} ms  "
            f"runs {[round(r*1e3, 3) for r in runs]}  spread {spread:.1%}",
            flush=True,
        )

    xla, _ = results["xla_lean"]
    lean, _ = results["fused_lean"]
    saves, saves_runs = results["fused_saves"]
    # Decision rule: save-s earns default-on iff its WORST rep beats the
    # competing variants' BEST rep — a jitter-proof separation.
    best_other = min(results["xla_lean"][1][0], results["fused_lean"][1][0])
    print(
        f"\nsave-s worst {saves_runs[-1]*1e3:.3f} ms vs others' best "
        f"{best_other*1e3:.3f} ms -> "
        + ("SEPARATED: save-s wins beyond jitter"
           if saves_runs[-1] < best_other else "NOT separated")
    )


if __name__ == "__main__":
    main()
