"""Dump the optimized HLO of the flagship LM train step (diagnostic).

The tunnel cannot serve profiler traces, but the compiled executable's
optimized HLO text comes back through the compile path — fusion
boundaries, buffer sizes, and kernel count are readable from it.
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _make_step_body  # noqa: E402

from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.train import TrainState


def main():
    fused = "fused" in sys.argv[1:]
    model = TransformerLM(
        vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6,
        max_len=1024, impl="flash", rope=True, compute_dtype=jnp.bfloat16,
        fused_ln=fused,
    )
    opt = make_optimizer("adamw", 3e-4)
    seqs = jnp.asarray(synthetic_lm(8, 1024, 32768, seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]
    body = _make_step_body(model, opt)
    ts0 = TrainState.create(model, opt, seed_key(0))
    compiled = jax.jit(body).lower(ts0, x, y).compile()
    txt = compiled.as_text()
    out = sys.argv[-1] if sys.argv[-1].endswith(".txt") else "/tmp/hlo.txt"
    with open(out, "w") as f:
        f.write(txt)
    print(f"wrote {len(txt)} chars to {out}")


if __name__ == "__main__":
    main()
