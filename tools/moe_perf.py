"""Single-chip MoE throughput recording (VERDICT r4 item 4).

Dense-vs-MoE tokens/sec at MATCHED ACTIVE FLOPs: a top-1 Switch FFN with
per-expert width equal to the dense FFN routes every token through
exactly one expert, so the per-token matmul math is identical to the
dense model's — any throughput gap is dispatch overhead (router,
capacity buffers, gather/scatter, the per-expert loop/einsum).

Prints, for the flagship trunk config (d=512, L=6, T=1024, B=8, bf16):
- dense baseline tokens/sec (standard step, flash attention);
- MoE tokens/sec at E ∈ {4, 8} experts (top-1, capacity 1.25/2.0);
- capacity utilization (fraction of expert slots filled, from the
  router's aux state) and the implied dispatch overhead ms/step.

Protocol: the bench fori clock (K steps per dispatch, differenced).

``--attrib`` runs the per-E fwd/bwd KERNEL attribution instead: the
isolated expert-FFN composition on presorted rows — stock
``lax.ragged_dot`` (whose dW transpose is the E-scaled masked matmul)
vs the grouped-dW ``custom_vjp`` (``ops/moe_kernel.py``) vs the dense
two-matmul floor at matched active FLOPs — at E ∈ {4, 8}, top-1 and
top-2. This is the probe behind BASELINE.md's "3.4× backward at E=8"
number and the one that shows where the grouped kernel buys it back.
Off-TPU the grouped backward runs its reference segment-einsum, so CPU
``--attrib`` checks wiring and ratios-of-convenience only.
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _time_fori  # noqa: E402

from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.train import TrainState, make_train_step_body


def record(model, label, x, y, on_tpu=True):
    opt = make_optimizer("adamw", 3e-4)
    body_full = make_train_step_body(model, opt)

    def body(ts, xx, yy):
        new_ts, m = body_full(ts, xx, yy)
        return new_ts, m["loss"]

    ts0 = TrainState.create(model, opt, seed_key(0))
    sec, runs = _time_fori(
        body, ts0, (x, y), *((8, 24) if on_tpu else (1, 3)),
        reps=3 if on_tpu else 1,
    )
    tok = x.shape[0] * x.shape[1]
    print(
        f"{label:34s} {sec*1e3:8.2f} ms/step  {tok/sec:12,.0f} tok/s  "
        f"runs {[round(r*1e3, 2) for r in sorted(runs)]}",
        flush=True,
    )
    return sec


def main():
    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        base = dict(
            vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6,
            max_len=1024, impl="flash", rope=True,
            compute_dtype=jnp.bfloat16,
        )
        t, b = 1024, 8
    else:
        base = dict(
            vocab_size=256, embed_dim=64, num_heads=4, num_layers=2,
            max_len=128, impl="full",
        )
        t, b = 128, 4
    seqs = jnp.asarray(synthetic_lm(b, t, base["vocab_size"], seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]

    sec_dense = record(TransformerLM(**base), "dense FFN (baseline)", x, y, on_tpu)
    for e in (4, 8):
        for cap in (1.25, 2.0):
            # Keep-rate/utilization depend only on (E, cap), not on the
            # dispatch implementation — print them once per config.
            keep, util = capacity_probe(
                base["embed_dim"], e, cap, x.shape[0] * x.shape[1]
            )
            print(
                f"MoE E={e} top-1 cap={cap}: token keep-rate {keep:.1%}, "
                f"slot utilization {util:.1%} (router at init)",
                flush=True,
            )
            for dispatch in ("einsum", "gather"):
                sec = record(
                    TransformerLM(
                        **base, moe_experts=e, moe_top_k=1,
                        moe_capacity_factor=cap, moe_dispatch=dispatch,
                    ),
                    f"MoE E={e} top-1 cap={cap} [{dispatch}]", x, y, on_tpu,
                )
                print(
                    f"    -> dispatch overhead {1e3*(sec - sec_dense):+.2f} ms/step "
                    f"({sec/sec_dense:.2f}x dense)",
                    flush=True,
                )


def _attrib_row(label, fwd_sec, tot_sec):
    print(
        f"{label:40s} fwd {fwd_sec*1e3:8.3f} ms   "
        f"bwd {(tot_sec - fwd_sec)*1e3:8.3f} ms   "
        f"fwd+bwd {tot_sec*1e3:8.3f} ms",
        flush=True,
    )


def attrib():
    """Per-E fwd/bwd kernel attribution of the ragged FFN composition.

    Rows are presorted by expert (the layout ``dispatch='ragged'``
    guarantees); group sizes come from an untrained router on random
    tokens — the realistic early-training imbalance. Three paths:

    - ``dense``: plain two-matmul MLP on the same P rows — the
      E-independent floor at matched active FLOPs;
    - ``stock``: ``lax.ragged_dot`` composition differentiated as-is
      (its dW transpose is the E-scaled masked matmul — J109);
    - ``grouped``: ``ops.moe_kernel.ragged_ffn`` (grouped-dW backward).

    Timing: the bench fori clock. The fwd carry chains the output back
    into the input (shape-preserving, renormalized) and the bwd carry
    applies a tiny SGD update, so no iteration is loop-invariant and
    XLA cannot hoist the work out of the differenced loop.
    """
    from jax import lax

    from tpudml.ops.moe_kernel import ragged_ffn

    on_tpu = jax.devices()[0].platform != "cpu"
    d, h, g = (512, 2048, 16384) if on_tpu else (64, 128, 512)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    k_lo, k_hi, reps = (8, 24, 3) if on_tpu else (1, 3, 1)
    print(
        f"kernel attribution: d={d} ffn={h} tokens={g} dtype={jnp.dtype(dtype).name} "
        f"grouped_dw={'pallas' if on_tpu else 'reference_einsum'}",
        flush=True,
    )

    def time_fwd(f, x0):
        def body(x_carry, xx, yy):
            y = f(x_carry)
            # Renormalize so 24 chained applications stay bounded; the
            # dependency defeats loop-invariant code motion.
            y = y / (1e-3 + jnp.max(jnp.abs(y.astype(jnp.float32))))
            return y.astype(x_carry.dtype), jnp.sum(y).astype(jnp.float32)

        sec, _ = _time_fori(body, x0, (x0, x0), k_lo, k_hi, reps=reps)
        return sec

    def time_tot(f, weights, x0):
        def body(w_carry, xx, yy):
            def loss_fn(w):
                out = f(w, xx)
                return 0.5 * jnp.sum(out.astype(jnp.float32) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(w_carry)
            new_w = jax.tree.map(lambda p, gr: p - 1e-6 * gr, w_carry, grads)
            return new_w, loss

        sec, _ = _time_fori(body, weights, (x0, x0), k_lo, k_hi, reps=reps)
        return sec

    for e in (4, 8):
        for top_k in (1, 2):
            p = g * top_k
            kx, kr, k1, kb1, k2, kb2 = jax.random.split(seed_key(11), 6)
            xt = jax.random.normal(kx, (g, d), jnp.float32)
            router = jax.random.normal(kr, (d, e), jnp.float32) * d**-0.5
            _, topi = jax.lax.top_k(jax.nn.softmax(xt @ router), top_k)
            eids = topi.reshape(p)
            order = jnp.argsort(eids)
            group_sizes = jnp.bincount(eids, length=e).astype(jnp.int32)
            x_sorted = jnp.take(xt, order // top_k, axis=0).astype(dtype)
            onehot = jax.nn.one_hot(eids[order], e, dtype=dtype)
            w1 = (jax.random.normal(k1, (e, d, h)) * 0.02).astype(dtype)
            b1 = (jax.random.normal(kb1, (e, h)) * 0.02).astype(dtype)
            w2 = (jax.random.normal(k2, (e, h, d)) * 0.02).astype(dtype)
            b2 = (jax.random.normal(kb2, (e, d)) * 0.02).astype(dtype)
            sizes = [int(s) for s in group_sizes]
            print(f"E={e} top-{top_k} P={p} group_sizes={sizes}", flush=True)

            def dense(w, x):
                hid = jax.nn.relu(x @ w[0] + w[1])
                return hid @ w[2] + w[3]

            def stock(w, x):
                hid = jax.nn.relu(
                    lax.ragged_dot(x, w[0], group_sizes) + onehot @ w[1])
                return lax.ragged_dot(hid, w[2], group_sizes) + onehot @ w[3]

            def grouped(w, x):
                return ragged_ffn(x, w[0], w[1], w[2], w[3], onehot,
                                  group_sizes)

            wd = (w1[0], b1[0], w2[0], b2[0])
            we = (w1, b1, w2, b2)
            for label, f, w in (
                (f"  dense floor [{p}x{d}]x[{d}x{h}]", dense, wd),
                (f"  ragged stock dW E={e}", stock, we),
                (f"  ragged grouped dW E={e}", grouped, we),
            ):
                _attrib_row(
                    label,
                    time_fwd(lambda x, f=f, w=w: f(w, x), x_sorted),
                    time_tot(f, w, x_sorted),
                )


def capacity_probe(d, experts, cap_factor, n_tokens):
    """(token keep-rate, expert-slot utilization) of a top-1 layer with an
    UNTRAINED router on random tokens — the early-training capacity
    picture (a trained router with the aux pressure approaches uniform,
    which only raises both numbers toward min(1, cap_factor))."""
    from tpudml.nn.moe import MoELayer

    layer = MoELayer(d, experts, capacity_factor=cap_factor, top_k=1)
    params, state = layer.init(jax.random.PRNGKey(7))
    xt = jax.random.normal(jax.random.PRNGKey(8), (n_tokens, d), jnp.float32)
    y, _ = layer.apply(params, state, xt)
    kept = jnp.mean((jnp.sum(jnp.abs(y), axis=-1) > 0).astype(jnp.float32))
    cap = layer._capacity(n_tokens)
    util = float(kept) * n_tokens / (experts * cap)
    return float(kept), util


if __name__ == "__main__":
    attrib() if "--attrib" in sys.argv[1:] else main()
