"""Single-chip MoE throughput recording (VERDICT r4 item 4).

Dense-vs-MoE tokens/sec at MATCHED ACTIVE FLOPs: a top-1 Switch FFN with
per-expert width equal to the dense FFN routes every token through
exactly one expert, so the per-token matmul math is identical to the
dense model's — any throughput gap is dispatch overhead (router,
capacity buffers, gather/scatter, the per-expert loop/einsum).

Prints, for the flagship trunk config (d=512, L=6, T=1024, B=8, bf16):
- dense baseline tokens/sec (standard step, flash attention);
- MoE tokens/sec at E ∈ {4, 8} experts (top-1, capacity 1.25/2.0);
- capacity utilization (fraction of expert slots filled, from the
  router's aux state) and the implied dispatch overhead ms/step.

Protocol: the bench fori clock (K steps per dispatch, differenced).
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _time_fori  # noqa: E402

from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.train import TrainState, make_train_step_body


def record(model, label, x, y, on_tpu=True):
    opt = make_optimizer("adamw", 3e-4)
    body_full = make_train_step_body(model, opt)

    def body(ts, xx, yy):
        new_ts, m = body_full(ts, xx, yy)
        return new_ts, m["loss"]

    ts0 = TrainState.create(model, opt, seed_key(0))
    sec, runs = _time_fori(
        body, ts0, (x, y), *((8, 24) if on_tpu else (1, 3)),
        reps=3 if on_tpu else 1,
    )
    tok = x.shape[0] * x.shape[1]
    print(
        f"{label:34s} {sec*1e3:8.2f} ms/step  {tok/sec:12,.0f} tok/s  "
        f"runs {[round(r*1e3, 2) for r in sorted(runs)]}",
        flush=True,
    )
    return sec


def main():
    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        base = dict(
            vocab_size=32768, embed_dim=512, num_heads=4, num_layers=6,
            max_len=1024, impl="flash", rope=True,
            compute_dtype=jnp.bfloat16,
        )
        t, b = 1024, 8
    else:
        base = dict(
            vocab_size=256, embed_dim=64, num_heads=4, num_layers=2,
            max_len=128, impl="full",
        )
        t, b = 128, 4
    seqs = jnp.asarray(synthetic_lm(b, t, base["vocab_size"], seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]

    sec_dense = record(TransformerLM(**base), "dense FFN (baseline)", x, y, on_tpu)
    for e in (4, 8):
        for cap in (1.25, 2.0):
            # Keep-rate/utilization depend only on (E, cap), not on the
            # dispatch implementation — print them once per config.
            keep, util = capacity_probe(
                base["embed_dim"], e, cap, x.shape[0] * x.shape[1]
            )
            print(
                f"MoE E={e} top-1 cap={cap}: token keep-rate {keep:.1%}, "
                f"slot utilization {util:.1%} (router at init)",
                flush=True,
            )
            for dispatch in ("einsum", "gather"):
                sec = record(
                    TransformerLM(
                        **base, moe_experts=e, moe_top_k=1,
                        moe_capacity_factor=cap, moe_dispatch=dispatch,
                    ),
                    f"MoE E={e} top-1 cap={cap} [{dispatch}]", x, y, on_tpu,
                )
                print(
                    f"    -> dispatch overhead {1e3*(sec - sec_dense):+.2f} ms/step "
                    f"({sec/sec_dense:.2f}x dense)",
                    flush=True,
                )


def capacity_probe(d, experts, cap_factor, n_tokens):
    """(token keep-rate, expert-slot utilization) of a top-1 layer with an
    UNTRAINED router on random tokens — the early-training capacity
    picture (a trained router with the aux pressure approaches uniform,
    which only raises both numbers toward min(1, cap_factor))."""
    from tpudml.nn.moe import MoELayer

    layer = MoELayer(d, experts, capacity_factor=cap_factor, top_k=1)
    params, state = layer.init(jax.random.PRNGKey(7))
    xt = jax.random.normal(jax.random.PRNGKey(8), (n_tokens, d), jnp.float32)
    y, _ = layer.apply(params, state, xt)
    kept = jnp.mean((jnp.sum(jnp.abs(y), axis=-1) > 0).astype(jnp.float32))
    cap = layer._capacity(n_tokens)
    util = float(kept) * n_tokens / (experts * cap)
    return float(kept), util


if __name__ == "__main__":
    main()
