"""LR schedules, Scheduled optimizer wrapper, device prefetch, and
ring-attention remat tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data import prefetch_to_device
from tpudml.models import LeNet
from tpudml.optim import (
    Scheduled,
    Sgd,
    constant,
    cosine_decay,
    linear_warmup,
    step_decay,
    warmup_cosine,
)


def test_schedule_shapes():
    s = cosine_decay(1.0, 100)
    np.testing.assert_allclose(float(s(0)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(s(50)), 0.5, rtol=1e-6)
    np.testing.assert_allclose(float(s(100)), 0.0, atol=1e-7)
    np.testing.assert_allclose(float(s(1000)), 0.0, atol=1e-7)  # clamped

    w = linear_warmup(2.0, 4)
    np.testing.assert_allclose([float(w(i)) for i in range(5)],
                               [0.5, 1.0, 1.5, 2.0, 2.0], rtol=1e-6)

    wc = warmup_cosine(1.0, 10, 110)
    assert float(wc(0)) < float(wc(9))
    np.testing.assert_allclose(float(wc(10)), 1.0, rtol=1e-5)
    np.testing.assert_allclose(float(wc(110)), 0.0, atol=1e-6)

    sd = step_decay(1.0, 10, gamma=0.1)
    np.testing.assert_allclose(float(sd(9)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(sd(10)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sd(25)), 0.01, rtol=1e-5)


def test_scheduled_matches_manual_lr_sequence():
    """Scheduled(SGD, schedule) == running plain SGD with the per-step lr."""
    sched = step_decay(0.1, 2, gamma=0.5)
    opt = Scheduled(Sgd(momentum=0.9), sched)
    params = {"w": jnp.arange(4.0)}
    grads = {"w": jnp.ones(4)}
    state = opt.init(params)

    ref = {"w": jnp.arange(4.0)}
    buf = {"w": jnp.zeros(4)}
    for t in range(5):
        params, state = opt.update(grads, state, params)
        lr = float(sched(t))
        buf = {"w": 0.9 * buf["w"] + grads["w"]}
        ref = {"w": ref["w"] - lr * buf["w"]}
    np.testing.assert_allclose(np.asarray(params["w"]), np.asarray(ref["w"]), rtol=1e-6)
    assert int(state["t"]) == 5


def test_scheduled_trains_jitted():
    from tpudml.data.datasets import synthetic_classification
    from tpudml.train import TrainState, make_train_step

    model = LeNet()
    opt = Scheduled(Sgd(momentum=0.9), warmup_cosine(0.05, 5, 30))
    images, labels = synthetic_classification(32, (28, 28, 1), 10, seed=0)
    step = make_train_step(model, opt)
    ts = TrainState.create(model, opt, seed_key(0))
    first = None
    for _ in range(10):
        ts, m = step(ts, images, labels)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first


def test_prefetch_yields_all_on_device():
    batches = [(np.full((2, 2), i, np.float32), np.array([i])) for i in range(5)]
    out = list(prefetch_to_device(iter(batches), size=2))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        assert isinstance(x, jax.Array)
        np.testing.assert_array_equal(np.asarray(x), batches[i][0])
    with pytest.raises(ValueError, match=">= 1"):
        next(prefetch_to_device(iter(batches), size=0))


def test_prefetch_with_sharding():
    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P("data"))
    batches = [np.ones((8, 3), np.float32)]
    (x,) = list(prefetch_to_device(iter(batches), sharding=sharding))
    assert x.sharding == sharding


def test_scheduled_rejects_lr_less_base():
    class NoLr(Sgd.__mro__[1]):  # plain Optimizer subclass, not a dataclass
        def update(self, grads, state, params):
            return params, state

    with pytest.raises(ValueError, match="'lr' field"):
        Scheduled(NoLr(), constant(0.1))
    # Zero-length schedules must not produce NaN lrs.
    assert np.isfinite(float(cosine_decay(0.1, 0)(5)))
    assert np.isfinite(float(step_decay(0.1, 0)(5)))


def test_remat_reachable_from_model():
    """TransformerLM(remat=True) must plumb down to ring attention and
    still match the non-remat model exactly."""
    from tpudml.core.config import MeshConfig as MC
    from tpudml.models import TransformerLM
    from tpudml.parallel.cp import ContextParallel
    from tpudml.optim import make_optimizer

    mesh = make_mesh(MC({"seq": 4}), jax.devices()[:4])
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, size=(2, 16)).astype(np.int32)
    )
    base = dict(vocab_size=32, embed_dim=16, num_heads=4, num_layers=1,
                max_len=16, impl="ring", seq_sharded=True)
    params, _ = TransformerLM(**base).init(seed_key(0))
    opt = make_optimizer("sgd", 0.1)
    plain = ContextParallel(TransformerLM(**base), opt, mesh).make_forward()
    remat = ContextParallel(TransformerLM(**base, remat=True), opt, mesh).make_forward()
    np.testing.assert_allclose(
        np.asarray(remat(params, tokens)), np.asarray(plain(params, tokens)),
        rtol=1e-5,
    )


@pytest.mark.slow  # ~22s compile; ring-backward parity also pinned in test_cp
def test_ring_attention_remat_flag_compat():
    """``remat=`` is accepted for API compatibility only: the ring
    custom-VJP backward always recomputes per block (flash-style), so the
    flag is implied. This pins that passing it still works, matches full
    attention, and differentiates (gradient parity of the backward itself
    lives in tests/test_cp.py::test_ring_grads_match_full)."""
    from tpudml.nn.attention import dot_product_attention
    from tpudml.parallel.cp import ring_attention
    from tpudml.parallel.sharding import shard_map_fn

    # seq 2 keeps the compile small — the flag-compat contract is the
    # point here; ring math/grad parity lives in tests/test_cp.py.
    mesh = make_mesh(MeshConfig({"seq": 2}), jax.devices()[:2])
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 16, 4, 8)).astype(np.float32))
        for _ in range(3)
    )
    spec = P(None, "seq")

    def loss(q, k, v):
        fn = shard_map_fn(
            lambda q, k, v: ring_attention(q, k, v, "seq", causal=True, remat=True),
            mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )
        return jnp.sum(fn(q, k, v) ** 2)

    want = jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)
    np.testing.assert_allclose(float(loss(q, k, v)), float(want), rtol=1e-5)
    assert np.isfinite(np.asarray(jax.grad(lambda q: loss(q, k, v))(q))).all()
