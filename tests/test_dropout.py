"""Transformer dropout tests: off by default, stochastic only in train
mode with an rng, per-layer streams, and trainable end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.prng import seed_key
from tpudml.models import TransformerLM

BASE = dict(vocab_size=32, embed_dim=32, num_heads=4, num_layers=2, max_len=8)


@pytest.fixture(scope="module")
def tokens():
    return jnp.asarray(
        np.random.default_rng(0).integers(0, 32, size=(2, 8)).astype(np.int32)
    )


def test_zero_dropout_is_identity_config(tokens):
    params, _ = TransformerLM(**BASE).init(seed_key(0))
    a = TransformerLM(**BASE)(params, tokens)
    b, _ = TransformerLM(**BASE, dropout=0.5).apply(params, {}, tokens, train=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_mode_is_stochastic_and_eval_deterministic(tokens):
    lm = TransformerLM(**BASE, dropout=0.5)
    params, _ = lm.init(seed_key(1))
    y1, _ = lm.apply(params, {}, tokens, train=True, rng=jax.random.key(0))
    y2, _ = lm.apply(params, {}, tokens, train=True, rng=jax.random.key(1))
    assert not np.allclose(np.asarray(y1), np.asarray(y2))
    # Same rng → same mask (reproducible).
    y3, _ = lm.apply(params, {}, tokens, train=True, rng=jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y3))
    # Eval ignores dropout entirely.
    e1, _ = lm.apply(params, {}, tokens, train=False)
    e2, _ = lm.apply(params, {}, tokens, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))


def test_train_without_rng_raises(tokens):
    lm = TransformerLM(**BASE, dropout=0.5)
    params, _ = lm.init(seed_key(2))
    with pytest.raises(ValueError, match="requires an rng"):
        lm.apply(params, {}, tokens, train=True)


def test_dropout_under_context_parallelism(tokens):
    """CP engine threads per-step/per-shard dropout streams when given an
    rng_root (a replicated key would reuse one mask on every shard)."""
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.optim import make_optimizer
    from tpudml.parallel.cp import ContextParallel

    mesh = make_mesh(MeshConfig({"seq": 4}), jax.devices()[:4])
    lm = TransformerLM(**BASE, dropout=0.1, impl="ring", seq_sharded=True)
    cp = ContextParallel(lm, make_optimizer("adam", 5e-3), mesh,
                         rng_root=jax.random.key(11))
    ts = cp.create_state(seed_key(4))
    step = cp.make_train_step()
    first = None
    for _ in range(6):
        ts, m = step(ts, tokens, tokens)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first
    # Without an rng_root, dropout>0 under CP fails loudly at trace time.
    cp2 = ContextParallel(lm, make_optimizer("adam", 5e-3), mesh)
    ts2 = cp2.create_state(seed_key(5))
    with pytest.raises(ValueError, match="requires an rng"):
        cp2.make_train_step()(ts2, tokens, tokens)


def test_pipeline_rejects_dropout_blocks():
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.models import TransformerBlock
    from tpudml.optim import make_optimizer
    from tpudml.parallel.pp import GPipe

    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    pipe = GPipe(
        TransformerBlock(32, 4, dropout=0.1), 2, mesh, make_optimizer("sgd", 0.1)
    )
    with pytest.raises(ValueError, match="do not support dropout"):
        pipe.init_params(seed_key(0))


def test_dropout_lm_trains_end_to_end(tokens):
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState, make_train_step

    lm = TransformerLM(**BASE, dropout=0.1)
    opt = make_optimizer("adam", 5e-3)
    step = make_train_step(lm, opt, rng_root=jax.random.key(7))
    ts = TrainState.create(lm, opt, seed_key(3))
    first = None
    for _ in range(10):
        ts, m = step(ts, tokens, tokens)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first
