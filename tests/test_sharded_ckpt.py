"""Sharded (per-host) checkpoint tests.

Load-bearing properties: each owner writes exactly its shards once
(replicated copies deduplicated by replica_id), reassembly reproduces the
full state bitwise for TP-sharded, EP-sharded, and replicated trees, and
incomplete/incompatible checkpoints are rejected rather than silently
zero-filled.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.checkpoint import (
    restore_sharded_checkpoint,
    save_sharded_checkpoint,
)
from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.parallel.mp import GSPMDParallel, tensor_parallel_rules
from tpudml.train import TrainState


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_tp_sharded_roundtrip(tmp_path):
    model = TransformerLM(vocab_size=32, embed_dim=32, num_heads=4,
                          num_layers=1, max_len=8)
    opt = make_optimizer("adam", 1e-3)
    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    tp = GSPMDParallel(model, opt, mesh, rule=tensor_parallel_rules("model"),
                       axis_name="model")
    ts = tp.create_state(seed_key(0))
    path = save_sharded_checkpoint(tmp_path, ts, step=3)
    assert os.path.basename(path) == "step_3"

    fresh = TrainState.create(model, opt, seed_key(9))
    restored = restore_sharded_checkpoint(path, fresh)
    _assert_trees_equal(jax.device_get(ts), restored)


def test_replicated_state_written_once(tmp_path):
    """Fully-replicated arrays appear exactly once in the shard files."""
    mesh = make_mesh(MeshConfig({"data": 8}))
    from tpudml.parallel.sharding import replicate

    tree = {"w": jnp.arange(16.0).reshape(4, 4), "n": jnp.int32(7)}
    placed = replicate(tree, mesh)
    path = save_sharded_checkpoint(tmp_path, placed, step=0)
    with np.load(os.path.join(path, "shards_p0.npz")) as data:
        assert len(data.files) == 2  # one entry per leaf, not per device
    restored = restore_sharded_checkpoint(path, jax.tree.map(jnp.zeros_like, tree))
    _assert_trees_equal(jax.device_get(placed), restored)


def test_ep_expert_shards_roundtrip(tmp_path):
    from tpudml.nn import Activation, Dense, Flatten, MoELayer, Sequential
    from tpudml.parallel.ep import ExpertParallel

    mesh = make_mesh(MeshConfig({"expert": 4}), jax.devices()[:4])
    model = Sequential((
        Flatten(), Dense(16, 8), Activation(jax.nn.relu),
        MoELayer(8, 8, mlp_ratio=2, axis_name="expert"), Dense(8, 4),
    ))
    ep = ExpertParallel(model, make_optimizer("sgd", 0.1), mesh)
    ts = ep.create_state(seed_key(2))
    path = save_sharded_checkpoint(tmp_path, ts, step=1)
    restored = restore_sharded_checkpoint(
        path, TrainState.create(model, make_optimizer("sgd", 0.1), seed_key(5))
    )
    _assert_trees_equal(jax.device_get(ts), restored)


def test_incomplete_checkpoint_rejected(tmp_path):
    tree = {"w": jnp.ones((4,))}
    path = save_sharded_checkpoint(tmp_path, tree, step=0)
    # Claim a second process exists whose file never arrived.
    mpath = os.path.join(path, "manifest_p0.json")
    m = json.load(open(mpath))
    m["num_processes"] = 2
    json.dump(m, open(mpath, "w"))
    with pytest.raises(ValueError, match="incomplete checkpoint"):
        restore_sharded_checkpoint(path, tree)


def test_structure_mismatch_rejected(tmp_path):
    path = save_sharded_checkpoint(tmp_path, {"a": jnp.ones(3)}, step=0)
    with pytest.raises(ValueError, match="structure mismatch"):
        restore_sharded_checkpoint(path, {"a": jnp.ones(3), "b": jnp.ones(2)})


def test_fsdp_sharded_roundtrip(tmp_path):
    """ZeRO-3 state (params AND Adam moments sharded over data) must
    round-trip through the per-host sharded checkpoint, restore into a
    fresh replica-layout state, and resume training identically."""
    from tpudml.models import ForwardMLP
    from tpudml.parallel.fsdp import FSDP

    model = ForwardMLP()
    opt = make_optimizer("adam", 1e-3)
    mesh = make_mesh(MeshConfig({"data": 8}))
    eng = FSDP(model, opt, mesh)
    ts = eng.create_state(seed_key(0))

    # One real step so opt-state moments are non-trivial.
    from tpudml.data.datasets import synthetic_classification

    x, y = synthetic_classification(16, (28, 28, 1), 10, seed=0)
    step = eng.make_train_step()
    ts, _ = step(ts, jnp.asarray(x), jnp.asarray(y))

    path = save_sharded_checkpoint(tmp_path, ts, step=1)
    host_ts = jax.device_get(ts)
    fresh = TrainState.create(model, opt, seed_key(5))
    restored = restore_sharded_checkpoint(path, fresh)
    _assert_trees_equal(host_ts, restored)

    # Resuming from the restored state continues IDENTICALLY to the
    # original (same next-step loss and params — layout semantics intact).
    ts2, m = step(ts, jnp.asarray(x), jnp.asarray(y))
    placed = jax.device_put(restored, eng._shardings(eng._specs))
    ts3, m2 = step(placed, jnp.asarray(x), jnp.asarray(y))
    assert int(ts3.step) == 2
    np.testing.assert_allclose(float(m2["loss"]), float(m["loss"]), rtol=1e-6)
    _assert_trees_equal(jax.device_get(ts2.params), jax.device_get(ts3.params))


def test_cross_world_restore_matrix(tmp_path):
    """The elastic-recovery contract, pinned exhaustively: a checkpoint
    written under ANY data-mesh world in {1, 2, 4} restores under ANY
    other, and the reassembled full state is CRC-identical in all nine
    combinations (zero-filled restores or shard mixups would change the
    CRC). This is the property that lets the shrink-re-plan drill treat
    a chain/world switch as a restore, not a retrain."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpudml.checkpoint.sharded import (
        restore_latest_valid_sharded,
        save_sharded_checkpoint,
    )
    from tpudml.elastic.drill import _params_crc

    rng = np.random.default_rng(0)
    host = {
        "w": rng.standard_normal((8, 5)).astype(np.float32),
        "b": rng.standard_normal((8,)).astype(np.float32),
        "step": np.int64(7),
    }
    ref_crc = _params_crc(host)
    worlds = (1, 2, 4)
    for w_save in worlds:
        mesh = make_mesh(MeshConfig({"data": w_save}), jax.devices()[:w_save])
        sharded = NamedSharding(mesh, P("data"))
        placed = {
            "w": jax.device_put(host["w"], sharded),
            "b": jax.device_put(host["b"], sharded),
            "step": host["step"],
        }
        ckpt_dir = tmp_path / f"save_w{w_save}"
        save_sharded_checkpoint(ckpt_dir, placed, step=7)
        for w_restore in worlds:
            target = jax.tree.map(np.zeros_like, host)
            restored = restore_latest_valid_sharded(str(ckpt_dir), target)
            assert int(restored["step"]) == 7, (w_save, w_restore)
            assert _params_crc(restored) == ref_crc, (w_save, w_restore)
            # Re-placing onto the restore world's mesh keeps bit parity.
            mesh_r = make_mesh(
                MeshConfig({"data": w_restore}), jax.devices()[:w_restore]
            )
            placed_r = jax.tree.map(
                lambda a: jax.device_put(
                    a, NamedSharding(mesh_r, P("data"))
                ),
                {"w": restored["w"], "b": restored["b"]},
            )
            assert _params_crc(placed_r) == _params_crc(
                {"w": host["w"], "b": host["b"]}
            ), (w_save, w_restore)
