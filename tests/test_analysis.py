"""tpudml.analysis: every rule fires on its seeded fixture and stays
silent on the clean twin, the jaxpr pass traces the real engine
entrypoints, and ``--strict`` with the committed allowlist is green.

The jaxpr fixtures are built inline (tiny jitted functions with one
deliberate hazard each); the AST fixtures live in analysis_fixtures/.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.analysis import (
    analyze_callable,
    analyze_entrypoint,
    analyze_file,
    donation_findings,
    load_allowlist,
    split_allowed,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------- AST pass


def test_ast_rules_fire_on_seeded_fixtures():
    findings = analyze_file(os.path.join(FIXTURES, "seeded_violations.py"))
    assert {"A201", "A202", "A203", "A204"} <= _rules(findings)
    # A201 fires on both the if and the for
    assert sum(1 for f in findings if f.rule == "A201") == 2
    # every finding points at a real line with a hint
    for f in findings:
        assert f.line > 0 and f.hint


def test_ast_rules_silent_on_clean_fixtures():
    assert analyze_file(os.path.join(FIXTURES, "clean.py")) == []


# ------------------------------------------------------------ jaxpr pass


def test_j101_unbound_axis_fires_and_bound_is_silent():
    bad = analyze_callable(
        lambda x: jax.lax.psum(x, "ghost"), (jnp.ones((4,)),), "fix-j101")
    assert _rules(bad) == {"J101"}

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])
    good_fn = jax.jit(shard_map_fn(
        lambda x: jax.lax.psum(x, "data"), mesh,
        in_specs=(P("data"),), out_specs=P()))
    good = analyze_callable(good_fn, (jnp.ones((4,)),), "ok-j101")
    assert "J101" not in _rules(good)


def test_j102_divergent_branch_collectives():
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def diverging(x):
        return jax.lax.cond(
            x[0] > 0,
            lambda v: jax.lax.psum(v, "data"),  # collective in ONE arm only
            lambda v: v * 2.0,
            x,
        )

    def balanced(x):
        return jax.lax.cond(
            x[0] > 0,
            lambda v: jax.lax.psum(v, "data"),
            lambda v: jax.lax.psum(v * 2.0, "data"),
            x,
        )

    def wrap(fn):
        return jax.jit(shard_map_fn(
            fn, mesh, in_specs=(P("data"),), out_specs=P(None)))

    bad = analyze_callable(wrap(diverging), (jnp.ones((4,)),), "fix-j102")
    assert "J102" in _rules(bad)
    good = analyze_callable(wrap(balanced), (jnp.ones((4,)),), "ok-j102")
    assert "J102" not in _rules(good)


def test_j103_host_callback():
    def chatty(x):
        jax.debug.print("loss={l}", l=x.sum())
        return x * 2.0

    bad = analyze_callable(jax.jit(chatty), (jnp.ones((4,)),), "fix-j103")
    assert "J103" in _rules(bad)
    good = analyze_callable(
        jax.jit(lambda x: x * 2.0), (jnp.ones((4,)),), "ok-j103")
    assert "J103" not in _rules(good)


def test_j104_upcast_outside_accumulation():
    x16 = jnp.ones((8,), jnp.bfloat16)
    bad = analyze_callable(
        lambda x: x.astype(jnp.float32) * 2.0, (x16,), "fix-j104")
    assert "J104" in _rules(bad)
    # upcast feeding a reduction is the intended accumulate-in-f32 idiom
    good = analyze_callable(
        lambda x: jnp.sum(x.astype(jnp.float32)), (x16,), "ok-j104")
    assert "J104" not in _rules(good)


def test_j105_large_closure_constant():
    big = np.ones((600, 600), np.float32)  # 1.44 MiB
    bad = analyze_callable(
        lambda x: x + jnp.asarray(big)[0, 0], (jnp.ones((2,)),), "fix-j105")
    assert "J105" in _rules(bad)
    small = np.ones((8, 8), np.float32)
    good = analyze_callable(
        lambda x: x + jnp.asarray(small)[0, 0], (jnp.ones((2,)),), "ok-j105")
    assert "J105" not in _rules(good)


def test_j106_undonated_buffers():
    state = jnp.ones((1024, 512), jnp.float32)  # 2 MiB
    x = jnp.ones((4,), jnp.float32)

    def step(s, v):
        return s + v.sum(), v * 2.0

    bad = analyze_callable(
        jax.jit(step), (state, x), "fix-j106", expects_donation=True)
    assert "J106" in _rules(bad)
    good = analyze_callable(
        jax.jit(step, donate_argnums=(0,)), (state, x), "ok-j106",
        expects_donation=True)
    assert "J106" not in _rules(good)


def test_j107_vocab_sharded_unsharded_head():
    """J107 fires when the UNSHARDED fused head consumes a vocab-sharded
    kernel inside shard_map — including through the 2-D W all_gather —
    and stays silent for the shard-merge wrapper and a replicated W."""
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.ops.xent_kernel import (
        linear_cross_entropy,
        sharded_linear_cross_entropy,
    )
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])
    x = jnp.zeros((8, 4))
    w = jnp.zeros((4, 32))
    lab = jnp.zeros((8,), jnp.int32)

    def wrap(body, w_spec):
        return shard_map_fn(
            body, mesh, in_specs=(P(), w_spec, P()), out_specs=P())

    hazard = wrap(
        lambda x, w, ln: linear_cross_entropy(x, w, ln), P(None, "data"))
    assert "J107" in _rules(analyze_callable(hazard, (x, w, lab), "fix-j107"))

    fixed = wrap(
        lambda x, w, ln: sharded_linear_cross_entropy(
            x, w, ln, axis_name="data"),
        P(None, "data"))
    assert "J107" not in _rules(analyze_callable(fixed, (x, w, lab), "ok"))

    replicated = wrap(
        lambda x, w, ln: linear_cross_entropy(x, w, ln), P())
    assert "J107" not in _rules(
        analyze_callable(replicated, (x, w, lab), "ok-replicated"))

    # 2-D form: W sharded P(data, model); the dim-0 gather over "data"
    # must not launder the vocab-dim sharding over "model".
    mesh2 = make_mesh(MeshConfig({"data": 2, "model": 2}), jax.devices()[:4])

    def hazard2d(x, w, ln):
        def body(x, w, ln):
            k = jax.lax.all_gather(w, "data", axis=0, tiled=True)
            return linear_cross_entropy(x, k, ln)
        return shard_map_fn(
            body, mesh2, in_specs=(P(), P("data", "model"), P()),
            out_specs=P())(x, w, ln)

    bad2d = analyze_callable(hazard2d, (x, w, lab), "fix-j107-2d")
    assert "J107" in _rules(bad2d)
    (f,) = [f for f in bad2d if f.rule == "J107"]
    assert "model" in f.message and "sharded_linear_cross_entropy" in f.message


def test_j107_marker_names_match_kernel_module():
    """The pass keys on string literals so it never imports kernel code;
    this is the drift pin."""
    from tpudml.analysis import jaxpr_pass
    from tpudml.ops import xent_kernel

    assert jaxpr_pass.FUSED_XENT_NAME == xent_kernel.FUSED_XENT_MARKER
    assert jaxpr_pass.SHARDED_XENT_NAME == xent_kernel.SHARDED_XENT_MARKER


def test_j108_replicated_update_under_data_axis():
    """J108 fires on the replicated-DP shape (≥2 gradient psums over a
    data axis, matching outputs returned replicated, no reduce-scatter)
    and stays silent for the ZeRO-1 shape (psum_scatter present) and for
    the FSDP shape (outputs sharded over the axis)."""
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])
    p1, p2 = jnp.ones((8, 4)), jnp.ones((16,))
    x = jnp.ones((4, 4))

    def replicated_update(p1, p2, x):
        s = x.sum()
        g1 = jax.lax.pmean(p1 * s, "data")
        g2 = jax.lax.pmean(p2 * s, "data")
        return p1 - 0.1 * g1, p2 - 0.1 * g2

    bad = shard_map_fn(
        replicated_update, mesh,
        in_specs=(P(), P(), P("data")), out_specs=(P(), P()))
    found = analyze_callable(bad, (p1, p2, x), "fix-j108")
    assert "J108" in _rules(found)
    (f,) = [f for f in found if f.rule == "J108"]
    assert "reduce-scatter" in f.message

    def zero1_update(p1, p2, x):
        s = x.sum()
        c1 = jax.lax.psum_scatter(
            (p1 * s).reshape(-1), "data", scatter_dimension=0, tiled=True)
        c2 = jax.lax.psum_scatter(
            p2 * s, "data", scatter_dimension=0, tiled=True)
        n1 = jax.lax.all_gather(c1 / 2, "data", axis=0, tiled=True)
        n2 = jax.lax.all_gather(c2 / 2, "data", axis=0, tiled=True)
        return p1 - 0.1 * n1.reshape(p1.shape), p2 - 0.1 * n2

    ok_z = shard_map_fn(
        zero1_update, mesh,
        in_specs=(P(), P(), P("data")), out_specs=(P(), P()))
    assert "J108" not in _rules(analyze_callable(ok_z, (p1, p2, x), "ok-z1"))

    def sharded_out_update(p1, p2, x):
        s = x.sum()
        g1 = jax.lax.pmean(p1 * s, "data")
        g2 = jax.lax.pmean(p2 * s, "data")
        return p1 - 0.1 * g1, p2 - 0.1 * g2

    ok_f = shard_map_fn(
        sharded_out_update, mesh,
        in_specs=(P(), P(), P("data")), out_specs=(P("data"), P("data")))
    assert "J108" not in _rules(
        analyze_callable(ok_f, (p1, p2, x), "ok-fsdp"))


@pytest.mark.parametrize("ragged_dw", ["stock", "grouped"])
def test_j109_ragged_transpose_backward(ragged_dw):
    """J109 fires on lax.ragged_dot's stock grouped-transpose dW (both
    dW sites of the two-matmul FFN — the E-scaled masked batched
    dot_general) and stays silent when the grouped-dW custom_vjp
    (ops.moe_kernel.ragged_ffn, the default) owns the backward."""
    from tpudml.core.prng import seed_key
    from tpudml.nn.moe import MoELayer

    moe = MoELayer(16, 4, mlp_ratio=2, dispatch="ragged",
                   ragged_dw=ragged_dw)
    params, _ = moe.init(seed_key(0))
    x = jnp.ones((32, 16))

    def loss(p, x):
        y, st = moe.apply(p, {}, x)
        return jnp.sum(y**2) + st["aux_loss"]

    findings = analyze_callable(
        jax.jit(jax.grad(loss)), (params, x), f"j109-{ragged_dw}")
    fired = [f for f in findings if f.rule == "J109"]
    if ragged_dw == "stock":
        assert len(fired) == 2, findings  # dW1 and dW2
        assert all("4×" in f.message and f.line > 0 for f in fired)
    else:
        assert fired == [], fired


def test_j110_cacheless_decode_fires_and_cached_is_silent():
    """J110 fires on a decode-marked program that recomputes the full
    [T, T] attention per emitted token (make_cacheless_decode_step, the
    serving bench's A/B baseline) and stays silent on the KV-cached step
    whose softmax is [B, H, 1, L]."""
    from tpudml.models import TransformerLM
    from tpudml.serve import (ServeConfig, ServingEngine,
                              make_cacheless_decode_step)

    lm = TransformerLM(vocab_size=32, embed_dim=16, num_heads=2,
                       num_layers=2, max_len=16, rope=True)
    params, _ = lm.init(jax.random.key(0))
    bad = analyze_callable(
        make_cacheless_decode_step(lm), (params, np.zeros((2, 12), np.int32)),
        "j110-cacheless")
    fired = [f for f in bad if f.rule == "J110"]
    assert len(fired) == 1, bad  # one finding per marked program, not per layer
    assert "full-sequence" in fired[0].message and fired[0].hint

    eng = ServingEngine(
        lm, params, ServeConfig(slots=2, max_len=16, prefill_chunk=4))
    good = analyze_callable(
        eng._decode,
        (params, eng.caches, np.zeros(2, np.int32), np.zeros(2, np.int32)),
        "j110-cached")
    assert [f for f in good if f.rule == "J110"] == [], good


def test_j110_marker_name_matches_serve_module():
    """Same drift pin as J107: the analyzer's string literal must equal
    the marker the serving engine jits its decode step under."""
    from tpudml.analysis import jaxpr_pass
    from tpudml.serve import engine

    assert jaxpr_pass.SERVE_DECODE_NAME == engine.SERVE_DECODE_MARKER


def test_j117_marker_names_match_serve_modules():
    """Drift pin for the paged/spec decode markers J117 keys on — and
    they must NOT collide with the dense marker (the spec window softmax
    would false-fire J110's single-token contract)."""
    from tpudml.analysis import jaxpr_pass
    from tpudml.serve import paged, spec

    assert set(jaxpr_pass.PAGED_DECODE_NAMES) == {
        paged.PAGED_DECODE_MARKER, spec.SPEC_DECODE_MARKER}
    assert jaxpr_pass.SERVE_DECODE_NAME not in jaxpr_pass.PAGED_DECODE_NAMES


def test_j117_silent_on_real_paged_and_spec_steps():
    """The shipped paged decode step (table gather) and the paged spec
    step must trace J117-silent — and J110-silent too, their softmax
    widths being none of the rule's business under their own markers."""
    from tpudml.models import TransformerLM
    from tpudml.serve import ServeConfig, ServingEngine

    lm = TransformerLM(vocab_size=32, embed_dim=16, num_heads=2,
                       num_layers=2, max_len=16, rope=True)
    params, _ = lm.init(jax.random.key(0))
    eng = ServingEngine(
        lm, params,
        ServeConfig(slots=2, max_len=16, prefill_chunk=4,
                    cache_layout="paged", page_size=4, num_pages=9,
                    spec_k=2))
    table = np.zeros((2, eng.cfg.max_pages), np.int32)
    toks = np.zeros(2, np.int32)
    pos = np.zeros(2, np.int32)
    plain = analyze_callable(
        eng._decode, (params, eng.caches, table, toks, pos), "j117-paged")
    assert [f for f in plain if f.rule in ("J110", "J117")] == [], plain
    spec = analyze_callable(
        eng._spec,
        (params, eng._dparams, eng.caches, eng._dcaches, table, toks, pos),
        "j117-paged-spec")
    assert [f for f in spec if f.rule in ("J110", "J117")] == [], spec


def test_j119_unfused_tail_fires_and_fused_is_silent():
    """J119 fires once on the stock dense decode step (materialized
    [B, V] logits + separate argmax tail) and stays silent — J110 too —
    when ServeConfig(fused_head=True) routes the tail through the fused
    head marker, whose INTERNAL argmax the scan must skip."""
    from tpudml.models import TransformerLM
    from tpudml.serve import ServeConfig, ServingEngine

    lm = TransformerLM(vocab_size=32, embed_dim=16, num_heads=2,
                       num_layers=2, max_len=16, rope=True)
    params, _ = lm.init(jax.random.key(0))

    def args(eng):
        return (params, eng.caches, np.zeros(2, np.int32),
                np.zeros(2, np.int32))

    plain = ServingEngine(
        lm, params, ServeConfig(slots=2, max_len=16, prefill_chunk=4))
    bad = analyze_callable(plain._decode, args(plain), "j119-unfused")
    fired = [f for f in bad if f.rule == "J119"]
    assert len(fired) == 1, bad  # one finding per marked program
    assert "full-vocab" in fired[0].message and fired[0].line > 0
    assert fired[0].hint

    fused = ServingEngine(
        lm, params,
        ServeConfig(slots=2, max_len=16, prefill_chunk=4, fused_head=True))
    good = analyze_callable(fused._decode, args(fused), "j119-fused")
    assert [f for f in good if f.rule in ("J110", "J119")] == [], good


def test_j119_fires_on_paged_tail_too():
    """The paged decode step's tail is the same unfused argmax — J119
    covers every decode-marked program, not just the dense one."""
    from tpudml.models import TransformerLM
    from tpudml.serve import ServeConfig, ServingEngine

    lm = TransformerLM(vocab_size=32, embed_dim=16, num_heads=2,
                       num_layers=2, max_len=16, rope=True)
    params, _ = lm.init(jax.random.key(0))
    eng = ServingEngine(
        lm, params,
        ServeConfig(slots=2, max_len=16, prefill_chunk=4,
                    cache_layout="paged", page_size=4, num_pages=9))
    table = np.zeros((2, eng.cfg.max_pages), np.int32)
    found = analyze_callable(
        eng._decode,
        (params, eng.caches, table, np.zeros(2, np.int32),
         np.zeros(2, np.int32)),
        "j119-paged")
    assert len([f for f in found if f.rule == "J119"]) == 1, found


def test_j119_overlap_claim_verified_against_marker():
    """The overlap half: a plan whose winner claims ``tp_overlap`` must
    see the TP_OVERLAP_NAME pjit in the traced program — a program
    routed through tp_overlap_matmul passes, a plain matmul program
    fires, and an unclaiming plan checks nothing."""
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.overlap import tp_overlap_matmul
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    x = jnp.ones((8, 16), jnp.float32)
    w = jnp.ones((16, 8), jnp.float32)

    def claiming(key):
        return {"winner": {"candidate": {"tp_overlap": True, "key": key}}}

    overlapped = jax.jit(shard_map_fn(
        lambda x, w: tp_overlap_matmul(x, w, axis_name="model"),
        mesh, in_specs=(P(), P(None, "model")), out_specs=P()))
    ok = analyze_callable(
        overlapped, (x, w), "j119-overlap-ok", plan=claiming("t1"))
    assert [f for f in ok if f.rule == "J119"] == [], ok

    plain = jax.jit(shard_map_fn(
        lambda x, w: jax.lax.psum(x @ w, "model"),
        mesh, in_specs=(P(), P(None, "model")), out_specs=P()))
    bad = analyze_callable(
        plain, (x, w), "j119-overlap-bad", plan=claiming("t1"))
    fired = [f for f in bad if f.rule == "J119"]
    assert len(fired) == 1 and "tp_overlap" in fired[0].message, bad

    unclaiming = {"winner": {"candidate": {"tp_overlap": False, "key": "t0"}}}
    silent = analyze_callable(
        plain, (x, w), "j119-no-claim", plan=unclaiming)
    assert [f for f in silent if f.rule == "J119"] == [], silent


def test_j119_marker_names_match_modules():
    """Drift pins for the fused-head and overlap markers J119 keys on —
    same discipline as the J107/J110/J117 pins."""
    from tpudml.analysis import jaxpr_pass
    from tpudml.ops import decode_head
    from tpudml.parallel import overlap

    assert set(jaxpr_pass.FUSED_HEAD_NAMES) == {
        decode_head.FUSED_HEAD_MARKER, decode_head.FUSED_HEAD_INT8_MARKER}
    assert jaxpr_pass.TP_OVERLAP_NAME == overlap.TP_OVERLAP_MARKER


def test_j100_trace_failure_becomes_finding():
    def broken(x):
        return x + jnp.ones((x.shape[0] + 1,))  # shape mismatch at trace

    bad = analyze_callable(broken, (jnp.ones((4,)),), "fix-j100")
    assert _rules(bad) == {"J100"}
    good = analyze_callable(lambda x: x + 1.0, (jnp.ones((4,)),), "ok-j100")
    assert "J100" not in _rules(good)


def test_donation_parser_reads_aliasing():
    state = jnp.ones((1024, 512), jnp.float32)
    lowered = jax.jit(
        lambda s: s * 2.0, donate_argnums=(0,)).lower(state).as_text()
    assert donation_findings(lowered, "donated") == []
    lowered_not = jax.jit(lambda s: s * 2.0).lower(state).as_text()
    assert [f.rule for f in donation_findings(lowered_not, "plain")] == ["J106"]


# ----------------------------------------------- real engine entrypoints


@pytest.mark.parametrize(
    "name",
    ["task2_dp", "dp_zero1", "dp_sentinel", "fsdp", "pp_gpipe", "tp_fused",
     "fsdp_fused", "moe_ragged", "serve_decode", "serve_paged_decode"])
def test_entrypoints_trace_on_cpu(name):
    """The acceptance floor: the DP, FSDP, and pipeline steps trace and
    analyze without TPU hardware, with no error-severity findings and
    nothing outside the committed allowlist."""
    findings = analyze_entrypoint(name)
    assert not [f for f in findings if f.severity == "error"], findings
    entries = load_allowlist(os.path.join(REPO, "analysis", "allowlist.toml"))
    active, _ = split_allowed(findings, entries)
    assert active == [], active


# ------------------------------------------------------------ CLI smoke


def test_strict_cli_green_on_repo():
    """CI contract: the committed allowlist covers the whole repo."""
    proc = subprocess.run(
        [sys.executable, "-m", "tpudml.analysis", "--strict"],
        cwd=REPO, capture_output=True, text=True, timeout=540,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout
    # Full-surface strict run: no committed suppression may be stale.
    assert "stale allowlist" not in proc.stdout


def test_j111_unguarded_update_fires_and_sentinel_is_silent():
    """J111 fires on a plain training step (parameter-update subs with no
    finiteness predicate anywhere in the program), anchors at the
    optimizer file so ONE allowlist entry covers every plain engine, and
    goes silent the moment the step carries a GradSentinel — whose
    isfinite lowers to the is_finite primitive the rule looks for."""
    plain = analyze_entrypoint("task2_dp")
    fired = [f for f in plain if f.rule == "J111"]
    assert len(fired) == 1, plain
    assert fired[0].severity == "info"
    assert fired[0].file == "tpudml/optim/optimizers.py"
    assert "is_finite" in fired[0].message

    guarded = analyze_entrypoint("dp_sentinel")
    assert [f for f in guarded if f.rule == "J111"] == [], guarded
    # And the sentinel engine introduces nothing else un-allowlisted.
    entries = load_allowlist(os.path.join(REPO, "analysis", "allowlist.toml"))
    active, _ = split_allowed(guarded, entries)
    assert active == [], active


def test_j111_allowlist_covers_plain_engines():
    """The committed allowlist's single optimizers.py entry absorbs the
    by-design finding on the plain baseline entrypoints."""
    findings = analyze_entrypoint("task2_dp")
    entries = load_allowlist(os.path.join(REPO, "analysis", "allowlist.toml"))
    active, allowed = split_allowed(findings, entries)
    assert [f for f in active if f.rule == "J111"] == []
    assert any(f.rule == "J111" for f in allowed)


# ----------------------------------- dataflow rules (J112-J116) fixtures


JAXPR_FIXDIR = os.path.join(FIXTURES, "jaxpr")


def _jaxpr_fixture_names():
    return sorted(f[:-3] for f in os.listdir(JAXPR_FIXDIR)
                  if f.endswith(".py") and f != "__init__.py")


@pytest.mark.parametrize("name", _jaxpr_fixture_names())
def test_dataflow_fixture(name):
    """One test per module in analysis_fixtures/jaxpr/ — discovery is by
    filename, so a fixture that fails to import or build fails THIS test
    under its own name instead of aborting collection with an opaque
    parametrize error. Protocol: see that directory's __init__.py."""
    import importlib.util

    path = os.path.join(JAXPR_FIXDIR, name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except Exception as e:  # noqa: BLE001 - reported with the fixture name
        pytest.fail(f"fixture {name}: import failed: {e!r}")
    missing = [a for a in ("RULE", "EXPECT", "build") if not hasattr(mod, a)]
    if missing:
        pytest.fail(f"fixture {name}: missing {missing} "
                    "(protocol in analysis_fixtures/jaxpr/__init__.py)")
    try:
        fn, fargs = mod.build()
    except Exception as e:  # noqa: BLE001 - reported with the fixture name
        pytest.fail(f"fixture {name}: build() failed: {e!r}")

    findings = analyze_callable(
        fn, fargs, entrypoint=name, **getattr(mod, "ANALYZE_KWARGS", {}))
    fired = [f for f in findings if f.rule == mod.RULE]
    if mod.EXPECT == "fire":
        assert fired, (name, findings)
        assert all(f.hint for f in fired)
    else:
        assert fired == [], (name, fired)


def test_jaxpr_fixture_dir_covers_every_dataflow_rule():
    """Each dataflow rule ships a firing seeded-bug fixture AND a silent
    correct-code twin; a deleted fixture file fails here by rule name."""
    names = _jaxpr_fixture_names()
    for rule in ("j112", "j113", "j114", "j115", "j116", "j117", "j118"):
        kinds = {n.rsplit("_", 1)[1] for n in names if n.startswith(rule)}
        assert kinds == {"fire", "silent"}, (rule, kinds)


# ------------------------------------------- dataflow lattice fixpoint


@pytest.mark.parametrize("name", ["serve_decode", "dp_sentinel"])
def test_dataflow_converges_on_looping_entrypoints(name):
    """The lattice fixpoint must settle within its iteration cap on the
    entrypoints with the most control flow: the serving decode step
    (scan + caches) and the sentinel ZeRO-1 step (is_finite cond around
    the sharded update)."""
    from tpudml.analysis.dataflow import _MAX_FIXPOINT_ITERS, analyze_dataflow
    from tpudml.analysis.entrypoints import ENTRYPOINTS

    prog = ENTRYPOINTS[name]()[0]
    closed = jax.make_jaxpr(prog.fn)(*prog.args)
    flow = analyze_dataflow(closed, name, in_specs=prog.in_specs,
                            mesh_axes=prog.mesh_axes)
    assert flow.converged, flow
    assert flow.iterations < _MAX_FIXPOINT_ITERS
    assert not [f for f in flow.findings if f.severity == "error"], flow


# --------------------------- static cost vs measured CommStats (5% pin)


@pytest.mark.parametrize("zero1", [False, True], ids=["dp", "zero1"])
def test_static_cost_matches_measured_comm_bytes(zero1):
    """Acceptance pin: the --cost byte counts for the DP and ZeRO-1
    steps agree with the measured-path CommStats accounting within 5%
    on a world-4 mesh. Both sides price the same ring model
    (comm.timing.collective_wire_bytes), so this checks the static
    interpreter's event inventory — collective kinds, payload bytes,
    trip counts — against the program the engine actually times."""
    from tpudml.analysis.dataflow import analyze_dataflow
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(8,)).astype(np.int32)
    opt = "adam" if zero1 else "sgd"

    measured_dp = DataParallel(
        LeNet(), make_optimizer(opt, 0.01), mesh,
        measure_comm=True, zero1=zero1)
    ts = measured_dp.create_state(seed_key(0))
    measured_dp.make_train_step()(ts, x, y)
    measured = measured_dp.comm_stats.comm_bytes
    assert measured > 0.0

    static_dp = DataParallel(
        LeNet(), make_optimizer(opt, 0.01), mesh, zero1=zero1)
    ts2 = static_dp.create_state(seed_key(0))
    fused = static_dp.make_train_step()
    closed = jax.make_jaxpr(fused.jitted)(ts2, x, y)
    flow = analyze_dataflow(closed, f"xval-{opt}", in_specs=fused.in_specs,
                            mesh_axes=fused.mesh_axes)
    static = sum(ev.wire_bytes * ev.trips for ev in flow.comm_events)
    assert abs(static - measured) / measured <= 0.05, (static, measured)


# --------------------------------------------- stale allowlist entries


def test_stale_allowlist_entries_detected():
    """unused_entries flags suppressions whose finding no longer exists
    (and only those), so --strict can warn before an allowlist entry
    silently outlives its bug."""
    from tpudml.analysis.allowlist import AllowEntry, unused_entries
    from tpudml.analysis.findings import Finding

    live = AllowEntry(rule="J111", path="tpudml/optim/*",
                      reason="plain engines omit the sentinel by design")
    live_line = AllowEntry(rule="A201", path="tools/*.py", line=12,
                           reason="host-side CLI glue")
    stale = AllowEntry(rule="J105", path="tpudml/nn/old_layer.py",
                       reason="fixed in the ragged-dW rework")
    wrong_line = AllowEntry(rule="A201", path="tools/*.py", line=99,
                            reason="drifted line anchor")
    findings = [
        Finding("J111", "no finiteness gate",
                file="tpudml/optim/optimizers.py", line=40),
        Finding("A201", "python if on traced value",
                file="tools/bench.py", line=12),
    ]
    entries = [live, live_line, stale, wrong_line]
    assert unused_entries(findings, entries) == [stale, wrong_line]
    assert unused_entries(findings, [live, live_line]) == []


# ------------------------------------------------ CLI output formats


def _run_cli(*cli_args, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "tpudml.analysis", *cli_args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_cli_format_json_golden():
    """--format json emits one machine-readable object with the three
    fixed keys; every finding carries rule/severity/location. Scoped to
    the seeded AST fixture (fast, deterministic — no tracing)."""
    import json

    proc = _run_cli(
        "--skip-jaxpr", "--format", "json", "--paths",
        os.path.join("tests", "analysis_fixtures", "seeded_violations.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert set(out) == {"active", "allowed", "stale_allowlist"}
    # Partial runs never judge staleness (they see a partial surface).
    assert out["stale_allowlist"] == []
    assert {f["rule"] for f in out["active"]} >= {"A201", "A202", "A203",
                                                 "A204"}
    for f in out["active"]:
        assert f["file"].endswith("seeded_violations.py")
        assert f["line"] > 0
        assert f["severity"] in ("error", "warn", "info")


def test_cli_format_github_golden():
    """--format github emits only workflow-annotation lines, each with a
    file= (and line=) location and a '::'-free message so the annotation
    cannot be truncated by the runner."""
    import re

    proc = _run_cli(
        "--skip-jaxpr", "--format", "github", "--paths",
        os.path.join("tests", "analysis_fixtures", "seeded_violations.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln]
    assert lines, proc.stdout
    shape = re.compile(
        r"^::(error|warning|notice) file=[^,]+,line=\d+::[AJ]\d{3}")
    for ln in lines:
        assert shape.match(ln), ln
        _, _, message = ln.split("::", 2)
        assert "::" not in message, ln
    assert any("A201" in ln for ln in lines)
