"""tpudml.plan: the static autosharding planner's contracts.

Four pinned properties:

- **determinism** — same spec + world → byte-identical ``plan.json``
  (no timestamps, sorted keys, stable candidate ordering);
- **prune honesty** — every enumerated candidate is either a survivor
  or a dropped record carrying its rule and reason: no silent caps;
- **planner ↔ runtime agreement** — the capability table the prune
  pass reads is the same table every engine guard raises from, checked
  in both directions (every table key is raised by some ``reject()``
  call; every ``reject()`` key exists in the table) plus live
  constructor spot-checks that the raised message IS the table message;
- **rank order vs reality** — ``bench.py --plan`` measures the dryrun
  regimes through the planner's own ``build_candidate``; the planner's
  top-1 must be within 10% of the measured best (the acceptance pin).
"""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def plan4():
    from tpudml.plan import flagship_lm, make_plan

    return make_plan(flagship_lm(), 4)


# ------------------------------------------------------------ determinism


def test_plan_json_is_byte_deterministic(plan4):
    from tpudml.plan import flagship_lm, make_plan, plan_to_json

    again = make_plan(flagship_lm(), 4)
    assert plan_to_json(plan4) == plan_to_json(again)


def test_plan_roundtrips_through_json(plan4, tmp_path):
    from tpudml.plan import load_plan, plan_to_json

    path = tmp_path / "plan.json"
    path.write_text(plan_to_json(plan4))
    assert load_plan(str(path)) == json.loads(plan_to_json(plan4))
    bad = dict(plan4, version=99)
    path.write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="version"):
        load_plan(str(path))


# ---------------------------------------------------------- prune honesty


@pytest.mark.parametrize("world", [4, 8])
def test_prune_reports_every_dropped_candidate(world):
    """No silent caps: survivors + dropped == enumerated, and every drop
    carries a rule and a human-readable reason."""
    from tpudml.plan import enumerate_candidates, flagship_lm, prune

    spec = flagship_lm()
    cands = enumerate_candidates(world)
    survivors, dropped = prune(spec, cands)
    assert len(survivors) + len(dropped) == len(cands)
    assert dropped, "the space deliberately includes rejected combos"
    for rec in dropped:
        assert rec.rule
        assert rec.reason
    # The capability rejections carry the table's exact message.
    from tpudml.capabilities import TABLE

    cap = [r for r in dropped if r.rule.startswith("capability:")]
    assert cap
    for rec in cap:
        key = rec.rule.split(":", 1)[1]
        assert rec.reason == TABLE[key].message


def test_prune_drops_overlap_without_zero1():
    """The enumeration includes table-rejected combos so the report
    demonstrates the shared rules firing (not silently never generating
    them)."""
    from tpudml.plan import enumerate_candidates, flagship_lm, prune

    _, dropped = prune(flagship_lm(), enumerate_candidates(4))
    rules = {r.rule for r in dropped}
    assert "capability:zero1_overlap_needs_zero1" in rules
    assert "capability:pp_fused_xent" in rules


def test_prune_hbm_budget_drops_and_reports():
    from tpudml.plan import enumerate_candidates, flagship_lm, prune

    spec = flagship_lm()
    cands = enumerate_candidates(4)
    # A 1 MB budget is below every candidate's params+moments footprint.
    survivors, dropped = prune(spec, cands, hbm_budget_bytes=1_000_000)
    assert not survivors
    assert {r.rule for r in dropped} >= {"hbm"}


def test_divisibility_prunes_odd_heads():
    from tpudml.plan import ModelSpec, enumerate_candidates, prune

    spec = ModelSpec(vocab_size=256, embed_dim=64, num_heads=3,
                     num_layers=2, seq_len=128, per_chip_batch=4)
    _, dropped = prune(spec, enumerate_candidates(4, engines=["tp"]))
    assert any(r.rule == "divisibility" and "num_heads" in r.reason
               for r in dropped)


# ------------------------------------- capability table <-> runtime guards

_REJECT_RE = re.compile(r"""reject\(\s*["']([a-z0-9_]+)["']""")

_SOURCE_ROOTS = ("tpudml", "tasks")


def _reject_keys_in_source():
    keys = {}
    for root in _SOURCE_ROOTS:
        for dirpath, _, files in os.walk(os.path.join(REPO, root)):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path) as fh:
                    for key in _REJECT_RE.findall(fh.read()):
                        keys.setdefault(key, []).append(
                            os.path.relpath(path, REPO))
    return keys


def test_every_runtime_reject_key_is_in_the_table():
    from tpudml.capabilities import TABLE

    used = _reject_keys_in_source()
    assert used, "reject() call sites expected in the engines"
    unknown = {k: v for k, v in used.items() if k not in TABLE}
    assert not unknown, f"reject() keys missing from the table: {unknown}"


def test_every_table_key_is_raised_by_some_runtime_guard():
    from tpudml.capabilities import TABLE

    used = _reject_keys_in_source()
    orphans = [k for k in TABLE if k not in used]
    assert not orphans, (
        f"capability table entries no engine raises: {orphans} — either "
        f"wire the guard through reject() or drop the entry")


def test_runtime_guard_raises_the_table_message():
    """Live spot-checks: constructors raise CompositionError carrying the
    table's exact message for a sample of composition rejections."""
    import jax

    from tpudml.capabilities import CompositionError, TABLE
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])
    model, opt = LeNet(), make_optimizer("sgd", 0.1)
    cases = {
        "zero1_overlap_needs_zero1": dict(zero1_overlap=True),
        "zero1_overlap_needs_accum": dict(zero1=True, zero1_overlap=True,
                                          accum_steps=1),
        "zero1_replaces_aggregation": dict(zero1=True,
                                           aggregation="allgather"),
        "save_scores_needs_fused_xent": dict(save_scores=True),
    }
    for key, kwargs in cases.items():
        with pytest.raises(CompositionError) as exc:
            DataParallel(model, opt, mesh, **kwargs)
        assert str(exc.value) == TABLE[key].message, key


def test_planner_prunes_exactly_what_the_constructor_rejects():
    """Planner/runtime agreement the other way: a candidate the table
    rejects must also fail to construct, with the same message."""
    from tpudml.capabilities import (
        TABLE,
        CompositionError,
        candidate_rejection,
    )
    from tpudml.plan import build_candidate
    from tpudml.plan.space import Candidate, flagship_lm

    cand = Candidate(engine="zero1", mesh=(("data", 2),), zero1=True,
                     zero1_overlap=True, accum_steps=1, fused_xent=False,
                     sentinel=False, obs=False)
    key = candidate_rejection(cand.to_dict())
    assert key == "zero1_overlap_needs_accum"
    with pytest.raises(CompositionError) as exc:
        build_candidate(flagship_lm(), cand)
    assert str(exc.value) == TABLE[key].message


# ----------------------------------------------------- winner verification


def test_winner_verifies_with_zero_dataflow_findings(plan4):
    """Acceptance: every emitted plan passes J112-J116 with zero
    findings, and nothing was demoted to get there."""
    ver = plan4["verification"]
    assert ver["ok"]
    assert ver["demoted"] == []
    dataflow = [f for f in ver["findings"]
                if f["rule"] in ("J112", "J113", "J114", "J115", "J116")]
    assert dataflow == []


def test_fresh_plan_is_j118_clean_and_stale_plan_fires(plan4):
    """predicted is stamped from the verification trace, so a fresh plan
    re-traces clean; doubling the predicted comm must fire J118."""
    from tpudml.plan import plan_drift_findings

    assert [f for f in plan_drift_findings(plan4) if f.rule == "J118"] == []
    stale = json.loads(json.dumps(plan4))
    stale["predicted"]["comm_wire_bytes"] *= 2.0
    fired = [f for f in plan_drift_findings(stale) if f.rule == "J118"]
    assert fired
    assert "re-plan" in fired[0].message


# ------------------------------------------------- rank order vs measured


def test_planner_top1_within_tolerance_of_measured_best():
    """The acceptance pin: on the world-4 CPU dryrun mesh, the planner's
    top-1 candidate (among the measured DP/ZeRO-1/ZeRO-1-overlap/FSDP
    regimes) costs at most 1.10x the measured-best candidate's step
    time. Rank order of the middle of the field is NOT pinned — CPU
    dryrun middle ranks are noise — the claim is the planner does not
    pick a loser."""
    sys.path.insert(0, REPO)
    try:
        from bench import bench_plan
    finally:
        sys.path.remove(REPO)

    report = bench_plan(world=4)
    assert report["within_tolerance"], report
    assert report["top1_vs_best_ratio"] <= report["tolerance"]
    rows = report["rows"]
    assert set(rows) == {"dp_replicated", "dp_zero1", "dp_zero1_overlap",
                        "fsdp"}
    for row in rows.values():
        assert row["sec_per_step"] > 0


# ------------------------------------------------------------ CLI contract


def test_plan_cli_check_smoke():
    """The tier-1 CI smoke: ``python -m tpudml.plan --check`` plans the
    flagship spec at world 4 and 8 and exits 0 with a verified winner at
    both."""
    proc = subprocess.run(
        [sys.executable, "-m", "tpudml.plan", "--check"],
        capture_output=True, text=True, cwd=REPO, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "world=4: ok" in proc.stdout
    assert "world=8: ok" in proc.stdout


def test_plan_cli_github_format(tmp_path):
    """--format github emits workflow-annotation lines in the same
    grammar as the analysis CLI (``::level ::message``)."""
    out = tmp_path / "plan.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpudml.plan", "--world", "4",
         "--engines", "dp,zero1", "--format", "github",
         "--out", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("::")]
    assert lines and lines[0].startswith("::notice ::PLAN[world=4]: winner ")
    # And the emitted file is a loadable v1 plan.
    from tpudml.plan import PLAN_VERSION, load_plan

    assert load_plan(str(out))["version"] == PLAN_VERSION


def test_analysis_cost_writes_report_fresh(tmp_path, monkeypatch):
    """Satellite pin: ``--cost`` writes analysis/cost_report.json anew
    in the working directory (the file is gitignored, never committed)."""
    monkeypatch.chdir(tmp_path)
    from tpudml.analysis.__main__ import main

    rc = main(["--cost", "--entrypoints", "task2_dp"])
    assert rc == 0
    report = json.loads((tmp_path / "analysis" / "cost_report.json")
                        .read_text())
    assert [e["entrypoint"] for e in report["entrypoints"]] == ["task2_dp"]
    assert report["total_wire_bytes"] > 0


def test_gitignore_covers_generated_reports():
    gitignore = open(os.path.join(REPO, ".gitignore")).read().split("\n")
    assert "analysis/cost_report.json" in gitignore
    assert "analysis/plan.json" in gitignore


# --------------------------------------------------------- train wiring


def test_train_config_merges_plan_engine_config(plan4, tmp_path):
    """--plan plan.json fills TrainConfig knobs left at their defaults;
    explicit CLI flags win."""
    from tpudml.core.config import build_parser, config_from_args
    from tpudml.plan import plan_to_json

    path = tmp_path / "plan.json"
    path.write_text(plan_to_json(plan4))
    ec = plan4["engine_config"]
    assert ec["zero1"] and ec["accum_steps"] == 2  # the dryrun winner

    cfg = config_from_args(build_parser().parse_args(["--plan", str(path)]))
    assert cfg.zero1 == ec["zero1"]
    assert cfg.accum_steps == ec["accum_steps"]

    cfg = config_from_args(build_parser().parse_args(
        ["--plan", str(path), "--accum_steps", "8"]))
    assert cfg.accum_steps == 8  # explicit flag beats the plan


# --------------------------------------------------- v2 schema + calibration


def test_plan_v2_schema_keys_always_present(plan4):
    """v2 totality: calibration/replan are ALWAYS keys (null when unused)
    — schema shape never depends on how the plan was produced, which is
    what keeps byte-determinism trivial."""
    from tpudml.plan import PLAN_VERSION

    assert plan4["version"] == PLAN_VERSION == 2
    assert plan4["calibration"] is None
    assert plan4["replan"] is None


def test_v1_plan_still_loads(plan4, tmp_path):
    """Back-compat: a v1 plan.json (no calibration/replan keys) loads
    and is upgraded in-memory to the v2 shape."""
    from tpudml.plan import load_plan

    v1 = {k: v for k, v in plan4.items() if k not in ("calibration", "replan")}
    v1["version"] = 1
    path = tmp_path / "v1_plan.json"
    path.write_text(json.dumps(v1, indent=2, sort_keys=True) + "\n")
    plan = load_plan(str(path))
    assert plan["version"] == 1
    assert plan["calibration"] is None and plan["replan"] is None
    assert plan["winner"] == plan4["winner"]


def test_calibrated_plan_is_byte_deterministic(tmp_path):
    from tpudml.plan import Calibration, flagship_lm, load_plan, make_plan, plan_to_json

    cal = Calibration(comm_scale=1.25, source="obs/drift")
    replan = {"trigger": "drift", "why": "test", "old_world": 4,
              "old_winner": {}, "receipts": []}
    a = make_plan(flagship_lm(), 4, verify=False, calibration=cal,
                  replan=dict(replan))
    b = make_plan(flagship_lm(), 4, verify=False, calibration=cal,
                  replan=dict(replan))
    assert plan_to_json(a) == plan_to_json(b)
    assert a["calibration"]["comm_scale"] == 1.25
    path = tmp_path / "plan.json"
    path.write_text(plan_to_json(a))
    assert load_plan(str(path)) == json.loads(plan_to_json(a))


def test_calibration_scales_the_roofline_terms():
    """comm_scale multiplies every comm term, hbm_scale the HBM estimate
    — monotonically, so a measured-slower network can only demote
    comm-heavy candidates, never spuriously promote them."""
    from tpudml.plan import flagship_lm, score_candidate
    from tpudml.plan.score import Calibration
    from tpudml.plan.space import enumerate_candidates

    spec = flagship_lm()
    cand = next(c for c in enumerate_candidates(4, engines=["zero1"])
                if c.zero1 and not c.zero1_overlap)
    base = score_candidate(spec, cand)
    cal = score_candidate(spec, cand,
                          calibration=Calibration(comm_scale=2.0))
    assert cal.comm_wire_bytes == pytest.approx(2.0 * base.comm_wire_bytes)
    assert (cal.exposed_comm_s + cal.hidden_comm_s) == pytest.approx(
        2.0 * (base.exposed_comm_s + base.hidden_comm_s))
    assert cal.step_time_s > base.step_time_s
    assert cal.compute_s == base.compute_s  # comm scale touches only comm
    hbm = score_candidate(spec, cand,
                          calibration=Calibration(hbm_scale=1.5))
    assert hbm.est_hbm_bytes == pytest.approx(1.5 * base.est_hbm_bytes, rel=1e-6)


def test_calibration_fit_and_roundtrip():
    from tpudml.plan import Calibration

    records = [
        {"entrypoint": "a", "static_wire_bytes": 1.0e6,
         "measured_wire_bytes": 1.25e6, "rel_err": 0.2},
        {"entrypoint": "b", "static_wire_bytes": 4.0e5,
         "measured_wire_bytes": 5.0e5, "rel_err": 0.2},
    ]
    cal = Calibration.from_drift_records(records)
    assert cal.comm_scale == pytest.approx(1.75e6 / 1.4e6)
    assert len(cal.basis) == 2
    assert Calibration.from_dict(cal.to_dict()).comm_scale == cal.comm_scale


def test_world1_enumeration_is_dp_only():
    """World 1: only plain DP is enumerable — sharding chains (zero1 /
    fsdp / tp) have nothing to shard, so the planner reports them as
    infeasible rather than scoring degenerate single-chip variants."""
    from tpudml.plan import flagship_lm, make_plan
    from tpudml.plan.space import enumerate_candidates

    cands = enumerate_candidates(1)
    assert cands
    assert {c.engine for c in cands} == {"dp"}

    plan = make_plan(flagship_lm(), 1, engines=["dp", "zero1"], verify=False)
    assert plan["winner"]["candidate"]["engine"] == "dp"
    assert plan["winner"]["candidate"]["mesh"] == {"data": 1}
