"""Fused LayerNorm kernel (tpudml/ops/layernorm_kernel.py).

Parity oracle: tpudml.nn.layers.LayerNorm. Interpret mode on CPU (as in
test_flash / test_xent_kernel); compiled parity was verified on the real
chip at [8192, 512] bf16 (y err 7.8e-3 in bf16 output, dx err 1.6e-2 —
bf16 quantization, f32 paths agree to 1e-6). NOTE: the kernel is an
unplugged primitive — in-situ it measured SLOWER than XLA's fused LN
(see the module docstring's measured-outcome note).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.nn.layers import LayerNorm
from tpudml.ops.layernorm_kernel import fused_layernorm


@pytest.mark.parametrize("n,d,bn", [(16, 32, 8), (24, 16, 16), (10, 8, 8)])
def test_matches_reference_value_and_grads(n, d, bn):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, d), jnp.float32) * 2 + 1
    g = jax.random.normal(key, (d,)) * 0.5 + 1
    b = jax.random.normal(key, (d,)) * 0.1
    ln = LayerNorm(d)
    ref = lambda x, g, b: ln.apply({"scale": g, "bias": b}, {}, x)[0]
    fused = lambda x, g, b: fused_layernorm(x, g, b, block_n=bn, interpret=True)

    np.testing.assert_allclose(
        np.asarray(fused(x, g, b)), np.asarray(ref(x, g, b)),
        rtol=1e-5, atol=1e-5,
    )
    for i in range(3):  # dx, dscale, dbias
        got = jax.grad(lambda *a: jnp.sum(jnp.sin(fused(*a))), argnums=i)(x, g, b)
        want = jax.grad(lambda *a: jnp.sum(jnp.sin(ref(*a))), argnums=i)(x, g, b)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )


def test_batched_shapes_and_validation():
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (2, 5, 16))
    g, b = jnp.ones((16,)), jnp.zeros((16,))
    y = fused_layernorm(x, g, b, interpret=True)
    assert y.shape == x.shape
    with pytest.raises(ValueError, match="scale/bias"):
        fused_layernorm(x, jnp.ones((8,)), b)
