"""Fused LayerNorm kernel (tpudml/ops/layernorm_kernel.py).

Parity oracle: tpudml.nn.layers.LayerNorm. Interpret mode on CPU (as in
test_flash / test_xent_kernel); compiled parity was verified on the real
chip at [8192, 512] bf16 (y err 7.8e-3 in bf16 output, dx err 1.6e-2 —
bf16 quantization, f32 paths agree to 1e-6). NOTE: the kernel is an
unplugged primitive — in-situ it measured SLOWER than XLA's fused LN
(see the module docstring's measured-outcome note).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.nn.layers import LayerNorm
from tpudml.ops.layernorm_kernel import fused_layernorm


# (16,32,8): exact grid, n % bn == 0 (no padding); (10,32,8): padded
# last row block; (24,16,16): bn rounding against a non-multiple n.
@pytest.mark.parametrize("n,d,bn", [(16, 32, 8), (10, 32, 8), (24, 16, 16)])
def test_matches_reference_value_and_grads(n, d, bn):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, d), jnp.float32) * 2 + 1
    g = jax.random.normal(key, (d,)) * 0.5 + 1
    b = jax.random.normal(key, (d,)) * 0.1
    ln = LayerNorm(d)
    ref = lambda x, g, b: ln.apply({"scale": g, "bias": b}, {}, x)[0]
    fused = lambda x, g, b: fused_layernorm(x, g, b, block_n=bn, interpret=True)

    np.testing.assert_allclose(
        np.asarray(fused(x, g, b)), np.asarray(ref(x, g, b)),
        rtol=1e-5, atol=1e-5,
    )
    for i in range(3):  # dx, dscale, dbias
        got = jax.grad(lambda *a: jnp.sum(jnp.sin(fused(*a))), argnums=i)(x, g, b)
        want = jax.grad(lambda *a: jnp.sum(jnp.sin(ref(*a))), argnums=i)(x, g, b)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )


def test_batched_shapes_and_validation():
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (2, 5, 16))
    g, b = jnp.ones((16,)), jnp.zeros((16,))
    y = fused_layernorm(x, g, b, interpret=True)
    assert y.shape == x.shape
    with pytest.raises(ValueError, match="scale/bias"):
        fused_layernorm(x, jnp.ones((8,)), b)


# ---------------------------------------------- fused residual-add + LN


def _addln_ref(x, r, g, b):
    """The unfused model composition: bf16-rounded sum, then LayerNorm."""
    s = x + r
    return s, LayerNorm(x.shape[-1]).apply({"scale": g, "bias": b}, {}, s)[0]


@pytest.mark.parametrize("n,d,bn", [(10, 16, 8)])
def test_add_ln_matches_reference(n, d, bn):
    from tpudml.ops.layernorm_kernel import fused_add_layernorm

    key = jax.random.PRNGKey(2)
    kx, kr = jax.random.split(key)
    x = jax.random.normal(kx, (n, d), jnp.float32) * 2 + 1
    r = jax.random.normal(kr, (n, d), jnp.float32)
    g = jax.random.normal(key, (d,)) * 0.5 + 1
    b = jax.random.normal(key, (d,)) * 0.1
    fused = lambda *a: fused_add_layernorm(*a, block_n=bn, interpret=True)

    s_got, y_got = fused(x, r, g, b)
    s_want, y_want = _addln_ref(x, r, g, b)
    np.testing.assert_allclose(np.asarray(s_got), np.asarray(s_want), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(y_got), np.asarray(y_want), rtol=1e-5, atol=1e-5
    )

    # The loss uses BOTH outputs so the backward exercises the fused
    # residual-cotangent merge (ds + LN-bwd(dy) in one kernel).
    def loss(fn):
        def f(x, r, g, b):
            s, y = fn(x, r, g, b)
            return jnp.sum(jnp.sin(y)) + jnp.sum(jnp.cos(s) * 0.3)
        return f

    for i in range(4):  # dx, dr, dscale, dbias
        got = jax.grad(loss(fused), argnums=i)(x, r, g, b)
        want = jax.grad(loss(_addln_ref), argnums=i)(x, r, g, b)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )


def test_add_ln_bf16_rounds_sum_before_stats():
    """The kernel must round x+r to the stream dtype BEFORE the f32
    statistics — the unfused path's exact numerics."""
    from tpudml.ops.layernorm_kernel import fused_add_layernorm

    key = jax.random.PRNGKey(3)
    kx, kr = jax.random.split(key)
    x = (jax.random.normal(kx, (8, 16)) * 3).astype(jnp.bfloat16)
    r = (jax.random.normal(kr, (8, 16)) * 3).astype(jnp.bfloat16)
    g, b = jnp.ones((16,)), jnp.zeros((16,))
    s_got, y_got = fused_add_layernorm(x, r, g, b, interpret=True)
    s_want, y_want = _addln_ref(x, r, g, b)
    assert s_got.dtype == jnp.bfloat16 and y_got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(s_got), np.asarray(s_want))
    np.testing.assert_allclose(
        np.asarray(y_got, np.float32), np.asarray(y_want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.parametrize("dropout", [0.0, 0.3])
def test_fused_ln_lm_matches_unfused(dropout):
    """TransformerLM(fused_ln=True) is numerically the same model: on a
    non-TPU backend the fused junctions dispatch to reference math, so
    logits and grads must match the standard trunk exactly. The dropout
    case additionally pins that the deferred trunk folds the SAME
    per-block keys and salts (train-mode rng threading)."""
    from tpudml.models import TransformerLM

    kw = dict(vocab_size=64, embed_dim=32, num_heads=2, num_layers=2,
              max_len=16, rope=True, dropout=dropout)
    train = dropout > 0
    rng = jax.random.PRNGKey(7) if train else None
    base = TransformerLM(**kw)
    fused = TransformerLM(**kw, fused_ln=True)
    params, _ = base.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)

    lb, _ = base.apply(params, {}, tokens, train=train, rng=rng)
    lf, _ = fused.apply(params, {}, tokens, train=train, rng=rng)
    np.testing.assert_allclose(np.asarray(lb), np.asarray(lf), rtol=1e-5,
                               atol=1e-5)

    def loss(model, p):
        out, _ = model.apply(p, {}, tokens, train=train, rng=rng)
        return jnp.mean(jnp.square(out))

    gb = jax.grad(lambda p: loss(base, p))(params)
    gf = jax.grad(lambda p: loss(fused, p))(params)
    flat_b, treedef_b = jax.tree_util.tree_flatten(gb)
    flat_f, treedef_f = jax.tree_util.tree_flatten(gf)
    assert treedef_b == treedef_f
    for a, c in zip(flat_b, flat_f):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(c), rtol=1e-4, atol=1e-6
        )

    # features path (fused-xent input contract) matches too
    hb, _ = base.apply_features(params, {}, tokens, train=train, rng=rng)
    hf, _ = fused.apply_features(params, {}, tokens, train=train, rng=rng)
    np.testing.assert_allclose(np.asarray(hb), np.asarray(hf), rtol=1e-5,
                               atol=1e-5)


def test_fused_ln_zero_layers_falls_back():
    """num_layers=0 leaves no junction; fused_ln must fall back to the
    unfused trunk instead of passing pend=None into the kernel."""
    from tpudml.models import TransformerLM

    kw = dict(vocab_size=32, embed_dim=16, num_heads=2, num_layers=0,
              max_len=8, rope=True)
    params, _ = TransformerLM(**kw).init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 32)
    lb, _ = TransformerLM(**kw).apply(params, {}, tokens)
    lf, _ = TransformerLM(**kw, fused_ln=True).apply(params, {}, tokens)
    np.testing.assert_allclose(np.asarray(lb), np.asarray(lf))
