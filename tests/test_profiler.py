"""Profiler + task-level checkpoint/resume/profile flag tests."""

import glob
import os

import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.metrics.profiler import SpanTimer, annotate, trace


def test_span_timer_accumulates():
    t = SpanTimer()
    x = jnp.arange(8.0)
    for _ in range(3):
        with t.span("step", sync=x):
            x = x * 1.5
    assert t.counts["step"] == 3
    assert t.totals["step"] > 0
    assert "step: " in t.report() and "3 calls" in t.report()


def test_trace_disabled_is_noop(tmp_path):
    with trace(tmp_path / "prof", enabled=False):
        pass
    assert not (tmp_path / "prof").exists()


def test_trace_captures_events(tmp_path):
    with trace(tmp_path / "prof"):
        with annotate("tiny"):
            jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    files = glob.glob(str(tmp_path / "prof" / "**" / "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files)  # trace artifacts written


def test_task1_checkpoint_resume_cli(tmp_path):
    """--ckpt_dir/--ckpt_every/--resume through the real entrypoint."""
    from tasks.task1 import main

    common = [
        "--dataset", "synthetic", "--epochs", "1", "--optimizer", "adam",
        "--lr", "0.002", "--log_every", "0", "--batch_size", "256",
        "--log_dir", str(tmp_path / "logs"), "--ckpt_dir", str(tmp_path / "ckpt"),
        "--ckpt_every", "8",
    ]
    main(common)
    steps = sorted(
        int(p.split("_")[-1]) for p in os.listdir(tmp_path / "ckpt")
    )
    assert steps and steps[-1] == 16  # 4096/256 = 16 steps, final save incl.

    # --epochs is a TOTAL budget: resuming a finished 1-epoch run with
    # the same budget trains nothing further...
    metrics = main(common + ["--resume"])
    assert metrics["steps"] == 16
    # ...and raising the budget to 2 trains exactly the remaining epoch.
    metrics = main(common[:3] + ["2"] + common[4:] + ["--resume"])
    steps_after = sorted(
        int(p.split("_")[-1]) for p in os.listdir(tmp_path / "ckpt")
    )
    assert steps_after[-1] == 32  # resumed at 16, trained 16 more
    assert np.isfinite(metrics["loss"])


# Slow lane: jax.profiler's stop_trace has been observed to take 6+ min
# in this container when finalizing a full-epoch trace (training itself
# finishes in ~10 s; the hang is entirely inside the trace export) —
# that is most of the tier-1 time budget for one test. The trace API
# itself stays pinned fast by test_trace_captures_events above.
@pytest.mark.slow
def test_task1_profile_flag_writes_trace(tmp_path):
    from tasks.task1 import main

    main([
        "--dataset", "synthetic", "--epochs", "1", "--optimizer", "adam",
        "--lr", "0.002", "--log_every", "0", "--batch_size", "1024",
        "--log_dir", str(tmp_path / "logs"), "--profile",
    ])
    traces = glob.glob(str(tmp_path / "logs" / "**" / "profile" / "**"), recursive=True)
    assert any(os.path.isfile(f) for f in traces)
