"""GSPMD model parallelism: sharding placement + single-device parity.

The task4 parity contract (SURVEY.md §7): observable equivalence = loss
curves match single-device training; mechanism = params sharded over the
``stage`` axis with optimizer state colocated (DistributedOptimizer
analogue).
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.models import lenet_stages
from tpudml.optim import make_optimizer
from tpudml.parallel.mp import GSPMDParallel, apply_rules, stage_sharding_rules
from tpudml.train import TrainState, make_train_step


@pytest.fixture(scope="module")
def batch():
    images, labels = synthetic_classification(32, (28, 28, 1), 10, seed=11)
    return np.asarray(images), np.asarray(labels)


def test_rules_shard_output_dims_and_demote_indivisible():
    mesh = make_mesh(MeshConfig({"stage": 8}))
    model = lenet_stages()
    params, _ = model.init(seed_key(0))
    specs = apply_rules(stage_sharding_rules(), params, mesh)
    # fc Dense(400,120): out=120 divisible by 8 -> sharded.
    assert specs["fc"]["layer0"]["kernel"] == P(None, "stage")
    # conv layer0 Conv2D(1,6): out-channels 6 NOT divisible by 8 -> demoted.
    assert specs["conv"]["layer0"]["kernel"] == P(None, None, None, None)
    # final Dense(120,10): out=10 not divisible -> demoted.
    assert specs["fc"]["layer2"]["kernel"] == P(None, None)


def test_mp_matches_single_device(batch):
    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    model = lenet_stages()
    opt = make_optimizer("sgd", 0.01)

    mp = GSPMDParallel(model, opt, mesh)
    ts_mp = mp.create_state(seed_key(0))
    step_mp = mp.make_train_step()

    ts_1 = TrainState.create(model, opt, seed_key(0))
    step_1 = make_train_step(model, opt)

    losses_mp, losses_1 = [], []
    for _ in range(3):
        ts_mp, m = step_mp(ts_mp, *batch)
        losses_mp.append(float(m["loss"]))
        ts_1, m1 = step_1(ts_1, *batch)
        losses_1.append(float(m1["loss"]))
    np.testing.assert_allclose(losses_mp, losses_1, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(ts_mp.params), jax.tree.leaves(ts_1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_mp_params_actually_sharded(batch):
    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    model = lenet_stages()
    opt = make_optimizer("sgd", 0.01, momentum=0.9)  # momentum state shards too
    mp = GSPMDParallel(model, opt, mesh)
    ts = mp.create_state(seed_key(0))
    kernel = ts.params["fc"]["layer0"]["kernel"]  # Dense(400,120)
    assert kernel.sharding.spec == P(None, "stage")
    # One shard per device, half the columns each.
    shards = kernel.addressable_shards
    assert len(shards) == 2
    assert shards[0].data.shape == (400, 60)
    # Optimizer momentum buffer colocated with its parameter.
    buf = ts.opt_state["fc"]["layer0"]["kernel"]
    assert buf.sharding.spec == P(None, "stage")


def test_mp_composes_with_dp(batch):
    mesh = make_mesh(MeshConfig({"data": 4, "stage": 2}))
    model = lenet_stages()
    opt = make_optimizer("sgd", 0.01)
    mp = GSPMDParallel(model, opt, mesh, batch_axis="data")
    ts = mp.create_state(seed_key(0))
    step = mp.make_train_step()

    ts_1 = TrainState.create(model, opt, seed_key(0))
    step_1 = make_train_step(model, opt)

    losses, losses_1 = [], []
    for _ in range(2):
        ts, m = step(ts, *batch)
        losses.append(float(m["loss"]))
        ts_1, m1 = step_1(ts_1, *batch)
        losses_1.append(float(m1["loss"]))
    np.testing.assert_allclose(losses, losses_1, rtol=1e-4)


@pytest.mark.slow  # ~20s; engine parity is pinned by the fast tests above
def test_task4_end_to_end(tmp_path):
    import tasks.task4 as task4

    cfg = task4.reference_defaults()
    cfg.epochs = 2
    cfg.lr = 0.05
    cfg.momentum = 0.9
    cfg.log_every = 0
    cfg.log_dir = str(tmp_path / "logs")
    cfg.data.dataset = "synthetic"
    cfg.data.batch_size = 32
    metrics = task4.run(cfg)
    assert metrics["world"] == 8
    assert metrics["test_accuracy"] > 0.5
