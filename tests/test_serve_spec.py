"""Speculative decoding: exactness is the verify step's job.

Load-bearing properties:

- the acceptance rule keeps exactly the longest draft prefix agreeing
  with the target's greedy argmax and emits the target's correction at
  the first mismatch (direct ``_verify`` unit);
- the committed token stream of a spec engine — dense OR paged, with a
  deliberately weak draft — is EXACTLY the non-spec engine's pure
  target-greedy stream per request (the whole-point property test);
- a draft that perfectly agrees with the target accepts all K tokens
  every step, collapsing decode-step count by ~(K+1)× (the throughput
  lever, measurable on the event log);
- ``draft_from_trunk`` returns a true layer-truncated view sharing the
  embedding/head, and validates its bounds;
- admission reserves spec_k rows of verify headroom per slot.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.models import TransformerLM
from tpudml.serve import (
    Request,
    ServeConfig,
    ServingEngine,
    draft_from_trunk,
    make_spec_decode_step,
    poisson_workload,
)
from tpudml.serve.spec import _verify

V, D, HEADS, LAYERS, MAX_LEN = 48, 32, 4, 2, 32


def _model(**kw):
    base = dict(vocab_size=V, embed_dim=D, num_heads=HEADS,
                num_layers=LAYERS, max_len=MAX_LEN, rope=True,
                num_kv_heads=2)
    base.update(kw)
    return TransformerLM(**base)


def _onehot_logits(rows):
    """[B, K+1, V] logits whose argmax per row is the given token."""
    out = np.zeros((len(rows), len(rows[0]), V), np.float32)
    for b, toks in enumerate(rows):
        for j, t in enumerate(toks):
            out[b, j, t] = 1.0
    return jnp.asarray(out)


# ------------------------------------------------------- verify kernel


def test_verify_accepts_longest_agreeing_prefix():
    """Acceptance stops at the FIRST mismatch even if later draft rows
    happen to agree again, and the bonus token rides a full match."""
    window = jnp.asarray([[10, 5, 7, 9],    # drafts 5,7,9
                          [10, 5, 7, 9],
                          [10, 5, 7, 9]], jnp.int32)
    target = [[5, 7, 9, 3],   # all match -> 3 accepted + bonus
              [5, 8, 9, 3],   # mismatch at d2; d3's "match" is ignored
              [4, 7, 9, 3]]   # mismatch at d1
    emitted, n_emit = _verify(window, _onehot_logits(target), spec_k=3)
    np.testing.assert_array_equal(np.asarray(n_emit), [4, 2, 1])
    np.testing.assert_array_equal(np.asarray(emitted), target)
    # Committed tokens = target greedy by construction: row 1 commits
    # [5, 8] (accepted draft + correction), row 2 commits [4].


def test_verify_rejects_all_and_still_emits_one():
    window = jnp.asarray([[1, 2, 3]], jnp.int32)
    emitted, n_emit = _verify(window, _onehot_logits([[7, 8, 9]]), spec_k=2)
    assert int(n_emit[0]) == 1  # progress guarantee: never zero tokens
    assert int(emitted[0, 0]) == 7


# ------------------------------------------------------------ the draft


def test_draft_from_trunk_shares_trunk_params():
    model = _model()
    params, _ = model.init(jax.random.key(0))
    draft, dparams = draft_from_trunk(model, params, 1)
    assert draft.num_layers == 1
    assert set(dparams) == {"tok_embed", "ln_f", "head", "block0"}
    assert dparams["block0"] is params["block0"]  # a view, not a copy
    pos_model = _model(rope=False)
    pparams, _ = pos_model.init(jax.random.key(0))
    _, pdparams = draft_from_trunk(pos_model, pparams, 1)
    assert "pos_embed" in pdparams


def test_draft_from_trunk_validates_bounds():
    model = _model()
    params, _ = model.init(jax.random.key(0))
    for bad in (0, LAYERS, LAYERS + 1):
        with pytest.raises(ValueError, match="draft num_layers"):
            draft_from_trunk(model, params, bad)
    with pytest.raises(ValueError, match="spec_k"):
        make_spec_decode_step(model, model, 0)


# --------------------------------------------- exactness property test


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_spec_stream_equals_pure_target_greedy(layout):
    """The whole point: with a deliberately WEAK draft (1-layer trunk),
    every request's committed tokens are exactly what the non-spec
    engine produces — acceptance quality affects speed, never output."""
    model = _model()
    params, _ = model.init(jax.random.key(1))
    paged_kw = (dict(cache_layout="paged", page_size=4)
                if layout == "paged" else {})

    def run(spec_k):
        cfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                          spec_k=spec_k, **(paged_kw if spec_k else {}))
        reqs, _ = poisson_workload(6, math.inf, 13, vocab_size=V,
                                   prompt_len=(2, 8), new_tokens=(4, 7))
        return ServingEngine(model, params, cfg, draft_layers=1).run(reqs)

    ref, spec = run(0), run(2)
    for rid in ref.requests:
        assert spec.requests[rid].tokens == ref.requests[rid].tokens
    specs = [e for e in spec.events if e[0] == "spec"]
    assert specs and all(0 <= e[4] <= 2 for e in specs)
    assert spec.mean_accepted_len >= 0.0


def test_perfect_draft_accepts_every_token():
    """Draft == target: all K drafts match every step, so each spec step
    commits K+1 tokens and the decode-step count collapses ~3×."""
    model = _model()
    params, _ = model.init(jax.random.key(2))
    reqs = [Request(rid=0, prompt=np.arange(5, dtype=np.int32),
                    max_new_tokens=9)]
    cfg = ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4, spec_k=2)
    eng = ServingEngine(model, params, cfg, draft_model=model,
                        draft_params=params)
    rep = eng.run(reqs)
    assert all(e[4] == 2 for e in rep.events if e[0] == "spec")
    assert rep.mean_accepted_len == 2.0
    assert rep.decode_steps == 3  # ceil(9 / (K+1)) target steps, not 9
    ref_cfg = ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4)
    ref = ServingEngine(model, params, ref_cfg).run(reqs)
    assert rep.requests[0].tokens == ref.requests[0].tokens


def test_spec_event_logs_only_committed_tokens():
    """A verify window truncated by the max_new_tokens budget logs the
    accepted length actually COMMITTED, not the window's n_emit-1 — so
    Σ (accepted_len + 1) over spec events is exactly the generated token
    count and mean_accepted_len never overstates throughput."""
    model = _model()
    params, _ = model.init(jax.random.key(2))
    # Perfect draft commits K+1=3 per step; max_new=4 truncates the
    # second window after a single token (accepted_len 0, not 2).
    reqs = [Request(rid=0, prompt=np.arange(5, dtype=np.int32),
                    max_new_tokens=4)]
    cfg = ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4, spec_k=2)
    eng = ServingEngine(model, params, cfg, draft_model=model,
                        draft_params=params)
    rep = eng.run(reqs)
    assert [e[4] for e in rep.events if e[0] == "spec"] == [2, 0]
    assert sum(e[4] + 1 for e in rep.events
               if e[0] == "spec") == rep.generated_tokens == 4


def test_engine_requires_draft_params_with_draft_model():
    model = _model()
    params, _ = model.init(jax.random.key(0))
    cfg = ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4, spec_k=2)
    with pytest.raises(ValueError, match="draft_params"):
        ServingEngine(model, params, cfg, draft_model=model)


# ---------------------------------------------------------- admission


def test_spec_headroom_reserved_at_admission():
    """prompt + max_new + spec_k must fit max_len: the verify window
    writes up to spec_k rows past the commit point, and a clamped
    dynamic_update_slice would silently corrupt the last cache rows."""
    model = _model()
    params, _ = model.init(jax.random.key(3))
    cfg = ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4, spec_k=2)
    eng = ServingEngine(model, params, cfg, draft_layers=1)
    fits_dense_only = Request(rid=0, prompt=np.zeros(22, np.int32),
                              max_new_tokens=9)  # 22+9+2 = 33 > 32
    with pytest.raises(ValueError, match="verify headroom"):
        eng.run([fits_dense_only])
    # The same request is admissible without spec.
    ref = ServingEngine(model, params,
                        ServeConfig(slots=1, max_len=MAX_LEN,
                                    prefill_chunk=4))
    rep = ref.run([Request(rid=0, prompt=np.zeros(22, np.int32),
                           max_new_tokens=9)])
    assert rep.requests[0].finished is not None
