"""FSDP/ZeRO-3 engine tests on the simulated 8-device mesh.

Load-bearing properties: (1) parameters AND optimizer state actually live
sharded 1/W per device over the data axis; (2) the training math is
exactly DP/single-device — sharding changes where bytes live, never the
update; (3) the layout composes with tensor parallelism on a 2-D mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.models import ForwardMLP
from tpudml.optim import make_optimizer
from tpudml.parallel.dp import DataParallel
from tpudml.parallel.fsdp import FSDP, fsdp_sharding_rules
from tpudml.parallel.mp import tensor_parallel_rules

WORLD = 8
GLOBAL = 32


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig({"data": WORLD}))


@pytest.fixture(scope="module")
def batch():
    images, labels = synthetic_classification(GLOBAL, (28, 28, 1), 10, seed=11)
    return jnp.asarray(images), jnp.asarray(labels)


def test_rule_shards_largest_divisible_dim():
    rule = fsdp_sharding_rules("data", axis_size=8)
    w = jax.ShapeDtypeStruct((784, 512), jnp.float32)
    assert rule(("fc1", "kernel"), w) == P("data")  # dim 0 (784) sharded
    b = jax.ShapeDtypeStruct((512,), jnp.float32)
    assert rule(("fc1", "bias"), b) == P("data")
    odd = jax.ShapeDtypeStruct((10,), jnp.float32)  # 10 % 8 != 0
    assert rule(("head", "bias"), odd) == P()
    # base rule's axes are respected; data takes the largest FREE dim
    base = tensor_parallel_rules("model")
    rule2 = fsdp_sharding_rules("data", base=base, axis_size=8)
    qkv = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    spec = rule2(("block0", "attn", "q", "kernel"), qkv)
    assert spec == P("data", "model")


def test_params_and_opt_state_are_sharded(mesh):
    model = ForwardMLP()
    opt = make_optimizer("adam", 1e-3)
    eng = FSDP(model, opt, mesh)
    ts = eng.create_state(seed_key(0))
    w = ts.params["layer1"]["kernel"]  # [784, 512] → sharded 1/8 on dim 0
    shard_shape = w.addressable_shards[0].data.shape
    assert shard_shape[0] * WORLD == w.shape[0]
    # Adam moments shard identically to their parameter.
    m = ts.opt_state["m"]["layer1"]["kernel"]
    assert m.sharding == w.sharding


def test_fsdp_matches_dp_and_single_device(mesh, batch):
    """The ZeRO-3 layout must be invisible to the math: FSDP == DP ==
    single-device training on the same global batch, step for step."""
    from tpudml.train import TrainState, make_train_step

    images, labels = batch
    model = ForwardMLP()

    def run(engine_ctor, steps=4):
        opt = make_optimizer("sgd", 0.05, momentum=0.9)
        eng = engine_ctor(model, opt)
        ts = eng.create_state(seed_key(1))
        step = eng.make_train_step()
        losses = []
        for _ in range(steps):
            ts, m = step(ts, images, labels)
            losses.append(float(m["loss"]))
        return losses, jax.device_get(ts.params)

    fsdp_losses, fsdp_params = run(lambda m, o: FSDP(m, o, mesh))
    dp_losses, dp_params = run(lambda m, o: DataParallel(m, o, mesh))

    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    ts = TrainState.create(model, opt, seed_key(1))
    step = make_train_step(model, opt)
    single_losses = []
    for _ in range(4):
        ts, m = step(ts, images, labels)
        single_losses.append(float(m["loss"]))

    np.testing.assert_allclose(fsdp_losses, dp_losses, rtol=1e-4)
    np.testing.assert_allclose(fsdp_losses, single_losses, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(fsdp_params), jax.tree.leaves(dp_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_fsdp_composes_with_tp(batch):
    """2-D {"data": 2, "model": 4} mesh: TP claims its dims, FSDP shards
    the largest remaining free dim; training still matches single-device."""
    from tpudml.models import TransformerLM
    from tpudml.data.datasets import synthetic_lm
    from tpudml.train import TrainState, make_train_step

    mesh2 = make_mesh(MeshConfig({"data": 2, "model": 4}))
    lm = TransformerLM(vocab_size=32, embed_dim=32, num_heads=4, num_layers=1,
                       max_len=16)
    # SGD for the param-parity oracle: Adam's early steps are ±sign-like
    # (m/√v with v≈0), which amplifies benign float-reassociation noise
    # from the sharded collectives far past any useful tolerance.
    opt = make_optimizer("sgd", 0.1, momentum=0.9)
    eng = FSDP(lm, opt, mesh2, base_rule=tensor_parallel_rules("model"))
    ts = eng.create_state(seed_key(2))
    step = eng.make_train_step()
    seqs = jnp.asarray(synthetic_lm(8, 16, 32, seed=3))
    x, y = seqs[:, :-1], seqs[:, 1:]

    ref_ts = TrainState.create(lm, opt, seed_key(2))
    ref_step = make_train_step(lm, opt)
    for _ in range(3):
        ts, m = step(ts, x, y)
        ref_ts, rm = ref_step(ref_ts, x, y)
        np.testing.assert_allclose(float(m["loss"]), float(rm["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3, atol=2e-5)


def test_fsdp_memory_layout_scales(mesh):
    """Total per-device parameter bytes ≈ 1/W of the model (replicated
    remainder = small/odd leaves only)."""
    model = ForwardMLP()
    opt = make_optimizer("sgd", 0.05)
    eng = FSDP(model, opt, mesh)
    ts = eng.create_state(seed_key(0))
    total = local = 0
    for leaf in jax.tree.leaves(ts.params):
        total += leaf.size
        local += leaf.addressable_shards[0].data.size
    assert local < total / (WORLD / 2)  # well under half; ~1/8 ideally
