"""Collective wrappers vs numpy references on a simulated 8-device mesh
(SURVEY.md §4 test pyramid: collective equivalence tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudml.comm import (
    allgather_average_gradients,
    allreduce_average_gradients,
    broadcast_from,
    ppermute_ring,
    psum_tree,
    reduce_scatter_average_gradients,
)
from tpudml.comm.collectives import all_to_all, get_aggregator
from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.parallel.sharding import shard_map_fn

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig({"data": WORLD}))


def per_replica_values(rng, shape=(WORLD, 4, 3)):
    return rng.standard_normal(shape).astype(np.float32)


def run_sharded(mesh, fn, x, in_axis="data", out_spec=P()):
    """Apply fn under shard_map with x sharded on its leading axis."""
    sharded = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))
    wrapped = shard_map_fn(fn, mesh, in_specs=P("data"), out_specs=out_spec)
    return np.asarray(jax.jit(wrapped)(sharded))


def test_psum_tree_matches_numpy_sum(mesh, rng):
    x = per_replica_values(rng)
    out = run_sharded(mesh, lambda v: psum_tree(v, "data"), x)
    # Each shard contributes one [1,4,3] slice; psum -> sum over replicas.
    np.testing.assert_allclose(out[0], x.sum(0), rtol=1e-5)


def test_allreduce_mean_matches_numpy_mean(mesh, rng):
    x = per_replica_values(rng)
    out = run_sharded(mesh, lambda v: allreduce_average_gradients(v, "data"), x)
    np.testing.assert_allclose(out[0], x.mean(0), rtol=1e-5)


def test_allgather_mean_equals_allreduce_mean(mesh, rng):
    """The two task2 aggregation strategies are mathematically identical
    (sections/checking.tex:20-21 compares their COST, not results). Also
    pins the fix of the reference's [zeros]*2 allgather bug
    (codes/task2/dist_utils.py:44-49) for any world size."""
    x = per_replica_values(rng)

    def body(v):
        v = v[0]  # strip shard dim -> per-replica value
        ar = allreduce_average_gradients(v, "data")
        ag = allgather_average_gradients(v, "data")
        return ar[None], ag[None]

    sharded = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))
    wrapped = shard_map_fn(
        body, mesh, in_specs=P("data"), out_specs=(P("data"), P("data"))
    )
    ar, ag = jax.jit(wrapped)(sharded)
    np.testing.assert_allclose(np.asarray(ar), np.asarray(ag), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ar)[0], x.mean(0), rtol=1e-5)


def test_reduce_scatter_mean_equals_mean(mesh, rng):
    x = per_replica_values(rng, (WORLD, WORLD * 2, 3))  # leading dim divisible
    out = run_sharded(mesh, lambda v: reduce_scatter_average_gradients(v[0], "data")[None], x, out_spec=P("data"))
    np.testing.assert_allclose(out[0], x.mean(0), rtol=1e-5)


def test_reduce_scatter_falls_back_on_indivisible(mesh, rng):
    x = per_replica_values(rng, (WORLD, 3, 2))  # 3 not divisible by 8
    out = run_sharded(mesh, lambda v: reduce_scatter_average_gradients(v[0], "data")[None], x, out_spec=P("data"))
    np.testing.assert_allclose(out[0], x.mean(0), rtol=1e-5)


def test_broadcast_from_root(mesh, rng):
    x = per_replica_values(rng)
    root = 3

    def body(v):
        return broadcast_from(v, "data", root=root)

    out = run_sharded(mesh, body, x, out_spec=P("data"))
    # Every replica ends with root's value.
    for r in range(WORLD):
        np.testing.assert_allclose(out[r], x[root], rtol=1e-6)


def test_ppermute_ring_shift(mesh, rng):
    x = per_replica_values(rng)
    out = run_sharded(mesh, lambda v: ppermute_ring(v, "data", 1), x, out_spec=P("data"))
    # replica i's value lands on replica i+1.
    for r in range(WORLD):
        np.testing.assert_allclose(out[(r + 1) % WORLD], x[r], rtol=1e-6)


def test_all_to_all_transposes_shard_axis(mesh, rng):
    # Each replica holds [1, WORLD, 2]; all_to_all swaps the sharded axis
    # with the local axis (Ulysses-style sequence redistribution).
    x = rng.standard_normal((WORLD, WORLD, 2)).astype(np.float32)
    out = run_sharded(
        mesh,
        lambda v: all_to_all(v, "data", split_axis=1, concat_axis=0),
        x,
        out_spec=P("data"),
    )
    np.testing.assert_allclose(
        out.reshape(WORLD, WORLD, 2), x.transpose(1, 0, 2), rtol=1e-6
    )


def test_get_aggregator_rejects_unknown():
    with pytest.raises(ValueError):
        get_aggregator("ring-of-power")
