"""Checkpoint integrity + fallback, proven by the fault harness: the
4-vandal x 2-format corruption matrix (docs/RESILIENCE.md), retention
that never garbage-collects the only valid checkpoint, and the
kill -> restore_latest_valid -> resume bit-exact parity that is this
PR's acceptance criterion.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.checkpoint import (
    CheckpointCorruptError,
    CheckpointHook,
    CheckpointManager,
    restore_checkpoint,
    restore_latest_valid,
    restore_latest_valid_sharded,
    restore_sharded_checkpoint,
    save_checkpoint,
    save_sharded_checkpoint,
    verify_checkpoint,
    verify_sharded_checkpoint,
)
from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import LeNet
from tpudml.optim import make_optimizer
from tpudml.resilience import VANDALS, vandalize
from tpudml.train import TrainState, train_loop

KINDS = sorted(VANDALS)


def _tree(tag: float):
    """A small state tree whose values encode which step wrote it."""
    return {
        "w": jnp.full((64, 8), tag, jnp.float32),
        "b": jnp.arange(32, dtype=jnp.bfloat16) + jnp.bfloat16(tag),
        "n": jnp.int32(tag),
    }


def _assert_tree(got, tag: float):
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(_tree(tag)["w"]))
    assert int(got["n"]) == int(tag)


def _zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


# --------------------------------------------------- vandal matrix: store


@pytest.mark.parametrize("kind", KINDS)
def test_store_vandal_detected_and_fallback(tmp_path, kind, capsys):
    """Every vandal is caught by verification, and restore_latest_valid
    walks past the corrupt newest step to the older intact one (with a
    stderr warning naming what it skipped)."""
    save_checkpoint(tmp_path, _tree(1), step=1)
    save_checkpoint(tmp_path, _tree(2), step=2)
    vandalize(tmp_path, kind)  # newest (step_2) dies

    with pytest.raises((CheckpointCorruptError, OSError)):
        verify_checkpoint(tmp_path / "step_2")
    verify_checkpoint(tmp_path / "step_1")  # older one still intact

    out = restore_latest_valid(tmp_path, _zeros_like(_tree(0)))
    _assert_tree(out, 1)
    assert "skipping invalid" in capsys.readouterr().err


@pytest.mark.parametrize("kind", ["bitflip", "truncate"])
def test_store_restore_verify_catches_payload_corruption(tmp_path, kind):
    """A DIRECT restore of a vandalized dir must fail loudly under the
    default verify=True instead of handing back silently wrong bytes."""
    path = save_checkpoint(tmp_path, _tree(3), step=3)
    vandalize(tmp_path, kind)
    with pytest.raises((CheckpointCorruptError, OSError, ValueError)):
        restore_checkpoint(path, _zeros_like(_tree(0)))


def test_store_no_valid_checkpoint_raises_with_inventory(tmp_path):
    save_checkpoint(tmp_path, _tree(1), step=1)
    save_checkpoint(tmp_path, _tree(2), step=2)
    vandalize(tmp_path, "bitflip", step=1)
    vandalize(tmp_path, "partial", step=2)
    with pytest.raises(CheckpointCorruptError, match="step_1") as exc:
        restore_latest_valid(tmp_path, _zeros_like(_tree(0)))
    assert "step_2" in str(exc.value)  # every failure is listed


def test_store_passthrough_when_no_step_dirs(tmp_path):
    target = _tree(7)
    assert restore_latest_valid(tmp_path, target) is target


# ------------------------------------------------- vandal matrix: sharded


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(MeshConfig({"data": 8}))


def _placed(tree, mesh):
    from tpudml.parallel.sharding import replicate

    return replicate(tree, mesh)


@pytest.mark.parametrize("kind", KINDS)
def test_sharded_vandal_detected_and_fallback(tmp_path, mesh8, kind, capsys):
    save_sharded_checkpoint(tmp_path, _placed(_tree(1), mesh8), step=1)
    save_sharded_checkpoint(tmp_path, _placed(_tree(2), mesh8), step=2)
    vandalize(tmp_path, kind)

    with pytest.raises((CheckpointCorruptError, OSError)):
        verify_sharded_checkpoint(tmp_path / "step_2")
    verify_sharded_checkpoint(tmp_path / "step_1")

    out = restore_latest_valid_sharded(tmp_path, _zeros_like(_tree(0)))
    _assert_tree(out, 1)
    assert "skipping invalid" in capsys.readouterr().err


def test_sharded_no_valid_checkpoint_raises(tmp_path, mesh8):
    save_sharded_checkpoint(tmp_path, _placed(_tree(1), mesh8), step=1)
    vandalize(tmp_path, "no_manifest")
    with pytest.raises(CheckpointCorruptError, match="step_1"):
        restore_latest_valid_sharded(tmp_path, _zeros_like(_tree(0)))


def test_sharded_bitflip_caught_by_crc(tmp_path, mesh8):
    path = save_sharded_checkpoint(tmp_path, _placed(_tree(5), mesh8), step=5)
    vandalize(tmp_path, "bitflip")
    with pytest.raises((CheckpointCorruptError, OSError)):
        restore_sharded_checkpoint(path, _zeros_like(_tree(0)))


# -------------------------------------------------------------- retention


def test_retention_spares_the_only_valid_checkpoint(tmp_path):
    """Keep-last-K must not delete the single restorable checkpoint when
    everything in the keep window has been vandalized — otherwise the
    fallback walk has nothing left to fall back to."""
    mgr = CheckpointManager(tmp_path, keep=3)
    for s in (1, 2, 3):
        mgr.save(_tree(s), s)
    vandalize(tmp_path, "bitflip", step=2)
    vandalize(tmp_path, "partial", step=3)
    mgr.keep = 1
    mgr._prune()
    kept = sorted(p.name for p in tmp_path.iterdir())
    assert "step_3" in kept  # the keep window itself
    assert "step_1" in kept  # spared: the only VALID checkpoint
    assert "step_2" not in kept  # ordinary invalid candidate is collected
    _assert_tree(restore_latest_valid(tmp_path, _zeros_like(_tree(0))), 1)


def test_checkpoint_hook_validates_cadence(tmp_path):
    mgr = CheckpointManager(tmp_path)
    with pytest.raises(ValueError, match="every_n_steps"):
        CheckpointHook(mgr, every_n_steps=0)


# --------------------------------------------- kill -> resume parity


class _Loader:
    """Deterministic epoch-reshuffled loader with the set_epoch/len
    contract train_loop's step-granular fast-forward relies on."""

    def __init__(self, x, y, batch):
        self.x, self.y, self.batch = x, y, batch
        self.epoch = 0

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        return len(self.x) // self.batch

    def __iter__(self):
        order = np.random.default_rng(100 + self.epoch).permutation(len(self.x))
        for i in range(len(self)):
            sl = order[i * self.batch: (i + 1) * self.batch]
            yield self.x[sl], self.y[sl]


class _KillAt(Exception):
    pass


def _kill_hook(at_step):
    def hook(*, step, **_):
        if step == at_step:
            raise _KillAt(str(step))

    return hook


def test_kill_resume_parity_bit_exact(tmp_path):
    """The end-to-end acceptance drill: train with a rolling mid-epoch
    CheckpointHook, die mid-epoch, vandalize the NEWEST checkpoint
    (the preemption also cut a write short), restart -> the restore
    walks back to the last valid step and the resumed run's final params
    are bit-identical to an uninterrupted run's."""
    rng = np.random.default_rng(17)
    x = rng.normal(size=(24, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(24,)).astype(np.int32)
    model, opt = LeNet(), make_optimizer("adam", 1e-3)
    epochs, batch = 2, 4  # 6 steps/epoch, 12 total

    # Reference: uninterrupted.
    ts_ref, _ = train_loop(model, opt, _Loader(x, y, batch), epochs,
                           seed_key(0), log_every=0)

    # Faulted: rolling saves every 2 steps, preempted at step 9 (mid
    # epoch 2), newest checkpoint (step 8) vandalized by the "crash".
    mgr = CheckpointManager(tmp_path, keep=5)
    hooks = [CheckpointHook(mgr, every_n_steps=2), _kill_hook(9)]
    with pytest.raises(_KillAt):
        train_loop(model, opt, _Loader(x, y, batch), epochs, seed_key(0),
                   log_every=0, hooks=hooks)
    vandalize(tmp_path, "truncate")  # step_8 is now a torn write

    # Restart: fresh params, restore the latest VALID step (6), resume.
    fresh = TrainState.create(model, opt, seed_key(99))
    ts = mgr.restore_latest(fresh)
    assert int(ts.step) == 6
    ts_res, _ = train_loop(model, opt, _Loader(x, y, batch), epochs,
                           seed_key(0), log_every=0, state=ts)

    assert int(ts_res.step) == int(ts_ref.step)
    for a, b in zip(jax.tree.leaves(ts_ref.params), jax.tree.leaves(ts_res.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
