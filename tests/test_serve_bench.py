"""bench.py --serve wiring.

The smoke canary (tier-1) proves the three compiled decode paths —
dense, paged, paged+spec — emit identical tokens on a seeded workload;
the slow-marked full report pins the measured wins the serving tier
claims: paged beats dense at equal HBM on a stranding workload, prefix
sharing cuts admit→first-token ≥2×, and spec decoding accepts >1 draft
token per step on the converged-model stand-in.
"""

import pytest

from bench import bench_serve


def test_serve_smoke_canary_parity():
    out = bench_serve(False, smoke=True)
    assert out["smoke"] is True
    assert out["metric"] == "serving_multitenant_parity_smoke"
    assert out["parity_dense_paged_spec"] is True
    assert set(out["rows"]) == {"dense", "paged", "paged_spec"}
    for row in out["rows"].values():
        assert row["decode_steps"] > 0
        assert row["tokens_per_step"] > 0
    assert out["rows"]["paged_spec"]["mean_accepted_len"] >= 0.0


@pytest.mark.slow
def test_serve_full_report_measured_wins():
    out = bench_serve(False)
    assert out["metric"] == "serving_multitenant_tier"
    # (c) equal HBM: dense strands >=50%, paged converts it to tokens.
    hbm = out["equal_hbm"]
    assert hbm["rows"]["dense"]["stranded_hbm_frac"] >= 0.5
    assert hbm["paged_over_dense_tokens_per_step"] > 1.0
    assert (hbm["rows"]["paged"]["hbm_occupancy"]
            > hbm["rows"]["dense"]["hbm_occupancy"])
    assert (hbm["rows"]["paged"]["tokens_per_sec_virtual"]
            > hbm["rows"]["dense"]["tokens_per_sec_virtual"])
    # (d) prefix sharing: >=2x admit-to-first-token on repeated heads.
    assert out["prefix_sharing"]["speedup_admit_to_first_token"] >= 2.0
    assert out["prefix_sharing"]["pool_stats"]["prefix_hits"] == 5
    # (e) spec: accepted_len > 1 with exact parity.
    assert out["spec_decode"]["mean_accepted_len"] > 1.0
    assert out["spec_decode"]["parity"] is True
    assert (out["spec_decode"]["decode_steps_spec"]
            < out["spec_decode"]["decode_steps_dense"])
    # (f) the full 2x2x2x2 Pareto grid materialized.
    assert len(out["pareto"]["rows"]) == 16
    for row in out["pareto"]["rows"].values():
        assert row["tokens_per_sec_virtual"] > 0
        assert row["ttft_p50_s"] is not None
