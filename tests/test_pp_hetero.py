"""Heterogeneous pipeline (HeteroPipeline): the task4 conv/fc split as
TRUE micro-batched pipeline stages.

Contract (VERDICT r2 item 4): stages with different block structures and
different activation shapes — the reference's actual model-parallel
workload, codes/task4/model.py:18-47 — pipeline with grad-exact parity
vs the sequential chain. Params ravel into a padded [S, L] stage-sharded
buffer; activations travel as padded flat buffers; lax.switch picks each
device's stage apply.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models.staged import lenet_stages
from tpudml.nn import Activation, Dense, Sequential
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import make_optimizer
from tpudml.parallel.pp import HeteroPipeline


def lenet_pipe(n_mb=4, opt=None, n_data=1):
    stages = [m for _, m in lenet_stages().stages]
    if n_data > 1:
        mesh = make_mesh(
            MeshConfig({"data": n_data, "stage": 2}), jax.devices()[: 2 * n_data]
        )
    else:
        mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    return HeteroPipeline(
        stages, n_microbatches=n_mb, mesh=mesh,
        optimizer=opt or make_optimizer("sgd", 0.05, momentum=0.9),
        batch_axis="data" if n_data > 1 else None,
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(16, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(16,)).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


@pytest.mark.parametrize("n_mb", [1, 2, 8])
def test_lenet_forward_matches_sequential(batch, n_mb):
    """n_mb=1 is exactly the reference's degenerate RPC pipeline regime."""
    x, _ = batch
    pipe = lenet_pipe(n_mb)
    params = pipe.init_params(seed_key(0))
    got = pipe.make_forward()(params, x)
    want = pipe.sequential_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def test_lenet_train_step_matches_single_device(batch):
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = lenet_pipe(4, opt=opt)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)

    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)
    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_lenet_hetero_pp_x_dp(batch):
    """2 stage × 2 data: hetero pipeline composes with DP — first update
    equals single-device on the full global batch."""
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = lenet_pipe(2, opt=opt, n_data=2)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)

    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)
    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_four_uneven_mlp_stages():
    """Four stages of *different* widths and param counts on 4 devices —
    the general heterogeneous case beyond the 2-way reference split."""
    stages = [
        Sequential((Dense(12, 48), Activation(jax.nn.relu))),
        Sequential((Dense(48, 20), Activation(jax.nn.relu))),
        Sequential((Dense(20, 64), Activation(jax.nn.relu))),
        Sequential((Dense(64, 10),)),
    ]
    mesh = make_mesh(MeshConfig({"stage": 4}), jax.devices()[:4])
    opt = make_optimizer("adam", 1e-2)
    pipe = HeteroPipeline(stages, n_microbatches=4, mesh=mesh, optimizer=opt)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(8, 12)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, size=(8,)).astype(np.int32))

    params = pipe.init_params(seed_key(0))
    got = pipe.make_forward()(params, x)
    want = pipe.sequential_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    ts = pipe.create_state(seed_key(2))
    step = pipe.make_train_step()
    losses = []
    for _ in range(30):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.5


def test_validation_errors():
    from tpudml.nn import BatchNorm, Dropout

    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    opt = make_optimizer("sgd", 0.1)
    with pytest.raises(ValueError, match="stages need"):
        HeteroPipeline([Dense(4, 4)], 2, mesh, opt)
    with pytest.raises(ValueError, match="dropout"):
        HeteroPipeline(
            [Sequential((Dense(4, 4), Dropout(0.5))), Dense(4, 4)], 2, mesh, opt
        )
    with pytest.raises(ValueError, match="stateful"):
        HeteroPipeline(
            [Sequential((Dense(4, 4), BatchNorm(4))), Dense(4, 4)], 2, mesh, opt
        )
    # prologue/epilogue would be silently dropped by the hetero schedule
    # (stage 0 IS the prologue); rejected loudly instead.
    with pytest.raises(TypeError, match="prologue"):
        HeteroPipeline([Dense(4, 4), Dense(4, 4)], 2, mesh, opt,
                       prologue=Dense(4, 4))


# ------------------------------------------------------- hetero 1F1B


def test_hetero_1f1b_train_step_matches_single_device(batch):
    """The 1F1B schedule over the heterogeneous conv→fc split: first
    update grad-exact vs the sequential single-device reference (VERDICT
    r3 item 4 — S-bounded memory for the reference's actual MP workload)."""
    from tpudml.parallel.pp import HeteroOneFOneB

    x, y = batch
    stages = [m for _, m in lenet_stages().stages]
    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = HeteroOneFOneB(stages, n_microbatches=4, mesh=mesh, optimizer=opt)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)

    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    # 1F1B sums per-micro mean losses / M — identical to the full-batch
    # mean only when micro losses are equal-sized, as here.
    M = 4
    mb = x.reshape(M, -1, *x.shape[1:])
    yb = y.reshape(M, -1)

    def ref_loss(p):
        total = 0.0
        for mi in range(M):
            total = total + softmax_cross_entropy(
                pipe.sequential_forward(p, mb[mi]), yb[mi]
            )
        return total / M

    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=1e-6)


def test_hetero_1f1b_dropout_grads_exact():
    """Dropout through heterogeneous 1F1B stages (HeteroPipeline rejects
    it; this engine lifts the restriction): gradients match a hand-built
    single-device replica applying the SAME per-(stage, micro) keys."""
    from tpudml.parallel.pp import HeteroOneFOneB
    from tpudml.nn import Dropout

    stages = [
        Sequential((Dense(12, 48), Activation(jax.nn.relu), Dropout(0.5))),
        Sequential((Dense(48, 10),)),
    ]
    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    opt = make_optimizer("sgd", 0.05)
    rng_root = jax.random.key(7)
    M = 4
    pipe = HeteroOneFOneB(stages, n_microbatches=M, mesh=mesh,
                          optimizer=opt, rng_root=rng_root)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(8, 12)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, size=(8,)).astype(np.int32))

    ts = pipe.create_state(seed_key(3))
    params0 = jax.device_get(ts.params)
    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    step_key = jax.random.fold_in(rng_root, 0)
    mb = x.reshape(M, -1, 12)
    yb = y.reshape(M, -1)

    def replica_loss(params):
        total = 0.0
        for mi in range(M):
            h = mb[mi]
            for s in range(2):
                key = jax.random.fold_in(jax.random.fold_in(step_key, s), mi)
                p_s = pipe._unravel(s, params["stages"][s])
                h = pipe.stages[s].apply(p_s, {}, h, train=True, rng=key)[0]
            total = total + softmax_cross_entropy(h, yb[mi])
        return total / M

    loss0, ref_grads = jax.value_and_grad(replica_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=1e-6)
