"""Round-4 fused kernels composed with the parallel engines.

Load-bearing properties (VERDICT r4 item 1):

- ``fused_xent`` on the DP/CP engines trains the SAME trajectory as the
  unfused logits path — the fused head loss fn is token-parallel, so per-
  shard token means pmean to the global mean under any batch/sequence
  sharding (equal shards);
- ``fused_ln`` threads through the CP trunk (TransformerLM) and the
  pipeline stage (TransformerBlock's ln2-junction fusion) with identical
  math to the unfused junctions;
- ``fused_ln`` + MoE is the same function as the unfused MoE trunk
  (the junction kernel fuses the residual ADD, not the FFN branch; aux
  state threads through the deferred trunk); save_scores without
  fused_xent raises at engine construction.

On CPU both kernels dispatch to reference math, so these tests pin the
PLUMBING and the sharded-mean structure; kernel numerics are pinned
separately in interpret mode (test_layernorm_kernel / test_xent_kernel).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import TransformerBlock, TransformerLM
from tpudml.optim import make_optimizer
from tpudml.parallel.cp import ContextParallel
from tpudml.parallel.dp import DataParallel

V, B, T, DIM, HEADS, LAYERS = 32, 4, 16, 16, 4, 2


def _tokens(seed=3, t=T, b=B):
    rng = np.random.default_rng(seed)
    return rng.integers(0, V, size=(b, t + 1)).astype(np.int32)


def _lm(**kw):
    cfg = dict(
        vocab_size=V, embed_dim=DIM, num_heads=HEADS, num_layers=LAYERS,
        max_len=T,
    )
    cfg.update(kw)
    return TransformerLM(**cfg)


def _run_steps(engine, steps=2, seed=3):
    ts = engine.create_state(seed_key(0))
    step = engine.make_train_step()
    batch = _tokens(seed)
    losses = []
    for _ in range(steps):
        ts, m = step(ts, batch[:, :-1], batch[:, 1:])
        losses.append(float(m["loss"]))
    return ts, losses


def _assert_tree_close(a, b, rtol=1e-5, atol=1e-6):
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(b))
    for path, la in flat_a:
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(flat_b[path]), rtol=rtol, atol=atol,
            err_msg=jax.tree_util.keystr(path),
        )


# ------------------------------------------------------------ CP × fused


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_fused_xent_matches_unfused(impl):
    mesh = make_mesh(MeshConfig({"seq": 4}), jax.devices()[:4])
    opt = make_optimizer("sgd", 0.05)
    model = _lm(impl=impl, seq_sharded=True)
    ts_f, loss_f = _run_steps(
        ContextParallel(model, opt, mesh, fused_xent=True)
    )
    ts_u, loss_u = _run_steps(ContextParallel(model, opt, mesh))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_cp_fused_ln_matches_unfused():
    mesh = make_mesh(MeshConfig({"seq": 4}), jax.devices()[:4])
    opt = make_optimizer("sgd", 0.05)
    ts_f, loss_f = _run_steps(
        ContextParallel(
            _lm(impl="ring", seq_sharded=True, fused_ln=True), opt, mesh
        )
    )
    ts_u, loss_u = _run_steps(
        ContextParallel(_lm(impl="ring", seq_sharded=True), opt, mesh)
    )
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_cp_fused_ln_and_xent_together_match_single_device():
    """The full round-4 step — fused trunk + fused head — under the seq
    sharding tracks the single-device unfused trajectory."""
    from tpudml.train import TrainState, make_train_step

    mesh = make_mesh(MeshConfig({"seq": 4}), jax.devices()[:4])
    # SGD for trajectory parity: parameters with a ~zero true gradient
    # (e.g. the attention k bias, shift-invariant under softmax) carry
    # pure float noise — Adam normalizes that noise to O(1) sign-flip
    # updates, which would fail ANY two numerically-different-but-equal
    # implementations. SGD keeps noise at noise scale.
    opt = make_optimizer("sgd", 0.05)
    cp = ContextParallel(
        _lm(impl="ring", seq_sharded=True, fused_ln=True), opt, mesh,
        fused_xent=True,
    )
    ts_f, loss_f = _run_steps(cp)

    single = _lm(impl="full")
    ts = TrainState.create(single, opt, seed_key(0))
    step = make_train_step(single, opt)
    batch = _tokens()
    losses = []
    for _ in range(2):
        ts, m = step(ts, batch[:, :-1], batch[:, 1:])
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(loss_f, losses, rtol=1e-4)
    _assert_tree_close(ts_f.params, ts.params, rtol=1e-4, atol=1e-5)


def test_cp_striped_fused_xent_matches_unfused():
    mesh = make_mesh(MeshConfig({"seq": 4}), jax.devices()[:4])
    opt = make_optimizer("sgd", 0.05)
    model = _lm(impl="ring", seq_sharded=True, seq_layout="striped")
    ts_f, loss_f = _run_steps(
        ContextParallel(model, opt, mesh, layout="striped", fused_xent=True)
    )
    ts_u, loss_u = _run_steps(
        ContextParallel(model, opt, mesh, layout="striped")
    )
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


# ------------------------------------------------------------ DP × fused


def test_dp_fused_xent_matches_unfused():
    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    opt = make_optimizer("sgd", 0.05)
    model = _lm(impl="full")
    common = dict(stacked_batches=False)
    ts_f, loss_f = _run_steps(
        DataParallel(model, opt, mesh, fused_xent=True, **common)
    )
    ts_u, loss_u = _run_steps(DataParallel(model, opt, mesh, **common))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


@pytest.mark.slow
def test_dp_fused_xent_with_accum_matches_plain():
    """fused_xent × accum_steps (previously rejected at construction):
    the fused loss threads through the micro-batch scan with grad-exact
    parity — mean of equal-chunk token means == batch token mean."""
    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])
    model = _lm(impl="full")
    common = dict(stacked_batches=False, fused_xent=True)
    ts_a, loss_a = _run_steps(
        DataParallel(
            model, make_optimizer("sgd", 0.05), mesh, accum_steps=2, **common
        )
    )
    ts_1, loss_1 = _run_steps(
        DataParallel(model, make_optimizer("sgd", 0.05), mesh, **common)
    )
    np.testing.assert_allclose(loss_a, loss_1, rtol=1e-5)
    _assert_tree_close(ts_a.params, ts_1.params)


# ------------------------------------------ sharded head (TP/FSDP) × fused


def _tp_rules():
    from tpudml.parallel.mp import tensor_parallel_rules

    return tensor_parallel_rules("model")


def test_tp_fused_xent_matches_unfused():
    """Vocab-sharded fused head under tensor parallelism: per-shard
    partial (lse, picked) statistics merged by the online lse rule train
    the SAME trajectory as the unfused sharded logits path."""
    from tpudml.parallel.mp import GSPMDParallel

    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    model = _lm(impl="full")

    def eng(fused):
        return GSPMDParallel(
            model, make_optimizer("sgd", 0.05), mesh, rule=_tp_rules(),
            axis_name="model", fused_xent=fused,
        )

    ts_f, loss_f = _run_steps(eng(True))
    ts_u, loss_u = _run_steps(eng(False))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_fsdp_fused_xent_matches_unfused():
    """1-D FSDP shards tokens AND vocab over the same axis; the fused
    path all-gathers tokens into the head region so each shard scores
    all tokens against its vocab slice — grad-exact vs unfused FSDP."""
    from tpudml.parallel.fsdp import FSDP

    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    model = _lm(impl="full")

    def eng(fused):
        return FSDP(model, make_optimizer("sgd", 0.05), mesh, fused_xent=fused)

    ts_f, loss_f = _run_steps(eng(True))
    ts_u, loss_u = _run_steps(eng(False))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_fsdp_tp_fused_xent_matches_unfused():
    """2-D FSDP×TP composition: head kernel P('data', 'model') — vocab
    merge over model, W all-gathered over data on use (its transpose IS
    the ZeRO reduce-scatter for dW), tokens stay data-sharded with a
    final pmean. Grad-exact vs the unfused 2-D engine."""
    from tpudml.parallel.fsdp import FSDP

    mesh = make_mesh(MeshConfig({"data": 2, "model": 2}), jax.devices()[:4])
    model = _lm(impl="full")

    def eng(fused):
        return FSDP(
            model, make_optimizer("sgd", 0.05), mesh,
            base_rule=_tp_rules(), fused_xent=fused,
        )

    ts_f, loss_f = _run_steps(eng(True))
    ts_u, loss_u = _run_steps(eng(False))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_tp_fused_xent_indivisible_vocab_falls_back():
    """A vocab the mesh can't divide demotes the head spec to replicated
    — the sharded loss fn then takes the plain full-vocab kernel path
    inside the shard_map region, still matching the unfused engine."""
    from tpudml.parallel.mp import GSPMDParallel

    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    model = _lm(impl="full", vocab_size=34)  # 34 % 4 != 0 -> demoted

    def eng(fused):
        return GSPMDParallel(
            model, make_optimizer("sgd", 0.05), mesh, rule=_tp_rules(),
            axis_name="model", fused_xent=fused,
        )

    ts_f, loss_f = _run_steps(eng(True))
    ts_u, loss_u = _run_steps(eng(False))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


@pytest.mark.parametrize("save_s", [False, True])
def test_sharded_kernel_path_grad_parity(save_s):
    """The Pallas machinery itself (interpret mode) under every sharded
    composition: value AND gradients match the unsharded reference at
    the single-shard parity tolerances. The engine tests above exercise
    the reference dispatch on CPU; this pins the kernel dispatch —
    including the shard_map transpose convention the custom_vjp's
    cotangent psum compensates for."""
    from jax.sharding import PartitionSpec as P

    from tpudml.ops.xent_kernel import (
        linear_cross_entropy,
        sharded_linear_cross_entropy,
    )
    from tpudml.parallel.sharding import shard_map_fn

    n, d, v = 16, 8, 64
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(d, v)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(v,)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, v, size=(n,)).astype(np.int32))
    labels = labels.at[3].set(v + 5)  # out-of-range: loss = lse row

    lr, gr = jax.value_and_grad(
        lambda x, w, b: linear_cross_entropy(x, w, labels, b),
        argnums=(0, 1, 2),
    )(x, w, b)

    def check(fn):
        ls, gs = jax.value_and_grad(fn, argnums=(0, 1, 2))(x, w, b)
        np.testing.assert_allclose(float(ls), float(lr), rtol=1e-6)
        for got, want, nm in zip(gs, gr, ("dx", "dw", "db")):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6,
                err_msg=nm,
            )

    # TP: x replicated, vocab sharded over "model".
    tp = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])

    def tp_loss(x, w, b):
        def body(x, w, b, ln):
            return sharded_linear_cross_entropy(
                x, w, ln, b, axis_name="model", interpret=True,
                save_s=save_s,
            )
        return shard_map_fn(
            body, tp,
            in_specs=(P(), P(None, "model"), P("model"), P()),
            out_specs=P(),
        )(x, w, b, labels)

    check(tp_loss)

    # 1-D FSDP: tokens AND vocab share "data"; gather the batch first.
    fs = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])

    def fs_loss(x, w, b):
        def body(x, w, b, ln):
            xg = jax.lax.all_gather(x, "data", axis=0, tiled=True)
            lg = jax.lax.all_gather(ln, "data", axis=0, tiled=True)
            return sharded_linear_cross_entropy(
                xg, w, lg, b, axis_name="data", interpret=True,
                save_s=save_s,
            )
        return shard_map_fn(
            body, fs,
            in_specs=(P("data"), P(None, "data"), P("data"), P("data")),
            out_specs=P(),
        )(x, w, b, labels)

    check(fs_loss)

    # 2-D FSDP×TP: tokens over "data", vocab over "model", W dim 0
    # gathered over "data" on use, per-shard token means pmean'd.
    ft = make_mesh(MeshConfig({"data": 2, "model": 2}), jax.devices()[:4])

    def ft_loss(x, w, b):
        def body(x, w, b, ln):
            k = jax.lax.all_gather(w, "data", axis=0, tiled=True)
            loss = sharded_linear_cross_entropy(
                x, k, ln, b, axis_name="model", interpret=True,
                save_s=save_s,
            )
            return jax.lax.pmean(loss, "data")
        return shard_map_fn(
            body, ft,
            in_specs=(P("data"), P("data", "model"), P("model"), P("data")),
            out_specs=P(),
        )(x, w, b, labels)

    check(ft_loss)


# ------------------------------------------------------- pipeline × fused


def test_block_fused_ln_grads_match_unfused():
    """The ln2-junction fusion is the same function as the unfused block —
    values and gradients."""
    block_u = TransformerBlock(DIM, HEADS)
    block_f = TransformerBlock(DIM, HEADS, fused_ln=True)
    params, _ = block_u.init(seed_key(1))
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(B, T, DIM)).astype(np.float32)
    )

    def loss(block, p):
        out, _ = block.apply(p, {}, x)
        return jnp.sum(out * jnp.cos(x))  # fixed nontrivial cotangent

    lu, gu = jax.value_and_grad(lambda p: loss(block_u, p))(params)
    lf, gf = jax.value_and_grad(lambda p: loss(block_f, p))(params)
    np.testing.assert_allclose(float(lf), float(lu), rtol=1e-6)
    _assert_tree_close(gf, gu)


def test_pp_fused_ln_matches_unfused():
    from tpudml.models import TransformerEmbed, TransformerHead
    from tpudml.parallel.pp import GPipe

    mesh = make_mesh(MeshConfig({"stage": 4}), jax.devices()[:4])
    opt = make_optimizer("sgd", 0.05)

    def pipe(fused):
        return GPipe(
            TransformerBlock(DIM, HEADS, fused_ln=fused),
            n_microbatches=2,
            mesh=mesh,
            optimizer=opt,
            prologue=TransformerEmbed(V, DIM, T),
            epilogue=TransformerHead(DIM, V),
        )

    ts_f, loss_f = _run_steps(pipe(True))
    ts_u, loss_u = _run_steps(pipe(False))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_task5_accepts_fused_flags_multichip():
    """task5 runs --fused_xent/--fused_ln under cp/dp/pp end-to-end."""
    from tasks.task5_longcontext import main

    base = ["--steps", "2", "--seq_len", "16", "--batch_size", "4",
            "--vocab", "32", "--embed_dim", "16", "--num_heads", "4",
            "--num_layers", "1", "--log_every", "0", "--n_devices", "2"]
    out = main(base + ["--parallel", "cp", "--fused_xent", "--fused_ln"])
    assert np.isfinite(out["final_loss"])
    out = main(base + ["--parallel", "dp", "--fused_xent"])
    assert np.isfinite(out["final_loss"])
    out = main(base + ["--parallel", "pp", "--fused_ln",
                       "--microbatches", "2"])
    assert np.isfinite(out["final_loss"])
    out = main(base + ["--parallel", "tp", "--fused_xent"])
    assert np.isfinite(out["final_loss"])
    out = main(base + ["--parallel", "fsdp", "--fused_xent"])
    assert np.isfinite(out["final_loss"])


# ------------------------------------------------------------------ guards


def test_fused_ln_moe_matches_unfused():
    """fused_ln composes with MoE: the deferred trunk routes the FFN
    branch through the MoE layer and threads the aux-loss state, so
    values, gradients (router included), AND the aux loss match the
    unfused MoE trunk."""
    kw = dict(moe_experts=2, moe_capacity_factor=8.0)
    lm_u = _lm(**kw)
    lm_f = _lm(fused_ln=True, **kw)
    params, state = lm_u.init(seed_key(2))
    toks = jnp.asarray(_tokens()[:, :-1])

    def loss(lm, p):
        logits, new_state = lm.apply(p, state, toks, train=True)
        from tpudml.train import collect_aux_losses
        return jnp.sum(jnp.sin(logits)) * 1e-2 + \
            jnp.sum(logits**2) * 1e-3 + collect_aux_losses(new_state)

    lu, gu = jax.value_and_grad(lambda p: loss(lm_u, p))(params)
    lf, gf = jax.value_and_grad(lambda p: loss(lm_f, p))(params)
    np.testing.assert_allclose(float(lf), float(lu), rtol=1e-5)
    _assert_tree_close(gf, gu)

    # The pipeline-stage form too (block-level ln2 fusion + MoE).
    block_u = TransformerBlock(DIM, HEADS, **kw)
    block_f = TransformerBlock(DIM, HEADS, fused_ln=True, **kw)
    bp, bs = block_u.init(seed_key(3))
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(B, T, DIM)).astype(np.float32)
    )

    def bloss(block, p):
        out, st = block.apply(p, bs, x)
        return jnp.sum(out * jnp.cos(x)) + st["moe"]["aux_loss"]

    blu, bgu = jax.value_and_grad(lambda p: bloss(block_u, p))(bp)
    blf, bgf = jax.value_and_grad(lambda p: bloss(block_f, p))(bp)
    np.testing.assert_allclose(float(blf), float(blu), rtol=1e-6)
    _assert_tree_close(bgf, bgu)


def test_fused_ln_moe_matches_unfused_under_ep():
    """fused_ln + MoE under expert parallelism: the shard_map EP engine
    trains the SAME trajectory fused vs unfused — the junction kernel
    fuses the residual add, not the FFN branch, so expert dispatch across
    the mesh and the psum'd aux loss are untouched (README's 'including
    under expert parallelism' claim, pinned on the CPU mesh)."""
    from tpudml.parallel.ep import ExpertParallel

    mesh = make_mesh(MeshConfig({"expert": 2}), jax.devices()[:2])
    kw = dict(moe_experts=2, moe_capacity_factor=8.0, moe_axis="expert")
    opt = lambda: make_optimizer("adam", 1e-2)
    ts_u, loss_u = _run_steps(ExpertParallel(_lm(**kw), opt(), mesh))
    ts_f, loss_f = _run_steps(
        ExpertParallel(_lm(fused_ln=True, **kw), opt(), mesh)
    )
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_save_scores_requires_fused_xent():
    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])
    opt = make_optimizer("adam", 1e-3)
    with pytest.raises(ValueError, match="save_scores"):
        DataParallel(_lm(), opt, mesh, save_scores=True)
    seq = make_mesh(MeshConfig({"seq": 2}), jax.devices()[:2])
    with pytest.raises(ValueError, match="save_scores"):
        ContextParallel(
            _lm(impl="ring", seq_sharded=True), opt, seq, save_scores=True
        )


def test_task5_fused_xent_rejects_pp_only():
    """pp is the one remaining non-composition: the pipeline epilogue
    ships logits between stages, so there is no feature tensor for the
    fused head to consume. tp/fsdp now build (covered above)."""
    from tasks.task5_longcontext import build_engine, parse_args

    args = parse_args(["--parallel", "pp", "--fused_xent"])
    with pytest.raises(ValueError, match="fused_xent"):
        build_engine(args, jax.devices()[:2])


# ---------------------------------------------- embed backward chunking


def test_embed_backward_chunked_matches_dense(monkeypatch):
    """Above the one-hot cap the scan-chunked dTable equals the dense
    matmul (and autodiff-of-gather)."""
    from tpudml.models import transformer as tr

    table = jnp.asarray(
        np.random.default_rng(7).normal(size=(V, DIM)).astype(np.float32)
    )
    tokens = jnp.asarray(_tokens(11)[:, :T])
    cot = jnp.asarray(
        np.random.default_rng(8).normal(
            size=(*tokens.shape, DIM)
        ).astype(np.float32)
    )

    def grad_of(fn):
        return jax.grad(lambda t: jnp.sum(fn(t, tokens) * cot))(table)

    dense = grad_of(tr.embed_lookup)
    # n*V = 64*32 = 2048; a cap of 256 forces chunking (chunk=8 rows).
    monkeypatch.setattr(tr, "_ONEHOT_ELEM_CAP", 256)
    chunked = grad_of(tr.embed_lookup)
    reference = grad_of(lambda tab, tok: tab[tok])
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(reference), rtol=1e-5, atol=1e-6)


def test_save_s_auto_threshold():
    """save_s=None resolves to speed mode iff the padded f32 score
    residual fits SAVE_S_AUTO_MAX_BYTES (VERDICT r4 item 5's default-on
    criterion): flagship 8k×32k (1 GiB) and chip-filling 16k×32k (2 GiB)
    are ON; the 131k-token long-context regime (16 GiB) falls back to
    the O(N) lean contract."""
    from tpudml.ops.xent_kernel import _auto_save_s

    bn, bv = 256, 2048
    assert _auto_save_s(8192, 32768, bn, bv) is True     # flagship
    assert _auto_save_s(16384, 32768, bn, bv) is True    # --large (2 GiB)
    assert _auto_save_s(16640, 32768, bn, bv) is False   # just past budget
    assert _auto_save_s(131072, 32768, bn, bv) is False  # long-context
    # Padding counts: n=1 still pads to a block row multiple of 8.
    assert _auto_save_s(1, 256, bn, bv) is True


def test_save_s_auto_threshold_sharded(monkeypatch):
    """The sharded head resolves save_s=None against its LOCAL vocab —
    each shard holds a 1/W slice of the score residual, so a 16k×32k
    problem that is lean unsharded (2 GiB + one padded block row) flips
    to speed mode once 4 shards each hold 16k×8k (512 MiB). Pinned at
    the exact byte boundary, and the wiring is pinned by recording the
    (n, v) the public entry point hands to the auto rule."""
    from jax.sharding import PartitionSpec as P

    from tpudml.ops import xent_kernel as xk
    from tpudml.parallel.sharding import shard_map_fn

    bn, bv = 256, 2048
    n, v, shards = 16640, 32768, 4
    # Unsharded: one padded block row past the 2 GiB budget.
    assert xk._auto_save_s(n, v, bn, bv) is False
    _, _, n_pad, v_pad = xk._padded_dims(n, v, bn, bv)
    assert (n_pad - bn) * v_pad * 4 == xk.SAVE_S_AUTO_MAX_BYTES
    # Each shard's residual is exactly 1/W of that -> back under budget.
    assert xk._auto_save_s(n, v // shards, bn, bv) is True

    # And sharded_linear_cross_entropy really uses the local slice.
    seen = []
    real = xk._auto_save_s

    def spy(n, v, block_n, block_v):
        seen.append((n, v))
        return real(n, v, block_n, block_v)

    monkeypatch.setattr(xk, "_auto_save_s", spy)
    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    nn, d, vv = 8, 4, 32
    x = jnp.zeros((nn, d), jnp.float32)
    w = jnp.zeros((d, vv), jnp.float32)
    labels = jnp.zeros((nn,), jnp.int32)

    def body(x, w, ln):
        return xk.sharded_linear_cross_entropy(
            x, w, ln, axis_name="model", save_s=None
        )

    shard_map_fn(
        body, mesh,
        in_specs=(P(), P(None, "model"), P()), out_specs=P(),
    )(x, w, labels)
    assert (nn, vv // 4) in seen


def test_pick_bv_dw_divisor_contract():
    from tpudml.ops.xent_kernel import _pick_bv_dw

    # Non-power-of-two block_v (the ADVICE case): halving 384 would
    # strand above a 256 cap; the divisor pick lands on 256 | 1536.
    assert _pick_bv_dw(1536, 384, 256) == 256
    # Power-of-two happy path unchanged.
    assert _pick_bv_dw(4096, 2048, 1024) == 1024
    # Cap below 128 clamps to the 128 floor.
    assert _pick_bv_dw(1024, 2048, 64) == 128
    # Small-vocab clamp (v_pad = block_v < 128) keeps the full tile — the
    # 128 floor must NOT override a tile that already fits (it would not
    # divide v_pad and the dW grid would be empty).
    assert _pick_bv_dw(64, 64, 1024) == 64
    # v_pad is always a multiple of block_v by construction.
    for v_pad, bv, cap in [(1536, 384, 256), (8192, 2048, 896), (1536, 512, 512)]:
        got = _pick_bv_dw(v_pad, bv, cap)
        assert got % 128 == 0 and v_pad % got == 0
        assert got <= max(128, min(bv, cap))
