"""Checkpoint/resume tests (SURVEY.md §5.4 — a gap the reference leaves open).

Load-bearing properties: round-trip bitwise fidelity (incl. bfloat16
leaves), resume-equivalence (train k then save/restore/train k == train 2k
straight through), retention pruning, and structure-mismatch detection.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.checkpoint import (
    CheckpointManager,
    checkpoint_hook,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.models import LeNet
from tpudml.optim import make_optimizer
from tpudml.train import TrainState, make_train_step


@pytest.fixture()
def state():
    model = LeNet()
    opt = make_optimizer("adam", 1e-3)
    return model, opt, TrainState.create(model, opt, seed_key(0))


def test_roundtrip_bitwise(tmp_path, state):
    _, _, ts = state
    path = save_checkpoint(tmp_path, ts, step=7, metadata={"note": "x"})
    assert latest_checkpoint(tmp_path) == str(path)
    model = LeNet()
    opt = make_optimizer("adam", 1e-3)
    fresh = TrainState.create(model, opt, seed_key(1))
    restored = restore_checkpoint(path, fresh)
    for a, b in zip(jax.tree.leaves(ts), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roundtrip_bfloat16(tmp_path):
    tree = {"w": jnp.arange(12, dtype=jnp.bfloat16).reshape(3, 4), "n": jnp.int32(3)}
    path = save_checkpoint(tmp_path, tree, step=0)
    out = restore_checkpoint(path, jax.tree.map(jnp.zeros_like, tree))
    assert np.asarray(out["w"]).dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(tree["w"]))


def test_resume_equivalence(tmp_path, state):
    model, opt, ts = state
    images, labels = synthetic_classification(16, (28, 28, 1), 10, seed=3)
    step = make_train_step(model, opt)

    for _ in range(2):
        ts, _ = step(ts, images, labels)
    save_checkpoint(tmp_path, ts, step=2)

    resumed = restore_checkpoint(
        latest_checkpoint(tmp_path), TrainState.create(model, opt, seed_key(9))
    )
    for _ in range(2):
        ts, _ = step(ts, images, labels)
        resumed, _ = step(resumed, images, labels)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_manager_retention_and_latest(tmp_path, state):
    _, _, ts = state
    mgr = CheckpointManager(tmp_path, keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(ts, s)
    assert mgr.latest_step() == 4
    kept = sorted(p.name for p in tmp_path.iterdir())
    assert kept == ["step_3", "step_4"]


def test_structure_mismatch_raises(tmp_path):
    path = save_checkpoint(tmp_path, {"a": jnp.ones(3)}, step=0)
    with pytest.raises(ValueError, match="structure mismatch"):
        restore_checkpoint(path, {"a": jnp.ones(3), "b": jnp.ones(2)})
    with pytest.raises(ValueError, match="shape"):
        restore_checkpoint(path, {"a": jnp.ones(4)})


def _at_step(ts, s):
    return TrainState(
        params=ts.params,
        model_state=ts.model_state,
        opt_state=ts.opt_state,
        step=jnp.int32(s),
    )


def test_train_loop_hook(tmp_path, state):
    model, opt, ts = state
    mgr = CheckpointManager(tmp_path, keep=3)
    hook = checkpoint_hook(mgr, every=2)
    for s in range(1, 5):
        hook(epoch=0, step=s, train_state=_at_step(ts, s), metrics={})
    assert mgr.latest_step() == 4
    assert sorted(p.name for p in tmp_path.iterdir()) == ["step_2", "step_4"]


def test_hook_keys_by_global_step_across_resume(tmp_path, state):
    """After a resume, the loop counter restarts at 1 but the TrainState
    step is monotonic — retention must keep the post-resume checkpoints,
    not resurrect the pre-crash one."""
    model, opt, ts = state
    mgr = CheckpointManager(tmp_path, keep=2)
    hook = checkpoint_hook(mgr, every=2)
    hook(epoch=0, step=100, train_state=_at_step(ts, 100), metrics={})
    # "Restart" = a fresh process creates a fresh hook; its loop counter
    # restarts at 1 while the restored global step continues at 101.
    hook = checkpoint_hook(mgr, every=2)
    for counter, global_step in enumerate(range(101, 105), start=1):
        hook(epoch=0, step=counter, train_state=_at_step(ts, global_step), metrics={})
    assert mgr.latest_step() == 104
    assert sorted(p.name for p in tmp_path.iterdir()) == ["step_102", "step_104"]


def test_async_write_roundtrip(tmp_path, state):
    """Async saves land complete checkpoints; restore/wait join the
    in-flight write and errors surface at the next call."""
    _, _, ts = state
    mgr = CheckpointManager(tmp_path, keep=2, async_write=True)
    for s in (1, 2, 3):
        mgr.save(ts, s)
    assert mgr.latest_step() == 3  # implies wait() joined the writer
    assert sorted(p.name for p in tmp_path.iterdir()) == ["step_2", "step_3"]
    restored = mgr.restore_latest(
        TrainState.create(LeNet(), make_optimizer("adam", 1e-3), seed_key(4))
    )
    for a, b in zip(jax.tree.leaves(ts), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_write_error_surfaces(tmp_path, state):
    _, _, ts = state
    mgr = CheckpointManager(tmp_path / "f", keep=2, async_write=True)
    mgr.save(ts, 1)
    mgr.wait()
    # Sabotage the directory so the next background write fails.
    import shutil

    shutil.rmtree(tmp_path / "f")
    (tmp_path / "f").write_text("not a directory")
    mgr.save(ts, 2)
    with pytest.raises(OSError):  # makedirs over the file-at-path
        mgr.wait()


def test_restore_into_dp_engine(tmp_path, state):
    """A checkpoint taken from a DP run restores into a fresh DP engine and
    training continues bit-identically with an uninterrupted run."""
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.dp import DataParallel

    model = LeNet()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    images, labels = synthetic_classification(32, (28, 28, 1), 10, seed=4)

    dp = DataParallel(model, opt, mesh)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    for _ in range(2):
        ts, _ = step(ts, images, labels)
    save_checkpoint(tmp_path, ts, step=2)

    dp2 = DataParallel(model, opt, mesh)
    resumed = restore_checkpoint(
        latest_checkpoint(tmp_path), dp2.create_state(seed_key(9))
    )
    step2 = dp2.make_train_step()
    for _ in range(2):
        ts, _ = step(ts, images, labels)
        resumed, _ = step2(resumed, images, labels)
    assert int(resumed.step) == 4
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_restore_latest_passthrough_when_empty(tmp_path, state):
    _, _, ts = state
    mgr = CheckpointManager(tmp_path / "none")
    assert mgr.restore_latest(ts) is ts
