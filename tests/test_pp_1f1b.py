"""1F1B pipeline schedule tests.

Load-bearing properties: (1) grad-exact parity with the sequential
single-device reference (same oracle as GPipe); (2) dropout works through
the schedule with per-(stage, micro) keys, gradients exact against a
hand-built single-device replica of the same masks; (3) the memory claim —
1F1B's compiled temp footprint stays bounded by S activation slots while
GPipe's grows with M.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.nn import Activation, Dense, Sequential
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import make_optimizer
from tpudml.parallel.pp import GPipe, OneFOneB

STAGES = 4
WIDTH = 32
BATCH = 16


def make_1f1b(n_microbatches=8, opt=None, block=None, rng_root=None):
    mesh = make_mesh(MeshConfig({"stage": STAGES}), jax.devices()[:STAGES])
    block = block or Sequential((Dense(WIDTH, WIDTH), Activation(jax.nn.relu)))
    return OneFOneB(
        block,
        n_microbatches=n_microbatches,
        mesh=mesh,
        optimizer=opt or make_optimizer("sgd", 0.05, momentum=0.9),
        prologue=Dense(16, WIDTH),
        epilogue=Dense(WIDTH, 10),
        rng_root=rng_root,
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, 16)).astype(np.float32)
    y = rng.integers(0, 10, size=(BATCH,)).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


@pytest.mark.parametrize("n_mb", [2, 4, 8, 16])
def test_1f1b_matches_single_device_update(batch, n_mb):
    """One 1F1B step == one single-device step on the full batch (same
    params, same optimizer): the schedule is invisible to the math."""
    x, y = batch
    pipe = make_1f1b(n_microbatches=n_mb)
    ts = pipe.create_state(seed_key(1))
    ref_params = jax.device_get(ts.params)

    ts2, m = pipe.make_train_step()(ts, x, y)

    opt = make_optimizer("sgd", 0.05, momentum=0.9)

    def ref_loss(p):
        return softmax_cross_entropy(pipe.sequential_forward(p, x), y)

    g = jax.grad(ref_loss)(ref_params)
    want_params, _ = opt.update(g, opt.init(ref_params), ref_params)

    np.testing.assert_allclose(
        float(m["loss"]), float(ref_loss(ref_params)), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(ts2.params), jax.tree.leaves(want_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )


def test_1f1b_training_descends(batch):
    x, y = batch
    pipe = make_1f1b()
    ts = pipe.create_state(seed_key(2))
    step = pipe.make_train_step()
    losses = []
    for _ in range(12):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_1f1b_dropout_grads_exact(batch):
    """Dropout through the pipeline: per-(stage, micro) keys fold
    (rng_root, step, stage, micro), and the backward recomputes the same
    masks — gradients must match a hand-built single-device replica that
    applies blocks micro-batch by micro-batch with identical keys."""
    from tpudml.nn.layers import Dropout

    x, y = batch
    M = 4
    rng_root = jax.random.key(7)
    block = Sequential(
        (Dense(WIDTH, WIDTH), Activation(jax.nn.relu), Dropout(0.5))
    )
    pipe = make_1f1b(n_microbatches=M, block=block, rng_root=rng_root)
    ts = pipe.create_state(seed_key(3))
    ref_params = jax.device_get(ts.params)
    ts2, m = pipe.make_train_step()(ts, x, y)

    # Single-device replica with the SAME key derivation.
    step_key = jax.random.fold_in(rng_root, 0)

    def replica_loss(params):
        mb = x.reshape(M, BATCH // M, 16)
        yb = y.reshape(M, BATCH // M)
        total = 0.0
        for mi in range(M):
            h = pipe.prologue(params["prologue"], mb[mi])
            for s in range(STAGES):
                key = jax.random.fold_in(jax.random.fold_in(step_key, s), mi)
                p_s = jax.tree.map(lambda p, s=s: p[s], params["stages"])
                h = block.apply(p_s, {}, h, train=True, rng=key)[0]
            logits = pipe.epilogue(params["epilogue"], h)
            total = total + softmax_cross_entropy(logits, yb[mi]) / M
        return total

    want_loss = float(replica_loss(ref_params))
    np.testing.assert_allclose(float(m["loss"]), want_loss, rtol=1e-5)

    g = jax.grad(replica_loss)(ref_params)
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    want_params, _ = opt.update(g, opt.init(ref_params), ref_params)
    for a, b in zip(jax.tree.leaves(ts2.params), jax.tree.leaves(want_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )


def test_gpipe_rejects_dropout_with_pointer():
    from tpudml.nn.layers import Dropout

    mesh = make_mesh(MeshConfig({"stage": STAGES}), jax.devices()[:STAGES])
    block = Sequential((Dense(WIDTH, WIDTH), Dropout(0.5)))
    pipe = GPipe(block, 4, mesh, make_optimizer("sgd", 0.1))
    with pytest.raises(ValueError, match="OneFOneB"):
        pipe.create_state(seed_key(0))


def _scan_residual_bytes(jaxpr) -> int:
    """Total bytes of per-tick stacked scan outputs (``ys``) anywhere in a
    jaxpr — exactly where scan-AD banks its per-tick residuals (each tick's
    saved activations become a ys output with leading dim = n_ticks).
    XLA:CPU's memory_analysis doesn't surface these (heap, not the static
    temp arena), so the accounting is structural."""
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            num_carry = eqn.params["num_carry"]
            for v in eqn.outvars[num_carry:]:
                total += v.aval.size * v.aval.dtype.itemsize
        for p in eqn.params.values():
            vals = p if isinstance(p, (tuple, list)) else (p,)
            for sub in vals:
                inner = getattr(sub, "jaxpr", sub)  # ClosedJaxpr → Jaxpr
                if hasattr(inner, "eqns"):
                    total += _scan_residual_bytes(inner)
    return total


def test_1f1b_memory_bounded_by_stages():
    """The memory claim, at FIXED micro-batch size (the deep-pipeline
    regime — more micros to shrink the bubble, same per-tick work): GPipe's
    scan-AD residuals hold every in-flight micro activation, so residual
    bytes grow with M; 1F1B's scan banks NO per-tick residuals at all —
    its only activation storage is the S-slot input buffer in the carry,
    so residual bytes are zero at any M."""
    from jax.sharding import PartitionSpec as P

    from tpudml.parallel.sharding import shard_map_fn
    from tpudml.train import TrainState

    MICRO = 4
    rng = np.random.default_rng(3)

    def residual_bytes(eng, n_mb):
        x = jnp.asarray(rng.normal(size=(MICRO * n_mb, 16)).astype(np.float32))
        y = jnp.asarray(rng.integers(0, 10, size=(MICRO * n_mb,)).astype(np.int32))
        ts = eng.create_state(seed_key(0))
        specs = TrainState(
            params=eng.param_specs(),
            model_state=P(),
            opt_state=eng.optimizer.init_spec(eng.param_specs()),
            step=P(),
        )
        fn = shard_map_fn(
            eng._spmd_step, eng.mesh,
            in_specs=(specs, P(), P()), out_specs=(specs, P()),
        )
        return _scan_residual_bytes(jax.make_jaxpr(fn)(ts, x, y).jaxpr)

    def gpipe_ctor(n_mb):
        mesh = make_mesh(MeshConfig({"stage": STAGES}), jax.devices()[:STAGES])
        return GPipe(
            Sequential((Dense(WIDTH, WIDTH), Activation(jax.nn.relu))),
            n_microbatches=n_mb, mesh=mesh,
            optimizer=make_optimizer("sgd", 0.05, momentum=0.9),
            prologue=Dense(16, WIDTH), epilogue=Dense(WIDTH, 10),
        )

    gpipe_4 = residual_bytes(gpipe_ctor(4), 4)
    gpipe_16 = residual_bytes(gpipe_ctor(16), 16)
    f1b_4 = residual_bytes(make_1f1b(4), 4)
    f1b_16 = residual_bytes(make_1f1b(16), 16)

    sizes = dict(gpipe_4=gpipe_4, gpipe_16=gpipe_16, f1b_4=f1b_4, f1b_16=f1b_16)
    assert gpipe_4 > 0, sizes          # GPipe banks per-tick residuals
    assert gpipe_16 > 2 * gpipe_4, sizes  # ... growing with the micro count
    assert f1b_4 == f1b_16 == 0, sizes  # 1F1B banks none at any M
