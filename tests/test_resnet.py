"""ResNet-18 north-star model: shapes, learning, bf16 path, DP parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.models import ResNet18
from tpudml.optim import make_optimizer
from tpudml.parallel.dp import DataParallel
from tpudml.train import TrainState, make_train_step


def small_resnet(**kw):
    # Narrow 2-stage variant: same code paths (stem, blocks, projection
    # shortcut, head), ~1000x fewer FLOPs than the full ResNet-18.
    from tpudml.models.resnet import ResNet

    return ResNet(stage_sizes=(1, 1), width=8, **kw)


def test_forward_shape():
    model = small_resnet()
    params, state = model.init(seed_key(0))
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    logits, new_state = model.apply(params, state, x, train=True)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32
    # BN running stats updated in train mode.
    diff = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), state, new_state
    )
    assert max(jax.tree.leaves(diff)) > 0


def test_resnet18_structure():
    model = ResNet18()
    # eval_shape: the structural check needs shapes only — materializing
    # 11M params eagerly on the 1-core CPU box cost ~12 s of pure init.
    params, _ = jax.eval_shape(model.init, seed_key(0))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    # Canonical CIFAR ResNet-18 parameter count ~11.17M.
    assert 11_000_000 < n_params < 11_300_000


def test_bf16_compute_path():
    model = small_resnet(compute_dtype=jnp.bfloat16)
    params, state = model.init(seed_key(0))
    # Params stay float32 (master copy).
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(params))
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    logits, _ = model.apply(params, state, x, train=False)
    assert logits.dtype == jnp.float32
    # bf16 and f32 paths agree loosely.
    ref, _ = small_resnet().apply(params, state, x, train=False)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=0.15)


def test_learns_synthetic_cifar():
    model = small_resnet()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    images, labels = synthetic_classification(128, (32, 32, 3), 10, seed=0)
    images, labels = jnp.asarray(images), jnp.asarray(labels)
    step = make_train_step(model, opt)
    ts = TrainState.create(model, opt, seed_key(0))
    # The step donates its input state — always rebind ts.
    ts, m0 = step(ts, images, labels)
    for _ in range(15):
        ts, m = step(ts, images, labels)
    assert float(m["loss"]) < float(m0["loss"])


def test_dp_resnet_runs():
    mesh = make_mesh(MeshConfig(axes={"data": 4}), jax.devices()[:4])
    model = small_resnet()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    dp = DataParallel(model, opt, mesh)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    images, labels = synthetic_classification(32, (32, 32, 3), 10, seed=0)
    ts, metrics = step(ts, images, labels)
    assert np.isfinite(float(metrics["loss"]))
    assert int(ts.step) == 1


def small_bottleneck_resnet(**kw):
    from tpudml.models.resnet import ResNet

    return ResNet(stage_sizes=(1, 1), width=8, block="bottleneck", **kw)


def test_bottleneck_forward_and_projection():
    """Bottleneck path: 1x1-3x3-1x1 with x4 expansion, projection shortcut
    on every stage entry, stride-2 downsampling in stage 1."""
    model = small_bottleneck_resnet()
    params, state = model.init(seed_key(0))
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    logits, new_state = model.apply(params, state, x, train=True)
    assert logits.shape == (2, 10)
    assert model.feature_dim == 8 * 2 * 4  # top width x EXPANSION
    # First block must carry a projection (8 -> 32 channels).
    assert "proj" in params["block0"]


def test_resnet50_structure():  # eval_shape: milliseconds, fast-suite ok
    from tpudml.models import ResNet50

    model = ResNet50()
    params, _ = jax.eval_shape(model.init, seed_key(0))  # shapes only
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    # Canonical ResNet-50 trunk ~23.5M (10-class head).
    assert 23_300_000 < n_params < 23_800_000


def test_bottleneck_learns_and_matches_dp():
    """Narrow bottleneck net: descends single-device and matches DP over
    the 8-way mesh step for step (same oracle as ResNet-18)."""
    images, labels = synthetic_classification(32, (32, 32, 3), 10, seed=4)
    images, labels = jnp.asarray(images), jnp.asarray(labels)
    model = small_bottleneck_resnet()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)

    ts = TrainState.create(model, opt, seed_key(1))
    step = make_train_step(model, opt)
    single_losses = []
    for _ in range(4):
        ts, m = step(ts, images, labels)
        single_losses.append(float(m["loss"]))
    assert single_losses[-1] < single_losses[0]

    mesh = make_mesh(MeshConfig({"data": 8}))
    dp = DataParallel(model, opt, mesh)
    ts_dp = dp.create_state(seed_key(1))
    dp_step = dp.make_train_step()
    for i in range(4):
        ts_dp, m = dp_step(ts_dp, images, labels)
        # Loose tolerance: BN normalizes each replica's 4-sample shard
        # locally (vs the single device's full 32), so the trajectories
        # drift slightly — same caveat as the ResNet-18 parity test.
        np.testing.assert_allclose(float(m["loss"]), single_losses[i], rtol=8e-3)
