"""High-level Model API tests (MindSpore-track parity, SURVEY.md §3.5)."""

import io
from contextlib import redirect_stdout

import jax
import numpy as np
import pytest

from tpudml.api import LossMonitor, Model
from tpudml.data import DataLoader
from tpudml.data.datasets import ArrayDataset, synthetic_classification
from tpudml.models import ForwardMLP
from tpudml.optim import make_optimizer


def _dataset(n=128, seed=0):
    imgs, labels = synthetic_classification(n, (28, 28, 1), 10, seed=seed, proto_seed=9)
    return ArrayDataset(imgs, labels)


def test_train_learns_and_eval_reports():
    """Deflake note (long-standing tier-1 failure, fixed at PR 14): at
    adam lr=1e-3 this smoke run sat on the edge of convergence — 80 steps
    is barely enough for the 784→512→…→10 MLP, and whether it cleared
    0.95 depended on environment-specific float reassociation (XLA
    device-count/threading config); in the suite's environment it
    deterministically plateaued at ~0.45, which only LOOKED random across
    machines. lr=3e-3 converges decisively everywhere probed (≥0.99 with
    and without the 8-virtual-device flag) — the Model-facade train/eval
    contract this test is actually about is unchanged."""
    model = Model(
        ForwardMLP(), optimizer=make_optimizer("adam", 3e-3), metrics={"Accuracy"}
    )
    loader = DataLoader(_dataset(256), 32)
    model.train(10, loader)
    train_results = model.eval(loader)
    held_out = model.eval(DataLoader(_dataset(seed=1), 32, drop_remainder=False))
    assert set(held_out) == {"Accuracy"}
    assert train_results["Accuracy"] > 0.95
    assert held_out["Accuracy"] > 0.75
    assert int(model.state.step) == 10 * 8


def test_loss_monitor_prints():
    model = Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.01))
    loader = DataLoader(_dataset(64), 32)
    buf = io.StringIO()
    with redirect_stdout(buf):
        model.train(1, loader, callbacks=[LossMonitor(1)])
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 2  # one per step
    assert lines[0].startswith("step: 1, loss is ")


def test_sink_and_eager_modes_match():
    """dataset_sink_mode=False is the same math without jit."""
    loader = DataLoader(_dataset(64), 32)
    sink = Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.05), seed=3)
    eager = Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.05), seed=3)
    sink.train(2, loader, dataset_sink_mode=True)
    eager.train(2, loader, dataset_sink_mode=False)
    for a, b in zip(
        jax.tree.leaves(sink.state.params), jax.tree.leaves(eager.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_mesh_auto_parallel_matches_single_device():
    """Model(mesh=...) trains DataParallel under the same facade — the
    MindSpore auto-parallel analogue — and matches the single-device
    trajectory."""
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh

    loader = DataLoader(_dataset(128), 32)
    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    auto = Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.05), seed=7,
                 mesh=mesh)
    single = Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.05), seed=7)
    auto.train(2, loader)
    single.train(2, loader)
    for a, b in zip(
        jax.tree.leaves(auto.state.params), jax.tree.leaves(single.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
    with pytest.raises(ValueError, match="eager mode"):
        auto.train(1, loader, dataset_sink_mode=False)
    with pytest.raises(ValueError, match="not divisible"):
        auto.train(1, DataLoader(_dataset(60), 30))  # 30 % 4 != 0


def test_predict_shape():
    model = Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.01))
    x = np.zeros((5, 28, 28, 1), np.float32)
    assert model.predict(x).shape == (5, 10)


def test_validation():
    with pytest.raises(ValueError, match="optimizer"):
        Model(ForwardMLP())
    with pytest.raises(ValueError, match="unknown metrics"):
        Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.01), metrics={"f1"})


def test_task1_mlp_entrypoint():
    from tasks.task1_mlp import main

    metrics = main(
        ["--dataset", "synthetic", "--epochs", "2", "--optimizer", "adam",
         "--lr", "0.002", "--log_every", "0", "--batch_size", "64"]
    )
    assert metrics["test_accuracy"] > 0.8