"""Test harness: simulated 8-device CPU mesh.

The TPU-native analogue of the reference's multi-node-without-a-cluster
story (mp.spawn / docker-compose, SURVEY.md §4): XLA's forced host-platform
device count gives 8 fake devices on CPU, so every sharding/collective path
is exercised in CI without TPU hardware.

Provisioning logic lives in ``__graft_entry__._provision_cpu_mesh`` (the
driver hook needs the identical dance, and two copies would drift); it
defers the jax import, so it is safe to call before any backend exists and
works even when a site hook latched JAX_PLATFORMS at interpreter startup.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _provision_cpu_mesh  # noqa: E402

_provision_cpu_mesh(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
