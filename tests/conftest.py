"""Test harness: simulated 8-device CPU mesh.

The TPU-native analogue of the reference's multi-node-without-a-cluster
story (mp.spawn / docker-compose, SURVEY.md §4): XLA's forced host-platform
device count gives 8 fake devices on CPU, so every sharding/collective path
is exercised in CI without TPU hardware.

Provisioning logic lives in ``__graft_entry__._provision_cpu_mesh`` (the
driver hook needs the identical dance, and two copies would drift); it
defers the jax import, so it is safe to call before any backend exists and
works even when a site hook latched JAX_PLATFORMS at interpreter startup.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _provision_cpu_mesh  # noqa: E402

_provision_cpu_mesh(8)

# Persistent XLA compilation cache (gitignored): the suite's cost on this
# 1-core box is dominated by CPU XLA compiles, most of which repeat
# identically across runs. The first (cold) run pays full compile; repeat
# runs — the signal loop a developer actually sits in — reuse cached
# executables. Numbers in pytest.ini.
#
# OPT-IN (TPUDML_TEST_CACHE=1): on jax 0.4.37/jaxlib 0.4.36 the CPU
# deserialization path of cached executables corrupts the heap — the
# suite dies mid-run with munmap_chunk()/segfaults at random points
# after a few cache hits (reproducer: pytest tests/test_api.py
# tests/test_checkpoint.py with the cache on). Correct-but-slow beats
# fast-but-crashing as the default; flip it back on when the pinned
# jaxlib moves past the bug.
import jax  # noqa: E402

if os.environ.get("TPUDML_TEST_CACHE"):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     ".jax_test_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
