"""Test harness: simulated 8-device CPU mesh.

The TPU-native analogue of the reference's multi-node-without-a-cluster
story (mp.spawn / docker-compose, SURVEY.md §4): XLA's forced host-platform
device count gives 8 fake devices on CPU, so every sharding/collective path
is exercised in CI without TPU hardware.

Note: platform selection uses ``jax.config.update`` rather than the
JAX_PLATFORMS env var — in environments where a site hook imports jax at
interpreter startup (e.g. a preloaded TPU PJRT plugin), the env var is
already latched by the time conftest runs; the config API still works as
long as no backend has been initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
