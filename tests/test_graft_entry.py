"""Driver-hook regression tests.

Round 1 shipped ``__graft_entry__.dryrun_multichip`` broken under the driver
(one real chip visible, no virtual-mesh provisioning → ``mesh wants 8
devices, have 1``) precisely because nothing in tests/ exercised the hook.
These tests run it the way the driver does: a fresh subprocess with NO
XLA_FLAGS / JAX_PLATFORMS in the environment, so the hook must provision
the virtual CPU mesh itself. entry() and dryrun share ONE subprocess
(entry first — provisioning clears backends, which would invalidate
entry()'s outputs the other way around); r2's two separate ~40 s
subprocess compiles were half the graft-entry wall clock.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_PLATFORM_NAME")
    }
    env["PYTHONPATH"] = REPO
    return env


@pytest.mark.slow
def test_entry_and_dryrun_from_clean_environment():
    """entry() must jit+run, then dryrun_multichip(8) must self-provision
    — one subprocess, driver conditions. Only a 2-regime subset runs here
    (the subprocess's job is the clean-env PROVISIONING path; compiling
    all 16 regimes cost 98 s). Full-regime coverage lives in the
    driver's round-end dryrun and in the per-engine pytest parity tests
    — not in any pytest dryrun invocation."""
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            (
                "import jax, __graft_entry__;"
                "fn, args = __graft_entry__.entry();"
                "out = jax.jit(fn)(*args);"
                "jax.block_until_ready(out);"
                "print('entry ok', out.shape);"
                "__graft_entry__.dryrun_multichip(8, regimes=('dp', 'hetero1f1b'))"
            ),
        ],
        cwd=REPO,
        env=_clean_env(),
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "entry ok" in proc.stdout
    for regime in (
        "dp ok",
        "hetero 1f1b pipeline ok",
    ):
        assert regime in proc.stdout, f"missing regime '{regime}':\n{proc.stdout}"


def test_dryrun_in_process_after_backend_init():
    """The latched-backend path: jax already initialized (conftest's 8-CPU
    mesh counts) must not break provisioning for n <= device_count. The
    regimes filter keeps this to one compile — full-regime coverage is
    the driver's round-end dryrun + the per-engine parity tests. dpzero1
    runs the same DataParallel engine as the old "dp" pick PLUS the
    ZeRO-1 sharded update, so one regime covers both paths."""
    import jax

    assert jax.device_count() >= 4
    import __graft_entry__

    __graft_entry__.dryrun_multichip(4, regimes=("dpzero1",))
