"""Integration: task3 division strategies on the simulated 8-device mesh.

Pins the semantic difference the reference lab is about (sections/
task3.tex:19-24): random partition = disjoint + jointly exhaustive shards;
random sampling = independent per-rank draws with cross-rank overlap. Both
must train (SURVEY.md §4 integration tier).
"""

import numpy as np
import pytest

import tasks.task3 as task3
from tpudml.data.sampler import RandomPartitionSampler, RandomSamplingSampler


def test_partition_disjoint_and_exhaustive():
    n, world = 1000, 8
    shards = [
        np.fromiter(iter(RandomPartitionSampler(n, world, r, seed=5)), dtype=np.int64)
        for r in range(world)
    ]
    union = np.concatenate(shards)
    # ceil(1000/8)=125 per shard; 1000 seen examples = whole dataset
    # (padding wraps the first 0 extras here since 1000 % 8 == 0).
    assert all(len(s) == 125 for s in shards)
    assert len(np.unique(union)) == n


def test_sampling_overlaps_across_ranks():
    n, world = 1000, 8
    shards = [
        np.fromiter(iter(RandomSamplingSampler(n, world, r, seed=5)), dtype=np.int64)
        for r in range(world)
    ]
    union = np.concatenate(shards)
    # Independent draws: with 8×125 of 1000, overlap is near-certain and
    # coverage incomplete.
    assert len(np.unique(union)) < n


def test_set_epoch_reshuffles_but_epoch_is_stable():
    s = RandomPartitionSampler(100, 4, 1, seed=9)
    e0 = np.fromiter(iter(s), dtype=np.int64)
    e0_again = np.fromiter(iter(s), dtype=np.int64)
    s.set_epoch(1)
    e1 = np.fromiter(iter(s), dtype=np.int64)
    np.testing.assert_array_equal(e0, e0_again)
    assert not np.array_equal(e0, e1)


@pytest.mark.parametrize("division", ["partition", "sampling"])
def test_task3_end_to_end(tmp_path, division):
    """Deflake note (long-standing tier-1 failure, fixed at PR 14): the
    original smoke config (lr=0.1 + momentum=0.9, global batch 64) sat
    PAST LeNet's stability edge on the synthetic set — the partition run
    reproducibly diverged to chance accuracy (~10%) in the suite's
    8-device environment, while float-reassociation differences under
    other XLA device-count/threading configs let it sometimes converge,
    which made it LOOK random across machines. lr=0.05 steps back inside
    the stability region: ≥99% test accuracy in every device-count
    config probed (1 and 8 virtual devices, 3 seeds), same margin for
    both division strategies — the sampler semantics this test is
    actually about."""
    cfg = task3.reference_defaults()
    cfg.epochs = 3
    cfg.lr = 0.05  # synthetic smoke run (ref lr 0.001 is MNIST-scaled)
    cfg.momentum = 0.9
    cfg.log_every = 0
    cfg.log_dir = str(tmp_path / "logs")
    cfg.data.dataset = "synthetic"
    cfg.data.batch_size = 8
    cfg.data.division = division
    metrics = task3.run(cfg)
    assert metrics["world"] == 8
    assert metrics["test_accuracy"] > 0.5
