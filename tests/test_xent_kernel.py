"""Fused linear-cross-entropy kernel (tpudml/ops/xent_kernel.py).

Parity oracle: the XLA reference loss over materialized logits. The
Pallas kernels run under the interpreter on CPU (as in test_flash);
compiled-kernel parity on the real chip was verified at
[8192, 512] @ [512, 32768] bf16 (loss diff 4e-6, grad diff <4e-6 — see
BASELINE.md round-3 notes).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.nn.losses import softmax_cross_entropy
from tpudml.ops.xent_kernel import linear_cross_entropy


def ref(x, w, y, b=None):
    logits = x @ w
    if b is not None:
        logits = logits + b
    return softmax_cross_entropy(logits.astype(jnp.float32), y)


@pytest.mark.parametrize(
    "n,d,v,bn,bv",
    [
        (16, 32, 64, 8, 64),
        (24, 16, 100, 8, 128),  # vocab padded to the tile multiple
        (8, 8, 16, 16, 128),    # blocks capped at the padded sizes
    ],
)
@pytest.mark.parametrize("bias", [False, True])
@pytest.mark.parametrize("save_s", [False, True])
def test_matches_reference_loss_and_grads(n, d, v, bn, bv, bias, save_s):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, d), jnp.float32)
    w = jax.random.normal(key, (d, v), jnp.float32) * 0.1
    b = jax.random.normal(key, (v,), jnp.float32) * 0.1 if bias else None
    y = jax.random.randint(key, (n,), 0, v)

    fused = lambda x, w, b: linear_cross_entropy(
        x, w, y, b, block_n=bn, block_v=bv, interpret=True, save_s=save_s
    )
    np.testing.assert_allclose(
        float(fused(x, w, b)), float(ref(x, w, y, b)), rtol=1e-6, atol=1e-6
    )
    argnums = (0, 1, 2) if bias else (0, 1)
    got = jax.grad(fused, argnums=argnums)(x, w, b)
    want = jax.grad(lambda x, w, b: ref(x, w, y, b), argnums=argnums)(x, w, b)
    for g, r in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-5, atol=1e-6)


def test_batched_shape_flattening_and_fallback():
    """[..., d] inputs flatten; non-TPU default dispatch = XLA reference."""
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (2, 8, 16), jnp.float32)
    w = jax.random.normal(key, (16, 32), jnp.float32) * 0.1
    y = jax.random.randint(key, (2, 8), 0, 32)
    got = linear_cross_entropy(x, w, y)  # CPU → XLA fallback path
    want = ref(x.reshape(-1, 16), w, y.reshape(-1))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)
    with pytest.raises(ValueError, match="labels"):
        linear_cross_entropy(x, w, y[:, :4])


def test_fused_lm_train_step_learns():
    """make_lm_fused_train_step on a tiny LM: loss decreases and the step
    contract (donated TrainState, loss-only metrics) holds."""
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM
    from tpudml.optim import make_optimizer
    from tpudml.train import TrainState, make_lm_fused_train_step

    model = TransformerLM(vocab_size=32, embed_dim=32, num_heads=4,
                          num_layers=1, max_len=32)
    opt = make_optimizer("adam", 1e-2)
    step = make_lm_fused_train_step(model, opt)
    ts = TrainState.create(model, opt, seed_key(0))
    seqs = jnp.asarray(synthetic_lm(8, 32, 32, seed=0))
    x, y = seqs[:, :-1], seqs[:, 1:]
    losses = []
    for _ in range(40):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.5 < losses[0]
    assert int(ts.step) == 40


def test_save_s_out_of_range_labels_and_padded_rows():
    """The save-s backward must keep the padded-row/-column semantics of
    the lean backward: zero dlogits on padded rows (lse re-padded +inf),
    no pull-up for labels landing in [V, V_pad)."""
    key = jax.random.PRNGKey(3)
    n, d, v = 10, 16, 100  # rows pad to 16, vocab pads to 128
    x = jax.random.normal(key, (n, d), jnp.float32)
    w = jax.random.normal(key, (d, v), jnp.float32) * 0.1
    y = jnp.array([0, 5, 99, 100, 110, 127, 3000, -7, 1, 2], jnp.int32)
    args = dict(block_n=16, block_v=128, interpret=True)
    loss_s = linear_cross_entropy(x, w, y, save_s=True, **args)
    loss_l = linear_cross_entropy(x, w, y, save_s=False, **args)
    np.testing.assert_allclose(float(loss_s), float(loss_l), rtol=1e-6)
    for i in (0, 1):
        gs = jax.grad(
            lambda x, w: linear_cross_entropy(x, w, y, save_s=True, **args),
            argnums=i,
        )(x, w)
        gl = jax.grad(
            lambda x, w: linear_cross_entropy(x, w, y, save_s=False, **args),
            argnums=i,
        )(x, w)
        assert np.all(np.isfinite(np.asarray(gs)))
        np.testing.assert_allclose(
            np.asarray(gs), np.asarray(gl), rtol=1e-6, atol=1e-7
        )


def test_out_of_range_labels_give_lse_loss_not_inf():
    """Labels in [V, V_pad) land on PADDED columns; the pick must exclude
    them (loss = lse, no pull-up, same as any out-of-range id) instead of
    picking the padded column's -inf (which would poison the loss)."""
    key = jax.random.PRNGKey(2)
    n, d, v = 8, 16, 100  # v pads to 128
    x = jax.random.normal(key, (n, d), jnp.float32)
    w = jax.random.normal(key, (d, v), jnp.float32) * 0.1
    y = jnp.array([0, 5, 99, 100, 110, 127, 3000, -7], jnp.int32)
    loss = linear_cross_entropy(x, w, y, block_n=8, block_v=128, interpret=True)
    assert np.isfinite(float(loss))
    g = jax.grad(
        lambda x: linear_cross_entropy(x, w, y, block_n=8, block_v=128,
                                       interpret=True)
    )(x)
    assert np.all(np.isfinite(np.asarray(g)))
    # The non-TPU fallback dispatch must implement the SAME semantics
    # (loss = lse, no pull-up for invalid ids — NOT edge-class clamping).
    fallback = linear_cross_entropy(x, w, y)  # CPU default dispatch
    np.testing.assert_allclose(float(fallback), float(loss), rtol=1e-6)
