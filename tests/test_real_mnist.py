"""Real-MNIST integration (VERDICT r2 "what's missing" item 2).

Every recorded accuracy pin in this environment is on the deterministic
synthetic set because no MNIST IDX files ship with the image and egress
is blocked. These tests fire THE MOMENT real files are present, holding
the framework to the reference's actual bar: LeNet-class ≳98% on the
real test set (codes/task1/pytorch/model.py:93-100, checking.tex:5-9).

Fetch-and-verify path (documented in docs/DEPLOY.md): place the four IDX
files (raw or .gz) under ``./data`` —

    train-images-idx3-ubyte[.gz]   train-labels-idx1-ubyte[.gz]
    t10k-images-idx3-ubyte[.gz]    t10k-labels-idx1-ubyte[.gz]

e.g. ``python -c "import urllib.request as u; [u.urlretrieve(
'https://storage.googleapis.com/cvdf-datasets/mnist/'+f, 'data/'+f)
for f in [...]]"`` on a connected machine, then rerun the suite; these
tests un-skip automatically.
"""

import os

import numpy as np
import pytest

DATA_DIR = os.environ.get("TPUDML_DATA_DIR", "./data")


def _has_real_mnist() -> bool:
    from tpudml.data.datasets import MNIST_FILES  # candidate names

    def present(key):
        return any(
            os.path.exists(os.path.join(DATA_DIR, name + suffix))
            for name in MNIST_FILES[key]
            for suffix in ("", ".gz")
        )

    try:
        return all(present(k) for k in MNIST_FILES)
    except Exception:
        return False


pytestmark = pytest.mark.skipif(
    not _has_real_mnist(),
    reason="real MNIST IDX files not present under ./data (synthetic "
    "pins cover this environment; see module docstring for the fetch path)",
)


def test_real_mnist_loads_with_reference_statistics():
    from tpudml.data.datasets import load_mnist

    train = load_mnist(DATA_DIR, "train", synthetic_fallback=False)
    test = load_mnist(DATA_DIR, "test", synthetic_fallback=False)
    assert len(train) == 60000 and len(test) == 10000
    x, y = train[np.arange(256)]
    assert x.shape == (256, 28, 28, 1) and x.dtype == np.float32
    assert 0.0 <= float(x.min()) and float(x.max()) <= 1.0
    assert set(np.unique(y)) <= set(range(10))


def test_real_mnist_task1_reaches_reference_accuracy():
    """The reference's implied acceptance bar: ≳98% test accuracy with
    the LeNet-class CNN (checking.tex:5-9). One adam epoch reaches it."""
    from tasks.task1 import reference_defaults, run

    cfg = reference_defaults()
    cfg.data.dataset = "mnist"
    cfg.data.data_dir = DATA_DIR
    cfg.data.synthetic_fallback = False
    cfg.epochs = 2
    cfg.optimizer = "adam"
    cfg.lr = 1e-3
    cfg.log_every = 0
    metrics = run(cfg)
    assert metrics["test_accuracy"] >= 0.98
