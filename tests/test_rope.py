"""Rotary position embedding (RoPE) tests.

Load-bearing properties: rotation preserves norms, attention scores
depend only on RELATIVE position (shift invariance — the property that
makes sharded-sequence offsets compose), the rope LM drops the learned
pos table, and ring-CP rope matches single-device rope exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import TransformerLM
from tpudml.nn.attention import rotary_embedding
from tpudml.optim import make_optimizer

B, T, H, D = 2, 16, 4, 8


@pytest.fixture(scope="module")
def x():
    return jnp.asarray(
        np.random.default_rng(0).normal(size=(B, T, H, D)).astype(np.float32)
    )


def test_rope_preserves_norm(x):
    rot = rotary_embedding(x, jnp.arange(T))
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(rot), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )


def test_rope_scores_are_shift_invariant(x):
    """q·k after RoPE depends only on relative positions: shifting ALL
    positions by a constant leaves every score unchanged — the exact
    property that lets sharded sequence offsets compose."""
    q = x
    k = jnp.roll(x, 1, axis=0)
    scores = lambda off: jnp.einsum(
        "bqhd,bkhd->bhqk",
        rotary_embedding(q, off + jnp.arange(T)),
        rotary_embedding(k, off + jnp.arange(T)),
    )
    np.testing.assert_allclose(
        np.asarray(scores(0)), np.asarray(scores(137)), rtol=1e-4, atol=1e-5
    )
    # But relative changes DO change scores.
    shifted = jnp.einsum(
        "bqhd,bkhd->bhqk",
        rotary_embedding(q, jnp.arange(T)),
        rotary_embedding(k, 3 + jnp.arange(T)),
    )
    assert not np.allclose(np.asarray(scores(0)), np.asarray(shifted), atol=1e-3)


def test_rope_lm_has_no_pos_table_and_trains():
    lm = TransformerLM(vocab_size=32, embed_dim=32, num_heads=4, num_layers=1,
                       max_len=T, rope=True)
    params, _ = lm.init(seed_key(0))
    assert "pos_embed" not in params
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, size=(B, T)).astype(np.int32)
    )
    from tpudml.nn.losses import softmax_cross_entropy

    g = jax.grad(lambda p: softmax_cross_entropy(lm(p, tokens), tokens))(params)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in jax.tree.leaves(g))


def test_rope_ring_cp_matches_single_device():
    from tpudml.parallel.cp import ContextParallel

    mesh = make_mesh(MeshConfig({"seq": 4}), jax.devices()[:4])
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, size=(B, T)).astype(np.int32)
    )
    base = dict(vocab_size=32, embed_dim=32, num_heads=4, num_layers=2,
                max_len=T, rope=True)
    params, _ = TransformerLM(**base).init(seed_key(3))
    want = TransformerLM(**base)(params, tokens)
    cp = ContextParallel(
        TransformerLM(**base, impl="ring", seq_sharded=True),
        make_optimizer("sgd", 0.1), mesh,
    )
    got = cp.make_forward()(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=1e-5)
