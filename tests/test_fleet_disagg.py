"""Disaggregated prefill/decode handoff + int8 weight quantization.

Load-bearing properties: a decode replica that adopts a prefill
replica's KV handoff serves BYTE-identical tokens to a single engine
that prefilled locally (the pages hold bitwise-identical K/V, published
under the same content hash); a vandalized handoff is rejected by the
checkpoint store's CRC and the request transparently falls back to
local prefill (same tokens, zero shared pages); int8 weight
quantization is pinned to the ``_sim`` oracle bitwise, its
reconstruction error is bounded by half a quantization step per
channel, and the cost model prices the smaller param-byte term through
ONE code path (``_params_bytes(itemsize=...)``).
"""

import jax
import numpy as np
import pytest

from tpudml.models import TransformerLM
from tpudml.resilience import vandalize
from tpudml.serve import (
    DecodeCostModel,
    Request,
    ServeCompositionError,
    ServeConfig,
    ServingEngine,
    SLOConfig,
)
from tpudml.serve.fleet.disagg import adopt_handoff, write_handoff
from tpudml.serve.fleet.quant import (
    dequantize_params,
    quantize_params,
    quantized_param_bytes,
    sim_quantize_params,
)

V = 48


def _model():
    return TransformerLM(vocab_size=V, embed_dim=32, num_heads=4,
                         num_kv_heads=2, num_layers=2, max_len=64)


@pytest.fixture(scope="module")
def setup():
    model = _model()
    params, state = model.init(jax.random.key(0))
    return model, params, state


def _paged_cfg(**kw):
    base = dict(slots=2, max_len=64, prefill_chunk=8,
                cache_layout="paged", page_size=8, prefix_sharing=True)
    base.update(kw)
    return ServeConfig(**base)


def _serve_one(model, params, cfg, prompt, n_new=6):
    eng = ServingEngine(model, params, cfg)
    report = eng.run([Request(rid=0, prompt=prompt, max_new_tokens=n_new)])
    return eng, report.requests[0]


# ------------------------------------------------------ KV handoff


def test_handoff_adopt_greedy_parity(setup, tmp_path):
    """Adopted pages ≡ local prefill: same tokens, and the adopting
    engine's admit maps the shipped pages instead of prefilling them."""
    model, params, _ = setup
    cfg = _paged_cfg()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, V, size=20).astype(np.int32)

    info = write_handoff(model, params, cfg, prompt, tmp_path)
    # 20-token prompt, first decode write at position 19 → pages 0..1
    # (8-token pages) end strictly before it; page 2 is decode-dirty.
    assert info["n_pages"] == 2
    assert info["covered_tokens"] == 16

    # Reference: an engine with NO handoff prefills everything locally.
    _, ref = _serve_one(model, params, cfg, prompt)
    assert ref.shared_pages == 0

    eng = ServingEngine(model, params, cfg)
    adopted = adopt_handoff(eng, tmp_path)
    assert adopted == 2
    report = eng.run([Request(rid=0, prompt=prompt, max_new_tokens=6)])
    st = report.requests[0]
    assert st.tokens == ref.tokens  # byte-exact greedy parity
    assert st.shared_pages == 2  # served FROM the handoff, not prefill


def test_vandalized_handoff_rejected_with_fallback(setup, tmp_path):
    """CRC rollback: truncating the handoff payload makes adopt return
    0 (strict=True raises instead), and the request falls back to local
    prefill with identical tokens — correctness never depended on the
    handoff, only prefill work did."""
    from tpudml.checkpoint.store import CheckpointCorruptError

    model, params, _ = setup
    cfg = _paged_cfg()
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, V, size=20).astype(np.int32)
    write_handoff(model, params, cfg, prompt, tmp_path)
    _, ref = _serve_one(model, params, cfg, prompt)

    vandalize(tmp_path, "truncate")

    eng = ServingEngine(model, params, cfg)
    with pytest.raises(CheckpointCorruptError):
        adopt_handoff(eng, tmp_path, strict=True)
    assert adopt_handoff(eng, tmp_path) == 0  # quiet fallback path
    report = eng.run([Request(rid=0, prompt=prompt, max_new_tokens=6)])
    st = report.requests[0]
    assert st.tokens == ref.tokens
    assert st.shared_pages == 0  # nothing adopted, prefilled locally


def test_handoff_config_mismatch_raises(setup, tmp_path):
    """Wrong page size at adopt is a wiring bug, not a fault — always
    loud, even without strict."""
    model, params, _ = setup
    prompt = np.arange(20, dtype=np.int32) % V
    write_handoff(model, params, _paged_cfg(), prompt, tmp_path)
    eng = ServingEngine(model, params, _paged_cfg(page_size=16))
    with pytest.raises(ValueError, match="mismatch"):
        adopt_handoff(eng, tmp_path)


def test_handoff_requires_paged_sharing(setup, tmp_path):
    model, params, _ = setup
    dense = ServeConfig(slots=2, max_len=64, prefill_chunk=8)
    with pytest.raises(ValueError, match="prefix_sharing"):
        write_handoff(model, params, dense,
                      np.arange(20, dtype=np.int32), tmp_path)


def test_sub_page_prompt_hands_off_nothing(setup, tmp_path):
    """A prompt smaller than one page has no shareable prefix: n_pages
    is 0 and adopt is a no-op (decode falls back to local prefill)."""
    model, params, _ = setup
    cfg = _paged_cfg()
    info = write_handoff(model, params, cfg,
                         np.arange(5, dtype=np.int32), tmp_path)
    assert info["n_pages"] == 0
    eng = ServingEngine(model, params, cfg)
    assert adopt_handoff(eng, tmp_path) == 0


# ------------------------------------------------ int8 weight quant


def test_quant_matches_sim_oracle_bitwise(setup):
    """dequantize(quantize(p)) must equal the ``_sim`` oracle bitwise —
    the cache.py discipline: the real storage path and the f32-storage
    simulation are the same arithmetic."""
    _, params, _ = setup
    qparams, scales = quantize_params(params)
    deq = dequantize_params(qparams, scales)
    sim = sim_quantize_params(params)

    flat_d, _ = jax.tree_util.tree_flatten(deq)
    flat_s, _ = jax.tree_util.tree_flatten(sim)
    assert len(flat_d) == len(flat_s)
    for d, s in zip(flat_d, flat_s):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(s))


def test_quant_error_bounded_by_half_step(setup):
    """Per-output-channel absmax reconstruction error: |w − dq(q(w))|
    ≤ scale/2 elementwise on every 2-D kernel; non-kernel leaves pass
    through untouched (bitwise)."""
    _, params, _ = setup
    qparams, scales = quantize_params(params)
    deq = dequantize_params(qparams, scales)

    def walk(orig, dq, sc):
        for name in orig:
            o, d, s = orig[name], dq[name], sc[name]
            if isinstance(o, dict):
                walk(o, d, s)
            elif s is None:
                np.testing.assert_array_equal(np.asarray(o), np.asarray(d))
            else:
                o, d = np.asarray(o), np.asarray(d)
                bound = 0.5 * np.asarray(s)[None, :] + 1e-7
                assert np.all(np.abs(o - d) <= bound)

    walk(params, deq, scales)


def test_engine_weight_quant_real_equals_sim(setup):
    """An int8 engine and an int8_sim engine hold bitwise-identical
    decode params — the flag changes STORAGE, never arithmetic — and
    both serve exact token accounting."""
    model, params, _ = setup
    cfg_real = ServeConfig(slots=2, max_len=64, prefill_chunk=8,
                           weight_quant="int8")
    cfg_sim = ServeConfig(slots=2, max_len=64, prefill_chunk=8,
                          weight_quant="int8_sim")
    eng_real = ServingEngine(model, params, cfg_real)
    eng_sim = ServingEngine(model, params, cfg_sim)
    flat_r, _ = jax.tree_util.tree_flatten(eng_real.params)
    flat_s, _ = jax.tree_util.tree_flatten(eng_sim.params)
    for r, s in zip(flat_r, flat_s):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(s))
    assert eng_real.quantized_params is not None
    assert eng_sim.quantized_params is None

    rng = np.random.default_rng(2)
    prompt = rng.integers(0, V, size=12).astype(np.int32)
    rep = eng_real.run([Request(rid=0, prompt=prompt, max_new_tokens=6)])
    assert len(rep.requests[0].tokens) == 6


def test_engine_weight_quant_atol_parity(setup):
    """Quantized decode stays close to f32 decode where it matters: the
    forward logits of the dequantized params are atol-bounded against
    the exact params (the acceptance bound — token streams MAY differ
    at argmax ties, logits may not drift)."""
    model, params, state = setup
    deq = dequantize_params(*quantize_params(params))
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, V, size=(1, 12)).astype(np.int32)
    exact = np.asarray(model.apply(params, state, tokens)[0])
    quant = np.asarray(model.apply(deq, state, tokens)[0])
    assert np.max(np.abs(exact - quant)) < 0.15, (
        np.max(np.abs(exact - quant))
    )


def test_quantized_param_bytes(setup):
    """int8 storage is strictly smaller than f32 and dominated by the
    kernel leaves (1 byte/element + a per-channel f32 scale row)."""
    _, params, _ = setup
    qparams, scales = quantize_params(params)
    q_bytes = quantized_param_bytes(qparams, scales)
    f32_bytes = sum(
        np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(params)
    )
    assert q_bytes < f32_bytes / 2


def test_engine_rejects_unknown_weight_quant():
    with pytest.raises(ValueError, match="weight_quant"):
        ServeConfig(slots=2, max_len=64, prefill_chunk=8,
                    weight_quant="int4")


def test_tp_rejects_weight_quant(setup):
    """TP × weight_quant is a capability-table rejection
    (``serve_tp_weight_quant``): the TP engine shards the ORIGINAL
    params; serving dequantized weights under shard_map would silently
    serve different arithmetic per composition."""
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh

    model, params, _ = setup
    mesh = make_mesh(MeshConfig({"model": 2}), jax.devices()[:2])
    cfg = ServeConfig(slots=2, max_len=64, prefill_chunk=8,
                      weight_quant="int8")
    with pytest.raises(ServeCompositionError, match="weight_quant"):
        ServingEngine(model, params, cfg, mesh=mesh, axis_name="model")


# ------------------------------------------- cost-model param pricing


def test_params_bytes_single_code_path():
    """``_params_bytes`` is parameterized by itemsize — every dtype
    prices through the SAME element count, so the ratios are exact."""
    model = _model()
    f32 = DecodeCostModel._params_bytes(model, itemsize=4)
    bf16 = DecodeCostModel._params_bytes(model, itemsize=2)
    int8 = DecodeCostModel._params_bytes(model, itemsize=1)
    assert f32 == 2 * bf16 == 4 * int8


def test_cost_model_prices_weight_quant():
    """The fleet's placement honesty: an int8 replica's cost model
    carries exactly ¼ the param-byte term; the ``int8_sim`` oracle
    still prices as f32 (it STORES f32 — pricing it as int8 would be
    the dishonest-placement bug)."""
    model = _model()
    slo = SLOConfig(tpot_budget_s=0.5)

    def cm(wq):
        cfg = ServeConfig(slots=2, max_len=64, prefill_chunk=8,
                          weight_quant=wq)
        return DecodeCostModel(model, cfg, slo)

    assert cm(None).params_bytes == cm("int8").params_bytes * 4
    assert cm("int8_sim").params_bytes == cm(None).params_bytes
    # Fewer param bytes → cheaper predicted step at equal occupancy.
    assert cm("int8").step_seconds(1) < cm(None).step_seconds(1)
