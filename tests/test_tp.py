"""Tensor-parallel (Megatron-style GSPMD rules) tests.

Load-bearing properties: TP shardings actually shard (params are placed
on the model axis), the math is unchanged (training trajectory matches
single-device), and TP composes with DP on a 2-D mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import TransformerLM
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import make_optimizer
from tpudml.parallel.mp import GSPMDParallel, apply_rules, tensor_parallel_rules

B, T, V = 2, 16, 32
BASE = dict(vocab_size=V, embed_dim=32, num_heads=4, num_layers=2, max_len=T)


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, V, size=(B, T + 1)).astype(np.int32))
    return tokens[:, :-1], tokens[:, 1:]


def test_rules_shard_the_right_dims():
    model = TransformerLM(**BASE)
    params, _ = model.init(seed_key(0))
    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    specs = apply_rules(tensor_parallel_rules("model"), params, mesh)
    b0 = specs["block0"]
    for n in ("q", "k", "v"):
        assert b0["attn"][n]["kernel"] == P(None, "model")
        assert b0["attn"][n]["bias"] == P("model")
    assert b0["attn"]["out"]["kernel"] == P("model", None)
    assert b0["fc1"]["kernel"] == P(None, "model")
    assert b0["fc2"]["kernel"] == P("model", None)
    assert specs["tok_embed"] == P("model", None)
    assert specs["pos_embed"] == P()
    assert specs["head"]["kernel"] == P(None, "model")
    assert b0["ln1"]["scale"] == P()


def test_tp_training_matches_single_device(batch):
    x, y = batch
    opt = make_optimizer("sgd", 0.1)
    model = TransformerLM(**BASE)
    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    tp = GSPMDParallel(
        model, opt, mesh, rule=tensor_parallel_rules("model"), axis_name="model"
    )
    ts = tp.create_state(seed_key(1))
    # Params really live sharded on the model axis.
    q_kernel = ts.params["block0"]["attn"]["q"]["kernel"]
    assert q_kernel.sharding.spec == P(None, "model")

    ref_params = jax.device_get(ts.params)
    ref_opt = opt.init(ref_params)
    ref_loss = lambda p: softmax_cross_entropy(model(p, x), y)
    step = tp.make_train_step()
    losses = []
    for _ in range(3):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_opt = opt.update(g, ref_opt, ref_params)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)
    assert losses[-1] < losses[0]


def test_tp_composes_with_dp(batch):
    x, y = batch
    opt = make_optimizer("sgd", 0.1)
    model = TransformerLM(**BASE)
    mesh = make_mesh(MeshConfig({"data": 2, "model": 4}), jax.devices())
    tp = GSPMDParallel(
        model, opt, mesh,
        rule=tensor_parallel_rules("model"),
        axis_name="model",
        batch_axis="data",
    )
    ts = tp.create_state(seed_key(2))
    step = tp.make_train_step()
    ts, m = step(ts, x, y)
    assert int(ts.step) == 1 and np.isfinite(float(m["loss"]))

    ref_model = TransformerLM(**BASE)
    ref_params = jax.device_get(ts.params)  # after 1 step
    # One more step on both paths must stay in lockstep.
    ref_loss = lambda p: softmax_cross_entropy(ref_model(p, x), y)
    g = jax.grad(ref_loss)(ref_params)
    want, _ = opt.update(g, opt.init(ref_params), ref_params)
    ts, _ = step(ts, x, y)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)
