"""ZeRO-1 weight-update sharding (arXiv 2004.13336) on the DP hot path.

Load-bearing properties:

- **Parity**: reduce-scatter → 1/N-shard update → all_gather is the SAME
  optimization as allreduce → replicated update — params match the
  replicated engine at rtol=1e-5/atol=1e-6 over multiple steps, for
  divisible and non-divisible leaf sizes (LeNet's odd-sized filters),
  stateful optimizers (Adam / SGD-momentum), gradient accumulation, and
  a global-norm clip chain.
- **Memory**: the optimizer moments live sharded 1/N over the data axis —
  per-chip opt-state bytes shrink accordingly (this is the whole point).
- **Overlap variant**: param chunks in TrainState + gather-at-step-start
  trains the same trajectory; ``gather_params`` reassembles originals.
- **Accounting**: the split ZeRO-1 step charges the weight-update
  exchange to comm_stats; ``overlap_report`` decomposes exposed vs
  hidden comm; CommStats gains p50/p99; comm_time_table covers every
  aggregation strategy.
- **Composition**: a ZeRO1 optimizer rides the PP×DP pipeline engines
  (stacked stage leaves chunk along the feature axis, sharded over
  ``("stage", "data")``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.comm.timing import CommStats, comm_time_table
from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.models import LeNet
from tpudml.optim import Adam, ClipByGlobalNorm, ZeRO1, make_optimizer, with_stacked
from tpudml.parallel.dp import DataParallel

GLOBAL_BATCH = 32


def data_mesh(world):
    return make_mesh(MeshConfig({"data": world}), jax.devices()[:world])


@pytest.fixture(scope="module")
def batch():
    images, labels = synthetic_classification(GLOBAL_BATCH, (28, 28, 1), 10, seed=7)
    return np.asarray(images), np.asarray(labels)


def params_allclose(a, b, rtol=1e-5, atol=1e-6):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for xa, xb in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(xa), np.asarray(xb), rtol=rtol, atol=atol
        )


def run_steps(engine, batch, n=3, seed=0):
    ts = engine.create_state(seed_key(seed))
    step = engine.make_train_step()
    losses = []
    for _ in range(n):
        ts, m = step(ts, *batch)
        losses.append(float(m["loss"]))
    return ts, losses


# ------------------------------------------------------------ parity


# Tier-1 keeps only the cheapest variant of each parity claim; the rest
# ride the slow marker (the full suite sat at 863.7 s of the 870 s tier-1
# budget BEFORE this file existed — every fast-lane second here is real).
@pytest.mark.parametrize(
    "world,opt_name",
    [
        (2, "adam"),
        pytest.param(4, "adam", marks=pytest.mark.slow),
        pytest.param(4, "sgd", marks=pytest.mark.slow),
    ],
)
def test_zero1_matches_replicated_dp(batch, world, opt_name):
    """LeNet's leaves (150/2400/48000/850-element filters, 6/16/10-element
    biases) are mostly NOT divisible by the world size, so the padded
    chunking is exercised on every leaf."""
    mesh = data_mesh(world)
    model = LeNet()

    def build(zero1):
        opt = make_optimizer(opt_name, 1e-2, 0.9)
        return DataParallel(model, opt, mesh, zero1=zero1)

    ts_z, losses_z = run_steps(build(True), batch)
    ts_r, losses_r = run_steps(build(False), batch)
    np.testing.assert_allclose(losses_z, losses_r, rtol=1e-5)
    params_allclose(ts_z.params, ts_r.params)


@pytest.mark.slow
def test_zero1_with_accum_matches(batch):
    mesh = data_mesh(4)
    model = LeNet()

    def build(zero1):
        return DataParallel(
            model, make_optimizer("adam", 1e-3), mesh, zero1=zero1,
            accum_steps=2,
        )

    ts_z, losses_z = run_steps(build(True), batch)
    ts_r, losses_r = run_steps(build(False), batch)
    np.testing.assert_allclose(losses_z, losses_r, rtol=1e-5)
    params_allclose(ts_z.params, ts_r.params)


@pytest.mark.slow
def test_zero1_with_global_norm_clip_matches(batch):
    """ZeRO-1 rewraps the clip to compute the global norm from disjoint
    chunks via psum over the data axis — same norm, same clip factor,
    same trajectory (max_norm small enough that the clip binds)."""
    mesh = data_mesh(4)
    model = LeNet()

    def build(zero1):
        opt = ClipByGlobalNorm(Adam(lr=1e-3), max_norm=0.05)
        return DataParallel(model, opt, mesh, zero1=zero1)

    ts_z, losses_z = run_steps(build(True), batch)
    ts_r, losses_r = run_steps(build(False), batch)
    np.testing.assert_allclose(losses_z, losses_r, rtol=1e-5)
    params_allclose(ts_z.params, ts_r.params)


@pytest.mark.slow
def test_zero1_overlap_matches_replicated(batch):
    """The double-buffered variant (param chunks in TrainState, gather at
    step START) is the same math; gather_params reassembles originals."""
    mesh = data_mesh(4)
    model = LeNet()

    dp_o = DataParallel(
        model, make_optimizer("adam", 1e-3), mesh,
        zero1=True, zero1_overlap=True, accum_steps=2,
    )
    ts_o = dp_o.create_state(seed_key(0))
    step_o = dp_o.make_train_step()
    losses_o = []
    for _ in range(3):
        ts_o, m = step_o(ts_o, *batch)
        losses_o.append(float(m["loss"]))

    dp_r = DataParallel(
        model, make_optimizer("adam", 1e-3), mesh, accum_steps=2
    )
    ts_r, losses_r = run_steps(dp_r, batch)

    np.testing.assert_allclose(losses_o, losses_r, rtol=1e-5)
    params_allclose(dp_o.gather_params(ts_o), ts_r.params)


# ------------------------------------------------------------ memory


def _opt_bytes_on_device0(ts):
    total = 0
    for leaf in jax.tree.leaves(ts.opt_state):
        shards = [s for s in leaf.addressable_shards if s.device == jax.devices()[0]]
        total += sum(np.asarray(s.data).nbytes for s in shards)
    return total


def test_zero1_opt_state_is_sharded_one_over_n(batch):
    """THE memory claim: per-chip Adam moment bytes ~ 1/N of the
    replicated engine's (exactly ceil(n/N) per leaf, so slightly above
    1/N from padding on LeNet's small biases)."""
    world = 4
    mesh = data_mesh(world)
    model = LeNet()

    dp_z = DataParallel(model, make_optimizer("adam", 1e-3), mesh, zero1=True)
    ts_z = dp_z.create_state(seed_key(0))
    dp_r = DataParallel(model, make_optimizer("adam", 1e-3), mesh)
    ts_r = dp_r.create_state(seed_key(0))

    z_bytes = _opt_bytes_on_device0(ts_z)
    r_bytes = _opt_bytes_on_device0(ts_r)
    assert z_bytes < r_bytes / world * 1.2, (z_bytes, r_bytes)
    assert z_bytes > r_bytes / world * 0.8, (z_bytes, r_bytes)

    # The moments really carry the data axis in their sharding spec.
    biggest = max(jax.tree.leaves(ts_z.opt_state), key=lambda x: x.size)
    assert "data" in str(biggest.sharding.spec)

    # Parity still holds from this sharded state.
    step = dp_z.make_train_step()
    ts_z, m = step(ts_z, *batch)
    assert np.isfinite(float(m["loss"]))


# ------------------------------------------------------- comm accounting


@pytest.mark.slow
def test_zero1_split_step_counts_comm_and_matches_fused(batch):
    mesh = data_mesh(4)
    model = LeNet()

    fused = DataParallel(model, make_optimizer("adam", 1e-3), mesh, zero1=True)
    ts_f, losses_f = run_steps(fused, batch)

    split = DataParallel(
        model, make_optimizer("adam", 1e-3), mesh, zero1=True,
        measure_comm=True,
    )
    ts_s, losses_s = run_steps(split, batch)

    np.testing.assert_allclose(losses_s, losses_f, rtol=1e-4)
    params_allclose(ts_s.params, ts_f.params, rtol=1e-4, atol=1e-5)
    assert split.comm_stats.calls == 3
    assert split.comm_stats.comm_time_s > 0.0
    assert "p50" in split.comm_stats.report()


@pytest.mark.slow
def test_overlap_report_decomposes_exposed_vs_hidden(batch):
    mesh = data_mesh(4)
    dp = DataParallel(LeNet(), make_optimizer("adam", 1e-3), mesh, zero1=True)
    ts = dp.create_state(seed_key(0))
    rep = dp.overlap_report(ts, *batch, iters=2, warmup=1)
    for key in ("fused_s", "compute_s", "comm_s", "exposed_comm_s",
                "hidden_comm_s", "overlap_frac"):
        assert key in rep and rep[key] >= 0.0, rep
    np.testing.assert_allclose(
        rep["exposed_comm_s"] + rep["hidden_comm_s"], rep["comm_s"]
    )
    assert 0.0 <= rep["overlap_frac"] <= 1.0


@pytest.mark.slow
def test_overlap_report_on_overlap_variant(batch):
    mesh = data_mesh(2)
    dp = DataParallel(
        LeNet(), make_optimizer("adam", 1e-3), mesh,
        zero1=True, zero1_overlap=True, accum_steps=2,
    )
    ts = dp.create_state(seed_key(0))
    dp.make_train_step()  # the variant's own program must also build
    rep = dp.overlap_report(ts, *batch, iters=2, warmup=1)
    assert rep["overlap_step_s"] > 0.0


def test_comm_stats_percentiles():
    cs = CommStats()
    assert cs.percentiles() == {}
    assert "p50" not in cs.report()
    for dt in (0.01, 0.02, 0.03):
        cs.add(dt)
    pct = cs.percentiles()
    np.testing.assert_allclose(pct["p50_s"], 0.02)
    assert 0.02 < pct["p99_s"] <= 0.03
    rep = cs.report()
    assert rep.startswith("Total communication time:")
    assert "p50" in rep and "p99" in rep


def test_comm_time_table_covers_every_strategy():
    mesh = data_mesh(2)
    grads = {"w": jnp.ones((64, 8)), "b": jnp.ones((8,))}
    table = comm_time_table(mesh, grads, iters=2, warmup=1)
    assert set(table) == {"allreduce", "allgather", "reducescatter"}
    for row in table.values():
        assert row["median_s"] > 0.0


# -------------------------------------------------------- PP×DP stacking


@pytest.mark.slow
def test_pp_dp_zero1_matches_plain_pp_dp():
    """A ZeRO1 optimizer on the 2-D {data, stage} pipeline: stacked stage
    leaves chunk along the flattened feature axis (P("stage", "data")
    moments) and the reduce-scatter over ``data`` doubles as the grads
    pmean — same trajectory as the replicated PP×DP update."""
    from tpudml.nn import Activation, Dense, Sequential
    from tpudml.parallel.pp import GPipe

    mesh = make_mesh(
        MeshConfig({"data": 2, "stage": 2}), jax.devices()[:4]
    )
    rng = np.random.default_rng(5)
    x = rng.normal(size=(8, 8)).astype(np.float32)
    y = rng.integers(0, 10, size=(8,)).astype(np.int32)

    def pipe(opt):
        return GPipe(
            Sequential((Dense(16, 16), Activation(jax.nn.relu))),
            n_microbatches=2,
            mesh=mesh,
            optimizer=opt,
            prologue=Dense(8, 16),
            epilogue=Dense(16, 10),
            batch_axis="data",
        )

    def run(opt):
        eng = pipe(opt)
        ts = eng.create_state(seed_key(1))
        step = eng.make_train_step()
        losses = []
        for _ in range(3):
            ts, m = step(ts, x, y)
            losses.append(float(m["loss"]))
        return ts, losses

    ts_z, losses_z = run(
        ZeRO1(make_optimizer("adam", 1e-3), axis_name="data", world=2)
    )
    ts_r, losses_r = run(make_optimizer("adam", 1e-3))
    np.testing.assert_allclose(losses_z, losses_r, rtol=1e-5)
    params_allclose(ts_z.params, ts_r.params, rtol=1e-4, atol=1e-6)


# ------------------------------------------------------------- guards


def test_zero1_guards(batch):
    mesh = data_mesh(2)
    model = LeNet()
    opt = make_optimizer("adam", 1e-3)

    with pytest.raises(ValueError, match="world"):
        ZeRO1(opt, axis_name="data")
    with pytest.raises(ValueError, match="zero1=True"):
        DataParallel(model, opt, mesh, zero1_overlap=True)
    with pytest.raises(ValueError, match="aggregation"):
        DataParallel(model, opt, mesh, zero1=True, aggregation="allgather")
    with pytest.raises(ValueError, match="accum_steps"):
        DataParallel(model, opt, mesh, zero1=True, zero1_overlap=True)
    with pytest.raises(ValueError, match="overlap_report"):
        DataParallel(
            model, opt, mesh, zero1=True, zero1_overlap=True,
            accum_steps=2, measure_comm=True,
        )
    # Pre-wrapped optimizer: zero1=True and axis/world agreement required.
    z = ZeRO1(opt, axis_name="data", world=2)
    with pytest.raises(ValueError, match="zero1=True"):
        DataParallel(model, z, mesh)
    with pytest.raises(ValueError, match="does not match"):
        DataParallel(
            model, ZeRO1(opt, axis_name="data", world=4), mesh, zero1=True
        )
    # Stacked (pipeline) layout × global-norm clip is rejected: the
    # two-bucket clip model cannot express the two-axis chunk sharding.
    clipped = ZeRO1(
        ClipByGlobalNorm(Adam(lr=1e-3), max_norm=1.0),
        axis_name="data", world=2,
    )
    with pytest.raises(ValueError, match="stacked"):
        with_stacked(clipped, lambda path: True)
    # The overlap variant's chunks are distinct by design.
    dp_o = DataParallel(
        model, opt, mesh, zero1=True, zero1_overlap=True, accum_steps=2
    )
    ts = dp_o.create_state(seed_key(0))
    with pytest.raises(ValueError, match="zero1_overlap"):
        dp_o.broadcast_params(ts)


def test_zero1_overlap_requires_create_state_first():
    mesh = data_mesh(2)
    dp = DataParallel(
        LeNet(), make_optimizer("adam", 1e-3), mesh,
        zero1=True, zero1_overlap=True, accum_steps=2,
    )
    with pytest.raises(ValueError, match="create_state"):
        dp.make_train_step()


def test_zero1_init_flattens_to_padded_chunks():
    """State leaves take the flat [world*ceil(n/world)] layout (the unit
    behind the 1/N placement)."""
    opt = ZeRO1(Adam(lr=1e-3), axis_name="data", world=4)
    params = {"w": jnp.ones((3, 5)), "b": jnp.ones((6,))}
    state = opt.init(params)
    assert state["m"]["w"].shape == (16,)  # 15 -> pad to 4*4
    assert state["m"]["b"].shape == (8,)   # 6 -> pad to 4*2
    assert state["t"].shape == ()
