"""Pallas fused-attention tests (interpret mode on the CPU harness).

Load-bearing property: the kernels are the same function as the reference
``dot_product_attention`` — forward (all block sizes, causal on/off,
bfloat16) and gradients via BOTH backward paths: the blocked dQ/dK/dV
kernels (default) and the custom_vjp reference-recompute fallback.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.models import TransformerLM
from tpudml.nn.attention import dot_product_attention
from tpudml.ops import flash_attention

B, T, H, D = 2, 32, 4, 8


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(11)
    return tuple(
        jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_q,block_k", [(8, 8), (16, 8), (32, 16), (8, 32)])
def test_kernel_matches_reference(qkv, causal, block_q, block_k):
    q, k, v = qkv
    got = flash_attention(q, k, v, causal=causal, block_q=block_q, block_k=block_k, interpret=True)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def test_kernel_bfloat16(qkv):
    q, k, v = (a.astype(jnp.bfloat16) for a in qkv)
    got = flash_attention(q, k, v, causal=True, block_q=16, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = dot_product_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), rtol=0.05, atol=0.02
    )


def test_gradients_match_reference(qkv):
    """The recompute FALLBACK path (blocked_backward=False) — the blocked
    kernels have their own parametrized test below."""
    q, k, v = qkv
    w = jnp.asarray(np.random.default_rng(3).normal(size=(B, T, H, D)).astype(np.float32))
    got = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=16, interpret=True,
                            blocked_backward=False) * w
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(dot_product_attention(q, k, v, causal=True) * w),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g, r in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize(
    "t,block_q,block_k,causal",
    [
        (30, 16, 512, False),
        (30, 16, 512, True),
        (32, 5, 512, True),
        # Multiple K tiles WITH K padding: the padded-tail mask must apply
        # at global k positions across tiles (kj > 0).
        (30, 16, 8, False),
        (30, 16, 8, True),
        (27, 8, 4, True),
    ],
)
def test_odd_lengths_pad_and_mask(qkv, t, block_q, block_k, causal):
    """Any T works via pad-and-mask (never by shrinking the MXU block):
    padded keys get no attention mass, padded queries are sliced off."""
    q, k, v = (a[:, :t] for a in qkv)
    got = flash_attention(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k, interpret=True
    )
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize(
    "t,block_q,block_k,causal",
    [(32, 8, 8, False), (32, 8, 8, True), (30, 16, 8, True), (27, 8, 4, False)],
)
def test_blocked_backward_matches_reference(qkv, t, block_q, block_k, causal):
    """The flash backward kernels (dQ, dK/dV with tile streaming) must
    reproduce reference gradients across multi-tile grids, odd lengths,
    and causal skipping."""
    q, k, v = (a[:, :t] for a in qkv)
    w = jnp.asarray(
        np.random.default_rng(7).normal(size=q.shape).astype(np.float32)
    )
    got = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(
                q, k, v, causal=causal, block_q=block_q, block_k=block_k,
                interpret=True,
            ) * w
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(dot_product_attention(q, k, v, causal=causal) * w),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g, r in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=5e-4, atol=1e-5)


@pytest.mark.skipif(jax.default_backend() != "cpu", reason="CPU dispatch path")
def test_cpu_dispatch_falls_back_to_reference(qkv):
    """interpret=None off-TPU must use the reference math (not the slow
    interpreter): identical values by construction."""
    q, k, v = qkv
    got = flash_attention(q, k, v, causal=True)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_transformer_flash_impl_matches_full():
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 50, size=(B, T)).astype(np.int32)
    )
    base = dict(vocab_size=50, embed_dim=32, num_heads=4, num_layers=2, max_len=T)
    full = TransformerLM(**base)
    flash = TransformerLM(**base, impl="flash")
    params, _ = full.init(jax.random.key(0))
    np.testing.assert_allclose(
        np.asarray(jax.jit(lambda p, t: flash(p, t))(params, tokens)),
        np.asarray(jax.jit(lambda p, t: full(p, t))(params, tokens)),
        rtol=2e-4,
        atol=1e-5,
    )
