"""Context-parallel attention tests on the simulated CPU mesh.

Load-bearing property: ring and Ulysses attention over a sharded sequence
axis are the SAME function as single-device full attention — forward and
gradients — including causal masking across shard boundaries (global
positions). Plus: the ContextParallel transformer training trajectory
matches single-device training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import TransformerLM
from tpudml.nn.attention import dot_product_attention
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import make_optimizer
from tpudml.parallel.cp import ContextParallel, ring_attention, ulysses_attention
from tpudml.parallel.sharding import shard_map_fn

WORLD = 4
B, T, H, D = 2, 32, 4, 8
SPEC = P(None, "seq")  # [B, T, ...] sharded along time


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig({"seq": WORLD}), jax.devices()[:WORLD])


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(1)
    return tuple(
        jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
        for _ in range(3)
    )


def _sharded(mesh, fn):
    return jax.jit(
        shard_map_fn(fn, mesh, in_specs=(SPEC, SPEC, SPEC), out_specs=SPEC)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_sharded_attention_matches_full(mesh, qkv, causal, impl):
    q, k, v = qkv
    got = _sharded(mesh, lambda q, k, v: impl(q, k, v, "seq", causal=causal))(q, k, v)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_sharded_attention_grads_match_full(mesh, qkv, impl):
    q, k, v = qkv
    # Fixed cotangent via a weighted-sum scalar so grads are comparable.
    w = jnp.asarray(np.random.default_rng(2).normal(size=(B, T, H, D)).astype(np.float32))

    def sharded_loss(q, k, v, w):
        return jax.lax.psum(
            jnp.sum(impl(q, k, v, "seq", causal=True) * w), "seq"
        )

    loss_fn = jax.jit(
        shard_map_fn(
            sharded_loss, mesh, in_specs=(SPEC, SPEC, SPEC, SPEC), out_specs=P()
        )
    )
    got = jax.grad(lambda q, k, v: loss_fn(q, k, v, w), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(dot_product_attention(q, k, v, causal=True) * w),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g, r in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=5e-4, atol=1e-5)


def test_causal_mask_blocks_future(qkv):
    """Perturbing a future token must not change past outputs."""
    q, k, v = qkv
    out = dot_product_attention(q, k, v, causal=True)
    k2 = k.at[:, T - 1].add(10.0)
    v2 = v.at[:, T - 1].add(10.0)
    out2 = dot_product_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[:, : T - 1]), np.asarray(out2[:, : T - 1]), rtol=1e-6
    )
    assert not np.allclose(np.asarray(out[:, T - 1]), np.asarray(out2[:, T - 1]))


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_transformer_cp_forward_matches_single_device(mesh, impl):
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, 50, size=(B, T)).astype(np.int32))
    base = dict(
        vocab_size=50, embed_dim=32, num_heads=4, num_layers=2, max_len=T
    )
    ref_model = TransformerLM(**base)
    cp_model = TransformerLM(**base, impl=impl, seq_sharded=True)
    params, _ = ref_model.init(seed_key(0))

    want = ref_model(params, tokens)
    cp = ContextParallel(cp_model, make_optimizer("sgd", 0.1), mesh)
    got = cp.make_forward()(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=1e-5)


def test_cp_training_trajectory_matches_single_device(mesh):
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, 50, size=(B, T + 1)).astype(np.int32))
    x, y = tokens[:, :-1], tokens[:, 1:]
    base = dict(vocab_size=50, embed_dim=32, num_heads=4, num_layers=2, max_len=T)
    opt = make_optimizer("sgd", 0.1)

    cp_model = TransformerLM(**base, impl="ring", seq_sharded=True)
    cp = ContextParallel(cp_model, opt, mesh)
    ts = cp.create_state(seed_key(5))
    step = cp.make_train_step()

    ref_model = TransformerLM(**base)
    ref_params = jax.device_get(ts.params)
    ref_opt = opt.init(ref_params)
    ref_loss = lambda p: softmax_cross_entropy(ref_model(p, x), y)

    losses = []
    for _ in range(4):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_opt = opt.update(g, ref_opt, ref_params)

    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)
    assert losses[-1] < losses[0]


def test_cp_composes_with_dp_matches_single_device():
    """2-D {"data": 2, "seq": 4} mesh: batch sharded over data, time over
    seq — still the same optimization as one device on the global batch."""
    mesh2d = make_mesh(MeshConfig({"data": 2, "seq": 4}), jax.devices())
    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, 50, size=(4, T + 1)).astype(np.int32))
    x, y = tokens[:, :-1], tokens[:, 1:]
    base = dict(vocab_size=50, embed_dim=32, num_heads=4, num_layers=2, max_len=T)
    opt = make_optimizer("sgd", 0.1)

    cp_model = TransformerLM(**base, impl="ring", seq_sharded=True)
    cp = ContextParallel(cp_model, opt, mesh2d, batch_axis="data")
    ts = cp.create_state(seed_key(8))
    step = cp.make_train_step()

    ref_model = TransformerLM(**base)
    ref_params = jax.device_get(ts.params)
    ref_opt = opt.init(ref_params)
    ref_loss = lambda p: softmax_cross_entropy(ref_model(p, x), y)

    for _ in range(3):
        ts, m = step(ts, x, y)
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_opt = opt.update(g, ref_opt, ref_params)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)


def test_cp_evaluate_matches_single_device(mesh):
    rng = np.random.default_rng(9)
    tokens = jnp.asarray(rng.integers(0, 50, size=(B, T + 1)).astype(np.int32))
    x, y = tokens[:, :-1], tokens[:, 1:]
    base = dict(vocab_size=50, embed_dim=32, num_heads=4, num_layers=1, max_len=T)
    cp = ContextParallel(
        TransformerLM(**base, impl="ring", seq_sharded=True),
        make_optimizer("sgd", 0.1),
        mesh,
    )
    ts = cp.create_state(seed_key(10))
    acc = cp.evaluate(ts, [(x, y)])
    ref_model = TransformerLM(**base)
    logits = ref_model(jax.device_get(ts.params), x)
    want = float(np.mean(np.argmax(np.asarray(logits), -1) == np.asarray(y)))
    np.testing.assert_allclose(acc, want, atol=1e-6)


def test_ulysses_head_divisibility_check(mesh):
    q = jnp.ones((B, T // WORLD, 3, D))  # 3 heads, world 4

    def f(q, k, v):
        return ulysses_attention(q, k, v, "seq")

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(
            shard_map_fn(f, mesh, in_specs=(P(), P(), P()), out_specs=P())
        )(q, q, q)


def test_causal_ring_skips_fully_masked_blocks(mesh, qkv):
    """Fully-masked (future) K/V blocks must never reach the fold: NaNs in
    v-rows that only future devices would see cannot corrupt the output.
    (The old implementation computed every block and relied on exp(-1e30)
    ·NaN — this pins the skip as a behavioral property, not a FLOPs
    claim.)"""
    q, k, v = qkv
    t_shard = T // WORLD
    # Device 0's output attends only shard 0; poison every later v row.
    v_poisoned = v.at[:, t_shard:].set(jnp.nan)
    got = _sharded(
        mesh, lambda q, k, v: ring_attention(q, k, v, "seq", causal=True)
    )(q, k, v_poisoned)
    want = dot_product_attention(q, k, v, causal=True)
    got0 = np.asarray(got)[:, :t_shard]
    assert np.isfinite(got0).all()
    np.testing.assert_allclose(
        got0, np.asarray(want)[:, :t_shard], rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_path_matches_full(mesh, qkv, causal):
    """The Pallas-kernel fold (forced via interpret mode off-TPU) is the
    same function as the math fold and full attention — forward and
    gradients."""
    q, k, v = qkv

    def ring_flash(q, k, v):
        return ring_attention(
            q, k, v, "seq", causal=causal, use_flash=True, interpret=True
        )

    got = _sharded(mesh, ring_flash)(q, k, v)
    want = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def loss_flash(q, k, v):
        return jnp.sum(_sharded(mesh, ring_flash)(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    got_grads = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gg, wg in zip(got_grads, want_grads):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(wg), rtol=5e-5, atol=5e-6
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_full(mesh, qkv, causal):
    """Direct gradient parity of the custom-VJP ring backward (math fold)
    against AD through full attention."""
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(
            _sharded(
                mesh, lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal)
            )(q, k, v)
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gg, wg in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(wg), rtol=5e-5, atol=5e-6
        )


def _stripe(x):
    """Contiguous [B, T, ...] → striped layout: device i's shard-slice
    holds tokens {t : t mod WORLD == i} in order."""
    b, t = x.shape[:2]
    tl = t // WORLD
    return (
        x.reshape(b, tl, WORLD, *x.shape[2:])
        .swapaxes(1, 2)
        .reshape(b, t, *x.shape[2:])
    )


def _unstripe(x):
    b, t = x.shape[:2]
    tl = t // WORLD
    return (
        x.reshape(b, WORLD, tl, *x.shape[2:])
        .swapaxes(1, 2)
        .reshape(b, t, *x.shape[2:])
    )


@pytest.mark.parametrize("use_flash", [False, True])
def test_striped_causal_ring_matches_full(mesh, qkv, use_flash):
    """Striped layout (token t on device t mod W): every ring block is a
    balanced triangular tile (strict below the diagonal for src > idx),
    and the result — forward AND gradients — still equals full causal
    attention on the contiguous sequence."""
    q, k, v = qkv
    qs, ks, vs = (_stripe(a) for a in (q, k, v))

    def ring_striped(q, k, v):
        return ring_attention(
            q, k, v, "seq", causal=True, layout="striped",
            use_flash=use_flash, interpret=use_flash,
        )

    got = _unstripe(_sharded(mesh, ring_striped)(qs, ks, vs))
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def loss_striped(q, k, v):
        return jnp.sum(
            _unstripe(_sharded(mesh, ring_striped)(_stripe(q), _stripe(k), _stripe(v)))
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    got_g = jax.grad(loss_striped, argnums=(0, 1, 2))(q, k, v)
    want_g = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gg, wg in zip(got_g, want_g):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(wg), rtol=5e-5, atol=5e-6
        )


def test_context_parallel_striped_engine_matches_contiguous(mesh):
    """End to end through the engine + model: striped-CP training (host
    striping, strided positions, shifted-diagonal ring masks) produces the
    SAME losses as contiguous-CP training, step for step — the layout is
    invisible to the math."""
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.optim import make_optimizer

    seqs = jnp.asarray(synthetic_lm(8, 33, 32, seed=4))
    x, y = seqs[:, :32], seqs[:, 1:33]  # T=32 divides the 4-way seq mesh

    def run(layout):
        lm = TransformerLM(
            vocab_size=32, embed_dim=32, num_heads=4, num_layers=1,
            max_len=64, impl="ring", seq_sharded=True, seq_layout=layout,
            rope=True,
        )
        eng = ContextParallel(lm, make_optimizer("adam", 0.01), mesh,
                              layout=layout)
        ts = eng.create_state(seed_key(5))
        step = eng.make_train_step()
        losses = []
        for _ in range(5):
            ts, m = step(ts, x, y)
            losses.append(float(m["loss"]))
        return losses, eng, ts

    cont, _, _ = run("contiguous")
    strip, eng_s, ts_s = run("striped")
    np.testing.assert_allclose(strip, cont, rtol=2e-4)
    assert strip[-1] < strip[0]
    # Eval path stripes inputs too.
    acc = eng_s.evaluate(ts_s, [(x, y)])
    assert 0.0 <= acc <= 1.0


def test_context_parallel_layout_mismatch_rejected(mesh):
    from tpudml.optim import make_optimizer

    lm = TransformerLM(vocab_size=32, embed_dim=32, num_heads=4,
                       num_layers=1, impl="ring", seq_sharded=True)
    with pytest.raises(ValueError, match="seq_layout"):
        ContextParallel(lm, make_optimizer("adam", 0.01), mesh, layout="striped")


def test_striped_composes_with_gqa_and_rope(mesh):
    """Feature interaction: striped layout × GQA (kv groups) × RoPE
    (strided positions) through the full model — still matches the
    contiguous run step for step."""
    from tpudml.core.prng import seed_key
    from tpudml.data.datasets import synthetic_lm
    from tpudml.optim import make_optimizer

    seqs = jnp.asarray(synthetic_lm(4, 33, 32, seed=6))
    x, y = seqs[:, :32], seqs[:, 1:33]

    def run(layout):
        lm = TransformerLM(
            vocab_size=32, embed_dim=32, num_heads=4, num_kv_heads=2,
            num_layers=1, max_len=64, impl="ring", seq_sharded=True,
            seq_layout=layout, rope=True,
        )
        eng = ContextParallel(lm, make_optimizer("adam", 0.01), mesh,
                              layout=layout)
        ts = eng.create_state(seed_key(7))
        step = eng.make_train_step()
        out = []
        for _ in range(4):
            ts, m = step(ts, x, y)
            out.append(float(m["loss"]))
        return out

    np.testing.assert_allclose(run("striped"), run("contiguous"), rtol=2e-4)
