"""Clean twins of seeded_violations.py: no rule may fire on this module.

Each function does the same job as its seeded counterpart using the
compliant idiom the rule's fix hint prescribes.
"""

import time

import jax
import jax.numpy as jnp


def c201_host_control(x):
    y = float(jnp.mean(x))  # materialized on host before branching
    if y > 0:
        x = x + 1.0
    return jnp.where(jnp.mean(x) > 0, x, -x)  # traced branch, traced select


def c202_key_split(shape):
    key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, shape)
    b = jax.random.uniform(key, shape)  # each key consumed exactly once
    return a + b


def c203_epoch_loop(loader, model):
    seen = 0
    for epoch in range(3):
        loader.set_epoch(epoch)
        for batch in loader:
            seen += 1
    return seen


def c204_bracketed_timing(step, ts, batch):
    t0 = time.time()
    ts, metrics = step(ts, *batch)
    jax.block_until_ready(metrics)
    elapsed = time.time() - t0
    return ts, batch[0].shape[0] / elapsed
