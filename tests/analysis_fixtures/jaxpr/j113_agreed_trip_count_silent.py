"""J113 silent twin: the predicate derives from a pmax-reduced local
condition, so every shard agrees on the trip count and the body psum is
balanced across ranks — the fix the rule's hint prescribes."""

RULE = "J113"
EXPECT = "silent"


def build():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(xs):
        # Shard-local stopping signal, reduced so all shards agree.
        limit = jax.lax.pmax(xs.max(), "data")

        def cond(c):
            return c[0] < limit

        def step(c):
            return (c[0] + 1.0, jax.lax.psum(c[1], "data"))

        return jax.lax.while_loop(cond, step, (jnp.float32(0), xs.sum()))[1]

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P("data"),),
                              out_specs=P()))
    return fn, (jnp.ones((8,)),)
