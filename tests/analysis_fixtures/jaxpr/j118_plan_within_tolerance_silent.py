"""J118 silent twin: the plan's ``predicted`` block is computed at
import time from the SAME analysis APIs the planner stamps it with
(dataflow walk for wire bytes, liveness walk for peak HBM) — a fresh
plan is within tolerance of its own trace by construction, whatever the
estimators currently say."""

RULE = "J118"
EXPECT = "silent"


def _build_fn():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(x):
        big = jnp.outer(x, x)
        g = big.sum(axis=0)
        return jax.lax.psum(g, "data")

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P(),), out_specs=P()))
    return fn, (jnp.ones((512,)),)


def _predict():
    import jax

    from tpudml.analysis.cost import peak_live_bytes
    from tpudml.analysis.dataflow import analyze_dataflow

    fn, args = _build_fn()
    closed = jax.make_jaxpr(fn)(*args)
    flow = analyze_dataflow(closed, "j118_silent")
    return {
        "comm_wire_bytes": float(
            sum(ev.wire_bytes * ev.trips for ev in flow.comm_events)
        ),
        "peak_hbm_bytes": int(peak_live_bytes(closed)),
    }


ANALYZE_KWARGS = {"plan": {"predicted": _predict()}}


def build():
    return _build_fn()
