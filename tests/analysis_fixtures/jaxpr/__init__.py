"""Jaxpr-pass fixture modules, discovered by filename.

Each module in this directory is one seeded program for the dataflow
rules (J112–J116): ``RULE`` names the rule under test, ``EXPECT`` is
``"fire"`` or ``"silent"``, ``build()`` returns ``(fn, args)`` for
``analyze_callable``, and optional ``ANALYZE_KWARGS`` forwards extra
analyzer arguments (e.g. ``hbm_budget_bytes`` to arm J116).
test_analysis.py parametrizes over the directory listing, so a fixture
that fails to import/build/trace reports ITS OWN filename instead of an
opaque parametrize error — add a module, get a test.
"""
