"""J117 silent twin: the same marked decode step reading K/V through the
slot's page TABLE — ``pool[table]`` gathers max_pages·page_size = 6 rows
per slot (< the pool's 12), so attention cost tracks per-slot capacity
and the rule stays quiet."""

RULE = "J117"
EXPECT = "silent"

N, P, M, H, D, B = 6, 2, 3, 2, 4, 2  # table window 6 rows, pool 12


def build():
    import jax
    import jax.numpy as jnp

    def _serve_paged_decode_step(pool_k, pool_v, table, q):
        k = pool_k[table].reshape(B, M * P, H, D)
        v = pool_v[table].reshape(B, M * P, H, D)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    inner = jax.jit(_serve_paged_decode_step)
    fn = jax.jit(lambda pk, pv, tb, q: inner(pk, pv, tb, q))
    return fn, (
        jnp.zeros((N, P, H, D)),
        jnp.zeros((N, P, H, D)),
        jnp.zeros((B, M), jnp.int32),
        jnp.zeros((B, 1, H, D)),
    )
