"""J112 silent twin: the same per-shard partial, but a ``pmean`` merges
it over the data axis before the replicated output — the value really
is identical across shards, so the lattice proves it replicated."""

RULE = "J112"
EXPECT = "silent"


def build():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(xs):
        return jax.lax.pmean(jnp.mean(xs), "data")

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P("data"),),
                              out_specs=P()))
    return fn, (jnp.ones((8, 4)),)
