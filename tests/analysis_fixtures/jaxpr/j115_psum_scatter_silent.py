"""J115 silent twin: the same reduce-then-keep-your-shard dataflow
expressed directly as psum_scatter — each device receives only its
shard, so there is no oversized allreduce to flag."""

RULE = "J115"
EXPECT = "silent"


def build():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(xs):
        return jax.lax.psum_scatter(xs, "data", tiled=True)

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P(),),
                              out_specs=P("data")))
    return fn, (jnp.ones((8,)),)
