"""J112 firing: a shard_map body computes a per-shard partial (the mean
of its local batch slice) and returns it through ``out_specs=P()`` —
declared replicated — with no reducing collective. check_rep=False (the
engines' setting, forced by custom_vjp regions) means JAX never checks
the claim: every device silently returns a different loss. This is the
missing-psum / lost-transpose-factor class the fused-xent backward had
to hand-fix."""

RULE = "J112"
EXPECT = "fire"


def build():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(xs):
        return jnp.mean(xs)  # per-shard partial, no psum

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P("data"),),
                              out_specs=P()))
    return fn, (jnp.ones((8, 4)),)
