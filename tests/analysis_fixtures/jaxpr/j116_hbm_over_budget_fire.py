"""J116 firing: the program materialises a 1 MB f32 intermediate while
``hbm_budget_bytes`` arms the checker at 64 KB — the static peak-live
walk must report the budget breach before any compile happens."""

RULE = "J116"
EXPECT = "fire"
ANALYZE_KWARGS = {"hbm_budget_bytes": 64 * 1024}


def build():
    import jax.numpy as jnp

    def fn(x):
        big = jnp.outer(x, x)  # 512*512*4 = 1 MB live
        return big.sum()

    return fn, (jnp.ones((512,)),)
