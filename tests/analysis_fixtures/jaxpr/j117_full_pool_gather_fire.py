"""J117 firing: a paged-decode-marked step whose attention keys are the
WHOLE page pool broadcast per token ([num_pages·page_size] = 12 rows)
instead of the slot's table window — per-token cost scales with total
HBM provisioned, not one tenant's capacity. The healthy pattern gathers
``pool[table]`` first (see the silent twin)."""

RULE = "J117"
EXPECT = "fire"

N, P, H, D, B = 6, 2, 2, 4, 2  # pool rows 12 > any per-slot table window


def build():
    import jax
    import jax.numpy as jnp

    def _serve_paged_decode_step(pool_k, pool_v, q):
        # The bug: every slot attends all N·P pool rows.
        k = jnp.broadcast_to(pool_k.reshape(1, N * P, H, D), (B, N * P, H, D))
        v = jnp.broadcast_to(pool_v.reshape(1, N * P, H, D), (B, N * P, H, D))
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    inner = jax.jit(_serve_paged_decode_step)
    fn = jax.jit(lambda pk, pv, q: inner(pk, pv, q))
    return fn, (
        jnp.zeros((N, P, H, D)),
        jnp.zeros((N, P, H, D)),
        jnp.zeros((B, 1, H, D)),
    )
