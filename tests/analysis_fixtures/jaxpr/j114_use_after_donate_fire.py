"""J114 firing: an inner jitted update donates its argument buffer,
and the caller then reads the donated value again — on TPU the second
read observes whatever the donated-out allocation was reused for."""

RULE = "J114"
EXPECT = "fire"


def build():
    import jax
    import jax.numpy as jnp

    update = jax.jit(lambda s: s + 1.0, donate_argnums=(0,))

    def fn(s):
        new = update(s)
        return new + s  # reads s after its buffer was donated

    return fn, (jnp.ones((16,)),)
