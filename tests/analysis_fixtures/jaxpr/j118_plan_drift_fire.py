"""J118 firing: the "emitted plan" promises a tiny program (a handful
of wire bytes, a few KB peak-live) but the traced step psums a 256 KB
gradient-sized buffer and materialises a ~1 MB intermediate — both
traced costs deviate far beyond the 10% drift tolerance, so the plan
no longer describes the program that runs."""

RULE = "J118"
EXPECT = "fire"
ANALYZE_KWARGS = {
    "plan": {
        "predicted": {"comm_wire_bytes": 64.0, "peak_hbm_bytes": 4096},
    },
}


def build():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(x):
        big = jnp.outer(x, x)  # 512*512*4 = 1 MB live
        g = big.sum(axis=0)  # 512*4*... per-shard "gradient"
        return jax.lax.psum(g, "data")

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P(),), out_specs=P()))
    return fn, (jnp.ones((512,)),)
