"""J115 firing: an all-gather-shaped psum whose full result is consumed
only through per-shard dynamic slices (index = axis_index) — every
device pays for the whole allreduce but keeps 1/N of it. psum_scatter
(reduce_scatter) moves (N-1)/N fewer wire bytes for the same answer."""

RULE = "J115"
EXPECT = "fire"


def build():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(xs):
        full = jax.lax.psum(xs, "data")  # everyone gets all 8 elements
        i = jax.lax.axis_index("data")
        return jax.lax.dynamic_slice(full, (i * 4,), (4,))  # keeps 1/N

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P(),),
                              out_specs=P("data")))
    return fn, (jnp.ones((8,)),)
