"""J114 silent twin: the donated argument's last use IS the donating
call — the caller only touches the returned buffer afterwards, which is
exactly the in-place update pattern donation exists for."""

RULE = "J114"
EXPECT = "silent"


def build():
    import jax
    import jax.numpy as jnp

    update = jax.jit(lambda s: s + 1.0, donate_argnums=(0,))

    def fn(s):
        new = update(s)
        return new * 2.0

    return fn, (jnp.ones((16,)),)
