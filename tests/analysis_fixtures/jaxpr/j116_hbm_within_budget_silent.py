"""J116 silent twin: same program, but the armed budget (16 MB) has
headroom over the ~1 MB static peak — no finding."""

RULE = "J116"
EXPECT = "silent"
ANALYZE_KWARGS = {"hbm_budget_bytes": 16 * 1024 * 1024}


def build():
    import jax.numpy as jnp

    def fn(x):
        big = jnp.outer(x, x)
        return big.sum()

    return fn, (jnp.ones((512,)),)
