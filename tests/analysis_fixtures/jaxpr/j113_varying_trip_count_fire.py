"""J113 firing: a while loop whose trip count depends on
``axis_index`` — each shard iterates a different number of times — with
a psum inside the body. Shards that exit early never post the
collective their peers are blocked in: the slice deadlocks."""

RULE = "J113"
EXPECT = "fire"


def build():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])

    def body(xs):
        limit = jax.lax.axis_index("data").astype(jnp.float32)

        def cond(c):
            return c[0] < limit  # per-shard trip count

        def step(c):
            return (c[0] + 1.0, jax.lax.psum(c[1], "data"))

        return jax.lax.while_loop(cond, step, (jnp.float32(0), xs.sum()))[1]

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(P("data"),),
                              out_specs=P()))
    return fn, (jnp.ones((8,)),)
