"""Protocol-pass fixture modules, discovered by filename.

Each module is one seeded scenario for the P-series rules: ``RULE``
names the rule under test, ``EXPECT`` is ``"fire"`` or ``"silent"``,
and ``MODE`` selects how the scenario is evaluated:

- ``"schedule"`` — ``build()`` returns ``(spec, schedules)``: a
  ``PipelineSpec`` plus a (possibly hand-tampered) ``build_schedules``
  output; the test runs ``check_schedules`` over it. Tampering the
  model rather than the spec is the point — a *constructible* spec is
  protocol-clean by design, so the broken twins simulate the bug
  classes (dropped frames, reordered 1F1B loops, divergent collective
  sequences, missing votes) the checker exists to catch.
- ``"ast"`` — the module's own source IS the scenario; the test runs
  ``analyze_file`` on it and filters for ``RULE`` (P304's fire twin
  contains deliberately leaky port code — never executed, import-safe).

test_protocol.py parametrizes over the directory listing and pins that
every P rule has both twins, mirroring the jaxpr fixture protocol.
"""
