"""P302 silent: every rank of each dp>1 stage group carries the same
injected (op, axis, shape) collective sequence — the stage_collectives
hook with identical per-stage signatures, as traced programs would
provide via ``traced_collective_events``."""

RULE = "P302"
EXPECT = "silent"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules
    from tpudml.mpmd.drill import _drill_pipeline

    spec = _drill_pipeline()
    colls = {
        s: (("psum", "data", (8, 16)), ("psum", "data", (16,)))
        for s in range(len(spec.stages))
    }
    return spec, build_schedules(spec, stage_collectives=colls)
