"""P303 silent: the real run_step order — every dp>1 rank votes on the
drain barrier before entering the stage-group collective."""

RULE = "P303"
EXPECT = "silent"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules
    from tpudml.mpmd.drill import _drill_pipeline

    spec = _drill_pipeline()
    return spec, build_schedules(spec)
