"""P301 silent: the heterogeneous 3-stage [2,2,2] pipeline in its real
1F1B order — warmup depths from ``warmup_microbatches`` keep enough
rows in flight that the simulation drains every schedule."""

RULE = "P301"
EXPECT = "silent"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules, protocol_surface

    spec = protocol_surface()["mpmd_3stage"]
    return spec, build_schedules(spec)
