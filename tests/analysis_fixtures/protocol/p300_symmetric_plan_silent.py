"""P300 silent: the drill's [2,2] pipeline with both sides deriving the
schedule from the same boundary plan — every sent frame has exactly one
receiver and vice versa."""

RULE = "P300"
EXPECT = "silent"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules
    from tpudml.mpmd.drill import _drill_pipeline

    spec = _drill_pipeline()
    return spec, build_schedules(spec)
