"""P303 firing: the entire trunk stage group skips its drain vote and
goes straight into the gradient allreduce. No P301 fires (the group
still agrees on the barrier sequence), but a peer death mid-step now
parks the group inside gloo instead of draining at the ctl barrier —
the membership-event path the vote exists to protect."""

RULE = "P303"
EXPECT = "fire"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules
    from tpudml.mpmd.drill import _drill_pipeline

    spec = _drill_pipeline()
    sched = build_schedules(spec)
    for r in range(spec.stages[0].dp):
        sched[(0, r)] = [e for e in sched[(0, r)] if e.kind != "vote"]
    return spec, sched
