"""P304 firing twins (AST mode: this file's own source is analyzed,
never executed): (a) a bound-and-listening socket that neither escapes
the scope nor reaches ``close()`` — leaked the moment ``accept`` (or
anything before it) raises; (b) the bind-and-hold reservations released
*before* the round's wiring is committed — a squatter can take the
ports in the window between release and spawn."""

import json
import socket

RULE = "P304"
EXPECT = "fire"
MODE = "ast"


def accept_one_leaky(host, port):
    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.bind((host, port))
    lst.listen(1)
    conn, _ = lst.accept()
    return conn


def form_round_released_early(host, path, reserve, spawn):
    holds = []
    ports = []
    for _ in range(2):
        sock, p = reserve(host)
        holds.append(sock)
        ports.append(p)
    for hold in holds:
        hold.close()
    write_wiring(path, json.dumps({"ports": ports}))
    spawn(ports)


def write_wiring(path, doc):
    path.write_text(doc)
