"""P302 firing: trunk rank 1's gradient allreduce carries a different
operand shape than rank 0's — the per-rank model code diverged (e.g. a
rank-conditional parameter slice) and gloo would deadlock or corrupt,
not diagnose. The simulation itself stays happy (barriers only match
kinds), which is exactly why the signature comparison is its own
rule."""

from dataclasses import replace

RULE = "P302"
EXPECT = "fire"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules
    from tpudml.mpmd.drill import _drill_pipeline

    spec = _drill_pipeline()
    sched = build_schedules(spec)
    sched[(0, 1)] = [
        replace(e, shape=(4096,)) if e.kind == "collective" else e
        for e in sched[(0, 1)]
    ]
    return spec, sched
