"""P304 silent twins (AST mode): the same two shapes done right — the
listener is closed in a ``finally`` (and handed off, either suffices),
and the bind-and-hold reservations survive until ``write_wiring`` has
committed the topology."""

import json
import socket

RULE = "P304"
EXPECT = "silent"
MODE = "ast"


def accept_one_safely(host, port):
    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        lst.bind((host, port))
        lst.listen(1)
        conn, _ = lst.accept()
        return conn
    finally:
        lst.close()


def form_round_held_until_commit(host, path, reserve, spawn):
    holds = []
    ports = []
    for _ in range(2):
        sock, p = reserve(host)
        holds.append(sock)
        ports.append(p)
    write_wiring(path, json.dumps({"ports": ports}))
    for hold in holds:
        hold.close()
    spawn(ports)


def write_wiring(path, doc):
    path.write_text(doc)
