"""P300 firing: one act frame's row interval is tampered on the send
side only — the sender believes it ships rows (0, 999) while every
receiver still expects the planned interval, so the (edge, mb, tag,
rows) multisets no longer match. This is the "tampered boundary
interval" regression: the two endpoints derived *different* boundary
plans."""

from dataclasses import replace

RULE = "P300"
EXPECT = "fire"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules
    from tpudml.mpmd.drill import _drill_pipeline

    spec = _drill_pipeline()
    sched = build_schedules(spec)
    key = (0, 0)
    evs = list(sched[key])
    i = next(k for k, e in enumerate(evs)
             if e.kind == "send" and e.tag == "act")
    evs[i] = replace(evs[i], rows=(0, 999))
    sched[key] = evs
    return spec, sched
