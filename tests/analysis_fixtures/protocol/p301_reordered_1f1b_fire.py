"""P301 firing: trunk rank 0 runs its host loop in REVERSE — the
vote/collective tail first, p2p frames last. Its group peer reaches the
drain vote while rank 0 sits in the collective (barrier kinds disagree)
and the head stage starves waiting for activations that are scheduled
after a barrier that can never complete: a wait-for cycle across
ranks."""

RULE = "P301"
EXPECT = "fire"
MODE = "schedule"


def build():
    from tpudml.analysis.protocol import build_schedules
    from tpudml.mpmd.drill import _drill_pipeline

    spec = _drill_pipeline()
    sched = build_schedules(spec)
    sched[(0, 0)] = list(reversed(sched[(0, 0)]))
    return spec, sched
