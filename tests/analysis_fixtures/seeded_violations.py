"""Seeded violations: every A2xx rule must fire on this module.

Nothing here is executed — the AST pass reads source only. Each function
is the minimal natural form of the hazard its rule describes.
"""

import time

import jax
import jax.numpy as jnp


def a201_branch_on_traced(x):
    y = jnp.mean(x)
    if y > 0:  # A201: traced value in Python control flow
        x = x + 1.0
    for v in jnp.arange(4):  # A201: loop unrolls into the program
        x = x + v
    return x


def a202_key_reuse(shape):
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)  # A202: same key, both draws related
    return a + b


def a203_epoch_loop(loader, model):
    seen = 0
    for epoch in range(3):  # A203: no loader.set_epoch(epoch)
        for batch in loader:
            seen += 1
    return seen


def a204_unbracketed_timing(step, ts, batch):
    t0 = time.time()
    ts, metrics = step(ts, *batch)
    elapsed = time.time() - t0  # A204: dispatch returned, device still busy
    rate = jnp.asarray(batch[0].shape[0] / elapsed)
    return ts, rate
