# Fixture package for tests/test_analysis.py: seeded_violations.py must
# trip every AST rule, clean.py none. Lives under tests/ so the repo-wide
# analyzer run (tpudml/ tasks/ tools/) never sees the seeded violations.
