"""Round-20 MFU-gap fusions: the fused decode tail, the fused train
attention junction, and the psum-overlapped TP matmul.

Load-bearing properties:

- ``fused_decode_head`` (Pallas machinery, interpret mode) emits tokens
  EXACTLY equal to ``argmax(x @ W + b)`` — first-occurrence ties and
  padded vocab tails included — plus the f32 online (max, lse)
  statistics, under plain, row-sharded (DP), vocab-sharded (TP), and
  rows×vocab (FSDP×TP) compositions;
- the int8 variant's greedy picks are bitwise those of the dequantized-
  weights oracle (``serve/fleet/quant.py`` op order), pinned kernel-
  level and end-to-end on the serving engine's fixture prompts;
- ``fused_attn_junction`` is the same function as the unfused block
  junction — values AND gradients at the single-shard parity tolerances
  (rtol=1e-5/atol=1e-6) — standalone and under the sharded regimes;
- ``tp_overlap_matmul`` equals the unchunked ``psum(x @ w)`` in value
  and gradient (the chunk split is over rows the reduce never mixes);
- the train engines' ``flash_attn`` knob changes the attention DISPATCH
  only: DP/TP/FSDP trajectories match the unfused engines exactly on
  CPU (reference-dispatch plumbing, like test_fused_compose's contract)
  and the capability row rejects the ring/ulysses and seq_sharded
  compositions at construction.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import TransformerLM
from tpudml.ops.decode_head import (
    _reference_head,
    fused_decode_head,
    fused_decode_head_int8,
)
from tpudml.ops.junction_kernel import (
    fused_attn_junction,
    reference_attn_junction,
)
from tpudml.optim import make_optimizer
from tpudml.parallel.sharding import shard_map_fn

V = 48


def _model(**kw):
    cfg = dict(vocab_size=V, embed_dim=32, num_heads=4, num_layers=2,
               max_len=64, rope=True)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _assert_tree_close(a, b, rtol=1e-5, atol=1e-6):
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(b))
    for path, la in flat_a:
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(flat_b[path]), rtol=rtol, atol=atol,
            err_msg=jax.tree_util.keystr(path),
        )


# -------------------------------------------------- decode tail: kernel


def _head_operands(n=16, d=8, v=64, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(d, v)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(v,)).astype(np.float32))
    return x, w, b


@pytest.mark.parametrize("v", [64, 70])  # 70: padded vocab tail masked
def test_decode_head_interpret_matches_reference(v):
    x, w, b = _head_operands(v=v)
    tok, mx, lse = fused_decode_head(
        x, w, b, block_n=8, block_v=32, interpret=True)
    rt, rm, rl = _reference_head(x, w, b)
    np.testing.assert_array_equal(np.asarray(tok), np.asarray(rt))
    np.testing.assert_allclose(np.asarray(mx), np.asarray(rm), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(rl),
                               rtol=1e-5, atol=1e-6)


def test_decode_head_first_occurrence_tie_break():
    """Duplicated max columns — including duplicates split ACROSS vocab
    tiles — must resolve to the first occurrence, like jnp.argmax."""
    x = jnp.ones((4, 4), jnp.float32)
    w = jnp.zeros((4, 96), jnp.float32)
    # row of logits all equal -> pick must be column 0; then plant an
    # early max duplicated in a LATER tile (block_v=32: cols 7 and 40).
    w = w.at[:, 7].set(2.0).at[:, 40].set(2.0)
    tok, _, _ = fused_decode_head(
        x, w, None, block_n=8, block_v=32, interpret=True)
    assert np.asarray(tok).tolist() == [7, 7, 7, 7]
    flat = jnp.zeros((4, 96), jnp.float32)
    tok0, _, _ = fused_decode_head(
        x, flat, None, block_n=8, block_v=32, interpret=True)
    assert np.asarray(tok0).tolist() == [0, 0, 0, 0]


def test_decode_head_int8_bitwise_vs_dequant_oracle():
    """The in-kernel per-tile dequant follows the oracle's exact op
    order, so picks AND statistics are bitwise those of the f32 kernel
    on dequantize(wq, scale)."""
    from tpudml.serve.fleet.quant import _dequant_kernel, _quant_kernel

    x, w, b = _head_operands(v=64, seed=3)
    wq, scale = _quant_kernel(w)
    tok, mx, lse = fused_decode_head_int8(
        x, wq, scale, b, block_n=8, block_v=32, interpret=True)
    rt, rm, rl = fused_decode_head(
        x, _dequant_kernel(wq, scale), b, block_n=8, block_v=32,
        interpret=True)
    np.testing.assert_array_equal(np.asarray(tok), np.asarray(rt))
    np.testing.assert_array_equal(np.asarray(mx), np.asarray(rm))
    np.testing.assert_array_equal(np.asarray(lse), np.asarray(rl))


def test_decode_head_sharded_compositions():
    """The fused head under the engine shardings: rows over data (DP),
    vocab over model with an online (m, lse, tok) shard merge (TP), and
    rows×vocab (FSDP×TP) — tokens exact, statistics at parity tolerance
    against the unsharded reference."""
    x, w, b = _head_operands(n=16, d=8, v=64, seed=5)
    rt, rm, rl = _reference_head(x, w, b)

    def check(tok, mx, lse):
        np.testing.assert_array_equal(np.asarray(tok), np.asarray(rt))
        np.testing.assert_allclose(np.asarray(mx), np.asarray(rm), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(rl),
                                   rtol=1e-5, atol=1e-6)

    # DP: rows sharded, everything else replicated — pure map.
    dp = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])

    def dp_body(x, w, b):
        return fused_decode_head(x, w, b, block_n=8, block_v=32,
                                 interpret=True)

    check(*shard_map_fn(
        dp_body, dp, in_specs=(P("data"), P(), P()),
        out_specs=(P("data"), P("data"), P("data")))(x, w, b))

    # TP: vocab sharded; each shard picks over its slice, then the
    # global pick is the max-logit shard's local pick offset by its
    # vocab base (strict > with index tie-break = first occurrence),
    # and lse merges by the online rule — the same merge the sharded
    # xent head uses for its statistics.
    tp = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])

    def tp_body(x, w, b):
        v_loc = w.shape[1]
        base = jax.lax.axis_index("model") * v_loc
        tok, m, lse = fused_decode_head(x, w, b, block_n=8, block_v=16,
                                        interpret=True)
        gm = jax.lax.all_gather(m, "model", axis=1)          # [n, S]
        gt = jax.lax.all_gather(tok + base, "model", axis=1)  # [n, S]
        gl = jax.lax.all_gather(lse, "model", axis=1)
        best = jnp.argmax(gm, axis=1)                         # first occ.
        rows = jnp.arange(gm.shape[0])
        mx = gm[rows, best]
        lse = mx + jnp.log(jnp.sum(jnp.exp(gl - mx[:, None]), axis=1))
        return gt[rows, best], mx, lse

    check(*shard_map_fn(
        tp_body, tp,
        in_specs=(P(), P(None, "model"), P("model")),
        out_specs=(P(), P(), P()))(x, w, b))

    # FSDP×TP: rows over data AND vocab over model — the 2-D engine
    # layout; per-row merge identical to TP on the data-local rows.
    ft = make_mesh(MeshConfig({"data": 2, "model": 2}), jax.devices()[:4])
    check(*shard_map_fn(
        tp_body, ft,
        in_specs=(P("data"), P(None, "model"), P("model")),
        out_specs=(P("data"), P("data"), P("data")))(x, w, b))


# --------------------------------------------- decode tail: serve engine


def _fixture_requests():
    """Committed fixture prompts: fixed token ids, not random draws, so
    the greedy streams this file pins are reproducible byte-for-byte."""
    from tpudml.serve import Request

    prompts = [
        [1, 7, 3, 12, 9],
        [40, 2, 2, 31],
        [5, 19, 23, 8, 44, 17],
        [11, 30],
    ]
    return [
        Request(rid=i, prompt=np.asarray(p, np.int32), max_new_tokens=6)
        for i, p in enumerate(prompts)
    ]


def _greedy_streams(model, params, **cfg_kw):
    from tpudml.serve import ServeConfig, ServingEngine

    cfg = ServeConfig(slots=2, max_len=32, prefill_chunk=4, **cfg_kw)
    rep = ServingEngine(model, params, cfg).run(_fixture_requests())
    return {rid: st.tokens for rid, st in rep.requests.items()}


@pytest.fixture(scope="module")
def served():
    model = _model(num_kv_heads=2)
    params, _ = model.init(jax.random.key(0))
    return model, params


def test_engine_fused_head_greedy_parity(served):
    """fused_head=True serves the exact unfused token streams on the
    fixture prompts (greedy decode is a pure function of the logits
    argmax, which the fused tail reproduces tie-for-tie)."""
    model, params = served
    assert _greedy_streams(model, params, fused_head=True) == \
        _greedy_streams(model, params)


def test_engine_fused_head_int8_greedy_parity(served):
    """The full int8 fused tail: int8 codes + scales fed straight to the
    kernel equal the int8_sim oracle path (dequantized f32 weights,
    unfused tail) token-for-token on the fixture prompts."""
    model, params = served
    fused = _greedy_streams(model, params, fused_head=True,
                            weight_quant="int8")
    oracle = _greedy_streams(model, params, weight_quant="int8_sim")
    assert fused == oracle


def test_engine_fused_head_rejects_non_dense(served):
    """The capability row: fused_head composes with the dense single-
    device step only — paged layout and spec decode reject at init with
    the table's message."""
    from tpudml.serve import ServeConfig, ServingEngine
    from tpudml.serve.engine import ServeCompositionError

    model, params = served
    with pytest.raises(ServeCompositionError, match="fused_head"):
        ServingEngine(model, params, ServeConfig(
            slots=2, max_len=32, prefill_chunk=4, fused_head=True,
            cache_layout="paged", page_size=4))
    with pytest.raises(ServeCompositionError, match="fused_head"):
        ServingEngine(model, params, ServeConfig(
            slots=2, max_len=32, prefill_chunk=4, fused_head=True,
            spec_k=2))


def test_cost_model_prices_fused_tail(served):
    """DecodeCostModel drops the [B, V] logits round-trip from the
    per-slot HBM bytes when the tail is fused — fused step_seconds is
    strictly cheaper at every occupancy."""
    from tpudml.serve import ServeConfig
    from tpudml.serve.sched import DecodeCostModel, SLOConfig

    model, _ = served
    slo = SLOConfig(tpot_budget_s=0.01)
    plain = DecodeCostModel(
        model, ServeConfig(slots=2, max_len=32, prefill_chunk=4), slo)
    fused = DecodeCostModel(
        model, ServeConfig(slots=2, max_len=32, prefill_chunk=4,
                           fused_head=True), slo)
    assert fused.tail_bytes_per_slot == 0
    assert plain.tail_bytes_per_slot == 2 * V * 4
    for n in (1, 2):
        assert fused.step_seconds(n) < plain.step_seconds(n)


# ------------------------------------------------------- junction block


def _junction_operands(b=2, t=16, h=4, dh=8, seed=0):
    rng = np.random.default_rng(seed)
    d = h * dh
    f32 = lambda *s: jnp.asarray(rng.normal(size=s).astype(np.float32))
    return (f32(b, t, h, dh), f32(b, t, h, dh), f32(b, t, h, dh),
            f32(b, t, d), f32(d, d) * 0.2, f32(d), f32(d), f32(d))


def _junction_loss(fn):
    def loss(q, k, v, r, wo, bo, scale, bias):
        s, y = fn(q, k, v, r, wo, bo, scale, bias)
        return jnp.sum(y * jnp.cos(s)) + jnp.sum(s * s) * 1e-2
    return loss


def test_junction_grad_parity_single_shard():
    """The representative tier-1 grad-exact case: the fused junction's
    chained kernel vjps (flash recompute-tiles → projection transpose →
    add+LN one-pass) equal the unfused reference end to end in
    interpret mode."""
    ops = _junction_operands()
    lf, gf = jax.value_and_grad(
        _junction_loss(lambda *a: fused_attn_junction(*a, interpret=True)),
        argnums=tuple(range(8)))(*ops)
    lr, gr = jax.value_and_grad(
        _junction_loss(reference_attn_junction),
        argnums=tuple(range(8)))(*ops)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-6)
    _assert_tree_close(gf, gr)


@pytest.mark.slow
def test_junction_grad_parity_sharded_sweep():
    """The heaviest parity sweep: the fused junction under each train
    regime's sharding — batch over data (DP), heads gathered over model
    (TP), and batch×heads with the out-projection FSDP-gathered over
    data (FSDP×TP) — gradients at single-shard tolerances against the
    unsharded reference. The junction is batch-parallel; feature-bearing
    operands follow the fused-xent compose discipline: gather on use,
    psum the data-sharded row-sum loss."""
    ops = _junction_operands(b=4, seed=7)
    lr, gr = jax.value_and_grad(
        _junction_loss(reference_attn_junction),
        argnums=tuple(range(8)))(*ops)

    def check(fn, in_specs, mesh):
        sharded = shard_map_fn(
            fn, mesh, in_specs=in_specs, out_specs=P())
        ls, gs = jax.value_and_grad(sharded, argnums=tuple(range(8)))(*ops)
        np.testing.assert_allclose(float(ls), float(lr), rtol=1e-6)
        _assert_tree_close(gs, gr)

    fused = _junction_loss(
        lambda *a: fused_attn_junction(*a, interpret=True))

    # DP: batch rows sharded, weights replicated; the loss is a SUM over
    # rows, so the shard merge is psum.
    dp = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])

    def dp_body(*a):
        return jax.lax.psum(fused(*a), "data")

    check(dp_body,
          (P("data"), P("data"), P("data"), P("data"), P(), P(), P(), P()),
          dp)

    # TP: heads sharded over model, gathered on use (causal attention
    # needs every head's full sequence; the junction consumes the
    # gathered block, per-shard loss already replicated).
    tp = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])

    def tp_body(q, k, v, *rest):
        qg = jax.lax.all_gather(q, "model", axis=2, tiled=True)
        kg = jax.lax.all_gather(k, "model", axis=2, tiled=True)
        vg = jax.lax.all_gather(v, "model", axis=2, tiled=True)
        return fused(qg, kg, vg, *rest)

    hs = P(None, None, "model")
    check(tp_body, (hs, hs, hs, P(), P(), P(), P(), P()), tp)

    # FSDP×TP: batch over data AND heads over model, wo row-sharded
    # over data and gathered on use (its transpose is the ZeRO
    # reduce-scatter for dWo), loss pmean'd over data.
    ft = make_mesh(MeshConfig({"data": 2, "model": 2}), jax.devices()[:4])

    def ft_body(q, k, v, r, wo, *rest):
        qg = jax.lax.all_gather(q, "model", axis=2, tiled=True)
        kg = jax.lax.all_gather(k, "model", axis=2, tiled=True)
        vg = jax.lax.all_gather(v, "model", axis=2, tiled=True)
        wg = jax.lax.all_gather(wo, "data", axis=0, tiled=True)
        return jax.lax.psum(fused(qg, kg, vg, r, wg, *rest), "data")

    bhs = P("data", None, "model")
    check(ft_body,
          (bhs, bhs, bhs, P("data"), P("data"), P(), P(), P()), ft)


# ------------------------------------------------ train engines × flash


def _tokens(seed=3, b=4, t=16):
    rng = np.random.default_rng(seed)
    return rng.integers(0, V, size=(b, t + 1)).astype(np.int32)


def _run_steps(engine, steps=2, seed=3):
    ts = engine.create_state(seed_key(0))
    step = engine.make_train_step()
    batch = _tokens(seed)
    losses = []
    for _ in range(steps):
        ts, m = step(ts, batch[:, :-1], batch[:, 1:])
        losses.append(float(m["loss"]))
    return ts, losses


def test_dp_flash_attn_matches_unfused():
    from tpudml.parallel.dp import DataParallel

    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    model = _model(max_len=16)
    common = dict(stacked_batches=False)
    ts_f, loss_f = _run_steps(
        DataParallel(model, make_optimizer("sgd", 0.05), mesh,
                     flash_attn=True, **common))
    ts_u, loss_u = _run_steps(
        DataParallel(model, make_optimizer("sgd", 0.05), mesh, **common))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_tp_and_fsdp_flash_attn_match_unfused():
    from tpudml.parallel.fsdp import FSDP
    from tpudml.parallel.mp import GSPMDParallel, tensor_parallel_rules

    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    model = _model(max_len=16)

    def tp_eng(flash):
        return GSPMDParallel(
            model, make_optimizer("sgd", 0.05), mesh,
            rule=tensor_parallel_rules("model"), axis_name="model",
            flash_attn=flash)

    ts_f, loss_f = _run_steps(tp_eng(True))
    ts_u, loss_u = _run_steps(tp_eng(False))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)

    fmesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])

    def fs_eng(flash):
        return FSDP(model, make_optimizer("sgd", 0.05), fmesh,
                    flash_attn=flash)

    ts_f, loss_f = _run_steps(fs_eng(True))
    ts_u, loss_u = _run_steps(fs_eng(False))
    np.testing.assert_allclose(loss_f, loss_u, rtol=1e-5)
    _assert_tree_close(ts_f.params, ts_u.params)


def test_flash_attn_rejects_non_dense_trunks():
    """The capability row: flash_attn swaps the DENSE causal trunk only
    — ring/ulysses trunks (already sequence-fused) and seq_sharded
    models reject at construction with the table's key."""
    from tpudml.capabilities import CompositionError
    from tpudml.parallel.dp import DataParallel

    mesh = make_mesh(MeshConfig({"data": 2}), jax.devices()[:2])
    opt = make_optimizer("sgd", 0.05)
    with pytest.raises(CompositionError, match="flash_attn"):
        DataParallel(_model(max_len=16, impl="ring", seq_sharded=True),
                     opt, mesh, flash_attn=True)


# --------------------------------------------------- TP overlap matmul


def test_tp_overlap_matmul_value_and_grad_parity():
    """Chunked psum-overlapped matmul == unchunked psum(x @ w) in value
    and gradient under TP and FSDP×TP meshes (the chunk split is over
    rows the reduce never mixes)."""
    from tpudml.parallel.overlap import tp_overlap_matmul

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))

    def run(mesh, body, in_specs):
        fn = shard_map_fn(body, mesh, in_specs=in_specs, out_specs=P())
        loss = lambda x, w: jnp.sum(jnp.sin(fn(x, w)))
        return jax.value_and_grad(loss, argnums=(0, 1))(x, w)

    tp = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    specs = (P(), P(None, "model"))

    lo, go = run(tp, lambda x, w: tp_overlap_matmul(
        x, w, axis_name="model"), specs)
    lr, gr = run(tp, lambda x, w: jax.lax.psum(
        jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype),
        "model"), specs)
    np.testing.assert_allclose(float(lo), float(lr), rtol=1e-6)
    _assert_tree_close(go, gr)

    ft = make_mesh(MeshConfig({"data": 2, "model": 2}), jax.devices()[:4])
    ft_specs = (P("data"), P(None, "model"))
    lo, go = run(ft, lambda x, w: tp_overlap_matmul(
        x, w, axis_name="model", chunks=2), ft_specs)
    lr, gr = run(ft, lambda x, w: jax.lax.psum(
        jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype),
        "model"), ft_specs)
    np.testing.assert_allclose(float(lo), float(lr), rtol=1e-6)
    _assert_tree_close(go, gr)


def test_tp_overlap_rejects_trivial_axis():
    from tpudml.capabilities import CompositionError
    from tpudml.parallel.overlap import tp_overlap_matmul

    mesh = make_mesh(MeshConfig({"model": 1}), jax.devices()[:1])
    x = jnp.ones((4, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)
    body = shard_map_fn(
        lambda x, w: tp_overlap_matmul(x, w, axis_name="model"),
        mesh, in_specs=(P(), P()), out_specs=P())
    with pytest.raises(CompositionError, match="tp_overlap"):
        body(x, w)


def test_planner_enumerates_and_prices_overlap():
    """plan/space enumerates tp_overlap TP candidates and plan/score
    prices them with the exposed-vs-hidden split: overlap moves exactly
    (K−1)/K of the TP wire from exposed to hidden, total wire equal."""
    import dataclasses

    from tpudml.parallel.overlap import OVERLAP_CHUNKS
    from tpudml.plan.score import score_candidate
    from tpudml.plan.space import enumerate_candidates, flagship_lm

    cands = [c for c in enumerate_candidates(4, engines=("tp",))
             if c.tp_overlap]
    assert cands, "no overlap TP candidate enumerated"
    cand = cands[0]
    spec = flagship_lm()
    on = score_candidate(spec, cand)
    off = score_candidate(spec, dataclasses.replace(cand, tp_overlap=False))
    moved = off.exposed_comm_s - on.exposed_comm_s
    assert moved > 0
    # every second moved off the exposed term lands in the hidden term
    np.testing.assert_allclose(
        on.hidden_comm_s - off.hidden_comm_s, moved, rtol=1e-9)
    # and the split is exactly (K-1)/K of the overlap-eligible TP wire:
    # exposed kept 1/K, so moved = (K-1)/K * tp_wire.
    tp_wire_s = moved * OVERLAP_CHUNKS / (OVERLAP_CHUNKS - 1)
    np.testing.assert_allclose(
        on.exposed_comm_s - (off.exposed_comm_s - tp_wire_s),
        tp_wire_s / OVERLAP_CHUNKS, rtol=1e-9)
    assert on.comm_wire_bytes == off.comm_wire_bytes
