"""Torch-weight interop tests: a reference-architecture torch model's
state_dict loads into the tpudml model and produces matching logits on the
same inputs (the migration guarantee for reference users)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from tpudml.interop import lenet_params_from_torch, mlp_params_from_torch  # noqa: E402
from tpudml.models import ForwardMLP, LeNet  # noqa: E402


class TorchNet(tnn.Module):
    """The reference's Net (codes/task1/pytorch/model.py:16-35)."""

    def __init__(self):
        super().__init__()
        self.conv1 = tnn.Conv2d(1, 6, 5, padding=2)
        self.conv2 = tnn.Conv2d(6, 16, 5)
        self.pool = tnn.MaxPool2d(2, 2)
        self.fc1 = tnn.Linear(400, 120)
        self.fc2 = tnn.Linear(120, 10)

    def forward(self, x):
        x = self.pool(torch.relu(self.conv1(x)))
        x = self.pool(torch.relu(self.conv2(x)))
        x = x.flatten(1)
        return self.fc2(torch.relu(self.fc1(x)))


def test_lenet_logits_match_torch():
    tm = TorchNet().eval()
    x = np.random.default_rng(0).normal(size=(4, 1, 28, 28)).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x)).numpy()

    params = lenet_params_from_torch(tm.state_dict())
    model = LeNet()
    got = model(params, jnp.asarray(x.transpose(0, 2, 3, 1)))  # NCHW → NHWC
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_mlp_logits_match_torch():
    hidden = (512, 256, 128, 64, 32)
    layers = []
    prev = 784
    for h in hidden:
        layers += [tnn.Linear(prev, h), tnn.ReLU()]
        prev = h
    layers.append(tnn.Linear(prev, 10))
    tm = tnn.Sequential(*layers).eval()
    x = np.random.default_rng(1).normal(size=(4, 784)).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x)).numpy()

    params = mlp_params_from_torch(tm.state_dict())
    model = ForwardMLP()
    got = model(params, jnp.asarray(x.reshape(4, 28, 28, 1)))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_wrong_architecture_rejected():
    with pytest.raises(ValueError, match="expected 2 conv"):
        lenet_params_from_torch({"w.weight": np.zeros((6, 1, 5, 5))})
    with pytest.raises(ValueError, match="no linear"):
        mlp_params_from_torch({"w.weight": np.zeros((6, 1, 5, 5))})
