"""tpudml.mpmd: the heterogeneity parity proof and the e2e re-mesh drill.

Two cost tiers, same runtime code path (``mpmd/runtime.py`` is built to
run both ways):

- **in-process** — stage workers on threads over ``socketpair`` channels
  prove that a pipeline whose stages differ in microbatch count AND
  precision (bf16 trunk → f32 head) trains grad-exact against the
  equivalent single-program reference;
- **spawned** — the 2-stage×2-dp drill with a real SIGKILL: survivors
  drain, the planner is consulted fail-open, the groups re-form in place
  on fresh ports, and the resumed run's final params are CRC-identical
  to an uninterrupted reference of the re-meshed pipeline. The naive
  whole-world-restart A/B arm is slow-tier (it doubles the drill).
"""

import json
import threading

import numpy as np
import pytest

from tpudml.comm.p2p import channel_pair
from tpudml.mpmd import PipelineSpec, StageSpec
from tpudml.mpmd.runtime import (
    StageProgram,
    StageWorker,
    make_batch_fn,
    reference_step_fn,
    stage_layer_dims,
)

FEATURE, HIDDEN, CLASSES = 8, (16,), 4
LR, MOMENTUM, SEED = 0.1, 0.9, 0


def _hetero_spec() -> PipelineSpec:
    return PipelineSpec(
        stages=(
            StageSpec("trunk", dp=1, microbatches=2, dtype="bfloat16"),
            StageSpec("head", dp=1, microbatches=1, dtype="float32"),
        ),
        global_batch=8,
    )


def test_hetero_pipeline_grad_exact_vs_single_program_reference():
    """ISSUE 18 acceptance: stages differing in microbatch count and
    precision train grad-exact (rtol=1e-5/atol=1e-6) against the
    equivalent single-program step — the reference makes the per-chunk
    bf16 roundings explicit, so the only daylight left is f32 summation
    order."""
    spec = _hetero_spec()
    steps = 5
    batch_for = make_batch_fn(spec.global_batch, FEATURE, CLASSES, SEED)
    edge = "s0r0->s1r0"
    ch_trunk, ch_head = channel_pair(edge, timeout_s=30.0)
    kw = dict(feature_dim=FEATURE, hidden=HIDDEN, classes=CLASSES,
              seed=SEED, lr=LR, momentum=MOMENTUM)
    trunk = StageWorker(
        spec, 0, 0,
        program=StageProgram(spec, 0, **kw), batch_for=batch_for,
        down_channels={edge: ch_trunk},
    )
    head = StageWorker(
        spec, 1, 0,
        program=StageProgram(spec, 1, **kw), batch_for=batch_for,
        up_features=stage_layer_dims(FEATURE, HIDDEN, CLASSES, 2)[0][-1][1],
        up_channels={edge: ch_head},
    )
    losses = {}

    def drive(worker, name):
        for k in range(steps):
            losses.setdefault(name, []).append(worker.run_step(k))

    ts = [threading.Thread(target=drive, args=(w, n))
          for n, w in [("trunk", trunk), ("head", head)]]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in ts), "pipeline deadlocked"
    ch_trunk.close(), ch_head.close()

    params, mom, step_fn = reference_step_fn(spec, **kw)
    ref_losses = []
    for k in range(steps):
        x, y = batch_for(k)
        params, mom, loss, _g = step_fn(params, mom, x, y)
        ref_losses.append(float(loss))

    np.testing.assert_allclose(
        losses["head"], ref_losses, rtol=1e-5, atol=1e-6
    )
    for stage_params, worker in [(params[0], trunk), (params[1], head)]:
        for ref_layer, got_layer in zip(stage_params, worker.program.params):
            for key in ("w", "b"):
                np.testing.assert_allclose(
                    got_layer[key], np.asarray(ref_layer[key]),
                    rtol=1e-5, atol=1e-6,
                )
    # The trunk's wire really carried bf16 (the precision boundary is
    # on the wire, not just in the jit).
    assert trunk.program.dtype == np.dtype("bfloat16")
    assert head.losses and not trunk.losses[0]  # head owns the loss


def test_remesh_drill_e2e_bit_exact(tmp_path):
    """The tentpole e2e: 2-stage×2-dp MPMD run, SIGKILL of stage 1 rank
    1 at step 13 → all three survivors drain at the step boundary →
    planner consulted fail-open (receipts recorded) → groups re-form in
    place [2,2]→[2,1] on fresh ports → resume from the step-10
    checkpoint → every surviving rank's final params AND loss history
    CRC-identical to an uninterrupted reference run of the re-meshed
    pipeline from the same checkpoint."""
    from tpudml.mpmd.drill import run_mpmd_drill

    rep = run_mpmd_drill(str(tmp_path))
    assert rep["ok"], rep
    assert rep["bit_exact"] and rep["in_place"]
    assert rep["reforms"] == 1 and rep["stop_reason"] == "success"
    assert rep["final_stage_worlds"] == [2, 1]
    assert rep["victim"] == {"stage": 1, "rank": 1, "rc": 17, "slot": 3}
    assert rep["resume_step"] == 10 and rep["steps_lost"] == 3
    assert rep["fresh_ports"]
    assert rep["replan_error"] is None and rep["replan_receipts"]
    assert sorted(rep["params_crc"]) == ["s0r0", "s0r1", "s1r0"]
    # dp replicas of the trunk converge to identical params.
    assert rep["params_crc"]["s0r0"] == rep["params_crc"]["s0r1"]
    assert rep["trace_pids"] == [0, 1, 2]

    # The obs artifacts: merged per-stage trace + the report section.
    from tools.obs_report import report as obs_report
    from tpudml.obs.tracer import validate_chrome_trace

    merged = json.loads((tmp_path / "obs" / "trace.json").read_text())
    validate_chrome_trace(merged)
    names = {
        m["args"]["name"] for m in merged["traceEvents"]
        if m.get("ph") == "M" and m.get("name") == "process_name"
    }
    assert names == {"mpmd stage 0", "mpmd stage 1", "mpmd controller"}
    comm = [e for e in merged["traceEvents"] if e.get("cat") == "comm"]
    assert any(e["args"].get("edge", "").startswith("s0r") for e in comm)

    rendered = obs_report(tmp_path)
    assert "MPMD re-mesh" in rendered
    assert "bit_exact=True" in rendered
    assert "p2p_send:act" in rendered


@pytest.mark.slow
def test_remesh_beats_whole_world_restart(tmp_path):
    """The A/B arm: the same kill under ``--drain_mode abort`` makes
    every surviving group's containment fire (the whole-world restart
    an SPMD job would pay); both arms anchor MTTR on the kill marker's
    mtime, so the comparison is measured on one clock."""
    from tpudml.mpmd.drill import run_mpmd_drill

    rep = run_mpmd_drill(str(tmp_path), include_naive=True)
    assert rep["ok"], rep
    assert rep["naive"] and rep["naive"]["success"]
    assert rep["naive"]["restart_mttr_s"] is not None
    assert rep["remesh_beats_naive"]
