"""Micro-batched pipeline (GPipe) tests on the simulated CPU mesh.

Load-bearing property (SURVEY.md §7: model-parallel parity = loss-curve
equivalence, not mechanism equivalence): the pipelined forward/backward
over S stages × M micro-batches is mathematically the plain sequential
model — so logits, gradients, and whole training trajectories must match a
single-device reference to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.nn import Activation, Dense, Sequential
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import make_optimizer
from tpudml.parallel.pp import GPipe

STAGES = 4
WIDTH = 32
BATCH = 16


def make_pipe(n_microbatches=8, mesh=None, opt=None):
    mesh = mesh or make_mesh(MeshConfig({"stage": STAGES}), jax.devices()[:STAGES])
    block = Sequential((Dense(WIDTH, WIDTH), Activation(jax.nn.relu)))
    return GPipe(
        block,
        n_microbatches=n_microbatches,
        mesh=mesh,
        optimizer=opt or make_optimizer("sgd", 0.05, momentum=0.9),
        prologue=Dense(16, WIDTH),
        epilogue=Dense(WIDTH, 10),
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, 16)).astype(np.float32)
    y = rng.integers(0, 10, size=(BATCH,)).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


@pytest.mark.parametrize("n_mb", [1, 2, 8, 16])
def test_forward_matches_sequential(batch, n_mb):
    """n_mb=1 is the reference task4 regime (degenerate pipeline); higher
    micro-batch counts must not change the math."""
    x, _ = batch
    pipe = make_pipe(n_mb)
    params = pipe.init_params(seed_key(0))
    got = pipe.make_forward()(params, x)
    want = pipe.sequential_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def test_train_step_matches_single_device_update(batch):
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = make_pipe(8, opt=opt)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)

    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)
    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )
    assert int(new_ts.step) == 1


def test_training_trajectory_parity_and_descent(batch):
    """Five pipeline steps == five single-device steps (the §7 parity
    criterion), and the loss goes down."""
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = make_pipe(4, opt=opt)
    ts = pipe.create_state(seed_key(2))
    ref_params = jax.device_get(ts.params)
    ref_opt = opt.init(ref_params)
    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)

    step = pipe.make_train_step()
    losses = []
    for _ in range(5):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_opt = opt.update(g, ref_opt, ref_params)

    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5)
    assert losses[-1] < losses[0]


def test_remat_matches_plain(batch):
    """remat=True recomputes tick activations in backward — identical math."""
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    mesh = make_mesh(MeshConfig({"stage": STAGES}), jax.devices()[:STAGES])
    block = Sequential((Dense(WIDTH, WIDTH), Activation(jax.nn.relu)))
    results = []
    for remat in (False, True):
        pipe = GPipe(
            block, n_microbatches=4, mesh=mesh,
            optimizer=opt, prologue=Dense(16, WIDTH), epilogue=Dense(WIDTH, 10),
            remat=remat,
        )
        ts = pipe.create_state(seed_key(4))
        step = pipe.make_train_step()
        for _ in range(2):
            ts, m = step(ts, x, y)
        results.append(ts)
    for a, b in zip(
        jax.tree.leaves(results[0].params), jax.tree.leaves(results[1].params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_batch_not_divisible_raises(batch):
    x, y = batch
    pipe = make_pipe(3)  # 16 % 3 != 0
    with pytest.raises(ValueError, match="not divisible"):
        pipe.make_forward()(pipe.init_params(seed_key(0)), x)


def test_stateful_block_rejected():
    from tpudml.nn import BatchNorm

    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    pipe = GPipe(BatchNorm(WIDTH), 2, mesh, make_optimizer("sgd", 0.1))
    with pytest.raises(ValueError, match="stateless"):
        pipe.init_params(seed_key(0))


def test_clip_in_pipeline_keeps_replicas_synced(batch):
    """ClipByGlobalNorm under GPipe: the engine psums the squared norm over
    the stage axis (stage leaves are device-local slices), so every device
    derives the SAME clip scale and the replicated prologue/epilogue
    parameters stay bitwise identical — and the clipped update matches a
    single-device reference computing the true global norm."""
    from tpudml.optim import ClipByGlobalNorm, Sgd

    x, y = batch
    # Tiny max_norm: every step clips, making any per-stage norm divergence
    # visible as replica de-sync.
    opt = ClipByGlobalNorm(Sgd(lr=0.1), max_norm=1e-2)
    pipe = make_pipe(opt=opt)
    assert pipe.optimizer.axes == ("stage",)  # engine rewrapped the clip
    ts = pipe.create_state(seed_key(2))
    step = pipe.make_train_step()

    # Single-device reference on identical math.
    ref_params = jax.device_get(ts.params)
    ref_state = ()

    def ref_loss(p):
        return softmax_cross_entropy(pipe.sequential_forward(p, x), y)

    for _ in range(3):
        ts, _ = step(ts, x, y)
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_state = ClipByGlobalNorm(Sgd(lr=0.1), max_norm=1e-2).update(
            g, ref_state, ref_params
        )

    pro = ts.params["prologue"]["kernel"]
    shards = [np.asarray(s.data) for s in pro.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)
