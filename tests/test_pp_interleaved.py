"""Interleaved (virtual-stage) 1F1B: V model chunks per device.

Parity oracle: sequential application of the V*S blocks in virtual-stage
order (sigma = v*S + s -> device s chunk v) on one device. The verdict-r2
stretch item: bubble below plain 1F1B's (S-1)/(M+S-1) by making each
ramp tick one block instead of V blocks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.nn import Activation, Dense, Dropout, Sequential
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import make_optimizer
from tpudml.parallel.pp import Interleaved1F1B

STAGES = 4
WIDTH = 24
BATCH = 16


def make_pipe(n_mb=4, v_chunks=2, opt=None, dropout=0.0, rng_root=None,
              n_data=1):
    layers = [Dense(WIDTH, WIDTH), Activation(jax.nn.relu)]
    if dropout:
        layers.append(Dropout(dropout))
    if n_data > 1:
        mesh = make_mesh(
            MeshConfig({"data": n_data, "stage": STAGES}),
            jax.devices()[: n_data * STAGES],
        )
    else:
        mesh = make_mesh(MeshConfig({"stage": STAGES}), jax.devices()[:STAGES])
    return Interleaved1F1B(
        Sequential(tuple(layers)),
        n_microbatches=n_mb,
        mesh=mesh,
        optimizer=opt or make_optimizer("sgd", 0.05, momentum=0.9),
        prologue=Dense(12, WIDTH),
        epilogue=Dense(WIDTH, 10),
        v_chunks=v_chunks,
        rng_root=rng_root,
        batch_axis="data" if n_data > 1 else None,
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(11)
    x = rng.normal(size=(BATCH, 12)).astype(np.float32)
    y = rng.integers(0, 10, size=(BATCH,)).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


@pytest.mark.parametrize("n_mb,v", [(4, 2), (8, 2), (4, 3), (4, 1)])
def test_update_matches_single_device(batch, n_mb, v):
    """V*S-block model: first interleaved update == single-device update.
    v=1 degenerates to the plain 1F1B schedule."""
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = make_pipe(n_mb, v, opt=opt)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)

    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)
    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )


def _make_pipe_stages(n_stages, n_mb=4, v_chunks=2, opt=None):
    mesh = make_mesh(MeshConfig({"stage": n_stages}), jax.devices()[:n_stages])
    return Interleaved1F1B(
        Sequential((Dense(WIDTH, WIDTH), Activation(jax.nn.relu))),
        n_microbatches=n_mb,
        mesh=mesh,
        optimizer=opt or make_optimizer("sgd", 0.05, momentum=0.9),
        prologue=Dense(12, WIDTH),
        epilogue=Dense(WIDTH, 10),
        v_chunks=v_chunks,
    )


@pytest.mark.parametrize("n_stages,v", [(3, 2), (3, 3), (5, 2)])
def test_update_matches_single_device_odd_stages(batch, n_stages, v):
    """Odd S exercises the parity-class half-buffer ring ticks (fwd ships
    chunks v ≡ t+s, bwd the complement; v=3 additionally exercises the
    ragged-parity pad slot, and S=5 a longer odd ring's wrap edge)."""
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = _make_pipe_stages(n_stages, v_chunks=v, opt=opt)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)

    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)
    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )


def _ppermute_bytes(jaxpr, mult=1):
    """Total ppermute operand bytes across the jaxpr, scan-length-weighted
    (the transfer-volume accounting of the ring schedule)."""
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "ppermute":
            total += mult * sum(
                int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
                for v in eqn.invars
            )
        m2 = mult * eqn.params["length"] if eqn.primitive.name == "scan" else mult
        for p in eqn.params.values():
            for j in (p if isinstance(p, (list, tuple)) else [p]):
                # ClosedJaxpr carries .jaxpr; shard_map's body is a raw
                # Jaxpr with .eqns directly.
                inner = getattr(j, "jaxpr", j)
                if hasattr(inner, "eqns"):
                    total += _ppermute_bytes(inner, m2)
    return total


def _step_ppermute_bytes(pipe, x, y):
    from jax.sharding import PartitionSpec as P

    from tpudml.parallel.sharding import shard_map_fn
    from tpudml.train import TrainState

    ts = pipe.create_state(seed_key(1))
    specs = TrainState(
        params=pipe.param_specs(), model_state=P(),
        opt_state=pipe.optimizer.init_spec(pipe.param_specs()), step=P(),
    )
    fn = shard_map_fn(
        pipe._spmd_step, pipe.mesh, in_specs=(specs, P(), P()),
        out_specs=(specs, P()),
    )
    jaxpr = jax.make_jaxpr(fn)(ts, x, y)
    return _ppermute_bytes(jaxpr.jaxpr)


def test_ring_bytes_at_the_combined_floor_for_even_and_odd_s(batch):
    """VERDICT r3 item 5 + r4 item 7's accounting: BOTH parities of S ship
    V act-slots per tick (for even V) — even S as ONE combined [V, act]
    ppermute, odd S as TWO [V/2, act] parity-class ppermutes (fwd lives
    on chunks v ≡ t+s, bwd on the complement; see the class docstring's
    ring-traffic note). The classic two-full-buffer tick would be
    2·V·act. (A [<V] combined buffer is not possible: on a live tick
    every in-window chunk of a device fires.)"""
    x, y = batch
    M, V = 4, 2
    even = _make_pipe_stages(4, n_mb=M, v_chunks=V)
    odd = _make_pipe_stages(3, n_mb=M, v_chunks=V)
    bytes_even = _step_ppermute_bytes(even, x, y)
    bytes_odd = _step_ppermute_bytes(odd, x, y)
    ticks_even = 2 * (M + V * 4 - 1)
    ticks_odd = 2 * (M + V * 3 - 1)
    per_tick_even = bytes_even / ticks_even
    per_tick_odd = bytes_odd / ticks_odd
    act_bytes = BATCH // M * WIDTH * 4  # f32 micro activation
    assert per_tick_even == V * act_bytes  # ONE [V, act] buffer per tick
    assert per_tick_odd == V * act_bytes   # TWO [V/2, act] parity halves


def test_odd_s_odd_v_ring_bytes_pad_one_slot(batch):
    """V odd on odd S: the parity classes are ragged (⌈V/2⌉ vs ⌊V/2⌋), so
    the static half-buffer pads one slot — 2·⌈V/2⌉ per tick, still under
    the classic 2·V whenever V > 1."""
    x, y = batch
    M, V = 4, 3
    odd = _make_pipe_stages(3, n_mb=M, v_chunks=V)
    per_tick = _step_ppermute_bytes(odd, x, y) / (2 * (M + V * 3 - 1))
    act_bytes = BATCH // M * WIDTH * 4
    assert per_tick == 2 * ((V + 1) // 2) * act_bytes  # 4 < 2·V = 6


def test_training_descends_with_dropout(batch):
    x, y = batch
    pipe = make_pipe(4, 2, dropout=0.2, rng_root=seed_key(7))
    ts = pipe.create_state(seed_key(2))
    step = pipe.make_train_step()
    losses = []
    for _ in range(8):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_dropout_without_rng_rejected():
    with pytest.raises(ValueError, match="rng_root"):
        make_pipe(4, 2, dropout=0.5).init_params(seed_key(0))


def test_interleaved_composes_with_dp(batch):
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = make_pipe(2, 2, opt=opt, n_data=2)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)
    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)
    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)
    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )
