"""Data-parallel engine tests on the simulated 8-device mesh.

The load-bearing property (SURVEY.md §4 integration tier): a DP step over N
replicas with aggregated gradients is mathematically the same optimization
as a single-device step on the concatenated global batch — so DP-vs-single
loss curves must match to float tolerance given the same seed and global
batch.
"""

import jax
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.models import LeNet
from tpudml.optim import make_optimizer
from tpudml.parallel.dp import DataParallel
from tpudml.train import TrainState, make_train_step

WORLD = 8
PER_REPLICA = 4
GLOBAL = WORLD * PER_REPLICA


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig({"data": WORLD}))


@pytest.fixture(scope="module")
def batch():
    images, labels = synthetic_classification(GLOBAL, (28, 28, 1), 10, seed=7)
    return np.asarray(images), np.asarray(labels)


def params_allclose(a, b, rtol=1e-5, atol=1e-6):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for xa, xb in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(xa), np.asarray(xb), rtol=rtol, atol=atol)


def run_steps(step, ts, batch, n=3):
    losses = []
    for _ in range(n):
        ts, m = step(ts, *batch)
        losses.append(float(m["loss"]))
    return ts, losses


@pytest.mark.parametrize("aggregation", ["allreduce", "allgather", "reducescatter"])
def test_dp_matches_single_device(mesh, batch, aggregation):
    model = LeNet()
    opt = make_optimizer("sgd", 0.01, momentum=0.9)

    dp = DataParallel(model, opt, mesh, aggregation=aggregation)
    ts_dp = dp.create_state(seed_key(0))
    step_dp = dp.make_train_step()
    ts_dp, losses_dp = run_steps(step_dp, ts_dp, batch)

    ts_1 = TrainState.create(model, opt, seed_key(0))
    step_1 = make_train_step(model, opt)
    ts_1, losses_1 = run_steps(step_1, ts_1, batch)

    np.testing.assert_allclose(losses_dp, losses_1, rtol=1e-4)
    params_allclose(ts_dp.params, ts_1.params, rtol=1e-4, atol=1e-5)


def test_split_step_matches_fused_and_counts_comm(mesh, batch):
    model = LeNet()
    opt = make_optimizer("sgd", 0.01, momentum=0.9)

    fused = DataParallel(model, opt, mesh)
    ts_f = fused.create_state(seed_key(0))
    ts_f, losses_f = run_steps(fused.make_train_step(), ts_f, batch)

    split = DataParallel(model, opt, mesh, measure_comm=True)
    ts_s = split.create_state(seed_key(0))
    ts_s, losses_s = run_steps(split.make_train_step(), ts_s, batch)

    np.testing.assert_allclose(losses_s, losses_f, rtol=1e-4)
    params_allclose(ts_s.params, ts_f.params, rtol=1e-4, atol=1e-5)
    assert split.comm_stats.calls == 3
    assert split.comm_stats.comm_time_s > 0.0


def test_bottleneck_injection_slows_steps(mesh, batch):
    import time

    model = LeNet()
    opt = make_optimizer("sgd", 0.01)
    delay = 0.05

    def best_of(step, ts, reps=3):
        # min-of-reps: one scheduler hiccup must not decide the test.
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            step(ts, *batch)
            times.append(time.perf_counter() - t0)
        return min(times)

    base = DataParallel(model, opt, mesh, measure_comm=True)
    ts = base.create_state(seed_key(0))
    step = base.make_train_step()
    step(ts, *batch)  # compile
    base_time = best_of(step, ts)

    slow = DataParallel(
        model, opt, mesh, measure_comm=True,
        bottleneck_rank=0, bottleneck_delay_s=delay,
    )
    ts2 = slow.create_state(seed_key(0))
    step2 = slow.make_train_step()
    step2(ts2, *batch)
    slow_time = best_of(step2, ts2)

    assert slow_time >= base_time + 0.8 * delay


def test_broadcast_params_restores_agreement(mesh):
    model = LeNet()
    opt = make_optimizer("sgd", 0.01)
    dp = DataParallel(model, opt, mesh)
    ts = dp.create_state(seed_key(3))
    ts_b = dp.broadcast_params(ts)
    params_allclose(ts_b.params, ts.params, rtol=0, atol=0)


def test_sharded_stacked_batch_accepted(mesh):
    """ShardedDataLoader's [world, B, ...] form flattens correctly."""
    model = LeNet()
    opt = make_optimizer("sgd", 0.01)
    dp = DataParallel(model, opt, mesh)
    images = np.random.default_rng(0).normal(size=(WORLD, 2, 28, 28, 1)).astype(np.float32)
    labels = np.zeros((WORLD, 2), np.int32)
    ts = dp.create_state(seed_key(0))
    ts2, m = dp.make_train_step()(ts, images, labels)
    assert int(ts2.step) == 1
    assert np.isfinite(float(m["loss"]))


def test_lm_batch_not_mistaken_for_stacked(mesh):
    """[B, T] token batches with B == world must NOT be flattened by the
    stacked-form inference (they are global batches, not stacked ones)."""
    model = LeNet()  # model unused; we only exercise shard_batch
    dp = DataParallel(model, make_optimizer("sgd", 0.01), mesh)
    tokens = np.ones((WORLD, 16), np.int32)
    labels = np.ones((WORLD, 16), np.int32)
    x, y = dp.shard_batch(tokens, labels)
    assert x.shape == (WORLD, 16)
    assert y.shape == (WORLD, 16)


def test_explicit_stacked_batches_flag(mesh):
    """stacked_batches=True flattens any [world, B, ...] form, including
    stacked LM batches the inference can't identify; False never flattens."""
    model = LeNet()
    dp_t = DataParallel(
        model, make_optimizer("sgd", 0.01), mesh, stacked_batches=True
    )
    tokens = np.ones((WORLD, 2, 16), np.int32)
    x, y = dp_t.shard_batch(tokens, tokens)
    assert x.shape == (WORLD * 2, 16)
    assert y.shape == (WORLD * 2, 16)

    dp_f = DataParallel(
        model, make_optimizer("sgd", 0.01), mesh, stacked_batches=False
    )
    imgs = np.ones((WORLD, 2, 28, 28, 1), np.float32)  # would match inference
    lbls = np.ones((WORLD, 2), np.int32)
    x, y = dp_f.shard_batch(imgs, lbls)
    assert x.shape == (WORLD, 2, 28, 28, 1)

    with pytest.raises(ValueError, match="stacked batch leading dim"):
        dp_t.shard_batch(np.ones((WORLD * 2, 2, 16)), np.ones((WORLD * 2, 2)))


def test_dispatch_throttle_overlaps_steps(mesh, batch):
    """The CPU-mesh dispatch window must genuinely overlap steps (>1 in
    flight — round 1 fully serialized, hiding TPU's async execution mode
    from every simulated run) while staying bounded (no XLA:CPU rendezvous
    pool exhaustion)."""
    model = LeNet()
    dp = DataParallel(model, make_optimizer("sgd", 0.01), mesh)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    images, labels = batch
    for _ in range(24):
        ts, m = step(ts, images, labels)
    jax.block_until_ready(m["loss"])
    assert dp._throttle.enabled
    assert 1 < dp._throttle.max_pending_seen <= dp._throttle.max_in_flight
    assert np.isfinite(float(m["loss"]))


def test_dispatch_throttle_unit():
    import jax.numpy as jnp

    from tpudml.parallel.sharding import DispatchThrottle

    mesh = make_mesh(MeshConfig({"data": WORLD}))
    th = DispatchThrottle(mesh, max_in_flight=3)
    vals = [jnp.zeros(()) + i for i in range(10)]
    for v in vals:
        th.after_step(v)
    assert th.max_pending_seen == 3
    assert len(th._pending) == 2  # window keeps max_in_flight - 1 after pop


def test_dp_final_accuracy_matches_single_device(mesh):
    """Quality-parity regression (VERDICT r2 item 6): trained to the same
    budget at matched global batch and steps, DP must reach the same test
    accuracy as single-device training — DP changes WHERE the math runs,
    not what is learned. (The recorded task2/task3 pins train the full
    60k-synthetic set to 99.9%; this is the fast in-suite version.)"""
    from tpudml.nn.losses import accuracy as acc_fn

    train_x, train_y = synthetic_classification(2048, (28, 28, 1), 10, seed=0,
                                                proto_seed=100)
    test_x, test_y = synthetic_classification(512, (28, 28, 1), 10, seed=1,
                                              proto_seed=100)
    test_x, test_y = jax.numpy.asarray(test_x), jax.numpy.asarray(test_y)
    batch = 256
    epochs = 3
    model = LeNet()
    accs = {}
    for regime in ("single", "dp"):
        opt = make_optimizer("adam", 2e-3)
        if regime == "dp":
            engine = DataParallel(model, opt, mesh, stacked_batches=False)
            ts = engine.create_state(seed_key(0))
            step = engine.make_train_step()
        else:
            ts = TrainState.create(model, opt, seed_key(0))
            step = make_train_step(model, opt)
        for _ in range(epochs):
            for i in range(0, len(train_x), batch):
                xb = jax.numpy.asarray(train_x[i:i + batch])
                yb = jax.numpy.asarray(train_y[i:i + batch])
                ts, _ = step(ts, xb, yb)
        logits, _ = model.apply(ts.params, ts.model_state, test_x, train=False)
        accs[regime] = float(acc_fn(logits, test_y))
    assert accs["dp"] > 0.9, accs
    assert abs(accs["dp"] - accs["single"]) < 0.02, accs
