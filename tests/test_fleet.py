"""Serving-fleet router contract (tpudml.serve.fleet.router).

Load-bearing properties: the fleet event log is BYTE-deterministic (a
committed golden pins the serialization, two runs re-serialize
identically), replica death conserves tokens exactly (drain → re-queue
as continuations → re-admit elsewhere; a finished request has precisely
its owed token count and — greedy decode being a pure function of the
prompt — the SAME tokens an uninterrupted run produces), the committed
CI fixtures replay meshless, and the composition/validation guards
reject the shapes the router cannot honestly serve. The spawned drill
(real processes, real SIGKILL, ElasticController supervision) is the
``slow``-marked e2e at the bottom.
"""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from tpudml.models import TransformerLM
from tpudml.serve import ServeCompositionError, ServeConfig, poisson_workload
from tpudml.serve.fleet import (
    FLEET_FIXTURE_VERSION,
    FleetConfig,
    FleetRouter,
    replay_fleet_fixture,
)

FIXTURES = Path(__file__).parent / "fleet_fixtures"
V = 48


def _model():
    return TransformerLM(vocab_size=V, embed_dim=32, num_heads=4,
                         num_kv_heads=2, num_layers=2, max_len=64)


@pytest.fixture(scope="module")
def setup():
    model = _model()
    params, _ = model.init(jax.random.key(0))
    return model, params


def _ecfg(**kw):
    base = dict(slots=2, max_len=64, prefill_chunk=8, step_time_s=0.01)
    base.update(kw)
    return ServeConfig(**base)


def _workload(n, qps, seed):
    requests, _ = poisson_workload(
        n, qps, seed, vocab_size=V, prompt_len=(4, 10), new_tokens=(4, 8),
    )
    return requests


# ------------------------------------------------- byte determinism


def test_golden_event_log_bytes(setup):
    """The steady fixture's event log re-serializes byte-for-byte
    against the committed golden — THE fleet determinism contract."""
    model, params = setup
    fixture = json.loads((FIXTURES / "steady.json").read_text())
    w = fixture["workload"]
    requests = _workload(w["n"], w["qps"], w["seed"])
    f = fixture["fleet"]
    cfg = FleetConfig(engine=ServeConfig(**f["engine"]),
                      replicas=f["replicas"], max_queue=f["max_queue"])
    report = FleetRouter(model, params, cfg).run(requests)
    golden = (FIXTURES / "golden_steady_events.json").read_text()
    assert report.canonical_events() == golden


def test_run_twice_byte_identical(setup):
    model, params = setup
    requests = _workload(12, 200.0, 7)
    cfg = FleetConfig(engine=_ecfg(), replicas=2, reform_after_steps=4)

    def go():
        rep = FleetRouter(model, params, cfg).run(
            requests, kills=[(4, 0)]
        )
        return rep.canonical_events(), {
            rid: list(st.tokens) for rid, st in rep.requests.items()
        }

    ev1, tok1 = go()
    ev2, tok2 = go()
    assert ev1 == ev2
    assert tok1 == tok2


# -------------------------------------------- drain/re-admit accounting


def test_drain_readmit_exact_accounting(setup):
    """A mid-run kill changes WHERE requests run, never their tokens:
    every request still finishes with exactly its owed count, and the
    per-request token streams equal the uninterrupted run's byte-for-
    byte (greedy decode is a pure function of the prompt, and the
    continuation re-prefills the identical prefix)."""
    model, params = setup
    requests = _workload(12, 200.0, 7)
    owed = {r.rid: r.max_new_tokens for r in requests}

    base_cfg = FleetConfig(engine=_ecfg(), replicas=2)
    clean = FleetRouter(model, params, base_cfg).run(requests)
    assert clean.finished == len(requests)

    cfg = FleetConfig(engine=_ecfg(), replicas=2, reform_after_steps=4)
    rep = FleetRouter(model, params, cfg).run(requests, kills=[(4, 0)])
    assert rep.kills == 1
    assert rep.drains >= 1
    assert rep.finished == len(requests)
    readmitted = [st for st in rep.requests.values() if st.readmits]
    assert readmitted, "the kill must have drained someone mid-flight"
    for rid, st in rep.requests.items():
        assert len(st.tokens) == owed[rid], rid
        assert st.tokens == clean.requests[rid].tokens, rid
    # Σ tokens conserved across the drain.
    assert rep.generated_tokens == sum(owed.values())
    # The drained requests really were re-placed: a second admit means a
    # second replicas_visited entry (possibly the SAME index if the
    # re-formed incarnation won the pricing — identity, not instance).
    for st in readmitted:
        assert len(st.replicas_visited) >= 2


def test_drained_request_keeps_original_deadline(setup):
    """Continuations expire against the ORIGINAL arrival (PR 9
    semantics) — a kill must not grant the victim a fresh deadline."""
    model, params = setup
    requests = _workload(6, 300.0, 5)
    # Deadline so tight the re-queued continuation cannot finish: the
    # re-admitted request must EXPIRE, not finish late.
    cfg = FleetConfig(
        engine=_ecfg(deadline_s=0.06, slots=1),
        replicas=1, reform_after_steps=2,
    )
    rep = FleetRouter(model, params, cfg).run(requests, kills=[(3, 0)])
    assert rep.drains >= 1
    # Terminal-state invariant: exactly one of finished/rejected/expired
    # per touched request, and nobody exceeds their owed budget.
    for rid, st in rep.requests.items():
        states = sum(x is not None
                     for x in (st.finished, st.rejected, st.expired))
        assert states <= 1
        assert len(st.tokens) <= st.max_new_tokens


# ----------------------------------------------------- fixture replay


@pytest.mark.parametrize("name", ["steady.json", "kill_drain.json"])
def test_fixture_replays_clean(name):
    fixture = json.loads((FIXTURES / name).read_text())
    report = replay_fleet_fixture(fixture)
    assert report["ok"], report["mismatches"]
    assert not report["mismatches"]


def test_fixture_version_gate():
    fixture = json.loads((FIXTURES / "steady.json").read_text())
    fixture["version"] = FLEET_FIXTURE_VERSION + 1
    with pytest.raises(ValueError, match="fixture version"):
        replay_fleet_fixture(fixture)


def test_fixture_detects_drift():
    """A wrong expectation must surface as a mismatch, not pass."""
    fixture = json.loads((FIXTURES / "steady.json").read_text())
    fixture["expect"]["generated_tokens"] += 1
    report = replay_fleet_fixture(fixture)
    assert not report["ok"]
    assert "generated_tokens" in report["mismatches"]


def test_fixture_cli_exits_zero():
    from tpudml.serve.fleet.__main__ import main

    assert main(["--fixture", str(FIXTURES / "steady.json")]) == 0


# ------------------------------------------------ replan + membership


def test_reform_consults_replanner(setup):
    class Replanner:
        def __init__(self):
            self.calls = []

        def replan(self, world, *, why):
            self.calls.append((world, why))
            return {"world": world}

    model, params = setup
    rp = Replanner()
    cfg = FleetConfig(engine=_ecfg(), replicas=2, reform_after_steps=3)
    rep = FleetRouter(model, params, cfg, replanner=rp).run(
        _workload(8, 200.0, 3), kills=[(3, 1)]
    )
    assert rp.calls and rp.calls[0][1] == "fleet-reform replica 1"
    assert rep.replans and rep.replans[0]["decision"] == {"world": 2}


def test_raising_replanner_fails_open(setup):
    class Bad:
        def replan(self, world, *, why):
            raise RuntimeError("planner down")

    model, params = setup
    cfg = FleetConfig(engine=_ecfg(), replicas=2, reform_after_steps=3)
    rep = FleetRouter(model, params, cfg, replanner=Bad()).run(
        _workload(8, 200.0, 3), kills=[(3, 1)]
    )
    # Re-form proceeded anyway, error recorded in the receipt.
    assert rep.finished == 8
    assert rep.replans and "RuntimeError" in rep.replans[0]["error"]


def test_all_dead_without_reform_raises(setup):
    model, params = setup
    cfg = FleetConfig(engine=_ecfg(), replicas=1)
    with pytest.raises(ValueError, match="no live replica"):
        FleetRouter(model, params, cfg).run(
            _workload(6, 200.0, 3), kills=[(1, 0)]
        )


# -------------------------------------------------- validation guards


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="step_time_s"):
        FleetConfig(engine=ServeConfig(slots=2, max_len=64,
                                       prefill_chunk=8))
    with pytest.raises(ValueError, match="replicas"):
        FleetConfig(engine=_ecfg(), replicas=0)
    with pytest.raises(ValueError, match="max_queue"):
        FleetConfig(engine=_ecfg(), max_queue=0)
    with pytest.raises(ValueError, match="reform_after_steps"):
        FleetConfig(engine=_ecfg(), reform_after_steps=0)


def test_fleet_rejects_spec():
    """fleet × spec_k is a capability-table rejection: the router's
    drain/re-admit continuation assumes one committed token per slot
    per step (serve_fleet_spec)."""
    with pytest.raises(ServeCompositionError, match="spec"):
        FleetConfig(engine=_ecfg(spec_k=2))


# ----------------------------------------------------- trace plumbing


def test_trace_docs_merge_and_validate(setup):
    from tpudml.obs.tracer import merge_chrome_traces, validate_chrome_trace

    model, params = setup
    cfg = FleetConfig(engine=_ecfg(), replicas=2, reform_after_steps=3)
    rep = FleetRouter(model, params, cfg).run(
        _workload(8, 200.0, 3), kills=[(3, 0)]
    )
    merged = merge_chrome_traces(rep.to_trace_docs(0.01))
    validate_chrome_trace(merged)
    names = {e["name"] for e in merged["traceEvents"]}
    assert {"kill", "reform", "queue_depth"} <= names


# --------------------------------------------------- spawned drill e2e


@pytest.mark.slow
def test_fleet_drill_survives_sigkill(tmp_path):
    """Real processes, real SIGKILL: the victim replica dies mid-serve,
    the controller re-forms, and every rank's final tokens match an
    uninterrupted in-process reference (CRC over the sorted token
    streams). Also pins the merged per-replica trace artifact and the
    obs_report fleet section."""
    from tools.obs_report import report as obs_report
    from tpudml.serve.fleet import run_fleet_drill

    rep = run_fleet_drill(tmp_path, world=2, requests=8, kill_rank=1,
                          seed=0, timeout_s=240.0)
    assert rep["ok"], rep
    assert rep["reforms"] >= 1
    assert rep["crc_ok"]
    merged = Path(rep["merged_trace"])
    assert merged.is_file()
    doc = json.loads(merged.read_text())
    pids = {e.get("pid") for e in doc["traceEvents"]}
    assert {0, 1} <= pids  # one track per replica survived the merge
    rendered = obs_report(tmp_path)
    assert "fleet.json (serving fleet)" in rendered
    assert "merged fleet trace" in rendered


# ----------------------------------------------- heterogeneous fleets


def test_mixed_fleet_routes_cache_bound_to_int8(setup):
    """One int8 + two f32 replicas under one SLO: the int8 replica's
    cost model carries a smaller param-byte term, so cache-bound
    head-of-line traffic is priced onto it first — routing follows the
    honest byte accounting, not replica index order. The int8 replica
    is deliberately the LAST index so tie-breaking cannot explain the
    placement."""
    from tpudml.serve import SLOConfig

    model, params = setup
    slo = SLOConfig(tpot_budget_s=1.0)  # loose: prices, never defers
    f32 = _ecfg(slo=slo)
    i8 = _ecfg(slo=slo, weight_quant="int8")
    cfg = FleetConfig(engine=f32, replicas=3,
                      replica_engines=(f32, f32, i8))
    assert cfg.engine_for(2).weight_quant == "int8"
    router = FleetRouter(model, params, cfg)
    # Pricing honesty: int8 storage really is the cheaper stream.
    assert (router.replicas[2].eng._cost.params_bytes
            < router.replicas[0].eng._cost.params_bytes)
    requests = _workload(6, 500.0, 3)
    rep = FleetRouter(model, params, cfg).run(requests)
    assert rep.finished == len(requests)
    admits = [e for e in rep.events if e[0] == "admit"]
    # The int8 replica (2 slots) soaks up the line first; f32 replicas
    # only see traffic once it is full.
    assert [e[2] for e in admits[:2]] == [2, 2]
    assert rep.per_replica[2]["busy_slot_steps"] > 0


def test_mixed_fleet_run_twice_byte_identical(setup):
    model, params = setup
    from tpudml.serve import SLOConfig

    slo = SLOConfig(tpot_budget_s=1.0)
    cfg = FleetConfig(
        engine=_ecfg(slo=slo), replicas=2, reform_after_steps=4,
        replica_engines=(_ecfg(slo=slo),
                         _ecfg(slo=slo, weight_quant="int8")),
    )
    requests = _workload(10, 200.0, 11)

    def go():
        rep = FleetRouter(model, params, cfg).run(requests, kills=[(4, 1)])
        return rep.canonical_events(), {
            rid: list(st.tokens) for rid, st in rep.requests.items()
        }

    assert go() == go()


def test_replica_engines_validation():
    with pytest.raises(ValueError, match="entries for"):
        FleetConfig(engine=_ecfg(), replicas=3,
                    replica_engines=(_ecfg(), _ecfg()))
    with pytest.raises(ValueError, match="one virtual clock"):
        FleetConfig(engine=_ecfg(), replicas=2,
                    replica_engines=(_ecfg(), _ecfg(step_time_s=0.02)))
    with pytest.raises(ServeCompositionError):
        FleetConfig(engine=_ecfg(), replicas=2,
                    replica_engines=(_ecfg(), _ecfg(spec_k=2)))
    # Homogeneous default: engine_for returns the template.
    cfg = FleetConfig(engine=_ecfg(), replicas=2)
    assert cfg.engine_for(1) is cfg.engine
