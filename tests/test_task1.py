"""Integration: task1 end-to-end on a small synthetic dataset — loss
decreases and accuracy clears a floor (SURVEY.md §4 integration tier)."""

import jax

from tpudml.core.config import TrainConfig
from tpudml.core.prng import seed_key
from tpudml.data import DataLoader, load_dataset
from tpudml.data.sampler import RandomPartitionSampler
from tpudml.models import LeNet
from tpudml.optim import make_optimizer
from tpudml.train import TrainState, make_train_step


def test_task1_end_to_end(tmp_path):
    import tasks.task1 as task1

    cfg = TrainConfig()
    cfg.epochs = 1
    cfg.optimizer = "adam_ref"
    cfg.lr = 1e-3
    cfg.log_every = 5
    cfg.log_dir = str(tmp_path / "logs")
    cfg.data.dataset = "synthetic"
    cfg.data.batch_size = 64
    metrics = task1.run(cfg)
    assert metrics["test_accuracy"] > 0.5  # prototype data is easily learnable
    assert metrics["loss"] < 2.3  # below initial uniform CE


def test_loss_decreases_monotonically_enough():
    train_set = load_dataset("synthetic", "/nonexistent", "train")
    loader = DataLoader(
        train_set, 64, RandomPartitionSampler(len(train_set), 1, 0, seed=0)
    )
    model = LeNet()
    opt = make_optimizer("adam", 1e-3)
    step = make_train_step(model, opt)
    ts = TrainState.create(model, opt, seed_key(0))
    losses = []
    for images, labels in loader:
        ts, m = step(ts, images, labels)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.85


def test_train_state_is_pytree():
    model = LeNet()
    opt = make_optimizer("sgd", 1e-2, 0.9)
    ts = TrainState.create(model, opt, seed_key(0))
    leaves = jax.tree.leaves(ts)
    assert len(leaves) > 4
