"""Transformer mixed-precision (master-weight) path.

Load-bearing properties: with ``compute_dtype=bf16`` the parameters (and
therefore the optimizer state) stay float32 while the matmul path runs
bf16; LayerNorm statistics and the attention softmax are float32 on EVERY
path (bf16 exp/sum loses probability mass at long T); and short training
tracks the f32 trajectory within bf16 tolerance instead of diverging.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.models import TransformerLM
from tpudml.nn.attention import dot_product_attention
from tpudml.nn.layers import LayerNorm
from tpudml.optim import make_optimizer
from tpudml.train import TrainState, make_train_step


def _lm(**kw):
    return TransformerLM(vocab_size=64, embed_dim=32, num_heads=4,
                         num_layers=2, max_len=16, **kw)


def test_params_stay_f32_under_bf16_compute():
    model = _lm(compute_dtype=jnp.bfloat16)
    opt = make_optimizer("adam", 1e-3)
    ts = TrainState.create(model, opt, seed_key(0))
    for leaf in jax.tree.leaves(ts.params) + jax.tree.leaves(ts.opt_state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32, leaf.dtype
    seqs = jnp.asarray(synthetic_lm(4, 16, 64, seed=0))
    step = make_train_step(model, opt)
    ts, m = step(ts, seqs[:, :-1], seqs[:, 1:])
    # Master copies still f32 after the update; logits path returned f32.
    for leaf in jax.tree.leaves(ts.params):
        assert leaf.dtype == jnp.float32
    assert np.isfinite(float(m["loss"]))


def test_bf16_tracks_f32_trajectory():
    seqs = jnp.asarray(synthetic_lm(8, 16, 64, seed=1))
    x, y = seqs[:, :-1], seqs[:, 1:]

    def losses(compute_dtype):
        model = _lm(compute_dtype=compute_dtype)
        opt = make_optimizer("sgd", 0.1, momentum=0.9)
        ts = TrainState.create(model, opt, seed_key(2))
        step = make_train_step(model, opt)
        out = []
        for _ in range(6):
            ts, m = step(ts, x, y)
            out.append(float(m["loss"]))
        return out

    f32 = losses(None)
    bf16 = losses(jnp.bfloat16)
    assert f32[-1] < f32[0] and bf16[-1] < bf16[0]  # both learn
    np.testing.assert_allclose(bf16, f32, rtol=0.05)  # bf16 rounding only


def test_layernorm_stats_f32_for_bf16_inputs():
    ln = LayerNorm(64)
    params, _ = ln.init(seed_key(0))
    # Mean >> spread: bf16 input quantization stays small relative to the
    # spread (ulp ≈ 0.03 near 8), but a pure-bf16 mean/var at this offset
    # would lose most of the variance signal.
    rng = np.random.default_rng(0)
    x = (8.0 + rng.normal(0, 1.0, size=(4, 64))).astype(np.float32)
    xq = np.asarray(jnp.asarray(x, jnp.bfloat16), np.float32)
    y32, _ = ln.apply(params, {}, jnp.asarray(xq))  # same quantized input
    y16, _ = ln.apply(params, {}, jnp.asarray(x, jnp.bfloat16))
    assert y16.dtype == jnp.bfloat16  # stays in the compute dtype
    # f32 statistics: identical math up to the final bf16 rounding of y.
    np.testing.assert_allclose(
        np.asarray(y16, np.float32), np.asarray(y32), atol=0.02
    )


def test_attention_softmax_f32_for_bf16_inputs():
    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 32, 4, 16)).astype(np.float32))
        for _ in range(3)
    )
    want = dot_product_attention(q, k, v, causal=True)
    got = dot_product_attention(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
        causal=True,
    )
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), atol=0.04
    )
