"""Parity tests for the grouped-dW Pallas kernel and the ragged_ffn
custom_vjp (tpudml/ops/moe_kernel.py).

The oracle for ``grouped_dw`` is the stock masked transpose — exactly
what ``lax.ragged_dot``'s VJP computes: per expert, mask rows outside
the group's slab and contract ``x^T @ g``. The kernel must reproduce it
through the Pallas interpreter (uneven groups, empty experts, rows that
straddle tile boundaries, bf16 inputs with f32 accumulation), and the
``ragged_ffn`` backward must be grad-identical to differentiating the
plain ragged composition.

Cheapest variants run tier-1; the multi-tiling interpreter sweep is
slow-marked (the interpreter re-traces per tiling).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from tpudml.core.prng import seed_key
from tpudml.ops.moe_kernel import grouped_dw, ragged_ffn

E = 8


def _stock_dw(x, g, group_sizes):
    """The masked-transpose oracle (what ragged_dot's VJP computes)."""
    ends = jnp.cumsum(group_sizes)
    starts = ends - group_sizes
    rows = jnp.arange(x.shape[0])[:, None]
    out = []
    for i in range(group_sizes.shape[0]):
        m = ((rows >= starts[i]) & (rows < ends[i])).astype(x.dtype)
        out.append((x * m).T @ (g * m))
    return jnp.stack(out)


def _operands(key, m, k, n):
    k1, k2 = jax.random.split(key)
    return (
        jax.random.normal(k1, (m, k), jnp.float32),
        jax.random.normal(k2, (m, n), jnp.float32),
    )


# Uneven groups including empty experts and tile-straddling boundaries.
GROUPS = {
    "uneven": jnp.array([3, 11, 2, 17, 9, 5, 12, 5], jnp.int32),
    "empty": jnp.array([20, 0, 10, 0, 14, 0, 20, 0], jnp.int32),
    "collapsed": jnp.array([64, 0, 0, 0, 0, 0, 0, 0], jnp.int32),
}
# The collapsed slab accumulates one expert across many sequential tile
# partials, so its sum association differs from the oracle's single
# masked dot by an extra f32 ulp or two — everything else holds 1e-6.
ATOL = {"uneven": 1e-6, "empty": 1e-6, "collapsed": 5e-6}


@pytest.mark.parametrize("groups", sorted(GROUPS))
def test_grouped_dw_reference_matches_stock(groups):
    gs = GROUPS[groups]
    x, g = _operands(seed_key(0), int(jnp.sum(gs)), 16, 24)
    np.testing.assert_allclose(
        np.asarray(grouped_dw(x, g, gs)),  # reference path on CPU
        np.asarray(_stock_dw(x, g, gs)),
        rtol=1e-5,
        atol=1e-6,
    )


@pytest.mark.parametrize("groups", sorted(GROUPS))
def test_grouped_dw_interpret_matches_stock(groups):
    gs = GROUPS[groups]
    x, g = _operands(seed_key(1), int(jnp.sum(gs)), 16, 24)
    got = grouped_dw(x, g, gs, tiling=(16, 128, 128), interpret=True)
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(_stock_dw(x, g, gs)),
        rtol=1e-5,
        atol=ATOL[groups],
    )


@pytest.mark.slow
@pytest.mark.parametrize("tm", [8, 16, 32])
@pytest.mark.parametrize("groups", sorted(GROUPS))
def test_grouped_dw_interpret_tiling_sweep(groups, tm):
    """Boundary visits must stay correct for every row-tile size: groups
    smaller than a tile, straddling tiles, and owning many tiles."""
    gs = GROUPS[groups]
    x, g = _operands(seed_key(2), int(jnp.sum(gs)), 16, 24)
    got = grouped_dw(x, g, gs, tiling=(tm, 128, 128), interpret=True)
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(_stock_dw(x, g, gs)),
        rtol=2e-5,
        atol=ATOL[groups],
    )


def test_grouped_dw_ignores_tail_rows():
    """Rows beyond sum(group_sizes) are unowned padding and must not
    leak into any expert's tile."""
    gs = jnp.array([5, 0, 9, 2, 0, 3, 1, 4], jnp.int32)  # sums to 24
    x, g = _operands(seed_key(3), 40, 16, 24)  # 16 junk tail rows
    want = _stock_dw(x, g, gs)
    for kwargs in ({}, {"tiling": (8, 128, 128), "interpret": True}):
        np.testing.assert_allclose(
            np.asarray(grouped_dw(x, g, gs, **kwargs)),
            np.asarray(want),
            rtol=1e-5,
            atol=1e-6,
        )


def test_grouped_dw_bf16_in_f32_accum():
    gs = GROUPS["empty"]
    x, g = _operands(seed_key(4), int(jnp.sum(gs)), 16, 24)
    xb, gb = x.astype(jnp.bfloat16), g.astype(jnp.bfloat16)
    want = _stock_dw(xb.astype(jnp.float32), gb.astype(jnp.float32), gs)
    got = grouped_dw(xb, gb, gs, tiling=(8, 128, 128), interpret=True)
    assert got.dtype == jnp.float32  # accumulator dtype survives to the output
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_grouped_dw_validates_operands():
    x = jnp.zeros((8, 4))
    with pytest.raises(ValueError, match="row-aligned"):
        grouped_dw(x, jnp.zeros((9, 4)), jnp.array([8], jnp.int32))
    with pytest.raises(ValueError, match="integer"):
        grouped_dw(x, jnp.zeros((8, 4)), jnp.array([8.0]))


def _ffn_inputs(key, m, d, h, gs):
    ks = jax.random.split(key, 6)
    e = gs.shape[0]
    eids = jnp.repeat(jnp.arange(e), gs, total_repeat_length=m)
    return dict(
        x=jax.random.normal(ks[0], (m, d)),
        w1=jax.random.normal(ks[1], (e, d, h)) * 0.2,
        b1=jax.random.normal(ks[2], (e, h)) * 0.2,
        w2=jax.random.normal(ks[3], (e, h, d)) * 0.2,
        b2=jax.random.normal(ks[4], (e, d)) * 0.2,
        onehot=jax.nn.one_hot(eids, e, dtype=jnp.float32),
        dout=jax.random.normal(ks[5], (m, d)),
    )


def _stock_ffn(x, w1, b1, w2, b2, onehot, gs):
    h = jax.nn.relu(lax.ragged_dot(x, w1, gs) + onehot @ b1)
    return lax.ragged_dot(h, w2, gs) + onehot @ b2


@pytest.mark.parametrize("groups", ["uneven", "empty"])
def test_ragged_ffn_grads_match_stock(groups):
    """The hand-written VJP (grouped dW, ragged_dot dx/dh, one-hot db)
    must be grad-identical to differentiating the plain composition."""
    gs = GROUPS[groups]
    v = _ffn_inputs(seed_key(5), int(jnp.sum(gs)), 16, 32, gs)
    args = (v["x"], v["w1"], v["b1"], v["w2"], v["b2"], v["onehot"])

    np.testing.assert_allclose(
        np.asarray(ragged_ffn(*args, gs)),
        np.asarray(_stock_ffn(*args, gs)),
        rtol=1e-5,
        atol=1e-6,
    )
    g_new = jax.grad(
        lambda *a: jnp.vdot(ragged_ffn(*a, gs), v["dout"]), argnums=range(6)
    )(*args)
    g_old = jax.grad(
        lambda *a: jnp.vdot(_stock_ffn(*a, gs), v["dout"]), argnums=range(6)
    )(*args)
    for name, a, b in zip(["dx", "dw1", "db1", "dw2", "db2"], g_new, g_old):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6, err_msg=name
        )
    # onehot is integer-derived: our VJP returns zeros for it by contract.
    assert not np.any(np.asarray(g_new[5]))


@pytest.mark.slow
def test_ragged_ffn_interpret_grads_match_stock():
    """Same parity with the Pallas interpreter doing both dW kernels,
    under jit (the vjp must trace cleanly inside a jitted step)."""
    gs = GROUPS["empty"]
    v = _ffn_inputs(seed_key(6), int(jnp.sum(gs)), 16, 32, gs)
    args = (v["x"], v["w1"], v["b1"], v["w2"], v["b2"], v["onehot"])

    g_new = jax.jit(
        jax.grad(
            lambda *a: jnp.vdot(
                ragged_ffn(*a, gs, (8, 128, 128), True), v["dout"]
            ),
            argnums=(1, 3),
        )
    )(*args)
    g_old = jax.grad(
        lambda *a: jnp.vdot(_stock_ffn(*a, gs), v["dout"]), argnums=(1, 3)
    )(*args)
    for name, a, b in zip(["dw1", "dw2"], g_new, g_old):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6, err_msg=name
        )
