"""MoE + expert-parallelism tests.

Load-bearing properties: the dense-dispatch math routes correctly (top-1,
capacity, drops), EP over W shards is the same function as dense
single-shard evaluation when nothing is dropped, and EP training matches
dense training step for step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.nn import Activation, Dense, Flatten, MoELayer, Sequential
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.nn.moe import load_balancing_loss
from tpudml.optim import make_optimizer
from tpudml.parallel.ep import ExpertParallel, expert_specs

D, E, W = 16, 8, 4
G = 64  # tokens


@pytest.fixture(scope="module")
def tokens():
    return jnp.asarray(
        np.random.default_rng(0).normal(size=(G, D)).astype(np.float32)
    )


def dense_moe(capacity_factor=8.0, axis_name=None):
    return MoELayer(D, E, mlp_ratio=2, capacity_factor=capacity_factor,
                    axis_name=axis_name)


def test_dense_routing_uses_multiple_experts(tokens):
    moe = dense_moe()
    params, _ = moe.init(seed_key(0))
    y, _ = moe.apply(params, {}, tokens)
    assert y.shape == tokens.shape
    assert np.all(np.isfinite(np.asarray(y)))
    probs = jax.nn.softmax(tokens @ params["router"]["kernel"], -1)
    assert len(np.unique(np.argmax(np.asarray(probs), -1))) > 1


def test_capacity_overflow_drops_tokens(tokens):
    """With capacity 1 per expert, most tokens get zero output (dropped)."""
    moe = dense_moe(capacity_factor=E / G)  # capacity = 1
    params, _ = moe.init(seed_key(0))
    y, _ = moe.apply(params, {}, tokens)
    zero_rows = np.sum(np.all(np.asarray(y) == 0.0, axis=-1))
    assert zero_rows >= G - E  # at most one survivor per expert


def test_ep_matches_dense(tokens):
    """Sharded EP forward == dense forward (no drops)."""
    dense = dense_moe()
    params, _ = dense.init(seed_key(1))
    want, _ = dense.apply(params, {}, tokens)

    mesh = make_mesh(MeshConfig({"expert": W}), jax.devices()[:W])
    ep_layer = dense_moe(axis_name="expert")
    from jax.sharding import PartitionSpec as P

    from tpudml.parallel.sharding import shard_map_fn

    fwd = jax.jit(
        shard_map_fn(
            lambda p, x: ep_layer.apply(p, {}, x)[0],
            mesh,
            in_specs=(expert_specs(params, "expert"), P("expert")),
            out_specs=P("expert"),
        )
    )
    got = fwd(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def _classifier(axis_name=None):
    return Sequential((
        Flatten(),
        Dense(28 * 28, D),
        Activation(jax.nn.relu),
        MoELayer(D, E, mlp_ratio=2, capacity_factor=8.0, axis_name=axis_name),
        Dense(D, 10),
    ))


def test_ep_training_matches_dense():
    from tpudml.data.datasets import synthetic_classification

    images, labels = synthetic_classification(G, (28, 28, 1), 10, seed=5)
    images, labels = jnp.asarray(images), jnp.asarray(labels)
    opt = make_optimizer("sgd", 0.05)

    mesh = make_mesh(MeshConfig({"expert": W}), jax.devices()[:W])
    ep = ExpertParallel(_classifier(axis_name="expert"), opt, mesh)
    ts = ep.create_state(seed_key(3))
    step = ep.make_train_step()

    dense_model = _classifier()
    ref_params = jax.device_get(ts.params)
    ref_opt = opt.init(ref_params)
    ref_loss = lambda p: softmax_cross_entropy(dense_model(p, images), labels)

    losses = []
    for _ in range(4):
        ts, m = step(ts, images, labels)
        losses.append(float(m["loss"]))
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_opt = opt.update(g, ref_opt, ref_params)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)
    assert losses[-1] < losses[0]


def test_load_balancing_loss_uniform_is_one(tokens):
    moe = dense_moe()
    params, _ = moe.init(seed_key(0))
    # Zero router → uniform probs; aux loss = E * Σ_e frac_e * (1/E) = 1.
    params = dict(params, router={"kernel": jnp.zeros((D, E))})
    aux = load_balancing_loss(params, tokens, E)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)
