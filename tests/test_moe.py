"""MoE + expert-parallelism tests.

Load-bearing properties: the dense-dispatch math routes correctly (top-1,
capacity, drops), EP over W shards is the same function as dense
single-shard evaluation when nothing is dropped, and EP training matches
dense training step for step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.nn import Activation, Dense, Flatten, MoELayer, Sequential
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.nn.moe import load_balancing_loss
from tpudml.optim import make_optimizer
from tpudml.parallel.ep import ExpertParallel, expert_specs

D, E, W = 16, 8, 4
G = 64  # tokens


@pytest.fixture(scope="module")
def tokens():
    return jnp.asarray(
        np.random.default_rng(0).normal(size=(G, D)).astype(np.float32)
    )


def dense_moe(capacity_factor=8.0, axis_name=None):
    return MoELayer(D, E, mlp_ratio=2, capacity_factor=capacity_factor,
                    axis_name=axis_name)


def test_dense_routing_uses_multiple_experts(tokens):
    moe = dense_moe()
    params, _ = moe.init(seed_key(0))
    y, _ = moe.apply(params, {}, tokens)
    assert y.shape == tokens.shape
    assert np.all(np.isfinite(np.asarray(y)))
    probs = jax.nn.softmax(tokens @ params["router"]["kernel"], -1)
    assert len(np.unique(np.argmax(np.asarray(probs), -1))) > 1


def test_capacity_overflow_drops_tokens(tokens):
    """With capacity 1 per expert, most tokens get zero output (dropped)."""
    moe = dense_moe(capacity_factor=E / G)  # capacity = 1
    params, _ = moe.init(seed_key(0))
    y, _ = moe.apply(params, {}, tokens)
    zero_rows = np.sum(np.all(np.asarray(y) == 0.0, axis=-1))
    assert zero_rows >= G - E  # at most one survivor per expert


def test_ep_matches_dense(tokens):
    """Sharded EP forward == dense forward (no drops)."""
    dense = dense_moe()
    params, _ = dense.init(seed_key(1))
    want, _ = dense.apply(params, {}, tokens)

    mesh = make_mesh(MeshConfig({"expert": W}), jax.devices()[:W])
    ep_layer = dense_moe(axis_name="expert")
    from jax.sharding import PartitionSpec as P

    from tpudml.parallel.sharding import shard_map_fn

    fwd = jax.jit(
        shard_map_fn(
            lambda p, x: ep_layer.apply(p, {}, x)[0],
            mesh,
            in_specs=(expert_specs(params, "expert"), P("expert")),
            out_specs=P("expert"),
        )
    )
    got = fwd(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def _classifier(axis_name=None):
    return Sequential((
        Flatten(),
        Dense(28 * 28, D),
        Activation(jax.nn.relu),
        MoELayer(D, E, mlp_ratio=2, capacity_factor=8.0, axis_name=axis_name),
        Dense(D, 10),
    ))


def test_ep_training_matches_dense():
    from tpudml.data.datasets import synthetic_classification

    images, labels = synthetic_classification(G, (28, 28, 1), 10, seed=5)
    images, labels = jnp.asarray(images), jnp.asarray(labels)
    opt = make_optimizer("sgd", 0.05)

    mesh = make_mesh(MeshConfig({"expert": W}), jax.devices()[:W])
    # aux pressure off: this test pins strict parity with the plain
    # cross-entropy objective of the dense reference.
    ep = ExpertParallel(_classifier(axis_name="expert"), opt, mesh, aux_loss_weight=0.0)
    ts = ep.create_state(seed_key(3))
    step = ep.make_train_step()

    dense_model = _classifier()
    ref_params = jax.device_get(ts.params)
    ref_opt = opt.init(ref_params)
    ref_loss = lambda p: softmax_cross_entropy(dense_model(p, images), labels)

    losses = []
    for _ in range(4):
        ts, m = step(ts, images, labels)
        losses.append(float(m["loss"]))
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_opt = opt.update(g, ref_opt, ref_params)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)
    assert losses[-1] < losses[0]


def test_moe_transformer_trains_under_ep():
    """The modern flagship: a MoE decoder LM trained expert-parallel —
    tokens sharded over the expert axis, experts all_to_all-dispatched,
    and it learns the successor task."""
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM

    mesh = make_mesh(MeshConfig({"expert": W}), jax.devices()[:W])
    lm = TransformerLM(
        vocab_size=32, embed_dim=32, num_heads=4, num_layers=1, max_len=16,
        moe_experts=E, moe_axis="expert",
    )
    ep = ExpertParallel(lm, make_optimizer("adam", 0.01), mesh)
    ts = ep.create_state(seed_key(6))
    step = ep.make_train_step()
    seqs = jnp.asarray(synthetic_lm(16, 16, 32, seed=0))
    first = None
    for _ in range(30):
        ts, m = step(ts, seqs[:, :-1], seqs[:, 1:])
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first * 0.5
    # Sharded eval agrees with train-time accuracy direction.
    acc = ep.evaluate(ts, [(seqs[:, :-1], seqs[:, 1:])])
    assert 0.0 <= acc <= 1.0 and acc > 0.2


def test_moe_transformer_dense_matches_sharded_init():
    """Same seed ⇒ same params whether the block is dense-MoE (axis None)
    or EP-MoE (axis set): routing config must not affect initialization."""
    from tpudml.models import TransformerLM

    base = dict(vocab_size=16, embed_dim=16, num_heads=2, num_layers=1,
                max_len=8, moe_experts=4)
    a, _ = TransformerLM(**base).init(seed_key(1))
    b, _ = TransformerLM(**base, moe_axis="expert").init(seed_key(1))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_aux_loss_threads_through_state_and_objective():
    """MoE layers record their Switch aux loss in model state;
    make_loss_fn(aux_loss_weight=α) folds it into the objective and its
    gradient reaches the router."""
    import jax.numpy as jnp

    from tpudml.models import TransformerLM
    from tpudml.train import make_loss_fn

    lm = TransformerLM(
        vocab_size=16, embed_dim=16, num_heads=2, num_layers=1, max_len=8,
        moe_experts=4,
    )
    params, state = lm.init(seed_key(0))
    assert set(state) == {"block0"}
    # Multi-block state namespacing, abstractly (no compute): every block
    # must own its OWN aux-loss slot — a collision would silently drop
    # all but one block's load-balancing pressure.
    lm2 = TransformerLM(
        vocab_size=16, embed_dim=16, num_heads=2, num_layers=3, max_len=8,
        moe_experts=4,
    )
    p2, s2 = jax.eval_shape(lm2.init, seed_key(0))
    assert set(s2) == {"block0", "block1", "block2"}
    toks2 = jax.ShapeDtypeStruct((2, 8), np.int32)
    _, s2_out = jax.eval_shape(
        lambda p, s, t: lm2.apply(p, s, t), p2, s2, toks2
    )
    assert set(s2_out) == {"block0", "block1", "block2"}
    assert all("moe" in s2_out[k] for k in s2_out)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 16, size=(2, 8)).astype(np.int32)
    )
    _, new_state = lm.apply(params, state, tokens)
    aux = float(new_state["block0"]["moe"]["aux_loss"])
    assert np.isfinite(aux) and aux >= 1.0  # ≥1, =1 iff perfectly balanced

    plain = make_loss_fn(lm)
    with_aux = make_loss_fn(lm, aux_loss_weight=0.1)
    g0 = jax.grad(lambda p: plain(p, state, tokens, tokens, None)[0])(params)
    g1 = jax.grad(lambda p: with_aux(p, state, tokens, tokens, None)[0])(params)
    r0 = np.asarray(g0["block0"]["moe"]["router"]["kernel"])
    r1 = np.asarray(g1["block0"]["moe"]["router"]["kernel"])
    assert not np.allclose(r0, r1)  # aux pressure reaches the router
    l0 = float(plain(params, state, tokens, tokens, None)[0])
    l1 = float(with_aux(params, state, tokens, tokens, None)[0])
    assert l1 > l0  # aux adds a positive term


def test_load_balancing_loss_uniform_is_one(tokens):
    moe = dense_moe()
    params, _ = moe.init(seed_key(0))
    # Zero router → uniform probs; aux loss = E * Σ_e frac_e * (1/E) = 1.
    params = dict(params, router={"kernel": jnp.zeros((D, E))})
    aux = load_balancing_loss(params, tokens, E)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_aux_loss_defaults_on_for_moe_models():
    """Dense-MoE runs outside the EP engine must still get the Switch
    load-balancing pressure: resolve_aux_loss_weight defaults α on exactly
    when the model contains MoE layers."""
    from tpudml.models import TransformerLM
    from tpudml.train import (
        DEFAULT_MOE_AUX_WEIGHT,
        model_has_moe,
        resolve_aux_loss_weight,
    )

    moe_lm = TransformerLM(vocab_size=32, embed_dim=16, num_heads=2,
                           num_layers=1, moe_experts=4)
    plain_lm = TransformerLM(vocab_size=32, embed_dim=16, num_heads=2,
                             num_layers=1)
    assert model_has_moe(moe_lm)
    assert model_has_moe(_classifier())  # Sequential-contained MoELayer
    assert not model_has_moe(plain_lm)
    assert resolve_aux_loss_weight(moe_lm, None) == DEFAULT_MOE_AUX_WEIGHT
    assert resolve_aux_loss_weight(plain_lm, None) == 0.0
    assert resolve_aux_loss_weight(moe_lm, 0.0) == 0.0  # explicit opt-out


def test_dense_moe_train_step_applies_aux_pressure():
    """make_train_step's objective for a MoE model includes the aux term:
    losses diverge from an aux_loss_weight=0 run within a few steps."""
    from tpudml.data.datasets import synthetic_classification
    from tpudml.train import TrainState, make_train_step

    images, labels = synthetic_classification(G, (28, 28, 1), 10, seed=2)
    images, labels = jnp.asarray(images), jnp.asarray(labels)
    model = _classifier()
    opt = make_optimizer("sgd", 0.05)
    step_aux = make_train_step(model, opt)            # auto: aux on
    step_no = make_train_step(model, opt, aux_loss_weight=0.0)
    ts_a = TrainState.create(model, opt, seed_key(0))
    ts_n = TrainState.create(model, opt, seed_key(0))
    diverged = False
    for _ in range(5):
        ts_a, ma = step_aux(ts_a, images, labels)
        ts_n, mn = step_no(ts_n, images, labels)
        if not np.allclose(float(ma["loss"]), float(mn["loss"])):
            diverged = True
    assert diverged


def test_clip_in_ep_keeps_replicas_synced():
    """ClipByGlobalNorm under ExpertParallel: the engine psums the squared
    norm over the expert axis, so every device derives the SAME clip scale
    and replicated (router/dense) parameters stay bitwise identical."""
    from tpudml.data.datasets import synthetic_classification
    from tpudml.optim import ClipByGlobalNorm, Sgd

    images, labels = synthetic_classification(G, (28, 28, 1), 10, seed=9)
    mesh = make_mesh(MeshConfig({"expert": W}), jax.devices()[:W])
    # Tiny max_norm: every step clips, so an un-psum-ed norm would scale
    # each shard differently and de-sync the replicated parameters.
    opt = ClipByGlobalNorm(Sgd(lr=0.1), max_norm=1e-2)
    ep = ExpertParallel(_classifier(axis_name="expert"), opt, mesh)
    assert ep.optimizer.axes == ("expert",)  # engine rewrapped the clip
    ts = ep.create_state(seed_key(1))
    step = ep.make_train_step()
    for _ in range(3):
        ts, _ = step(ts, jnp.asarray(images), jnp.asarray(labels))
    # Router params are replicated: every device copy must be identical.
    router = ts.params["layer3"]["router"]["kernel"]
    shards = [np.asarray(s.data) for s in router.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_top2_matches_direct_mixture(tokens):
    """With capacity ample enough that nothing drops, top-2 output ==
    the direct per-token mixture sum_j gate_j * FFN_{e_j}(t) with gates
    renormalized over the chosen 2 (GShard semantics)."""
    moe = MoELayer(D, E, mlp_ratio=2, capacity_factor=8.0, top_k=2)
    params, _ = moe.init(seed_key(4))
    y, _ = moe.apply(params, {}, tokens)

    probs = jax.nn.softmax(tokens @ params["router"]["kernel"], -1)
    topv, topi = jax.lax.top_k(probs, 2)
    gates = topv / jnp.sum(topv, -1, keepdims=True)
    w = params["experts"]

    def ffn(e, t):
        h = jax.nn.relu(t @ w["w1"][e] + w["b1"][e])
        return h @ w["w2"][e] + w["b2"][e]

    want = jnp.stack([
        gates[i, 0] * ffn(int(topi[i, 0]), tokens[i])
        + gates[i, 1] * ffn(int(topi[i, 1]), tokens[i])
        for i in range(G)
    ])
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=2e-5, atol=2e-6)


def test_top2_ep_matches_dense(tokens):
    """Expert-parallel top-2 == dense top-2 (no drops)."""
    from jax.sharding import PartitionSpec as P

    from tpudml.parallel.sharding import shard_map_fn

    dense = MoELayer(D, E, mlp_ratio=2, capacity_factor=8.0, top_k=2)
    params, _ = dense.init(seed_key(5))
    want, _ = dense.apply(params, {}, tokens)

    mesh = make_mesh(MeshConfig({"expert": W}), jax.devices()[:W])
    ep_layer = MoELayer(D, E, mlp_ratio=2, capacity_factor=8.0, top_k=2,
                        axis_name="expert")
    fwd = jax.jit(
        shard_map_fn(
            lambda p, x: ep_layer.apply(p, {}, x)[0],
            mesh,
            in_specs=(expert_specs(params, "expert"), P("expert")),
            out_specs=P("expert"),
        )
    )
    got = fwd(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def test_top2_choice_priority_under_overflow(tokens):
    """Capacity so tight every expert holds ~1 token: outputs stay finite
    and the layer still routes (secondary choices drop first — capacity
    accounting must not corrupt surviving slots)."""
    moe = MoELayer(D, E, mlp_ratio=2, capacity_factor=E / (2 * G), top_k=2)
    params, _ = moe.init(seed_key(6))
    y, _ = moe.apply(params, {}, tokens)
    assert np.all(np.isfinite(np.asarray(y)))
    # Some rows survive (capacity E experts x 1 slot), some are dropped.
    zero_rows = np.sum(np.all(np.asarray(y) == 0.0, axis=-1))
    assert 0 < zero_rows < G


def test_top_k_validation():
    with pytest.raises(ValueError, match="top_k"):
        MoELayer(D, E, top_k=0)
    with pytest.raises(ValueError, match="top_k"):
        MoELayer(D, E, top_k=E + 1)


def test_moe_transformer_top2_trains():
    from tpudml.data.datasets import synthetic_lm
    from tpudml.models import TransformerLM
    from tpudml.optim import make_optimizer as mk
    from tpudml.train import TrainState, make_train_step

    lm = TransformerLM(vocab_size=32, embed_dim=32, num_heads=4, num_layers=1,
                       max_len=16, moe_experts=4, moe_top_k=2)
    opt = mk("adam", 0.01)
    ts = TrainState.create(lm, opt, seed_key(7))
    step = make_train_step(lm, opt)
    seqs = jnp.asarray(synthetic_lm(16, 16, 32, seed=2))
    first = None
    for _ in range(12):
        ts, m = step(ts, seqs[:, :-1], seqs[:, 1:])
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first


def test_ep_composes_with_dp():
    """EP×DP on a 2-D {"data": 2, "expert": 4} mesh: tokens shard over
    both axes, experts shard over `expert` and replicate over `data`;
    training matches dense single-device step for step (no drops)."""
    from tpudml.data.datasets import synthetic_classification
    from tpudml.train import TrainState, make_train_step

    images, labels = synthetic_classification(G, (28, 28, 1), 10, seed=8)
    images, labels = jnp.asarray(images), jnp.asarray(labels)
    opt = make_optimizer("sgd", 0.05)

    mesh = make_mesh(MeshConfig({"data": 2, "expert": W}), jax.devices()[: 2 * W])
    ep = ExpertParallel(
        _classifier(axis_name="expert"), opt, mesh,
        aux_loss_weight=0.0, batch_axis="data",
    )
    ts = ep.create_state(seed_key(3))
    step = ep.make_train_step()

    dense_model = _classifier()
    ref_ts = TrainState.create(dense_model, opt, seed_key(3))
    ref_step = make_train_step(dense_model, opt, aux_loss_weight=0.0)

    for _ in range(4):
        ts, m = step(ts, images, labels)
        ref_ts, rm = ref_step(ref_ts, images, labels)
        np.testing.assert_allclose(float(m["loss"]), float(rm["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)
    # Eval agrees with the dense model on the same batch (the counting
    # eval must psum correct/count over BOTH axes to get this right).
    acc = ep.evaluate(ts, [(images, labels)])
    ref_logits = dense_model(ref_ts.params, images)
    ref_acc = float(jnp.mean(jnp.argmax(ref_logits, -1) == labels))
    np.testing.assert_allclose(acc, ref_acc, atol=1e-6)


@pytest.mark.parametrize("top_k,cap", [(1, 8.0), (2, 8.0), (1, E / G), (2, 0.5)])
def test_gather_matches_einsum_dispatch(tokens, top_k, cap):
    """The gather dispatch (default) and the GShard one-hot einsum oracle
    consume the identical slot assignment, so outputs AND gradients —
    router included, through the gate/combine path — must agree to f32
    tolerance, with and without capacity drops."""
    kw = dict(mlp_ratio=2, capacity_factor=cap, top_k=top_k)
    gather = MoELayer(D, E, **kw)  # dispatch="gather" default
    einsum = MoELayer(D, E, dispatch="einsum", **kw)
    params, _ = gather.init(seed_key(1))

    def loss(moe, params, x):
        y, st = moe.apply(params, {}, x)
        return jnp.sum(y**2) + st["aux_loss"], y

    (lg, yg), gg = jax.value_and_grad(lambda p, x: loss(gather, p, x), (0, 1), has_aux=True)(params, tokens)
    (le, ye), ge = jax.value_and_grad(lambda p, x: loss(einsum, p, x), (0, 1), has_aux=True)(params, tokens)
    np.testing.assert_allclose(np.asarray(yg), np.asarray(ye), rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(lg), float(le), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(gg), jax.tree.leaves(ge)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6)


def test_gather_dispatch_validation():
    with pytest.raises(ValueError):
        MoELayer(D, E, dispatch="loop")


@pytest.mark.parametrize("ragged_dw", ["grouped", "stock"])
@pytest.mark.parametrize("top_k", [1, 2])
def test_ragged_matches_direct_mixture(tokens, top_k, ragged_dw):
    """dispatch='ragged' is DROPLESS: every token reaches all its chosen
    experts regardless of load imbalance, so the direct per-token mixture
    is an exact oracle (no ample-capacity caveat) — outputs, aux loss,
    and all gradients. Runs through both backwards: the grouped-dW
    custom_vjp (default) and lax.ragged_dot's stock transpose."""
    moe = MoELayer(
        D, E, mlp_ratio=2, top_k=top_k, dispatch="ragged", ragged_dw=ragged_dw
    )
    params, _ = moe.init(seed_key(4))

    probs = jax.nn.softmax(tokens @ params["router"]["kernel"], -1)
    topv, topi = jax.lax.top_k(probs, top_k)
    gates = topv if top_k == 1 else topv / (jnp.sum(topv, -1, keepdims=True) + 1e-9)
    w = params["experts"]

    def ffn(e, t):
        h = jax.nn.relu(t @ w["w1"][e] + w["b1"][e])
        return h @ w["w2"][e] + w["b2"][e]

    y, _ = moe.apply(params, {}, tokens)
    want = jnp.stack([
        sum(gates[i, j] * ffn(int(topi[i, j]), tokens[i]) for j in range(top_k))
        for i in range(G)
    ])
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=2e-5, atol=2e-6)

    # Gradients vs the high-capacity gather path (nothing drops there, so
    # the two formulations compute the same function).
    ref = MoELayer(D, E, mlp_ratio=2, capacity_factor=8.0, top_k=top_k)

    def loss(moe, params, x):
        y, st = moe.apply(params, {}, x)
        return jnp.sum(y**2) + st["aux_loss"]

    lr_, gr = jax.value_and_grad(lambda p, x: loss(moe, p, x), (0, 1))(params, tokens)
    le_, ge = jax.value_and_grad(lambda p, x: loss(ref, p, x), (0, 1))(params, tokens)
    np.testing.assert_allclose(float(lr_), float(le_), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(gr), jax.tree.leaves(ge)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6)


def test_ragged_rejects_ep():
    with pytest.raises(ValueError, match="single-shard"):
        MoELayer(D, E, dispatch="ragged", axis_name="expert")
